"""One simulation round: the whole cluster advances in a single traced step.

Round structure (mirrors the reference's data plane, SURVEY §1):

  local writes → eager ring-0 broadcast → gossip dissemination →
  delivery + bookkeeping + CRDT merge → rebroadcast of fresh chunks →
  SWIM tick → (every ``sync_interval`` rounds) anti-entropy sync.

Every stage is a batched array op over all nodes; there is no per-node
control flow, so the step jits to one XLA program that `lax.scan` can
iterate on-device.

Changesets are seq-structured like the reference's: one version = one
transaction's multi-cell changeset (``corro-api-types/src/lib.rs:235-245``),
gossiped as ``chunks_per_version`` chunks (the ≤8 KiB ``ChunkedChanges``
split, ``corro-types/src/change.rs:16-122``); a receiver buffers partial
versions and merges only once seq-complete (``agent/util.rs:458-501``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from corro_sim.config import SimConfig
from corro_sim.core.bookkeeping import partial_versions
from corro_sim.core.changelog import append_changesets
from corro_sim.core.compaction import update_ownership
from corro_sim.core.crdt import NEG, local_write
from corro_sim.core.delivery import delivery_pass
from corro_sim.faults.inject import (
    LaneFaultKnobs,
    blackhole_mask,
    burst_update,
    fault_keys,
    link_fault_masks,
)
from corro_sim.faults.nodes import (
    apply_node_faults,
    recovering_mask,
    skew_plane,
    straggler_active,
)
from corro_sim.engine.probe import (
    probe_book_update,
    probe_metrics,
    probe_sync_mark,
    probe_write_update,
)
from corro_sim.engine.state import SimState
from corro_sim.gossip.broadcast import (
    broadcast_step,
    enqueue_broadcasts,
    enqueue_own,
)
from corro_sim.membership.rtt import link_delay, observe_rtt, recompute_ring0
from corro_sim.membership.swim import swim_step, view_alive  # noqa: F401
from corro_sim.membership.swim_window import membership_view
from corro_sim.sync.sync import sync_round

# The step's PRNG stream map — declared contract, checked observed by
# the key-lineage auditor (analysis/keys.py, `corro-sim audit --keys`):
# the round key splits exactly once into these lanes, in this order,
# and child i of that split feeds ONLY the named subsystem. Golden
# derivation addresses in analysis/golden/key_lineage.json are spelled
# against these positions (e.g. the broadcast target draw is
# ``in:key/split9[6]/fold(7)/...``). Reordering or renaming a lane is
# a stream re-key: every seeded simulation changes.
STEP_KEY_STREAMS = (
    "write",   # [0] workload write-commit coin
    "row",     # [1] write target row
    "col",     # [2] write target column (randint hi/lo pair)
    "val",     # [3] written value (randint hi/lo pair)
    "del",     # [4] delete coin
    "ncell",   # [5] cells-per-changeset draw (unconsumed by 1-cell cfgs)
    "bcast",   # [6] gossip broadcast targets (gossip/broadcast.py)
    "swim",    # [7] SWIM probe/indirect/exchange (membership/swim*.py)
    "sync",    # [8] anti-entropy partner + payload (sync/sync.py)
)


def make_step(cfg: SimConfig, repair: bool = False, mesh=None):
    """The scan-shaped closure over :func:`sim_step`: ``(state, (key,
    alive, part, write_enable)) -> (state, metrics)``. The one place the
    chunk program's body is defined — the driver's ``lax.scan`` and the
    jaxpr audit harness (:mod:`corro_sim.analysis.jaxpr_audit`) both
    build from here, so the program they pin is the program that runs.

    ``mesh``: the sharded fast path (ISSUE 8) — the kernel merge sites
    run per-shard inside ``shard_map`` regions with explicit collectives
    for cross-shard lanes. ``None`` (every single-device caller) traces
    the byte-identical program the jaxpr golden pins.

    Program scope (ISSUE 10): the body traces from ONLY the leaves the
    config enables — registry feature leaves (``SimState.features``,
    engine/features.py) a config does not enable simply do not exist in
    the carry, so each chunk program's cache key covers exactly its own
    feature set. Unconsumed enabled features thread through
    ``state.replace`` untouched (``replace`` keeps unnamed fields)."""

    def body(state, inp):
        key, alive, part, we = inp
        return sim_step(
            cfg, state, key, alive, part, we, repair=repair, mesh=mesh
        )

    return body


def make_workload_step(cfg: SimConfig, repair: bool = False, mesh=None):
    """The workload-driven scan body: ``(state, (key, alive, part,
    write_enable, writers, rows, cols, vals, dels, ncells)) -> (state,
    metrics)`` — a compiled write schedule (:mod:`corro_sim.workload`)
    rides the scan inputs into ``sim_step``'s explicit ``writes=`` port
    (the live agent's port), replacing the uniform sampler. A separate
    program from :func:`make_step` by construction: with no workload
    armed the driver builds :func:`make_step` exactly as before, so the
    hot step program stays byte-identical (the jaxpr golden pins it;
    ``assert_feature_vacuous`` proves the zero-schedule run bit-equal)."""

    def body(state, inp):
        key, alive, part, we, *writes = inp
        return sim_step(
            cfg, state, key, alive, part, we,
            writes=None if repair else tuple(writes), repair=repair,
            mesh=mesh,
        )

    return body


def step_input_avals(cfg: SimConfig, workload: bool = False) -> tuple:
    """The canonical traced-step argument avals ``(state, key, alive,
    part, write_enable, *writes)`` — the ONE definition of the chunk
    program's input ABI, shared by the jaxpr audit's tracer
    (:func:`corro_sim.analysis.jaxpr_audit.step_jaxpr`) and the
    contract auditor's provenance mapping
    (:mod:`corro_sim.analysis.contracts`): flattening this tuple with
    ``jax.tree_util.tree_flatten_with_path`` yields exactly the traced
    program's invars, in order, so a flat invar index maps to a state
    leaf path maps to a registry feature
    (:func:`corro_sim.engine.features.leaf_provenance`) without any
    parallel bookkeeping that could drift from the real trace."""
    from corro_sim.engine.state import init_state

    n = cfg.num_nodes
    s = cfg.seqs_per_version
    args = (
        jax.eval_shape(lambda: init_state(cfg, seed=0)),
        jax.eval_shape(lambda: jax.random.PRNGKey(0)),
        jax.ShapeDtypeStruct((n,), jnp.bool_),  # alive
        jax.ShapeDtypeStruct((n,), jnp.int32),  # part
        jax.ShapeDtypeStruct((), jnp.bool_),  # write_enable
    )
    if workload:
        args += (
            jax.ShapeDtypeStruct((n,), jnp.bool_),  # writers
            jax.ShapeDtypeStruct((n, s), jnp.int32),  # rows
            jax.ShapeDtypeStruct((n, s), jnp.int32),  # cols
            jax.ShapeDtypeStruct((n, s), jnp.int32),  # vals
            jax.ShapeDtypeStruct((n,), jnp.bool_),  # dels
            jax.ShapeDtypeStruct((n,), jnp.int32),  # ncells
        )
    return args


def _reachable_fn(alive: jnp.ndarray, part: jnp.ndarray):
    """Ground-truth link predicate: both up and in the same partition."""

    def reach(src, dst):
        return alive[src] & alive[dst] & (part[src] == part[dst])

    return reach


def _tile_chunks(cpv: int, *arrays):
    """Repeat each lane cpv times, appending a chunk index array."""
    out = [jnp.repeat(a, cpv) for a in arrays]
    n = arrays[0].shape[0]
    chunk = jnp.tile(jnp.arange(cpv, dtype=jnp.int32), n)
    return (*out, chunk)


def sim_step(
    cfg: SimConfig,
    state: SimState,
    key: jax.Array,
    alive: jnp.ndarray,  # (N,) ground truth
    part: jnp.ndarray,  # (N,) int32 partition id (ground truth)
    write_enable: jnp.ndarray,  # () bool — workload phase switch
    writes: tuple | None = None,  # explicit write batch (live agent path)
    repair: bool = False,  # static: the post-quiesce specialization
    mesh=None,  # device mesh: shard_map the kernel merge sites (ISSUE 8)
):
    """Advance the cluster one round.

    ``writes`` — when None, the synthetic workload samples this round's
    local writes (benchmark path). A live agent instead passes the
    transactions its API accepted this round as a tuple of arrays
    ``(writers (N,) bool, rows (N,S) i32, cols (N,S) i32, vals (N,S) i32,
    dels (N,) bool, ncells (N,) i32)`` — the single-write-per-node-per-round
    shape mirrors the reference's one write conn + ``Semaphore(1)``
    serialization (``corro-types/src/agent.rs:500-731``).

    ``repair`` — static compile-time switch for the convergence tail: with
    writes disabled AND every gossip ring drained (``pend_live == 0``, a
    precondition the driver checks on the host between chunks), the whole
    write → emit → sort → deliver → merge → enqueue pipeline is provably a
    no-op, so this variant traces only SWIM + anti-entropy + bookkeeping.
    Bit-for-bit equivalent to the full step under that precondition (same
    key split, same HLC/metric arithmetic; requires ``inflight_slots == 0``
    and ``rtt_rings`` off — the driver gates on both). The reference's
    agents idle the same way: no local commits and empty broadcast queues
    leave only the SWIM runtime and the sync loop awake
    (``agent/handlers.rs``, ``broadcast/mod.rs:532-597``).
    """
    if repair:
        return _repair_step(cfg, state, key, alive, part, mesh=mesh)
    n = cfg.num_nodes
    s = cfg.seqs_per_version
    cpv = cfg.chunks_per_version
    rows_idx = jnp.arange(n, dtype=jnp.int32)
    (k_write, k_row, k_col, k_val, k_del, k_ncell, k_bcast, k_swim, k_sync) = (
        jax.random.split(key, len(STEP_KEY_STREAMS))
    )
    reach = _reachable_fn(alive, part)

    # ------------------------------------------------ sweep knob planes
    # (corro_sim/sweep/): inside a vmapped fleet program the per-lane
    # fault knobs ride the sweep_knobs registry feature leaf — every
    # gate below stays STATIC (SweepConfig), only thresholds/schedules
    # become traced per-lane data. cfg.sweep off (every existing
    # config) touches nothing: the program is byte-identical.
    sw = state.features["sweep_knobs"] if cfg.sweep.enabled else None
    # SimConfig scalar knobs (sweep/knobs.py SIM_KNOB_FIELDS): the
    # write/delete thresholds and the sync/SWIM cadences read the leaf
    # instead of the baked constant when the sim_knobs gate is armed —
    # same comparisons, traced operands, so each lane stays
    # value-identical to the serial twin that bakes its value.
    sim_sw = sw if (sw is not None and cfg.sweep.sim_knobs) else None

    # ---------------------------------------------- node-lifecycle faults
    # (faults/nodes.py): scheduled crash-restart wipes / stale-rejoin
    # restores rebind the carry BEFORE anything reads it, plus the
    # straggler duty mask and the HLC skew plane. Static gate — off
    # traces ZERO extra ops (the cfg.probes discipline) — and every
    # mask is a pure function of the round counter and baked constants
    # (no new key draws), so the repair step derives the identical
    # fault timeline. Under a sweep the masks derive from per-lane
    # planes instead of constants — same expressions, traced operands.
    nf_sweep = sw if (sw is not None and cfg.sweep.node_faults) else None
    nf_on = cfg.node_faults.enabled or nf_sweep is not None
    if nf_on:
        state, nf_wiped = apply_node_faults(
            cfg, state, state.round, sweep=nf_sweep
        )
        nf_active = straggler_active(
            cfg.node_faults, n, state.round, sweep=nf_sweep
        )
        nf_skew = skew_plane(cfg.node_faults, n, sweep=nf_sweep)
    else:
        nf_active = None
        nf_skew = None

    # ----------------------------------------------------- chaos injection
    # Static gate (cfg.probes discipline): faults off traces ZERO extra
    # ops and the program is bit-identical to the fault-free one. The
    # fault key lane is fold_in-derived, NOT a wider split, so the 9
    # subkeys above are byte-identical either way and the repair step
    # derives the same fault stream (faults/inject.py).
    lane_link = sw is not None and cfg.sweep.link_faults
    fault_on = cfg.faults.enabled or lane_link
    if fault_on:
        fconf = (
            LaneFaultKnobs(sw, cfg.sweep.burst) if lane_link
            else cfg.faults
        )
        k_fburst, k_flink, k_fsync = fault_keys(key)
        burst = burst_update(fconf, state.fault_burst, k_fburst)
        bh = blackhole_mask(cfg.faults, n)
        bh = None if bh is None else jnp.asarray(bh)
    else:
        fconf = None
        burst = state.fault_burst
        k_fsync = None
        bh = None

    # ------------------------------------------------------------------ view
    view = membership_view(cfg, state.swim, n)

    # ---------------------------------------------------------- local writes
    # One changeset per node per round max — the reference serializes local
    # writes through one write conn + Semaphore(1) (agent.rs:500-731).
    lane_wl = sw is not None and cfg.sweep.workload
    if writes is not None and not lane_wl:
        writers, w_row_s, w_col, w_val, w_del, w_ncells = writes
        writers = writers & alive
        w_del = w_del & writers
    else:
        writers = (
            (jax.random.uniform(k_write, (n,)) < (
                sim_sw["write_rate"] if sim_sw is not None
                else cfg.write_rate
            ))
            & alive
            & write_enable
        )
        u = jax.random.uniform(k_row, (n,))
        w_row = jnp.searchsorted(state.row_cdf, u).astype(jnp.int32).clip(
            0, cfg.num_rows - 1
        )
        w_del = (jax.random.uniform(k_del, (n,)) < (
            sim_sw["delete_rate"] if sim_sw is not None else cfg.delete_rate
        )) & writers

        # Cells: 1..S distinct columns of the written row (a transaction
        # touching several columns — each cell is a seq-numbered Change). The
        # synthetic workload writes one row per changeset, so it can fill at
        # most num_cols of the S cell lanes (replayed traces may use all S
        # across rows).
        s_eff = min(s, cfg.num_cols)
        if s_eff > 1:
            w_ncells = jax.random.randint(
                k_ncell, (n,), 1, s_eff + 1, dtype=jnp.int32
            )
            w_col = jnp.argsort(
                jax.random.uniform(k_col, (n, cfg.num_cols)), axis=1,
                stable=True,
            ).astype(jnp.int32)[:, :s_eff]
            if s_eff < s:
                w_col = jnp.pad(w_col, ((0, 0), (0, s - s_eff)))
        else:
            w_ncells = jnp.ones((n,), jnp.int32)
            w_col = jax.random.randint(
                k_col, (n, 1), 0, cfg.num_cols, jnp.int32
            )
            if s > 1:
                w_col = jnp.pad(w_col, ((0, 0), (0, s - 1)))
        w_ncells = jnp.where(w_del, 1, w_ncells)  # DELETE = one cl-only change
        w_val = jax.random.randint(
            k_val, (n, s), 0, cfg.value_universe, dtype=jnp.int32
        )
        w_row_s = jnp.broadcast_to(w_row[:, None], (n, s))

        if writes is not None:
            # mixed sweep (corro_sim/sweep/): a lane whose knob says
            # use_workload takes its staged schedule rows; sampler
            # lanes keep the draws above — both sources are traced,
            # the per-lane scalar selects. Each lane is thereby
            # bit-identical to its serial twin (the twin runs exactly
            # one of the two sources through the same expressions).
            s_writers, s_rows, s_cols, s_vals, s_dels, s_ncells = writes
            s_writers = s_writers & alive
            s_dels = s_dels & s_writers
            uw = sw["use_workload"]
            writers = jnp.where(uw, s_writers, writers)
            w_row_s = jnp.where(uw, s_rows, w_row_s)
            w_col = jnp.where(uw, s_cols, w_col)
            w_val = jnp.where(uw, s_vals, w_val)
            w_del = jnp.where(uw, s_dels, w_del)
            w_ncells = jnp.where(uw, s_ncells, w_ncells)

    if nf_on:
        # post-wipe write gate (faults/nodes.py module docstring): a
        # restarted node must not mint fresh versions until anti-entropy
        # has served its own actor's history back — self-bookkeeping
        # assumes head[i, i] == log.head[i] at write time, and breaking
        # it would stamp old version numbers onto new content.
        # Identically all-pass absent wipes, so the vacuous trace is a
        # bit-identical no-op.
        writers = writers & ~(
            recovering_mask(state.book, state.log) & alive
        )
        w_del = w_del & writers

    table, ch_cv, ch_cl, ch_vr = local_write(
        state.table, rows_idx, w_row_s, w_col, w_val, w_del, w_ncells, writers
    )
    log, w_ver = append_changesets(
        state.log, rows_idx, w_row_s, w_col, ch_vr, ch_cv, ch_cl, w_ncells,
        writers,
    )
    # Self-bookkeeping: a node's own writes are trivially in-order.
    book = state.book.replace(
        head=state.book.head.at[rows_idx, rows_idx].add(
            writers.astype(jnp.int32)
        )
    )
    # Ring-wrap tripwire (changelog.py ring invariant): a live node lagging
    # an actor by more than the log capacity would gather *new* cells under
    # *old* version numbers and mark them applied — silently-wrong state.
    # Evaluated from the post-write log heads against the PRE-delivery
    # bookkeeping — the precondition of every stale gather this round can
    # perform — so a same-round sync repair cannot mask the violation. The
    # reference keeps its overload drops visible (handlers.rs:866-884);
    # here the violation poisons the run: the driver refuses to report
    # convergence once this fires (engine/driver.py, harness/cluster.py).
    lag_pre = log.head[None, :] - state.book.head
    log_wrapped = ((lag_pre > log.capacity) & alive[:, None]).sum(
        dtype=jnp.int32
    )
    # pre-delivery repair signal for the adaptive sync cadence (below)
    behind_pre = ((lag_pre > 0) & alive[:, None]).any()

    # Global ownership fold: which versions lost cells to this round's
    # writes (find_overwritten_versions → store_empty_changeset).
    w_cell_live = (
        writers[:, None]
        & (jnp.arange(s, dtype=jnp.int32)[None, :] < w_ncells[:, None])
    )
    pre_cleared = log.cleared
    own, log = update_ownership(
        state.own,
        log,
        jnp.broadcast_to(rows_idx[:, None], (n, s)).reshape(-1),
        jnp.broadcast_to(w_ver[:, None], (n, s)).reshape(-1),
        w_row_s.reshape(-1),
        w_col.reshape(-1),
        ch_cv.reshape(-1),
        ch_vr.reshape(-1),
        jnp.where(
            w_del[:, None], NEG, jnp.broadcast_to(rows_idx[:, None], (n, s))
        ).reshape(-1),
        ch_cl.reshape(-1),
        w_cell_live.reshape(-1),
        jnp.broadcast_to(w_del[:, None], (n, s)).reshape(-1),
    )
    # Stamp each version cleared this round with the round's write-phase
    # clock (max HLC over this round's live writers) — the ts its EmptySet
    # carries (store_empty_changeset, change.rs:267-389). Message-granular
    # per (actor, version-slot): a later EmptySet for a different version
    # gets its own, newer stamp, exactly like the reference's per-range ts
    # buffering in handle_emptyset (handlers.rs:524-719). The round-max
    # writer clock is an upper bound minted by SOME live writer this
    # round (per-lane attribution would mean threading clocks through the
    # ownership fold); the monotone-max gate on last_cleared is unaffected.
    newly_cleared = log.cleared & ~pre_cleared  # (A, L)
    writer_ts = jnp.max(jnp.where(writers, state.hlc, -1))
    cleared_hlc = jnp.where(
        newly_cleared,
        jnp.maximum(state.cleared_hlc, writer_ts),
        state.cleared_hlc,
    )

    # ------------------------------------------------- eager ring-0 messages
    # Every chunk of a fresh local changeset goes to every ring-0 peer
    # (broadcast/mod.rs:489-499).
    r0 = state.ring0.shape[1]
    e_dst, e_src, e_ver, e_valid, e_chunk = _tile_chunks(
        cpv,
        state.ring0.reshape(-1),
        jnp.repeat(rows_idx, r0),
        jnp.repeat(w_ver, r0),
        jnp.repeat(writers, r0),
    )
    e_actor = e_src
    if nf_active is not None:
        # straggler duty mask: an inactive node skips this round's eager
        # sends; the write already sat down in its own pending ring
        # (enqueue_own below), so dissemination is DELAYED to its next
        # active round, never lost — the emit_slots saturation semantics
        e_valid = e_valid & nf_active[e_src]

    # ------------------------------------------------- gossip dissemination
    gossip, g_dst, g_src, g_actor, g_ver, g_chunk, g_valid = broadcast_step(
        state.gossip, k_bcast,
        alive if nf_active is None else alive & nf_active,
        view, cfg.fanout,
        emit_slots=cfg.emit_slots, round_idx=state.round,
        need_chunk=cpv > 1,
    )

    dst = jnp.concatenate([e_dst, g_dst])
    src = jnp.concatenate([e_src, g_src])
    actor = jnp.concatenate([e_actor, g_actor])
    ver = jnp.concatenate([e_ver, g_ver])
    chunk = jnp.concatenate([e_chunk, g_chunk])
    valid = jnp.concatenate([e_valid, g_valid])
    msgs_sent = valid.sum(dtype=jnp.int32)  # emissions, pre-delay split

    # ------------------------------------------------ in-flight latency
    # A slow link DELAYS delivery instead of dropping (VERDICT r2 next #6;
    # reference per-conn RTT, transport.rs:199-233): lanes whose link
    # delay d > 1 park in a ring slot and re-enter the delivery pipeline
    # at round + d - 1. Reachability is evaluated AT DELIVERY — a message
    # in flight when a partition lands is lost with it. Matured lanes
    # merge the sender's CURRENT clock (hlc_recv below): clocks are
    # monotone, so a newer-than-emission stamp is still a clock the
    # sender reached — the uhlc max-merge is unaffected.
    if cfg.inflight_slots:
        d = link_delay(cfg, src, dst)
        slot = state.round % cfg.inflight_slots
        mat = state.inflight[slot]  # (6, L) — lanes maturing this round
        # A lane only parks if the link is up AT EMISSION — a send into a
        # live partition fails immediately (the reference transport errors
        # at send time); reach() is then re-checked at delivery below, so a
        # partition landing mid-flight loses the lane too.
        far = valid & (d > 1)
        park_ok = far & reach(src, dst)
        if fault_on:
            # conservation accounting for the invariant checker
            # (faults/invariants.py): emissions that parked vs died at
            # emission, and parked lanes re-entering this round
            f_parked = park_ok.sum(dtype=jnp.int32)
            f_emit_lost = (far & ~reach(src, dst)).sum(dtype=jnp.int32)
            f_matured = mat[5].sum(dtype=jnp.int32)
        inflight = state.inflight.at[slot].set(
            jnp.stack([dst, src, actor, ver, chunk,
                       park_ok.astype(jnp.int32)])
        )
        dst = jnp.concatenate([dst, mat[0]])
        src = jnp.concatenate([src, mat[1]])
        actor = jnp.concatenate([actor, mat[2]])
        ver = jnp.concatenate([ver, mat[3]])
        chunk = jnp.concatenate([chunk, mat[4]])
        valid = jnp.concatenate([valid & (d <= 1), mat[5].astype(bool)])
    else:
        inflight = state.inflight
        if fault_on:
            f_parked = f_emit_lost = f_matured = jnp.int32(0)

    # Ground truth: the packet lands iff the link is actually up at
    # delivery time (same round for near lanes, d-1 rounds later for far).
    delivered = valid & reach(src, dst)

    # ------------------------------------------------- link-fault masks
    # The broadcast transport point: deliverable lanes die to the seeded
    # Bernoulli loss draw (receiver-burst-aware), to the static blackhole
    # mask, or arrive twice (dup — accounted only: every merge path is
    # idempotent per (dst, actor, ver, chunk), so the second copy of a
    # datagram changes no state, exactly like real UDP duplication).
    if fault_on:
        f_unreachable = (valid & ~delivered).sum(dtype=jnp.int32)
        if bh is not None:
            holed = delivered & bh[src, dst]
            delivered = delivered & ~holed
            f_blackholed = holed.sum(dtype=jnp.int32)
        else:
            f_blackholed = jnp.int32(0)
        keep, dup_m = link_fault_masks(fconf, k_flink, dst, burst)
        f_lost = (delivered & ~keep).sum(dtype=jnp.int32)
        delivered = delivered & keep
        f_dup = (delivered & dup_m).sum(dtype=jnp.int32)
        f_delivered = delivered.sum(dtype=jnp.int32)

    # ------------------------------------------------------- probe origins
    # Origin seeding ahead of the fused pass (engine/probe.py). The flag
    # is static: probes == 0 traces ZERO extra ops and the step program
    # stays bit-identical to the uninstrumented one.
    if cfg.probes:
        probe = probe_write_update(state.probe, state.round, writers, w_ver)
    else:
        probe = state.probe

    # --------------------------------------- fused delivery merge (1 pass)
    # ONE lane sort feeds the whole delivery pipeline — HLC scatter-max,
    # apply-queue rank, bookkeeping dedupe, the probe delivery merge
    # point, changeset gathers and the CRDT merge scatter — instead of
    # each stage re-deriving masks over its own order (core/delivery.py).
    dv = delivery_pass(
        cfg, table, book, log, probe, state.hlc,
        dst, src, actor, ver, chunk, delivered, state.round, mesh=mesh,
    )
    table, book, probe = dv.table, dv.book, dv.probe
    hlc_recv = dv.hlc_recv
    dst, src, actor, ver, chunk = dv.dst, dv.src, dv.actor, dv.ver, dv.chunk
    delivered = dv.delivered
    fresh_chunk, complete, dropped = dv.fresh_chunk, dv.complete, dv.dropped
    c_cleared, g_actor, g_slot = dv.c_cleared, dv.g_actor, dv.g_slot
    cell_live = dv.cell_live

    # ------------------------------------------------- RTT samples + rings
    # Every landed packet is an RTT sample, capped or not
    # (transport.rs:199-233); rings recompute from observations every
    # ring_update_interval rounds (members.rs:140-188). Static config →
    # both fully traced out when off.
    if cfg.rtt_rings:
        rtt = observe_rtt(cfg, state.rtt, dst, src, dv.delivered_precap)
        ring0 = jax.lax.cond(
            (state.round % cfg.ring_update_interval)
            == (cfg.ring_update_interval - 1),
            lambda args: recompute_ring0(*args),
            lambda args: args[1],
            (rtt, state.ring0),
        )
    else:
        rtt = state.rtt
        ring0 = state.ring0

    # ------------------------------------------------- rebroadcast + enqueue
    # Fresh foreign chunks re-enter the destination's pending ring
    # (handlers.rs:950-960); a node's own fresh chunks enter its own ring
    # for random dissemination (the eager ring-0 send already happened).
    if cpv <= cfg.pend_slots:
        # own-write lanes are node-major with a fixed per-node lane count,
        # so the ring-slot rank is the lane index — no rank/count pass at
        # all (gossip/broadcast.py enqueue_own; bit-equivalent to the
        # grouped path while cpv fits the ring)
        gossip = enqueue_own(
            gossip, jnp.repeat(rows_idx, cpv), jnp.repeat(w_ver, cpv),
            jnp.tile(jnp.arange(cpv, dtype=jnp.int32), n), writers,
            cfg.max_transmissions, cpv,
        )
    else:
        # degenerate ring (cpv > pend_slots): the grouped path's unbiased
        # overflow rotation must pick which chunks survive
        wq_dst, wq_actor, wq_ver, wq_valid, wq_chunk = _tile_chunks(
            cpv, rows_idx, rows_idx, w_ver, writers
        )
        gossip = enqueue_broadcasts(
            gossip, wq_dst, wq_actor, wq_ver, wq_chunk, wq_valid,
            cfg.max_transmissions, grouped=True,
        )
    # delivery lanes carry the fused pass's hoisted sort order
    gossip = enqueue_broadcasts(
        gossip, dst, actor, ver, chunk, fresh_chunk,
        cfg.rebroadcast_transmissions, grouped=True,
    )

    # ----------------------------------------------------------------- SWIM
    swim, swim_metrics = _swim_block(
        cfg, state.swim, k_swim, alive, reach, state.round,
        suspect_rounds=(
            sim_sw["swim_suspect_rounds"] if sim_sw is not None else None
        ),
    )

    # last_cleared_ts analog, HLC-gated (handlers.rs:524-719): applying an
    # emptied version advances the node's last-cleared ts to the EmptySet's
    # HLC stamp via max — never backwards, so a sender with a stale clock
    # cannot regress it.
    last_cleared = state.last_cleared.at[
        jnp.where(complete & c_cleared, dst, n)
    ].max(cleared_hlc[g_actor, g_slot], mode="drop")

    # ----------------------------------------------------------------- sync
    si = sim_sw["sync_interval"] if sim_sw is not None else cfg.sync_interval
    is_sync = (state.round % si) == (si - 1)
    if cfg.sync_adaptive:
        # accelerated repair: when the cluster quiesces (zero writes this
        # round) but somebody is still behind, sync on the floor cadence
        # (the reference's 1 s backoff floor, util.rs:327-371) instead of
        # the lean sync_interval. Write-phase rounds keep the lean cadence.
        quiesced = writers.sum(dtype=jnp.int32) == 0
        floor_hit = (state.round % cfg.sync_floor_rounds) == (
            cfg.sync_floor_rounds - 1
        )
        is_sync = is_sync | (quiesced & behind_pre & floor_hit)

    # straggler sync gating (faults/nodes.py): a parked node initiates
    # no sweep but still serves inbound requests. The duty cycle ticks
    # on the SWEEP counter, not the round counter — a round-based phase
    # could deterministically alias with sync_interval and starve the
    # node's client side forever, which is a scheduler artifact, not a
    # slow agent.
    nf_sync_ok = (
        None if nf_active is None
        else straggler_active(
            cfg.node_faults, n, state.sync_rounds, sweep=nf_sweep
        )
    )
    book, table, hlc_s, last_cleared, sync_metrics = _sync_block(
        cfg, is_sync, book, log, table, state.hlc, last_cleared, cleared_hlc,
        k_sync, alive, view, part,
        rtt=rtt if cfg.rtt_rings else None, round_idx=state.sync_rounds,
        fault_key=k_fsync, mesh=mesh, client_ok=nf_sync_ok,
        fault_cfg=fconf if lane_link else None,
    )
    if cfg.probes:
        # the anti-entropy merge point: heads that now cover a probe's
        # version without a recorded gossip delivery joined via sync
        probe = probe_book_update(probe, book.head, state.round)
        probe = probe_sync_mark(probe, is_sync, alive, state.round)

    # -------------------------------------------------------------- metrics
    # float32 sum: magnitudes can exceed int32 at 10k×10k scale, and the
    # convergence test is exactness-of-zero, which f32 addition of
    # non-negative terms preserves.
    gap = jnp.where(
        alive[:, None], (log.head[None, :] - book.head).astype(jnp.float32), 0.0
    ).sum()
    hlc, skew = _hlc_tick(alive, hlc_s, hlc_recv, state.round, nf_skew)
    metrics = {
        "writes": writers.sum(dtype=jnp.int32),
        "deletes": w_del.sum(dtype=jnp.int32),
        "cells_written": jnp.where(writers, w_ncells, 0).sum(dtype=jnp.int32),
        "msgs_sent": msgs_sent,
        "delivered": delivered.sum(dtype=jnp.int32),
        "fresh": complete.sum(dtype=jnp.int32),
        "fresh_chunks": fresh_chunk.sum(dtype=jnp.int32),
        # cell lanes merged off the gossip path — broadcast byte-volume
        # signal (corro.broadcast.recv.bytes analog, metrics.rs)
        "gossip_cells": cell_live.sum(dtype=jnp.int32),
        "buffered_partials": partial_versions(book, cpv),
        "dropped_window": dropped.sum(dtype=jnp.int32),
        "queue_overflow": gossip.overflow,
        # live pending-broadcast slots cluster-wide (drained == 0): the
        # driver's precondition for switching to the repair-specialized step
        "pend_live": (gossip.pend_tx > 0).sum(dtype=jnp.int32),
        "cleared_versions": log.cleared.sum(dtype=jnp.int32),
        "gap": gap,
        "log_wrapped": log_wrapped,
        "clock_skew": skew,
        **swim_metrics,
        **sync_metrics,
        **(probe_metrics(probe) if cfg.probes else {}),
        # fault accounting (additive-only, like the probe metrics): the
        # conservation invariant checker reconstructs per-round message
        # flow from these — msgs_sent + matured - parked - emit_lost ==
        # delivered + unreachable + blackholed + lost (invariants.py)
        **({
            "fault_lost": f_lost,
            "fault_dup": f_dup,
            "fault_blackholed": f_blackholed,
            "fault_unreachable": f_unreachable,
            "fault_delivered": f_delivered,
            "fault_parked": f_parked,
            "fault_emit_lost": f_emit_lost,
            "fault_matured": f_matured,
            "fault_burst_nodes": (
                burst.sum(dtype=jnp.int32)
                if fconf.burst_on else jnp.int32(0)
            ),
        } if fault_on else {}),
        # node-lifecycle fault accounting (faults/nodes.py; additive):
        # wipes executed this round, straggler node-rounds parked, and
        # node-rounds still resyncing their own write cursor — the
        # scorecard and the corro_node_fault_* exposition read these
        **(_node_fault_metrics(
            nf_wiped, nf_active, alive, book, log
        ) if nf_on else {}),
    }

    new_state = state.replace(
        table=table,
        book=book,
        log=log,
        own=own,
        gossip=gossip,
        swim=swim,
        round=state.round + 1,
        sync_rounds=state.sync_rounds + is_sync.astype(jnp.int32),
        hlc=hlc,
        last_cleared=last_cleared,
        cleared_hlc=cleared_hlc,
        rtt=rtt,
        ring0=ring0,
        inflight=inflight,
        probe=probe,
        fault_burst=burst,
    )
    return new_state, metrics


def _pairwise_mask(alive: jnp.ndarray, part: jnp.ndarray):
    """(N, N) ground-truth reachability for sync peer choice."""
    return alive[:, None] & alive[None, :] & (part[:, None] == part[None, :])


# --- shared blocks ---------------------------------------------------------
# sim_step and _repair_step MUST stay bit-for-bit equivalent under the
# repair precondition; the SWIM tick, the sync cond and the end-of-round
# clock update live here once so the two paths cannot drift.


def _swim_block(cfg, swim_state, k_swim, alive, reach, round_,
                suspect_rounds=None):
    """The SWIM cadence: tick every ``swim_interval``-th round.

    foca probes every 1-5 s vs the 500 ms broadcast flush — SWIM ticking
    every k-th gossip round is the faithful ratio AND cuts the (N, N)
    plane traffic k-fold (config.swim_interval). ``suspect_rounds``
    (sweep sim_knobs) overrides the baked suspicion timeout with a
    traced per-lane scalar."""
    if not cfg.swim_enabled:
        return swim_state, {
            "swim_suspects": jnp.int32(0),
            "swim_down": jnp.int32(0),
            "swim_probe_failures": jnp.int32(0),
        }
    if cfg.swim_view_size > 0:
        from corro_sim.membership.swim_window import swim_window_step

        step_fn = swim_window_step
    else:
        step_fn = swim_step
    if cfg.swim_interval <= 1:
        return step_fn(cfg, swim_state, k_swim, alive, reach, round_,
                       suspect_rounds=suspect_rounds)

    def tick_swim(args):
        sw, k = args
        return step_fn(cfg, sw, k, alive, reach, round_,
                       suspect_rounds=suspect_rounds)

    def skip_swim(args):
        sw, _ = args
        st = sw.status
        tracked = (
            sw.member >= 0 if cfg.swim_view_size > 0
            else jnp.ones(st.shape, bool)
        )
        return sw, {
            "swim_suspects": (
                (st == 1) & tracked & alive[:, None]
            ).sum(dtype=jnp.int32),
            "swim_down": (
                (st >= 2) & tracked & alive[:, None]
            ).sum(dtype=jnp.int32),
            "swim_probe_failures": jnp.int32(0),
        }

    return jax.lax.cond(
        (round_ % cfg.swim_interval) == 0,
        tick_swim,
        skip_swim,
        (swim_state, k_swim),
    )


def _sync_block(
    cfg, is_sync, book, log, table, hlc, last_cleared, cleared_hlc,
    k_sync, alive, view, part, rtt, round_idx=0, fault_key=None,
    mesh=None, client_ok=None, fault_cfg=None,
):
    """The sync cond: one anti-entropy sweep when ``is_sync``.

    ``fault_key``: the per-round sync-fault subkey (faults/inject.py)
    when chaos injection is on — admitted connections then drop with
    ``faults.resolved_sync_loss`` and across blackholed edges. Static:
    None (faults off) traces the pre-fault program exactly.

    ``client_ok``: the straggler duty mask (faults/nodes.py) — a parked
    node initiates no sweep this round (its sync_loop backoff has
    stretched) but still SERVES inbound requests: the reference's sync
    server is a passive semaphore-guarded responder, so only the client
    side slows down. Gating the pair-mask rows gates exactly that.
    None (node faults off) traces the pre-fault program exactly.

    ``fault_cfg``: per-lane knob substitute for ``cfg.faults``
    (corro_sim/sweep/ LaneFaultKnobs) — None everywhere off-sweep."""

    def do_sync(args):
        book, table, hlc, lc = args
        # reachability as a matrix-free pair of masks: same-partition
        # check happens inside via gathered part ids
        pairs = _pairwise_mask(alive, part)
        if client_ok is not None:
            pairs = pairs & client_ok[:, None]
        return sync_round(
            cfg, book, log, table, hlc, lc, cleared_hlc, k_sync, alive,
            view, pairs,
            rtt=rtt, round_idx=round_idx, fault_key=fault_key, mesh=mesh,
            fault_cfg=fault_cfg,
        )

    def no_sync(args):
        book, table, hlc, lc = args
        zero = jnp.int32(0)
        m = {
            "sync_pairs": zero,
            "sync_requests": zero,
            "sync_rejections": zero,
            "sync_versions": zero,
            "sync_empties": zero,
            "sync_cells": zero,
        }
        if cfg.faults.enabled or fault_cfg is not None:
            m["fault_sync_lost"] = zero
        return book, table, hlc, lc, m

    return jax.lax.cond(
        is_sync, do_sync, no_sync, (book, table, hlc, last_cleared)
    )


def _node_fault_metrics(nf_wiped, nf_active, alive, book, log):
    """The node-fault metric block, shared verbatim by both step
    programs (the repair step must compute bit-identical series under
    its precondition). All additive: node-rounds, not gauges."""
    return {
        "node_fault_wipes": nf_wiped.sum(dtype=jnp.int32),
        "node_fault_straggling": (
            (alive & ~nf_active).sum(dtype=jnp.int32)
            if nf_active is not None else jnp.int32(0)
        ),
        # end-of-round resync window: the write gate's own predicate
        # (faults/nodes.py — one definition, no drift)
        "node_fault_recovering": (
            recovering_mask(book, log) & alive
        ).sum(dtype=jnp.int32),
    }


def _hlc_tick(alive, hlc_s, hlc_recv, round_, skew=None):
    """uhlc max+tick: merged clocks from this round's deliveries + sync
    contacts, physical floor = the round counter — raised per node by
    the ``skew`` offset plane when the node-fault clock-skew knob is on
    (faults/nodes.py; None traces the pre-skew expression exactly).
    Down nodes freeze. Returns (hlc, skew)."""
    floor = round_ if skew is None else round_ + skew
    hlc = jnp.where(
        alive,
        jnp.maximum(jnp.maximum(hlc_s, hlc_recv), floor) + 1,
        hlc_s,
    )
    int_min = jnp.int32(-(2**31) + 1)
    int_max = jnp.int32(2**31 - 1)
    skew = jnp.maximum(
        jnp.max(jnp.where(alive, hlc, int_min))
        - jnp.min(jnp.where(alive, hlc, int_max)),
        0,
    )
    return hlc, skew


def _repair_step(
    cfg: SimConfig,
    state: SimState,
    key: jax.Array,
    alive: jnp.ndarray,
    part: jnp.ndarray,
    mesh=None,
):
    """The post-quiesce round: SWIM + sync + bookkeeping only.

    Preconditions (driver-checked): no writes this round, every gossip
    pending ring drained, no in-flight delay ring, no RTT rings. Under
    those, this is bit-for-bit ``sim_step`` — the same subkeys reach SWIM
    and sync, the dead pipeline's state updates are all masked no-ops, and
    each metric either repeats the full step's expression or is the zero
    the full step would compute.
    """
    assert cfg.inflight_slots == 0 and not cfg.rtt_rings
    n = cfg.num_nodes
    cpv = cfg.chunks_per_version
    # same 9-way split as the full step — k_swim/k_sync must match
    (_k_write, _k_row, _k_col, _k_val, _k_del, _k_ncell, _k_bcast, k_swim,
     k_sync) = jax.random.split(key, len(STEP_KEY_STREAMS))
    reach = _reachable_fn(alive, part)

    # sweep knob planes: the identical handle the full step holds (the
    # sweep engine itself never dispatches this program — it always
    # runs the full step so every lane can write/wipe at any chunk —
    # but the two programs must stay trace-equivalent under ANY config)
    sw = state.features["sweep_knobs"] if cfg.sweep.enabled else None
    sim_sw = sw if (sw is not None and cfg.sweep.sim_knobs) else None

    # node-lifecycle faults: the identical prologue the full step runs
    # (masks are pure functions of the round counter — no keys), so a
    # wipe landing in the convergence tail executes bit-for-bit on this
    # program too and the driver's specialization stays equivalence-safe
    nf_sweep = sw if (sw is not None and cfg.sweep.node_faults) else None
    nf_on = cfg.node_faults.enabled or nf_sweep is not None
    if nf_on:
        state, nf_wiped = apply_node_faults(
            cfg, state, state.round, sweep=nf_sweep
        )
        nf_active = straggler_active(
            cfg.node_faults, n, state.round, sweep=nf_sweep
        )
        nf_skew = skew_plane(cfg.node_faults, n, sweep=nf_sweep)
    else:
        nf_active = None
        nf_skew = None

    # same fold_in-derived fault lane as the full step: the burst Markov
    # state keeps evolving and the sync grant keeps failing through the
    # convergence tail — recovery under loss must not get a fault-free
    # repair program. The unused link-loss subkey costs nothing (the full
    # step's draws on zero valid lanes are masked no-ops there too).
    lane_link = sw is not None and cfg.sweep.link_faults
    fault_on = cfg.faults.enabled or lane_link
    if fault_on:
        fconf = (
            LaneFaultKnobs(sw, cfg.sweep.burst) if lane_link
            else cfg.faults
        )
        k_fburst, _k_flink, k_fsync = fault_keys(key)
        burst = burst_update(fconf, state.fault_burst, k_fburst)
    else:
        fconf = None
        burst = state.fault_burst
        k_fsync = None

    view = membership_view(cfg, state.swim, n)

    log = state.log
    book = state.book
    lag_pre = log.head[None, :] - book.head
    log_wrapped = ((lag_pre > log.capacity) & alive[:, None]).sum(
        dtype=jnp.int32
    )
    behind_pre = ((lag_pre > 0) & alive[:, None]).any()

    zero = jnp.int32(0)
    hlc_recv = jnp.zeros((n,), jnp.int32)

    # SWIM keeps its tick cadence through the tail (shared block)
    swim, swim_metrics = _swim_block(
        cfg, state.swim, k_swim, alive, reach, state.round,
        suspect_rounds=(
            sim_sw["swim_suspect_rounds"] if sim_sw is not None else None
        ),
    )

    # ----------------------------------------------------------------- sync
    si = sim_sw["sync_interval"] if sim_sw is not None else cfg.sync_interval
    is_sync = (state.round % si) == (si - 1)
    if cfg.sync_adaptive:
        # quiesced is identically True here (no writers by precondition)
        floor_hit = (state.round % cfg.sync_floor_rounds) == (
            cfg.sync_floor_rounds - 1
        )
        is_sync = is_sync | (behind_pre & floor_hit)

    nf_sync_ok = (
        None if nf_active is None
        else straggler_active(
            cfg.node_faults, n, state.sync_rounds, sweep=nf_sweep
        )
    )
    book, table, hlc_s, last_cleared, sync_metrics = _sync_block(
        cfg, is_sync, book, log, state.table, state.hlc, state.last_cleared,
        state.cleared_hlc, k_sync, alive, view, part, rtt=None,
        round_idx=state.sync_rounds, fault_key=k_fsync, mesh=mesh,
        client_ok=nf_sync_ok, fault_cfg=fconf if lane_link else None,
    )
    probe = state.probe
    if cfg.probes:
        # Bit-for-bit the full step's probe path under the precondition:
        # no writers and no valid lanes make the origin/delivery updates
        # masked no-ops there, so only the sync merge point + sweep stamp
        # remain live here.
        probe = probe_book_update(probe, book.head, state.round)
        probe = probe_sync_mark(probe, is_sync, alive, state.round)

    # -------------------------------------------------------------- metrics
    gap = jnp.where(
        alive[:, None], (log.head[None, :] - book.head).astype(jnp.float32),
        0.0,
    ).sum()
    hlc, skew = _hlc_tick(alive, hlc_s, hlc_recv, state.round, nf_skew)
    metrics = {
        "writes": zero,
        "deletes": zero,
        "cells_written": zero,
        "msgs_sent": zero,
        "delivered": zero,
        "fresh": zero,
        "fresh_chunks": zero,
        "gossip_cells": zero,
        "buffered_partials": partial_versions(book, cpv),
        "dropped_window": zero,
        "queue_overflow": state.gossip.overflow,
        "pend_live": (state.gossip.pend_tx > 0).sum(dtype=jnp.int32),
        "cleared_versions": log.cleared.sum(dtype=jnp.int32),
        "gap": gap,
        "log_wrapped": log_wrapped,
        "clock_skew": skew,
        **swim_metrics,
        **sync_metrics,
        **(probe_metrics(probe) if cfg.probes else {}),
        # the zeros the full step would compute on zero lanes, plus the
        # two live fault series (burst state, sync-grant losses)
        **({
            "fault_lost": zero,
            "fault_dup": zero,
            "fault_blackholed": zero,
            "fault_unreachable": zero,
            "fault_delivered": zero,
            "fault_parked": zero,
            "fault_emit_lost": zero,
            "fault_matured": zero,
            "fault_burst_nodes": (
                burst.sum(dtype=jnp.int32)
                if fconf.burst_on else zero
            ),
        } if fault_on else {}),
        # node-fault series stay LIVE through the tail (wipes can land
        # here; recovery is exactly what the tail repairs) — the shared
        # helper keeps the expressions bit-identical to the full step's
        **(_node_fault_metrics(
            nf_wiped, nf_active, alive, book, log
        ) if nf_on else {}),
    }

    new_state = state.replace(
        table=table,
        book=book,
        swim=swim,
        round=state.round + 1,
        sync_rounds=state.sync_rounds + is_sync.astype(jnp.int32),
        hlc=hlc,
        last_cleared=last_cleared,
        probe=probe,
        fault_burst=burst,
    )
    return new_state, metrics
