"""SQLSTATE codes for the Postgres wire API.

The reference ships a full SQLSTATE table (`corro-pg/src/sql_state.rs`,
1336 LoC of generated code→condition-name pairs) so its `ErrorResponse`s
carry real Postgres error codes. This is the same table as data: the
standard PostgreSQL error codes (appendix A of the PG docs), keyed by the
condition name the code paths raise with.

Severity is always ERROR here; the wire layer fills in the rest.
"""

from __future__ import annotations

# condition name -> SQLSTATE code (PostgreSQL Appendix A)
SQL_STATE: dict[str, str] = {
    # Class 00/01/02 — success / warnings / no data
    "successful_completion": "00000",
    "warning": "01000",
    "no_data": "02000",
    # Class 03 — SQL statement not yet complete
    "sql_statement_not_yet_complete": "03000",
    # Class 08 — connection exceptions
    "connection_exception": "08000",
    "connection_does_not_exist": "08003",
    "connection_failure": "08006",
    "sqlclient_unable_to_establish_sqlconnection": "08001",
    "sqlserver_rejected_establishment_of_sqlconnection": "08004",
    "transaction_resolution_unknown": "08007",
    "protocol_violation": "08P01",
    # Class 0A — feature not supported
    "feature_not_supported": "0A000",
    # Class 0B — invalid transaction initiation
    "invalid_transaction_initiation": "0B000",
    # Class 21/22 — cardinality / data exceptions
    "cardinality_violation": "21000",
    "data_exception": "22000",
    "string_data_right_truncation": "22001",
    "null_value_not_allowed": "22004",
    "numeric_value_out_of_range": "22003",
    "invalid_datetime_format": "22007",
    "division_by_zero": "22012",
    "invalid_parameter_value": "22023",
    "invalid_text_representation": "22P02",
    "invalid_binary_representation": "22P03",
    # Class 23 — integrity constraint violations
    "integrity_constraint_violation": "23000",
    "restrict_violation": "23001",
    "not_null_violation": "23502",
    "foreign_key_violation": "23503",
    "unique_violation": "23505",
    "check_violation": "23514",
    # Class 24/25 — cursor / transaction state
    "invalid_cursor_state": "24000",
    "invalid_transaction_state": "25000",
    "active_sql_transaction": "25001",
    "branch_transaction_already_active": "25002",
    "inappropriate_access_mode_for_branch_transaction": "25003",
    "inappropriate_isolation_level_for_branch_transaction": "25004",
    "no_active_sql_transaction_for_branch_transaction": "25005",
    "read_only_sql_transaction": "25006",
    "schema_and_data_statement_mixing_not_supported": "25007",
    "no_active_sql_transaction": "25P01",
    "in_failed_sql_transaction": "25P02",
    "idle_in_transaction_session_timeout": "25P03",
    # Class 26/27/28 — statement name / data change / authorization
    "invalid_sql_statement_name": "26000",
    "triggered_data_change_violation": "27000",
    "invalid_authorization_specification": "28000",
    "invalid_password": "28P01",
    # Class 2D/2F — transaction termination / SQL routine
    "invalid_transaction_termination": "2D000",
    "sql_routine_exception": "2F000",
    # Class 34 — invalid cursor name
    "invalid_cursor_name": "34000",
    # Class 3D/3F — invalid catalog/schema name
    "invalid_catalog_name": "3D000",
    "invalid_schema_name": "3F000",
    # Class 40 — transaction rollback
    "transaction_rollback": "40000",
    "transaction_integrity_constraint_violation": "40002",
    "serialization_failure": "40001",
    "statement_completion_unknown": "40003",
    "deadlock_detected": "40P01",
    # Class 42 — syntax error or access rule violation
    "syntax_error_or_access_rule_violation": "42000",
    "syntax_error": "42601",
    "insufficient_privilege": "42501",
    "cannot_coerce": "42846",
    "grouping_error": "42803",
    "windowing_error": "42P20",
    "invalid_recursion": "42P19",
    "invalid_foreign_key": "42830",
    "invalid_name": "42602",
    "name_too_long": "42622",
    "reserved_name": "42939",
    "datatype_mismatch": "42804",
    "indeterminate_datatype": "42P18",
    "collation_mismatch": "42P21",
    "indeterminate_collation": "42P22",
    "wrong_object_type": "42809",
    "undefined_column": "42703",
    "undefined_function": "42883",
    "undefined_table": "42P01",
    "undefined_parameter": "42P02",
    "undefined_object": "42704",
    "duplicate_column": "42701",
    "duplicate_cursor": "42P03",
    "duplicate_database": "42P04",
    "duplicate_function": "42723",
    "duplicate_prepared_statement": "42P05",
    "duplicate_schema": "42P06",
    "duplicate_table": "42P07",
    "duplicate_alias": "42712",
    "duplicate_object": "42710",
    "ambiguous_column": "42702",
    "ambiguous_function": "42725",
    "ambiguous_parameter": "42P08",
    "ambiguous_alias": "42P09",
    "invalid_column_reference": "42P10",
    "invalid_column_definition": "42611",
    "invalid_cursor_definition": "42P11",
    "invalid_database_definition": "42P12",
    "invalid_function_definition": "42P13",
    "invalid_prepared_statement_definition": "42P14",
    "invalid_schema_definition": "42P15",
    "invalid_table_definition": "42P16",
    "invalid_object_definition": "42P17",
    # Class 53/54/55/57/58 — resources / limits / object state / intervention
    "insufficient_resources": "53000",
    "disk_full": "53100",
    "out_of_memory": "53200",
    "too_many_connections": "53300",
    "configuration_limit_exceeded": "53400",
    "program_limit_exceeded": "54000",
    "statement_too_complex": "54001",
    "too_many_columns": "54011",
    "too_many_arguments": "54023",
    "object_not_in_prerequisite_state": "55000",
    "object_in_use": "55006",
    "cant_change_runtime_param": "55P02",
    "lock_not_available": "55P03",
    "operator_intervention": "57000",
    "query_canceled": "57014",
    "admin_shutdown": "57P01",
    "crash_shutdown": "57P02",
    "cannot_connect_now": "57P03",
    "database_dropped": "57P04",
    "system_error": "58000",
    "io_error": "58030",
    "undefined_file": "58P01",
    "duplicate_file": "58P02",
    # Class XX — internal errors
    "internal_error": "XX000",
    "data_corrupted": "XX001",
    "index_corrupted": "XX002",
}


def code(condition: str) -> str:
    """SQLSTATE code for a condition name; internal_error if unknown."""
    return SQL_STATE.get(condition, "XX000")
