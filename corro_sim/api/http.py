"""HTTP API: the reference's public client surface over a LiveCluster.

Route parity with ``corro-agent/src/api/public`` (SURVEY §2.3):

  POST /v1/transactions   — statement batch → one committed version
                            (``api_v1_transactions``, ``public/mod.rs:134-205``)
  POST /v1/queries        — one SELECT → streaming ND-JSON ``QueryEvent``s
                            (``api_v1_queries``, ``public/mod.rs:215-441``)
  POST /v1/subscriptions  — SELECT → live query stream; dedupe by normalized
                            SQL; ``corro-query-id``/``corro-query-hash``
                            headers (``public/pubsub.rs:665``)
  GET  /v1/subscriptions/:id?from=N&skip_rows= — re-attach an existing sub
                            (``api_v1_sub_by_id``, ``public/pubsub.rs:36-110``)
  POST /v1/migrations     — DDL batch → additive schema migration
                            (``api_v1_db_schema``, ``public/mod.rs:443-528``)
  POST /v1/table_stats    — per-table row counts (``public/mod.rs:535-590``)
  GET  /v1/cluster/members, GET /metrics — membership + Prometheus text
                            (the reference serves these via corro-admin and
                            the Prometheus exporter; one port suffices here)

Differences by design: one server fronts the *whole simulated cluster*, so
every route takes ``?node=N`` to pick which agent you'd have dialed
(default 0). Event bodies are ND-JSON lines exactly like the reference
(serde shapes of ``TypedQueryEvent``, ``corro-api-types/src/lib.rs:25-38``),
so a reference client's decode loop works unchanged.

Authorization mirrors ``BearerToken`` authz (``agent/util.rs:219-246``):
when the server is given a token, every request must carry
``Authorization: Bearer <token>``.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import select
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from corro_sim.api.wire import decode_values as _decode_wire_values
from corro_sim.api.wire import encode_value as _json_value
from corro_sim.harness.cluster import ExecError, LiveCluster
from corro_sim.utils.tracing import parse_traceparent, tracer

_SUB_PATH = re.compile(r"^/v1/subscriptions/([A-Za-z0-9_-]+)$")

# Stream poll cadence. The reference parks on a tokio broadcast receiver;
# an HTTP thread here polls its deque instead.
_POLL_S = 0.02


class _ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


def _parse_qs(query: str) -> dict:
    return dict(urllib.parse.parse_qsl(query, keep_blank_values=True))


def query_hash(sql: str) -> str:
    """Stable hash of the normalized query — the ``corro-query-hash``
    header value (``public/pubsub.rs:640-663`` hashes the statement)."""
    return hashlib.sha256(sql.strip().encode()).hexdigest()[:16]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "corro-sim"

    # quiet request logging; the cluster has its own metrics
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    # ----------------------------------------------------------- plumbing
    @property
    def api(self) -> "ApiServer":
        return self.server.api  # type: ignore[attr-defined]

    def _authz(self) -> bool:
        token = self.api.authz_token
        if token is None:
            return True
        got = self.headers.get("Authorization", "")
        if got == f"Bearer {token}":
            return True
        self._send_json({"error": "unauthorized"}, status=401)
        return False

    def _body_json(self):
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        if not raw:
            raise _ApiError(400, "empty body")
        try:
            return _decode_wire_values(json.loads(raw))
        except json.JSONDecodeError as e:
            raise _ApiError(400, f"invalid JSON body: {e}") from None
        except ValueError as e:  # malformed blob shape
            raise _ApiError(400, str(e)) from None

    def _node(self, params: dict) -> int:
        try:
            return int(params.get("node", "0") or 0)
        except ValueError:
            raise _ApiError(400, "node must be an integer") from None

    def _send_json(self, obj, status: int = 200, headers: dict | None = None):
        body = (json.dumps(obj, default=_json_value) + "\n").encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        ctx = getattr(self, "_trace_ctx", None)
        if ctx is not None:
            self.send_header("traceparent", ctx.to_traceparent())
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _start_stream(self, status: int = 200, headers: dict | None = None):
        """Open an unbounded ND-JSON response (read-until-close framing)."""
        self.send_response(status)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Connection", "close")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.close_connection = True

    def _stream_events(self, events) -> None:
        for e in events:
            self.wfile.write(
                (json.dumps(_as_wire(e), default=_json_value) + "\n")
                .encode()
            )
        self.wfile.flush()

    # ------------------------------------------------------------- routes
    def _traced(self, name: str, fn, streaming: bool = False):
        """Run a route handler under a span.

        Distributed trace propagation: an incoming W3C ``traceparent``
        parents the span (``SyncTraceContextV1`` analog,
        ``corro-types/src/sync.rs:33-67``). Streaming routes record only
        an *accept* span — a subscription body lives as long as the
        client stays connected, and measuring connection lifetime would
        flood the slow-span watchdog and bury real signals."""
        parent = parse_traceparent(self.headers.get("traceparent"))
        try:
            if streaming:
                with tracer.span(f"{name} accept", parent=parent) as ctx:
                    self._trace_ctx = ctx
                fn()
            else:
                with tracer.span(name, parent=parent) as ctx:
                    self._trace_ctx = ctx
                    fn()
        except _ApiError as e:
            self._send_json({"error": e.message}, status=e.status)
        except BrokenPipeError:
            pass

    def do_POST(self):  # noqa: N802
        with self.api.cluster._api_req_lock:
            self.api.cluster._api_requests += 1
        self._trace_ctx = None  # never leak a prior request's context
        if not self._authz():
            return
        path, _, qs = self.path.partition("?")
        params = _parse_qs(qs)
        name = f"http POST {path}"
        if path == "/v1/transactions":
            self._traced(name, lambda: self._post_transactions(params))
        elif path == "/v1/queries":
            self._traced(name, lambda: self._post_queries(params))
        elif path == "/v1/subscriptions":
            self._traced(name, lambda: self._post_subscriptions(params),
                         streaming=True)
        elif path in ("/v1/migrations", "/v1/db/schema"):
            self._traced(name, lambda: self._post_migrations(params))
        elif path == "/v1/table_stats":
            self._traced(name, lambda: self._post_table_stats(params))
        elif path == "/v1/faults":
            self._traced(name, self._post_faults)
        else:
            self._traced(name, lambda: self._send_json(
                {"error": "not found"}, status=404))

    # POST /v1/faults — arm/disarm a chaos scenario on the live cluster:
    # {"scenario": "lossy:p=0.1", "rounds": 128} or {"clear": true}
    def _post_faults(self):
        body = self._body_json()
        if not isinstance(body, dict):
            raise _ApiError(400, "body must be a JSON object")
        if body.get("clear"):
            self._send_json(self.api.cluster.clear_scenario())
            return
        spec = body.get("scenario")
        if not spec:
            raise _ApiError(400, "body needs \"scenario\" (or \"clear\")")
        try:
            out = self.api.cluster.load_scenario(
                str(spec), rounds=int(body.get("rounds", 128)),
                seed=body.get("seed"),
            )
        except (ValueError, KeyError, TypeError) as e:
            raise _ApiError(400, str(e)) from None
        self._send_json(out)

    def do_GET(self):  # noqa: N802
        with self.api.cluster._api_req_lock:
            self.api.cluster._api_requests += 1
        self._trace_ctx = None
        if not self._authz():
            return
        path, _, qs = self.path.partition("?")
        params = _parse_qs(qs)
        name = f"http GET {path}"
        m = _SUB_PATH.match(path)
        if m:
            self._traced(
                name, lambda: self._get_subscription(m.group(1), params),
                streaming=True,
            )
        elif path == "/v1/cluster/members":
            self._traced(
                name, lambda: self._send_json(self.api.cluster.members())
            )
        elif path == "/v1/table_stats":
            self._traced(
                name,
                lambda: self._post_table_stats(params, body={"tables": []}),
            )
        elif path == "/v1/flight":
            self._traced(name, lambda: self._get_flight(params))
        elif path == "/v1/sweep":
            self._traced(name, self._get_sweep)
        elif path == "/v1/perf":
            self._traced(name, self._get_perf)
        elif path == "/v1/doctor":
            self._traced(name, self._get_doctor)
        elif path == "/v1/probes":
            self._traced(name, lambda: self._get_probes(params))
        elif path == "/v1/faults":
            self._traced(
                name,
                lambda: self._send_json(self.api.cluster.fault_report()),
            )
        elif path == "/v1/workload":
            # the last load-harness run's report (corro_sim/workload/):
            # sub-delivery latency quantiles, coalescing, query fan — 404
            # until a load has been driven through this cluster
            self._traced(name, self._get_workload)
        elif path == "/v1/changes":
            self._traced(name, lambda: self._get_changes(params))
        elif path == "/metrics":
            self._traced(name, self._get_metrics)
        else:
            self._traced(name, lambda: self._send_json(
                {"error": "not found"}, status=404))

    # GET /v1/changes?offset=N&limit=K — relay a growing ND-JSON
    # changeset feed by line position: the serving side of the twin's
    # live HTTP watch (corro_sim/io/feedsource.py HTTPWatchSource). The
    # body is raw ND-JSON starting at line `offset`; an unterminated
    # final line is served as-is (the watcher holds torn fragments back
    # and re-fetches), so the relay never invents a newline the writer
    # has not committed.
    def _get_changes(self, params):
        path = getattr(self.api, "feed_path", None)
        if path is None:
            raise _ApiError(
                404, "no changeset feed attached to this server "
                     "(ApiServer(feed_path=...))"
            )
        try:
            offset = max(0, int(params.get("offset", "0")))
            limit = int(params.get("limit", "4096"))
        except ValueError:
            raise _ApiError(400, "offset/limit must be integers") \
                from None
        limit = max(1, min(limit, 65536))
        out: list = []
        try:
            with open(path, "rb") as f:
                for i, raw in enumerate(f):
                    if i < offset:
                        continue
                    out.append(raw)
                    if len(out) >= limit:
                        break
        except OSError as e:
            raise _ApiError(503, f"feed unreadable: {e}") from None
        body = b"".join(out)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _get_workload(self):
        rep = getattr(self.api.cluster, "workload_report", None)
        if rep is None:
            raise _ApiError(
                404, "no workload has been driven through this cluster "
                     "(corro-sim load, corro_sim.workload.harness)"
            )
        self._send_json(rep)

    # POST /v1/transactions — ExecResponse; statement errors come back as
    # per-statement {"error"} results with HTTP 200, like the reference.
    def _post_transactions(self, params):
        stmts = self._body_json()
        if not isinstance(stmts, list):
            raise _ApiError(400, "body must be a JSON array of statements")
        t0 = time.perf_counter()
        try:
            resp = self.api.cluster.execute(stmts, node=self._node(params))
        except ExecError as e:
            resp = {
                "results": [{"error": str(e)}],
                "time": time.perf_counter() - t0,
                "version": None,
            }
        self._send_json(resp)

    def _post_queries(self, params):
        stmt = self._body_json()
        node = self._node(params)
        sql, bound, structured = _parse_body_statement(stmt)
        self._start_stream()
        t0 = time.perf_counter()
        try:
            # binding errors stream as QueryEvent::Error like any other
            # query failure (the reference's api_v1_queries streams them).
            # Structured statements always bind — a placeholder with an
            # empty params list must fail as a binding error, not as a
            # downstream '?' syntax error.
            if structured:
                from corro_sim.api.statements import bind_params

                sql = bind_params(sql, bound)
            events = self.api.cluster.query(sql, node=node)
        except Exception as e:  # streamed QueryEvent::Error, like reference
            self._stream_events([{"error": str(e)}])
            return
        events = _with_eoq_time(events, time.perf_counter() - t0)
        self._stream_events(events)

    def _post_subscriptions(self, params):
        stmt = self._body_json()
        node = self._node(params)
        skip_rows = params.get("skip_rows", "") in ("true", "1")
        sql = _sql_of_body(stmt)
        cluster = self.api.cluster
        try:
            sub_id, initial, q = cluster.subscribe_attached(sql, node=node)
        except Exception as e:
            raise _ApiError(400, str(e)) from None
        try:
            # hash the *normalized* SQL so create and re-attach agree
            # (the reference hashes the deduped statement the same way)
            norm = cluster.subs.get(sub_id).select.normalized()
            self._start_stream(
                headers={
                    "corro-query-id": sub_id,
                    "corro-query-hash": query_hash(norm),
                }
            )
            if not skip_rows:
                self._stream_events(initial)
            else:
                # skip_rows still announces where the change feed starts
                eoq = [e for e in initial if "eoq" in e]
                self._stream_events(eoq)
            self._tail(q)
        finally:
            cluster.sub_detach_queue(sub_id, q)

    def _get_subscription(self, sub_id: str, params):
        cluster = self.api.cluster
        skip_rows = params.get("skip_rows", "") in ("true", "1")
        from_raw = params.get("from")
        from_id = None
        if from_raw is not None:
            try:
                from_id = int(from_raw)
            except ValueError:
                raise _ApiError(400, "from must be an integer") from None
        try:
            initial, q = cluster.sub_attach(
                sub_id, from_change_id=from_id, skip_rows=skip_rows
            )
        except KeyError:
            raise _ApiError(404, f"no such subscription {sub_id!r}") from None
        if initial is None:
            # compacted past `from` — reference 404s; resubscribe
            raise _ApiError(404, f"change id {from_id} no longer buffered")
        m = cluster.subs.get(sub_id)
        try:
            self._start_stream(
                headers={
                    "corro-query-id": sub_id,
                    "corro-query-hash": query_hash(m.select.normalized()),
                }
            )
            self._stream_events(initial)
            self._tail(q)
        finally:
            cluster.sub_detach_queue(sub_id, q)

    def _tail(self, q) -> None:
        """Forward live events until the client hangs up or shutdown.

        Hangup on an *idle* stream is detected by readability: the client
        sends nothing after its request, so a readable socket means EOF —
        without this, an event-less subscription would pin its handler
        thread and queue forever."""
        trip = self.api.cluster.tripwire
        sock = self.connection
        while not trip.tripped and not self.api._closing:
            if q:
                batch = []
                while q:
                    batch.append(q.popleft())
                self.api.cluster.channels.on_recv("subs_events", len(batch))
                try:
                    self._stream_events(batch)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    return
            else:
                readable, _, _ = select.select([sock], [], [], _POLL_S)
                if readable:
                    return  # EOF (or protocol violation) — hang up

    def _post_migrations(self, params):
        stmts = self._body_json()
        if isinstance(stmts, str):
            stmts = [stmts]
        if not isinstance(stmts, list) or not all(
            isinstance(s, str) for s in stmts
        ):
            raise _ApiError(400, "body must be DDL statement string(s)")
        sql = ";\n".join(s.rstrip().rstrip(";") for s in stmts)
        try:
            plan = self.api.cluster.migrate(sql)
        except Exception as e:
            raise _ApiError(400, str(e)) from None
        self._send_json(plan)

    def _post_table_stats(self, params, body=None):
        req = body if body is not None else self._body_json()
        want = req.get("tables") if isinstance(req, dict) else None
        stats = self.api.cluster.table_stats()
        invalid = [t for t in (want or []) if t not in stats]
        picked = (
            {t: stats[t] for t in want if t in stats} if want else stats
        )
        total = sum(
            sum(s["live_rows_per_node"]) for s in picked.values()
        )
        self._send_json(
            {
                "total_row_count": total,
                "invalid_tables": invalid,
                "tables": picked,
            }
        )

    def _get_flight(self, params):
        """GET /v1/flight — the cluster's per-round telemetry timeline.

        ``?n=K`` trims to the last K rounds; ``?format=ndjson`` returns
        the raw ND-JSON export (loadable by ``FlightRecorder.load``)."""
        fl = getattr(self.api.cluster, "flight", None)
        if fl is None:
            raise _ApiError(404, "no flight recorder attached")
        if params.get("format") == "ndjson":
            body = fl.to_ndjson().encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        last = None
        if params.get("n"):
            try:
                last = int(params["n"])
            except ValueError:
                raise _ApiError(400, "n must be an integer") from None
            if last < 0:
                raise _ApiError(400, "n must be >= 0")
        self._send_json(fl.timeline(last_rounds=last))

    def _get_sweep(self):
        """GET /v1/sweep — the fleet observatory's live sweep snapshot
        (corro_sim/obs/lanes.py): per-chunk lane-state counts, the
        one-char-per-lane state string and cumulative wasted
        frozen-lane rounds while a sweep runs in this process, the
        final summary after. 404 until a sweep has run."""
        from corro_sim.obs.lanes import sweep_status

        st = sweep_status()
        if st is None:
            raise _ApiError(404, "no sweep has run in this process")
        self._send_json(st)

    def _get_perf(self):
        """GET /v1/perf — the performance-ledger snapshot
        (corro_sim/obs/ledger.py, doc/performance.md §9): the last
        ledger operation run in THIS process (ingest/show/check or a
        bench/sweep/twin auto-append), falling back to the committed
        seed-history trajectory. 404 only when neither exists."""
        from corro_sim.obs import ledger as perf_ledger

        st = perf_ledger.perf_status()
        if st is None:
            golden = perf_ledger.golden_ledger_path()
            if not os.path.exists(golden):
                raise _ApiError(
                    404, "no perf-ledger operation has run in this "
                         "process and no committed seed ledger exists "
                         "(corro-sim perf --ingest)"
                )
            records, _bad = perf_ledger.load_ledger(golden)
            st = {
                "ledger": golden,
                "trajectory": perf_ledger.build_trajectory(records),
            }
        self._send_json(st)

    def _get_doctor(self):
        """GET /v1/doctor — the cross-artifact diagnosis snapshot
        (corro_sim/obs/doctor.py, doc/observability.md §8): the last
        `corro-sim doctor` report produced in THIS process, falling
        back to a fresh diagnosis over the committed golden ledger.
        404 only when neither exists."""
        from corro_sim.obs import doctor as doctor_mod
        from corro_sim.obs import ledger as perf_ledger

        st = doctor_mod.doctor_status()
        if st is None:
            golden = perf_ledger.golden_ledger_path()
            if not os.path.exists(golden):
                raise _ApiError(
                    404, "no diagnosis has run in this process and no "
                         "committed golden ledger exists to diagnose "
                         "(corro-sim doctor <artifacts>)"
                )
            st = doctor_mod.diagnose([golden])
            doctor_mod.update_doctor_gauges(st)
        self._send_json(st)

    def _get_probes(self, params):
        """GET /v1/probes — probe-tracer provenance + lag observatory.

        Default: JSON report (per-probe summaries with BFS stretch,
        infection trees, node lag). ``?format=ndjson`` streams the raw
        probe journal; ``?format=trace`` returns Chrome trace-event JSON
        loadable in Perfetto / chrome://tracing."""
        cluster = self.api.cluster
        fmt = params.get("format")
        if fmt in ("ndjson", "trace"):
            tr = cluster.probe_trace()
            if tr is None:
                raise _ApiError(
                    404,
                    "probe tracer disabled — start the cluster with "
                    "cfg_overrides={'probes': K}",
                )
            if fmt == "ndjson":
                body = tr.to_ndjson().encode()
                ctype = "application/x-ndjson"
            else:
                body = (json.dumps(tr.to_chrome_trace()) + "\n").encode()
                ctype = "application/json"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self._send_json(cluster.probe_report())

    def _get_metrics(self):
        from corro_sim.utils.metrics import render_prometheus

        text = render_prometheus(self.api.cluster)
        body = text.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _parse_body_statement(stmt):
    """A request body as ``(sql, params, structured)``; bad wire shapes
    (``corro-api-types/src/lib.rs:181-201``) → 400. Binding stays with the
    caller: queries stream binding errors, subscriptions 400 them."""
    if isinstance(stmt, str):
        return stmt, [], False
    from corro_sim.api.statements import parse_statement

    try:
        sql, params = parse_statement(stmt)
    except Exception as e:
        raise _ApiError(400, str(e)) from None
    return sql, params, True


def _sql_of_body(stmt) -> str:
    """A request body as SQL text with params INLINED as literals — the
    reference binds them in ``api_v1_queries`` and inlines them for
    subscriptions via ``expand_sql`` (``api/public/pubsub.rs:226-331``);
    inlining makes subscription dedupe-by-normalized-SQL see the bound
    values. Structured statements always bind: a placeholder with an empty
    params list is a binding error here, not a '?' syntax error later."""
    sql, params, structured = _parse_body_statement(stmt)
    if not structured:
        return sql
    from corro_sim.api.statements import bind_params

    try:
        return bind_params(sql, params)
    except Exception as e:
        raise _ApiError(400, str(e)) from None




def _as_wire(e) -> dict:
    """Events are dicts already; buffered SubEvents expose as_json()."""
    return e if isinstance(e, dict) else e.as_json()


def _with_eoq_time(events, elapsed: float):
    out = []
    for e in events:
        if isinstance(e, dict) and "eoq" in e:
            eoq = dict(e["eoq"])
            eoq["time"] = elapsed
            e = {"eoq": eoq}
        out.append(e)
    return out


class ApiServer:
    """Threaded HTTP front-end bound to one LiveCluster.

    Lifecycle mirrors ``setup_http_api_handler`` (``agent/util.rs:167-296``):
    bind, serve until tripwire, drain. ``tick_interval`` optionally runs a
    background gossip ticker so subscription tails advance without writes
    (the reference's agents gossip on their own clock)."""

    def __init__(
        self,
        cluster: LiveCluster,
        host: str = "127.0.0.1",
        port: int = 0,
        authz_token: str | None = None,
        tick_interval: float | None = None,
        ssl_context=None,
        feed_path: str | None = None,
    ):
        self.cluster = cluster
        self.authz_token = authz_token
        # ND-JSON changeset feed relayed at GET /v1/changes — the
        # serving side of the twin's HTTPWatchSource (`twin
        # http://host/v1/changes --tail`); 404 when unset
        self.feed_path = feed_path
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.api = self  # type: ignore[attr-defined]
        self._tls = ssl_context is not None
        if ssl_context is not None:
            # TLS (optionally mutual) on the API listener — the posture
            # the reference applies to its gossip endpoint
            # (api/peer.rs:129-343). Wrap per-CONNECTION with the
            # handshake deferred: an eager handshake would run inside the
            # single accept loop, letting one stalled client wedge every
            # other connection. Deferred, OpenSSL negotiates on the
            # handler thread's first read — the same exposure profile as
            # a plain-HTTP silent client.
            httpd = self._httpd
            plain_get_request = httpd.get_request

            def get_request():
                sock, addr = plain_get_request()
                return (
                    ssl_context.wrap_socket(
                        sock, server_side=True,
                        do_handshake_on_connect=False,
                    ),
                    addr,
                )

            httpd.get_request = get_request
        self._thread: threading.Thread | None = None
        self._ticker: threading.Thread | None = None
        self._tick_interval = tick_interval
        self._closing = False

    @property
    def addr(self) -> tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.addr
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{host}:{port}"

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="corro-api", daemon=True
        )
        self._thread.start()
        if self._tick_interval:
            self._ticker = threading.Thread(
                target=self._tick_loop, name="corro-ticker", daemon=True
            )
            self._ticker.start()
        return self

    def _tick_loop(self):
        trip = self.cluster.tripwire
        while not trip.tripped and not self._closing:
            self.cluster.tick(1)
            time.sleep(self._tick_interval)

    def close(self) -> None:
        self._closing = True
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        if self._ticker:
            self._ticker.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
