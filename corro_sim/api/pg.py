"""Postgres wire-protocol API over a LiveCluster (corro-pg equivalent).

The reference runs a pgwire-v3 server on ``api.pg.addr`` that lets any
Postgres client/driver talk to a corrosion agent: it peeks for an
SSLRequest (`corro-pg/src/lib.rs:424`), answers the startup handshake,
implements both the simple ('Q') and the extended
(Parse/Bind/Describe/Execute/Close/Sync/Flush) query protocols with
prepared statements + portals (`lib.rs:719-1600`), translates Postgres
SQL to its storage layer, and serves minimal ``pg_catalog`` tables so
drivers' introspection queries work (`vtab/pg_*.rs`). Errors carry real
SQLSTATE codes (`sql_state.rs`).

This is the TPU-native equivalent: same wire protocol, same session
machinery, but statements execute against the simulated cluster —
SELECTs through the compiled rank-space query path, DML through the
changeset write path. The ``database`` startup parameter selects the
node ordinal to talk to (``node<K>`` → node K, anything else → node 0),
mirroring "which agent did you connect to".

Transaction semantics: ``BEGIN … COMMIT`` buffers DML and commits it as
ONE changeset batch (atomic, like the reference's single SQLite tx);
autocommit statements are one transaction each. Reads and rows-affected
counts inside an open transaction observe the transaction's own buffered
writes via the cluster's staged-write overlay (``plan_overlay`` — the
same mechanism ``execute()`` uses for in-batch visibility, matching the
reference's single-SQLite-tx semantics, api/public/mod.rs:104-131).
"""

from __future__ import annotations

import re
import socket
import socketserver
import struct
import threading

from corro_sim.api.sql_state import code as sqlstate
from corro_sim.api.statements import StatementError, bind_params
from corro_sim.schema import SchemaError
from corro_sim.subs.query import QueryError, eval_predicate_py, parse_query

PROTO_V3 = 196608  # 3.0
SSL_REQUEST = 80877103
GSSENC_REQUEST = 80877104
CANCEL_REQUEST = 80877102

# type OIDs (pg_type.h)
OID_BOOL = 16
OID_BYTEA = 17
OID_INT8 = 20
OID_INT4 = 23
OID_TEXT = 25
OID_FLOAT8 = 701

_TYPLEN = {OID_BOOL: 1, OID_BYTEA: -1, OID_INT8: 8, OID_INT4: 4,
           OID_TEXT: -1, OID_FLOAT8: 8}


class PgError(Exception):
    """Protocol-level error → ErrorResponse with a SQLSTATE code."""

    def __init__(self, condition: str, message: str):
        super().__init__(message)
        self.condition = condition
        self.code = sqlstate(condition)


def _affinity_oid(decl_type: str) -> int:
    """SQLite declared type → result OID, by SQLite affinity rules
    (schema.rs:803-834 resolves affinity the same way)."""
    t = (decl_type or "").upper()
    if "INT" in t:
        return OID_INT8
    if "CHAR" in t or "CLOB" in t or "TEXT" in t:
        return OID_TEXT
    if t == "" or "BLOB" in t:
        return OID_BYTEA
    if "REAL" in t or "FLOA" in t or "DOUB" in t:
        return OID_FLOAT8
    return OID_TEXT  # NUMERIC affinity: render as text


# ------------------------------------------------------------ wire encoding


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


def _msg(tag: bytes, payload: bytes = b"") -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


def msg_auth_ok() -> bytes:
    return _msg(b"R", struct.pack("!I", 0))


def msg_parameter_status(k: str, v: str) -> bytes:
    return _msg(b"S", _cstr(k) + _cstr(v))


def msg_backend_key(pid: int, secret: int) -> bytes:
    return _msg(b"K", struct.pack("!II", pid, secret))


def msg_ready(status: bytes) -> bytes:
    return _msg(b"Z", status)


def msg_row_description(fields) -> bytes:
    """fields: [(name, oid)]"""
    out = [struct.pack("!H", len(fields))]
    for name, oid in fields:
        out.append(_cstr(name))
        out.append(struct.pack("!IHIhih", 0, 0, oid,
                               _TYPLEN.get(oid, -1), -1, 0))
    return _msg(b"T", b"".join(out))


def msg_data_row(cells: list[bytes | None]) -> bytes:
    out = [struct.pack("!H", len(cells))]
    for c in cells:
        if c is None:
            out.append(struct.pack("!i", -1))
        else:
            out.append(struct.pack("!I", len(c)) + c)
    return _msg(b"D", b"".join(out))


def msg_command_complete(tag: str) -> bytes:
    return _msg(b"C", _cstr(tag))


def msg_error(code_: str, message: str, severity: str = "ERROR") -> bytes:
    body = (b"S" + _cstr(severity) + b"V" + _cstr(severity)
            + b"C" + _cstr(code_) + b"M" + _cstr(message) + b"\x00")
    return _msg(b"E", body)


def msg_notice(code_: str, message: str) -> bytes:
    body = (b"S" + _cstr("WARNING") + b"V" + _cstr("WARNING")
            + b"C" + _cstr(code_) + b"M" + _cstr(message) + b"\x00")
    return _msg(b"N", body)


def msg_parameter_description(oids) -> bytes:
    return _msg(b"t", struct.pack("!H", len(oids))
                + b"".join(struct.pack("!I", o) for o in oids))


# ----------------------------------------------------------- value encoding


def _encode_cell(v, oid: int, fmt: int) -> bytes | None:
    if v is None:
        return None
    if fmt == 0:  # text
        if isinstance(v, bool):
            return b"t" if v else b"f"
        if isinstance(v, bytes):
            return b"\\x" + v.hex().encode()
        if isinstance(v, float):
            return repr(v).encode()
        return str(v).encode()
    # binary
    if oid == OID_INT8:
        return struct.pack("!q", int(v))
    if oid == OID_INT4:
        return struct.pack("!i", int(v))
    if oid == OID_FLOAT8:
        return struct.pack("!d", float(v))
    if oid == OID_BOOL:
        return b"\x01" if v else b"\x00"
    if oid == OID_BYTEA:
        return v if isinstance(v, bytes) else str(v).encode()
    return str(v).encode()  # text-ish


def _decode_param(raw: bytes | None, oid: int, fmt: int):
    if raw is None:
        return None
    if oid == 0 and fmt == 0:
        # Unspecified type: infer, but only from a *canonical* numeric
        # rendering so TEXT-bound values like '007' or '1e3' round-trip
        # unchanged (a real PG resolves unknown params from context; the
        # canonicality check is the conservative approximation).
        s = raw.decode("utf-8", "replace")
        try:
            if str(int(s)) == s:
                return int(s)
        except ValueError:
            pass
        try:
            if repr(float(s)) == s:
                return float(s)
        except ValueError:
            pass
        return s
    if fmt == 1:  # binary
        try:
            if oid == OID_INT8:
                return struct.unpack("!q", raw)[0]
            if oid == OID_INT4:
                return struct.unpack("!i", raw)[0]
            if oid == OID_FLOAT8:
                return struct.unpack("!d", raw)[0]
            if oid == OID_BOOL:
                return raw != b"\x00"
            if oid == OID_BYTEA:
                return raw
        except struct.error:
            raise PgError("invalid_binary_representation",
                          f"bad binary value for oid {oid}") from None
        return raw.decode("utf-8", "replace")
    # text format
    s = raw.decode("utf-8", "replace")
    try:
        if oid in (OID_INT8, OID_INT4):
            return int(s)
        if oid == OID_FLOAT8:
            return float(s)
        if oid == OID_BOOL:
            return s.lower() in ("t", "true", "1", "on", "yes")
        if oid == OID_BYTEA:
            if s.startswith("\\x"):
                return bytes.fromhex(s[2:])
            return s.encode()
    except ValueError:
        raise PgError("invalid_text_representation",
                      f"invalid input for oid {oid}: {s!r}") from None
    return s


# ------------------------------------------------------------- SQL surface

_LEAD = re.compile(r"^\s*(?:--[^\n]*\n\s*|/\*.*?\*/\s*)*([A-Za-z]+)",
                   re.DOTALL)


def classify(sql: str) -> str:
    m = _LEAD.match(sql)
    if not m:
        return "EMPTY"
    w = m.group(1).upper()
    if w == "START":
        return "BEGIN"
    if w == "END":
        return "COMMIT"
    if w == "ABORT":
        return "ROLLBACK"
    return w


def split_statements(sql: str) -> list[str]:
    """Split a simple-query message on top-level semicolons — aware of
    string literals, ``--`` line comments, and ``/* */`` block comments."""
    out, buf = [], []
    for kind, seg in _lex_segments(sql):
        if kind != "text":
            buf.append(seg)
            continue
        while True:
            cut = seg.find(";")
            if cut == -1:
                buf.append(seg)
                break
            buf.append(seg[:cut])
            out.append("".join(buf))
            buf = []
            seg = seg[cut + 1:]
    out.append("".join(buf))
    return [s for s in (x.strip() for x in out) if s]


def _lex_segments(sql: str):
    """One quote/comment-aware scanner for the statement-level helpers.

    Yields (kind, text) with kind ∈ {'text', 'str', 'line', 'block'}:
    string literals (including their quotes), ``--`` line comments
    (excluding the terminating newline), ``/* */`` block comments, and
    the plain SQL text between them."""
    i, n, start = 0, len(sql), 0
    while i < n:
        c = sql[i]
        if c == "'":
            if start < i:
                yield "text", sql[start:i]
            j = i + 1
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        j += 2
                        continue
                    j += 1
                    break
                j += 1
            else:
                j = n
            yield "str", sql[i:j]
            i = start = j
        elif c == "-" and i + 1 < n and sql[i + 1] == "-":
            if start < i:
                yield "text", sql[start:i]
            end = sql.find("\n", i)
            end = n if end == -1 else end
            yield "line", sql[i:end]
            i = start = end  # the newline stays in the next text segment
        elif c == "/" and i + 1 < n and sql[i + 1] == "*":
            if start < i:
                yield "text", sql[start:i]
            end = sql.find("*/", i + 2)
            end = n if end == -1 else end + 2
            yield "block", sql[i:end]
            i = start = end
        else:
            i += 1
    if start < n:
        yield "text", sql[start:n]


def count_params(sql: str) -> int:
    """Highest $n placeholder index outside string literals (0 if none)."""
    high = 0
    for kind, seg in _lex_segments(sql):
        if kind == "text":
            for m in re.finditer(r"\$(\d+)", seg):
                high = max(high, int(m.group(1)))
    return high


def strip_comments(sql: str) -> str:
    """Remove -- and /* */ comments (quote-aware): the rank-space SQL
    tokenizer has no comment syntax, and comments carry no semantics."""
    out = []
    for kind, seg in _lex_segments(sql):
        if kind in ("text", "str"):
            out.append(seg)
        elif kind == "block":
            out.append(" ")
    return "".join(out)


_COPY_RE = re.compile(
    r"COPY\s+(?:\(\s*(?P<query>.+?)\s*\)|"
    r"(?P<table>[A-Za-z_]\w*)\s*(?:\(\s*(?P<cols>[^)]*?)\s*\))?)"
    r"\s+TO\s+STDOUT"
    r"(?:\s+(?:WITH\s+)?\(\s*(?P<opts>[^)]*?)\s*\))?\s*$",
    re.IGNORECASE | re.DOTALL,
)


def _copy_text_field(v, delim: str) -> str:
    """COPY text-format cell: ``\\N`` for NULL, backslash escapes for
    backslash/newline/CR/tab plus the delimiter (the format psql's
    \\copy parses back)."""
    if v is None:
        return r"\N"
    if isinstance(v, (bytes, bytearray)):
        s = "\\x" + bytes(v).hex()
    else:
        s = str(v)
    s = (s.replace("\\", "\\\\").replace("\n", "\\n")
          .replace("\r", "\\r").replace("\t", "\\t"))
    if delim not in ("\t", "\\"):  # tab/backslash already escaped above
        s = s.replace(delim, "\\" + delim)
    return s


def _copy_csv_field(v, delim: str) -> str:
    """COPY csv-format cell: empty for NULL, RFC-4180 quoting."""
    if v is None:
        return ""
    if isinstance(v, (bytes, bytearray)):
        s = "\\x" + bytes(v).hex()
    else:
        s = str(v)
    if any(c in s for c in (delim, '"', "\n", "\r")):
        return '"' + s.replace('"', '""') + '"'
    return s


_TAGS = {
    "INSERT": lambda n: f"INSERT 0 {n}",
    "UPDATE": lambda n: f"UPDATE {n}",
    "DELETE": lambda n: f"DELETE {n}",
    "SELECT": lambda n: f"SELECT {n}",
}


# --------------------------------------------------------------- catalogs


_CATALOG_NAMES = frozenset(
    ("pg_type", "pg_class", "pg_namespace", "pg_database", "pg_attribute",
     "pg_range", "pg_index", "pg_constraint"))


def _catalog_tables(cluster) -> dict[str, tuple[list, list, list]]:
    """Minimal pg_catalog contents, synthesized from the live schema —
    the vtab set the reference implements (`corro-pg/src/vtab/`).

    Each entry is (column names, rows, column OIDs); the static OIDs keep
    the simple and extended protocols' type reporting identical."""
    I8, TX = OID_INT8, OID_TEXT
    types = [
        ("bool", OID_BOOL, 1), ("bytea", OID_BYTEA, -1),
        ("int8", OID_INT8, 8), ("int4", OID_INT4, 4),
        ("text", OID_TEXT, -1), ("float8", OID_FLOAT8, 8),
    ]
    pg_type = (["oid", "typname", "typlen", "typnamespace"],
               [[oid, name, tlen, 11] for name, oid, tlen in types],
               [I8, TX, I8, I8])
    tables = list(cluster.layout.schema.tables)
    pg_class = (["oid", "relname", "relnamespace", "relkind"],
                [[16384 + i, t, 2200, "r"] for i, t in enumerate(tables)],
                [I8, TX, I8, TX])
    pg_namespace = (["oid", "nspname"],
                    [[11, "pg_catalog"], [2200, "public"]], [I8, TX])
    pg_database = (["oid", "datname"], [[1, "corro"]], [I8, TX])
    typlen_of = {oid: tlen for _, oid, tlen in types}
    pg_attribute_rows = []
    pg_index_rows = []
    pg_constraint_rows = []
    for i, t in enumerate(tables):
        tbl = cluster.layout.schema.tables[t]
        attnum = {c.name: j + 1 for j, c in enumerate(tbl.columns)}
        # pk-declaration order, not column order: composite keys reflect
        # as PRIMARY KEY (b, a) declared them (Table.pk preserves it)
        pk_nums = [attnum[name] for name in tbl.pk]
        for j, col in enumerate(tbl.columns):
            oid = _affinity_oid(col.type)
            pg_attribute_rows.append(
                [16384 + i, col.name, j + 1, oid,
                 typlen_of.get(oid, -1), -1,
                 "t" if (col.primary_key or not col.nullable) else "f",
                 "t" if col.default is not None else "f", "f"])
        # WITHOUT ROWID pk as both an index and a 'p' constraint — the
        # two places ORMs look for primary-key columns (corro-pg vtab
        # analog: pg_index.indisprimary / pg_constraint.contype = 'p')
        pg_index_rows.append(
            [24576 + i, 16384 + i, len(pk_nums), "t", "t",
             " ".join(str(x) for x in pk_nums)])
        pg_constraint_rows.append(
            [f"{t}_pkey", 16384 + i, "p", 2200,
             "{" + ",".join(str(x) for x in pk_nums) + "}"])
    pg_attribute = (
        ["attrelid", "attname", "attnum", "atttypid", "attlen",
         "atttypmod", "attnotnull", "atthasdef", "attisdropped"],
        pg_attribute_rows, [I8, TX, I8, I8, I8, I8, TX, TX, TX])
    pg_index = (
        ["indexrelid", "indrelid", "indnatts", "indisunique",
         "indisprimary", "indkey"],
        pg_index_rows, [I8, I8, I8, TX, TX, TX])
    pg_constraint = (
        ["conname", "conrelid", "contype", "connamespace", "conkey"],
        pg_constraint_rows, [TX, I8, TX, I8, TX])
    return {
        "pg_type": pg_type, "pg_class": pg_class,
        "pg_namespace": pg_namespace, "pg_database": pg_database,
        "pg_attribute": pg_attribute, "pg_range": (["rngtypid"], [], [I8]),
        "pg_index": pg_index, "pg_constraint": pg_constraint,
    }


# ---------------------------------------------------------------- session


class _Prepared:
    __slots__ = ("sql", "kind", "param_oids")

    def __init__(self, sql, kind, param_oids):
        self.sql = sql
        self.kind = kind
        self.param_oids = param_oids


class _Portal:
    __slots__ = ("stmt", "bound_sql", "result_formats", "rows", "fields",
                 "pos", "tag_n")

    def __init__(self, stmt, bound_sql, result_formats):
        self.stmt = stmt
        self.bound_sql = bound_sql
        self.result_formats = result_formats
        self.rows = None      # materialized on first Execute
        self.fields = None
        self.pos = 0
        self.tag_n = 0


class _Session:
    """One client connection's state: tx, prepared statements, portals."""

    def __init__(self, server, sock):
        self.server = server
        self.cluster = server.cluster
        self.sock = sock
        self.node = 0
        self.prepared: dict[str, _Prepared] = {}
        self.portals: dict[str, _Portal] = {}
        self.tx_writes: list | None = None  # None = autocommit
        self.tx_failed = False
        # incrementally-built staged-write overlay of the open tx + its
        # validity key (universe/layout generations, planned count)
        self._tx_ov = None
        self._tx_ov_key = None
        self.params = {
            "server_version": "14.0 (corro-sim)",
            "server_encoding": "UTF8",
            "client_encoding": "UTF8",
            "DateStyle": "ISO, MDY",
            "integer_datetimes": "on",
            "standard_conforming_strings": "on",
            "TimeZone": "UTC",
            "is_superuser": "on",
        }

    # --------------------------------------------------------------- io
    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client closed")
            buf += chunk
        return buf

    def send(self, *msgs: bytes) -> None:
        self.sock.sendall(b"".join(msgs))

    def tx_status(self) -> bytes:
        if self.tx_writes is None:
            return b"I"
        return b"E" if self.tx_failed else b"T"

    # ------------------------------------------------------------ startup
    def startup(self) -> bool:
        while True:
            (length,) = struct.unpack("!I", self._read_exact(4))
            body = self._read_exact(length - 4)
            (code_,) = struct.unpack("!I", body[:4])
            if code_ in (SSL_REQUEST, GSSENC_REQUEST):
                self.sock.sendall(b"N")  # no TLS on this listener
                continue
            if code_ == CANCEL_REQUEST:
                return False
            if code_ != PROTO_V3:
                self.send(msg_error(sqlstate("protocol_violation"),
                                    f"unsupported protocol {code_}"))
                return False
            kv = body[4:].split(b"\x00")
            opts = {}
            for k, v in zip(kv[::2], kv[1::2]):
                if k:
                    opts[k.decode()] = v.decode()
            db = opts.get("database", "")
            m = re.fullmatch(r"node(\d+)", db)
            if m:
                node = int(m.group(1))
                if not (0 <= node < self.cluster.cfg.num_nodes):
                    self.send(msg_error(sqlstate("invalid_catalog_name"),
                                        f'database "{db}" does not exist'))
                    return False
                self.node = node
            out = [msg_auth_ok()]
            for k, v in self.params.items():
                out.append(msg_parameter_status(k, v))
            out.append(msg_backend_key(threading.get_ident() & 0x7FFFFFFF,
                                       0x5EED))
            out.append(msg_ready(b"I"))
            self.send(*out)
            return True

    # --------------------------------------------------------- execution
    def _fields_for_select(self, select, cols: list) -> list:
        t = self.cluster.layout.schema.tables.get(select.table)
        by_name = {c.name: c for c in t.columns} if t else {}
        fields = []
        for c in cols:
            col = by_name.get(c)
            fields.append((c, _affinity_oid(col.type) if col else OID_TEXT))
        return fields

    @staticmethod
    def _strip_catalog_schema(sql: str) -> str:
        # only in table position, so a 'pg_catalog.x' string literal survives
        return re.sub(r"(\bFROM\s+)pg_catalog\.", r"\1", sql,
                      flags=re.IGNORECASE)

    def run_select(self, sql: str):
        """→ (fields [(name, oid)], rows [list])"""
        sql = self._strip_catalog_schema(sql)
        try:
            select = parse_query(sql)
        except QueryError as e:
            raise PgError("syntax_error", str(e)) from None
        if select.table in _CATALOG_NAMES:
            all_cols, all_rows, all_oids = \
                _catalog_tables(self.cluster)[select.table]
            cols = list(select.columns) if select.columns else all_cols
            idx = {}
            for c in cols:
                if c not in all_cols:
                    raise PgError("undefined_column",
                                  f'column "{c}" does not exist')
                idx[c] = all_cols.index(c)
            rows = []
            col_pos = {c: i for i, c in enumerate(all_cols)}
            for r in all_rows:
                # unmodeled catalog columns read as NULL (drivers probe
                # many pg_catalog columns; erroring would break them)
                get = lambda name: (  # noqa: E731
                    r[col_pos[name]] if name in col_pos else None)
                if select.where is not None and not eval_predicate_py(
                        select.where, get):
                    continue
                rows.append([r[idx[c]] for c in cols])
            fields = [(c, all_oids[idx[c]]) for c in cols]
            return fields, rows
        try:
            cols, rows = self.cluster.query_rows(
                sql, node=self.node, overlay=self._tx_overlay()
            )
        except (QueryError, SchemaError) as e:
            msg = str(e)
            cond = ("undefined_table" if "no such table" in msg
                    else "undefined_column" if "column" in msg
                    else "syntax_error")
            raise PgError(cond, msg) from None
        except KeyError as e:
            raise PgError("undefined_table",
                          f"relation {e} does not exist") from None
        if select.aggregates:
            # post-processed output: cols are already the final labels
            return self._agg_fields(select), rows
        if select.columns:
            # the matcher prepends pk row-key columns (like the reference's
            # injected __corro_pk_* aliases); a pg client gets exactly its
            # projection back
            want = list(select.columns)
            try:
                idx = [cols.index(c) for c in want]
            except ValueError as e:
                raise PgError("undefined_column", str(e)) from None
            rows = [[r[i] for i in idx] for r in rows]
            cols = want
        return self._fields_for_select(select, cols), rows

    def _agg_fields(self, select) -> list:
        """Result fields for an aggregate query, by SQLite type rules:
        COUNT → int8, AVG → float8, SUM/MIN/MAX and group columns → the
        argument column's affinity."""
        t = self.cluster.layout.schema.tables.get(select.table)
        by_name = {c.name: c for c in t.columns} if t else {}

        def col_oid(name):
            c = by_name.get(name)
            return _affinity_oid(c.type) if c else OID_TEXT

        fields = []
        for kind, item in select.items:
            if kind == "col":
                fields.append((item, col_oid(item)))
            elif item.fn == "COUNT":
                fields.append((item.label(), OID_INT8))
            elif item.fn == "AVG":
                fields.append((item.label(), OID_FLOAT8))
            else:  # SUM / MIN / MAX
                fields.append((item.label(), col_oid(item.col)))
        return fields

    def _ov_key(self, n_planned: int):
        cl = self.cluster
        return (
            n_planned,
            getattr(cl.universe, "version", 0),
            cl.layout.generation,
        )

    def _tx_overlay(self):
        """Staged-write overlay of the open transaction, or None.

        Built incrementally as statements buffer (O(1) planning per
        statement — replanning the whole buffer per use made transactions
        quadratic) and replanned wholesale only when a rank respace or a
        schema migration invalidated the staged coordinates. The overlay
        is a snapshot of committed state as of each statement's planning,
        the same visibility a reference SQLite transaction has."""
        if not self.tx_writes:
            return None
        if (
            self._tx_ov is None
            or self._tx_ov_key != self._ov_key(len(self.tx_writes))
        ):
            try:
                self._tx_ov, _ = self.cluster.plan_overlay(
                    self.tx_writes, node=self.node
                )
            except Exception as e:
                self._tx_ov = None
                raise PgError(self._write_cond(e), str(e)) from None
            self._tx_ov_key = self._ov_key(len(self.tx_writes))
        return self._tx_ov

    def run_write(self, sql: str) -> int:
        """Execute (autocommit) or buffer (explicit tx) one DML. Returns
        rows affected (in-tx: counted against the tx's own overlay, so a
        row inserted earlier in the tx is visible to a later UPDATE)."""
        if self.tx_writes is not None:
            base = self._tx_overlay()  # ({}, {}) when first statement
            if base is None:
                base = ({}, {})
            try:
                overlay, counts = self.cluster.plan_overlay(
                    [sql], node=self.node, base=base
                )
            except Exception as e:
                self._tx_ov = None  # base may be half-mutated
                raise PgError(self._write_cond(e), str(e)) from None
            self.tx_writes.append(sql)
            self._tx_ov = overlay
            self._tx_ov_key = self._ov_key(len(self.tx_writes))
            return counts[-1]
        try:
            resp = self.cluster.execute([sql], node=self.node)
        except Exception as e:  # ExecError and friends
            raise PgError(self._write_cond(e), str(e)) from None
        return int(resp["results"][0].get("rows_affected", 0))

    @staticmethod
    def _write_cond(e) -> str:
        msg = str(e)
        if "no such table" in msg:
            return "undefined_table"
        if "column" in msg:
            return "undefined_column"
        if "down" in msg:
            return "cannot_connect_now"
        return "syntax_error"

    def commit_tx(self) -> None:
        writes, self.tx_writes = self.tx_writes, None
        failed, self.tx_failed = self.tx_failed, False
        self._tx_ov = self._tx_ov_key = None
        if failed or not writes:
            return
        try:
            self.cluster.execute(writes, node=self.node)
        except Exception as e:
            raise PgError(self._write_cond(e), str(e)) from None

    def exec_one(self, sql: str) -> list[bytes]:
        """Execute one statement (simple protocol) → wire messages."""
        sql = strip_comments(sql).strip()
        kind = classify(sql)
        if kind == "EMPTY":
            return [_msg(b"I")]
        if self.tx_failed and kind not in ("COMMIT", "ROLLBACK"):
            raise PgError(
                "in_failed_sql_transaction",
                "current transaction is aborted, commands ignored until "
                "end of transaction block")
        if kind == "BEGIN":
            if self.tx_writes is not None:
                return [msg_notice(sqlstate("active_sql_transaction"),
                                   "there is already a transaction in "
                                   "progress"),
                        msg_command_complete("BEGIN")]
            self.tx_writes = []
            self.tx_failed = False
            self._tx_ov = self._tx_ov_key = None
            return [msg_command_complete("BEGIN")]
        if kind == "COMMIT":
            was_failed = self.tx_failed
            self.commit_tx()
            return [msg_command_complete(
                "ROLLBACK" if was_failed else "COMMIT")]
        if kind == "ROLLBACK":
            self.tx_writes = None
            self.tx_failed = False
            self._tx_ov = self._tx_ov_key = None
            return [msg_command_complete("ROLLBACK")]
        if kind == "SET":
            return [msg_command_complete("SET")]
        if kind == "SHOW":
            return self._exec_show(sql)
        if kind == "SELECT":
            fields, rows = self.run_select(sql)
            fmts = [0] * len(fields)
            out = [msg_row_description(fields)]
            for r in rows:
                out.append(msg_data_row([
                    _encode_cell(v, fields[i][1], fmts[i])
                    for i, v in enumerate(r)]))
            out.append(msg_command_complete(f"SELECT {len(rows)}"))
            return out
        if kind == "COPY":
            return self._exec_copy(sql)
        if kind in ("INSERT", "UPDATE", "DELETE"):
            n = self.run_write(sql)
            return [msg_command_complete(_TAGS[kind](n))]
        if kind == "CREATE":
            if self.tx_writes is not None:
                # schema changes apply immediately and cannot be rolled
                # back (drops are refused), so refuse transactional DDL
                raise PgError(
                    "active_sql_transaction",
                    "CREATE TABLE cannot run inside a transaction block")
            try:
                self.cluster.migrate(sql)
            except (SchemaError, ValueError) as e:
                raise PgError("invalid_table_definition", str(e)) from None
            return [msg_command_complete("CREATE TABLE")]
        raise PgError("feature_not_supported",
                      f"statement kind {kind} is not supported")

    def _exec_copy(self, sql: str) -> list[bytes]:
        """``COPY (query) TO STDOUT`` / ``COPY table [(cols)] TO STDOUT``
        with ``WITH (FORMAT text|csv [, HEADER])`` — the copy-out half of
        the protocol (CopyOutResponse / CopyData / CopyDone). COPY FROM
        STDIN is not accepted: writes go through INSERT like the
        reference's pg surface (`corro-pg` exposes no COPY either; this
        is the dump/export convenience ORMs and psql's \\copy use)."""
        m = _COPY_RE.match(sql.rstrip().rstrip(";"))
        if m is None:
            if re.search(r"\bFROM\s+STDIN\b", sql, re.IGNORECASE):
                raise PgError("feature_not_supported",
                              "COPY FROM STDIN is not supported; use "
                              "INSERT statements")
            raise PgError("syntax_error", "invalid COPY syntax")
        fmt, header, delim = "text", False, None
        opts_s = (m.group("opts") or "").strip()
        # quote-aware option scan: a comma inside '…' (e.g. DELIMITER ',')
        # must not split the list
        opt_pairs = re.findall(
            r"([A-Za-z_]+)(?:\s+('(?:[^']|'')*'|[^\s,()]+))?\s*(?:,|$)",
            opts_s) if opts_s else []
        if opts_s and sum(
                len(mm[0]) + len(mm[1]) for mm in opt_pairs) == 0:
            raise PgError("syntax_error", "invalid COPY options")
        for k, rawv in opt_pairs:
            k = k.upper()
            v = rawv.strip()
            if v.startswith("'") and v.endswith("'") and len(v) >= 2:
                v = v[1:-1].replace("''", "'")
            if k == "FORMAT":
                if v.lower() not in ("text", "csv"):
                    raise PgError("feature_not_supported",
                                  f'COPY format "{v}" not supported')
                fmt = v.lower()
            elif k == "HEADER":
                header = v.lower() in ("", "true", "on", "1")
            elif k == "DELIMITER":
                if len(v) != 1:
                    raise PgError("syntax_error",
                                  "COPY delimiter must be a single "
                                  "character")
                # Postgres copy.c rejects these outright: an alphanumeric
                # delimiter would collide with backslash escapes in text
                # format (e.g. data 'n' escaping to \n reads back as a
                # newline), and \r \n \\ . are structurally reserved.
                if v.isalnum() or v in "\\\r\n.":
                    raise PgError(
                        "feature_not_supported",
                        f'COPY delimiter cannot be "{v}"',
                    )
                delim = v
            else:
                raise PgError("syntax_error",
                              f'unrecognized COPY option "{k}"')
        if header and fmt != "csv":
            raise PgError("feature_not_supported",
                          "COPY HEADER available only in CSV mode")
        if m.group("query"):
            query = m.group("query")
        else:
            cols = m.group("cols")
            cols = ", ".join(c.strip() for c in cols.split(",")) \
                if cols else "*"
            query = f"SELECT {cols} FROM {m.group('table')}"
        fields, rows = self.run_select(query)
        delim = delim or ("," if fmt == "csv" else "\t")
        out = [_msg(b"H", struct.pack("!bH", 0, len(fields))
                    + struct.pack(f"!{len(fields)}H", *([0] * len(fields))))]
        if fmt == "csv" and header:
            out.append(_msg(b"d", (delim.join(
                _copy_csv_field(f[0], delim) for f in fields)
                + "\n").encode()))
        enc = _copy_csv_field if fmt == "csv" else _copy_text_field
        for r in rows:
            line = delim.join(enc(v, delim) for v in r)
            out.append(_msg(b"d", (line + "\n").encode()))
        out.append(_msg(b"c"))  # CopyDone
        out.append(msg_command_complete(f"COPY {len(rows)}"))
        return out

    def _exec_show(self, sql: str) -> list[bytes]:
        name = sql.split(None, 1)[1].strip().rstrip(";").lower() \
            if len(sql.split(None, 1)) > 1 else "all"
        if name == "all":
            fields = [("name", OID_TEXT), ("setting", OID_TEXT)]
            out = [msg_row_description(fields)]
            for k, v in sorted(self.params.items()):
                out.append(msg_data_row([k.encode(), v.encode()]))
            out.append(msg_command_complete(f"SHOW {len(self.params)}"))
            return out
        # case-insensitive lookup; "transaction isolation level" special
        if name == "transaction isolation level":
            val = "serializable"
        else:
            val = next((v for k, v in self.params.items()
                        if k.lower() == name), None)
            if val is None:
                raise PgError("cant_change_runtime_param",
                              f'unrecognized configuration parameter '
                              f'"{name}"')
        fields = [(name, OID_TEXT)]
        return [msg_row_description(fields), msg_data_row([val.encode()]),
                msg_command_complete("SHOW 1")]

    # --------------------------------------------------- extended protocol
    def handle_parse(self, body: bytes) -> list[bytes]:
        name, rest = body.split(b"\x00", 1)
        sql, rest = rest.split(b"\x00", 1)
        (n,) = struct.unpack("!H", rest[:2])
        oids = list(struct.unpack(f"!{n}I", rest[2:2 + 4 * n]))
        sql_s = sql.decode()
        stmts = split_statements(sql_s)
        if len(stmts) > 1:
            raise PgError("syntax_error",
                          "cannot insert multiple commands into a prepared "
                          "statement")
        one = strip_comments(stmts[0]).strip() if stmts else ""
        kind = classify(one)
        if kind not in ("SELECT", "INSERT", "UPDATE", "DELETE", "BEGIN",
                        "COMMIT", "ROLLBACK", "SET", "SHOW", "EMPTY",
                        "CREATE"):
            raise PgError("feature_not_supported",
                          f"cannot prepare statement kind {kind}")
        # infer unspecified param oids as 0 (decoded as unknown/text)
        n_params = count_params(one)
        while len(oids) < n_params:
            oids.append(0)
        self.prepared[name.decode()] = _Prepared(one, kind, oids)
        return [_msg(b"1")]  # ParseComplete

    def handle_bind(self, body: bytes) -> list[bytes]:
        portal, rest = body.split(b"\x00", 1)
        stmt_name, rest = rest.split(b"\x00", 1)
        pos = 0
        (n_fmt,) = struct.unpack_from("!H", rest, pos)
        pos += 2
        fmts = list(struct.unpack_from(f"!{n_fmt}H", rest, pos))
        pos += 2 * n_fmt
        (n_params,) = struct.unpack_from("!H", rest, pos)
        pos += 2
        prepped = self.prepared.get(stmt_name.decode())
        if prepped is None:
            raise PgError("invalid_sql_statement_name",
                          f'prepared statement "{stmt_name.decode()}" '
                          "does not exist")
        params = []
        for i in range(n_params):
            (plen,) = struct.unpack_from("!i", rest, pos)
            pos += 4
            raw = None
            if plen >= 0:
                raw = rest[pos:pos + plen]
                pos += plen
            fmt = fmts[i] if i < len(fmts) else (fmts[0] if n_fmt == 1 else 0)
            oid = (prepped.param_oids[i]
                   if i < len(prepped.param_oids) else 0)
            params.append(_decode_param(raw, oid, fmt))
        (n_rfmt,) = struct.unpack_from("!H", rest, pos)
        pos += 2
        rfmts = list(struct.unpack_from(f"!{n_rfmt}H", rest, pos))
        if len(params) < len(prepped.param_oids):
            raise PgError(
                "protocol_violation",
                f"bind message supplies {len(params)} parameters, but "
                f"prepared statement requires {len(prepped.param_oids)}")
        try:
            bound = bind_params(prepped.sql, params) if params \
                else prepped.sql
        except StatementError as e:
            raise PgError("undefined_parameter", str(e)) from None
        self.portals[portal.decode()] = _Portal(prepped, bound, rfmts)
        return [_msg(b"2")]  # BindComplete

    def _describe_fields(self, prepped: _Prepared, sql: str):
        if prepped.kind == "SELECT":
            try:
                select = parse_query(self._strip_catalog_schema(sql))
            except QueryError:
                return None
            if select.table in _CATALOG_NAMES:
                all_cols, _, all_oids = \
                    _catalog_tables(self.cluster)[select.table]
                cols = list(select.columns) if select.columns else all_cols
                for c in cols:
                    if c not in all_cols:
                        raise PgError("undefined_column",
                                      f'column "{c}" does not exist')
                return [(c, all_oids[all_cols.index(c)]) for c in cols]
            t = self.cluster.layout.schema.tables.get(select.table)
            if t is None:
                raise PgError("undefined_table",
                              f'relation "{select.table}" does not exist')
            if select.aggregates:
                return self._agg_fields(select)
            if select.columns:
                cols = list(select.columns)
            else:
                # SELECT *: the matcher emits pk row-key columns first,
                # then value columns — Describe must promise that order
                cols = list(t.pk) + [c.name for c in t.value_columns]
            return self._fields_for_select(select, cols)
        if prepped.kind == "SHOW":
            name = sql.split(None, 1)[1].strip().rstrip(";").lower() \
                if len(sql.split(None, 1)) > 1 else "all"
            if name == "all":
                return [("name", OID_TEXT), ("setting", OID_TEXT)]
            # real Postgres names the column after the parameter, and
            # _exec_show's data path does too — Describe must agree
            return [(name, OID_TEXT)]
        return None

    def handle_describe(self, body: bytes) -> list[bytes]:
        target = body[0:1]
        name = body[1:].split(b"\x00", 1)[0].decode()
        if target == b"S":
            prepped = self.prepared.get(name)
            if prepped is None:
                raise PgError("invalid_sql_statement_name",
                              f'prepared statement "{name}" does not exist')
            out = [msg_parameter_description(prepped.param_oids)]
            fields = self._describe_fields(prepped, prepped.sql)
            out.append(msg_row_description(fields) if fields else _msg(b"n"))
            return out
        portal = self.portals.get(name)
        if portal is None:
            raise PgError("invalid_cursor_name",
                          f'portal "{name}" does not exist')
        fields = self._describe_fields(portal.stmt, portal.bound_sql)
        return [msg_row_description(fields) if fields else _msg(b"n")]

    def handle_execute(self, body: bytes) -> list[bytes]:
        name, rest = body.split(b"\x00", 1)
        (max_rows,) = struct.unpack("!I", rest[:4])
        portal = self.portals.get(name.decode())
        if portal is None:
            raise PgError("invalid_cursor_name",
                          f'portal "{name.decode()}" does not exist')
        prepped = portal.stmt
        if self.tx_failed and prepped.kind not in ("COMMIT", "ROLLBACK"):
            raise PgError(
                "in_failed_sql_transaction",
                "current transaction is aborted, commands ignored until "
                "end of transaction block")
        if prepped.kind in ("SELECT", "SHOW"):
            if portal.rows is None:
                if prepped.kind == "SHOW":
                    msgs = self._exec_show(portal.bound_sql)
                    # re-use simple-path encoding: rows are already wire
                    # messages; strip RowDescription (Describe sends it)
                    portal.rows = [m for m in msgs if m[0:1] == b"D"]
                    portal.fields = []
                else:
                    fields, rows = self.run_select(portal.bound_sql)
                    fmts = portal.result_formats or [0] * len(fields)
                    if len(fmts) == 1:
                        fmts = fmts * len(fields)
                    portal.fields = fields
                    portal.rows = [msg_data_row([
                        _encode_cell(v, fields[i][1],
                                     fmts[i] if i < len(fmts) else 0)
                        for i, v in enumerate(r)]) for r in rows]
                portal.pos = 0
            out = []
            end = len(portal.rows) if max_rows == 0 \
                else min(portal.pos + max_rows, len(portal.rows))
            out.extend(portal.rows[portal.pos:end])
            n_sent = end - portal.pos
            portal.pos = end
            portal.tag_n += n_sent
            if end < len(portal.rows):
                out.append(_msg(b"s"))  # PortalSuspended
            else:
                out.append(msg_command_complete(
                    f"SELECT {portal.tag_n}" if prepped.kind == "SELECT"
                    else "SHOW"))
            return out
        sql = portal.bound_sql
        # non-row statements run through the simple-path machinery, minus
        # the RowDescription (extended protocol sends it via Describe)
        return [m for m in self.exec_one(sql) if m[0:1] != b"T"]

    def handle_close(self, body: bytes) -> list[bytes]:
        target = body[0:1]
        name = body[1:].split(b"\x00", 1)[0].decode()
        if target == b"S":
            self.prepared.pop(name, None)
        else:
            self.portals.pop(name, None)
        return [_msg(b"3")]  # CloseComplete

    # ---------------------------------------------------------- main loop
    def serve(self) -> None:
        import time as _time

        _histograms = getattr(
            self.cluster, "histograms", None
        )
        if _histograms is None:
            from corro_sim.utils.metrics import histograms as _histograms

        _t0 = _time.perf_counter()
        if not self.startup():
            return
        # wire-session establishment (pgwire startup handshake) — the
        # corro.transport.connect.time.seconds analog
        _histograms.observe(
            "corro_transport_connect_time_seconds",
            _time.perf_counter() - _t0,
            help_="wire-session establishment time (pgwire startup; "
                  "corro.transport.connect.time.seconds analog)",
        )
        buffered: list[bytes] = []
        skip_to_sync = False
        while True:
            tag = self._read_exact(1)
            (length,) = struct.unpack("!I", self._read_exact(4))
            body = self._read_exact(length - 4)
            if tag == b"X":
                return
            if tag == b"Q":
                buffered = []
                skip_to_sync = False
                out = []
                try:
                    stmts = split_statements(body.split(b"\x00", 1)[0]
                                             .decode())
                    if not stmts:
                        out.append(_msg(b"I"))
                    for s in stmts:
                        out.extend(self.exec_one(s))
                except PgError as e:
                    if self.tx_writes is not None:
                        self.tx_failed = True
                    out.append(msg_error(e.code, str(e)))
                except Exception as e:  # internal
                    if self.tx_writes is not None:
                        self.tx_failed = True
                    out.append(msg_error(sqlstate("internal_error"), str(e)))
                out.append(msg_ready(self.tx_status()))
                self.send(*out)
                continue
            if tag == b"S":  # Sync
                buffered.append(msg_ready(self.tx_status()))
                self.send(*buffered)
                buffered = []
                skip_to_sync = False
                continue
            if tag == b"H":  # Flush
                if buffered:
                    self.send(*buffered)
                    buffered = []
                continue
            if skip_to_sync:
                continue
            try:
                if tag == b"P":
                    buffered.extend(self.handle_parse(body))
                elif tag == b"B":
                    buffered.extend(self.handle_bind(body))
                elif tag == b"D":
                    buffered.extend(self.handle_describe(body))
                elif tag == b"E":
                    buffered.extend(self.handle_execute(body))
                elif tag == b"C":
                    buffered.extend(self.handle_close(body))
                else:
                    raise PgError("protocol_violation",
                                  f"unexpected message {tag!r}")
            except PgError as e:
                if self.tx_writes is not None:
                    self.tx_failed = True
                buffered.append(msg_error(e.code, str(e)))
                skip_to_sync = True
            except Exception as e:
                if self.tx_writes is not None:
                    self.tx_failed = True
                buffered.append(msg_error(sqlstate("internal_error"),
                                          str(e)))
                skip_to_sync = True


# ----------------------------------------------------------------- server


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            _Session(self.server.pg, self.request).serve()
        except (ConnectionError, OSError):
            pass


class _TcpServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PgServer:
    """The pg-wire listener (reference: `corro_pg::start`, lib.rs:469)."""

    def __init__(self, cluster, host: str = "127.0.0.1", port: int = 0):
        self.cluster = cluster
        self._srv = _TcpServer((host, port), _Handler, bind_and_activate=True)
        self._srv.pg = self
        self._thread = None

    @property
    def addr(self) -> tuple[str, int]:
        return self._srv.server_address[:2]

    def start(self) -> "PgServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="pg-server", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


# ------------------------------------------------------- minimal client
# (test/tooling helper: enough pgwire to talk to any v3 server)


class SimplePgClient:
    """A tiny blocking pgwire-v3 client for tests and the CLI.

    Speaks both the simple and extended protocols; returns rows as Python
    values (text-format decode by OID)."""

    def __init__(self, host: str, port: int, database: str = "corro",
                 user: str = "corro"):
        self.sock = socket.create_connection((host, port))
        self._send_startup(database, user)
        self.params: dict[str, str] = {}
        self.notices: list = []
        self._drain_until_ready()

    def _send_startup(self, database, user):
        body = struct.pack("!I", PROTO_V3)
        body += _cstr("user") + _cstr(user)
        body += _cstr("database") + _cstr(database)
        body += b"\x00"
        self.sock.sendall(struct.pack("!I", len(body) + 4) + body)

    def _read_exact(self, n):
        buf = b""
        while len(buf) < n:
            c = self.sock.recv(n - len(buf))
            if not c:
                raise ConnectionError("server closed")
            buf += c
        return buf

    def read_msg(self):
        tag = self._read_exact(1)
        (length,) = struct.unpack("!I", self._read_exact(4))
        return tag, self._read_exact(length - 4)

    def _drain_until_ready(self):
        msgs = []
        while True:
            tag, body = self.read_msg()
            msgs.append((tag, body))
            if tag == b"S":
                k, v = body.split(b"\x00")[:2]
                self.params[k.decode()] = v.decode()
            if tag == b"Z":
                self.status = body
                return msgs

    @staticmethod
    def _decode_row(body, fields):
        (n,) = struct.unpack_from("!H", body, 0)
        pos = 2
        out = []
        for i in range(n):
            (plen,) = struct.unpack_from("!i", body, pos)
            pos += 4
            if plen < 0:
                out.append(None)
                continue
            raw = body[pos:pos + plen]
            pos += plen
            oid = fields[i][1] if i < len(fields) else OID_TEXT
            out.append(_decode_param(raw, oid, 0))
        return out

    @staticmethod
    def _parse_fields(body):
        (n,) = struct.unpack_from("!H", body, 0)
        pos = 2
        fields = []
        for _ in range(n):
            end = body.index(b"\x00", pos)
            name = body[pos:end].decode()
            pos = end + 1
            _, _, oid, _, _, _ = struct.unpack_from("!IHIhih", body, pos)
            pos += 18
            fields.append((name, oid))
        return fields

    def query(self, sql: str):
        """Simple protocol. Returns (fields, rows, tags, errors).

        COPY TO STDOUT data lines land in ``self.copy_lines`` (one str
        per CopyData message, trailing newline stripped)."""
        body = _cstr(sql)
        self.sock.sendall(_msg(b"Q", body))
        fields, rows, tags, errors = [], [], [], []
        self.copy_lines: list[str] = []
        while True:
            tag, b = self.read_msg()
            if tag == b"T":
                fields = self._parse_fields(b)
            elif tag == b"D":
                rows.append(self._decode_row(b, fields))
            elif tag == b"d":  # CopyData
                self.copy_lines.append(b.decode().rstrip("\n"))
            elif tag == b"C":
                tags.append(b.rstrip(b"\x00").decode())
            elif tag == b"E":
                errors.append(self._parse_error(b))
            elif tag == b"Z":
                self.status = b
                return fields, rows, tags, errors

    @staticmethod
    def _parse_error(body) -> dict:
        out = {}
        pos = 0
        while pos < len(body) and body[pos:pos + 1] != b"\x00":
            f = body[pos:pos + 1].decode()
            end = body.index(b"\x00", pos + 1)
            out[f] = body[pos + 1:end].decode()
            pos = end + 1
        return out

    def extended(self, sql: str, params=(), param_oids=(), max_rows=0,
                 binary_results=False):
        """Parse/Bind/Describe/Execute/Sync round. Returns
        (fields, rows, tags, errors, suspended) — ``suspended`` is True
        when a row-limited Execute left the portal resumable."""
        msgs = []
        oids = list(param_oids)
        msgs.append(_msg(b"P", _cstr("") + _cstr(sql)
                         + struct.pack("!H", len(oids))
                         + b"".join(struct.pack("!I", o) for o in oids)))
        pb = [_cstr(""), _cstr(""), struct.pack("!H", 0),
              struct.pack("!H", len(params))]
        for p in params:
            if p is None:
                pb.append(struct.pack("!i", -1))
            else:
                raw = (str(p).encode() if not isinstance(p, bytes)
                       else b"\\x" + p.hex().encode())
                pb.append(struct.pack("!I", len(raw)) + raw)
        pb.append(struct.pack("!HH", 1, 1 if binary_results else 0))
        msgs.append(_msg(b"B", b"".join(pb)))
        msgs.append(_msg(b"D", b"P" + _cstr("")))
        msgs.append(_msg(b"E", _cstr("") + struct.pack("!I", max_rows)))
        msgs.append(_msg(b"S"))
        self.sock.sendall(b"".join(msgs))
        fields, rows, tags, errors = [], [], [], []
        suspended = False
        while True:
            tag, b = self.read_msg()
            if tag == b"T":
                fields = self._parse_fields(b)
            elif tag == b"D":
                rows.append(self._decode_row(b, fields)
                            if not binary_results else (b, fields))
            elif tag == b"C":
                tags.append(b.rstrip(b"\x00").decode())
            elif tag == b"s":
                suspended = True
            elif tag == b"E":
                errors.append(self._parse_error(b))
            elif tag == b"Z":
                self.status = b
                return fields, rows, tags, errors, suspended

    def close(self):
        try:
            self.sock.sendall(_msg(b"X"))
        except OSError:
            pass
        self.sock.close()
