"""JSON wire codec for non-native SQLite values — one definition shared
by the HTTP server and the Python client.

Blobs travel as the reference's ``SqliteValue`` JSON shape
``{"blob": [u8…]}`` (``corro-api-types``); everything else is JSON-native.
"""

from __future__ import annotations


def encode_value(v):
    """``json.dumps`` default hook: bytes → the blob wire shape."""
    if isinstance(v, (bytes, bytearray)):
        return {"blob": list(v)}
    raise TypeError(f"not JSON-serializable: {type(v)!r}")


def decode_values(v):
    """Recursively undo :func:`encode_value` in a decoded JSON tree.

    Raises ValueError on a malformed blob shape (non-int or out-of-range
    elements) — callers translate to their protocol's bad-request error.
    """
    if isinstance(v, dict):
        if set(v) == {"blob"} and isinstance(v["blob"], list):
            try:
                return bytes(v["blob"])
            except (ValueError, TypeError) as e:
                raise ValueError(f"malformed blob value: {e}") from None
        return {k: decode_values(x) for k, x in v.items()}
    if isinstance(v, list):
        return [decode_values(x) for x in v]
    return v
