"""Client-facing API layer (HTTP + statement parsing) — reference layer 5."""
