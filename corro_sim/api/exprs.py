"""Scalar SQL expressions for the DML surface: parser + SQLite-semantics
evaluator.

The reference executes arbitrary SQL inside the write transaction
(``corro-agent/src/api/public/mod.rs:104-131``) — ``UPDATE t SET v = v+1``,
expressions in WHERE, ``INSERT … SELECT`` all work because SQLite evaluates
them. The TPU framework's write path plans statements host-side, so the
scalar-expression subset SQLite would evaluate is implemented here:
arithmetic (``+ - * / %``), string concat (``||``), comparisons with SQL
three-valued logic, ``AND/OR/NOT``, ``IS [NOT] NULL``, ``[NOT] LIKE``,
``[NOT] IN (…)``, ``[NOT] BETWEEN``, ``CASE``, and the common scalar
functions. Evaluation is row-at-a-time against a ``{column: value}``
environment (NULL = ``None``), with SQLite's NULL propagation and
integer-division semantics.
"""

from __future__ import annotations

import dataclasses
import math

from corro_sim.subs.query import QueryError, _Parser, _tokenize


class ExprError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class Lit:
    value: object


@dataclasses.dataclass(frozen=True)
class Col:
    name: str


@dataclasses.dataclass(frozen=True)
class Bin:
    op: str
    left: object
    right: object


@dataclasses.dataclass(frozen=True)
class Un:
    op: str  # '-' | 'NOT'
    inner: object


@dataclasses.dataclass(frozen=True)
class Func:
    name: str
    args: tuple


@dataclasses.dataclass(frozen=True)
class Case:
    operand: object | None
    whens: tuple  # of (cond_expr, result_expr)
    default: object | None


@dataclasses.dataclass(frozen=True)
class IsNull:
    inner: object
    negate: bool


@dataclasses.dataclass(frozen=True)
class InExpr:
    inner: object
    items: tuple
    negate: bool


@dataclasses.dataclass(frozen=True)
class Between:
    inner: object
    lo: object
    hi: object
    negate: bool


_CMP_OPS = {"=", "!=", "<", "<=", ">", ">="}
_CASE_WORDS = {"CASE", "WHEN", "THEN", "ELSE", "END"}


def _word(p: _Parser):
    k, v = p.peek()
    if k == "ident" and v.upper() in _CASE_WORDS:
        return v.upper()
    return None


class ExprParser:
    """Pratt-style scalar/boolean expression parser over the query
    tokenizer's stream. Reuses the shared ``_Parser`` cursor so it can be
    embedded mid-statement (e.g. after ``SET col =``)."""

    def __init__(self, p: _Parser):
        self.p = p

    # --- boolean level (WHERE) -----------------------------------------
    def parse_bool(self):
        return self._or()

    def _or(self):
        node = self._and()
        while self.p.peek()[0] == "OR":
            self.p.next()
            node = Bin("OR", node, self._and())
        return node

    def _and(self):
        node = self._not()
        while self.p.peek()[0] == "AND":
            self.p.next()
            node = Bin("AND", node, self._not())
        return node

    def _not(self):
        if self.p.peek()[0] == "NOT":
            self.p.next()
            return Un("NOT", self._not())
        return self._predicate()

    def _predicate(self):
        left = self.parse_scalar()
        k, v = self.p.peek()
        if k == "op" and v in _CMP_OPS:
            self.p.next()
            return Bin(v, left, self.parse_scalar())
        if k == "IS":
            self.p.next()
            negate = False
            if self.p.peek()[0] == "NOT":
                self.p.next()
                negate = True
            self.p.expect("NULL")
            return IsNull(left, negate)
        negate = False
        if k == "NOT" and self.p.toks[self.p.i + 1][0] in ("LIKE", "IN",
                                                           "BETWEEN"):
            self.p.next()
            negate = True
            k, v = self.p.peek()
        if k == "LIKE":
            self.p.next()
            node = Bin("LIKE", left, self.parse_scalar())
            return Un("NOT", node) if negate else node
        if k == "IN":
            self.p.next()
            self.p.expect("(")
            items = [self.parse_scalar()]
            while self.p.peek()[0] == ",":
                self.p.next()
                items.append(self.parse_scalar())
            self.p.expect(")")
            return InExpr(left, tuple(items), negate)
        if k == "BETWEEN":
            self.p.next()
            lo = self.parse_scalar()
            self.p.expect("AND")
            hi = self.parse_scalar()
            return Between(left, lo, hi, negate)
        return left

    # --- scalar level ---------------------------------------------------
    def parse_scalar(self):
        node = self._mul()
        while True:
            k, v = self.p.peek()
            if k == "op" and v in ("+", "-", "||"):
                self.p.next()
                node = Bin(v, node, self._mul())
            elif (
                k == "lit" and isinstance(v, (int, float))
                and not isinstance(v, bool) and v < 0
            ):
                # the tokenizer fuses "-5" into one negative literal, so
                # "a -5" arrives as ident, lit(-5): that is a subtraction.
                # Re-split the token in place so the multiplicative tail
                # still binds tighter ("v-5*2" must parse as v - (5*2)).
                self.p.toks[self.p.i] = ("op", "-")
                self.p.toks.insert(self.p.i + 1, ("lit", -v))
            else:
                return node

    def _mul(self):
        node = self._unary()
        while True:
            k, v = self.p.peek()
            if (k == "op" and v in ("/", "%")) or k == "*":
                self.p.next()
                node = Bin("*" if k == "*" else v, node, self._unary())
            else:
                return node

    def _unary(self):
        k, v = self.p.peek()
        if k == "op" and v == "-":
            self.p.next()
            return Un("-", self._unary())
        if k == "op" and v == "+":
            self.p.next()
            return self._unary()
        return self._primary()

    def _primary(self):
        k, v = self.p.peek()
        if k == "lit":
            self.p.next()
            return Lit(v)
        if k == "NULL":
            self.p.next()
            return Lit(None)
        if k == "(":
            self.p.next()
            node = self.parse_bool()
            self.p.expect(")")
            return node
        if _word(self.p) == "CASE":
            return self._case()
        if k == "ident":
            name = v
            self.p.next()
            if self.p.peek()[0] == "(":
                self.p.next()
                args = []
                if self.p.peek()[0] != ")":
                    args.append(self.parse_bool())
                    while self.p.peek()[0] == ",":
                        self.p.next()
                        args.append(self.parse_bool())
                self.p.expect(")")
                return Func(name.lower(), tuple(args))
            if self.p.peek()[0] == ".":
                self.p.next()
                col = self.p.expect("ident")
                return Col(f"{name}.{col}")
            return Col(name)
        raise ExprError(f"unexpected token {k} {v!r} in expression")

    def _case(self):
        self.p.next()  # CASE
        operand = None
        if _word(self.p) != "WHEN":
            operand = self.parse_scalar()
        whens = []
        while _word(self.p) == "WHEN":
            self.p.next()
            cond = self.parse_bool()
            if _word(self.p) != "THEN":
                raise ExprError("CASE WHEN without THEN")
            self.p.next()
            whens.append((cond, self.parse_bool()))
        default = None
        if _word(self.p) == "ELSE":
            self.p.next()
            default = self.parse_bool()
        if _word(self.p) != "END":
            raise ExprError("CASE without END")
        self.p.next()
        return Case(operand, tuple(whens), default)


def parse_expr(sql: str):
    """Parse a standalone scalar/boolean expression string."""
    p = _Parser(_tokenize(sql))
    e = ExprParser(p).parse_bool()
    if p.peek()[0] != "eof":
        raise ExprError(f"trailing tokens at {p.peek()!r}")
    return e


def columns_of(node) -> set:
    """Column names an expression references."""
    out: set = set()

    def walk(e):
        if isinstance(e, Col):
            out.add(e.name)
        elif isinstance(e, Bin):
            walk(e.left)
            walk(e.right)
        elif isinstance(e, Un):
            walk(e.inner)
        elif isinstance(e, Func):
            for a in e.args:
                walk(a)
        elif isinstance(e, Case):
            if e.operand is not None:
                walk(e.operand)
            for c, r in e.whens:
                walk(c)
                walk(r)
            if e.default is not None:
                walk(e.default)
        elif isinstance(e, (IsNull,)):
            walk(e.inner)
        elif isinstance(e, InExpr):
            walk(e.inner)
            for i in e.items:
                walk(i)
        elif isinstance(e, Between):
            walk(e.inner)
            walk(e.lo)
            walk(e.hi)

    walk(node)
    return out


# ------------------------------------------------------------- evaluation

def _num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _sql_like(text, pat) -> bool:
    import re as _re

    # ASCII-only case folding, matching the predicate grammar's LIKE
    # (query.py builds per-char [aA] classes for the same reason:
    # re.IGNORECASE would fold Unicode, diverging from SQLite's default)
    rx = []
    for ch in str(pat):
        if ch == "%":
            rx.append(".*")
        elif ch == "_":
            rx.append(".")
        elif ch.isascii() and ch.isalpha():
            rx.append("[" + ch.lower() + ch.upper() + "]")
        else:
            rx.append(_re.escape(ch))
    return _re.fullmatch("".join(rx), str(text), _re.DOTALL) is not None


def _cmp(op, a, b):
    """SQL comparison with NULL → UNKNOWN (None). Cross-type operands
    order by SQLite's type order (numbers < text < blob) via the shared
    sort key — the same one eval_predicate_py uses."""
    if a is None or b is None:
        return None
    if _num(a) != _num(b) or isinstance(a, (bytes, bytearray)) != isinstance(
        b, (bytes, bytearray)
    ):
        from corro_sim.io.values import sqlite_sort_key

        a = sqlite_sort_key(a)
        b = sqlite_sort_key(b)
    if op == "=":
        return a == b
    if op == "!=":
        return a != b
    if op == "<":
        return a < b
    if op == "<=":
        return a <= b
    if op == ">":
        return a > b
    return a >= b


def _arith(op, a, b):
    if op == "||":
        if a is None or b is None:
            return None
        return _text(a) + _text(b)
    if a is None or b is None:
        return None
    if not (_num(a) and _num(b)):
        # SQLite coerces text that looks numeric; non-numeric text → 0
        a = _coerce_num(a)
        b = _coerce_num(b)
    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        if b == 0:
            return None  # SQLite: division by zero yields NULL
        if isinstance(a, int) and isinstance(b, int):
            # exact integer division truncating toward zero — int(a / b)
            # would round-trip through float and corrupt ints > 2^53
            q = a // b
            if q < 0 and q * b != a:
                q += 1
            return q
        return a / b
    if op == "%":
        if b == 0:
            return None
        if isinstance(a, int) and isinstance(b, int):
            q = a // b
            if q < 0 and q * b != a:
                q += 1
            return a - q * b  # sign follows the dividend, exact
        return math.fmod(a, b)
    raise ExprError(f"unknown operator {op!r}")


def _coerce_num(v):
    if _num(v):
        return v
    try:
        f = float(str(v))
        return int(f) if f.is_integer() else f
    except (TypeError, ValueError):
        return 0


def _text(v) -> str:
    if isinstance(v, float) and v.is_integer():
        return str(v)
    return str(v)


def _truth(v):
    """SQL boolean of a value: NULL→None, 0/0.0→False, else numeric!=0."""
    if v is None:
        return None
    if isinstance(v, bool):
        return v
    if _num(v):
        return v != 0
    return _coerce_num(v) != 0


_FUNCS = {
    "abs": lambda a: None if a[0] is None else abs(_coerce_num(a[0])),
    "length": lambda a: None if a[0] is None else len(_text(a[0])),
    "lower": lambda a: None if a[0] is None else _text(a[0]).lower(),
    "upper": lambda a: None if a[0] is None else _text(a[0]).upper(),
    "hex": lambda a: (
        "" if a[0] is None else (
            a[0].hex().upper() if isinstance(a[0], (bytes, bytearray))
            else _text(a[0]).encode().hex().upper()
        )
    ),
    "round": lambda a: _fn_round(a),
    "trim": lambda a: None if a[0] is None else _text(a[0]).strip(),
    "ltrim": lambda a: None if a[0] is None else _text(a[0]).lstrip(),
    "rtrim": lambda a: None if a[0] is None else _text(a[0]).rstrip(),
    "typeof": lambda a: (
        "null" if a[0] is None else
        "integer" if isinstance(a[0], int) and not isinstance(a[0], bool)
        else "real" if isinstance(a[0], float)
        else "blob" if isinstance(a[0], (bytes, bytearray)) else "text"
    ),
    "instr": lambda a: (
        None if a[0] is None or a[1] is None
        else _text(a[0]).find(_text(a[1])) + 1
    ),
    "replace": lambda a: (
        None if None in a[:3]
        else _text(a[0]).replace(_text(a[1]), _text(a[2]))
    ),
}


def _fn_round(args):
    """SQLite round(): REAL result, half-away-from-zero (Python's round
    is banker's and preserves int — both diverge from SQLite)."""
    if args[0] is None:
        return None
    x = _coerce_num(args[0])
    n = int(args[1]) if len(args) > 1 and args[1] is not None else 0
    m = 10.0 ** n
    return math.copysign(math.floor(abs(x) * m + 0.5) / m, x)


def _fn_substr(args):
    if args[0] is None or args[1] is None:
        return None
    s = _text(args[0])
    start = int(args[1])
    n = int(args[2]) if len(args) > 2 and args[2] is not None else None
    if start > 0:
        i = start - 1
    elif start == 0:
        i = 0
    else:
        i = max(len(s) + start, 0)
    return s[i:] if n is None else s[i:i + max(n, 0)]


def eval_expr(node, env: dict):
    """Evaluate an expression AST against ``{column: value}``.

    Boolean results use three-valued logic internally (None = UNKNOWN);
    callers of WHERE predicates collapse None → False like SQL does.
    """
    if isinstance(node, Lit):
        return node.value
    if isinstance(node, Col):
        name = node.name
        if name in env:
            return env[name]
        bare = name.split(".")[-1]
        if bare in env:
            return env[bare]
        raise ExprError(f"unknown column {name!r}")
    if isinstance(node, Un):
        if node.op == "-":
            v = eval_expr(node.inner, env)
            return None if v is None else -_coerce_num(v)
        t = _truth(eval_expr(node.inner, env))
        return None if t is None else (not t)
    if isinstance(node, Bin):
        if node.op == "AND":
            lt = _truth(eval_expr(node.left, env))
            if lt is False:
                return False
            rt = _truth(eval_expr(node.right, env))
            if rt is False:
                return False
            return None if (lt is None or rt is None) else True
        if node.op == "OR":
            lt = _truth(eval_expr(node.left, env))
            if lt is True:
                return True
            rt = _truth(eval_expr(node.right, env))
            if rt is True:
                return True
            return None if (lt is None or rt is None) else False
        if node.op in _CMP_OPS:
            return _cmp(node.op, eval_expr(node.left, env),
                        eval_expr(node.right, env))
        if node.op == "LIKE":
            a = eval_expr(node.left, env)
            b = eval_expr(node.right, env)
            if a is None or b is None:
                return None
            return _sql_like(a, b)
        return _arith(node.op, eval_expr(node.left, env),
                      eval_expr(node.right, env))
    if isinstance(node, IsNull):
        v = eval_expr(node.inner, env)
        return (v is not None) if node.negate else (v is None)
    if isinstance(node, InExpr):
        v = eval_expr(node.inner, env)
        if v is None:
            return None
        saw_null = False
        for item in node.items:
            iv = eval_expr(item, env)
            if iv is None:
                saw_null = True
            elif _cmp("=", v, iv):
                return not node.negate
        if saw_null:
            return None  # UNKNOWN per SQL IN semantics
        return node.negate
    if isinstance(node, Between):
        v = eval_expr(node.inner, env)
        lo = eval_expr(node.lo, env)
        hi = eval_expr(node.hi, env)
        ge = _cmp(">=", v, lo)
        le = _cmp("<=", v, hi)
        if ge is None or le is None:
            return None
        r = ge and le
        return (not r) if node.negate else r
    if isinstance(node, Case):
        if node.operand is not None:
            opv = eval_expr(node.operand, env)
            for cond, res in node.whens:
                if _cmp("=", opv, eval_expr(cond, env)):
                    return eval_expr(res, env)
        else:
            for cond, res in node.whens:
                if _truth(eval_expr(cond, env)):
                    return eval_expr(res, env)
        return None if node.default is None else eval_expr(node.default, env)
    if isinstance(node, Func):
        name = node.name
        args = [eval_expr(a, env) for a in node.args]
        if name == "coalesce":
            for a in args:
                if a is not None:
                    return a
            return None
        if name == "ifnull":
            return args[0] if args[0] is not None else args[1]
        if name == "nullif":
            return None if _cmp("=", args[0], args[1]) else args[0]
        if name == "iif":
            return args[1] if _truth(args[0]) else (
                args[2] if len(args) > 2 else None
            )
        if name in ("min", "max"):
            vals = [a for a in args if a is not None]
            if len(vals) != len(args) or not vals:
                return None  # scalar min/max: any NULL arg → NULL
            return min(vals) if name == "min" else max(vals)
        if name == "substr" or name == "substring":
            return _fn_substr(args)
        fn = _FUNCS.get(name)
        if fn is None:
            raise ExprError(f"unsupported function {name!r}")
        return fn(args)
    raise ExprError(f"cannot evaluate {node!r}")


def sql_of(node) -> str:
    """Canonical SQL rendering of an expression AST (normalization for
    subscription dedupe, like the predicate _render in subs/query.py)."""
    if isinstance(node, Lit):
        v = node.value
        if v is None:
            return "NULL"
        if isinstance(v, str):
            return "'" + v.replace("'", "''") + "'"
        if isinstance(v, (bytes, bytearray)):
            return "X'" + bytes(v).hex() + "'"
        return repr(v)
    if isinstance(node, Col):
        return node.name
    if isinstance(node, Un):
        if node.op == "NOT":
            return f"NOT ({sql_of(node.inner)})"
        return f"-({sql_of(node.inner)})"
    if isinstance(node, Bin):
        return f"({sql_of(node.left)} {node.op} {sql_of(node.right)})"
    if isinstance(node, IsNull):
        return (
            f"({sql_of(node.inner)} IS"
            f"{' NOT' if node.negate else ''} NULL)"
        )
    if isinstance(node, InExpr):
        items = ", ".join(sql_of(i) for i in node.items)
        return (
            f"({sql_of(node.inner)}{' NOT' if node.negate else ''}"
            f" IN ({items}))"
        )
    if isinstance(node, Between):
        return (
            f"({sql_of(node.inner)}{' NOT' if node.negate else ''} BETWEEN "
            f"{sql_of(node.lo)} AND {sql_of(node.hi)})"
        )
    if isinstance(node, Case):
        parts = ["CASE"]
        if node.operand is not None:
            parts.append(sql_of(node.operand))
        for c, r in node.whens:
            parts.append(f"WHEN {sql_of(c)} THEN {sql_of(r)}")
        if node.default is not None:
            parts.append(f"ELSE {sql_of(node.default)}")
        parts.append("END")
        return " ".join(parts)
    if isinstance(node, Func):
        return f"{node.name}({', '.join(sql_of(a) for a in node.args)})"
    raise ExprError(f"cannot render {node!r}")


def is_literal(node) -> bool:
    return isinstance(node, Lit)


def const_value(node):
    """Evaluate a column-free expression at parse time."""
    return eval_expr(node, {})
