"""Write-statement surface: the `Statement` wire shapes + a DML parser.

The reference accepts four JSON shapes for a statement
(``corro-api-types/src/lib.rs:181-201``): a bare SQL string,
``[sql, [params…]]``, ``{"query": sql, "params": […]}`` and
``{"query": sql, "named_params": {…}}`` — executed verbatim by SQLite
inside one write transaction (``api/public/mod.rs:104-131``). The TPU
framework has no SQLite, so the DML subset that makes sense against CRDT
tables is parsed here into *cell operations* against the
:class:`~corro_sim.schema.TableLayout`:

  INSERT INTO t (cols…) VALUES (…) [, (…)]…   -- upsert (CRDT tables are
      ON CONFLICT/REPLACE-natured: every write is a cell-wise LWW merge)
  UPDATE t SET c = v[, …] WHERE <pk-eq or predicate>
  DELETE FROM t WHERE <pk-eq or predicate>

Parameters bind SQLite-style: positional ``?`` against the params list,
named ``:name`` / ``$name`` / ``@name`` against the named map.
"""

from __future__ import annotations

import dataclasses
import re

from corro_sim.subs.query import (
    And,
    Cmp,
    QueryError,
    _Parser,
    _tokenize,
)


class StatementError(ValueError):
    pass


@dataclasses.dataclass
class WriteOp:
    """One parsed DML statement, normalized to cell operations."""

    kind: str  # 'upsert' | 'update' | 'delete' | 'insert_select'
    table: str
    # upsert: list of (pk_tuple, {col: value}) — one per VALUES tuple
    rows: list | None = None
    # update: {col: value-or-expression-AST} applied to selected rows
    sets: dict | None = None
    # update/delete row selection: either resolved pk tuples or a predicate
    pks: list | None = None
    where: object | None = None  # predicate AST when not pure pk-equality
    where_expr: object | None = None  # scalar-expression WHERE (api/exprs)
    # insert_select: target column list + the source SELECT
    cols: list | None = None
    select: object | None = None


def parse_statement(stmt) -> tuple[str, list | dict]:
    """Normalize a wire `Statement` into (sql, params)."""
    if isinstance(stmt, str):
        return stmt, []
    if isinstance(stmt, (list, tuple)):
        if not stmt or not isinstance(stmt[0], str):
            raise StatementError(f"bad statement shape: {stmt!r}")
        if len(stmt) == 2 and isinstance(stmt[1], (list, tuple)):
            return stmt[0], list(stmt[1])
        return stmt[0], list(stmt[1:])  # tolerate the flat form
    if isinstance(stmt, dict):
        sql = stmt.get("query")
        if not isinstance(sql, str):
            raise StatementError(f"statement dict needs 'query': {stmt!r}")
        if "named_params" in stmt:
            return sql, dict(stmt["named_params"])
        return sql, list(stmt.get("params", []))
    raise StatementError(f"bad statement shape: {type(stmt)!r}")


_PARAM = re.compile(r"\?\d*|\$\d+|[:$@][A-Za-z_][A-Za-z_0-9]*")


def bind_params(sql: str, params) -> str:
    """Inline bound parameters as SQL literals (the same param-expansion
    trick the reference uses for subscription dedupe, ``expand_sql``,
    ``api/public/pubsub.rs:226-331``). Strings are quoted; None → NULL."""
    pos = 0

    def lit(v):
        if v is None:
            return "NULL"
        if isinstance(v, bool):
            return str(int(v))
        if isinstance(v, (int, float)):
            return repr(v)
        if isinstance(v, str):
            return "'" + v.replace("'", "''") + "'"
        if isinstance(v, (bytes, bytearray)):
            return "X'" + bytes(v).hex() + "'"  # SQLite blob literal
        raise StatementError(f"unsupported param type {type(v)!r}")

    out = []
    last = 0
    idx = 0
    # incremental quote scan: a quote of one kind inside the other kind's
    # span is literal text (e.g. a '"' inside a 'string' must not open an
    # identifier), so independent parity counts are wrong — track both
    # states sequentially. SQL's '' / "" doubling self-corrects at the
    # character level (close + immediately reopen).
    in_str = in_ident = False
    for m in _PARAM.finditer(sql):
        prefix = sql[last:m.start()]
        out.append(prefix)
        for ch in prefix:
            if in_str:
                in_str = ch != "'"
            elif in_ident:
                in_ident = ch != '"'
            elif ch == "'":
                in_str = True
            elif ch == '"':
                in_ident = True
        if in_str or in_ident:
            # inside a string literal or a "quoted identifier" — e.g.
            # SELECT "a$1" names a column, it does not bind a parameter
            out.append(m.group(0))
            last = m.end()
            continue
        tok = m.group(0)
        if tok == "?":
            if not isinstance(params, (list, tuple)) or idx >= len(params):
                raise StatementError("not enough positional params")
            out.append(lit(params[idx]))
            idx += 1
        elif tok[0] == "?":
            # SQLite ?NNN — 1-based explicit positional; like SQLite, it
            # also advances the implicit cursor past NNN
            i = int(tok[1:]) - 1
            if not isinstance(params, (list, tuple)) or not (
                0 <= i < len(params)
            ):
                raise StatementError(f"missing positional param {tok}")
            out.append(lit(params[i]))
            idx = max(idx, i + 1)
        elif tok[0] == "$" and tok[1:].isdigit():
            # Postgres-style 1-based positional (the pg wire API binds these)
            i = int(tok[1:]) - 1
            if not isinstance(params, (list, tuple)) or not (
                0 <= i < len(params)
            ):
                raise StatementError(f"missing positional param {tok}")
            out.append(lit(params[i]))
        else:
            name = tok[1:]
            if not isinstance(params, dict) or name not in params:
                raise StatementError(f"missing named param {name!r}")
            out.append(lit(params[name]))
        last = m.end()
    out.append(sql[last:])
    return "".join(out)


# ---------------------------------------------------------------- DML parse

_KEYWORDS = {
    "INSERT", "INTO", "VALUES", "UPDATE", "SET", "DELETE", "FROM", "WHERE",
    "OR", "REPLACE", "ON", "CONFLICT", "DO", "NOTHING",
}


def _tok_dml(sql: str):
    """Tokenize, mapping DML keywords that the SELECT tokenizer treats as
    plain identifiers."""
    toks = []
    for k, v in _tokenize(sql):
        if k == "ident" and v.upper() in _KEYWORDS:
            toks.append((v.upper(), v.upper()))
        else:
            toks.append((k, v))
    return toks


def parse_dml(sql: str) -> WriteOp:
    sql = sql.strip().rstrip(";")
    toks = _tok_dml(sql)
    p = _Parser(toks)
    k, _ = p.peek()
    if k == "INSERT":
        return _parse_insert(p)
    if k == "UPDATE":
        return _parse_update(p)
    if k == "DELETE":
        return _parse_delete(p)
    raise StatementError(
        f"unsupported statement (INSERT/UPDATE/DELETE only): {sql[:60]!r}"
    )


def _parse_insert(p: _Parser) -> WriteOp:
    p.expect("INSERT")
    if p.peek()[0] == "OR":  # INSERT OR REPLACE — same thing for a CRDT table
        p.next()
        p.expect("REPLACE")
    p.expect("INTO")
    table = p.expect("ident")
    p.expect("(")
    cols = [p.expect("ident")]
    while p.peek()[0] == ",":
        p.next()
        cols.append(p.expect("ident"))
    p.expect(")")
    if p.peek()[0] == "SELECT":
        # INSERT … SELECT (reference: arbitrary SQL in the write tx,
        # api/public/mod.rs:104-131): the source SELECT evaluates against
        # the writing node's view at plan time, its rows become VALUES.
        # Projections are full scalar expressions (SELECT id, v + 10 …).
        from corro_sim.api.exprs import ExprError, ExprParser

        p.next()
        items = []
        try:
            while True:
                items.append(ExprParser(p).parse_scalar())
                if p.peek()[0] == "AS":
                    p.next()
                    p.expect("ident")
                elif p.peek()[0] == "ident":
                    p.next()  # bare alias
                if p.peek()[0] == ",":
                    p.next()
                    continue
                break
        except ExprError as err:
            raise StatementError(str(err)) from None
        p.expect("FROM")
        src = p.expect("ident")
        where = where_expr = None
        if p.peek()[0] == "WHERE":
            where, where_expr = _parse_where(p)
        elif p.peek()[0] != "eof":
            raise StatementError(f"trailing tokens at {p.peek()!r}")
        return WriteOp(
            kind="insert_select", table=table, cols=cols,
            select=(src, tuple(items)), where=where, where_expr=where_expr,
        )
    p.expect("VALUES")
    tuples = []
    while True:
        p.expect("(")
        vals = [_value(p)]
        while p.peek()[0] == ",":
            p.next()
            vals.append(_value(p))
        p.expect(")")
        if len(vals) != len(cols):
            raise StatementError(
                f"{len(cols)} columns but {len(vals)} values"
            )
        tuples.append(dict(zip(cols, vals)))
        if p.peek()[0] == ",":
            p.next()
            continue
        break
    # ON CONFLICT … is tolerated and ignored: CRDT merge IS the conflict
    # resolution (every insert is an upsert, doc/crdts.md:15-17).
    if p.peek()[0] == "ON":
        while p.peek()[0] != "eof":
            p.next()
    elif p.peek()[0] != "eof":
        raise StatementError(f"trailing tokens at {p.peek()!r}")
    return WriteOp(kind="upsert", table=table, rows=tuples)


def _value(p: _Parser):
    """One VALUES item: any column-free scalar expression, folded to its
    value at parse time (``VALUES (1 + 2, upper('x'))`` works; referencing
    a column inside VALUES is an error, as in SQLite)."""
    from corro_sim.api.exprs import (
        ExprError,
        ExprParser,
        columns_of,
        const_value,
    )

    try:
        e = ExprParser(p).parse_scalar()
        cols = columns_of(e)
        if cols:
            raise StatementError(
                f"VALUES may not reference columns: {sorted(cols)}"
            )
        return const_value(e)
    except ExprError as err:
        raise StatementError(str(err)) from None


def _parse_update(p: _Parser) -> WriteOp:
    from corro_sim.api.exprs import (
        ExprError,
        ExprParser,
        columns_of,
        const_value,
    )

    p.expect("UPDATE")
    table = p.expect("ident")
    p.expect("SET")
    sets = {}
    while True:
        col = p.expect("ident")
        k, v = p.next()
        if k != "op" or v != "=":
            raise StatementError(f"expected '=' after {col!r}")
        try:
            e = ExprParser(p).parse_scalar()
            # column-free expressions fold to plain values (the fast
            # path); column-referencing ones evaluate per target row at
            # plan time (SET v = v + 1 — reference executes these inside
            # the write tx, api/public/mod.rs:104-131)
            sets[col] = e if columns_of(e) else const_value(e)
        except ExprError as err:
            raise StatementError(str(err)) from None
        if p.peek()[0] == ",":
            p.next()
            continue
        break
    where, where_expr = _parse_where(p)
    return WriteOp(
        kind="update", table=table, sets=sets, where=where,
        where_expr=where_expr,
    )


def _parse_delete(p: _Parser) -> WriteOp:
    p.expect("DELETE")
    p.expect("FROM")
    table = p.expect("ident")
    where, where_expr = _parse_where(p)
    return WriteOp(
        kind="delete", table=table, where=where, where_expr=where_expr
    )


def _parse_where(p: _Parser):
    """Returns (predicate_ast, expr_ast): the vectorizable predicate
    grammar when it fits (pk fast path + Matcher evaluation), otherwise
    the scalar-expression fallback evaluated row-wise at plan time —
    arithmetic, functions, CASE in WHERE all land there."""
    from corro_sim.api.exprs import ExprError, ExprParser

    if p.peek()[0] != "WHERE":
        raise StatementError(
            "UPDATE/DELETE require a WHERE clause (full-table writes are "
            "refused, matching the constrained schema posture)"
        )
    p.next()
    mark = p.i
    try:
        where = p.parse_or()
        if p.peek()[0] != "eof":
            raise QueryError(f"trailing tokens at {p.peek()!r}")
        return where, None
    except QueryError:
        p.i = mark
    try:
        expr = ExprParser(p).parse_bool()
    except ExprError as err:
        raise StatementError(str(err)) from None
    if p.peek()[0] != "eof":
        raise StatementError(f"trailing tokens at {p.peek()!r}")
    return None, expr


def pk_equalities(where, pk_cols: tuple) -> tuple | None:
    """If `where` is exactly pk1 = l1 AND pk2 = l2 … (all pk columns, only
    pk columns), return the pk literal tuple — the fast path that skips
    predicate evaluation. Otherwise None."""
    eqs = {}

    def walk(node) -> bool:
        if isinstance(node, Cmp):
            if node.op != "=" or node.col in eqs:
                return False
            eqs[node.col] = node.lit
            return True
        if isinstance(node, And):
            return all(walk(q) for q in node.parts)
        return False

    if where is None or not walk(where):
        return None
    if set(eqs) != set(pk_cols):
        return None
    return tuple(eqs[c] for c in pk_cols)


def parse_write(stmt) -> WriteOp:
    """Wire statement → WriteOp (params bound, DML parsed)."""
    sql, params = parse_statement(stmt)
    try:
        return parse_dml(bind_params(sql, params))
    except QueryError as e:
        raise StatementError(str(e)) from None
