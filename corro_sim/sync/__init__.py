from corro_sim.sync.sync import sync_round

__all__ = ["sync_round"]
