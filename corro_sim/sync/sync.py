"""Anti-entropy sync: vectorized `compute_available_needs` + budgeted repair.

Reference protocol (``corro-agent/src/agent/handlers.rs:974-1085``,
``api/peer.rs:1036-1372``, ``corro-types/src/sync.rs:127-249``):

1. every 1-15 s a node generates its ``SyncStateV1`` (per-actor heads +
   needed gap ranges) and picks ``max(min(n/100, 10), 3)`` peers out of 10
   random candidates, preferring peers it needs the most from
   (``handlers.rs:1008-1042``);
2. servers reject beyond 3 concurrent inbound syncs (``Semaphore(3)``,
   ``corro-types/src/agent.rs:132``);
3. the client computes *needs* — set-difference of their haves minus ours —
   and an interleaved request scheduler chunks them across the chosen
   peers with GLOBAL dedupe maps so only one peer serves each range
   (``api/peer.rs:1179-1372``).

TPU shape: "their haves minus ours" over interval sets becomes plain
arithmetic on the (N, A) head matrix: ``delta = relu(head[peer] - head)``.
The multi-peer scheduler becomes an argmax *assignment*: each needed actor
is assigned to exactly one of the node's admitted peers (the one whose
head is furthest ahead), so no version range transfers twice — the tensor
equivalent of the reference's ``req_full``/``req_partials`` dedupe maps.
The transfer itself is a budgeted gather from the global change log:
``sync_actor_topk`` total actors split across peers × ≤cap versions each —
the analog of ``chunk_range(…, 10)`` + ≤10 reqs/peer/turn
(``peer.rs:1207,1241-1372``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from corro_sim.config import SimConfig
from corro_sim.core.bookkeeping import Bookkeeping, advance_heads
from corro_sim.core.changelog import ChangeLog, gather_changesets
from corro_sim.core.crdt import NEG, TableState, apply_cell_changes
from corro_sim.core.merge_kernel import (
    LANE_FIELDS,
    kernel_interpret,
    kernel_supported,
    merge_grouped,
    pick_block_nodes,
)
from corro_sim.utils.bits import WINDOW_BITS
from corro_sim.utils.slots import ranks_within_group


def choose_sync_peers(
    cfg: SimConfig,
    book: Bookkeeping,
    key: jax.Array,
    alive: jnp.ndarray,
    view_alive: jnp.ndarray,  # (N, N) or (1, N) believed-alive
    reachable: jnp.ndarray,  # (N, N) or (1, N) ground-truth link mask
    rtt: jnp.ndarray | None = None,  # (N, N) uint8 observed edge delays
):
    """Pick up to ``resolved_sync_peers`` peers per node; enforce the
    server-side semaphore across every (node, peer-slot) request.

    Candidate ranking is (need desc, ring asc) — the reference sorts sync
    candidates by need count then ring (``handlers.rs:1018-1042``); with
    ``rtt`` provided, lower-latency peers win ties.

    Returns ``(peer, granted)`` — (N, P) peer ids and admission mask
    (need > 0, both ends up, reachable, and within the server's 3-inbound
    cap; rejects model ``SyncRejectionV1::MaxConcurrencyReached``,
    ``api/peer.rs:1525-1542``).
    """
    n, a = book.head.shape
    p_cnt = cfg.resolved_sync_peers
    k_cand, k_samp, k_adm = jax.random.split(key, 3)
    c = cfg.sync_candidates

    cand = jax.random.randint(k_cand, (n, c), 0, n, dtype=jnp.int32)
    samp = jax.random.choice(
        k_samp, a, (min(cfg.sync_need_sample, a),), replace=False
    )

    head_s = book.head[:, samp]  # (N, S)
    # need[i, j] = sum over sampled actors of versions cand j has that i lacks
    need = jnp.maximum(
        head_s[cand] - head_s[:, None, :], 0
    ).sum(axis=-1, dtype=jnp.int32)  # (N, C)

    rows = jnp.arange(n, dtype=jnp.int32)
    if callable(view_alive):
        # windowed SWIM: per-pair membership test over K-entry views
        believed = view_alive(
            jnp.broadcast_to(rows[:, None], cand.shape), cand
        )
    elif view_alive.shape[0] == 1:
        believed = view_alive[0][cand]
    else:
        believed = view_alive[rows[:, None], cand]
    # a candidate repeated in the sample must not be chosen twice (the
    # reference's candidate set is a sample of *distinct* members)
    dup = (cand[:, :, None] == cand[:, None, :]) & jnp.tril(
        jnp.ones((c, c), bool), k=-1
    )[None]
    ok = believed & (cand != rows[:, None]) & ~dup.any(axis=2)
    if rtt is not None:
        # ring ascending as the secondary sort key: score = need · 64 +
        # (63 - rtt) keeps need dominant and prefers close peers on ties
        rtt_c = jnp.minimum(rtt[rows[:, None], cand].astype(jnp.int32), 63)
        score = jnp.minimum(need, 1 << 24) * 64 + (63 - rtt_c)
    else:
        score = need
    score = jnp.where(ok, score, -1)

    topv, topi = jax.lax.top_k(score, p_cnt)  # (N, P)
    peer = cand[rows[:, None], topi]
    # The reference syncs on CADENCE, not on estimated need — sync_loop
    # fires every 1-15 s and the need computation happens inside the
    # exchange with exact per-actor state (util.rs:327-371). The sampled
    # need only RANKS candidates here; a zero sample must not veto the
    # sweep, or the convergence tail (few missing versions outside the
    # sample) never gets served.
    valid_slot = topv >= 0

    # Ground truth: both ends actually up and connected.
    if reachable.shape[0] == 1:
        link = reachable[0][peer]
    else:
        link = reachable[rows[:, None], peer]
    want = valid_slot & alive[:, None] & alive[peer] & link

    # Server semaphore: at most sync_server_cap inbound syncs per peer,
    # counted across every (node, slot) request in the sweep. Which
    # requests win is RANDOM per sweep — the reference's semaphore is
    # first-come-first-served over network arrival order
    # (api/peer.rs:1525-1542); a deterministic rank would starve the same
    # requesters every sweep.
    big = jnp.int32(n + 1)
    m = n * p_cnt
    req = jnp.where(want, peer, big).reshape(-1)
    prio = jax.random.randint(k_adm, (m,), 0, 1 << 30, dtype=jnp.int32)
    order = jnp.lexsort((prio, req))
    rank = ranks_within_group(req[order])
    admitted_sorted = rank < cfg.sync_server_cap
    inv = jnp.zeros((m,), jnp.int32).at[order].set(
        jnp.arange(m, dtype=jnp.int32)
    )
    granted = want & admitted_sorted[inv].reshape(n, p_cnt)
    return peer, granted, want


def choose_serving_slots(
    delta_p: jnp.ndarray, topa: jnp.ndarray, phase
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(slot, best) — one serving peer slot per requested (node, actor)
    lane, the global range dedupe of ``api/peer.rs:1179-1372``: no two
    peers ever serve the same range. The furthest-ahead granted peer wins;
    TIES round-robin across the eligible slots (actor id + sweep phase mod
    eligible count) — so equally-capable peers share the load rather than
    funneling through slot 0.

    This is the exact argmax assignment (``sync_deal_probes = 0``): best
    repair depth per lane, at the cost of the full (N, P, K') capability
    gather its caller builds. The probe-dealing alternative
    (:func:`deal_serving_slots`) approximates it at a fraction of the
    cost when per-actor backlogs are shallow.

    ``delta_p``: (N, P, K') versions each granted peer could serve of each
    requested actor (0 where not granted / not ahead). Returns (N, K')
    slot ids and the winning delta (0 = nobody can serve the lane).
    """
    n, p_cnt, kprime = delta_p.shape
    best = delta_p.max(axis=1)  # (N, K')
    elig = (delta_p == best[:, None, :]) & (best[:, None, :] > 0)
    elig_cnt = elig.sum(axis=1)  # (N, K')
    k_tie = (topa + phase) % jnp.maximum(elig_cnt, 1)
    cum = jnp.zeros((n, kprime), jnp.int32)
    slot = jnp.zeros((n, kprime), jnp.int32)
    for p in range(p_cnt):
        slot = jnp.where(elig[:, p] & (cum == k_tie), p, slot)
        cum += elig[:, p].astype(jnp.int32)
    return slot, best


def deal_serving_slots(
    granted: jnp.ndarray, phase, kprime: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(slot, rank_in_slot) — deal request lanes round-robin across each
    node's GRANTED peer slots, the reference's request scheduler: chunked
    needs are shuffled and dealt round-robin over the sync peers
    (``api/peer.rs:1241-1372``), and no two peers are ever dealt the same
    lane (the global range dedupe of ``peer.rs:1179-1372``).

    Lane k goes to the ``(k + phase) mod g``-th granted slot (g = that
    node's granted count); nodes with nothing granted get the sentinel
    ``p_cnt`` on every lane. ``rank_in_slot`` is the lane's position
    among its slot's dealt lanes (``k // g`` under uniform dealing) —
    the per-connection budget rank, by arithmetic instead of the argsort
    the argmax assignment needed. Whether the dealt peer can actually
    serve the lane is the caller's ONE capability gather; a lane whose
    peer cannot serve dies for this sweep and re-deals next sweep under
    a new phase (the argmax form picked the furthest-ahead peer per lane
    but paid a full (N, P, K') head gather + (N, K') argsort — measured
    ~99 ms of the 376 ms sweep at 10k; this is pure VPU arithmetic)."""
    n, p_cnt = granted.shape
    gcount = granted.sum(axis=1, dtype=jnp.int32)  # (N,)
    grank = jnp.cumsum(granted.astype(jnp.int32), axis=1) - 1  # (N, P)
    lanes = jnp.arange(kprime, dtype=jnp.int32)[None, :]
    j = (lanes + phase) % jnp.maximum(gcount, 1)[:, None]  # (N, K')
    slot = jnp.full((n, kprime), p_cnt, jnp.int32)
    for p in range(p_cnt):
        match = granted[:, p:p + 1] & (grank[:, p:p + 1] == j)
        slot = jnp.where(match, p, slot)
    rank_in_slot = lanes // jnp.maximum(gcount, 1)[:, None]
    return slot, rank_in_slot


def _kth_positive(csum, kprime, n, axis_len, roll_phase=None):
    """(N, K') column index of the k-th positive, from per-row INCLUSIVE
    prefix counts of a positives mask.

    Fast form: a fused compare-reduce — for monotone csum, (first index
    with csum >= k) == #{j : csum[j] < k}, and the count is a multiset
    property, so ``csum`` may be in any column order. One streaming pass
    over the plane on TPU. Above 2^33 lanes (backends that materialize
    the compare, XLA:CPU, would OOM — hit at 50k x 50k) a batched binary
    search runs instead; that needs the MONOTONE row order, so callers
    whose csum is in rotated-scan order pass ``roll_phase`` and the roll
    materializes only on that branch.
    """
    # both the prefix counts (<= axis_len) and the targets (<= kprime)
    # must fit the compare dtype — sizing by axis_len alone would wrap
    # tk negative when kprime >= 2^15 with a small compacted axis
    cdt = jnp.int16 if max(axis_len, kprime) < (1 << 15) else jnp.int32
    tk = jnp.arange(1, kprime + 1, dtype=cdt)
    if n * axis_len * kprime <= (1 << 33):
        return jnp.sum(
            csum.astype(cdt)[:, :, None] < tk[None, None, :], axis=1,
            dtype=jnp.int32,
        )
    rolled = csum if roll_phase is None else jnp.roll(
        csum, -roll_phase, axis=1
    )
    return jax.vmap(
        lambda rw: jnp.searchsorted(rw, tk, side="left")
    )(rolled.astype(cdt)).astype(jnp.int32)


def _rank_within_slot(slot, rows, n, kprime):
    """Rank of each lane within its serving-slot group (lanes are in
    rotated scan order; the per-connection budget keeps the first kp)."""
    order = jnp.argsort(slot, axis=1, stable=True)
    s_sorted = jnp.take_along_axis(slot, order, 1)
    idx2 = jnp.broadcast_to(
        jnp.arange(kprime, dtype=jnp.int32)[None, :], (n, kprime)
    )
    newgrp = jnp.concatenate(
        [jnp.ones((n, 1), bool), s_sorted[:, 1:] != s_sorted[:, :-1]],
        axis=1,
    )
    grp_start = jax.lax.cummax(jnp.where(newgrp, idx2, 0), axis=1)
    return jnp.zeros((n, kprime), jnp.int32).at[
        rows[:, None], order
    ].set(idx2 - grp_start)


def _legacy_schedule(cfg, book, log, peer, granted, phase, rows,
                     n, a, p_cnt, kp, kprime):
    """The full-actor-axis request schedule (``sync_hot_actors == 0``).

    Kept for comparison and as the fallback when the dense hot-actor
    form is disabled. Built WITHOUT any (N, A)-sized gather OR scatter —
    the r2 form packed lanes with an (N, A)-update scatter, and 1e8
    scatter update lanes dominated the whole sweep on the real chip:

    1. Each node selects up to K' actors it still needs (its own
       bookkeeping vs the written heads — the needs side of
       compute_available_needs, sync.rs:127-249) by scanning the actor
       axis from a random per-sweep phase and keeping the first K'
       positives (rotated round-robin — the reference's shuffled request
       dealing, peer.rs:1241-1372). The k-th selected actor is recovered
       from the per-row inclusive cumsum of the need mask by a fused
       (N, A, K') compare-reduce (~26 ms at 10k on the real chip); above
       2^33 lanes a batched binary search avoids materializing it.
    2. One serving slot per lane: probe-dealing or exact argmax
       (cfg.sync_deal_probes; see config.py for the trade-off).
    """
    my_need = jnp.maximum(log.head[None, :] - book.head, 0)  # (N, A)
    pos = my_need > 0
    # Rolled-order inclusive cumsum WITHOUT materializing a rolled (N, A)
    # plane: for original column o, the prefix count in the rotated scan
    # is c[o] - c[phase-1] (+ total when o < phase wraps to the tail).
    c = jnp.cumsum(pos.astype(jnp.int32), axis=1)  # (N, A) original order
    total = c[:, -1:]
    cpm1 = jnp.where(
        phase > 0,
        jnp.take(c, jnp.maximum(phase - 1, 0), axis=1)[:, None],
        0,
    )
    wraps = jnp.arange(a, dtype=jnp.int32)[None, :] < phase  # (1, A)
    csum = c - cpm1 + jnp.where(wraps, total, 0)
    idx = _kth_positive(csum, kprime, n, a, roll_phase=phase)
    lane_ok = idx < a
    topa = (jnp.where(lane_ok, idx, 0) + phase) % a

    my_head = book.head[rows[:, None], topa]  # (N, K')
    if cfg.sync_deal_probes:
        # Deal lanes round-robin across granted slots, then probe k
        # candidate slots per lane and serve from the furthest-ahead
        # (see deal_serving_slots; budget rank is arithmetic on the
        # primary dealing).
        slot, rank_in_slot = deal_serving_slots(granted, phase, kprime)
        topv = jnp.zeros((n, kprime), jnp.int32)
        for i in range(min(cfg.sync_deal_probes, p_cnt)):
            slot_i, _ = deal_serving_slots(granted, phase + i, kprime)
            peer_i = peer[rows[:, None], jnp.minimum(slot_i, p_cnt - 1)]
            tv_i = jnp.where(
                slot_i < p_cnt,
                jnp.maximum(book.head[peer_i, topa] - my_head, 0), 0,
            )
            slot = jnp.where(tv_i > topv, slot_i, slot)
            topv = jnp.maximum(tv_i, topv)
        slot = jnp.where(lane_ok & (topv > 0), slot, p_cnt)
        within_budget = rank_in_slot < kp
    else:
        # Exact argmax: what each granted peer can serve of each
        # requested actor — an (N, P, K') gather — then the
        # furthest-ahead assignment with round-robin tie-breaking.
        # Dead lanes get the sentinel slot p_cnt so they sort into
        # their own budget group.
        ph = book.head[peer[:, :, None], topa[:, None, :]]  # (N, P, K')
        delta_p = jnp.maximum(ph - my_head[:, None, :], 0)
        delta_p = jnp.where(granted[:, :, None], delta_p, 0)
        slot, topv = choose_serving_slots(delta_p, topa, phase)
        slot = jnp.where(lane_ok & (topv > 0), slot, p_cnt)
        within_budget = _rank_within_slot(slot, rows, n, kprime) < kp
    return topa, slot, topv, lane_ok, within_budget


def sync_round(
    cfg: SimConfig,
    book: Bookkeeping,
    log: ChangeLog,
    table: TableState,
    hlc: jnp.ndarray,  # (N,) node clocks — exchanged on every contact
    last_cleared: jnp.ndarray,  # (N,) last-applied EmptySet ts (monotone)
    cleared_hlc: jnp.ndarray,  # (A, L) per-version EmptySet ts stamps
    key: jax.Array,
    alive: jnp.ndarray,
    view_alive: jnp.ndarray,
    reachable: jnp.ndarray,
    rtt: jnp.ndarray | None = None,
    round_idx: jnp.ndarray | int = 0,
    fault_key: jax.Array | None = None,
    mesh=None,
    fault_cfg=None,
):
    """One anti-entropy sweep (multi-peer).

    Returns (book, table, hlc, last_cleared, metrics).

    ``fault_cfg``: per-lane traced substitute for ``cfg.faults``
    (corro_sim/sweep/ ``LaneFaultKnobs``) — the sweep program's sync
    grants fail with each LANE's own sync-loss knob. None (every
    off-sweep caller) keeps the static-config path byte-identical.

    Each admitted peer slot carries a FULL per-connection budget
    (``sync_actor_topk`` actors × ``sync_cap_per_actor`` versions), so a
    node with P granted peers repairs up to P× per sweep — the parallel
    bandwidth of ``parallel_sync``. The request schedule is one joint
    top-K' + a per-slot budget rank; gather and merge run as a single
    pass over the combined lanes."""
    n, a = book.head.shape
    k_peer, k_phase = jax.random.split(key)
    peer, granted, requested = choose_sync_peers(
        cfg, book, key=k_peer, alive=alive, view_alive=view_alive,
        reachable=reachable, rtt=rtt)
    p_cnt = peer.shape[1]

    # The anti-entropy transport point (corro_sim/faults/): an ADMITTED
    # connection still fails with resolved_sync_loss — the QUIC stream
    # dying mid-sync — and deterministically across a blackholed edge.
    # Applied before the clock exchange: a dropped connection carries
    # nothing, clocks included. Static: faults off traces none of this.
    # `rejected` snapshots the semaphore verdict FIRST: a fault-killed
    # connection was admitted, so it must count in fault_sync_lost, not
    # in the concurrency-rejection metric.
    rejected = requested & ~granted
    fault_metrics = {}
    if cfg.faults.enabled or fault_cfg is not None:
        from corro_sim.faults.inject import blackhole_mask, sync_grant_keep

        bh = blackhole_mask(cfg.faults, n)
        keep = sync_grant_keep(
            fault_cfg if fault_cfg is not None else cfg.faults,
            fault_key, jnp.arange(n, dtype=jnp.int32), peer,
            None if bh is None else jnp.asarray(bh),
        )
        fault_metrics["fault_sync_lost"] = (granted & ~keep).sum(
            dtype=jnp.int32
        )
        granted = granted & keep

    # Clock exchange, both directions (SyncMessage::Clock is sent by client
    # AND server on every sync contact, api/peer.rs:1074-1126,1502-1521):
    # client merges each granted server's clock; the server merges the
    # client's. The +tick happens in sim_step's end-of-round HLC update.
    client_merge = hlc
    for p in range(p_cnt):
        client_merge = jnp.maximum(
            client_merge, jnp.where(granted[:, p], hlc[peer[:, p]], -1)
        )
    flat_ok = granted.reshape(-1)
    hlc = client_merge.at[
        jnp.where(flat_ok, peer.reshape(-1), n)
    ].max(
        jnp.broadcast_to(hlc[:, None], peer.shape).reshape(-1), mode="drop"
    )

    kp = min(cfg.sync_actor_topk, a)
    req = cfg.sync_req_actors or 2 * kp
    kprime = min(req, kp * p_cnt, a)
    cap = cfg.sync_cap_per_actor
    bpv = cfg.chunks_per_version
    vwin = WINDOW_BITS // bpv
    group_mask = jnp.uint32((1 << bpv) - 1)
    rows = jnp.arange(n, dtype=jnp.int32)
    s = log.seqs
    offs = jnp.arange(1, cap + 1, dtype=jnp.int32)  # (cap,)

    phase = jax.random.randint(k_phase, (), 0, a, dtype=jnp.int32)
    # The dense hot-actor schedule is exact-argmax only; an explicit
    # probe-dealing policy (sync_deal_probes > 0) takes the legacy path
    # so the configured policy actually executes.
    if cfg.sync_hot_actors > 0 and not cfg.sync_deal_probes:
        # ---------------- dense hot-actor schedule (the r5 form) --------
        # Per sweep, compact the actor axis to the actors anyone could
        # need — exactly {a : log.head[a] > min_n book.head[n, a]} — then
        # run needs, per-peer capability, and the serving assignment as
        # DENSE elementwise work over (N, P, A'). This replaces the
        # (N, P, K') per-element capability gather (~99 ms at 10k: every
        # lane a descriptor) and the (N, A, K') k-th-positive
        # compare-reduce (~26 ms) with a handful of streaming passes over
        # (N, P, A') plus one 100k-descriptor ROW gather — XLA gathers
        # cost per descriptor, not per byte, so gathering whole hot-axis
        # rows is ~free while per-element gathers are not. Semantically
        # this requests only what an admitted peer actually HAS (their
        # advertised heads minus ours — compute_available_needs,
        # sync.rs:127-249), like the reference; the legacy path burned
        # request lanes on needs no granted peer could serve.
        ahot = min(cfg.sync_hot_actors, a)
        min_head = book.head.min(axis=0)  # (A,)
        hot_mask = log.head > min_head
        hot_cs = jnp.cumsum(hot_mask.astype(jnp.int32))
        total_hot = hot_cs[-1]
        # SEQUENTIAL window rotation over the hot set: sweep k serves hot
        # ranks [k*A', (k+1)*A') mod total — full coverage of the hot set
        # every ceil(total/A') sweeps. A random phase would re-cover
        # actors coupon-collector style, which at 50k (≈35k hot after an
        # outage) multiplies catch-up sweeps ~3-4x. As repair progresses,
        # actors everyone holds drop out of the hot mask, so the window
        # automatically re-concentrates on what is still missing.
        start = (jnp.asarray(round_idx, jnp.int32) * ahot) % jnp.maximum(
            total_hot, 1
        )
        ranks = (
            start + jnp.arange(ahot, dtype=jnp.int32)
        ) % jnp.maximum(total_hot, 1) + 1  # 1-based hot ranks, wrapped
        hpos = jnp.searchsorted(hot_cs, ranks, side="left").astype(
            jnp.int32
        )
        # positions beyond the number of distinct hot actors are
        # wrapped duplicates — mask them (duplicate lanes would double
        # count served versions)
        hot_ok = jnp.arange(ahot, dtype=jnp.int32) < total_hot
        hot_idx = jnp.where(hot_ok, hpos, 0).clip(0, a - 1)  # (A',)

        head_hot = book.head[:, hot_idx]  # (N, A') column gather
        ph_hot = head_hot[peer]  # (N, P, A') row gather
        delta_p = jnp.maximum(ph_hot - head_hot[:, None, :], 0)
        delta_p = jnp.where(
            granted[:, :, None] & hot_ok[None, None, :], delta_p, 0
        )
        slot_d, best_d = choose_serving_slots(
            delta_p, jnp.broadcast_to(hot_idx[None, :], (n, ahot)), phase
        )  # (N, A') each

        # K' serviceable lanes per node, in (already rotated) hot order.
        ch = jnp.cumsum((best_d > 0).astype(jnp.int32), axis=1)
        idx = _kth_positive(ch, kprime, n, ahot)
        lane_ok = idx < ahot
        pos_sel = jnp.where(lane_ok, idx, 0)
        topa = hot_idx[pos_sel]  # (N, K') actor ids
        slot = jnp.take_along_axis(slot_d, pos_sel, 1)
        topv = jnp.where(
            lane_ok, jnp.take_along_axis(best_d, pos_sel, 1), 0
        )
        slot = jnp.where(lane_ok & (topv > 0), slot, p_cnt)
        if kp >= kprime:
            within_budget = jnp.ones((n, kprime), bool)
        else:
            within_budget = _rank_within_slot(slot, rows, n, kprime) < kp
    else:
        topa, slot, topv, lane_ok, within_budget = _legacy_schedule(
            cfg, book, log, peer, granted, phase, rows,
            n, a, p_cnt, kp, kprime,
        )

    # adaptive chunk sizing (peer.rs:345-349): the reference halves its
    # send buffer 8 KiB → ≥1 KiB as a link slows; here a slow (high
    # measured-RTT) connection serves halved per-actor caps, floored at 1
    # — same 8× dynamic range. Unobserved (255) starts at the full buffer,
    # like the reference before any slow send is seen.
    if rtt is not None:
        raw = rtt[rows[:, None], peer].astype(jnp.int32)  # (N, P)
        delay = jnp.where(raw == 255, 1, jnp.minimum(raw, 4))
        cap_slot = jnp.maximum(cap >> jnp.maximum(delay - 1, 0), 1)
        # sentinel slots clamp to the last peer; harmless — their topv
        # is 0 so take is 0 regardless of cap
        cap_lane = cap_slot[rows[:, None], jnp.minimum(slot, p_cnt - 1)]
    else:
        cap_lane = cap
    take = jnp.where(
        lane_ok & within_budget, jnp.minimum(topv, cap_lane), 0
    )

    # Flat gather lanes: (N, K', cap) → versions head+1 … head+take.
    base = book.head[rows[:, None], topa]  # (N, K')
    ver = base[:, :, None] + offs[None, None, :]  # (N, K', cap)
    lane_valid = offs[None, None, :] <= take[:, :, None]

    actor_l = jnp.broadcast_to(topa[:, :, None], ver.shape).reshape(-1)
    ver_l = ver.reshape(-1)
    valid_l = lane_valid.reshape(-1)
    dst_l = jnp.broadcast_to(rows[:, None, None], ver.shape).reshape(-1)

    row, col, vr, cv, cl, ncells = gather_changesets(
        log, jnp.where(valid_l, actor_l, 0), jnp.maximum(ver_l, 1)
    )
    m = dst_l.shape[0]
    # Cleared versions are served as empties: bookkeeping fast-forwards
    # but no rows transfer (handle_need cleared → SyncMessage
    # Empty/EmptySet, api/peer.rs:716-758).
    g_actor_l = jnp.where(valid_l, actor_l, 0)
    g_slot_l = (jnp.maximum(ver_l, 1) - 1) % log.capacity
    cleared_l = log.cleared[g_actor_l, g_slot_l]
    cell_live = (
        valid_l[:, None]
        & ~cleared_l[:, None]
        & (jnp.arange(s, dtype=jnp.int32)[None, :] < ncells[:, None])
    )
    # DELETE log entries (vr == NEG) are cl-only: no site claim.
    site_l = jnp.where(
        vr == NEG, NEG, jnp.broadcast_to(actor_l[:, None], (m, s))
    )

    # Seq-granular partial serving (SyncNeedV1::Partial,
    # api/peer.rs:351-762, sync.rs:127-249): a version the receiver has
    # PARTIALLY buffered via gossip only transfers its missing chunks —
    # the buffered seq ranges apply locally from the buffer
    # (__corro_buffered_changes in the reference; the shared change log
    # here), costing no wire bytes. ``shipped`` masks out cells whose
    # chunk bit is already set in the receiver's window; the byte-volume
    # metric counts only shipped cells, while the merge still applies the
    # full changeset (completion materializes the buffered data). Served
    # versions are base + o, so the window offset is o - 1 — no per-lane
    # gather needed beyond the (N, K') window word fetched for the
    # already-applied accounting below.
    win_k = book.win[rows[:, None], topa]  # (N, K') uint32
    chunk_of_seq = (
        jnp.arange(s, dtype=jnp.int32) * bpv // max(s, 1)
    )  # (S,) — which chunk each seq belongs to
    voff_o = (offs - 1).clip(0, vwin - 1)  # (cap,)
    bit_off = (
        voff_o[:, None] * bpv + chunk_of_seq[None, :]
    ).astype(jnp.uint32)  # (cap, S)
    buffered = (
        (win_k[:, :, None, None] >> bit_off[None, None, :, :])
        & jnp.uint32(1)
    ).astype(bool) & ((offs - 1) < vwin)[None, None, :, None]  # (N,K',cap,S)
    shipped = cell_live & ~buffered.reshape(m, s)
    if kernel_supported(cfg):
        # Sync lanes are already node-major ((N, K', cap, S) construction)
        # — the per-node mailbox is a reshape + pad, no routing scatter;
        # the Pallas kernel then merges with zero per-lane descriptors
        # (core/merge_kernel.py).
        lanes_per_node = kprime * cap * s
        pad = (-lanes_per_node) % 128
        cell_f = row * cfg.num_cols + col

        def node_major(x):
            v = x.reshape(n, lanes_per_node)
            if pad:
                v = jnp.pad(v, ((0, 0), (0, pad)))
            return v.reshape(-1)

        box = jnp.stack([
            node_major(cell_f),
            node_major(cv),
            node_major(vr),
            node_major(site_l),
            node_major(cl),
            node_major(cell_live.astype(jnp.int32)),
            jnp.zeros((n * (lanes_per_node + pad),), jnp.int32),
            jnp.zeros((n * (lanes_per_node + pad),), jnp.int32),
        ])
        assert box.shape[0] == LANE_FIELDS
        table = merge_grouped(
            table, box, lanes_per_node + pad,
            block_nodes=pick_block_nodes(n),
            interpret=kernel_interpret(),
            # sync lanes are requester-major: the mailbox is already
            # dst-sharded exactly like the table planes, so the
            # mesh-partitioned kernel needs NO collectives (ISSUE 8)
            mesh=mesh,
        )
    else:
        table = apply_cell_changes(
            table,
            jnp.broadcast_to(dst_l[:, None], (m, s)).reshape(-1),
            row.reshape(-1),
            col.reshape(-1),
            cv.reshape(-1),
            vr.reshape(-1),
            site_l.reshape(-1),
            cl.reshape(-1),
            cell_live.reshape(-1),
        )

    # Raise heads: floor[i, topa] = head + take (max-combine; slots serve
    # disjoint actors, so duplicate topa entries only occur at take == 0).
    floor = book.head.at[rows[:, None], topa].max(base + take)

    # Newly-applied count: versions in head+1..head+take that were already
    # seq-complete in the window arrived earlier via gossip and were
    # counted then — don't count the re-transfer again.
    already = jnp.zeros(take.shape, jnp.int32)
    for o in range(min(cap, vwin)):
        g = (win_k >> jnp.uint32(o * bpv)) & group_mask
        already = already + ((g == group_mask) & (o < take)).astype(jnp.int32)
    new_versions = (take - already).sum(dtype=jnp.int32)
    empties = (valid_l & cleared_l).sum(dtype=jnp.int32)

    # Served empties advance the receiver's last-cleared ts to the
    # EmptySet's stamp — monotone max, HLC-gated like the gossip path.
    last_cleared = last_cleared.at[
        jnp.where(valid_l & cleared_l, dst_l, n)
    ].max(cleared_hlc[g_actor_l, g_slot_l], mode="drop")

    book = advance_heads(book, floor, bpv)

    metrics = {
        "sync_pairs": granted.sum(dtype=jnp.int32),
        # client requests sent vs server-semaphore rejections
        # (corro.sync.client.member accepted/rejected, handlers.rs) —
        # pre-fault, so injected connection loss is not misread as
        # concurrency-limiter pressure
        "sync_requests": requested.sum(dtype=jnp.int32),
        "sync_rejections": rejected.sum(dtype=jnp.int32),
        "sync_versions": new_versions,
        "sync_empties": empties,
        # cell lanes SHIPPED by this sweep — the byte-volume signal
        # (corro.sync.chunk.sent.bytes analog, metrics.rs). Chunks the
        # receiver already buffered via gossip are excluded: partial
        # needs transfer only the missing seq ranges (SyncNeedV1::Partial).
        "sync_cells": shipped.sum(dtype=jnp.int32),
        **fault_metrics,
    }
    return book, table, hlc, last_cleared, metrics
