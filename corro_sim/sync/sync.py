"""Anti-entropy sync: vectorized `compute_available_needs` + budgeted repair.

Reference protocol (``corro-agent/src/agent/handlers.rs:974-1085``,
``api/peer.rs:1036-1372``, ``corro-types/src/sync.rs:127-249``):

1. every 1-15 s a node generates its ``SyncStateV1`` (per-actor heads +
   needed gap ranges) and picks ``max(min(n/100, 10), 3)`` peers out of 10
   random candidates, preferring peers it needs the most from;
2. servers reject beyond 3 concurrent inbound syncs (``Semaphore(3)``,
   ``corro-types/src/agent.rs:132``);
3. the client computes *needs* — set-difference of their haves minus ours —
   and requests version ranges in bounded chunks; the server re-reads
   ``crsql_changes`` and streams them back with adaptive chunk sizing.

TPU shape: "their haves minus ours" over interval sets becomes plain
arithmetic on the (N, A) head matrix: ``delta = relu(head[peer] - head)``.
Need-based peer scoring is estimated over a sampled actor subset (exact
need would be an (N, candidates, A) tensor — the sample plays the role of
the reference's chunked requests). The transfer itself is a budgeted gather
from the global change log: top-K needy actors × ≤cap versions each — the
analog of ``chunk_range(…, 10)`` + per-round request caps
(``peer.rs:1207,1241-1372``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from corro_sim.config import SimConfig
from corro_sim.core.bookkeeping import Bookkeeping, advance_heads
from corro_sim.core.changelog import ChangeLog, gather_changesets
from corro_sim.core.crdt import NEG, TableState, apply_cell_changes
from corro_sim.utils.bits import WINDOW_BITS
from corro_sim.utils.slots import ranks_within_group


def choose_sync_peers(
    cfg: SimConfig,
    book: Bookkeeping,
    key: jax.Array,
    alive: jnp.ndarray,
    view_alive: jnp.ndarray,  # (N, N) or (1, N) believed-alive
    reachable: jnp.ndarray,  # (N, N) or (1, N) ground-truth link mask
):
    """Pick one sync peer per node; enforce the server-side semaphore.

    Returns ``(peer, granted)`` — peer id per node and whether the pair was
    admitted (need > 0, both ends up, reachable, and within the server's
    3-inbound cap; rejects model ``SyncRejectionV1::MaxConcurrencyReached``,
    ``api/peer.rs:1525-1542``).
    """
    n, a = book.head.shape
    k_cand, k_samp, k_tie = jax.random.split(key, 3)
    c = cfg.sync_candidates

    cand = jax.random.randint(k_cand, (n, c), 0, n, dtype=jnp.int32)
    samp = jax.random.choice(
        k_samp, a, (min(cfg.sync_need_sample, a),), replace=False
    )

    head_s = book.head[:, samp]  # (N, S)
    # need[i, j] = sum over sampled actors of versions cand j has that i lacks
    need = jnp.maximum(
        head_s[cand] - head_s[:, None, :], 0
    ).sum(axis=-1, dtype=jnp.int32)  # (N, C)

    rows = jnp.arange(n, dtype=jnp.int32)
    if view_alive.shape[0] == 1:
        believed = view_alive[0][cand]
    else:
        believed = view_alive[rows[:, None], cand]
    ok = believed & (cand != rows[:, None])
    need = jnp.where(ok, need, -1)

    j = jnp.argmax(need, axis=1)
    peer = cand[rows, j]
    has_need = need[rows, j] > 0

    # Ground truth: both ends actually up and connected.
    if reachable.shape[0] == 1:
        link = reachable[0][peer]
    else:
        link = reachable[rows, peer]
    want = has_need & alive & alive[peer] & link

    # Server semaphore: at most sync_server_cap inbound syncs per peer.
    big = jnp.int32(n + 1)
    req = jnp.where(want, peer, big)
    order = jnp.argsort(req)
    rank = ranks_within_group(req[order])
    admitted_sorted = rank < cfg.sync_server_cap
    inv = jnp.zeros((n,), jnp.int32).at[order].set(rows)
    granted = want & admitted_sorted[inv]
    return peer, granted


def sync_round(
    cfg: SimConfig,
    book: Bookkeeping,
    log: ChangeLog,
    table: TableState,
    key: jax.Array,
    alive: jnp.ndarray,
    view_alive: jnp.ndarray,
    reachable: jnp.ndarray,
):
    """One anti-entropy sweep. Returns (book, table, metrics dict)."""
    n, a = book.head.shape
    k_peer, _ = jax.random.split(key)
    peer, granted = choose_sync_peers(cfg, book, key=k_peer, alive=alive,
                                      view_alive=view_alive, reachable=reachable)

    # Exact per-actor needs vs the chosen peer (their haves minus ours —
    # compute_available_needs, sync.rs:127-249 — on the head matrix).
    delta = jnp.maximum(book.head[peer] - book.head, 0)  # (N, A)
    delta = jnp.where(granted[:, None], delta, 0)

    k = min(cfg.sync_actor_topk, a)
    topv, topa = jax.lax.top_k(delta, k)  # (N, K) values + actor ids
    take = jnp.minimum(topv, cfg.sync_cap_per_actor)  # versions per actor

    # Build flat gather lanes: (N, K, cap) → versions head+1 … head+take.
    cap = cfg.sync_cap_per_actor
    base = book.head[jnp.arange(n)[:, None], topa]  # (N, K)
    offs = jnp.arange(1, cap + 1, dtype=jnp.int32)  # (cap,)
    ver = base[:, :, None] + offs[None, None, :]  # (N, K, cap)
    lane_valid = offs[None, None, :] <= take[:, :, None]

    actor_l = jnp.broadcast_to(topa[:, :, None], ver.shape).reshape(-1)
    ver_l = ver.reshape(-1)
    valid_l = lane_valid.reshape(-1)
    dst_l = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32)[:, None, None], ver.shape
    ).reshape(-1)

    row, col, vr, cv, cl, ncells = gather_changesets(
        log, jnp.where(valid_l, actor_l, 0), jnp.maximum(ver_l, 1)
    )
    s = log.seqs
    m = dst_l.shape[0]
    # Cleared versions are served as empties: bookkeeping fast-forwards but
    # no rows transfer (handle_need cleared → SyncMessage Empty/EmptySet,
    # api/peer.rs:716-758).
    cleared_l = log.cleared[
        jnp.where(valid_l, actor_l, 0),
        (jnp.maximum(ver_l, 1) - 1) % log.capacity,
    ]
    cell_live = (
        valid_l[:, None]
        & ~cleared_l[:, None]
        & (jnp.arange(s, dtype=jnp.int32)[None, :] < ncells[:, None])
    )
    # DELETE log entries (vr == NEG) are cl-only: no site claim.
    site_l = jnp.where(
        vr == NEG, NEG, jnp.broadcast_to(actor_l[:, None], (m, s))
    )
    table = apply_cell_changes(
        table,
        jnp.broadcast_to(dst_l[:, None], (m, s)).reshape(-1),
        row.reshape(-1),
        col.reshape(-1),
        cv.reshape(-1),
        vr.reshape(-1),
        site_l.reshape(-1),
        cl.reshape(-1),
        cell_live.reshape(-1),
    )

    # Raise heads: floor[i, topa] = head + take, absorb window bits above.
    floor = book.head.at[
        jnp.arange(n, dtype=jnp.int32)[:, None], topa
    ].max(base + take)

    # Newly-applied count: versions in head+1..head+take that were already
    # seq-complete in the window arrived earlier via gossip and were counted
    # then — don't count the re-transfer again.
    bpv = cfg.chunks_per_version
    vwin = WINDOW_BITS // bpv
    win_g = book.win[jnp.arange(n, dtype=jnp.int32)[:, None], topa]
    group_mask = jnp.uint32((1 << bpv) - 1)
    already = jnp.zeros(take.shape, jnp.int32)
    for o in range(min(cap, vwin)):
        g = (win_g >> jnp.uint32(o * bpv)) & group_mask
        already = already + ((g == group_mask) & (o < take)).astype(jnp.int32)
    new_versions = (take - already).sum(dtype=jnp.int32)

    book = advance_heads(book, floor, bpv)

    metrics = {
        "sync_pairs": granted.sum(dtype=jnp.int32),
        "sync_versions": new_versions,
        "sync_empties": (valid_l & cleared_l).sum(dtype=jnp.int32),
    }
    return book, table, metrics
