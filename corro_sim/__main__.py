"""`python -m corro_sim` → the CLI (same entry as the corro-sim script)."""

import sys

from corro_sim.cli import main

if __name__ == "__main__":
    sys.exit(main())
