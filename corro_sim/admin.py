"""Admin server — the `corro-admin` unix-socket command surface.

The reference runs a JSON-framed command server on a unix socket
(``corro-admin/src/lib.rs:44-120``) driven by the ``corrosion`` CLI:
Ping, Sync Generate, Locks{top}, Cluster Members / MembershipStates,
Actor Version, Subs Info/List. Same surface here, over ND-JSON lines
(one request object in, one response object out per line) against the
in-process LiveCluster.

Extra commands the reference does through other channels map naturally
onto the socket because the cluster lives in-process: ``backup`` /
``restore`` (``corrosion backup|restore``, ``main.rs:155-324``) and
fault injection (`corro-devcluster`'s role).
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading

import numpy as np


class AdminError(Exception):
    pass


class _Handler(socketserver.StreamRequestHandler):
    def handle(self):
        api: AdminServer = self.server.admin  # type: ignore[attr-defined]
        for raw in self.rfile:
            raw = raw.strip()
            if not raw:
                continue
            try:
                req = json.loads(raw)
                resp = api.dispatch(req)
            except AdminError as e:
                resp = {"ok": False, "error": str(e)}
            except json.JSONDecodeError as e:
                resp = {"ok": False, "error": f"bad request: {e}"}
            except Exception as e:  # survivable command failure
                resp = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            self.wfile.write((json.dumps(resp) + "\n").encode())
            self.wfile.flush()


class _Server(socketserver.ThreadingUnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class AdminServer:
    def __init__(self, cluster, sock_path: str):
        self.cluster = cluster
        self.path = str(sock_path)
        if os.path.exists(self.path):
            os.unlink(self.path)
        self._srv = _Server(self.path, _Handler)
        self._srv.admin = self  # type: ignore[attr-defined]
        self._thread: threading.Thread | None = None
        self._db_locks: dict = {}  # token -> (holder thread, release event)

    def start(self) -> "AdminServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="corro-admin", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if os.path.exists(self.path):
            os.unlink(self.path)
        if self._thread:
            self._thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------ dispatch
    def dispatch(self, req: dict) -> dict:
        cmd = req.get("cmd")
        fn = getattr(self, f"_cmd_{cmd}", None)
        if fn is None:
            raise AdminError(f"unknown command {cmd!r}")
        out = fn(req)
        return {"ok": True, **(out or {})}

    def _cmd_ping(self, req):
        return {"pong": True}

    def _cmd_locks(self, req):
        """`corrosion locks --top N` — LockRegistry dump
        (``corro-types/src/agent.rs:890-1099``, admin Locks{top})."""
        top = req.get("top")
        return {"locks": self.cluster.locks.snapshot(top=top)}

    def _cmd_cluster_members(self, req):
        return {"members": self.cluster.members()}

    def _cmd_cluster_membership_states(self, req):
        """SWIM per-node view matrix (admin MembershipStates analog)."""
        c = self.cluster
        out = {"swim_enabled": bool(c.cfg.swim_enabled)}
        if c.cfg.swim_enabled:
            sw = c.state.swim
            status = np.asarray(sw.status)
            if hasattr(sw, "member"):  # windowed O(N·K) belief state
                member = np.asarray(sw.member)
                tracked = member >= 0
                out["incarnation"] = np.asarray(sw.self_inc).tolist()
                n = member.shape[0]
                sus = np.zeros(n, np.int64)
                dwn = np.zeros(n, np.int64)
                np.add.at(
                    sus, member[tracked & (status == 1)], 1
                )
                np.add.at(
                    dwn, member[tracked & (status >= 2)], 1
                )
                out["suspected_by"] = sus.tolist()
                out["down_by"] = dwn.tolist()
            else:
                out["incarnation"] = np.asarray(sw.inc).diagonal().tolist()
                # per-node summary, not the full N×N belief matrix
                out["suspected_by"] = (status == 1).sum(axis=0).tolist()
                out["down_by"] = (status >= 2).sum(axis=0).tolist()
        return out

    def _cmd_cluster_rejoin(self, req):
        """Admin Cluster Rejoin: revive a node with a renewed identity
        (``FocaCmd::Rejoin``, ``corro-admin/src/lib.rs:364-383``)."""
        if "node" not in req:
            raise AdminError("cluster_rejoin requires 'node'")
        return self.cluster.rejoin(int(req["node"]))

    def _cmd_cluster_set_id(self, req):
        """Admin Cluster SetId (``corro-admin/src/lib.rs:431-474``):
        cluster ids map onto the partition plane (see
        LiveCluster.set_cluster_id). Both fields are required — a
        defaulted cluster_id of 0 would silently re-admit a walled-off
        node into the main cluster."""
        for field in ("node", "cluster_id"):
            if field not in req:
                raise AdminError(f"cluster_set_id requires {field!r}")
        return self.cluster.set_cluster_id(
            int(req["node"]), int(req["cluster_id"])
        )

    def _cmd_sync_reconcile_gaps(self, req):
        """Admin Sync ReconcileGaps (``corro-admin/src/lib.rs:315-341``)."""
        return self.cluster.reconcile_gaps()

    def _cmd_traces(self, req):
        """Recent spans from the process tracer — the admin-side read of
        what the reference ships to its OTLP collector."""
        from corro_sim.utils.tracing import tracer

        n = int(req.get("n", 100))
        name = req.get("name")
        trace_id = req.get("trace_id")
        if trace_id:
            spans = tracer.trace(trace_id)
        else:
            spans = tracer.recent(n=n, name=name)
        return {"spans": [s.as_json() for s in spans]}

    def _cmd_flight(self, req):
        """Flight-recorder timeline: per-round metrics, annotations,
        derived convergence diagnostics. ``diag_only`` trims the body to
        the diagnostics; ``n`` keeps only the last n rounds; ``export``
        additionally dumps the full ND-JSON to a path server-side."""
        fl = getattr(self.cluster, "flight", None)
        if fl is None:
            raise AdminError("no flight recorder attached")
        export = req.get("export")
        if export:
            fl.dump(str(export))
        if req.get("diag_only"):
            return {"diagnostics": fl.diagnostics(),
                    **({"exported": export} if export else {})}
        n = req.get("n")
        if n is not None and int(n) < 0:
            raise AdminError("n must be >= 0")
        out = fl.timeline(last_rounds=int(n) if n else None)
        if export:
            out["exported"] = export
        return out

    def _cmd_sweep(self, req):
        """The fleet observatory's sweep snapshot (corro_sim/obs/
        lanes.py) — the admin-socket face of GET /v1/sweep: live
        per-chunk lane-state while a sweep runs in this process, the
        final summary after."""
        from corro_sim.obs.lanes import sweep_status

        st = sweep_status()
        if st is None:
            raise AdminError("no sweep has run in this process")
        return {"sweep": st}

    def _cmd_probes(self, req):
        """Probe-tracer provenance + the per-node lag observatory
        (`corro-sim probes`). ``lag_only`` trims to the observatory;
        ``export`` writes the NDJSON journal + Chrome trace JSON
        server-side under the given path prefix."""
        if req.get("lag_only"):
            return {"node_lag": self.cluster.node_lag(
                top_k=int(req.get("top", 8))
            )}
        out = self.cluster.probe_report()
        export = req.get("export")
        if export:
            tr = self.cluster.probe_trace()
            if tr is None:
                raise AdminError(
                    "probe tracer disabled — nothing to export"
                )
            tr.dump_ndjson(f"{export}.ndjson")
            tr.dump_chrome_trace(f"{export}.trace.json")
            out["exported"] = [f"{export}.ndjson", f"{export}.trace.json"]
        return out

    # ------------------------------------------------------------- db lock
    # `corrosion db lock "cmd"` holds exclusive byte-range locks on the DB
    # while a shell command runs (``main.rs:492-530``,
    # ``sqlite3-restore/src/lib.rs:16-57``). The tensor-state analog: hold
    # the cluster's write lock between acquire/release admin calls — every
    # write, tick, migration and restore blocks until released. A holder
    # thread owns the (thread-bound) RLock and auto-releases on timeout in
    # case the client dies with the lock held.
    def _cmd_db_lock_acquire(self, req):
        import uuid

        timeout = float(req.get("timeout", 30.0))
        if not (0 < timeout <= 24 * 3600):
            raise AdminError(
                f"db lock timeout must be in (0, 86400], got {timeout}"
            )
        token = uuid.uuid4().hex[:12]
        acquired = threading.Event()
        release = threading.Event()
        expired = threading.Event()

        def hold():
            with self.cluster.locks.tracked(
                self.cluster._lock, f"db lock {token}", "write"
            ):
                acquired.set()
                if not release.wait(timeout):
                    expired.set()  # crash-safety auto-release fired
                    # prune our own entry: the client that would have
                    # released it is exactly the one that crashed
                    self._db_locks.pop(token, None)

        th = threading.Thread(target=hold, name=f"db-lock-{token}",
                              daemon=True)
        # register BEFORE starting: the holder's expiry-prune must always
        # find the entry, however small the timeout
        self._db_locks[token] = (th, release, expired)
        th.start()
        if not acquired.wait(10):
            release.set()
            self._db_locks.pop(token, None)
            raise AdminError("could not acquire the write lock in 10s")
        return {"token": token, "timeout": timeout}

    def _cmd_db_lock_release(self, req):
        token = req.get("token")
        entry = self._db_locks.pop(token, None)
        if entry is None:
            # distinguishable error text: the CLI treats an unknown token
            # as "the hold expired and self-pruned"
            raise AdminError(f"unknown db lock token {token!r}")
        th, release, expired = entry
        release.set()
        th.join(timeout=5)
        # an expired hold means the lock was NOT protecting the tail of
        # whatever ran under it — the caller must know
        return {"released": token, "expired": expired.is_set()}

    def _cmd_actor_version(self, req):
        actor = int(req.get("actor", 0))
        return self.cluster.actor_versions(actor)

    def _cmd_sync_generate(self, req):
        """SyncStateV1 analog for one node (admin Sync Generate):
        per-actor applied heads + total need vs the cluster's written
        heads (``generate_sync``, ``corro-types/src/sync.rs:284-344``)."""
        node = int(req.get("node", 0))
        self.cluster._check_node(node)
        heads = np.asarray(self.cluster.state.book.head)[node]
        written = np.asarray(self.cluster.state.log.head)
        need = np.maximum(written - heads, 0)
        return {
            "actor_id": node,
            "heads": heads.tolist(),
            "need": {
                str(a): int(n) for a, n in enumerate(need) if n > 0
            },
            "total_need": int(need.sum()),
        }

    def _cmd_subs_list(self, req):
        subs = []
        for sub_id, m in self.cluster.subs._by_id.items():
            subs.append(
                {
                    "id": sub_id,
                    "sql": m.select.normalized(),
                    "node": m.node,
                    "change_id": m.change_id,
                    "streams": len(self.cluster._sub_queues.get(sub_id, [])),
                }
            )
        return {"subs": subs}

    def _cmd_subs_info(self, req):
        sub_id = req.get("id")
        m = self.cluster.subs.get(sub_id)
        if m is None:
            raise AdminError(f"no such subscription {sub_id!r}")
        return {
            "id": sub_id,
            "sql": m.select.normalized(),
            "node": m.node,
            "change_id": m.change_id,
            "buffered_events": len(m._events),
            "streams": len(self.cluster._sub_queues.get(sub_id, [])),
        }

    def _cmd_table_stats(self, req):
        return {"tables": self.cluster.table_stats()}

    def _cmd_backup(self, req):
        from corro_sim.io.checkpoint import backup

        path = req.get("path")
        if not path:
            raise AdminError("backup needs a path")
        backup(self.cluster, path, node=int(req.get("node", 0)))
        return {"path": path}

    def _cmd_restore(self, req):
        from corro_sim.io.checkpoint import restore_into

        path = req.get("path")
        if not path or not os.path.exists(path):
            raise AdminError(f"no such backup file {path!r}")
        restore_into(self.cluster, path, node=int(req.get("node", 0)))
        return {"path": path}

    def _cmd_checkpoint(self, req):
        from corro_sim.io.checkpoint import save_checkpoint

        path = req.get("path")
        if not path:
            raise AdminError("checkpoint needs a path")
        save_checkpoint(self.cluster, path)
        return {"path": path}

    def _cmd_set_alive(self, req):
        """Fault injection (devcluster role): mark a node up/down."""
        self.cluster.set_alive(int(req["node"]), bool(req["alive"]))
        return {}

    def _cmd_tick(self, req):
        self.cluster.tick(int(req.get("rounds", 1)))
        return {"rounds_ticked": self.cluster._rounds_ticked}


class AdminClient:
    """Line-oriented client for the admin socket (CLI side)."""

    def __init__(self, sock_path: str, timeout: float = 30.0):
        self.path = str(sock_path)
        self.timeout = timeout

    def call(self, cmd: str, **args) -> dict:
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.settimeout(self.timeout)
            s.connect(self.path)
            s.sendall(
                (json.dumps({"cmd": cmd, **args}) + "\n").encode()
            )
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
        resp = json.loads(buf)
        if not resp.get("ok"):
            raise AdminError(resp.get("error", "command failed"))
        return resp
