"""`corro-sim` command line — the analog of the reference's `corrosion` CLI.

The reference binary exposes Agent/Backup/Restore/Cluster/Query/Exec/Sync/…
subcommands (``crates/corrosion/src/main.rs:626-801``). The simulator's
command surface grows toward that inventory; current subcommands:

  run     — run a simulation config to convergence, print a report
  bench   — the headline benchmark (same as bench.py)
"""

from __future__ import annotations

import argparse
import json
import sys


_FLAG_TO_FIELD = {
    "nodes": "num_nodes",
    "rows": "num_rows",
    "cols": "num_cols",
    "log_capacity": "log_capacity",
    "write_rate": "write_rate",
    "zipf": "zipf_alpha",
    "swim": "swim_enabled",
    "sync_interval": "sync_interval",
}


def _cmd_run(args: argparse.Namespace) -> int:
    import dataclasses

    import numpy as np

    from corro_sim.engine import init_state, run_sim
    from corro_sim.engine.driver import Schedule
    from corro_sim.io.config_file import load_config

    # --config (+ CORRO_SIM__* env) provides the base; explicit CLI flags
    # win — the reference's TOML < env < CLI precedence
    # (corro-types/src/config.rs:284-291, corrosion/src/main.rs:558-624).
    cfg = load_config(args.config)
    overrides = {
        field: getattr(args, flag)
        for flag, field in _FLAG_TO_FIELD.items()
        if getattr(args, flag) is not None
    }
    cfg = dataclasses.replace(cfg, **overrides).validate()
    res = run_sim(
        cfg,
        init_state(cfg, seed=args.seed),
        Schedule(write_rounds=args.write_rounds),
        max_rounds=args.max_rounds,
        chunk=args.chunk,
        seed=args.seed,
    )
    report = {
        "nodes": cfg.num_nodes,
        "converged_round": res.converged_round,
        "rounds_run": res.rounds,
        "writes": int(res.metrics["writes"].sum()),
        "changes_applied": int(res.metrics["fresh"].sum())
        + int(res.metrics["sync_versions"].sum()),
        "dropped_window": int(res.metrics["dropped_window"].sum()),
        "wall_per_round_ms": round(res.wall_per_round_ms, 3),
        "compile_seconds": round(res.compile_seconds, 2),
        "sim_seconds_per_round": cfg.round_ms / 1000.0,
        "final_gap": float(np.asarray(res.metrics["gap"])[-1]),
    }
    print(json.dumps(report, indent=2))
    return 0 if res.converged_round is not None else 3


def _cmd_bench(_args: argparse.Namespace) -> int:
    from corro_sim.benchmarks import main as bench_main

    return bench_main() or 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="corro-sim",
        description="TPU-native simulator of Corrosion's replication protocols",
    )
    sub = p.add_subparsers(dest="command", required=True)

    pr = sub.add_parser("run", help="run a simulation to convergence")
    pr.add_argument("--config", help="TOML config file ([sim] table)")
    pr.add_argument("--nodes", type=int)
    pr.add_argument("--rows", type=int)
    pr.add_argument("--cols", type=int)
    pr.add_argument("--log-capacity", type=int)
    pr.add_argument("--write-rate", type=float)
    pr.add_argument("--zipf", type=float)
    pr.add_argument("--swim", action="store_const", const=True)
    pr.add_argument("--sync-interval", type=int)
    pr.add_argument("--write-rounds", type=int, default=32)
    pr.add_argument("--max-rounds", type=int, default=4096)
    pr.add_argument("--chunk", type=int, default=16)
    pr.add_argument("--seed", type=int, default=0)
    pr.set_defaults(fn=_cmd_run)

    pb = sub.add_parser("bench", help="run the headline benchmark")
    pb.set_defaults(fn=_cmd_bench)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
