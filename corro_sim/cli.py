"""`corro-sim` command line — the analog of the reference's `corrosion` CLI.

Command surface vs the reference's Command enum
(``crates/corrosion/src/main.rs:626-801``):

  run          — run a simulation config to convergence, print a report
                 (--fork replays a what-if off a twin fork token)
  twin         — shadow a changeset feed + forecast what-if chaos
                 (streaming ingest, cursor resume; doc/twin.md)
  bench        — BASELINE benchmark configs 0-7 (default: 0, north star)
  agent        — live cluster: HTTP API + admin socket (+ --pg-addr
                 pgwire, + --tls-* for TLS/mTLS)      [Command::Agent]
  devcluster   — run an `A -> B` topology file        [corro-devcluster]
  query / exec — SELECT / DML against a running agent [Query/Exec]
  backup / restore — actor-neutral snapshots          [Backup/Restore]
  reload       — re-apply schema files                [Command::Reload]
  cluster      — members / membership-states / rejoin / set-id
  sync         — generate / reconcile-gaps            [Command::Sync]
  actor        — version bookkeeping introspection    [Command::Actor]
  subs         — list / inspect subscriptions         [Command::Subs]
  locks        — lock registry dump                   [Command::Locks]
  traces       — recent tracer spans                  [telemetry analog]
  lint         — corro-lint trace-safety analyzer     [corro_sim/analysis/]
  audit        — jaxpr vacuity + golden fingerprint   [corro_sim/analysis/]
  flight       — per-round telemetry timeline         [flight recorder]
  probes       — gossip provenance + lag observatory  [probe tracer]
  db lock      — hold the write lock around a command [DbCommand::Lock]
  tls          — ca / server / client cert generation [Command::Tls]
  template     — render templates w/ live re-render   [Command::Template]
  consul-sync  — mirror Consul services/checks        [Command::Consul]
"""

from __future__ import annotations

import argparse
import json
import os
import sys


_FLAG_TO_FIELD = {
    "nodes": "num_nodes",
    "rows": "num_rows",
    "cols": "num_cols",
    "log_capacity": "log_capacity",
    "write_rate": "write_rate",
    "zipf": "zipf_alpha",
    "swim": "swim_enabled",
    "swim_view": "swim_view_size",
    "sync_interval": "sync_interval",
    "probes": "probes",
    "pipeline": "pipeline",
}


def _cmd_run(args: argparse.Namespace) -> int:
    import dataclasses

    import numpy as np

    # the persistent cache the bench/tools entry points already share
    # (ISSUE 10): repeat runs hit instead of recompiling, and the
    # report's compile_cache block reads hit/miss instead of "unknown".
    # MUST run before the engine imports below: they jit at import
    # time, and jax latches its cache as uninitialized for every later
    # write if the first compile happens with no cache dir configured.
    from corro_sim.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    from corro_sim.engine import init_state, run_sim
    from corro_sim.engine.driver import Schedule
    from corro_sim.io.config_file import load_config

    # --config (+ CORRO_SIM__* env) provides the base; explicit CLI flags
    # win — the reference's TOML < env < CLI precedence
    # (corro-types/src/config.rs:284-291, corrosion/src/main.rs:558-624).
    overrides = {
        field: getattr(args, flag)
        for flag, field in _FLAG_TO_FIELD.items()
        if getattr(args, flag) is not None
    }
    if getattr(args, "shard_log", None) is not None:
        # tri-state: an explicit regime beats the size heuristic
        overrides["shard_log"] = {
            "on": True, "off": False, "auto": None
        }[args.shard_log]
    fork_tok = None
    if getattr(args, "fork", None):
        # what-if fork repro (corro_sim/engine/twin.py; doc/twin.md):
        # the run warm-starts from a twin's fork token — the token OWNS
        # the base shape, so shape flags are refused rather than
        # silently diverging the repro from the forecast lane it names
        from corro_sim.io.checkpoint import load_sim_checkpoint

        try:
            fork_tok = load_sim_checkpoint(args.fork)
        except (OSError, ValueError) as e:
            print(f"error: --fork {args.fork!r}: {e}", file=sys.stderr)
            return 2
        if not fork_tok.is_fork:
            print(
                f"error: {args.fork!r} is a mid-run soak cursor, not a "
                "fork token (corro-sim twin --fork-out writes one)",
                file=sys.stderr,
            )
            return 2
        if overrides:
            print(
                "error: --fork carries the base config in the token — "
                f"drop {sorted(overrides)} (only --scenario/--knob/"
                "--seed/--chunk/--max-rounds compose with a fork)",
                file=sys.stderr,
            )
            return 2
        if getattr(args, "workload", None):
            print(
                "error: --fork does not compose with --workload (the "
                "forked state IS the load; run_sim resume refuses "
                "workload schedules)",
                file=sys.stderr,
            )
            return 2
        cfg = fork_tok.cfg
    else:
        cfg = load_config(args.config)
        cfg = dataclasses.replace(cfg, **overrides).validate()
    mesh = None
    if getattr(args, "mesh", False):
        import jax

        from corro_sim.engine.sharding import make_mesh

        if len(jax.devices()) < 2:
            print(
                "error: --mesh needs >1 visible device (force a CPU "
                "mesh with XLA_FLAGS="
                "--xla_force_host_platform_device_count=8)",
                file=sys.stderr,
            )
            return 2
        mesh = make_mesh()
    schedule = Schedule(write_rounds=args.write_rounds)
    scenario = None
    if args.scenario:
        from corro_sim.faults import make_scenario

        scenario = make_scenario(
            args.scenario, cfg.num_nodes,
            # --scenario-rounds pins the fault-timeline horizon a sweep
            # lane was compiled with (corro_sim/sweep/ repro commands):
            # generators truncate/derive waves against `rounds`, so a
            # different horizon is a different timeline
            rounds=getattr(args, "scenario_rounds", None)
            or args.max_rounds,
            write_rounds=args.write_rounds, seed=args.seed,
        )
        cfg = scenario.apply(cfg)
        schedule = scenario.schedule()
    if getattr(args, "knob", None):
        # `--knob loss=0.2` / `--knob write_rate=0.3` knob overrides —
        # the sweep frontier's worst-seed repro surface (corro_sim/
        # sweep/plan.py repro_cmd): a knob-axis grid cell reproduces as
        # one serial run with the same override applied on top of the
        # scenario. Link-fault fields land on cfg.faults; SimConfig
        # sim-knob fields (write_rate/zipf_alpha/sync_interval/...)
        # land on cfg itself, int-cast where the field is integral.
        from corro_sim.sweep import SIM_KNOB_FIELDS, SWEEP_KNOB_FIELDS
        from corro_sim.sweep.plan import _SIM_INT_FIELDS

        fault_over, sim_over = {}, {}
        for kv in args.knob:
            field, _, value = kv.partition("=")
            try:
                num = float(value)
            except ValueError:
                num = None
            if field in SWEEP_KNOB_FIELDS and num is not None:
                fault_over[field] = num
            elif field in SIM_KNOB_FIELDS and num is not None:
                sim_over[field] = (
                    int(num) if field in _SIM_INT_FIELDS else num
                )
            else:
                print(
                    f"error: --knob {kv!r} (expected field=value with "
                    "field one of "
                    f"{', '.join(SWEEP_KNOB_FIELDS + SIM_KNOB_FIELDS)} "
                    "and a numeric value)",
                    file=sys.stderr,
                )
                return 2
        if fault_over:
            cfg = dataclasses.replace(
                cfg, faults=dataclasses.replace(cfg.faults, **fault_over)
            )
        if sim_over:
            cfg = dataclasses.replace(cfg, **sim_over)
        cfg = cfg.validate()
    if fork_tok is not None and cfg.node_faults.enabled:
        # the what-if frame shift (config.shift_node_faults): the forked
        # state's round counter continues the twin's timeline, so
        # scenario-relative wipe rounds become absolute (fork + k) —
        # exactly what the forecast lane this command reproduces baked
        from corro_sim.config import shift_node_faults

        cfg = dataclasses.replace(
            cfg, node_faults=shift_node_faults(
                cfg.node_faults, fork_tok.fork_round
            )
        ).validate()
    workload = None
    if getattr(args, "workload", None):
        # the unified spec surface: --scenario X --workload Y in ONE run
        # (load + faults overlapping is where the latency-under-load
        # story lives) — coupling validated up front, not after compile
        from corro_sim.workload import make_workload

        workload = make_workload(
            args.workload, cfg.num_nodes, rounds=args.write_rounds,
            seed=args.seed,
        )
        if scenario is not None:
            scenario.check_workload(workload)
    round_offset = fork_tok.fork_round if fork_tok is not None else 0
    invariants = None
    if args.check_invariants or args.scenario:
        from corro_sim.faults import InvariantChecker

        invariants = InvariantChecker(cfg, round_offset=round_offset)
    scorecard = None
    if getattr(args, "scorecard", False) or (
        scenario is not None and cfg.node_faults.enabled
    ):
        # node-fault scenarios are graded by default — the scorecard is
        # the evidence their catalog entry exists to produce
        from corro_sim.faults import ResilienceScorecard

        scorecard = ResilienceScorecard(
            cfg, scenario=scenario, workload=workload,
            round_offset=round_offset,
        )
    flight = None
    if args.flight_out:
        from corro_sim.obs.flight import FlightRecorder

        # journaled chunk-by-chunk: a killed run still leaves the curve
        flight = FlightRecorder(sink_path=args.flight_out)
        if not flight.sink_active:
            print(
                f"warning: cannot write flight timeline to "
                f"{args.flight_out!r} — continuing without it",
                file=sys.stderr,
            )
    res = run_sim(
        cfg,
        init_state(cfg, seed=args.seed),
        schedule,
        max_rounds=args.max_rounds,
        chunk=args.chunk,
        seed=args.seed,
        mesh=mesh,
        flight=flight,
        profile_dir=args.profile_dir,
        invariants=invariants,
        scorecard=scorecard,
        workload=workload,
        resume=(
            fork_tok.refit(cfg, args.seed, args.chunk)
            if fork_tok is not None else None
        ),
        # None defers to the CORRO_SIM_TRANSFER_GUARD env var
        transfer_guard=True if args.transfer_guard else None,
        min_rounds=(
            max(
                scenario.heal_round or 0, args.write_rounds,
                workload.rounds if workload is not None else 0,
            )
            if scenario is not None else None
        ),
    )
    diag = res.flight.diagnostics()
    report = {
        "nodes": cfg.num_nodes,
        "converged_round": res.converged_round,
        "rounds_run": res.rounds,
        "writes": int(res.metrics["writes"].sum()),
        "changes_applied": int(res.metrics["fresh"].sum())
        + int(res.metrics["sync_versions"].sum()),
        "dropped_window": int(res.metrics["dropped_window"].sum()),
        "wall_per_round_ms": round(res.wall_per_round_ms, 3),
        "compile_seconds": round(res.compile_seconds, 2),
        # compile-cost provenance (ISSUE 10): persistent-cache hits vs
        # cold compiles, with cold wall separated from sim wall
        "compile_cache": res.compile_cache,
        "sim_seconds_per_round": cfg.round_ms / 1000.0,
        "final_gap": float(np.asarray(res.metrics["gap"])[-1]),
        # curve-shaped convergence diagnostics off the flight record
        "gap_half_life_rounds": diag["gap_half_life_rounds"],
        "epidemic_window_rounds": diag["epidemic_window_rounds"],
        # chunk-pipeline stats (overlap ratio, speculation, fetch-wait
        # wall; doc/performance.md) — present in both modes so a
        # pipelined-vs-sequential pair is directly comparable
        "pipeline": res.pipeline,
    }
    if res.sharding is not None:
        # mesh placement provenance + the per-component state_bytes
        # placement breakdown (ISSUE 8: the multichip smoke's artifact)
        from corro_sim.engine.sharding import sharding_report

        report["sharding"] = sharding_report(cfg, res.sharding)
    if args.flight_out:
        # a sink that died mid-run (ENOSPC, deleted dir) must not be
        # reported as a written artifact
        wrote = res.flight.sink_active
        res.flight.close()
        report["flight"] = args.flight_out if wrote else None
    if res.probe is not None:
        # probe artifacts land next to the flight record: NDJSON journal
        # + Perfetto-loadable Chrome trace-event JSON. An unwritable
        # path must not eat the completed run's report (same manners as
        # the flight sink above).
        prefix = args.probe_out or (
            args.flight_out + ".probes" if args.flight_out else "probes"
        )
        try:
            res.probe.dump_ndjson(prefix + ".ndjson")
            res.probe.dump_chrome_trace(prefix + ".trace.json")
            report["probe_artifacts"] = [
                prefix + ".ndjson", prefix + ".trace.json",
            ]
        except OSError as e:
            print(
                f"warning: cannot write probe artifacts to {prefix!r}* "
                f"({e}) — continuing without them",
                file=sys.stderr,
            )
            report["probe_artifacts"] = None
        summaries = [
            res.probe.summary(k) for k in range(res.probe.num_probes)
        ]
        report["probe_delivery_p99_rounds"] = res.probe.delivery_p99()
        report["probe_coverage"] = [s["coverage"] for s in summaries]
    if args.profile_dir:
        report["profile_dir"] = args.profile_dir
    if fork_tok is not None:
        report["fork"] = args.fork
        report["fork_round"] = fork_tok.fork_round
    if scenario is not None:
        report["scenario"] = scenario.spec
        report["heal_round"] = scenario.heal_round
        if (
            scenario.heal_round is not None
            and res.converged_round is not None
        ):
            # the soak headline: rounds from heal to re-convergence
            report["recovery_rounds"] = (
                res.converged_round - scenario.heal_round
            )
    if workload is not None:
        report["workload"] = workload.spec
    if res.resilience is not None:
        report["resilience"] = res.resilience
    if cfg.faults.enabled:
        fault_keys = [
            k for k in res.metrics
            if k.startswith("fault_") and k != "fault_burst_nodes"
        ]  # burst_nodes is a gauge — summing it would lie
        report["fault_totals"] = {
            k: int(res.metrics[k].sum()) for k in sorted(fault_keys)
        }
    if invariants is not None:
        report["invariants"] = invariants.report()
    if res.poisoned:
        # ring-wrap tripwire (engine/step.py): state may be silently wrong —
        # distinct from an ordinary round-budget miss (exit 3)
        report["poisoned"] = True
    print(json.dumps(report, indent=2))
    if res.poisoned:
        return 4
    if invariants is not None and not invariants.ok:
        return 5
    return 0 if res.converged_round is not None else 3


def _cmd_soak(args: argparse.Namespace) -> int:
    """`corro-sim soak` — sweep chaos scenarios under invariant checking.

    Each scenario runs to (re-)convergence with every invariant checker
    armed; the report carries per-scenario recovery time (rounds from the
    scheduled heal to re-convergence), injected-fault totals and the
    invariant verdicts. Exit codes: 0 all green; 5 an invariant broke;
    3 a scenario failed to re-converge within the round budget.

    Since ISSUE 12 the scenarios race as lanes of ONE vmapped dispatch
    (corro_sim/sweep/ — bit-identical per-scenario numbers, one compile
    instead of one per scenario; doc/sweeping.md). ``--serial`` keeps
    the sequential loop below; ``--resume`` and an explicit
    ``--checkpoint`` imply it (resume tokens are a sequential-loop
    concept).

    Multi-hour soaks survive device loss (ISSUE 10) in SERIAL mode:
    with an artifact prefix (``--out``) or an explicit ``--checkpoint``
    / ``--checkpoint-every`` (either implies ``--serial``), a resumable
    checkpoint is written every ``--checkpoint-every`` chunks and a run
    that dies leaves ``<prefix>.partial.json`` (last completed chunk +
    the resume token) instead of rc=1 with no state. The default swept
    path finishes in one dispatch and writes no token. ``soak --resume
    <ckpt>`` reconstructs the sweep from the token — same config, seed
    and chunking — and continues the killed scenario BIT-IDENTICALLY
    (state, metrics and flight timeline match the uninterrupted run;
    tests/test_soak_resume.py), then finishes the remaining scenarios.

    ``--workload SPEC`` couples a traffic schedule into EVERY scenario
    run (load + faults in one spec — the SWARM latency-under-load
    posture); coupling is validated up front (the fault window and the
    write range must overlap) and checkpoints are disabled for coupled
    runs (the workload cursor is not checkpointed). ``--scorecard
    [PATH]`` arms the resilience scorecard on every scenario, writes the
    per-scenario blocks as a JSON artifact, and gates them against the
    committed threshold golden
    (``corro_sim/analysis/golden/resilience_thresholds.json``) —
    breaches exit 6.
    """
    import dataclasses
    import os

    import numpy as np

    # before the engine imports — they jit at import time (see _cmd_run)
    from corro_sim.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    from corro_sim.engine import init_state, run_sim
    from corro_sim.faults import InvariantChecker, make_scenario
    from corro_sim.io.checkpoint import (
        _cfg_json,
        _simconfig_from_dict,
        load_sim_checkpoint,
    )
    from corro_sim.io.config_file import load_config
    from corro_sim.obs.flight import FlightRecorder

    resume_ck = None
    runs: list = []
    if args.resume and getattr(args, "workload", None):
        print(
            "--resume does not compose with --workload (coupled runs "
            "are not checkpointed; re-run the sweep)",
            file=sys.stderr,
        )
        return 2
    if args.resume:
        resume_ck = load_sim_checkpoint(args.resume)
        soak_meta = resume_ck.meta.get("soak") or {}
        if not soak_meta:
            print(
                f"{args.resume!r} is a sim checkpoint but carries no "
                "soak sweep cursor — resume it via run_sim(resume=...)",
                file=sys.stderr,
            )
            return 2
        # the token is self-contained: CLI shape flags are ignored and
        # the killed sweep's own args/config continue (anything else
        # would break the bit-identity contract)
        base = _simconfig_from_dict(soak_meta["base_cfg"]).validate()
        sweep = dict(soak_meta["args"])
        specs = list(soak_meta["specs"])
        start_idx = int(soak_meta["index"])
        runs = list(soak_meta.get("completed", []))
        print(
            f"# resuming soak from {args.resume} — scenario "
            f"{start_idx + 1}/{len(specs)} at round {resume_ck.rounds}",
            file=sys.stderr, flush=True,
        )
    else:
        base = load_config(args.config)
        overrides = {
            field: getattr(args, flag)
            for flag, field in _FLAG_TO_FIELD.items()
            if getattr(args, flag) is not None
        }
        base = dataclasses.replace(base, **overrides).validate()
        from corro_sim.faults.scenarios import SOAK_DEFAULT

        # the default sweep covers the RECOVERABLE catalog — scenarios
        # whose faults persist forever by design (blackhole_one_way,
        # ring/star topology studies) can never re-converge and are
        # opt-in by name
        specs = args.scenario or list(SOAK_DEFAULT)
        start_idx = 0
        sweep = {
            "rounds": args.rounds,
            "write_rounds": args.write_rounds,
            "max_rounds": args.max_rounds,
            "chunk": args.chunk,
            "seed": args.seed,
            "out": args.out,
            "checkpoint": args.checkpoint,
            "checkpoint_every": args.checkpoint_every,
        }
    out = sweep.get("out")
    ckpt_path = sweep.get("checkpoint") or (
        f"{out}.ckpt.npz" if out else None
    )
    # None = the flag was not given (argparse default): serial mode
    # still checkpoints every 4 chunks when a path resolves
    _ck = sweep.get("checkpoint_every")
    ckpt_every = 4 if _ck is None else int(_ck)

    # ------------------------------------------------- sweep-engine path
    # The sequential scenario loop below is the ESCAPE HATCH (ISSUE 12):
    # by default the whole sweep races as lanes of ONE vmapped dispatch
    # (corro_sim/sweep/), with identical per-scenario report fields and
    # exit codes. Serial mode remains for --resume (checkpoint tokens
    # are a serial-loop concept), --serial, or an EXPLICIT checkpoint
    # request (--checkpoint or a hand-set --checkpoint-every) — a user
    # who asked for resumability must get the loop that provides it,
    # not a silent fast path that drops it.
    if not (
        args.resume or getattr(args, "serial", False)
        or sweep.get("checkpoint")
        # an explicit NONZERO cadence asks for checkpoints; an explicit
        # 0 asks for none — which is what the swept path provides
        or sweep.get("checkpoint_every")
    ):
        if out:
            print(
                "# swept soak: scenarios race as one vmapped dispatch; "
                "no resume checkpoint and no per-scenario flight "
                "journals are written (pass --serial for the "
                "checkpointed, journaling loop)",
                file=sys.stderr,
            )
        return _soak_swept(
            base, specs, sweep, getattr(args, "workload", None),
            getattr(args, "scorecard", None),
        )

    workload = None
    if getattr(args, "workload", None):
        from corro_sim.workload import make_workload

        workload = make_workload(
            args.workload, base.num_nodes,
            rounds=sweep["write_rounds"], seed=sweep["seed"],
        )
        # validate EVERY scenario's coupling up front (cheap host-side
        # compiles) — a bad spec at index 3 must fail in seconds, not
        # after minutes of earlier scenarios whose results would then
        # be discarded without a report
        for spec in specs[start_idx:]:
            try:
                make_scenario(
                    spec, base.num_nodes, rounds=sweep["rounds"],
                    write_rounds=sweep["write_rounds"],
                    seed=sweep["seed"],
                ).check_workload(workload)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
        if ckpt_path:
            print(
                "# --workload coupled: checkpointing disabled (the "
                "workload cursor is not checkpointed)",
                file=sys.stderr,
            )
            ckpt_path = None
    scorecard_path = getattr(args, "scorecard", None)

    any_violation = any(
        not r.get("invariants", {}).get("ok", True) for r in runs
    )
    any_unconverged = any(r.get("converged_round") is None for r in runs)
    # a resumed sweep must keep gating on breaches its pre-kill
    # scenarios already recorded — the exit-6 tripwire survives resume
    breaches: list = [
        b for r in runs for b in r.get("threshold_breaches", [])
    ]
    from corro_sim.faults import check_thresholds, load_thresholds

    thresholds = load_thresholds()  # raises on a corrupt golden
    if thresholds is None and scorecard_path:
        print(
            "warning: no resilience threshold golden committed — the "
            "scorecard artifact is written but nothing gates it "
            "(analysis/golden/resilience_thresholds.json)",
            file=sys.stderr,
        )
    for i in range(start_idx, len(specs)):
        spec = specs[i]
        sc = make_scenario(
            spec, base.num_nodes, rounds=sweep["rounds"],
            write_rounds=sweep["write_rounds"], seed=sweep["seed"],
        )
        cfg = sc.apply(base)
        inv = InvariantChecker(cfg)
        card = None
        if scorecard_path or cfg.node_faults.enabled:
            # node-fault scenarios are graded by default; --scorecard
            # grades the whole sweep and writes the artifact
            from corro_sim.faults import ResilienceScorecard

            card = ResilienceScorecard(
                cfg, scenario=sc, workload=workload
            )
        flight = None
        if out:
            # filename from the FULL spec (sanitized), indexed — two
            # parameterizations of one scenario must not share a journal
            safe = "".join(
                ch if ch.isalnum() or ch in "._-" else "-"
                for ch in sc.spec
            )
            flight = FlightRecorder(
                sink_path=f"{out}.{i:02d}.{safe}.ndjson"
            )
        ck_meta = {"soak": {
            "specs": specs,
            "index": i,
            "completed": list(runs),
            "base_cfg": _cfg_json(base),
            "args": sweep,
        }}
        try:
            res = run_sim(
                cfg, init_state(cfg, seed=sweep["seed"]), sc.schedule(),
                max_rounds=sweep["max_rounds"], chunk=sweep["chunk"],
                seed=sweep["seed"],
                min_rounds=max(
                    sc.heal_round or 0, sweep["write_rounds"],
                    workload.rounds if workload is not None else 0,
                ),
                flight=flight, invariants=inv, scorecard=card,
                workload=workload,
                resume=resume_ck if i == start_idx else None,
                checkpoint_path=ckpt_path,
                checkpoint_every=ckpt_every if ckpt_path else 0,
                checkpoint_meta=ck_meta,
            )
        except Exception as e:  # device loss / kill-adjacent failures
            # the BENCH_r05 fix: a dying soak leaves a partial artifact
            # naming how far it got and the token that resumes it,
            # instead of rc=1 with no state
            # only advertise a resume token that actually exists on
            # disk — a death before the first checkpoint write must not
            # hand the operator a recovery command that FileNotFounds.
            # The token may predate this scenario (died before ITS
            # first checkpoint): resuming is still correct — it replays
            # the tokened scenario's tail and re-derives everything
            # after — but the artifact says which scenario restarts.
            token = None
            token_index = None
            if ckpt_path and os.path.exists(ckpt_path):
                token = ckpt_path
                try:
                    token_index = load_sim_checkpoint(ckpt_path).meta[
                        "soak"]["index"]
                except Exception:
                    token_index = None
            partial = {
                "status": "died",
                "error": f"{type(e).__name__}: {e}",
                "scenario": sc.spec,
                "scenario_index": i,
                "scenarios_total": len(specs),
                "completed": runs,
                "resume_token": token,
                "resume_resumes_scenario_index": token_index,
                "resume_cmd": (
                    f"corro-sim soak --resume {token}"
                    if token else None
                ),
                "flight": (
                    flight.sink_path
                    if flight is not None and flight.sink_active else None
                ),
            }
            from corro_sim.utils.runtime import atomic_json_dump

            path = f"{out or 'soak'}.partial.json"
            if atomic_json_dump(path, partial, indent=2):
                partial["partial_artifact"] = path
            if flight is not None:
                flight.close()
            print(json.dumps(partial, indent=2))
            return 1
        resume_ck = None
        heal = sc.heal_round
        recovery = (
            res.converged_round - heal
            if heal is not None and res.converged_round is not None
            else None
        )
        fault_totals = {
            k: int(np.asarray(res.metrics[k]).sum())
            for k in sorted(res.metrics)
            if k.startswith("fault_") and k != "fault_burst_nodes"
        }
        run = {
            "scenario": sc.spec,
            "converged_round": res.converged_round,
            "rounds_run": res.rounds,
            "heal_round": heal,
            "recovery_rounds": recovery,
            "poisoned": res.poisoned,
            "fault_totals": fault_totals,
            "invariants": inv.report(),
            "compile_cache": res.compile_cache,
        }
        if workload is not None:
            run["workload"] = workload.spec
        if res.resilience is not None:
            run["resilience"] = res.resilience
            if thresholds is not None:
                run_breaches = check_thresholds(res.resilience, thresholds)
                run["threshold_breaches"] = run_breaches
                breaches.extend(run_breaches)
        if flight is not None:
            run["flight"] = (
                flight.sink_path if flight.sink_active else None
            )
            flight.close()
        runs.append(run)
        any_violation |= not inv.ok
        any_unconverged |= res.converged_round is None
        print(
            f"# {sc.spec}: converged={res.converged_round} "
            f"recovery={recovery} invariants="
            f"{'ok' if inv.ok else 'VIOLATED'}",
            file=sys.stderr, flush=True,
        )
    report = {
        "nodes": base.num_nodes,
        "rounds": sweep["rounds"],
        "seed": sweep["seed"],
        "scenarios": runs,
        "ok": not (any_violation or any_unconverged or breaches),
    }
    if workload is not None:
        report["workload"] = workload.spec
    if resume_ck is not None or args.resume:
        report["resumed_from"] = args.resume
    if ckpt_path:
        report["checkpoint"] = ckpt_path
    if breaches:
        report["threshold_breaches"] = breaches
    if scorecard_path:
        # the scorecard artifact: per-scenario resilience blocks + the
        # threshold verdict, one JSON the CI leg uploads and asserts on
        scorecard_doc = {
            "nodes": base.num_nodes,
            "seed": sweep["seed"],
            "workload": workload.spec if workload is not None else None,
            "scenarios": [
                {
                    "scenario": r["scenario"],
                    "resilience": r.get("resilience"),
                    "threshold_breaches": r.get("threshold_breaches", []),
                }
                for r in runs
            ],
            "thresholds_ok": not breaches,
            "breaches": breaches,
        }
        with open(scorecard_path, "w", encoding="utf-8") as f:
            json.dump(scorecard_doc, f, indent=2)
            f.write("\n")
        report["scorecard"] = scorecard_path
    if out:
        with open(f"{out}.report.json", "w") as f:
            json.dump(report, f, indent=2)
        report["report"] = f"{out}.report.json"
    print(json.dumps(report, indent=2))
    if any_violation:
        return 5
    if any_unconverged:
        return 3
    return 6 if breaches else 0


def _soak_swept(base, specs, sweep, workload_spec, scorecard_path) -> int:
    """The soak sweep as lanes of ONE vmapped dispatch (ISSUE 12): the
    per-scenario report fields, threshold gating and exit codes of the
    sequential loop, produced from the fleet-of-clusters engine. Every
    lane is bit-identical to the serial run it replaces
    (tests/test_sweep.py), so the report numbers are THE soak numbers."""
    import numpy as np

    from corro_sim.faults import check_thresholds, load_thresholds
    from corro_sim.sweep import build_plan, run_sweep

    try:
        plan = build_plan(
            base, specs, [sweep["seed"]], rounds=sweep["rounds"],
            write_rounds=sweep["write_rounds"],
            workload_spec=workload_spec,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    thresholds = load_thresholds()  # raises on a corrupt golden
    if thresholds is None and scorecard_path:
        print(
            "warning: no resilience threshold golden committed — the "
            "scorecard artifact is written but nothing gates it "
            "(analysis/golden/resilience_thresholds.json)",
            file=sys.stderr,
        )
    res = run_sweep(
        plan, max_rounds=sweep["max_rounds"], chunk=sweep["chunk"],
        on_chunk=lambda p: print(
            f"# sweep chunk {p['chunk']}: rounds {p['rounds_done']}, "
            f"{p['lanes_active']}/{plan.num_lanes} lanes racing",
            file=sys.stderr, flush=True,
        ),
    )
    runs: list = []
    breaches: list = []
    any_violation = False
    any_unconverged = False
    for lr, lane in zip(res.lanes, plan.lanes):
        # fault totals restricted to the families the lane's SERIAL
        # config emits — the union program accounts link flow for every
        # lane, but the report must match the serial soak's
        fault_totals = (
            {
                k: int(np.asarray(lr.metrics[k]).sum())
                for k in sorted(lr.metrics)
                if k.startswith("fault_") and k != "fault_burst_nodes"
            }
            if lane.cfg.faults.enabled else {}
        )
        inv = lr.invariants or {"ok": True, "violations": []}
        run = {
            "scenario": lr.spec,
            "converged_round": lr.converged_round,
            "rounds_run": lr.rounds,
            "heal_round": lr.heal_round,
            "recovery_rounds": lr.recovery_rounds,
            "poisoned": lr.poisoned,
            "fault_totals": fault_totals,
            "invariants": inv,
            "repro_cmd": lr.repro_cmd,
            # the serial loop's per-run fields, kept present so report
            # consumers never key-error on the (default) swept path:
            # compile cost lives on the ONE shared program (the
            # report-level "sweep" block) and flight journals are a
            # serial-mode feature
            "compile_cache": None,
            "flight": None,
        }
        if workload_spec is not None and lane.workload is not None:
            run["workload"] = lane.workload.spec
        if scorecard_path or lane.cfg.node_faults.enabled:
            run["resilience"] = lr.resilience
            if thresholds is not None and lr.resilience is not None:
                run_breaches = check_thresholds(lr.resilience, thresholds)
                run["threshold_breaches"] = run_breaches
                breaches.extend(run_breaches)
        runs.append(run)
        any_violation |= not inv.get("ok", True)
        any_unconverged |= lr.converged_round is None
        print(
            f"# {lr.spec}: converged={lr.converged_round} "
            f"recovery={lr.recovery_rounds} invariants="
            f"{'ok' if inv.get('ok', True) else 'VIOLATED'}",
            file=sys.stderr, flush=True,
        )
    report = {
        "nodes": base.num_nodes,
        "rounds": sweep["rounds"],
        "seed": sweep["seed"],
        "scenarios": runs,
        "ok": not (any_violation or any_unconverged or breaches),
        "sweep": {
            "lanes": plan.num_lanes,
            "dispatches": res.dispatches,
            "wall_seconds": round(res.wall_seconds, 3),
            "compile_seconds": round(res.compile_seconds, 3),
            "clusters_per_second_per_device": (
                round(res.clusters_per_second_per_device, 3)
                if res.clusters_per_second_per_device is not None
                else None
            ),
            "compile_cache": res.compile_cache,
        },
    }
    if workload_spec is not None:
        report["workload"] = workload_spec
    if breaches:
        report["threshold_breaches"] = breaches
    if scorecard_path:
        # keep this artifact's shape in lockstep with the serial loop's
        # scorecard_doc in _cmd_soak — CI asserts on either path
        scorecard_doc = {
            "nodes": base.num_nodes,
            "seed": sweep["seed"],
            "workload": workload_spec,
            "scenarios": [
                {
                    "scenario": r["scenario"],
                    "resilience": r.get("resilience"),
                    "threshold_breaches": r.get("threshold_breaches", []),
                }
                for r in runs
            ],
            "thresholds_ok": not breaches,
            "breaches": breaches,
        }
        with open(scorecard_path, "w", encoding="utf-8") as f:
            json.dump(scorecard_doc, f, indent=2)
            f.write("\n")
        report["scorecard"] = scorecard_path
    out = sweep.get("out")
    if out:
        with open(f"{out}.report.json", "w") as f:
            json.dump(report, f, indent=2)
        report["report"] = f"{out}.report.json"
    # swept-soak fleet numbers ride the perf ledger like plain sweeps
    # (normalize_sweep_report flattens the nested "sweep" block;
    # best-effort — a ledger write must never fail the soak)
    from corro_sim.obs.ledger import auto_append, normalize_sweep_report

    auto_append(normalize_sweep_report(report, source="soak"))
    print(json.dumps(report, indent=2))
    if any_violation:
        return 5
    if any_unconverged:
        return 3
    return 6 if breaches else 0


def _cmd_sweep(args: argparse.Namespace) -> int:
    """`corro-sim sweep` — race a (scenario × seed × knob) chaos matrix
    as lanes of ONE vmapped dispatch (corro_sim/sweep/, ISSUE 12).

    Grid axes are positional ``KEY=VALUES`` tokens::

        corro-sim sweep scenario=crash_amnesia,lossy seed=0..31 \\
            knob.loss=0.05,0.2 --nodes 64

    The report carries every lane's convergence/recovery numbers plus
    the **resilience frontier**: per-cell worst/p95 recovery across
    seeds with the arg-max worst seed named and the one serial
    ``corro-sim run`` command that reproduces it. Threshold gating is
    quantile-over-seeds against the committed golden — breaches exit 6
    (the soak tripwire, unchanged through the sweep path); exit 5 on
    an invariant violation, 3 when a lane fails to settle.
    """
    import dataclasses

    from corro_sim.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    from corro_sim.faults import load_thresholds
    from corro_sim.io.config_file import load_config
    from corro_sim.sweep import (
        build_frontier,
        build_plan,
        check_frontier,
        parse_grid,
        run_sweep,
    )

    base = load_config(args.config)
    overrides = {
        field: getattr(args, flag)
        for flag, field in _FLAG_TO_FIELD.items()
        if getattr(args, flag, None) is not None
    }
    base = dataclasses.replace(base, **overrides).validate()
    try:
        grid = parse_grid(args.grid)
        if not grid["scenario"]:
            raise ValueError("the grid needs a scenario=... axis")
        plan = build_plan(
            base, grid["scenario"], grid["seed"], grid["knobs"],
            rounds=args.rounds, write_rounds=args.write_rounds,
            workload_spec=args.workload,
        )
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    mesh = None
    if args.mesh:
        from corro_sim.engine.sharding import make_sweep_mesh

        mesh = make_sweep_mesh(plan.num_lanes)
        if args.compact or args.pipeline:
            from corro_sim.engine.sharding import check_compact_mesh

            try:
                check_compact_mesh(mesh)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
        print(
            f"# mesh: {plan.num_lanes} lanes over "
            f"{dict(mesh.shape)}", file=sys.stderr,
        )
    print(
        f"# sweeping {plan.num_lanes} lanes "
        f"({len(grid['scenario'])} scenarios x {len(grid['seed'])} seeds"
        + (f" x {len(grid['knobs'])} knob combos" if grid["knobs"] != [{}]
           else "")
        + ") in one dispatch",
        file=sys.stderr, flush=True,
    )
    def _on_chunk(p):
        if args.progress:
            # per-chunk lane-state line (fleet observatory): one char
            # per lane — A racing, C bit-frozen converged, P poisoned
            # (+ Q queued and width/pending under the fleet scheduler)
            fleet = (
                f" | w{p['width']} q{p['pending']}"
                if "width" in p else ""
            )
            print(
                f"# chunk {p['chunk']}: rounds {p['rounds_done']} | "
                f"{p['lanes_active']}A {p['lanes_converged']}C "
                f"{p['lanes_poisoned']}P | wasted "
                f"{p['wasted_lane_rounds_total']} frozen lane-rounds"
                f"{fleet} | "
                f"{p['lane_states']} ({p['chunk_wall_s']}s)",
                file=sys.stderr, flush=True,
            )
        else:
            print(
                f"# chunk {p['chunk']}: rounds {p['rounds_done']}, "
                f"{p['lanes_active']} lanes racing, "
                f"{p['lanes_settled']} settled "
                f"({p['chunk_wall_s']}s)",
                file=sys.stderr, flush=True,
            )

    with _profiled(args.profile_dir):
        res = run_sweep(
            plan, max_rounds=args.max_rounds, chunk=args.chunk,
            mesh=mesh, on_chunk=_on_chunk,
            compact=args.compact, width=args.width,
            pipeline=bool(args.pipeline),
        )
    frontier = build_frontier(res.lanes)
    thresholds = load_thresholds()
    breaches = (
        check_frontier(frontier, thresholds)
        if thresholds is not None else []
    )
    frontier["thresholds_ok"] = not breaches
    frontier["breaches"] = breaches
    from corro_sim.faults.invariants import merge_reports

    inv_summary = merge_reports([lr.invariants for lr in res.lanes])
    any_violation = not inv_summary["ok"]
    any_unsettled = any(
        lr.converged_round is None or lr.poisoned for lr in res.lanes
    )
    report = {
        "nodes": base.num_nodes,
        "lanes": plan.num_lanes,
        "rounds": args.rounds,
        "dispatches": res.dispatches,
        "wall_seconds": round(res.wall_seconds, 3),
        "compile_seconds": round(res.compile_seconds, 3),
        "clusters_per_second_per_device": (
            round(res.clusters_per_second_per_device, 3)
            if res.clusters_per_second_per_device is not None else None
        ),
        "devices": res.devices,
        "compile_cache": res.compile_cache,
        "lanes_detail": [
            {
                "scenario": lr.spec,
                "seed": lr.seed,
                "cell": lr.cell,
                "converged_round": lr.converged_round,
                "rounds_run": lr.rounds,
                "recovery_rounds": lr.recovery_rounds,
                "poisoned": lr.poisoned,
                "rows_lost": (lr.resilience or {}).get("rows_lost"),
                "invariants_ok": (lr.invariants or {}).get("ok", True),
                "repro_cmd": lr.repro_cmd,
            }
            for lr in res.lanes
        ],
        "frontier": frontier,
        "invariants": inv_summary,
        "ok": not (any_violation or any_unsettled or breaches),
    }
    if args.profile_dir:
        report["profile_dir"] = args.profile_dir
    # fleet observatory artifacts (corro_sim/obs/lanes.py): occupancy
    # stats always ride the report; per-lane flight timelines and the
    # grid heatmap are demuxed from the dispatch's own outputs — no
    # lane is ever re-run for its telemetry
    from corro_sim.obs.lanes import (
        demux_flights,
        fleet_occupancy,
        grid_heatmaps,
        render_heatmap,
        write_lane_flights,
    )

    report["occupancy"] = fleet_occupancy(res)
    if res.compaction is not None:
        # fleet-scheduler provenance: bucket widths visited, refill/
        # shrink counts, and the slot-reuse ledger (doc/sweeping.md)
        report["compaction"] = res.compaction
    if res.pipeline is not None:
        report["pipeline"] = res.pipeline
    if args.flight_dir:
        paths = write_lane_flights(
            demux_flights(plan, res, breaches=breaches),
            args.flight_dir,
        )
        report["lane_flights"] = {
            "dir": args.flight_dir, "count": len(paths),
        }
    if args.heatmap:
        heatmaps = grid_heatmaps(res.lanes)
        with open(args.heatmap, "w", encoding="utf-8") as f:
            json.dump(heatmaps, f, indent=2)
            f.write("\n")
        report["heatmap_artifact"] = args.heatmap
        metric = (
            "recovery_rounds"
            if any(
                v is not None
                for row in heatmaps["maps"]["recovery_rounds"]
                for v in row
            )
            else "rounds_to_convergence"
        )
        print(render_heatmap(heatmaps, metric), file=sys.stderr, end="")
    if args.workload:
        report["workload"] = args.workload
    if args.frontier:
        with open(args.frontier, "w", encoding="utf-8") as f:
            json.dump(frontier, f, indent=2)
            f.write("\n")
        report["frontier_artifact"] = args.frontier
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    # every sweep number rides the perf ledger (corro_sim/obs/ledger.py;
    # best-effort — a ledger write must never fail the sweep)
    from corro_sim.obs.ledger import auto_append, normalize_sweep_report

    auto_append(normalize_sweep_report(
        report, profile_dir=args.profile_dir
    ))
    print(json.dumps(report, indent=2))
    if any_violation:
        return 5
    if any_unsettled:
        return 3
    return 6 if breaches else 0


def _cmd_twin(args: argparse.Namespace) -> int:
    """`corro-sim twin` — shadow a changeset feed and forecast what-if
    chaos (corro_sim/engine/twin.py, doc/twin.md).

    Streams the ND-JSON feed chunk by chunk against a frozen scan-window
    universe, publishes per-chunk convergence + FIFO delivery headlines
    scored against the feed's own `ts` stamps, writes a resumable cursor
    checkpoint at chunk boundaries (`--resume` continues a SIGKILL'd
    twin bit-identically), and — with `--forecast` — forks the live twin
    state and races the scenario × seed grid as warm-start lanes of ONE
    vmapped dispatch, graded against the `twin_forecast` threshold
    section (breach = exit 6, the soak tripwire semantics).

    `--tail` is the LIVE operator loop (doc/twin.md §9): FEED becomes a
    growing source — a polled file tail (rotation re-binds, truncation
    refuses) or an http(s):// `/v1/changes` watch (reconnect with a
    backoff budget) — shadowed chunk by chunk as lines arrive,
    bit-identical to file-mode replay of the same lines. Source death
    past the backoff/idle budget is the tail's normal end: final
    partial chunk, drain, report, exit 5 with a resumable cursor.
    `--forecast-every N` re-forks the live state every N chunks and
    races the `--forecast` grid continuously; `--forecast-load R`
    additionally replays up to R rounds of the trailing feed window
    into every lane as coupled workload.

    Exit codes: 0 ok; 2 hostile feed refused (strict mode) / bad args;
    3 the shadow failed to drain to convergence; 4 poisoned (log ring
    wrapped); 5 live source died (tail mode — the cursor checkpoint
    resumes); 6 forecast threshold breach. Precedence: 4 > 5 > 3 > 6.
    """
    import dataclasses

    from corro_sim.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    from corro_sim.config import TwinConfig
    from corro_sim.engine.twin import (
        fork_twin,
        load_feed_lines,
        probe_feed_heads,
        run_forecast,
        run_twin,
        twin_universe,
    )
    from corro_sim.faults import load_thresholds
    from corro_sim.io.checkpoint import load_sim_checkpoint
    from corro_sim.sweep import parse_grid

    forecast_grid = None
    if args.forecast:
        try:
            forecast_grid = parse_grid(args.forecast)
            if not forecast_grid["scenario"]:
                raise ValueError(
                    "--forecast needs a scenario=... axis"
                )
            if forecast_grid["knobs"] != [{}]:
                raise ValueError(
                    "--forecast takes scenario/seed axes (knob axes "
                    "ride the scenario specs or `run --fork --knob`)"
                )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    resume = None
    universe = None
    if args.resume:
        try:
            resume = load_sim_checkpoint(args.resume)
        except (OSError, ValueError) as e:
            print(f"error: --resume {args.resume!r}: {e}",
                  file=sys.stderr)
            return 2

    source = None
    if args.tail:
        from corro_sim.io.feedsource import (
            FeedSourceError,
            FileTailSource,
            HTTPWatchSource,
        )

        scan = (
            resume.cfg.twin.scan_lines if resume is not None
            else args.scan_lines
        )
        if scan <= 0:
            print(
                "error: --tail needs --scan-lines N: a closed world "
                "cannot be frozen from 'the whole feed' while the feed "
                "is still growing",
                file=sys.stderr,
            )
            return 2
        kw = dict(
            poll_ms=args.tail_poll_ms,
            reconnect_max_s=args.reconnect_max_s,
            idle_timeout_s=args.idle_timeout_s,
            max_lag_lines=args.max_lag_lines,
            jitter_seed=args.seed,
        )
        if args.feed.startswith(("http://", "https://")):
            source = HTTPWatchSource(args.feed, **kw)
        else:
            source = FileTailSource(args.feed, **kw)
        # block until the scan window (plus, on resume, the already-
        # consumed prefix the cursor's feed_sha guards) is available
        need0 = scan
        if resume is not None:
            need0 = max(need0, int(
                ((resume.meta or {}).get("twin") or {})
                .get("cursor", {}).get("lines_seen", 0)
            ))
        try:
            lines = source.wait_lines(need0)
        except FeedSourceError as e:
            print(f"error: {e}", file=sys.stderr)
            return 5
        if len(lines) < need0:
            print(
                f"error: live source died ({source.death_reason}) "
                f"after {len(lines)}/{need0} lines — before the "
                "universe scan window (or the resume prefix) filled",
                file=sys.stderr,
            )
            return 5
    else:
        try:
            lines = load_feed_lines(args.feed)
        except OSError as e:
            print(f"error: cannot read feed {args.feed!r}: {e}",
                  file=sys.stderr)
            return 2
    if resume is not None:
        # the token is self-contained (the soak --resume posture): the
        # killed twin's own config continues, shape flags are ignored
        cfg = resume.cfg
    else:
        try:
            twin_knobs = TwinConfig(
                enabled=True,
                scan_lines=args.scan_lines,
                chunk_lines=args.chunk_lines,
                skip_bad=args.skip_bad,
                drain_rounds=args.drain_rounds,
                checkpoint_every=args.checkpoint_every,
                tail_poll_ms=args.tail_poll_ms,
                reconnect_max_s=args.reconnect_max_s,
                idle_timeout_s=args.idle_timeout_s,
                max_lag_lines=args.max_lag_lines,
                refresh_threshold=args.refresh_threshold,
                refresh_window_lines=args.refresh_window,
                forecast_every=args.forecast_every,
            )
        except AssertionError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        universe = twin_universe(lines, twin_knobs.scan_lines)
        heads = probe_feed_heads(lines, universe)
        overrides = {}
        if args.log_capacity is not None:
            overrides["log_capacity"] = args.log_capacity
        if args.nodes is not None:
            overrides["num_nodes"] = args.nodes
        try:
            cfg = dataclasses.replace(
                universe.suggest_config(
                    rounds=int(heads.max(initial=0)) + 1, **overrides
                ),
                twin=twin_knobs,
            ).validate()
        except AssertionError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    flight = None
    if args.flight_out:
        from corro_sim.obs.flight import FlightRecorder

        flight = FlightRecorder(sink_path=args.flight_out)
    checkpoint_path = args.checkpoint or (
        f"{args.out}.ckpt.npz" if args.out else None
    )

    on_cycle = None
    cycles: list = []
    if args.forecast_every > 0:
        if forecast_grid is None:
            print(
                "error: --forecast-every needs a --forecast grid to "
                "re-race",
                file=sys.stderr,
            )
            return 2
        from corro_sim.engine.twin import save_fork
        from corro_sim.workload.inject import trace_workload

        cycle_thresholds = load_thresholds()
        cycle_base = args.fork_out or (
            f"{args.out}.fork.npz" if args.out else "TWIN.fork.npz"
        )

        def on_cycle(ctx):
            # the cadence re-fork loop: fork the IN-FLIGHT state, race
            # the grid, append a trend point. One failed cycle logs and
            # degrades — it never kills the tail it grades.
            n = len(cycles) + 1
            path = f"{cycle_base}.cycle{n}.npz"
            try:
                tok = save_fork(
                    path, cfg=ctx["cfg"], state=ctx["state"],
                    seed=ctx["seed"], rounds=ctx["round"],
                    feed=args.feed,
                    lines_seen=ctx["stream"].lines_seen,
                    chunk=args.chunk,
                )
                wl = None
                if args.forecast_load > 0:
                    wl = trace_workload(ctx["window_chunks"], ctx["cfg"])
                    if wl is not None and wl.rounds > args.forecast_load:
                        k = args.forecast_load
                        wl = dataclasses.replace(
                            wl, rounds=k, writers=wl.writers[-k:],
                            rows=wl.rows[-k:], cols=wl.cols[-k:],
                            vals=wl.vals[-k:], dels=wl.dels[-k:],
                            ncells=wl.ncells[-k:], events=[],
                        )
                fc = run_forecast(
                    tok, forecast_grid["scenario"],
                    forecast_grid["seed"], rounds=args.forecast_rounds,
                    max_rounds=args.max_rounds, chunk=args.chunk,
                    thresholds=cycle_thresholds, coupled_workload=wl,
                )
            except (ValueError, AssertionError, OSError) as e:
                print(
                    f"# forecast cycle {n} @ chunk {ctx['chunk']} "
                    f"failed (degrading, tail continues): {e}",
                    file=sys.stderr, flush=True,
                )
                cycles.append({
                    "cycle": n, "chunk": ctx["chunk"], "error": str(e),
                })
                return None
            cycles.append({
                "cycle": n, "chunk": ctx["chunk"], "fork": path,
                "fork_round": fc["fork_round"], "lanes": fc["lanes"],
                "ok": fc["ok"],
                "breaches": len(fc["frontier"]["breaches"]),
                **(
                    {"coupled_load": fc["coupled_load"]}
                    if "coupled_load" in fc else {}
                ),
            })
            print(
                f"# forecast cycle {n} @ chunk {ctx['chunk']}: "
                f"{fc['lanes']} lanes from round {fc['fork_round']}"
                + (
                    f", coupled {wl.rounds} load rounds"
                    if wl is not None else ""
                )
                + ("" if fc["ok"] else " [NOT OK]"),
                file=sys.stderr, flush=True,
            )
            return {"trend": fc["trend"]}

    try:
        # PR 2 profiler hook, extended to the twin path: the shadow's
        # scan chunks and the forecast dispatch trace into separate
        # subdirs (two phases, two Perfetto-loadable traces)
        with _profiled(
            args.profile_dir
            and os.path.join(args.profile_dir, "shadow")
        ):
            res = run_twin(
                feed=args.feed, cfg=cfg, lines=lines, seed=args.seed,
                checkpoint_path=checkpoint_path, resume=resume,
                flight=flight, universe=universe, source=source,
                on_cycle=on_cycle,
                on_chunk=lambda h: print(
                    f"# twin chunk {h['chunk']}: {h['lines']} lines "
                    f"({h['bad']} bad), {h['rounds']} rounds, "
                    f"gap {h['gap']:.0f}",
                    file=sys.stderr, flush=True,
                ),
            )
    except ValueError as e:
        # the strict hostile-feed refusal: ONE error naming every bad
        # line, before any sim work (io/traces.py validate_feed)
        print(f"error: {e}", file=sys.stderr)
        return 2
    except Exception as e:
        from corro_sim.io.feedsource import FeedSourceError

        if not isinstance(e, FeedSourceError):
            raise
        # feed truncation mid-tail: committed history rewound under the
        # shadow — refuse loudly (exit 5; the last chunk-boundary
        # cursor, if any, is still resumable against an intact feed)
        print(f"error: {e}", file=sys.stderr)
        return 5
    finally:
        if source is not None:
            source.close()
    report = dict(res.report)
    if checkpoint_path:
        report["checkpoint"] = checkpoint_path
    if args.resume:
        report["resumed_from"] = args.resume

    rc = 0
    if res.poisoned:
        rc = 4
    elif source is not None and source.dead:
        # the tail's NORMAL end: every live source eventually dies
        # (idle timeout when the writer finishes, backoff budget when
        # it vanishes) — distinct exit, full report, resumable cursor
        rc = 5
    elif res.converged_round is None:
        rc = 3
    if cycles:
        report["forecast_cycles"] = cycles
    if forecast_grid is not None and not res.poisoned:
        fork_path = args.fork_out or (
            f"{args.out}.fork.npz" if args.out
            else (args.feed + ".fork.npz")
        )
        tok = fork_twin(res, fork_path, chunk=args.chunk)
        thresholds = load_thresholds()  # raises on a corrupt golden
        with _profiled(
            args.profile_dir
            and os.path.join(args.profile_dir, "forecast")
        ):
            fc = run_forecast(
                tok, forecast_grid["scenario"], forecast_grid["seed"],
                rounds=args.forecast_rounds, max_rounds=args.max_rounds,
                chunk=args.chunk, thresholds=thresholds,
                flight_dir=args.flight_dir,
                on_chunk=lambda p: print(
                    f"# forecast chunk {p['chunk']}: rounds "
                    f"{p['rounds_done']}, {p['lanes_active']} lanes "
                    "racing",
                    file=sys.stderr, flush=True,
                ),
            )
        report["fork"] = fork_path
        report["forecast"] = fc
        # the projected-recovery trend next to the shadow headlines:
        # one point per fork (continuous re-forking appends points —
        # the list IS the trend line: cadence cycles first, the final
        # fork last), and the final point annotates the shadow's
        # flight record at the fork round
        report["forecast_trend"] = list(res.trend) + [fc["trend"]]
        for cell in fc["trend"]["cells"]:
            rec = cell["recovery_rounds"] or {}
            res.flight.annotate(
                res.rounds, "forecast_trend",
                cell=cell["cell"], projected=True,
                fork_round=fc["trend"]["fork_round"],
                recovery_worst=rec.get("worst"),
                recovery_p95=rec.get("p95"),
                rows_lost_worst=cell["rows_lost_worst"],
            )
        if args.frontier:
            with open(args.frontier, "w", encoding="utf-8") as f:
                json.dump(fc["frontier"], f, indent=2)
                f.write("\n")
            report["frontier_artifact"] = args.frontier
        if rc == 0 and fc["frontier"]["breaches"]:
            rc = 6
        if rc == 0 and not fc["ok"]:
            rc = 3
    elif args.fork_out and not res.poisoned:
        fork_twin(res, args.fork_out, chunk=args.chunk)
        report["fork"] = args.fork_out
    if args.flight_out:
        # closed AFTER the forecast so the forecast_trend annotations
        # journal into the shadow timeline they grade
        wrote = res.flight.sink_active
        res.flight.close()
        report["flight"] = args.flight_out if wrote else None
    if args.profile_dir:
        report["profile_dir"] = args.profile_dir
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    # the shadow-delivery headline rides the perf ledger (best-effort,
    # corro_sim/obs/ledger.py)
    from corro_sim.obs.ledger import auto_append, normalize_twin_report

    auto_append(normalize_twin_report(
        report, profile_dir=args.profile_dir
    ))
    print(json.dumps(report, indent=2))
    return rc


def _cmd_load(args: argparse.Namespace) -> int:
    """`corro-sim load` — drive a production-shaped traffic workload
    (corro_sim/workload/, doc/workloads.md) through the simulator.

    Paths: `batched` runs the compiled write schedule through
    ``run_sim``'s scan (convergence under load); `live` maps the same
    schedule to SQL against a LiveCluster with concurrent subscriptions
    + query fans (sub-delivery latency under load); `both` (default)
    runs both and merges the reports. Exit 3 when the batched path fails
    to converge inside the round budget."""
    import time as _time

    # before the workload/engine imports — they jit at import time
    # (see _cmd_run)
    from corro_sim.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    from corro_sim.workload import assert_workload_vacuous, make_workload

    wl = make_workload(
        args.spec, args.nodes, rounds=args.rounds, seed=args.seed
    )
    report: dict = {
        "spec": wl.spec,
        "nodes": args.nodes,
        "load_rounds": wl.rounds,
        "schedule": {
            "writes": wl.total_writes,
            "deletes": wl.total_deletes,
            "events": len(wl.events),
            "key_universe": wl.key_universe(),
        },
    }
    rc = 0
    if args.verify_vacuous:
        # the workload-off vacuity claim, verified in-process: the
        # all-idle schedule runs bit-identical to the disabled sampler
        # (the OFF program itself is pinned by `corro-sim audit`)
        t0 = _time.perf_counter()
        assert_workload_vacuous()
        report["vacuous"] = True
        report["vacuity_check_seconds"] = round(
            _time.perf_counter() - t0, 2
        )
    if args.path in ("batched", "both"):
        import dataclasses

        import numpy as np

        from corro_sim.engine import init_state, run_sim
        from corro_sim.io.config_file import load_config

        cfg = load_config(args.config)
        cfg = dataclasses.replace(
            cfg,
            num_nodes=args.nodes,
            num_rows=max(args.rows or 0, wl.key_universe(), 16),
            num_cols=max(args.cols or cfg.num_cols, 1),
            seqs_per_version=max(
                cfg.seqs_per_version, wl.cells_width
            ),
        ).validate()
        res = run_sim(
            cfg,
            init_state(cfg, seed=args.seed),
            max_rounds=args.max_rounds,
            chunk=args.chunk,
            seed=args.seed,
            workload=wl,
        )
        report["batched"] = {
            "converged_round": res.converged_round,
            "rounds_run": res.rounds,
            "writes": int(res.metrics["writes"].sum()),
            "deletes": int(res.metrics["deletes"].sum()),
            "changes_applied": int(res.metrics["fresh"].sum())
            + int(res.metrics["sync_versions"].sum()),
            "final_gap": float(np.asarray(res.metrics["gap"])[-1]),
            "wall_per_round_ms": round(res.wall_per_round_ms, 3),
            "workload_events_annotated": len(
                res.flight.events("workload_event")
            ),
            "poisoned": res.poisoned,
        }
        if args.flight_out:
            res.flight.dump(args.flight_out)
            report["flight"] = args.flight_out
        if res.poisoned:
            rc = 4
        elif res.converged_round is None:
            rc = 3
    if args.path in ("live", "both"):
        from corro_sim.workload.harness import run_live_load

        rep = run_live_load(
            wl,
            subs=args.subs,
            subscribers_per_sub=args.subscribers,
            queries_per_round=args.queries_per_round,
            http=args.http,
            pg=args.pg,
            seed=args.seed,
            settle_rounds=args.settle_rounds,
        )
        report["live"] = rep.as_json()
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2)
            fh.write("\n")
    print(json.dumps(report, indent=2))
    return rc


def _cmd_lint(args: argparse.Namespace) -> int:
    """`corro-sim lint` — the AST trace-safety analyzer
    (corro_sim/analysis/, doc/static_analysis.md). Pure-AST: no jax
    import, runs in seconds on any machine. Exit 1 on any error-severity
    finding (warnings too under --strict)."""
    from corro_sim.analysis.lint import run_lint

    return run_lint(
        args.paths, fmt=args.format, strict=args.strict, out=args.out,
    )


def _cmd_audit(args: argparse.Namespace) -> int:
    """`corro-sim audit` — trace sim_step under the feature-off matrix,
    assert the vacuity invariants + hazard absence, and verify (or
    rewrite with --update-golden) the committed primitive-count
    fingerprint (analysis/golden/jaxpr_fingerprint.json). With
    --contracts, also run the program-contract auditor (dataflow
    vacuity proofs, collective budgets, determinism, static peak-HBM —
    analysis/contracts.py) against its committed manifest. With
    --keys, also run the key-lineage auditor (K1 single-consumption /
    K2 stream disjointness / K3 lane-fork independence —
    analysis/keys.py) against analysis/golden/key_lineage.json."""
    if args.contracts or args.keys:
        # the collective-budget contracts and the sharded key-lineage
        # program lower/trace against the 8-device host mesh (the
        # prime_cache/conftest posture) — force it BEFORE jax
        # initializes; a no-op when the flag is already set or jax is
        # already up (then the device gate records a skip)
        import sys as _sys

        if "jax" not in _sys.modules:
            _flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in _flags:
                os.environ["XLA_FLAGS"] = (
                    _flags + " --xla_force_host_platform_device_count=8"
                ).strip()
    from corro_sim.analysis.jaxpr_audit import run_audit

    return run_audit(
        update_golden=args.update_golden, out=args.out,
        as_json=args.json, diff=args.diff, contracts=args.contracts,
        keys=args.keys,
    )


def _cmd_bench(args: argparse.Namespace) -> int:
    from corro_sim.benchmarks import main as bench_main

    kw = {}
    if args.bench_nodes is not None:
        kw["n" if args.bench_config in (None, 0, 4) else "nodes"] = \
            args.bench_nodes
    return bench_main(config=args.bench_config, **kw) or 0


def _cmd_perf(args: argparse.Namespace) -> int:
    """`corro-sim perf` — the performance ledger & regression sentinel
    (corro_sim/obs/ledger.py, doc/performance.md §9).

    Modes (one per invocation):

    * ``--ingest [ARTIFACT...]`` — schema-normalize perf artifacts
      (BENCH_rNN/MULTICHIP_rNN round wrappers, bench one-line JSON,
      sweep/twin reports; default: the committed round artifacts in the
      cwd) and append them to the ledger;
    * ``--show`` (default) — per-(config, platform) trajectories with
      ASCII sparklines;
    * ``--check`` — grade each series' latest measured value against
      the committed tolerance bands; **exit 6 on breach** (the soak
      tripwire code). Cross-platform comparisons honest-skip: a CPU
      capture is never graded against a device band. ``--update``
      re-baselines the bands from the ledger instead (the audit-golden
      discipline — commit the diff with the change that moved the
      number).

    ``--out`` writes the JSON trajectory artifact in any mode.
    Exit codes: 0 ok, 2 bad args/unreadable artifact, 6 band breach.
    """
    from corro_sim.obs import ledger as perf_ledger

    ledger_path = args.ledger
    if ledger_path is None:
        golden = perf_ledger.golden_ledger_path()
        ledger_path = (
            golden if os.path.exists(golden)
            else perf_ledger.default_ledger_path()
        )
    modes = sum(1 for f in (args.ingest, args.check) if f)
    if modes > 1:
        print("error: --ingest and --check are exclusive modes",
              file=sys.stderr)
        return 2

    if args.ingest:
        paths = args.artifacts or perf_ledger.default_ingest_paths()
        if not paths:
            print(
                "error: nothing to ingest (no artifact paths given and "
                "no BENCH_r*/MULTICHIP_r* round artifacts in the cwd)",
                file=sys.stderr,
            )
            return 2
        records = []
        for path in paths:
            try:
                with open(path, encoding="utf-8") as f:
                    obj = json.load(f)
                records.extend(perf_ledger.normalize_artifact(
                    obj, source=os.path.basename(path)
                ))
            except (OSError, ValueError) as e:
                print(f"error: {path}: {e}", file=sys.stderr)
                return 2
        try:
            perf_ledger.append_records(ledger_path, records)
        except OSError as e:
            print(f"error: cannot append to {ledger_path!r}: {e}",
                  file=sys.stderr)
            return 2
        all_records, bad = perf_ledger.load_ledger(ledger_path)
        traj = perf_ledger.build_trajectory(all_records)
        perf_ledger.update_perf_gauges(traj)
        perf_ledger.set_perf_status({
            "ledger": ledger_path, "trajectory": traj,
        })
        if args.out:
            from corro_sim.utils.runtime import atomic_json_dump

            atomic_json_dump(args.out, traj, indent=2)
        print(json.dumps({
            "ledger": ledger_path,
            "ingested": len(records),
            "from": [os.path.basename(p) for p in paths],
            "records": len(all_records),
            "bad_lines": bad,
            "series": sorted(traj["series"]),
        }, indent=2))
        return 0

    try:
        all_records, bad = perf_ledger.load_ledger(ledger_path)
    except OSError as e:
        print(f"error: cannot read ledger {ledger_path!r}: {e}",
              file=sys.stderr)
        return 2
    traj = perf_ledger.build_trajectory(all_records)
    if args.out:
        from corro_sim.utils.runtime import atomic_json_dump

        atomic_json_dump(args.out, traj, indent=2)

    if args.check:
        bands_path = args.bands or perf_ledger.golden_bands_path()
        if args.update:
            prior = None
            if os.path.exists(bands_path):
                try:
                    prior = perf_ledger.load_bands(bands_path)
                except (OSError, ValueError) as e:
                    print(f"error: {bands_path}: {e}", file=sys.stderr)
                    return 2
            bands = perf_ledger.update_bands(
                all_records, prior=prior,
                tolerance_pct=args.tolerance_pct,
            )
            from corro_sim.utils.runtime import atomic_json_dump

            if not atomic_json_dump(bands_path, bands, indent=2):
                print(f"error: cannot write {bands_path!r}",
                      file=sys.stderr)
                return 2
            print(json.dumps({
                "updated": bands_path,
                "bands": sorted(bands["bands"]),
            }, indent=2))
            return 0
        try:
            bands = perf_ledger.load_bands(bands_path)
        except (OSError, ValueError) as e:
            print(
                f"error: cannot read bands {bands_path!r}: {e} "
                "(baseline with `corro-sim perf --check --update`)",
                file=sys.stderr,
            )
            return 2
        check = perf_ledger.check_bands(all_records, bands)
        check["ledger"] = ledger_path
        check["bands"] = bands_path
        perf_ledger.update_perf_gauges(traj, check)
        perf_ledger.set_perf_status({
            "ledger": ledger_path, "trajectory": traj, "check": check,
        })
        print(json.dumps(check, indent=2))
        from corro_sim.obs.ledger import BREACH_EXIT

        return BREACH_EXIT if check["breaches"] else 0

    # --show (the default mode)
    perf_ledger.update_perf_gauges(traj)
    perf_ledger.set_perf_status({
        "ledger": ledger_path, "trajectory": traj,
    })
    print(f"# ledger {ledger_path}: {len(all_records)} records"
          + (f" ({bad} bad lines skipped)" if bad else ""),
          file=sys.stderr)
    print(perf_ledger.render_trajectory(traj))
    return 0


def _cmd_doctor(args: argparse.Namespace) -> int:
    """`corro-sim doctor` — cross-artifact run diagnosis
    (corro_sim/obs/doctor.py, doc/observability.md §8).

    Classifies every given artifact by shape (flight journals, lane
    flights, sweep/soak/twin reports, frontiers, perf ledgers, bands,
    check results, profiler traces — a directory expands to all of
    them), joins the evidence, and prints a ranked finding report:
    each finding cites the artifact + field it read, suggests an
    action, and carries a one-command repro where one exists.

    ``--out`` writes the deterministic JSON report; ``--check`` exits
    6 (the soak/frontier/perf tripwire code) when a critical finding
    fires. Exit codes: 0 ok, 2 bad args/missing artifact, 6 critical
    finding under --check.
    """
    from corro_sim.obs import doctor as doctor_mod

    paths = list(args.artifacts)
    if not paths:
        from corro_sim.obs import ledger as perf_ledger

        golden = perf_ledger.golden_ledger_path()
        if os.path.exists(golden):
            paths.append(golden)
        if os.path.isdir("bench_out"):
            paths.append("bench_out")
    if not paths:
        print(
            "error: nothing to diagnose (no artifact paths given, no "
            "committed golden ledger, no bench_out/)",
            file=sys.stderr,
        )
        return 2
    missing = [p for p in args.artifacts if not os.path.exists(p)]
    if missing:
        print(f"error: no such artifact: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    report = doctor_mod.diagnose(paths)
    doctor_mod.update_doctor_gauges(report)
    doctor_mod.set_doctor_status(report)
    if args.out:
        from corro_sim.utils.runtime import atomic_json_dump

        atomic_json_dump(args.out, report, indent=2)
    print(doctor_mod.render_report(report))
    if args.check and not report["ok"]:
        return doctor_mod.CRITICAL_EXIT
    return 0


def _profiled(profile_dir: str | None):
    """The PR 2 ``--profile-dir`` hook (jax.profiler.trace), shared by
    the sweep/twin CLI paths: a failed trace start must never kill the
    dispatch it instruments — it increments the same counter the run
    path does and the work proceeds unprofiled."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        started = False
        if profile_dir:
            import jax

            from corro_sim.utils.metrics import counters
            try:
                jax.profiler.start_trace(profile_dir)
                started = True
            except Exception:
                counters.inc(
                    "corro_profile_trace_failures_total",
                    help_="jax.profiler.trace start failures "
                          "(profile skipped)",
                )
        try:
            yield
        finally:
            if started:
                import jax
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass

    return _cm()


def _cmd_agent(args: argparse.Namespace) -> int:
    """`corrosion agent` analog: run a live cluster behind the HTTP API
    and the admin socket until SIGINT/SIGTERM
    (``corrosion/src/command/agent.rs:16-93``)."""
    from corro_sim.admin import AdminServer
    from corro_sim.api.http import ApiServer
    from corro_sim.harness.cluster import LiveCluster
    from corro_sim.io.checkpoint import load_checkpoint
    from corro_sim.utils.runtime import Tripwire, wait_for_all_pending_handles

    tripwire = Tripwire.new_signals()
    if not args.resume and not args.schema:
        print("agent needs --schema or --resume", file=sys.stderr)
        return 2
    # TLS flag validation (and context build) runs BEFORE the cluster is
    # constructed — a misconfiguration must not cost minutes of compile
    ssl_ctx = None
    if (args.tls_key or args.tls_ca or args.tls_client_auth) \
            and not args.tls_cert:
        # a TLS flag without --tls-cert would silently serve plain HTTP
        print("TLS flags require --tls-cert", file=sys.stderr)
        return 2
    if args.tls_cert:
        from corro_sim.tls import server_ssl_context

        if not args.tls_key:
            print("--tls-cert requires --tls-key", file=sys.stderr)
            return 2
        if args.tls_client_auth and not args.tls_ca:
            print("--tls-client-auth requires --tls-ca", file=sys.stderr)
            return 2
        ssl_ctx = server_ssl_context(
            args.tls_cert, args.tls_key, ca_file=args.tls_ca,
            require_client_auth=args.tls_client_auth,
        )
    if args.resume:
        cluster = load_checkpoint(args.resume, tripwire=tripwire)
    else:
        with open(args.schema) as f:
            schema_sql = f.read()
        cluster = LiveCluster(
            schema_sql,
            num_nodes=args.nodes,
            seed=args.seed,
            default_capacity=args.capacity,
            tripwire=tripwire,
            cfg_overrides=(
                {"probes": args.probes} if args.probes else None
            ),
        )
    host, _, port = args.api_addr.partition(":")
    api = ApiServer(
        cluster,
        host=host or "127.0.0.1",
        port=int(port or 0),
        authz_token=args.authz_token,
        tick_interval=args.tick_interval or None,
        ssl_context=ssl_ctx,
    ).start()
    admin = AdminServer(cluster, args.admin_path).start()
    pg = None
    if args.pg_addr:
        from corro_sim.api.pg import PgServer

        pg_host, _, pg_port = args.pg_addr.partition(":")
        pg = PgServer(
            cluster, host=pg_host or "127.0.0.1", port=int(pg_port or 0)
        ).start()
    info = {
        "api": api.url,
        "admin": args.admin_path,
        "nodes": cluster.cfg.num_nodes,
    }
    if pg is not None:
        info["pg"] = f"{pg.addr[0]}:{pg.addr[1]}"
    print(json.dumps(info), flush=True)
    try:
        tripwire.wait()
    finally:
        if pg is not None:
            pg.close()
        api.close()
        admin.close()
        wait_for_all_pending_handles(timeout=10)
    return 0


def _client(args):
    from corro_sim.client import ApiClient

    return ApiClient(args.api, node=args.node, token=args.authz_token)


def _cmd_query(args: argparse.Namespace) -> int:
    """`corrosion query` — streams rows (``main.rs:368-412`` analog)."""
    client = _client(args)
    code = 0
    for e in client.query(args.sql):
        if args.raw:
            print(json.dumps(e))
        elif "row" in e:
            print("|".join(str(v) for v in e["row"][1]))
        elif "error" in e:
            print(f"error: {e['error']}", file=sys.stderr)
            code = 1
    return code


def _cmd_exec(args: argparse.Namespace) -> int:
    """`corrosion exec` — one transaction of statements."""
    resp = _client(args).execute(list(args.sql))
    print(json.dumps(resp))
    return 0 if all("error" not in r for r in resp["results"]) else 1


def _admin(args):
    from corro_sim.admin import AdminClient

    return AdminClient(args.admin_path)


def _print_json(obj) -> int:
    print(json.dumps(obj, indent=2))
    return 0


def _cmd_backup(args: argparse.Namespace) -> int:
    return _print_json(
        _admin(args).call("backup", path=args.path, node=args.node)
    )


def _cmd_restore(args: argparse.Namespace) -> int:
    return _print_json(
        _admin(args).call("restore", path=args.path, node=args.node)
    )


def _cmd_locks(args: argparse.Namespace) -> int:
    return _print_json(_admin(args).call("locks", top=args.top))


def _cmd_sync(args: argparse.Namespace) -> int:
    if args.what == "reconcile-gaps":
        return _print_json(_admin(args).call("sync_reconcile_gaps"))
    return _print_json(
        _admin(args).call("sync_generate", node=args.node)
    )


def _cmd_actor(args: argparse.Namespace) -> int:
    return _print_json(
        _admin(args).call("actor_version", actor=args.actor)
    )


def _cmd_subs(args: argparse.Namespace) -> int:
    if args.id:
        return _print_json(_admin(args).call("subs_info", id=args.id))
    return _print_json(_admin(args).call("subs_list"))


def _cmd_cluster(args: argparse.Namespace) -> int:
    if args.what == "members":
        return _print_json(_admin(args).call("cluster_members"))
    if args.what == "rejoin":
        return _print_json(
            _admin(args).call("cluster_rejoin", node=args.node)
        )
    if args.what == "set-id":
        if args.cluster_id is None:
            print("set-id requires --cluster-id", file=sys.stderr)
            return 2
        return _print_json(
            _admin(args).call(
                "cluster_set_id", node=args.node,
                cluster_id=args.cluster_id,
            )
        )
    return _print_json(_admin(args).call("cluster_membership_states"))


def _cmd_template(args: argparse.Namespace) -> int:
    """`corrosion template` — render + live re-render config files
    (``corrosion/src/command/tpl.rs``)."""
    from corro_sim.tpl import TemplateWatcher
    from corro_sim.utils.runtime import Tripwire

    src, _, dst = args.template.partition(":")
    if not dst:
        dst = src + ".out"
    w = TemplateWatcher(
        _client(args), src, dst, node=args.node,
        tripwire=Tripwire.new_signals(),
    )
    if args.once:
        w.render_once()
        return 0
    w.run()
    return 0


def _cmd_consul_sync(args: argparse.Namespace) -> int:
    """`corrosion consul sync` — poll the local Consul agent and mirror
    services/checks into the cluster (``command/consul/sync.rs``)."""
    from corro_sim.integrations.consul import (
        ConsulAgentClient,
        ConsulSync,
        FileConsulSource,
    )
    from corro_sim.utils.runtime import Tripwire

    source = (
        FileConsulSource(args.consul_file) if args.consul_file
        else ConsulAgentClient(args.consul_addr)
    )
    sync = ConsulSync(
        source, _client(args), node_name=args.node_name,
        state_path=args.state_path, target_node=args.node,
    )
    if args.once:
        print(json.dumps(sync.sync_once()))
        return 0
    sync.run(Tripwire.new_signals(), interval=args.interval)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="corro-sim",
        description="TPU-native simulator of Corrosion's replication protocols",
    )
    sub = p.add_subparsers(dest="command", required=True)

    pr = sub.add_parser("run", help="run a simulation to convergence")
    pr.add_argument("--config", help="TOML config file ([sim] table)")
    pr.add_argument("--nodes", type=int)
    pr.add_argument("--rows", type=int)
    pr.add_argument("--cols", type=int)
    pr.add_argument("--log-capacity", type=int)
    pr.add_argument("--write-rate", type=float)
    pr.add_argument("--zipf", type=float)
    pr.add_argument("--swim", action="store_const", const=True)
    pr.add_argument(
        "--swim-view", type=int,
        help="windowed SWIM: members tracked per node (0 = full view)",
    )
    pr.add_argument("--sync-interval", type=int)
    pr.add_argument(
        "--no-pipeline", dest="pipeline", action="store_const", const=False,
        help="disable pipelined chunk dispatch (speculative next-chunk "
             "dispatch + async metric fetch; doc/performance.md) and run "
             "the sequential chunk loop — results are bit-identical, "
             "only dispatch order changes",
    )
    pr.add_argument("--write-rounds", type=int, default=32)
    pr.add_argument("--max-rounds", type=int, default=4096)
    pr.add_argument("--chunk", type=int, default=16)
    pr.add_argument("--seed", type=int, default=0)
    pr.add_argument(
        "--flight-out",
        help="journal the per-round flight-recorder timeline (ND-JSON) "
             "to this path, chunk by chunk",
    )
    pr.add_argument(
        "--probes", type=int,
        help="track K sampled versions through the gossip fabric "
             "on-device (probe tracer; 0 = off)",
    )
    pr.add_argument(
        "--probe-out",
        help="path prefix for the probe artifacts (<prefix>.ndjson + "
             "<prefix>.trace.json, Perfetto-loadable); defaults next to "
             "--flight-out",
    )
    pr.add_argument(
        "--profile-dir",
        help="capture a jax.profiler trace of the scan loop into this "
             "directory (TensorBoard/Perfetto-loadable)",
    )
    pr.add_argument(
        "--scenario",
        help="chaos scenario spec `name[:k=v,...]` (faults/scenarios.py: "
             "lossy:p=0.1, rolling_restart, split_brain_heal, churn, "
             "flapper, blackhole_one_way, ...); arms the invariant "
             "checkers and reports recovery time",
    )
    pr.add_argument(
        "--workload",
        help="couple a traffic workload spec `name[:k=v,...][+...]` "
             "(corro_sim/workload/) into the run — accepted TOGETHER "
             "with --scenario (load + faults in one spec); the fault "
             "window and the write range must overlap, validated up "
             "front",
    )
    pr.add_argument(
        "--knob", action="append", metavar="FIELD=VALUE",
        help="link-fault threshold override on top of the scenario "
             "(loss/dup/burst_*/sync_loss); repeatable — the sweep "
             "frontier's worst-seed repro surface (doc/sweeping.md)",
    )
    pr.add_argument(
        "--scenario-rounds", type=int,
        help="fault-timeline horizon the scenario compiles against "
             "(default: --max-rounds). Sweep worst-seed repro commands "
             "pin this to the lane's horizon — wave-shaped generators "
             "derive different timelines from different horizons",
    )
    pr.add_argument(
        "--fork", metavar="TOKEN",
        help="warm-start from a twin fork token (corro-sim twin "
             "--fork-out; doc/twin.md): the run resumes the forked "
             "state under the --scenario applied on top — the what-if "
             "forecast's one-command serial repro. The token owns the "
             "base shape (shape flags refused); node-fault rounds "
             "shift into the fork's absolute frame automatically",
    )
    pr.add_argument(
        "--scorecard", action="store_true",
        help="arm the resilience scorecard (faults/scorecard.py): the "
             "report gains a `resilience` block (recovery_rounds, "
             "rows_lost, resync_rows, SWIM churn, sub-delivery "
             "degradation under a coupled --workload); armed "
             "automatically for node-fault scenarios",
    )
    pr.add_argument(
        "--check-invariants", action="store_true",
        help="run the fault invariant checkers (faults/invariants.py) "
             "even without a scenario; violations exit 5",
    )
    pr.add_argument(
        "--transfer-guard", action="store_true",
        help="arm jax.transfer_guard('disallow') around the chunk loop "
             "(analysis/transfer_guard.py): any device transfer outside "
             "the sanctioned staging/resolve points raises instead of "
             "silently re-serializing dispatch (also: "
             "CORRO_SIM_TRANSFER_GUARD=1)",
    )
    pr.add_argument(
        "--mesh", action="store_true",
        help="shard the cluster state over ALL visible devices "
             "(node-axis data parallel, engine/sharding.py; "
             "doc/multichip.md) — errors if only one device is visible",
    )
    pr.add_argument(
        "--shard-log", choices=("on", "off", "auto"),
        help="change-log placement on the mesh: on = actor-sharded "
             "(per-device log HBM drops by the mesh size, delivery/sync "
             "gathers become collectives), off = replicated, auto = the "
             "SHARD_LOG_ACTORS size heuristic (default; doc/multichip.md)",
    )
    pr.set_defaults(fn=_cmd_run)

    plo = sub.add_parser(
        "load",
        help="drive a production-shaped traffic workload (Zipf, bursts, "
             "churn storms) through the batched and/or live paths",
    )
    plo.add_argument(
        "spec",
        help="workload spec `name[:k=v,...][+name2...]` "
             "(corro_sim/workload/: zipf, uniform, burst, multiwriter, "
             "churn_storm; `+` composes — doc/workloads.md)",
    )
    plo.add_argument("--config", help="TOML config file ([sim] table)")
    plo.add_argument("--nodes", type=int, default=32)
    plo.add_argument("--rounds", type=int, default=32,
                     help="load-phase rounds to schedule")
    plo.add_argument("--rows", type=int,
                     help="row-slot capacity (default: the schedule's "
                          "key universe)")
    plo.add_argument("--cols", type=int)
    plo.add_argument("--seed", type=int, default=0)
    plo.add_argument(
        "--path", choices=("batched", "live", "both"), default="both",
        help="batched = run_sim convergence under load; live = "
             "LiveCluster + subscriptions + query fans (sub-delivery "
             "latency)",
    )
    plo.add_argument("--max-rounds", type=int, default=4096,
                     help="batched-path round budget")
    plo.add_argument("--chunk", type=int, default=16)
    plo.add_argument("--subs", type=int, default=16,
                     help="distinct subscription queries (live path)")
    plo.add_argument("--subscribers", type=int, default=1,
                     help="subscriber streams per subscription")
    plo.add_argument("--queries-per-round", type=int, default=0,
                     help="one-shot queries fanned per round")
    plo.add_argument("--http", action="store_true",
                     help="fan queries through a real HTTP API server")
    plo.add_argument("--pg", action="store_true",
                     help="fan queries through a real pgwire server")
    plo.add_argument("--settle-rounds", type=int, default=256,
                     help="post-load rounds allowed for the live cluster "
                          "to drain")
    plo.add_argument("--verify-vacuous", action="store_true",
                     help="prove the workload-off claim in-process: an "
                          "all-idle schedule must run bit-identical to "
                          "the disabled sampler")
    plo.add_argument("--flight-out",
                     help="dump the batched run's flight timeline "
                          "(ND-JSON) with workload_event annotations")
    plo.add_argument("--out", help="also write the report JSON here")
    plo.set_defaults(fn=_cmd_load)

    ps = sub.add_parser(
        "soak",
        help="sweep chaos scenarios under invariant checking; report "
             "recovery time per scenario",
    )
    ps.add_argument("--config", help="TOML config file ([sim] table)")
    ps.add_argument("--nodes", type=int)
    ps.add_argument("--rows", type=int)
    ps.add_argument("--cols", type=int)
    ps.add_argument("--log-capacity", type=int)
    ps.add_argument("--write-rate", type=float)
    ps.add_argument("--zipf", type=float)
    ps.add_argument("--swim", action="store_const", const=True)
    ps.add_argument("--swim-view", type=int)
    ps.add_argument("--sync-interval", type=int)
    ps.add_argument("--probes", type=int)
    ps.add_argument(
        "--no-pipeline", dest="pipeline", action="store_const", const=False,
        help="disable pipelined chunk dispatch for every scenario run",
    )
    ps.add_argument(
        "--scenario", action="append",
        help="scenario spec `name[:k=v,...]`; repeatable (default: sweep "
             "the recoverable catalog — permanent-fault scenarios like "
             "blackhole_one_way and ring/star are opt-in by name)",
    )
    ps.add_argument(
        "--workload",
        help="couple a traffic workload spec into EVERY scenario run "
             "(load + faults in one spec; corro_sim/workload/). "
             "Coupling is validated (fault window must overlap the "
             "write range) and checkpointing is disabled for coupled "
             "runs",
    )
    ps.add_argument(
        "--scorecard", nargs="?", const="SCORECARD.json", metavar="PATH",
        help="arm the resilience scorecard on every scenario, write the "
             "per-scenario blocks + threshold verdict to PATH (default "
             "SCORECARD.json), and gate against analysis/golden/"
             "resilience_thresholds.json — breaches exit 6",
    )
    ps.add_argument(
        "--rounds", type=int, default=128,
        help="scenario length in rounds (fault timeline horizon)",
    )
    ps.add_argument("--write-rounds", type=int, default=16)
    ps.add_argument("--max-rounds", type=int, default=4096)
    ps.add_argument("--chunk", type=int, default=16)
    ps.add_argument("--seed", type=int, default=0)
    ps.add_argument(
        "--out",
        help="artifact path prefix: <out>.<scenario>.ndjson flight "
             "journals + <out>.report.json (+ <out>.ckpt.npz resume "
             "token and <out>.partial.json if the run dies)",
    )
    ps.add_argument(
        "--checkpoint",
        help="resumable-checkpoint path (default: <out>.ckpt.npz when "
             "--out is set; io/checkpoint.py sim checkpoints)",
    )
    ps.add_argument(
        "--checkpoint-every", type=int, default=None,
        help="chunks between resumable checkpoints (default 4 in the "
             "serial loop; only active when a checkpoint path "
             "resolves). An explicit nonzero cadence implies --serial "
             "— the vmapped sweep path writes no resume tokens; 0 "
             "keeps the swept path (no checkpoints either way)",
    )
    ps.add_argument(
        "--resume",
        help="continue a killed soak from its checkpoint file — the "
             "token reconstructs the sweep (config, seed, chunking, "
             "remaining scenarios) and the killed scenario continues "
             "bit-identically; other flags are ignored (implies "
             "--serial: resume tokens are a sequential-loop concept)",
    )
    ps.add_argument(
        "--serial", action="store_true",
        help="run the sequential one-run_sim-per-scenario loop instead "
             "of the default vmapped sweep dispatch (corro_sim/sweep/) "
             "— the escape hatch for checkpointed multi-hour soaks and "
             "schedules the lane encoding cannot carry; also implied "
             "by --resume and an explicit --checkpoint",
    )
    ps.set_defaults(fn=_cmd_soak)

    psw = sub.add_parser(
        "sweep",
        help="race a scenario x seed x knob chaos matrix as lanes of "
             "ONE vmapped dispatch; resilience frontier + worst-seed "
             "repro (doc/sweeping.md)",
    )
    psw.add_argument(
        "grid", nargs="+", metavar="AXIS=VALUES",
        help="grid axes: scenario=name[:k=v,..][,name2...] (';' hard-"
             "separates), seed=0..31 or comma list, knob.loss=0.05,0.2 "
             "(link-fault threshold axes cross-product)",
    )
    psw.add_argument("--config", help="TOML config file ([sim] table)")
    psw.add_argument("--nodes", type=int)
    psw.add_argument("--rows", type=int)
    psw.add_argument("--cols", type=int)
    psw.add_argument("--log-capacity", type=int)
    psw.add_argument("--write-rate", type=float)
    psw.add_argument("--zipf", type=float)
    psw.add_argument("--swim", action="store_const", const=True)
    psw.add_argument("--swim-view", type=int)
    psw.add_argument("--sync-interval", type=int)
    psw.add_argument("--probes", type=int)
    psw.add_argument(
        "--rounds", type=int, default=128,
        help="scenario length in rounds (fault timeline horizon)",
    )
    psw.add_argument("--write-rounds", type=int, default=16)
    psw.add_argument("--max-rounds", type=int, default=4096)
    psw.add_argument("--chunk", type=int, default=16)
    psw.add_argument(
        "--workload",
        help="couple a traffic workload spec into EVERY lane "
             "(lane-seeded; fault-window overlap validated per lane "
             "up front, all errors in one report)",
    )
    psw.add_argument(
        "--mesh", action="store_true",
        help="shard the LANE axis over all visible devices (sweep on "
             "one mesh axis — lanes are independent, so this is pure "
             "batch data parallelism; doc/sweeping.md)",
    )
    psw.add_argument(
        "--compact", action="store_true",
        help="the fleet scheduler (doc/sweeping.md §fleet-scheduler): "
             "evict settled lanes at chunk boundaries, refill their "
             "slots from the pending-grid queue, shrink the batch to "
             "power-of-2 buckets once the queue drains — every lane "
             "stays bit-identical to its serial twin; does not compose "
             "with --mesh",
    )
    psw.add_argument(
        "--width", type=int, metavar="W",
        help="cap the compacted lane-batch width (rounded up to a "
             "power-of-2 bucket; lanes beyond it queue). Default: the "
             "whole grid in one batch",
    )
    psw.add_argument(
        "--pipeline", action="store_true",
        help="speculative dispatch: enqueue chunk N+1 before chunk N's "
             "convergence scalar lands (predicted on 'no lane "
             "settles'; a mispredict discards the speculative result "
             "and re-dispatches, so committed chunks are exactly the "
             "sequential ones — doc/performance.md §chunk-pipelining)",
    )
    psw.add_argument(
        "--frontier", nargs="?", const="FRONTIER.json", metavar="PATH",
        help="write the resilience-frontier artifact (per-cell "
             "worst/p95 over seeds + worst-seed repro commands) to "
             "PATH (default FRONTIER.json)",
    )
    psw.add_argument(
        "--progress", action="store_true",
        help="per-chunk lane-state progress lines (fleet observatory): "
             "racing/converged/poisoned counts, cumulative wasted "
             "frozen-lane rounds, and a one-char-per-lane state string",
    )
    psw.add_argument(
        "--flight-dir", metavar="DIR",
        help="demux every lane's flight timeline (per-round metrics + "
             "derived diagnostics + annotations, field-identical to "
             "its serial twin's) into per-lane ND-JSON files under DIR "
             "— no lane is re-run; read them with `corro-sim flight "
             "<file>` (doc/observability.md §lane-observatory)",
    )
    psw.add_argument(
        "--heatmap", metavar="PATH",
        help="write the cell x seed grid heatmap artifact "
             "(rounds-to-convergence / recovery / rows_lost / "
             "degradation_p99 matrices) to PATH "
             "and print an ASCII rendering to stderr",
    )
    psw.add_argument(
        "--profile-dir",
        help="capture a jax.profiler trace of the fleet dispatch into "
             "this directory (TensorBoard/Perfetto-loadable); the path "
             "rides the sweep's perf-ledger record",
    )
    psw.add_argument("--out", help="also write the full report JSON here")
    psw.set_defaults(fn=_cmd_sweep)

    pt2 = sub.add_parser(
        "twin",
        help="shadow a changeset feed (streaming ingest + per-chunk "
             "headlines) and forecast what-if chaos off a forked twin "
             "state (doc/twin.md)",
    )
    pt2.add_argument(
        "feed",
        help="ND-JSON changeset feed (corro-api-types wire shapes — "
             "io/traces.py module docstring)",
    )
    pt2.add_argument(
        "--scan-lines", type=int, default=0,
        help="universe scan window in feed lines (0 = scan the whole "
             "feed); lines naming actors/tables/values outside the "
             "frozen window quarantine",
    )
    pt2.add_argument(
        "--chunk-lines", type=int, default=64,
        help="feed lines consumed per shadow chunk (the checkpoint "
             "cursor granularity)",
    )
    pt2.add_argument(
        "--skip-bad", action="store_true",
        help="quarantine hostile feed lines with per-reason counters "
             "(corro_twin_bad_lines_total) + flight annotations instead "
             "of refusing the whole feed with one up-front error",
    )
    pt2.add_argument("--seed", type=int, default=0)
    pt2.add_argument(
        "--nodes", type=int,
        help="shadow cluster size (default: the feed's actor count)",
    )
    pt2.add_argument(
        "--log-capacity", type=int,
        help="change-log ring size (default: the feed's deepest actor "
             "history + 1)",
    )
    pt2.add_argument(
        "--drain-rounds", type=int, default=256,
        help="post-feed round budget chasing gap -> 0",
    )
    pt2.add_argument(
        "--checkpoint",
        help="cursor-checkpoint path (default: <out>.ckpt.npz when "
             "--out is set)",
    )
    pt2.add_argument(
        "--checkpoint-every", type=int, default=1,
        help="feed chunks between cursor checkpoints (0 = none)",
    )
    pt2.add_argument(
        "--resume", metavar="TOKEN",
        help="continue a SIGKILL'd twin from its cursor token — the "
             "remaining feed plays out bit-identically to the "
             "uninterrupted run (shape flags are ignored; the token "
             "carries the config)",
    )
    pt2.add_argument(
        "--tail", action="store_true",
        help="LIVE mode: treat FEED as a growing source — a polled "
             "file tail (rotation re-binds via inode + consumed-prefix "
             "sha, truncation refuses) or, for an http(s):// FEED, a "
             "reconnecting /v1/changes watch — and shadow chunks as "
             "they arrive, bit-identically to file-mode replay of the "
             "same lines; needs --scan-lines; exits 5 with a resumable "
             "cursor when the source dies past its backoff/idle budget",
    )
    pt2.add_argument(
        "--tail-poll-ms", type=int, default=250,
        help="tail poll interval in ms (file stat / HTTP request "
             "cadence between arrivals)",
    )
    pt2.add_argument(
        "--reconnect-max-s", type=float, default=30.0,
        help="total jittered-backoff budget retrying a vanished "
             "source before declaring it dead",
    )
    pt2.add_argument(
        "--idle-timeout-s", type=float, default=10.0,
        help="a reachable source delivering NOTHING for this long is "
             "dead (the tail's clean end when the writer finishes)",
    )
    pt2.add_argument(
        "--max-lag-lines", type=int, default=65536,
        help="backpressure bound: stop reading ahead when this many "
             "fetched lines await the shadow",
    )
    pt2.add_argument(
        "--refresh-threshold", type=float, default=0.0,
        help="stale-universe refresh trigger: when the windowed "
             "unknown-actor/row/col/value quarantine rate crosses this "
             "fraction, re-freeze the closed world from the trailing "
             "window at the next chunk boundary "
             "(corro_twin_refresh_total; 0 = never; needs --skip-bad)",
    )
    pt2.add_argument(
        "--refresh-window", type=int, default=256, metavar="LINES",
        help="trailing feed-line window the refresh rate is measured "
             "over (also the re-scan window on refresh)",
    )
    pt2.add_argument(
        "--forecast-every", type=int, default=0, metavar="CHUNKS",
        help="cadence re-fork loop: every N shadowed chunks, fork the "
             "live state and race the --forecast grid, appending one "
             "forecast_trend point per cycle (0 = only the final "
             "forecast)",
    )
    pt2.add_argument(
        "--forecast-load", type=int, default=0, metavar="ROUNDS",
        help="with --forecast-every: replay up to ROUNDS of the "
             "trailing feed window into every forecast lane as coupled "
             "workload (workload/inject.py trace_workload) so recovery "
             "is graded under the live traffic (0 = uncoupled)",
    )
    pt2.add_argument(
        "--forecast", nargs="+", metavar="AXIS=VALUES",
        help="what-if grid (the sweep grammar: scenario=crash_amnesia:"
             "nodes=2,at=4,down=4,lossy:p=0.2 seed=0..3): fork the "
             "final twin state and race every (scenario x seed) lane "
             "in ONE vmapped dispatch, graded against the "
             "twin_forecast threshold section (breach = exit 6)",
    )
    pt2.add_argument(
        "--forecast-rounds", type=int, default=64,
        help="fault-timeline horizon of each forecast lane (relative "
             "to the fork)",
    )
    pt2.add_argument("--max-rounds", type=int, default=1024,
                     help="forecast round budget")
    pt2.add_argument("--chunk", type=int, default=8,
                     help="forecast dispatch chunk")
    pt2.add_argument(
        "--fork-out", metavar="PATH",
        help="write the fork token here (default <out>.fork.npz with "
             "--forecast; also usable without --forecast to hand the "
             "token to `corro-sim run --fork`)",
    )
    pt2.add_argument(
        "--frontier", nargs="?", const="TWIN_frontier.json",
        metavar="PATH",
        help="write the projected resilience-frontier artifact "
             "(per-cell worst/p95 + worst-seed `run --fork` repro "
             "commands)",
    )
    pt2.add_argument("--flight-out",
                     help="journal the shadow's flight timeline "
                          "(ND-JSON) with twin_chunk/twin_bad_line "
                          "annotations")
    pt2.add_argument(
        "--flight-dir", metavar="DIR",
        help="with --forecast: demux every forecast lane's flight "
             "timeline (projected: true in its meta) into per-lane "
             "ND-JSON files under DIR — the fleet observatory surface "
             "(doc/observability.md §lane-observatory)",
    )
    pt2.add_argument(
        "--profile-dir",
        help="capture jax.profiler traces of the shadow scan "
             "(<dir>/shadow) and the forecast dispatch "
             "(<dir>/forecast); the path rides the twin's "
             "perf-ledger record",
    )
    pt2.add_argument("--out", help="also write the report JSON here")
    pt2.set_defaults(fn=_cmd_twin)

    pli = sub.add_parser(
        "lint",
        help="corro-lint: static trace-safety analysis "
             "(doc/static_analysis.md)",
    )
    pli.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: corro_sim)",
    )
    pli.add_argument(
        "--format", choices=["text", "json"], default="text",
        help="report format on stdout",
    )
    pli.add_argument(
        "--strict", action="store_true",
        help="exit 1 on warnings too, not just errors",
    )
    pli.add_argument(
        "--out",
        help="also write the JSON findings report to this path "
             "(the CI artifact)",
    )
    pli.set_defaults(fn=_cmd_lint)

    pau = sub.add_parser(
        "audit",
        help="jaxpr audit: feature-off vacuity + golden op-count "
             "fingerprint (doc/static_analysis.md)",
    )
    pau.add_argument(
        "--update-golden", action="store_true",
        help="re-baseline analysis/golden/jaxpr_fingerprint.json from "
             "the current tree (commit the diff with the change that "
             "moved the op counts)",
    )
    pau.add_argument(
        "--json", action="store_true", help="print the full JSON report"
    )
    pau.add_argument(
        "--diff", action="store_true",
        help="print (and embed in the report) the per-primitive eqn "
             "delta vs the committed golden — the PR's op-budget cost "
             "at a glance, shown pass or fail",
    )
    pau.add_argument(
        "--contracts", action="store_true",
        help="also run the program-contract auditor: jaxpr dataflow "
             "vacuity proofs for every registered feature x program, "
             "collective budgets of the sharded/sweep programs, "
             "determinism lints, and the static peak-HBM golden "
             "(analysis/golden/program_contracts.json; "
             "doc/static_analysis.md)",
    )
    pau.add_argument(
        "--keys", action="store_true",
        help="also run the key-lineage auditor: reconstruct every "
             "program's PRNG derivation forest and prove K1 single-"
             "consumption, K2 stream disjointness (declared == "
             "observed fold tags), and K3 lane/fork independence "
             "(analysis/golden/key_lineage.json; "
             "doc/static_analysis.md §4)",
    )
    pau.add_argument(
        "--out", help="also write the JSON report to this path"
    )
    pau.set_defaults(fn=_cmd_audit)

    pb = sub.add_parser(
        "bench",
        help="run a BASELINE benchmark config (default: 0, the north star)",
    )
    pb.add_argument(
        "--config", dest="bench_config", type=int,
        choices=[0, 1, 2, 3, 4, 5, 6, 7, 8],
        help="0=north-star (10k sim convergence wall vs 64-agent "
             "devcluster wall) 1=devcluster 2=64-node slice 3=1k zipf "
             "4=10k headline 5=50k outage catch-up 6=workload engine "
             "7=weak-scaling multichip (100k @ 8 devices, actor-sharded "
             "log, windowed SWIM; doc/multichip.md) 8=chaos-matrix "
             "sweep (scenario x seed grid in one vmapped dispatch, "
             "clusters/sec/device; doc/sweeping.md)",
    )
    pb.add_argument("--nodes", dest="bench_nodes", type=int,
                    help="override the config's cluster size")
    pb.set_defaults(fn=_cmd_bench)

    pp = sub.add_parser(
        "perf",
        help="performance ledger & regression sentinel: platform-keyed "
             "trajectories for every bench/sweep/twin number, gated by "
             "committed tolerance bands (doc/performance.md section 9)",
    )
    pp.add_argument(
        "artifacts", nargs="*", metavar="ARTIFACT",
        help="with --ingest: perf artifacts to normalize and append "
             "(BENCH_rNN/MULTICHIP_rNN round wrappers, bench one-line "
             "JSON, sweep/twin reports; default: the BENCH_r*/"
             "MULTICHIP_r* round artifacts in the cwd)",
    )
    pp.add_argument(
        "--ingest", action="store_true",
        help="normalize the artifacts into ledger records and append "
             "them (append-only ND-JSON; one record per number, keyed "
             "by config, platform, device_kind, git rev, seq)",
    )
    pp.add_argument(
        "--show", action="store_true",
        help="per-(config, platform) trajectories with ASCII "
             "sparklines (the default mode)",
    )
    pp.add_argument(
        "--check", action="store_true",
        help="grade each series' latest measured value against the "
             "committed tolerance bands — exit 6 on breach; "
             "cross-platform comparisons honest-skip and unmeasured "
             "records never grade",
    )
    pp.add_argument(
        "--update", action="store_true",
        help="with --check: re-baseline the bands from the ledger's "
             "latest measured values (the audit-golden discipline — "
             "commit the diff with the change that moved the number)",
    )
    pp.add_argument(
        "--ledger", metavar="PATH",
        help="ND-JSON ledger path (default: the committed "
             "analysis/golden/perf_ledger.ndjson when it exists, else "
             "the bench_out/ working ledger)",
    )
    pp.add_argument(
        "--bands", metavar="PATH",
        help="tolerance-bands file (default: the committed "
             "analysis/golden/perf_bands.json)",
    )
    pp.add_argument(
        "--tolerance-pct", type=float, default=25.0,
        help="default band width for --update (per-band values in the "
             "committed file survive re-baselines)",
    )
    pp.add_argument(
        "--out", metavar="PATH",
        help="also write the JSON trajectory artifact here",
    )
    pp.set_defaults(fn=_cmd_perf)

    pdoc = sub.add_parser(
        "doctor",
        help="cross-artifact run diagnosis: classify flight/sweep/twin/"
             "ledger/profile artifacts by shape, join the evidence, and "
             "rank findings with citations, actions and repro commands "
             "(doc/observability.md section 8)",
    )
    pdoc.add_argument(
        "artifacts", nargs="*", metavar="ARTIFACT",
        help="artifact files or directories to diagnose (flight "
             "journals, sweep/soak/twin reports, frontiers, perf "
             "ledgers/bands/check results, --profile-dir traces; "
             "default: the committed golden ledger plus bench_out/)",
    )
    pdoc.add_argument(
        "--check", action="store_true",
        help="exit 6 when a critical finding fires (the soak/frontier/"
             "perf tripwire code)",
    )
    pdoc.add_argument(
        "--out", metavar="PATH",
        help="also write the deterministic JSON report here",
    )
    pdoc.set_defaults(fn=_cmd_doctor)

    pa = sub.add_parser("agent", help="run a live cluster (HTTP API + admin)")
    pa.add_argument("--schema", help="schema DDL file")
    pa.add_argument("--resume", help="warm-boot from a checkpoint file")
    pa.add_argument("--nodes", type=int, default=4)
    pa.add_argument("--seed", type=int, default=0)
    pa.add_argument("--capacity", type=int, default=256)
    pa.add_argument("--api-addr", default="127.0.0.1:0")
    pa.add_argument(
        "--pg-addr",
        help="also serve the Postgres wire protocol on host:port "
             "(api.pg.addr analog; off when omitted)",
    )
    pa.add_argument("--admin-path", default="./corro-sim-admin.sock")
    pa.add_argument("--authz-token")
    pa.add_argument("--tls-cert", help="serve the HTTP API over TLS")
    pa.add_argument("--tls-key", help="private key for --tls-cert")
    pa.add_argument("--tls-ca", help="CA bundle for client verification")
    pa.add_argument(
        "--tls-client-auth", action="store_true",
        help="require client certificates (mutual TLS)",
    )
    pa.add_argument(
        "--tick-interval", type=float, default=0.1,
        help="background gossip cadence in seconds (0 disables)",
    )
    pa.add_argument(
        "--probes", type=int, default=0,
        help="track K sampled versions on-device (probe tracer; "
             "read via /v1/probes or `corro-sim probes`)",
    )
    pa.set_defaults(fn=_cmd_agent)

    def api_args(sp):
        sp.add_argument("--api", default="127.0.0.1:8080",
                        help="agent HTTP address")
        sp.add_argument("--node", type=int, default=0)
        sp.add_argument("--authz-token")

    def admin_args(sp):
        sp.add_argument("--admin-path", default="./corro-sim-admin.sock")

    pq = sub.add_parser("query", help="run a SELECT against an agent")
    api_args(pq)
    pq.add_argument("--raw", action="store_true", help="print raw events")
    pq.add_argument("sql")
    pq.set_defaults(fn=_cmd_query)

    pe = sub.add_parser("exec", help="execute DML statements (one tx)")
    api_args(pe)
    pe.add_argument("sql", nargs="+")
    pe.set_defaults(fn=_cmd_exec)

    pbk = sub.add_parser("backup", help="portable actor-neutral snapshot")
    admin_args(pbk)
    pbk.add_argument("--node", type=int, default=0)
    pbk.add_argument("path")
    pbk.set_defaults(fn=_cmd_backup)

    prs = sub.add_parser("restore", help="restore a backup into the agent")
    admin_args(prs)
    prs.add_argument("--node", type=int, default=0)
    prs.add_argument("path")
    prs.set_defaults(fn=_cmd_restore)

    pl = sub.add_parser("locks", help="lock registry dump")
    admin_args(pl)
    pl.add_argument("--top", type=int)
    pl.set_defaults(fn=_cmd_locks)

    psy = sub.add_parser("sync", help="sync state tooling")
    admin_args(psy)
    psy.add_argument(
        "what", nargs="?", default="generate",
        choices=["generate", "reconcile-gaps"],
    )
    psy.add_argument("--node", type=int, default=0)
    psy.set_defaults(fn=_cmd_sync)

    pac = sub.add_parser("actor", help="actor version bookkeeping")
    admin_args(pac)
    pac.add_argument("actor", type=int)
    pac.set_defaults(fn=_cmd_actor)

    psb = sub.add_parser("subs", help="list/inspect subscriptions")
    admin_args(psb)
    psb.add_argument("id", nargs="?")
    psb.set_defaults(fn=_cmd_subs)

    pc = sub.add_parser("cluster", help="membership introspection + ops")
    admin_args(pc)
    pc.add_argument(
        "what",
        choices=["members", "membership-states", "rejoin", "set-id"],
    )
    pc.add_argument("--node", type=int, default=0)
    pc.add_argument("--cluster-id", type=int)
    pc.set_defaults(fn=_cmd_cluster)

    pt = sub.add_parser(
        "template", help="render a template (live re-render on change)"
    )
    api_args(pt)
    pt.add_argument("template", help="src[:dst] template/output paths")
    pt.add_argument("--once", action="store_true")
    pt.set_defaults(fn=_cmd_template)

    pcs = sub.add_parser(
        "consul-sync", help="mirror Consul services/checks into the cluster"
    )
    api_args(pcs)
    pcs.add_argument("--consul-addr", default="http://127.0.0.1:8500")
    pcs.add_argument("--consul-file",
                     help="JSON file source instead of a live agent")
    pcs.add_argument("--node-name", default="corro-sim-node")
    pcs.add_argument(
        "--state-path", default="./corro-consul-state.json",
        help="hash-state sidecar file (persisting it lets deletions that "
             "happen while the daemon is down propagate on restart)",
    )
    pcs.add_argument("--interval", type=float, default=1.0)
    pcs.add_argument("--once", action="store_true")
    pcs.set_defaults(fn=_cmd_consul_sync)

    pdc = sub.add_parser(
        "devcluster",
        help="run an `A -> B` topology file as one simulated cluster",
    )
    pdc.add_argument("topology", help="topology file of `A -> B` lines")
    pdc.add_argument("--schema", required=True, help="schema DDL file")
    pdc.add_argument(
        "--statedir", help="write per-node state dirs with node.json maps"
    )
    pdc.add_argument("--seed", type=int, default=0)
    pdc.add_argument("--capacity", type=int, default=256)
    pdc.add_argument("--api-addr", default="127.0.0.1:0")
    pdc.add_argument("--admin-path", default="./corro-devcluster-admin.sock")
    pdc.add_argument("--tick-interval", type=float, default=0.1)
    pdc.set_defaults(fn=_cmd_devcluster)

    prl = sub.add_parser(
        "reload", help="re-apply schema files through the running agent"
    )
    api_args(prl)
    prl.add_argument("schema_files", nargs="+")
    prl.set_defaults(fn=_cmd_reload)

    pfl = sub.add_parser(
        "flight", help="per-round telemetry timeline (flight recorder)"
    )
    admin_args(pfl)
    pfl.add_argument(
        "path", nargs="?",
        help="read a flight ND-JSON export directly (a `run "
             "--flight-out` journal, or a per-lane file from `sweep/"
             "twin --flight-dir`) instead of dialing the admin socket",
    )
    pfl.add_argument("-n", type=int, help="only the last N rounds")
    pfl.add_argument(
        "--diag", action="store_true",
        help="print only the derived convergence diagnostics",
    )
    pfl.add_argument(
        "--export", help="dump the full ND-JSON timeline to this path "
        "(written by the agent process)",
    )
    pfl.set_defaults(fn=_cmd_flight)

    ppb = sub.add_parser(
        "probes",
        help="probe-tracer provenance + per-node lag observatory",
    )
    admin_args(ppb)
    ppb.add_argument(
        "--lag", action="store_true",
        help="print only the per-node lag observatory",
    )
    ppb.add_argument(
        "--top", type=int, default=8,
        help="laggards listed by the observatory",
    )
    ppb.add_argument(
        "--export",
        help="write <prefix>.ndjson + <prefix>.trace.json server-side",
    )
    ppb.set_defaults(fn=_cmd_probes)

    ptr = sub.add_parser("traces", help="recent spans from the tracer")
    admin_args(ptr)
    ptr.add_argument("-n", type=int, default=100)
    ptr.add_argument("--name", help="filter by span name")
    ptr.add_argument("--trace-id", help="all spans of one trace")
    ptr.set_defaults(fn=_cmd_traces)

    pdb = sub.add_parser("db", help="database-level operations")
    db_sub = pdb.add_subparsers(dest="db_cmd", required=True)
    pdbl = db_sub.add_parser(
        "lock", help="hold the write lock while a command runs"
    )
    admin_args(pdbl)
    pdbl.add_argument("cmd", help="shell command to run under the lock")
    pdbl.add_argument(
        "--timeout", type=float, default=600.0,
        help="crash-safety auto-release deadline (seconds); must exceed "
             "the command's runtime or its tail runs unprotected",
    )
    pdbl.set_defaults(fn=_cmd_db_lock)

    ptls = sub.add_parser(
        "tls", help="certificate authority / server / client cert tooling"
    )
    tls_sub = ptls.add_subparsers(dest="tls_cmd", required=True)
    tca = tls_sub.add_parser("ca", help="certificate authority commands")
    tca_sub = tca.add_subparsers(dest="tls_sub_cmd", required=True)
    tcag = tca_sub.add_parser("generate", help="generate a root CA")
    tcag.add_argument("--output-dir", default=".")
    tcag.set_defaults(fn=_cmd_tls_ca_generate)
    tsv = tls_sub.add_parser("server", help="server certificate commands")
    tsv_sub = tsv.add_subparsers(dest="tls_sub_cmd", required=True)
    tsvg = tsv_sub.add_parser(
        "generate", help="generate a server cert from a CA"
    )
    tsvg.add_argument("ip", help="IP address for the subject alt name")
    tsvg.add_argument("--ca-key", required=True)
    tsvg.add_argument("--ca-cert", required=True)
    tsvg.add_argument("--output-dir", default=".")
    tsvg.set_defaults(fn=_cmd_tls_server_generate)
    tcl = tls_sub.add_parser(
        "client", help="client certificate commands (mutual TLS)"
    )
    tcl_sub = tcl.add_subparsers(dest="tls_sub_cmd", required=True)
    tclg = tcl_sub.add_parser(
        "generate", help="generate a client cert from a CA"
    )
    tclg.add_argument("--ca-key", required=True)
    tclg.add_argument("--ca-cert", required=True)
    tclg.add_argument("--output-dir", default=".")
    tclg.set_defaults(fn=_cmd_tls_client_generate)
    return p


def _cmd_devcluster(args) -> int:
    """`corro-devcluster simple <topology>` analog: run the topology file
    as one simulated cluster behind the HTTP API + admin socket
    (`corro-devcluster/src/main.rs:104-216`)."""
    from corro_sim.admin import AdminServer
    from corro_sim.api.http import ApiServer
    from corro_sim.harness.devcluster import TopologyError, build_cluster
    from corro_sim.utils.runtime import Tripwire, wait_for_all_pending_handles

    tripwire = Tripwire.new_signals()
    with open(args.topology) as f:
        topo_text = f.read()
    with open(args.schema) as f:
        schema_sql = f.read()
    try:
        cluster, ordinals = build_cluster(
            topo_text,
            schema_sql,
            state_dir=args.statedir,
            seed=args.seed,
            default_capacity=args.capacity,
            tripwire=tripwire,
        )
    except TopologyError as e:
        print(str(e), file=sys.stderr)
        return 2
    host, _, port = args.api_addr.partition(":")
    api = ApiServer(
        cluster,
        host=host or "127.0.0.1",
        port=int(port or 0),
        tick_interval=args.tick_interval or None,
    ).start()
    admin = AdminServer(cluster, args.admin_path).start()
    print(
        json.dumps(
            {
                "api": api.url,
                "admin": args.admin_path,
                "nodes": ordinals,
            }
        ),
        flush=True,
    )
    try:
        tripwire.wait()
    finally:
        api.close()
        admin.close()
        wait_for_all_pending_handles(timeout=10)
    return 0


def _cmd_reload(args) -> int:
    """`corrosion reload` analog: re-apply schema files through the
    migrations endpoint (`corrosion/src/command/reload.rs`)."""
    client = _client(args)
    plan = client.schema_from_paths(args.schema_files)
    print(json.dumps(plan))
    return 0


def _cmd_flight(args) -> int:
    """Dump the agent's flight-recorder timeline (or just diagnostics).

    With a positional PATH, the timeline is read from an ND-JSON
    export on disk instead — the fleet-observatory workflow: every
    per-lane file a `sweep --flight-dir` demuxed loads here with the
    full diagnostics/timeline surface, no agent required."""
    if args.path:
        from corro_sim.obs.flight import FlightRecorder

        try:
            fl = FlightRecorder.load(args.path)
        except OSError as e:
            print(f"error: cannot read flight export "
                  f"{args.path!r}: {e}", file=sys.stderr)
            return 2
        tl = fl.timeline()
        if not (tl["meta"] or tl["rounds"] or tl["events"]):
            # load() tolerates unparseable lines (the torn-tail case),
            # so a non-NDJSON file decodes to nothing — say so instead
            # of printing an empty timeline with rc 0
            print(f"error: no flight records in {args.path!r} "
                  "(not a flight ND-JSON export?)", file=sys.stderr)
            return 2
        if args.export:
            fl.dump(args.export)
        if args.diag:
            out = {"diagnostics": fl.diagnostics()}
        else:
            out = fl.timeline(last_rounds=args.n)
        if args.export:
            out["exported"] = args.export
        return _print_json(out)
    return _print_json(
        _admin(args).call(
            "flight", n=args.n, diag_only=args.diag, export=args.export
        )
    )


def _cmd_probes(args) -> int:
    """Dump the agent's probe provenance / lag observatory."""
    return _print_json(
        _admin(args).call(
            "probes", lag_only=args.lag, top=args.top, export=args.export
        )
    )


def _cmd_traces(args) -> int:
    """Dump recent spans from the agent's tracer."""
    return _print_json(
        _admin(args).call(
            "traces", n=args.n, name=args.name, trace_id=args.trace_id
        )
    )


def _cmd_db_lock(args) -> int:
    """`corrosion db lock "cmd"` analog (``main.rs:492-530``): hold the
    cluster write lock while a shell command runs."""
    import shlex
    import subprocess
    import time as _time

    admin = _admin(args)
    t0 = _time.perf_counter()
    resp = admin.call("db_lock_acquire", timeout=args.timeout)
    token = resp["token"]
    print(f"lock acquired after {_time.perf_counter() - t0:.3f}s "
          f"(token {token})", file=sys.stderr)
    try:
        argv = shlex.split(args.cmd)
        exit_code = subprocess.run(argv).returncode
    finally:
        from corro_sim.admin import AdminError

        try:
            rel = admin.call("db_lock_release", token=token)
        except AdminError as e:
            if "unknown db lock token" not in str(e):
                raise  # a real admin failure, not an expired hold
            # the holder pruned the token itself: the hold expired
            rel = {"expired": True}
    if rel.get("expired"):
        print(
            "WARNING: the lock auto-released (timeout "
            f"{args.timeout}s) BEFORE the command finished — its tail ran "
            "unprotected; re-run with a larger --timeout",
            file=sys.stderr,
        )
        return exit_code or 1
    return exit_code


def _write_pem(path, content) -> None:
    import os

    with open(path, "w") as f:
        f.write(content)
    os.chmod(path, 0o600)
    print(f"wrote {path}")


def _cmd_tls_ca_generate(args) -> int:
    """`corrosion tls ca generate` (command/tls.rs:7-28): ca_cert.pem +
    ca_key.pem in the output dir."""
    import os

    from corro_sim.tls import generate_ca

    cert, key = generate_ca()
    _write_pem(os.path.join(args.output_dir, "ca_cert.pem"), cert)
    _write_pem(os.path.join(args.output_dir, "ca_key.pem"), key)
    return 0


def _cmd_tls_server_generate(args) -> int:
    """`corrosion tls server generate <ip>` (command/tls.rs:30-62)."""
    import os

    from corro_sim.tls import generate_server_cert

    with open(args.ca_cert) as f:
        ca_cert = f.read()
    with open(args.ca_key) as f:
        ca_key = f.read()
    cert, key = generate_server_cert(ca_cert, ca_key, args.ip)
    _write_pem(os.path.join(args.output_dir, "server_cert.pem"), cert)
    _write_pem(os.path.join(args.output_dir, "server_key.pem"), key)
    return 0


def _cmd_tls_client_generate(args) -> int:
    """`corrosion tls client generate` (command/tls.rs:64-96)."""
    import os

    from corro_sim.tls import generate_client_cert

    with open(args.ca_cert) as f:
        ca_cert = f.read()
    with open(args.ca_key) as f:
        ca_key = f.read()
    cert, key = generate_client_cert(ca_cert, ca_key)
    _write_pem(os.path.join(args.output_dir, "client_cert.pem"), cert)
    _write_pem(os.path.join(args.output_dir, "client_key.pem"), key)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # stdout piped into a pager/head that exited — standard CLI manners
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0


if __name__ == "__main__":
    sys.exit(main())
