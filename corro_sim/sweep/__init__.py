"""Fleet-of-clusters sweep engine: the whole chaos matrix in ONE dispatch.

The ROADMAP giga-sweep: every axis the simulator explores — chaos
scenarios, seeds, link-fault knobs, coupled workloads — used to run one
``run_sim`` at a time through the serial soak loop. This package stacks
the scan carry over a leading lane axis, ``vmap``s the exact serial step
body, and races dozens of simulated clusters per device in one jitted
program, the way SWARM (PAPERS.md) characterizes replication latency
across whole load envelopes instead of single points:

- :mod:`knobs` — per-lane fault parameters as carry data (the
  ``sweep_knobs`` registry feature leaf; non-sweeping configs stay
  byte-identical, the engine/features.py contract);
- :mod:`plan` — the grid grammar (``scenario=... seed=0..31
  knob.loss=...``), all-errors-at-once validation, and the union
  program's static gates;
- :mod:`engine` — the lane-batched dispatch loop: per-lane convergence
  via the serial rule, bit-freeze for settled lanes, per-lane
  scorecards/invariants batched over the lane axis;
- :mod:`frontier` — worst/p95-over-seeds resilience frontier with
  arg-max worst-seed repro commands and quantile threshold gating.

Surfaces: ``corro-sim sweep`` (grid spec → frontier artifact, exit 6 on
threshold breach), ``corro-sim soak`` (now a thin wrapper over the
sweep engine; ``--serial`` keeps the sequential loop and ``--resume``),
bench config 8 (clusters/sec/device), and the t1.yml chaos-matrix leg.
See doc/sweeping.md.
"""

# Lazy exports: engine/state.py imports corro_sim.sweep.knobs at import
# time (leaf registration), which initializes THIS package — an eager
# `from .engine import ...` here would re-enter engine/state mid-import.
from corro_sim.sweep.knobs import (  # noqa: F401  (registration + re-export)
    SIM_KNOB_FIELDS,
    SWEEP_KNOB_FIELDS,
    lane_knobs,
    neutral_knobs,
)

__all__ = [
    "SIM_KNOB_FIELDS",
    "SWEEP_KNOB_FIELDS",
    "LaneResult",
    "SweepLane",
    "SweepPlan",
    "SweepResult",
    "build_frontier",
    "build_plan",
    "check_frontier",
    "lane_knobs",
    "neutral_knobs",
    "parse_grid",
    "run_sweep",
    "sweep_runner",
]

_LAZY = {
    "LaneResult": "corro_sim.sweep.engine",
    "SweepResult": "corro_sim.sweep.engine",
    "run_sweep": "corro_sim.sweep.engine",
    "sweep_runner": "corro_sim.sweep.engine",
    "build_frontier": "corro_sim.sweep.frontier",
    "check_frontier": "corro_sim.sweep.frontier",
    "SweepLane": "corro_sim.sweep.plan",
    "SweepPlan": "corro_sim.sweep.plan",
    "build_plan": "corro_sim.sweep.plan",
    "parse_grid": "corro_sim.sweep.plan",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(mod), name)
