"""The fleet-of-clusters dispatch loop: L simulated clusters per program.

``run_sweep`` stacks every lane's :class:`SimState` along a leading lane
axis, ``jax.vmap``s the exact scan body the serial driver iterates
(:func:`corro_sim.engine.step.make_step` /
:func:`~corro_sim.engine.step.make_workload_step` — never a parallel
implementation), and drives chunks of rounds through ONE jitted program.
Per-lane scenario schedules, workload schedules and PRNG roots ride the
scan inputs stacked to ``(L, chunk, ...)``; per-lane fault knobs ride
the ``sweep_knobs`` feature leaf in the carry
(:mod:`corro_sim.sweep.knobs`).

Bit-identity contract (tests/test_sweep.py): every lane's final state,
metric series and resilience scorecard equal its serial ``run_sim``
twin's, because

- the per-lane key streams are the serial streams verbatim
  (``fold_in(PRNGKey(lane_seed), chunk_index)``, split per round);
- traced-knob expressions are the constant expressions with traced
  operands — same values, different program;
- a lane whose twin never traces some fault machinery carries
  value-neutral knobs, which the vacuity guards prove bit-identical;
- ``lax.cond`` under a batched predicate lowers to select — both
  branches run, the untaken one is discarded, values unchanged;
- the sweep always runs the FULL step program: the twin's post-quiesce
  repair specialization is bit-for-bit equivalent under its
  precondition (tests/test_pipeline.py pins it), so program choice
  cannot diverge results.

Convergence is judged host-side between chunks with the serial rule
(:func:`corro_sim.engine.driver.converged_at`) applied per lane; a
converged or poisoned lane FREEZES — the next dispatch carries its
state through ``jnp.where(active, new, old)`` untouched, bit-frozen at
its convergence chunk's boundary, exactly where its twin stopped. The
dispatch loop exits when every lane has settled or the round budget is
spent.

Mesh composition (PR 8): lanes are embarrassingly parallel, so a device
mesh shards the LANE axis (``sweep_state_shardings`` — sweep on one
mesh axis, nodes optionally on the other); GSPMD partitions the batch
dimension without a single collective.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from corro_sim.engine.driver import converged_at
from corro_sim.engine.state import init_state
from corro_sim.engine.step import make_step, make_workload_step
from corro_sim.obs.lanes import (
    publish_sweep_progress,
    publish_sweep_result,
)
from corro_sim.utils.compile_cache import CompileCacheProbe
from corro_sim.utils.metrics import (
    ROUNDS_BUCKETS,
    SWEEP_LANES_ACTIVE,
    SWEEP_LANES_ACTIVE_HELP,
    SWEEP_LANES_CONVERGED,
    SWEEP_LANES_CONVERGED_HELP,
    SWEEP_LANES_POISONED,
    SWEEP_LANES_POISONED_HELP,
    SWEEP_RECOVERY_ROUNDS,
    SWEEP_RECOVERY_ROUNDS_HELP,
    SWEEP_WASTED_LANE_ROUNDS_TOTAL,
    SWEEP_WASTED_LANE_ROUNDS_HELP,
    counters,
    gauges,
    histograms,
)
from corro_sim.utils.tracing import tracer
from corro_sim.workload.generators import empty_slice

__all__ = ["LaneResult", "SweepResult", "run_sweep", "sweep_chunk_args"]

# Collective-budget contract (analysis/contracts.py, checked by
# `corro-sim audit --contracts`): lanes are independent clusters, so
# the sweep-mesh program must contain ZERO collectives — explicit
# (jaxpr/StableHLO) AND GSPMD-inserted (compiled HLO): the lane axis is
# pure batch data-parallelism, and any collective appearing in the
# partitioned program means a lane coupled to another lane, which
# breaks the bit-identical-to-serial-twin contract above.
SWEEP_MESH_COLLECTIVES: dict[str, int] = {}


@dataclasses.dataclass
class LaneResult:
    """One lane's serial-equivalent outcome."""

    index: int
    spec: str
    seed: int
    cell: str  # frontier cell key (spec + knob suffix)
    converged_round: int | None
    rounds: int  # rounds this lane executed before freezing
    poisoned: bool
    heal_round: int | None
    recovery_rounds: int | None
    metrics: dict  # name -> (rounds,) np arrays, the twin's series
    resilience: dict | None
    invariants: dict | None
    repro_cmd: str
    state: object = None  # final per-lane SimState slice (device arrays)


@dataclasses.dataclass
class SweepResult:
    lanes: list
    rounds: int  # rounds the longest-running lane executed
    dispatches: int
    wall_seconds: float
    compile_seconds: float
    devices: int
    compile_cache: dict | None = None
    chunk: int = 16  # the dispatch chunk — chunk-boundary semantics of
    # the demuxed lane flights (corro_sim/obs/lanes.py) depend on it
    occupancy: list | None = None  # per-dispatch lane-state history:
    # {chunk, base, rounds, lanes_active, lanes_frozen, lanes_poisoned,
    # wasted_lane_rounds} — fleet_occupancy() derives the curve/waste
    # totals that motivate on-device lane freezing (ROADMAP)

    @property
    def clusters_per_second_per_device(self) -> float | None:
        if self.wall_seconds <= 0:
            return None
        return len(self.lanes) / self.wall_seconds / max(self.devices, 1)

    @property
    def ok(self) -> bool:
        return all(
            lane.converged_round is not None and not lane.poisoned
            and (lane.invariants or {}).get("ok", True)
            for lane in self.lanes
        )


def _stack(trees):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def _lane_slice(state, lane: int):
    """One lane's SimState view off the stacked carry (device-side
    slices — consumers np.asarray only the leaves they touch)."""
    return jax.tree.map(lambda x: x[lane], state)


def build_lane_states(plan):
    """The stacked ``(L, ...)`` carry: each lane's ``init_state`` under
    the UNION config (identical pytree structure across lanes) with its
    own seed and its own knob values swapped into the sweep leaf.

    A FORK plan (what-if forecasts, corro_sim/engine/twin.py) installs
    the fork token's state over every lane's template first — the same
    ``SimCheckpoint.install_state`` merge the lane's serial twin
    (``run_sim(resume=token.refit(...))``) performs, so the warm-start
    carries are byte-identical by construction; feature leaves the token
    scrubbed (probe/burst placeholders, registry features) stay at their
    per-lane init values on both sides."""
    states = []
    for lane in plan.lanes:
        st = init_state(plan.union_cfg, seed=lane.seed)
        if plan.fork is not None:
            st = plan.fork.install_state(st)
        feats = dict(st.features)
        feats["sweep_knobs"] = {
            k: jnp.asarray(v) for k, v in lane.knobs.items()
        }
        states.append(st.replace(features=feats))
    return _stack(states)


def sweep_runner(cfg, workload: bool = False):
    """The jitted lane-batched chunk program: vmapped scan over the
    exact serial body + the freeze select + packed metric stacks (the
    driver's two-read-per-chunk discipline, lane axis added)."""
    body = make_workload_step(cfg) if workload else make_step(cfg)
    meta: dict = {}

    def lane(state, xs):
        return jax.lax.scan(body, state, xs)

    @jax.jit
    def run_chunk(state, active, keys, alive, part, we, *wl):
        out, m = jax.vmap(lane)(state, (keys, alive, part, we, *wl))

        def freeze(new, old):
            mask = active.reshape(active.shape + (1,) * (new.ndim - 1))
            return jnp.where(mask, new, old)

        # a settled (converged/poisoned) lane is BIT-FROZEN: its carry
        # rides through unchanged, exactly the state its serial twin
        # returned when it stopped
        out = jax.tree.map(freeze, out, state)
        fkeys = sorted(k for k in m if m[k].dtype == jnp.float32)
        ikeys = sorted(k for k in m if k not in fkeys)
        # deliberate trace-time side channel: the packed-stack key order
        # is a pure function of cfg, identical on every (re)trace — the
        # driver's packed-metric idiom with a lane axis
        meta["fkeys"], meta["ikeys"] = fkeys, ikeys  # corro-lint: ignore[CL105]
        i_stack = jnp.stack([m[k].astype(jnp.int32) for k in ikeys])
        f_stack = jnp.stack([m[k].astype(jnp.float32) for k in fkeys])
        return out, i_stack, f_stack

    def unpack(i_np, f_np):
        m = {k: i_np[j] for j, k in enumerate(meta["ikeys"])}
        m.update({k: f_np[j] for j, k in enumerate(meta["fkeys"])})
        return m

    run_chunk.unpack = unpack
    return run_chunk


def sweep_chunk_args(plan, ci: int, base: int, chunk: int, roots) -> tuple:
    """Stage chunk ``ci``'s stacked scan inputs: per-lane keys, schedule
    rows and (when coupled) workload write rows, all ``(L, chunk, ...)``.
    Every lane's rows are the rows its serial twin would stage at the
    same absolute rounds — lockstep in ``base``, per-lane in content;
    the keys are the serial driver's ``fold_in(root, ci)`` verbatim.
    Returns ``(device_args, alive_rows, part_rows)`` — the host-side
    per-lane rows ride along for the post-dispatch bookkeeping."""
    cfg = plan.union_cfg
    n = cfg.num_nodes
    s = cfg.seqs_per_version
    keys, alive, part, we = [], [], [], []
    wl_cols: list = [[] for _ in range(6)]
    for lane, root in zip(plan.lanes, roots):
        keys.append(np.asarray(
            jax.random.split(jax.random.fold_in(root, ci), chunk)
        ))
        a, p, w = lane.schedule.slice(base, chunk, n)
        alive.append(a)
        part.append(p)
        we.append(w)
        if cfg.sweep.workload:
            rows = (
                lane.workload.slice(base, chunk, s)
                if lane.workload is not None
                else empty_slice(n, chunk, s)
            )
            for i, r in enumerate(rows):
                wl_cols[i].append(r)
    out = (
        jnp.asarray(np.stack(keys)),
        jnp.asarray(np.stack(alive)),
        jnp.asarray(np.stack(part)),
        jnp.asarray(np.stack(we)),
    )
    if cfg.sweep.workload:
        out += tuple(jnp.asarray(np.stack(col)) for col in wl_cols)
    # the host-side per-lane rows ride along so the post-dispatch
    # bookkeeping (scorecards/invariants) reuses them instead of
    # re-slicing every schedule a second time per chunk
    return out, alive, part


def sweep_chunk_avals(plan, chunk: int) -> tuple:
    """Aval-only ``(state, active, keys, alive, part, we, *wl)`` for
    AOT-compiling the sweep chunk program without materializing a
    single lane (tools/prime_cache.py — the persistent warm layer)."""
    cfg = plan.union_cfg
    L = plan.num_lanes
    n = cfg.num_nodes
    s = cfg.seqs_per_version
    state = jax.eval_shape(lambda: build_lane_states(plan))
    avals = (
        state,
        jax.ShapeDtypeStruct((L,), jnp.bool_),
        jax.ShapeDtypeStruct((L, chunk, 2), jnp.uint32),
        jax.ShapeDtypeStruct((L, chunk, n), jnp.bool_),
        jax.ShapeDtypeStruct((L, chunk, n), jnp.int32),
        jax.ShapeDtypeStruct((L, chunk), jnp.bool_),
    )
    if cfg.sweep.workload:
        avals += (
            jax.ShapeDtypeStruct((L, chunk, n), jnp.bool_),
            jax.ShapeDtypeStruct((L, chunk, n, s), jnp.int32),
            jax.ShapeDtypeStruct((L, chunk, n, s), jnp.int32),
            jax.ShapeDtypeStruct((L, chunk, n, s), jnp.int32),
            jax.ShapeDtypeStruct((L, chunk, n), jnp.bool_),
            jax.ShapeDtypeStruct((L, chunk, n), jnp.int32),
        )
    return avals


def run_sweep(
    plan,
    max_rounds: int = 4096,
    chunk: int = 16,
    mesh=None,
    scorecards: bool = True,
    invariants: bool = True,
    on_chunk=None,
) -> SweepResult:
    """Race the whole plan in lane-batched dispatches.

    ``mesh``: shard the lane axis over the devices
    (:func:`corro_sim.engine.sharding.sweep_state_shardings`) — lanes
    are independent, so this is pure batch data-parallelism.

    ``scorecards``/``invariants``: arm a per-lane
    :class:`~corro_sim.faults.ResilienceScorecard` /
    :class:`~corro_sim.faults.InvariantChecker`, fed each lane's own
    metric rows and schedule slices on the serial cadence (batched over
    the lane axis by slicing the stacked carry).
    """
    from corro_sim.faults import InvariantChecker, ResilienceScorecard

    cfg = plan.union_cfg
    lanes = plan.lanes
    L = len(lanes)
    roots = [jax.random.PRNGKey(lane.seed) for lane in lanes]
    cards = [
        ResilienceScorecard(
            lane.cfg, scenario=lane.scenario, workload=lane.workload,
            round_offset=plan.fork_round,
        ) if scorecards else None
        for lane in lanes
    ]
    checks = [
        InvariantChecker(lane.cfg, round_offset=plan.fork_round)
        if invariants else None
        for lane in lanes
    ]

    state = build_lane_states(plan)
    if mesh is not None:
        from corro_sim.engine.sharding import sweep_state_shardings

        state = jax.device_put(
            state, sweep_state_shardings(cfg, state, mesh)
        )
    runner = sweep_runner(cfg, workload=cfg.sweep.workload)

    active = np.ones(L, bool)
    converged = [None] * L
    poisoned = [False] * L
    lane_rounds = [0] * L
    lane_metrics: list[list] = [[] for _ in range(L)]

    compiled = None
    cache_probe = CompileCacheProbe()
    compile_seconds = 0.0
    wall = 0.0
    dispatches = 0
    rounds = 0
    ci = 0
    occupancy: list[dict] = []
    wasted_total = 0
    while active.any() and rounds < max_rounds:
        args, sched_alive, sched_part = sweep_chunk_args(
            plan, ci, rounds, chunk, roots
        )
        act = jnp.asarray(active)
        # pre-dispatch lane states: settled lanes still ride this
        # dispatch through the freeze select — their rounds are the
        # occupancy waste the fleet observatory accounts
        pre_active = int(active.sum())
        pre_poisoned = sum(poisoned)
        if ci == 0 and mesh is None:
            # AOT compile up front (compile wall separated from sim
            # wall, the driver discipline). Mesh runs stay on plain jit
            # — it auto-reshards the carry across dispatches, which the
            # unconstrained AOT executable would reject.
            t0 = time.perf_counter()
            try:
                with tracer.span("sweep aot compile", lanes=L,
                                 slow_warn=False):
                    lowered = runner.lower(state, act, *args)
                    cache_probe.begin()
                    t_c = time.perf_counter()
                    compiled = lowered.compile()
                    cache_probe.end(
                        "sweep", time.perf_counter() - t_c
                    )
            except Exception:  # AOT unsupported on some backend
                counters.inc(
                    "corro_compile_aot_fallback_total",
                    labels='{program="sweep"}',
                    help_="AOT lower/compile failures falling back to jit",
                )
            compile_seconds += time.perf_counter() - t0
        t0 = time.perf_counter()
        with tracer.span("sweep chunk", ci=ci, lanes=int(active.sum())):
            out = (compiled or runner)(state, act, *args)
            m = runner.unpack(np.asarray(out[1]), np.asarray(out[2]))
        elapsed = time.perf_counter() - t0
        if ci == 0 and compiled is None:
            # jit fallback: the first dispatch is compile+exec mixed
            compile_seconds += elapsed
        else:
            wall += elapsed
        dispatches += 1
        state = out[0]
        counters.inc(
            "corro_sweep_dispatch_total",
            help_="lane-batched sweep chunk dispatches "
                  "(corro_sim/sweep/engine.py)",
        )
        base = rounds
        rounds += chunk
        for li, lane in enumerate(lanes):
            if not active[li]:
                continue
            lm = {k: np.asarray(v[li]) for k, v in m.items()}
            lane_metrics[li].append(lm)
            lane_rounds[li] = rounds
            a, p = sched_alive[li], sched_part[li]
            lane_state = _lane_slice(state, li)
            if cards[li] is not None:
                cards[li].on_chunk(lane_state, lm, a, p, base)
            if checks[li] is not None:
                checks[li].on_chunk(lane_state, lm, a, p, base)
            if lm["log_wrapped"].any():
                poisoned[li] = True
                active[li] = False
                continue
            conv = converged_at(lm["gap"], base, chunk, lane.min_rounds)
            if conv is not None:
                converged[li] = conv
                active[li] = False
                if cards[li] is not None:
                    cards[li].on_converged(lane_state, a[-1], p[-1])
                if checks[li] is not None:
                    checks[li].on_converged(lane_state, a[-1], p[-1])
        # ---- fleet observatory bookkeeping (corro_sim/obs/lanes.py):
        # occupancy history + live lane-state metrics, all host-side
        wasted = (L - pre_active) * chunk
        wasted_total += wasted
        if wasted:
            counters.inc(
                SWEEP_WASTED_LANE_ROUNDS_TOTAL, n=wasted,
                help_=SWEEP_WASTED_LANE_ROUNDS_HELP,
            )
        occupancy.append({
            "chunk": ci,
            "base": base,
            "rounds": chunk,
            "lanes_active": pre_active,
            "lanes_frozen": L - pre_active - pre_poisoned,
            "lanes_poisoned": pre_poisoned,
            "wasted_lane_rounds": wasted,
        })
        n_active = int(active.sum())
        n_poisoned = sum(poisoned)
        n_converged = L - n_active - n_poisoned
        gauges.set(SWEEP_LANES_ACTIVE, n_active,
                   help_=SWEEP_LANES_ACTIVE_HELP)
        gauges.set(SWEEP_LANES_CONVERGED, n_converged,
                   help_=SWEEP_LANES_CONVERGED_HELP)
        gauges.set(SWEEP_LANES_POISONED, n_poisoned,
                   help_=SWEEP_LANES_POISONED_HELP)
        progress = {
            "chunk": ci,
            "rounds_done": rounds,
            "lanes_active": n_active,
            "lanes_settled": L - n_active,
            "lanes_converged": n_converged,
            "lanes_poisoned": n_poisoned,
            "wasted_lane_rounds_total": wasted_total,
            # one char per lane: A = racing, C = bit-frozen converged,
            # P = poisoned — the at-a-glance fleet state line
            "lane_states": "".join(
                "A" if active[li] else ("P" if poisoned[li] else "C")
                for li in range(L)
            ),
            "chunk_wall_s": round(elapsed, 3),
        }
        publish_sweep_progress({"lanes": L, "dispatches": ci + 1,
                                **progress})
        if on_chunk is not None:
            on_chunk(progress)
        ci += 1
    jax.block_until_ready(jax.tree.leaves(state)[0])
    histograms.observe(
        "corro_sweep_wall_seconds", wall,
        help_="whole-sweep execution wall (compile separate)",
    )

    results = []
    for li, lane in enumerate(lanes):
        metrics = (
            {
                k: np.concatenate([c[k] for c in lane_metrics[li]])
                for k in lane_metrics[li][0]
            }
            if lane_metrics[li] else {}
        )
        lane_state = _lane_slice(state, li)
        resilience = None
        if cards[li] is not None:
            resilience = cards[li].finalize(
                converged_round=(
                    None if poisoned[li] else converged[li]
                ),
                rounds=lane_rounds[li], final_state=lane_state,
            )
        heal = lane.scenario.heal_round
        conv = None if poisoned[li] else converged[li]
        results.append(LaneResult(
            index=lane.index, spec=lane.spec, seed=lane.seed,
            cell=lane.cell,
            converged_round=conv,
            rounds=lane_rounds[li],
            poisoned=poisoned[li],
            heal_round=heal,
            recovery_rounds=(
                conv - heal
                if conv is not None and heal is not None else None
            ),
            metrics=metrics,
            resilience=resilience,
            invariants=(
                checks[li].report() if checks[li] is not None else None
            ),
            repro_cmd=lane.repro_cmd(
                plan.base_cfg, plan.rounds, plan.write_rounds,
                max_rounds, chunk, fork_path=plan.fork_path,
            ),
            state=lane_state,
        ))
    for lr in results:
        if lr.recovery_rounds is not None:
            # the per-cell recovery distribution the frontier quantiles
            # summarize, scrape-visible (corro_sweep_recovery_rounds)
            histograms.observe(
                SWEEP_RECOVERY_ROUNDS, float(lr.recovery_rounds),
                labels=f'{{cell="{lr.cell}"}}',
                help_=SWEEP_RECOVERY_ROUNDS_HELP,
                buckets=ROUNDS_BUCKETS,
            )
    n_poisoned = sum(poisoned)
    n_converged = sum(
        1 for li in range(L)
        if converged[li] is not None and not poisoned[li]
    )
    publish_sweep_result({
        "lanes": L,
        "rounds": rounds,
        "dispatches": dispatches,
        "wall_seconds": round(wall, 3),
        "compile_seconds": round(compile_seconds, 3),
        "lanes_converged": n_converged,
        "lanes_poisoned": n_poisoned,
        "lanes_unsettled": L - n_converged - n_poisoned,
        "wasted_lane_rounds_total": wasted_total,
        "lane_states": "".join(
            "P" if poisoned[li]
            else ("C" if converged[li] is not None else "A")
            for li in range(L)
        ),
        "projected": plan.fork is not None,
    })
    return SweepResult(
        lanes=results,
        rounds=rounds,
        dispatches=dispatches,
        wall_seconds=wall,
        compile_seconds=compile_seconds,
        devices=(mesh.size if mesh is not None else 1),
        compile_cache=cache_probe.summary(),
        chunk=chunk,
        occupancy=occupancy,
    )
