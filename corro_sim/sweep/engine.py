"""The fleet-of-clusters dispatch loop: L simulated clusters per program.

``run_sweep`` stacks every lane's :class:`SimState` along a leading lane
axis, ``jax.vmap``s the exact scan body the serial driver iterates
(:func:`corro_sim.engine.step.make_step` /
:func:`~corro_sim.engine.step.make_workload_step` — never a parallel
implementation), and drives chunks of rounds through ONE jitted program.
Per-lane scenario schedules, workload schedules and PRNG roots ride the
scan inputs stacked to ``(L, chunk, ...)``; per-lane fault knobs ride
the ``sweep_knobs`` feature leaf in the carry
(:mod:`corro_sim.sweep.knobs`).

Bit-identity contract (tests/test_sweep.py): every lane's final state,
metric series and resilience scorecard equal its serial ``run_sim``
twin's, because

- the per-lane key streams are the serial streams verbatim
  (``fold_in(PRNGKey(lane_seed), chunk_index)``, split per round);
- traced-knob expressions are the constant expressions with traced
  operands — same values, different program;
- a lane whose twin never traces some fault machinery carries
  value-neutral knobs, which the vacuity guards prove bit-identical;
- ``lax.cond`` under a batched predicate lowers to select — both
  branches run, the untaken one is discarded, values unchanged;
- the sweep always runs the FULL step program: the twin's post-quiesce
  repair specialization is bit-for-bit equivalent under its
  precondition (tests/test_pipeline.py pins it), so program choice
  cannot diverge results.

Convergence is judged host-side between chunks with the serial rule
(:func:`corro_sim.engine.driver.converged_at`) applied per lane; a
converged or poisoned lane FREEZES — the next dispatch carries its
state through ``jnp.where(active, new, old)`` untouched, bit-frozen at
its convergence chunk's boundary, exactly where its twin stopped. The
dispatch loop exits when every lane has settled or the round budget is
spent.

Mesh composition (PR 8): lanes are embarrassingly parallel, so a device
mesh shards the LANE axis (``sweep_state_shardings`` — sweep on one
mesh axis, nodes optionally on the other); GSPMD partitions the batch
dimension without a single collective.

Fleet scheduler (this PR, ``run_sweep(compact=True)``): the lockstep
loop above still DISPATCHES frozen lanes — a settled lane rides every
remaining chunk through the freeze select, burning its slot's FLOPs
(the ``wasted_frozen_lane_rounds`` before-number PR 15 committed).
``_run_compact`` turns the loop into a work-stealing slot scheduler:
each lane owns a host-side ``(ci, base)`` cursor (its OWN serial
timeline — keys are ``fold_in(PRNGKey(seed), ci)`` regardless of which
slot or batch width runs it, and ``vmap`` is value-preserving per
element, so bit-identity survives every re-pack move); at a chunk
boundary where lanes settled, survivors re-pack into a power-of-2
bucketed batch (bounded, primeable compile keys), evicted slots refill
from the pending-grid queue, and the batch shrinks only once the queue
drains (the normal tail). ``pipeline=True`` adds the PR 4 speculative
dispatch: chunk N+1 enters the device queue before chunk N's
convergence fetch lands, predicted on "no lane settles"; a mispredict
discards the speculative result and re-dispatches from the committed
carry, so committed chunks are exactly the sequential ones.
Host-side demux (obs/lanes.py) reconstructs every lane's full flight
across moves because all bookkeeping is lane-local, never slot-local.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from corro_sim.engine.driver import (
    PIPELINE_SPECULATIVE_TOTAL,
    PIPELINE_SPECULATIVE_WASTED,
    chunk_keys,
    converged_at,
)
from corro_sim.engine.state import _row_cdf, init_state
from corro_sim.engine.step import make_step, make_workload_step
from corro_sim.obs.lanes import (
    publish_sweep_progress,
    publish_sweep_result,
)
from corro_sim.utils.compile_cache import CompileCacheProbe
from corro_sim.utils.metrics import (
    ROUNDS_BUCKETS,
    SWEEP_LANES_ACTIVE,
    SWEEP_LANES_ACTIVE_HELP,
    SWEEP_LANES_CONVERGED,
    SWEEP_LANES_CONVERGED_HELP,
    SWEEP_LANES_POISONED,
    SWEEP_LANES_POISONED_HELP,
    SWEEP_RECOVERY_ROUNDS,
    SWEEP_RECOVERY_ROUNDS_HELP,
    SWEEP_WASTED_LANE_ROUNDS_TOTAL,
    SWEEP_WASTED_LANE_ROUNDS_HELP,
    counters,
    gauges,
    histograms,
)
from corro_sim.utils.runtime import start_async_fetch
from corro_sim.utils.tracing import tracer
from corro_sim.workload.generators import empty_slice

__all__ = [
    "LaneResult", "SweepResult", "run_sweep", "sweep_chunk_args",
    "sweep_slot_args", "sweep_width_avals",
]

# Collective-budget contract (analysis/contracts.py, checked by
# `corro-sim audit --contracts`): lanes are independent clusters, so
# the sweep-mesh program must contain ZERO collectives — explicit
# (jaxpr/StableHLO) AND GSPMD-inserted (compiled HLO): the lane axis is
# pure batch data-parallelism, and any collective appearing in the
# partitioned program means a lane coupled to another lane, which
# breaks the bit-identical-to-serial-twin contract above.
SWEEP_MESH_COLLECTIVES: dict[str, int] = {}


@dataclasses.dataclass
class LaneResult:
    """One lane's serial-equivalent outcome."""

    index: int
    spec: str
    seed: int
    cell: str  # frontier cell key (spec + knob suffix)
    converged_round: int | None
    rounds: int  # rounds this lane executed before freezing
    poisoned: bool
    heal_round: int | None
    recovery_rounds: int | None
    metrics: dict  # name -> (rounds,) np arrays, the twin's series
    resilience: dict | None
    invariants: dict | None
    repro_cmd: str
    state: object = None  # final per-lane SimState slice (device arrays)


@dataclasses.dataclass
class SweepResult:
    lanes: list
    rounds: int  # rounds the longest-running lane executed
    dispatches: int
    wall_seconds: float
    compile_seconds: float
    devices: int
    compile_cache: dict | None = None
    chunk: int = 16  # the dispatch chunk — chunk-boundary semantics of
    # the demuxed lane flights (corro_sim/obs/lanes.py) depend on it
    occupancy: list | None = None  # per-dispatch lane-state history:
    # {chunk, base, rounds, lanes_active, lanes_frozen, lanes_poisoned,
    # wasted_lane_rounds} — fleet_occupancy() derives the curve/waste
    # totals that motivate on-device lane freezing (ROADMAP). Compacted
    # runs add {width, pending, refills}: occupancy is then judged
    # against the BATCH width, and a drained pending queue marks the
    # normal tail (obs/doctor.py occupancy_collapse semantics)
    compaction: dict | None = None  # fleet-scheduler provenance when
    # compact dispatch ran: {widths, refills, shrinks, max_pending,
    # slot_reuse: [{dispatch, slot, admitted, prev}]} — the re-pack
    # history the bit-identity tests pin slot reuse against
    pipeline: dict | None = None  # speculative-dispatch stats when
    # pipelined: {speculative_dispatched, speculative_wasted}

    @property
    def clusters_per_second_per_device(self) -> float | None:
        if self.wall_seconds <= 0:
            return None
        return len(self.lanes) / self.wall_seconds / max(self.devices, 1)

    @property
    def ok(self) -> bool:
        return all(
            lane.converged_round is not None and not lane.poisoned
            and (lane.invariants or {}).get("ok", True)
            for lane in self.lanes
        )


def _stack(trees):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def _lane_slice(state, lane: int):
    """One lane's SimState view off the stacked carry (device-side
    slices — consumers np.asarray only the leaves they touch)."""
    return jax.tree.map(lambda x: x[lane], state)


def _lane_state(plan, lane):
    """ONE lane's fresh carry under the UNION config: its own seed, its
    own knob values swapped into the sweep leaf, and — when the lane
    sweeps ``zipf_alpha`` — its own host-precomputed ``row_cdf`` plane
    (a pure per-lane data swap; the program never changes). The compact
    scheduler calls this again at refill time, so an evicted slot's
    replacement lane starts exactly where its serial twin would."""
    st = init_state(plan.union_cfg, seed=lane.seed)
    if plan.fork is not None:
        st = plan.fork.install_state(st)
    feats = dict(st.features)
    feats["sweep_knobs"] = {
        k: jnp.asarray(v) for k, v in lane.knobs.items()
    }
    st = st.replace(features=feats)
    if lane.cfg.zipf_alpha != plan.union_cfg.zipf_alpha:
        st = st.replace(row_cdf=jnp.asarray(_row_cdf(lane.cfg)))
    return st


def build_lane_states(plan):
    """The stacked ``(L, ...)`` carry: each lane's ``init_state`` under
    the UNION config (identical pytree structure across lanes) with its
    own seed and its own knob values swapped into the sweep leaf.

    A FORK plan (what-if forecasts, corro_sim/engine/twin.py) installs
    the fork token's state over every lane's template first — the same
    ``SimCheckpoint.install_state`` merge the lane's serial twin
    (``run_sim(resume=token.refit(...))``) performs, so the warm-start
    carries are byte-identical by construction; feature leaves the token
    scrubbed (probe/burst placeholders, registry features) stay at their
    per-lane init values on both sides."""
    return _stack([_lane_state(plan, lane) for lane in plan.lanes])


def sweep_runner(cfg, workload: bool = False):
    """The jitted lane-batched chunk program: vmapped scan over the
    exact serial body + the freeze select + packed metric stacks (the
    driver's two-read-per-chunk discipline, lane axis added)."""
    body = make_workload_step(cfg) if workload else make_step(cfg)
    meta: dict = {}

    def lane(state, xs):
        return jax.lax.scan(body, state, xs)

    @jax.jit
    def run_chunk(state, active, keys, alive, part, we, *wl):
        out, m = jax.vmap(lane)(state, (keys, alive, part, we, *wl))

        def freeze(new, old):
            mask = active.reshape(active.shape + (1,) * (new.ndim - 1))
            return jnp.where(mask, new, old)

        # a settled (converged/poisoned) lane is BIT-FROZEN: its carry
        # rides through unchanged, exactly the state its serial twin
        # returned when it stopped
        out = jax.tree.map(freeze, out, state)
        fkeys = sorted(k for k in m if m[k].dtype == jnp.float32)
        ikeys = sorted(k for k in m if k not in fkeys)
        # deliberate trace-time side channel: the packed-stack key order
        # is a pure function of cfg, identical on every (re)trace — the
        # driver's packed-metric idiom with a lane axis
        meta["fkeys"], meta["ikeys"] = fkeys, ikeys  # corro-lint: ignore[CL105]
        i_stack = jnp.stack([m[k].astype(jnp.int32) for k in ikeys])
        f_stack = jnp.stack([m[k].astype(jnp.float32) for k in fkeys])
        return out, i_stack, f_stack

    def unpack(i_np, f_np):
        m = {k: i_np[j] for j, k in enumerate(meta["ikeys"])}
        m.update({k: f_np[j] for j, k in enumerate(meta["fkeys"])})
        return m

    run_chunk.unpack = unpack
    return run_chunk


def sweep_slot_args(plan, entries, chunk: int, roots) -> tuple:
    """Stage one dispatch's stacked scan inputs from a SLOT TABLE:
    ``entries`` is ``[(lane_index, lane_ci, lane_base), ...]`` — one
    per slot, each lane at its OWN chunk cursor (a compacted batch
    mixes survivors deep in their run with freshly refilled lanes at
    ci 0; a pad slot repeats a live lane's entry and its outputs are
    discarded by the freeze select). Each slot's keys are the serial
    driver's ``fold_in(root, ci)`` verbatim and its schedule/workload
    rows are sliced at the lane's own ``base`` — so a lane's input
    stream is invariant under slot assignment and batch width, which
    is the whole bit-identity argument for re-packing.
    Returns ``(device_args, alive_rows, part_rows)`` — the host-side
    per-slot rows ride along for the post-dispatch bookkeeping."""
    cfg = plan.union_cfg
    n = cfg.num_nodes
    s = cfg.seqs_per_version
    keys, alive, part, we = [], [], [], []
    wl_cols: list = [[] for _ in range(6)]
    for li, ci, base in entries:
        lane = plan.lanes[li]
        keys.append(np.asarray(chunk_keys(roots[li], ci, chunk)))
        a, p, w = lane.schedule.slice(base, chunk, n)
        alive.append(a)
        part.append(p)
        we.append(w)
        if cfg.sweep.workload:
            rows = (
                lane.workload.slice(base, chunk, s)
                if lane.workload is not None
                else empty_slice(n, chunk, s)
            )
            for i, r in enumerate(rows):
                wl_cols[i].append(r)
    out = (
        jnp.asarray(np.stack(keys)),
        jnp.asarray(np.stack(alive)),
        jnp.asarray(np.stack(part)),
        jnp.asarray(np.stack(we)),
    )
    if cfg.sweep.workload:
        out += tuple(jnp.asarray(np.stack(col)) for col in wl_cols)
    # the host-side per-slot rows ride along so the post-dispatch
    # bookkeeping (scorecards/invariants) reuses them instead of
    # re-slicing every schedule a second time per chunk
    return out, alive, part


def sweep_chunk_args(plan, ci: int, base: int, chunk: int, roots) -> tuple:
    """Stage chunk ``ci``'s stacked scan inputs: per-lane keys, schedule
    rows and (when coupled) workload write rows, all ``(L, chunk, ...)``.
    Every lane's rows are the rows its serial twin would stage at the
    same absolute rounds — lockstep in ``base``, per-lane in content;
    the keys are the serial driver's ``fold_in(root, ci)`` verbatim.
    The lockstep special case of :func:`sweep_slot_args`: every lane in
    plan order, all at the same cursor."""
    entries = [(li, ci, base) for li in range(plan.num_lanes)]
    return sweep_slot_args(plan, entries, chunk, roots)


def sweep_chunk_avals(plan, chunk: int) -> tuple:
    """Aval-only ``(state, active, keys, alive, part, we, *wl)`` for
    AOT-compiling the sweep chunk program without materializing a
    single lane (tools/prime_cache.py — the persistent warm layer)."""
    cfg = plan.union_cfg
    L = plan.num_lanes
    n = cfg.num_nodes
    s = cfg.seqs_per_version
    state = jax.eval_shape(lambda: build_lane_states(plan))
    avals = (
        state,
        jax.ShapeDtypeStruct((L,), jnp.bool_),
        jax.ShapeDtypeStruct((L, chunk, 2), jnp.uint32),
        jax.ShapeDtypeStruct((L, chunk, n), jnp.bool_),
        jax.ShapeDtypeStruct((L, chunk, n), jnp.int32),
        jax.ShapeDtypeStruct((L, chunk), jnp.bool_),
    )
    if cfg.sweep.workload:
        avals += (
            jax.ShapeDtypeStruct((L, chunk, n), jnp.bool_),
            jax.ShapeDtypeStruct((L, chunk, n, s), jnp.int32),
            jax.ShapeDtypeStruct((L, chunk, n, s), jnp.int32),
            jax.ShapeDtypeStruct((L, chunk, n, s), jnp.int32),
            jax.ShapeDtypeStruct((L, chunk, n), jnp.bool_),
            jax.ShapeDtypeStruct((L, chunk, n), jnp.int32),
        )
    return avals


def sweep_width_avals(plan, width: int, chunk: int) -> tuple:
    """``sweep_chunk_avals`` at an arbitrary lane-batch ``width`` — the
    compacted dispatch's program signature at one power-of-2 bucket.
    tools/prime_cache.py primes every bucket a grid can visit so the
    re-pack boundaries hit warm executables (the cache-keys manifest
    names them ``sweep/<grid>-w<width>``)."""
    full = sweep_chunk_avals(plan, chunk)

    def rewidth(x):
        return jax.ShapeDtypeStruct((width,) + x.shape[1:], x.dtype)

    state = jax.tree.map(rewidth, full[0])
    return (state,) + tuple(rewidth(a) for a in full[1:])


def _bucket(n: int) -> int:
    """Smallest power of two >= n — compacted lane-batch widths are
    bucketed so the compile-key set stays bounded and primeable."""
    return 1 << max(n - 1, 0).bit_length()


def run_sweep(
    plan,
    max_rounds: int = 4096,
    chunk: int = 16,
    mesh=None,
    scorecards: bool = True,
    invariants: bool = True,
    on_chunk=None,
    compact: bool = False,
    width: int | None = None,
    pipeline: bool = False,
) -> SweepResult:
    """Race the whole plan in lane-batched dispatches.

    ``mesh``: shard the lane axis over the devices
    (:func:`corro_sim.engine.sharding.sweep_state_shardings`) — lanes
    are independent, so this is pure batch data-parallelism.

    ``scorecards``/``invariants``: arm a per-lane
    :class:`~corro_sim.faults.ResilienceScorecard` /
    :class:`~corro_sim.faults.InvariantChecker`, fed each lane's own
    metric rows and schedule slices on the serial cadence (batched over
    the lane axis by slicing the stacked carry).

    ``compact``: the fleet scheduler — evict settled lanes at chunk
    boundaries, refill their slots from the pending-grid queue, and
    re-pack survivors into power-of-2 bucketed batches once the queue
    drains. ``width`` caps the lane-batch (rounded up to a bucket);
    lanes beyond it queue. ``pipeline``: speculative dispatch of chunk
    N+1 before chunk N's convergence fetch (the driver's PR 4
    protocol). Both modes keep every lane bit-identical to its serial
    twin and neither composes with ``mesh``
    (:func:`corro_sim.engine.sharding.check_compact_mesh`).
    """
    if compact or pipeline:
        from corro_sim.engine.sharding import check_compact_mesh

        check_compact_mesh(mesh)
        return _run_compact(
            plan, max_rounds=max_rounds, chunk=chunk,
            scorecards=scorecards, invariants=invariants,
            on_chunk=on_chunk, compact=compact, width=width,
            pipeline=pipeline,
        )
    from corro_sim.faults import InvariantChecker, ResilienceScorecard

    cfg = plan.union_cfg
    lanes = plan.lanes
    L = len(lanes)
    roots = [jax.random.PRNGKey(lane.seed) for lane in lanes]
    cards = [
        ResilienceScorecard(
            lane.cfg, scenario=lane.scenario, workload=lane.workload,
            round_offset=plan.fork_round,
        ) if scorecards else None
        for lane in lanes
    ]
    checks = [
        InvariantChecker(lane.cfg, round_offset=plan.fork_round)
        if invariants else None
        for lane in lanes
    ]

    state = build_lane_states(plan)
    if mesh is not None:
        from corro_sim.engine.sharding import sweep_state_shardings

        state = jax.device_put(
            state, sweep_state_shardings(cfg, state, mesh)
        )
    runner = sweep_runner(cfg, workload=cfg.sweep.workload)

    active = np.ones(L, bool)
    converged = [None] * L
    poisoned = [False] * L
    lane_rounds = [0] * L
    lane_metrics: list[list] = [[] for _ in range(L)]

    compiled = None
    cache_probe = CompileCacheProbe()
    compile_seconds = 0.0
    wall = 0.0
    dispatches = 0
    rounds = 0
    ci = 0
    occupancy: list[dict] = []
    wasted_total = 0
    while active.any() and rounds < max_rounds:
        args, sched_alive, sched_part = sweep_chunk_args(
            plan, ci, rounds, chunk, roots
        )
        act = jnp.asarray(active)
        # pre-dispatch lane states: settled lanes still ride this
        # dispatch through the freeze select — their rounds are the
        # occupancy waste the fleet observatory accounts
        pre_active = int(active.sum())
        pre_poisoned = sum(poisoned)
        if ci == 0 and mesh is None:
            # AOT compile up front (compile wall separated from sim
            # wall, the driver discipline). Mesh runs stay on plain jit
            # — it auto-reshards the carry across dispatches, which the
            # unconstrained AOT executable would reject.
            t0 = time.perf_counter()
            try:
                with tracer.span("sweep aot compile", lanes=L,
                                 slow_warn=False):
                    lowered = runner.lower(state, act, *args)
                    cache_probe.begin()
                    t_c = time.perf_counter()
                    compiled = lowered.compile()
                    cache_probe.end(
                        "sweep", time.perf_counter() - t_c
                    )
            except Exception:  # AOT unsupported on some backend
                counters.inc(
                    "corro_compile_aot_fallback_total",
                    labels='{program="sweep"}',
                    help_="AOT lower/compile failures falling back to jit",
                )
            compile_seconds += time.perf_counter() - t0
        t0 = time.perf_counter()
        with tracer.span("sweep chunk", ci=ci, lanes=int(active.sum())):
            out = (compiled or runner)(state, act, *args)
            m = runner.unpack(np.asarray(out[1]), np.asarray(out[2]))
        elapsed = time.perf_counter() - t0
        if ci == 0 and compiled is None:
            # jit fallback: the first dispatch is compile+exec mixed
            compile_seconds += elapsed
        else:
            wall += elapsed
        dispatches += 1
        state = out[0]
        counters.inc(
            "corro_sweep_dispatch_total",
            help_="lane-batched sweep chunk dispatches "
                  "(corro_sim/sweep/engine.py)",
        )
        base = rounds
        rounds += chunk
        for li, lane in enumerate(lanes):
            if not active[li]:
                continue
            lm = {k: np.asarray(v[li]) for k, v in m.items()}
            lane_metrics[li].append(lm)
            lane_rounds[li] = rounds
            a, p = sched_alive[li], sched_part[li]
            lane_state = _lane_slice(state, li)
            if cards[li] is not None:
                cards[li].on_chunk(lane_state, lm, a, p, base)
            if checks[li] is not None:
                checks[li].on_chunk(lane_state, lm, a, p, base)
            if lm["log_wrapped"].any():
                poisoned[li] = True
                active[li] = False
                continue
            conv = converged_at(lm["gap"], base, chunk, lane.min_rounds)
            if conv is not None:
                converged[li] = conv
                active[li] = False
                if cards[li] is not None:
                    cards[li].on_converged(lane_state, a[-1], p[-1])
                if checks[li] is not None:
                    checks[li].on_converged(lane_state, a[-1], p[-1])
        # ---- fleet observatory bookkeeping (corro_sim/obs/lanes.py):
        # occupancy history + live lane-state metrics, all host-side
        wasted = (L - pre_active) * chunk
        wasted_total += wasted
        if wasted:
            counters.inc(
                SWEEP_WASTED_LANE_ROUNDS_TOTAL, n=wasted,
                help_=SWEEP_WASTED_LANE_ROUNDS_HELP,
            )
        occupancy.append({
            "chunk": ci,
            "base": base,
            "rounds": chunk,
            "lanes_active": pre_active,
            "lanes_frozen": L - pre_active - pre_poisoned,
            "lanes_poisoned": pre_poisoned,
            "wasted_lane_rounds": wasted,
        })
        n_active = int(active.sum())
        n_poisoned = sum(poisoned)
        n_converged = L - n_active - n_poisoned
        gauges.set(SWEEP_LANES_ACTIVE, n_active,
                   help_=SWEEP_LANES_ACTIVE_HELP)
        gauges.set(SWEEP_LANES_CONVERGED, n_converged,
                   help_=SWEEP_LANES_CONVERGED_HELP)
        gauges.set(SWEEP_LANES_POISONED, n_poisoned,
                   help_=SWEEP_LANES_POISONED_HELP)
        progress = {
            "chunk": ci,
            "rounds_done": rounds,
            "lanes_active": n_active,
            "lanes_settled": L - n_active,
            "lanes_converged": n_converged,
            "lanes_poisoned": n_poisoned,
            "wasted_lane_rounds_total": wasted_total,
            # one char per lane: A = racing, C = bit-frozen converged,
            # P = poisoned — the at-a-glance fleet state line
            "lane_states": "".join(
                "A" if active[li] else ("P" if poisoned[li] else "C")
                for li in range(L)
            ),
            "chunk_wall_s": round(elapsed, 3),
        }
        publish_sweep_progress({"lanes": L, "dispatches": ci + 1,
                                **progress})
        if on_chunk is not None:
            on_chunk(progress)
        ci += 1
    jax.block_until_ready(jax.tree.leaves(state)[0])
    histograms.observe(
        "corro_sweep_wall_seconds", wall,
        help_="whole-sweep execution wall (compile separate)",
    )

    results = []
    for li, lane in enumerate(lanes):
        metrics = (
            {
                k: np.concatenate([c[k] for c in lane_metrics[li]])
                for k in lane_metrics[li][0]
            }
            if lane_metrics[li] else {}
        )
        lane_state = _lane_slice(state, li)
        resilience = None
        if cards[li] is not None:
            resilience = cards[li].finalize(
                converged_round=(
                    None if poisoned[li] else converged[li]
                ),
                rounds=lane_rounds[li], final_state=lane_state,
            )
        heal = lane.scenario.heal_round
        conv = None if poisoned[li] else converged[li]
        results.append(LaneResult(
            index=lane.index, spec=lane.spec, seed=lane.seed,
            cell=lane.cell,
            converged_round=conv,
            rounds=lane_rounds[li],
            poisoned=poisoned[li],
            heal_round=heal,
            recovery_rounds=(
                conv - heal
                if conv is not None and heal is not None else None
            ),
            metrics=metrics,
            resilience=resilience,
            invariants=(
                checks[li].report() if checks[li] is not None else None
            ),
            repro_cmd=lane.repro_cmd(
                plan.base_cfg, plan.rounds, plan.write_rounds,
                max_rounds, chunk, fork_path=plan.fork_path,
            ),
            state=lane_state,
        ))
    for lr in results:
        if lr.recovery_rounds is not None:
            # the per-cell recovery distribution the frontier quantiles
            # summarize, scrape-visible (corro_sweep_recovery_rounds)
            histograms.observe(
                SWEEP_RECOVERY_ROUNDS, float(lr.recovery_rounds),
                labels=f'{{cell="{lr.cell}"}}',
                help_=SWEEP_RECOVERY_ROUNDS_HELP,
                buckets=ROUNDS_BUCKETS,
            )
    n_poisoned = sum(poisoned)
    n_converged = sum(
        1 for li in range(L)
        if converged[li] is not None and not poisoned[li]
    )
    publish_sweep_result({
        "lanes": L,
        "rounds": rounds,
        "dispatches": dispatches,
        "wall_seconds": round(wall, 3),
        "compile_seconds": round(compile_seconds, 3),
        "lanes_converged": n_converged,
        "lanes_poisoned": n_poisoned,
        "lanes_unsettled": L - n_converged - n_poisoned,
        "wasted_lane_rounds_total": wasted_total,
        "lane_states": "".join(
            "P" if poisoned[li]
            else ("C" if converged[li] is not None else "A")
            for li in range(L)
        ),
        "projected": plan.fork is not None,
    })
    return SweepResult(
        lanes=results,
        rounds=rounds,
        dispatches=dispatches,
        wall_seconds=wall,
        compile_seconds=compile_seconds,
        devices=(mesh.size if mesh is not None else 1),
        compile_cache=cache_probe.summary(),
        chunk=chunk,
        occupancy=occupancy,
    )


@dataclasses.dataclass
class _SlotDispatch:
    """One dispatched-but-unprocessed compacted chunk (the sweep's
    ``_InFlight``): output futures plus the slot table that staged it —
    the commit step reads lanes back out of slots through this record,
    never through the (possibly already mutated) scheduler lists."""

    out: tuple  # (state carry, packed int stack, packed float stack)
    entries: list  # [(lane_index, lane_ci, lane_base)] per slot
    act: np.ndarray  # (W,) bool — slot activity at dispatch time
    sched_alive: list  # host-side per-slot schedule rows (bookkeeping)
    sched_part: list
    width: int
    pending_depth: int  # refill-queue depth when dispatched
    speculative: bool
    untimed: bool  # jit-fallback first dispatch at this width: the
    # commit interval is compile+exec mixed — booked as compile


def _run_compact(
    plan,
    max_rounds: int,
    chunk: int,
    scorecards: bool,
    invariants: bool,
    on_chunk,
    compact: bool,
    width: int | None,
    pipeline: bool,
) -> SweepResult:
    """The fleet scheduler: slot-table dispatch with per-lane cursors.

    Every lane owns a host-side ``(ci, base)`` cursor — its serial
    timeline. A dispatch runs whatever lanes currently hold slots, each
    at its own cursor (``sweep_slot_args``); settled lanes are evicted
    at the boundary, their slots refill from the pending queue, and the
    batch shrinks to a smaller power-of-2 bucket once the queue drains.
    ``pipeline`` overlays the driver's PR 4 speculative dispatch.
    Bit-identity to the serial twin holds per lane because vmap is
    value-preserving per element and the lane's inputs depend only on
    its own cursor — never on slot index, batch width, or dispatch
    order."""
    from corro_sim.faults import InvariantChecker, ResilienceScorecard

    cfg = plan.union_cfg
    lanes = plan.lanes
    L = len(lanes)
    roots = [jax.random.PRNGKey(lane.seed) for lane in lanes]
    cards = [
        ResilienceScorecard(
            lane.cfg, scenario=lane.scenario, workload=lane.workload,
            round_offset=plan.fork_round,
        ) if scorecards else None
        for lane in lanes
    ]
    checks = [
        InvariantChecker(lane.cfg, round_offset=plan.fork_round)
        if invariants else None
        for lane in lanes
    ]
    runner = sweep_runner(cfg, workload=cfg.sweep.workload)

    # ---- per-lane scheduler state (all indexed by PLAN lane, not slot)
    lane_ci = [0] * L
    lane_base = [0] * L
    converged: list = [None] * L
    poisoned = [False] * L
    lane_rounds = [0] * L
    lane_metrics: list[list] = [[] for _ in range(L)]
    final_states: list = [None] * L

    # ---- the slot table: initial admission + pending queue
    if compact:
        W = _bucket(min(width, L)) if width else _bucket(L)
    else:
        W = L  # fixed full width — pipelined dispatch only
    slots = list(range(min(W, L)))
    slot_active = [True] * len(slots)
    pending: deque = deque(range(len(slots), L))
    while len(slots) < W:  # pad the first bucket up to its width
        slots.append(slots[0])
        slot_active.append(False)
    state = _stack([_lane_state(plan, lanes[li]) for li in slots])

    compiled: dict[int, object] = {}  # width -> AOT executable (None
    # after a fallback — that width runs through plain jit)
    jit_paid: set[int] = set()
    cache_probe = CompileCacheProbe()
    compile_seconds = 0.0
    compile_pending = 0.0  # in-dispatch blocking compile to subtract
    # from the next commit interval (the driver's pipelined-loop books)
    wall = 0.0
    dispatches = 0
    occupancy: list[dict] = []
    wasted_total = 0
    refills_total = 0
    shrinks = 0
    slot_reuse: list[dict] = []
    widths_used: list[int] = []
    max_pending = len(pending)
    spec_dispatched = 0
    spec_wasted = 0

    def _dispatch(st, entries, act_list, speculative) -> _SlotDispatch:
        nonlocal compile_seconds, compile_pending
        Wd = len(entries)
        args, sa, sp = sweep_slot_args(plan, entries, chunk, roots)
        act = jnp.asarray(np.asarray(act_list, bool))
        if Wd not in compiled:
            # AOT compile once per bucket width (compile wall separated
            # from sim wall, the driver discipline)
            t0 = time.perf_counter()
            ex = None
            try:
                with tracer.span("sweep aot compile", lanes=Wd,
                                 slow_warn=False):
                    lowered = runner.lower(st, act, *args)
                    cache_probe.begin()
                    t_c = time.perf_counter()
                    ex = lowered.compile()
                    cache_probe.end("sweep", time.perf_counter() - t_c)
            except Exception:  # AOT unsupported on some backend
                counters.inc(
                    "corro_compile_aot_fallback_total",
                    labels='{program="sweep"}',
                    help_="AOT lower/compile failures falling back to jit",
                )
            compiled[Wd] = ex
            dt = time.perf_counter() - t0
            compile_seconds += dt
            compile_pending += dt
        first_jit = compiled[Wd] is None and Wd not in jit_paid
        t0 = time.perf_counter()
        with tracer.span("sweep chunk", width=Wd,
                         lanes=int(np.asarray(act_list, bool).sum()),
                         slow_warn=False):
            out = (compiled[Wd] or runner)(st, act, *args)
        if first_jit:
            # jit fallback: the first call at this width traces and
            # compiles synchronously inside the dispatch
            jit_paid.add(Wd)
            blocked = time.perf_counter() - t0
            compile_seconds += blocked
            compile_pending += blocked
        counters.inc(
            "corro_sweep_dispatch_total",
            help_="lane-batched sweep chunk dispatches "
                  "(corro_sim/sweep/engine.py)",
        )
        # metric fetch rides under whatever the host does next
        # (speculation, bookkeeping) — the PR 4 async-fetch half
        start_async_fetch(out[1], out[2])
        return _SlotDispatch(
            out=out, entries=list(entries),
            act=np.asarray(act_list, bool),
            sched_alive=sa, sched_part=sp, width=Wd,
            pending_depth=len(pending), speculative=speculative,
            untimed=first_jit,
        )

    def _entries_now():
        return [(li, lane_ci[li], lane_base[li]) for li in slots]

    inflight = (
        _dispatch(state, _entries_now(), slot_active, False)
        if slots and max_rounds > 0 else None
    )
    last_commit = time.perf_counter()
    compile_pending = 0.0  # dispatch 0's compile predates the clock
    di = 0
    while inflight is not None:
        out = inflight.out
        # ---- speculate chunk N+1 while N's metrics are in flight:
        # prediction = "no lane settles this chunk" (slot table and
        # active mask unchanged, every active cursor one chunk ahead).
        # Guarded on the round budget: if any active lane's NEXT base
        # would be out of budget, the real next dispatch differs by
        # construction — don't waste the speculation.
        spec = None
        if pipeline and any(inflight.act) and all(
            base + chunk < max_rounds
            for (_, _, base), a in zip(inflight.entries, inflight.act)
            if a
        ):
            spec_entries = [
                (li, ci + (1 if a else 0), base + (chunk if a else 0))
                for (li, ci, base), a
                in zip(inflight.entries, inflight.act)
            ]
            spec = _dispatch(out[0], spec_entries,
                             list(inflight.act), True)
            spec_dispatched += 1
            counters.inc(
                PIPELINE_SPECULATIVE_TOTAL,
                help_="chunks dispatched before the previous chunk's "
                      "convergence scalar landed",
            )
        # ---- resolve + commit strictly in order
        m = runner.unpack(np.asarray(out[1]), np.asarray(out[2]))
        now = time.perf_counter()
        elapsed = max(now - last_commit - compile_pending, 0.0)
        last_commit = now
        compile_pending = 0.0
        if inflight.untimed:
            compile_seconds += elapsed
        else:
            wall += elapsed
        dispatches += 1
        state = out[0]
        W_d = inflight.width
        if W_d not in widths_used:
            widths_used.append(W_d)
        pre_active = int(inflight.act.sum())
        pre_pois = sum(poisoned)
        pre_conv = sum(
            1 for li in range(L)
            if converged[li] is not None and not poisoned[li]
        )

        settled: list[int] = []  # slot indices that settled this chunk
        for si, ((li, ci_, base), a) in enumerate(
            zip(inflight.entries, inflight.act)
        ):
            if not a:
                continue
            lane = lanes[li]
            lm = {k: np.asarray(v[si]) for k, v in m.items()}
            lane_metrics[li].append(lm)
            lane_ci[li] = ci_ + 1
            lane_base[li] = base + chunk
            lane_rounds[li] = base + chunk
            arow = inflight.sched_alive[si]
            prow = inflight.sched_part[si]
            lane_state = _lane_slice(state, si)
            if cards[li] is not None:
                cards[li].on_chunk(lane_state, lm, arow, prow, base)
            if checks[li] is not None:
                checks[li].on_chunk(lane_state, lm, arow, prow, base)
            if lm["log_wrapped"].any():
                poisoned[li] = True
                final_states[li] = lane_state
                settled.append(si)
                continue
            conv = converged_at(lm["gap"], base, chunk, lane.min_rounds)
            if conv is not None:
                converged[li] = conv
                if cards[li] is not None:
                    cards[li].on_converged(lane_state, arow[-1], prow[-1])
                if checks[li] is not None:
                    checks[li].on_converged(lane_state, arow[-1],
                                            prow[-1])
                final_states[li] = lane_state
                settled.append(si)
            elif lane_base[li] >= max_rounds:
                # round budget spent unsettled: evict — the serial twin
                # stops here too; stays "A" (unsettled) fleet-wide
                final_states[li] = lane_state
                settled.append(si)

        # ---- occupancy accounting: judged against the BATCH width
        wasted = (W_d - pre_active) * chunk
        wasted_total += wasted
        if wasted:
            counters.inc(
                SWEEP_WASTED_LANE_ROUNDS_TOTAL, n=wasted,
                help_=SWEEP_WASTED_LANE_ROUNDS_HELP,
            )
        occupancy.append({
            "chunk": di,
            "base": min(
                (base for (_, _, base), a
                 in zip(inflight.entries, inflight.act) if a),
                default=0,
            ),
            "rounds": chunk,
            "lanes_active": pre_active,
            "lanes_frozen": pre_conv,
            "lanes_poisoned": pre_pois,
            "wasted_lane_rounds": wasted,
            "width": W_d,
            "pending": inflight.pending_depth,
            "refills": 0,
        })

        # ---- boundary: evict settled slots, refill, maybe shrink
        refill_count = 0
        if settled and not compact:
            # fixed width: settled lanes bit-freeze in place (the
            # lockstep rule) — only the active mask changes
            for si in settled:
                slot_active[si] = False
        elif settled:
            old_slots = list(slots)
            new_slots = list(slots)
            new_active = list(slot_active)
            for si in settled:
                new_active[si] = False
            # refill evicted slots IN PLACE from the pending queue —
            # the work-stealing move the slot_reuse ledger records
            admits: dict[int, int] = {}
            for si in range(len(old_slots)):
                if new_active[si] or not pending:
                    continue
                admits[si] = pending.popleft()
            if admits:
                parts = []
                for si in range(len(old_slots)):
                    if si in admits:
                        li = admits[si]
                        parts.append(_lane_state(plan, lanes[li]))
                        new_slots[si] = li
                        new_active[si] = True
                        refill_count += 1
                        slot_reuse.append({
                            "dispatch": di, "slot": si, "admitted": li,
                            "prev": old_slots[si],
                        })
                    else:
                        parts.append(_lane_slice(state, si))
                state = _stack(parts)
            elif not pending:
                # queue drained: shrink survivors into the smallest
                # bucket that holds them (the normal tail)
                live = [
                    si for si in range(len(old_slots)) if new_active[si]
                ]
                nb = _bucket(len(live)) if live else 0
                if nb and nb < len(old_slots):
                    shrinks += 1
                    parts = [_lane_slice(state, si) for si in live]
                    new_slots = [old_slots[si] for si in live]
                    new_active = [True] * len(live)
                    while len(parts) < nb:
                        parts.append(parts[0])
                        new_slots.append(new_slots[0])
                        new_active.append(False)
                    state = _stack(parts)
                elif not live:
                    new_slots, new_active = [], []
            slots, slot_active = new_slots, new_active
            refills_total += refill_count
            occupancy[-1]["refills"] = refill_count
        max_pending = max(max_pending, len(pending))

        # ---- live fleet telemetry (the lockstep loop's exact gauges)
        n_pois = sum(poisoned)
        n_conv = sum(
            1 for li in range(L)
            if converged[li] is not None and not poisoned[li]
        )
        n_slot_active = sum(slot_active)
        gauges.set(SWEEP_LANES_ACTIVE, n_slot_active,
                   help_=SWEEP_LANES_ACTIVE_HELP)
        gauges.set(SWEEP_LANES_CONVERGED, n_conv,
                   help_=SWEEP_LANES_CONVERGED_HELP)
        gauges.set(SWEEP_LANES_POISONED, n_pois,
                   help_=SWEEP_LANES_POISONED_HELP)
        pending_set = set(pending)
        progress = {
            "chunk": di,
            "rounds_done": max(lane_rounds, default=0),
            "lanes_active": n_slot_active,
            "lanes_queued": len(pending),
            "lanes_settled": n_conv + n_pois,
            "lanes_converged": n_conv,
            "lanes_poisoned": n_pois,
            "wasted_lane_rounds_total": wasted_total,
            # one char per PLAN lane: A = racing (or unsettled at
            # budget), Q = queued, C = converged, P = poisoned
            "lane_states": "".join(
                "P" if poisoned[li]
                else "C" if converged[li] is not None
                else "Q" if li in pending_set
                else "A"
                for li in range(L)
            ),
            "chunk_wall_s": round(elapsed, 3),
            "width": W_d,
            "pending": len(pending),
            "refills": refill_count,
        }
        publish_sweep_progress({"lanes": L, "dispatches": di + 1,
                                **progress})
        if on_chunk is not None:
            on_chunk(progress)
        di += 1

        # ---- promote the speculative dispatch, or discard and
        # re-dispatch from the committed carry (mispredict)
        fleet_live = any(slot_active)
        if spec is not None and (settled or not fleet_live):
            spec_wasted += 1
            counters.inc(
                PIPELINE_SPECULATIVE_WASTED,
                labels='{reason="lane_settled"}',
                help_="speculative chunk results discarded, by reason",
            )
            spec = None
        if not fleet_live:
            inflight = None
        elif spec is not None:
            inflight = spec
        else:
            inflight = _dispatch(state, _entries_now(), slot_active,
                                 False)

    jax.block_until_ready(jax.tree.leaves(state)[0])
    histograms.observe(
        "corro_sweep_wall_seconds", wall,
        help_="whole-sweep execution wall (compile separate)",
    )

    rounds_total = max(lane_rounds, default=0)
    results = []
    for li, lane in enumerate(lanes):
        metrics = (
            {
                k: np.concatenate([c[k] for c in lane_metrics[li]])
                for k in lane_metrics[li][0]
            }
            if lane_metrics[li] else {}
        )
        lane_state = final_states[li]
        resilience = None
        if cards[li] is not None and lane_state is not None:
            resilience = cards[li].finalize(
                converged_round=(
                    None if poisoned[li] else converged[li]
                ),
                rounds=lane_rounds[li], final_state=lane_state,
            )
        heal = lane.scenario.heal_round
        conv = None if poisoned[li] else converged[li]
        results.append(LaneResult(
            index=lane.index, spec=lane.spec, seed=lane.seed,
            cell=lane.cell,
            converged_round=conv,
            rounds=lane_rounds[li],
            poisoned=poisoned[li],
            heal_round=heal,
            recovery_rounds=(
                conv - heal
                if conv is not None and heal is not None else None
            ),
            metrics=metrics,
            resilience=resilience,
            invariants=(
                checks[li].report() if checks[li] is not None else None
            ),
            repro_cmd=lane.repro_cmd(
                plan.base_cfg, plan.rounds, plan.write_rounds,
                max_rounds, chunk, fork_path=plan.fork_path,
            ),
            state=lane_state,
        ))
    for lr in results:
        if lr.recovery_rounds is not None:
            histograms.observe(
                SWEEP_RECOVERY_ROUNDS, float(lr.recovery_rounds),
                labels=f'{{cell="{lr.cell}"}}',
                help_=SWEEP_RECOVERY_ROUNDS_HELP,
                buckets=ROUNDS_BUCKETS,
            )
    n_poisoned = sum(poisoned)
    n_converged = sum(
        1 for li in range(L)
        if converged[li] is not None and not poisoned[li]
    )
    publish_sweep_result({
        "lanes": L,
        "rounds": rounds_total,
        "dispatches": dispatches,
        "wall_seconds": round(wall, 3),
        "compile_seconds": round(compile_seconds, 3),
        "lanes_converged": n_converged,
        "lanes_poisoned": n_poisoned,
        "lanes_unsettled": L - n_converged - n_poisoned,
        "wasted_lane_rounds_total": wasted_total,
        "lane_states": "".join(
            "P" if poisoned[li]
            else ("C" if converged[li] is not None else "A")
            for li in range(L)
        ),
        "projected": plan.fork is not None,
        "compact": compact,
        "pipelined": pipeline,
        "refills": refills_total,
    })
    return SweepResult(
        lanes=results,
        rounds=rounds_total,
        dispatches=dispatches,
        wall_seconds=wall,
        compile_seconds=compile_seconds,
        devices=1,
        compile_cache=cache_probe.summary(),
        chunk=chunk,
        occupancy=occupancy,
        compaction=(
            {
                "widths": widths_used,
                "refills": refills_total,
                "shrinks": shrinks,
                "max_pending": max_pending,
                "slot_reuse": slot_reuse,
            } if compact else None
        ),
        pipeline=(
            {
                "enabled": True,
                "speculative_dispatched": spec_dispatched,
                "speculative_wasted": spec_wasted,
            } if pipeline else None
        ),
    )
