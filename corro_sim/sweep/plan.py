"""Sweep planning: the grid grammar and the union-program construction.

A sweep is specified as a grid of axes (CLI ``corro-sim sweep``)::

    scenario=crash_amnesia,lossy  seed=0..31  knob.loss=0.05,0.2

- ``scenario`` — scenario specs (:mod:`corro_sim.faults.scenarios`).
  Commas separate scenarios; a comma followed by a bare ``k=v`` piece
  continues the PREVIOUS spec's parameters, so
  ``crash_amnesia:nodes=3,at=6,lossy:p=0.1`` is two scenarios. ``;`` is
  always a hard separator when the heuristic is unwanted.
- ``seed`` — ``0..31`` inclusive ranges or comma lists.
- ``knob.<field>`` — per-lane overrides: link-fault thresholds
  (:data:`corro_sim.sweep.knobs.SWEEP_KNOB_FIELDS`) or SimConfig
  scalars (:data:`corro_sim.sweep.knobs.SIM_KNOB_FIELDS` —
  ``write_rate``, ``delete_rate``, ``zipf_alpha``, ``sync_interval``,
  ``swim_suspect_rounds``); multiple knob axes cross-product.
  Shape-affecting fields (``sync_peers``, ``sync_actor_topk``,
  ``swim_view_size``) are refused by name: they change program
  structure, so lanes differing in them cannot share one dispatch.

The cartesian product of the axes is the lane list; every lane's config
is the exact config a serial ``run_sim`` of that cell would use (its
*twin* — the bit-identity oracle and the worst-seed repro target).

Validation is ALL-AT-ONCE: every invalid grid entry — unparseable
scenario spec, unknown knob field, a fault window that never overlaps
the coupled workload's write range (``Scenario.check_workload``), a
schedule the plane encoding cannot carry, mixed blackhole topologies —
is collected and raised as ONE ValueError, so a bad cell at index 37
fails in milliseconds with the full list instead of dying mid-sweep.
"""

from __future__ import annotations

import dataclasses

from corro_sim.config import (
    FaultConfig,
    NodeFaultConfig,
    SimConfig,
    SweepConfig,
    shift_node_faults,
)
from corro_sim.faults.scenarios import make_scenario
from corro_sim.sweep.knobs import (
    SIM_KNOB_FIELDS,
    SIM_KNOB_LEAF_FIELDS,
    SWEEP_KNOB_FIELDS,
    lane_knobs,
)

__all__ = ["SweepLane", "SweepPlan", "build_plan", "parse_grid"]


@dataclasses.dataclass
class SweepLane:
    """One (scenario × knobs × seed) grid cell — one vmapped lane."""

    index: int
    spec: str  # the scenario spec (canonical form)
    seed: int
    knob_overrides: dict  # the knob-axis FaultConfig overrides (may be {})
    scenario: object  # compiled Scenario
    cfg: SimConfig  # the serial twin's config (scenario + knobs applied)
    knobs: dict  # sweep_knobs leaf values (corro_sim/sweep/knobs.py)
    workload: object | None  # compiled Workload, lane-seeded
    min_rounds: int
    schedule: object = None  # the lane's driver Schedule (attached at
    # plan time, the serial driver's workload write-round rule applied)
    workload_prebuilt: bool = False  # workload handed in as a built
    # object (e.g. a trace_workload replay window) rather than a spec —
    # make_workload cannot re-parse it, so repro_cmd omits --workload

    @property
    def cell(self) -> str:
        """The frontier cell key: scenario spec + knob suffix (seeds
        aggregate within a cell)."""
        if not self.knob_overrides:
            return self.spec
        kv = ",".join(
            f"{k}={v:g}" for k, v in sorted(self.knob_overrides.items())
        )
        return f"{self.spec}#{kv}"

    # base-config fields expressible as `corro-sim run` flags — the
    # repro command emits the ones differing from SimConfig defaults
    # so the serial twin runs the LANE's exact base shape
    _REPRO_FLAGS = (
        ("--nodes", "num_nodes"),
        ("--rows", "num_rows"),
        ("--cols", "num_cols"),
        ("--log-capacity", "log_capacity"),
        ("--write-rate", "write_rate"),
        ("--zipf", "zipf_alpha"),
        ("--swim", "swim_enabled"),
        ("--swim-view", "swim_view_size"),
        ("--sync-interval", "sync_interval"),
        ("--probes", "probes"),
    )

    def repro_cmd(self, base_cfg, rounds: int, write_rounds: int,
                  max_rounds: int, chunk: int,
                  fork_path: str | None = None) -> str:
        """The ONE serial command that reproduces this lane — what a
        failing frontier cell prints next to its worst seed. ``rounds``
        pins the lane's fault-timeline horizon (``--scenario-rounds``):
        wave-shaped generators truncate against it, so the horizon is
        part of the timeline's identity even though the canonical spec
        pins every resolved parameter.

        ``fork_path``: a what-if forecast lane reproduces as ``run
        --fork <token>`` — the base config, seed-independent state and
        fork-round frame all ride the token, so base-shape flags are
        omitted (``run --fork`` refuses them)."""
        defaults = SimConfig()
        cmd = f"corro-sim run --scenario '{self.spec}' --seed {self.seed}"
        if fork_path is not None:
            cmd += f" --fork {fork_path}"
        else:
            for flag, field in self._REPRO_FLAGS:
                v = getattr(base_cfg, field)
                if v == getattr(defaults, field):
                    continue
                if isinstance(v, bool):
                    if v:
                        cmd += f" {flag}"
                else:
                    cmd += f" {flag} {v:g}" if isinstance(v, float) \
                        else f" {flag} {v}"
        cmd += (
            f" --scenario-rounds {rounds} --write-rounds {write_rounds} "
            f"--max-rounds {max_rounds} --chunk {chunk} --scorecard"
        )
        for k, v in sorted(self.knob_overrides.items()):
            cmd += f" --knob {k}={v:g}"
        if self.workload is not None and not self.workload_prebuilt:
            cmd += f" --workload '{self.workload.spec}'"
        return cmd


@dataclasses.dataclass
class SweepPlan:
    """A validated sweep: the lanes and the ONE union config whose
    vmapped program races them all."""

    base_cfg: SimConfig
    union_cfg: SimConfig
    lanes: list
    rounds: int
    write_rounds: int
    workload_spec: str | None = None
    fork: object | None = None  # SimCheckpoint fork token — every lane
    # warm-starts from its state (corro_sim/engine/twin.py what-if
    # forecasts) instead of a fresh init_state
    fork_round: int = 0  # the twin's absolute state.round at the fork
    # (node-fault schedules are shifted into this frame; scorecards and
    # invariant checkers map them back via round_offset)

    @property
    def num_lanes(self) -> int:
        return len(self.lanes)

    @property
    def fork_path(self) -> str | None:
        return getattr(self.fork, "path", None)


# ------------------------------------------------------------- grid spec

# SimConfig fields a knob axis must refuse BY NAME: each one shapes an
# array extent or a traced loop count, so two values mean two programs.
_SHAPE_AFFECTING = frozenset((
    "sync_peers", "sync_actor_topk", "swim_view_size", "swim_interval",
    "num_nodes", "num_rows", "num_cols", "log_capacity",
))

# the SimConfig int fields a knob axis casts back from the float grid
_SIM_INT_FIELDS = frozenset(("sync_interval", "swim_suspect_rounds"))


def _split_scenarios(value: str) -> list[str]:
    """Scenario-axis splitting: ';' is a hard separator; ',' starts a
    new spec unless the piece is a bare ``k=v`` parameter continuation
    (no ':' before its first '=')."""
    out: list[str] = []
    for group in value.split(";"):
        for piece in group.split(","):
            piece = piece.strip()
            if not piece:
                continue
            eq = piece.find("=")
            colon = piece.find(":")
            continuation = eq >= 0 and not (0 <= colon < eq)
            if continuation and out:
                out[-1] += "," + piece
            else:
                out.append(piece)
    return out


def _split_ints(value: str) -> list[int]:
    out: list[int] = []
    for piece in value.split(","):
        piece = piece.strip()
        if ".." in piece:
            lo, hi = piece.split("..", 1)
            out.extend(range(int(lo), int(hi) + 1))
        elif piece:
            out.append(int(piece))
    return out


def parse_grid(tokens: list[str]) -> dict:
    """``KEY=VALUES`` grid tokens → ``{"scenario": [...], "seed": [...],
    "knobs": [{...}, ...]}`` (knob axes cross-producted). Errors
    collect into one ValueError (the up-front-validation posture)."""
    scenarios: list[str] = []
    seeds: list[int] = []
    knob_axes: dict[str, list[float]] = {}
    errors: list[str] = []
    for tok in tokens:
        key, _, value = tok.partition("=")
        key = key.strip()
        if not value:
            errors.append(f"grid token {tok!r} is not KEY=VALUES")
            continue
        if key == "scenario":
            scenarios.extend(_split_scenarios(value))
        elif key == "seed":
            try:
                seeds.extend(_split_ints(value))
            except ValueError:
                errors.append(f"seed axis {value!r} is not ints/ranges")
        elif key.startswith("knob."):
            field = key[len("knob."):]
            if field in _SHAPE_AFFECTING:
                errors.append(
                    f"knob field {field!r} is shape-affecting — it "
                    "changes program structure, so lanes differing in "
                    "it cannot share one dispatch; sweep it as "
                    "separate runs"
                )
                continue
            if field not in SWEEP_KNOB_FIELDS + SIM_KNOB_FIELDS:
                errors.append(
                    f"unknown knob field {field!r} (sweepable: "
                    f"{', '.join(SWEEP_KNOB_FIELDS + SIM_KNOB_FIELDS)})"
                )
                continue
            try:
                knob_axes[field] = [
                    float(v) for v in value.split(",") if v.strip()
                ]
            except ValueError:
                errors.append(f"knob axis {tok!r} is not floats")
        else:
            errors.append(
                f"unknown grid axis {key!r} (have: scenario, seed, "
                "knob.<field>)"
            )
    if errors:
        raise ValueError(
            "invalid sweep grid:\n  " + "\n  ".join(errors)
        )
    # cross-product the knob axes into override dicts
    knob_combos: list[dict] = [{}]
    for field, values in knob_axes.items():
        knob_combos = [
            {**combo, field: v} for combo in knob_combos for v in values
        ]
    return {
        "scenario": scenarios,
        "seed": seeds or [0],
        "knobs": knob_combos,
    }


# ------------------------------------------------------------ plan build

def build_plan(
    base_cfg: SimConfig,
    scenarios: list[str],
    seeds: list[int],
    knob_combos: list[dict] | None = None,
    rounds: int = 128,
    write_rounds: int = 16,
    workload_spec: str | None = None,
    fork=None,
    workload=None,
) -> SweepPlan:
    """Compile the grid into a validated :class:`SweepPlan`.

    Every error across the WHOLE grid lands in one ValueError — the
    satellite contract: a sweep must refuse up front, never die on lane
    37 mid-dispatch.

    ``fork``: a :class:`corro_sim.io.checkpoint.SimCheckpoint` fork
    token (``save_fork_checkpoint``) — the what-if forecast grid: every
    lane warm-starts from the token's state, and each lane's node-fault
    schedule shifts into the fork's absolute round frame
    (:func:`corro_sim.config.shift_node_faults`), so "wipe at relative
    round k" fires k rounds after the fork on a ``state.round`` that
    keeps counting from the twin's timeline.

    ``workload``: a PREBUILT
    :class:`~corro_sim.workload.generators.Workload` shared by every
    lane — the coupled-load forecast path
    (:func:`corro_sim.workload.inject.trace_workload` replaying a live
    feed's trailing window into a fork). Unlike ``workload_spec`` it
    composes with ``fork``: the sweep engine plays workload rounds in
    the SWEEP-relative frame, i.e. immediately after the fork, which is
    exactly when the replayed traffic happened. Mutually exclusive with
    ``workload_spec`` (a spec is re-seeded per lane; a prebuilt object
    is one fixed tape)."""
    knob_combos = knob_combos or [{}]
    errors: list[str] = []
    fork_round = 0
    prebuilt = workload  # the loop below rebinds `workload` per lane
    if workload is not None and workload_spec is not None:
        raise ValueError(
            "build_plan takes workload_spec (per-lane seeded generator) "
            "or workload (one prebuilt tape), not both"
        )
    if fork is not None:
        if not fork.is_fork:
            raise ValueError(
                "build_plan(fork=...) needs a fork token "
                "(io/checkpoint.py save_fork_checkpoint), not a mid-run "
                "soak cursor"
            )
        if workload_spec is not None:
            raise ValueError(
                "a what-if forecast does not couple a workload — the "
                "forked state IS the load (run_sim resume does not "
                "compose with workload schedules)"
            )
        fork_round = fork.fork_round
    lanes: list[SweepLane] = []
    blackholes: set = set()
    index = 0
    for spec in scenarios:
        for knobs_over in knob_combos:
            for seed in seeds:
                cell = f"scenario={spec!r} seed={seed}" + (
                    f" knobs={knobs_over}" if knobs_over else ""
                )
                try:
                    sc = make_scenario(
                        spec, base_cfg.num_nodes, rounds=rounds,
                        write_rounds=write_rounds, seed=seed,
                    )
                except (ValueError, TypeError) as e:
                    errors.append(f"{cell}: {e}")
                    continue
                cfg = sc.apply(base_cfg)
                if fork_round and cfg.node_faults.enabled:
                    # the what-if frame shift: scenario-relative wipe
                    # rounds become absolute state rounds (fork + k)
                    cfg = dataclasses.replace(
                        cfg, node_faults=shift_node_faults(
                            cfg.node_faults, fork_round
                        )
                    ).validate()
                if knobs_over:
                    fault_over = {
                        k: v for k, v in knobs_over.items()
                        if k in SWEEP_KNOB_FIELDS
                    }
                    sim_over = {
                        k: (int(v) if k in _SIM_INT_FIELDS else float(v))
                        for k, v in knobs_over.items()
                        if k in SIM_KNOB_FIELDS
                    }
                    try:
                        if fault_over:
                            cfg = dataclasses.replace(
                                cfg, faults=dataclasses.replace(
                                    cfg.faults, **fault_over
                                )
                            )
                        if sim_over:
                            cfg = dataclasses.replace(cfg, **sim_over)
                        cfg = cfg.validate()
                    except AssertionError as e:
                        errors.append(f"{cell}: {e}")
                        continue
                workload = None
                if workload_spec is not None:
                    from corro_sim.workload import make_workload

                    try:
                        workload = make_workload(
                            workload_spec, base_cfg.num_nodes,
                            rounds=write_rounds, seed=seed,
                        )
                        workload.validate(cfg)
                        sc.check_workload(workload)
                    except (ValueError, AssertionError) as e:
                        errors.append(f"{cell}: {e}")
                        continue
                elif prebuilt is not None:
                    try:
                        prebuilt.validate(cfg)
                        sc.check_workload(prebuilt)
                    except (ValueError, AssertionError) as e:
                        errors.append(f"{cell}: {e}")
                        continue
                    workload = prebuilt
                blackholes.add(tuple(cfg.faults.blackhole))
                sched = sc.schedule()
                if (
                    workload is not None
                    and sched.write_rounds < workload.rounds
                ):
                    # the serial driver's rule: the load phase counts as
                    # write rounds for convergence gating (run_sim)
                    sched = dataclasses.replace(
                        sched, write_rounds=workload.rounds
                    )
                lanes.append(SweepLane(
                    index=index, spec=sc.spec, seed=int(seed),
                    knob_overrides=dict(knobs_over), scenario=sc, cfg=cfg,
                    knobs={}, workload=workload,
                    workload_prebuilt=prebuilt is not None,
                    min_rounds=max(
                        sc.heal_round or 0, write_rounds,
                        workload.rounds if workload is not None else 0,
                    ),
                    schedule=sched,
                ))
                index += 1
    if len(blackholes) > 1:
        errors.append(
            "lanes disagree on blackhole topology — static (N, N) "
            "masks are baked per program, so one dispatch cannot mix "
            "them; sweep topology studies separately or run serially"
        )
    if not lanes and not errors:
        errors.append("the grid is empty (no scenario axis?)")
    if errors:
        raise ValueError(
            f"invalid sweep grid ({len(errors)} bad entries):\n  "
            + "\n  ".join(errors)
        )

    # ---- union gates: which machinery the ONE program must trace
    union_sweep = SweepConfig(
        lanes=len(lanes),
        link_faults=any(lane.cfg.faults.enabled for lane in lanes),
        burst=any(lane.cfg.faults.burst_enter > 0 for lane in lanes),
        wipes=any(lane.cfg.node_faults.crash for lane in lanes),
        stale=any(lane.cfg.node_faults.stale for lane in lanes),
        skew=any(lane.cfg.node_faults.skew for lane in lanes),
        straggle=any(lane.cfg.node_faults.straggle for lane in lanes),
        workload=workload_spec is not None or prebuilt is not None,
        # arm the sim-knob leaf iff some lane's SimConfig scalar differs
        # from the base program's baked value — zipf_alpha excluded (it
        # rides the row_cdf plane, not the leaf)
        sim_knobs=any(
            getattr(lane.cfg, f) != getattr(base_cfg, f)
            for lane in lanes for f in SIM_KNOB_LEAF_FIELDS
        ),
    )
    union_cfg = dataclasses.replace(
        base_cfg,
        faults=FaultConfig(blackhole=next(iter(blackholes), ())),
        node_faults=NodeFaultConfig(),
        sweep=union_sweep,
    ).validate()
    # per-lane knob values under the UNION key set (knobs.py raises on
    # schedules the plane form cannot carry — collected like the rest)
    for lane in lanes:
        try:
            lane.knobs = lane_knobs(
                union_cfg, lane.cfg,
                use_workload=lane.workload is not None,
            )
        except ValueError as e:
            errors.append(f"scenario={lane.spec!r} seed={lane.seed}: {e}")
    if errors:
        raise ValueError(
            f"invalid sweep grid ({len(errors)} bad entries):\n  "
            + "\n  ".join(errors)
        )
    return SweepPlan(
        base_cfg=base_cfg, union_cfg=union_cfg, lanes=lanes,
        rounds=rounds, write_rounds=write_rounds,
        workload_spec=workload_spec, fork=fork, fork_round=fork_round,
    )
