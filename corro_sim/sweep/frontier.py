"""The resilience frontier: quantile-over-seeds grading of a chaos matrix.

PR 11's scorecard grades each scenario at ONE seed — a lucky seed can
hide a regression a 32-seed sweep would catch. The frontier aggregates
every sweep lane into per-cell (scenario spec × knob overrides) rows:
worst and p95 ``recovery_rounds`` across seeds, worst ``rows_lost``,
worst ``degradation_p99``, SWIM churn extremes — and NAMES the arg-max
worst seed, with the one serial ``run_sim`` command that reproduces it
(`SweepLane.repro_cmd`). A failing cell is therefore a one-command
repro, not a needle in a 32-run log.

Threshold gating moves from single-seed to quantile-over-seeds: the
committed golden (``analysis/golden/resilience_thresholds.json``)
carries ``recovery_rounds_worst_max`` / ``recovery_rounds_p95_max``
next to the serial path's single-run bounds; :func:`check_frontier`
merges ``default`` under the scenario's base name exactly like
:func:`corro_sim.faults.scorecard.check_thresholds` and returns
human-readable breaches (the soak exit-6 semantics, unchanged through
the sweep path).
"""

from __future__ import annotations

import numpy as np

__all__ = ["breaches_by_cell", "build_frontier", "check_frontier"]


def breaches_by_cell(breaches) -> dict:
    """Group :func:`check_frontier` breach strings by the cell tag each
    leads with. Every breach is formatted ``<cell>: <msg> (worst seed
    ...)`` and cell tags never contain spaces (scenario specs + knob
    suffixes), so the tag is everything before the first ``": "`` —
    a format contract the fleet observatory depends on to pin
    ``threshold_breach`` annotations onto the right lane flights
    (corro_sim/obs/lanes.py demux_flights)."""
    out: dict[str, list] = {}
    for b in breaches:
        out.setdefault(b.split(": ", 1)[0], []).append(b)
    return out


def _p95(values: list) -> float | None:
    return float(np.percentile(np.asarray(values, float), 95)) \
        if values else None


def build_frontier(lane_results: list, projected: bool = False) -> dict:
    """Aggregate :class:`~corro_sim.sweep.engine.LaneResult`s into the
    frontier artifact: one cell per (scenario spec × knob overrides),
    statistics across that cell's seeds.

    ``projected=True`` marks a what-if FORECAST frontier (lanes
    warm-started from a twin fork, corro_sim/engine/twin.py): the
    numbers are projections of faults the real cluster has NOT taken,
    and the artifact says so — a dashboard must never present a
    forecast as a measurement."""
    cells: dict[str, list] = {}
    for lane in lane_results:
        cells.setdefault(lane.cell, []).append(lane)
    out = []
    for cell, members in cells.items():
        recoveries = [
            lr.recovery_rounds for lr in members
            if lr.recovery_rounds is not None
        ]
        unconverged = sorted(
            lr.seed for lr in members if lr.converged_round is None
        )
        rows_lost = [
            (lr.resilience or {}).get("rows_lost") for lr in members
        ]
        rows_lost = [v for v in rows_lost if v is not None]
        resyncs = [
            (lr.resilience or {}).get("resync_rows", 0) for lr in members
        ]
        false_down = [
            (lr.resilience or {}).get("swim_false_down", 0)
            for lr in members
        ]
        degradations = []
        for lr in members:
            sub = (lr.resilience or {}).get("sub_delivery") or {}
            d = sub.get("degradation_p99")
            if d is not None:
                degradations.append(float(d))

        # the arg-max "worst seed": an unconverged lane beats any
        # converged recovery time; ties break to the larger recovery
        def badness(lr):
            return (
                lr.converged_round is None or lr.poisoned,
                lr.recovery_rounds
                if lr.recovery_rounds is not None else -1,
                (lr.resilience or {}).get("rows_lost") or 0,
            )

        worst = max(members, key=badness)
        out.append({
            "cell": cell,
            "scenario": members[0].spec,
            "lanes": len(members),
            "seeds": sorted(lr.seed for lr in members),
            "converged": len(members) - len(unconverged),
            "unconverged_seeds": unconverged,
            "poisoned_seeds": sorted(
                lr.seed for lr in members if lr.poisoned
            ),
            "recovery_rounds": {
                "worst": max(recoveries) if recoveries else None,
                "p95": _p95(recoveries),
                "mean": (
                    float(np.mean(recoveries)) if recoveries else None
                ),
            },
            "rows_lost_worst": max(rows_lost) if rows_lost else None,
            "resync_rows_min": min(resyncs) if resyncs else 0,
            "swim_false_down_worst": (
                max(false_down) if false_down else 0
            ),
            "degradation_p99_worst": (
                max(degradations) if degradations else None
            ),
            "worst_seed": worst.seed,
            "worst_repro": worst.repro_cmd,
            "invariants_ok": all(
                (lr.invariants or {}).get("ok", True) for lr in members
            ),
        })
    doc = {"cells": sorted(out, key=lambda c: c["cell"])}
    if projected:
        doc["projected"] = True
    return doc


def check_frontier(frontier: dict, thresholds: dict,
                   section: str | None = None) -> list[str]:
    """Grade the frontier against the committed threshold golden —
    quantile-over-seeds semantics. Per cell, the ``default`` table
    merges under the scenario's base-name entry (the
    ``check_thresholds`` rule); ``recovery_rounds_worst_max`` falls
    back to the serial ``recovery_rounds_max`` bound so a scenario
    graded before the sweep era keeps its tripwire. Every breach names
    the worst seed's one-command repro.

    ``section``: grade against a sub-table of the golden instead of its
    top level — the twin's what-if forecasts use ``"twin_forecast"``
    (projected bounds live apart from measured ones; an absent section
    gates nothing, exit-6 semantics unchanged where it exists)."""
    if section is not None:
        thresholds = thresholds.get(section) or {}
    breaches: list[str] = []
    for cell in frontier.get("cells", []):
        base = (cell["scenario"] or "").split(":", 1)[0]
        merged = dict(thresholds.get("default", {}))
        merged.update(thresholds.get("scenarios", {}).get(base, {}))
        tag = cell["cell"]

        def breach(msg):
            breaches.append(
                f"{tag}: {msg} (worst seed {cell['worst_seed']}; "
                f"repro: {cell['worst_repro']})"
            )

        if merged.get("require_converged") and cell["unconverged_seeds"]:
            breach(
                f"seeds {cell['unconverged_seeds']} did not re-converge"
            )
        if cell["poisoned_seeds"]:
            breach(f"seeds {cell['poisoned_seeds']} poisoned")
        rec = cell["recovery_rounds"]
        worst_max = merged.get(
            "recovery_rounds_worst_max", merged.get("recovery_rounds_max")
        )
        if (
            worst_max is not None and rec["worst"] is not None
            and rec["worst"] > worst_max
        ):
            breach(
                f"recovery_rounds worst {rec['worst']} > {worst_max}"
            )
        p95_max = merged.get("recovery_rounds_p95_max")
        if (
            p95_max is not None and rec["p95"] is not None
            and rec["p95"] > p95_max
        ):
            breach(f"recovery_rounds p95 {rec['p95']:.1f} > {p95_max}")
        if (
            merged.get("rows_lost_max") is not None
            and cell["rows_lost_worst"] is not None
            and cell["rows_lost_worst"] > merged["rows_lost_max"]
        ):
            breach(
                f"rows_lost worst {cell['rows_lost_worst']} > "
                f"{merged['rows_lost_max']}"
            )
        if (
            merged.get("resync_rows_min") is not None
            and cell["resync_rows_min"] < merged["resync_rows_min"]
        ):
            breach(
                f"resync_rows min {cell['resync_rows_min']} < "
                f"{merged['resync_rows_min']} (the stale-rejoin "
                "repayment evidence is missing)"
            )
        if (
            merged.get("swim_false_down_max") is not None
            and cell["swim_false_down_worst"]
            > merged["swim_false_down_max"]
        ):
            breach(
                f"swim_false_down worst {cell['swim_false_down_worst']}"
                f" > {merged['swim_false_down_max']}"
            )
    return breaches
