"""The sweep knob leaf: per-lane fault parameters as carry DATA.

A serial ``run_sim`` bakes every fault parameter into the compiled
program as a constant — ``loss=0.05`` is a literal in the jaxpr, a crash
schedule is a baked int array. That is exactly right for one cluster and
exactly wrong for a fleet: two lanes with different knobs would need two
programs, and the chaos matrix degenerates back into the serial soak
loop. This module moves the *varying* parameters into a registry feature
leaf (``sweep_knobs``, the PR 10 contract in
:mod:`corro_sim.engine.features`): under a sweep every lane's carry
holds its own traced knob scalars/planes, the step reads them in place
of the constants (same expressions, traced operands — value-identical),
and one vmapped program races the whole grid. Non-sweeping configs get
NOTHING — no leaf, no aval — so every existing program's pytree, jaxpr
and cache key stays byte-identical.

Leaf contents are keyed by the union :class:`corro_sim.config.
SweepConfig` gates, so the program's scope covers exactly the armed
sweep dimensions:

========================  =========================================
gate                      knobs
========================  =========================================
``link_faults``           ``loss``/``dup``/``burst_enter``/
                          ``burst_exit``/``burst_loss``/``sync_loss``
                          — () float32 thresholds
``wipes`` or ``stale``    ``wipe_round`` (N,) int32 (-1 = never),
                          ``wipe_stale`` (N,) bool, ``epoch_jump`` ()
``stale``                 ``snap_round`` (N,) int32 (-1 = never)
``skew``                  ``skew`` (N,) int32 HLC offsets
``straggle``              ``straggle_period``/``straggle_active``
                          (N,) int32 duty cycles (1/1 = full duty)
``workload``              ``use_workload`` () bool — schedule-driven
                          vs sampler-driven writes, per lane
``sim_knobs``             ``write_rate``/``delete_rate`` () float32
                          thresholds, ``sync_interval``/
                          ``swim_suspect_rounds`` () int32 cadences —
                          the SimConfig scalars beyond the link-fault
                          set (``zipf_alpha`` sweeps with NO knob: it
                          only shapes the host-built row_cdf plane)
========================  =========================================

The *neutral* values (what the builder emits, and what a lane that does
not use a dimension carries) are value-identical to the untraced path —
the vacuity guards in tests/test_faults.py and tests/test_node_faults.py
are the proof obligation this design leans on.
"""

from __future__ import annotations

import numpy as np

from corro_sim.engine.features import FeatureLeaf, register_feature

__all__ = [
    "SIM_KNOB_FIELDS", "SIM_KNOB_LEAF_FIELDS", "SWEEP_KNOB_FIELDS",
    "lane_knobs", "neutral_knobs",
]

# the link-fault scalar thresholds a `knob.<field>=...` grid axis may
# sweep (everything else on FaultConfig changes program structure)
SWEEP_KNOB_FIELDS = (
    "loss", "dup", "burst_enter", "burst_exit", "burst_loss", "sync_loss",
)

# SimConfig scalars a grid axis may sweep per lane. The leaf fields
# ride sweep_knobs as traced operands (sweep.sim_knobs gate);
# zipf_alpha rides the row_cdf state plane instead — a pure data swap,
# no gate, no knob. Shape-affecting SimConfig fields (sync_peers,
# sync_actor_topk, swim_view_size, num_*) can never appear here: they
# change program structure, so lanes differing in them cannot share
# one dispatch (plan.parse_grid names them in its rejection).
SIM_KNOB_LEAF_FIELDS = (
    "write_rate", "delete_rate", "sync_interval", "swim_suspect_rounds",
)
SIM_KNOB_FIELDS = SIM_KNOB_LEAF_FIELDS + ("zipf_alpha",)


def neutral_knobs(cfg, seed: int = 0) -> dict:
    """The value-neutral leaf for ``cfg``'s armed sweep dimensions —
    the feature builder (every lane starts here; the sweep engine
    overwrites the values with each lane's own before stacking)."""
    import jax.numpy as jnp

    sw = cfg.sweep
    n = cfg.num_nodes
    out: dict = {}
    if sw.link_faults:
        out.update(
            loss=jnp.float32(0.0), dup=jnp.float32(0.0),
            burst_enter=jnp.float32(0.0), burst_exit=jnp.float32(1.0),
            burst_loss=jnp.float32(0.0), sync_loss=jnp.float32(0.0),
        )
    if sw.wipe_planes:
        out["wipe_round"] = jnp.full((n,), -1, jnp.int32)
        out["wipe_stale"] = jnp.zeros((n,), bool)
        out["epoch_jump"] = jnp.int32(0)
    if sw.stale:
        out["snap_round"] = jnp.full((n,), -1, jnp.int32)
    if sw.skew:
        out["skew"] = jnp.zeros((n,), jnp.int32)
    if sw.straggle:
        out["straggle_period"] = jnp.ones((n,), jnp.int32)
        out["straggle_active"] = jnp.ones((n,), jnp.int32)
    if sw.workload:
        out["use_workload"] = jnp.asarray(False)
    if sw.sim_knobs:
        out["write_rate"] = jnp.float32(cfg.write_rate)
        out["delete_rate"] = jnp.float32(cfg.delete_rate)
        out["sync_interval"] = jnp.int32(cfg.sync_interval)
        out["swim_suspect_rounds"] = jnp.int32(cfg.swim_suspect_rounds)
    return out


register_feature(FeatureLeaf(
    name="sweep_knobs",
    enabled=lambda cfg: cfg.sweep.enabled,
    build=neutral_knobs,
    volatile=True,
))


def lane_knobs(union_cfg, lane_cfg, use_workload: bool = False) -> dict:
    """One lane's knob values (host numpy, the union leaf's exact key
    set) extracted from the lane's serial-twin config — the config a
    plain ``run_sim`` of this lane would bake as constants.

    Raises ValueError for schedules the plane form cannot carry (more
    than one wipe per node, a node both crashing and stale-rejoining)
    — those lanes must run serially (``soak --serial``)."""
    sw = union_cfg.sweep
    nf = lane_cfg.node_faults
    n = union_cfg.num_nodes
    out: dict = {}
    if sw.link_faults:
        f = lane_cfg.faults
        out.update(
            loss=np.float32(f.loss), dup=np.float32(f.dup),
            burst_enter=np.float32(f.burst_enter),
            burst_exit=np.float32(f.burst_exit),
            burst_loss=np.float32(f.burst_loss),
            sync_loss=np.float32(f.resolved_sync_loss),
        )
    if sw.wipe_planes:
        wipe_round = np.full((n,), -1, np.int32)
        wipe_stale = np.zeros((n,), bool)
        snap_round = np.full((n,), -1, np.int32)
        for node, r in nf.crash:
            node = int(node)
            if wipe_round[node] >= 0:
                raise ValueError(
                    f"node {node} carries more than one scheduled wipe — "
                    "the sweep's one-wipe-per-node planes cannot encode "
                    "it; run this lane serially (soak --serial)"
                )
            wipe_round[node] = int(r)
        for node, s, r in nf.stale:
            node = int(node)
            if wipe_round[node] >= 0:
                raise ValueError(
                    f"node {node} carries more than one scheduled wipe — "
                    "the sweep's one-wipe-per-node planes cannot encode "
                    "it; run this lane serially (soak --serial)"
                )
            wipe_round[node] = int(r)
            wipe_stale[node] = True
            snap_round[node] = int(s)
        out["wipe_round"] = wipe_round
        out["wipe_stale"] = wipe_stale
        out["epoch_jump"] = np.int32(nf.epoch_jump)
        if sw.stale:
            out["snap_round"] = snap_round
    if sw.skew:
        skew = np.zeros((n,), np.int32)
        for node, off in nf.skew:
            skew[int(node)] = int(off)
        out["skew"] = skew
    if sw.straggle:
        period = np.ones((n,), np.int32)
        active = np.ones((n,), np.int32)
        for node, p, a in nf.straggle:
            period[int(node)] = int(p)
            active[int(node)] = int(a)
        out["straggle_period"] = period
        out["straggle_active"] = active
    if sw.workload:
        out["use_workload"] = np.asarray(bool(use_workload))
    if sw.sim_knobs:
        out["write_rate"] = np.float32(lane_cfg.write_rate)
        out["delete_rate"] = np.float32(lane_cfg.delete_rate)
        out["sync_interval"] = np.int32(lane_cfg.sync_interval)
        out["swim_suspect_rounds"] = np.int32(lane_cfg.swim_suspect_rounds)
    return out
