"""LiveCluster: the running agent — a whole simulated cluster behind an API.

The reference's unit of deployment is one agent process per node
(``corro-agent/src/agent/run_root.rs``); clients talk to *their* node's
HTTP API and gossip spreads the writes. The TPU-native unit of deployment
is one *cluster* process: every node's state lives in the same sharded
tensors, one driver thread advances all nodes together, and the API
addresses a node by ordinal (`node=` parameter = which agent you'd have
connected to). Everything a reference agent does per node — accept writes,
commit + version them, gossip, merge, sync, notify subscriptions — happens
here for all nodes at once, one jitted round per tick.

Write path parity (``make_broadcastable_changes``,
``api/public/mod.rs:36-101``): `execute()` parses statements, interns
values, queues one changeset per transaction on the target node, and ticks
the simulator until the queue drains — the one-write-conn-per-node
serialization is the dequeue discipline (≤1 changeset per node per round,
``corro-types/src/agent.rs:500-731``).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from corro_sim.api.statements import (
    StatementError,
    WriteOp,
    parse_write,
    pk_equalities,
)
from corro_sim.config import SimConfig
from corro_sim.core.crdt import NEG
from corro_sim.engine.driver import round_key
from corro_sim.engine.state import SimState, init_state
from corro_sim.engine.step import sim_step
from corro_sim.io.values import LiveUniverse
from corro_sim.schema import (
    SchemaError,
    TableLayout,
    parse_and_constrain,
)
from corro_sim.subs.manager import (
    LayoutAdapter,
    Matcher,
    SubsManager,
    make_matcher,
)
from corro_sim.subs.query import QueryError, parse_query, post_process
from corro_sim.utils.metrics import (
    PIPELINE_FETCH_WAIT,
    PIPELINE_FETCH_WAIT_HELP,
    histograms as _global_histograms,
)
from corro_sim.utils.ranks import rank_map, translate_ranks
from corro_sim.utils.runtime import (
    LockRegistry,
    Tripwire,
    start_async_fetch as _start_async_fetch,
)


@dataclasses.dataclass
class _PendingChangeset:
    """One queued transaction: becomes exactly one version when committed."""

    is_delete: bool
    cells: list  # [(row_slot, col_plane, value_rank)]; delete: [(slot, 0, 0)]
    queued_at: float = 0.0  # perf_counter at enqueue — feeds the
    # corro.agent.changes.queued.seconds histogram at drain time


# Rounds per multi-round dispatch (the chunked fast path). Small clusters
# converging in a few rounds never pay this program's compile; bulk drains
# and long convergence runs amortize one dispatch over _CHUNK rounds.
_CHUNK = 16


class ExecError(ValueError):
    pass


class LiveCluster:
    def __init__(
        self,
        schema_sql: str,
        num_nodes: int = 4,
        seed: int = 0,
        default_capacity: int = 256,
        capacities: dict | None = None,
        cfg_overrides: dict | None = None,
        tripwire: Tripwire | None = None,
        layout: TableLayout | None = None,
        universe: LiveUniverse | None = None,
    ):
        # layout/universe injection is the warm-boot path: checkpoint
        # restore rebuilds them with their exact slot/rank assignments
        # (BookedVersions::from_conn analog, agent.rs:1334-1403).
        if layout is not None:
            self.layout = layout
        else:
            schema = parse_and_constrain(schema_sql)
            self.layout = TableLayout(
                schema, capacities=capacities,
                default_capacity=default_capacity,
            )
        self._schema_history: list[str] = [schema_sql]
        self.universe = universe if universe is not None else LiveUniverse()
        from corro_sim.utils.metrics import HistogramRegistry

        # cluster-scoped histograms: a process can host several clusters
        # (tests, devcluster) — mixing their observations would lie
        self.histograms = HistogramRegistry()
        self.locks = LockRegistry(histograms=self.histograms)
        self.tripwire = tripwire or Tripwire()
        self._lock = threading.RLock()
        self._seed = seed

        overrides = dict(cfg_overrides or {})
        # seqs_per_version bounds cells per transaction; default generous.
        overrides.setdefault("seqs_per_version", 8)
        self.cfg = SimConfig(
            num_nodes=num_nodes,
            num_rows=self.layout.num_rows,
            num_cols=max(self.layout.num_cols, 1),
            **overrides,
        ).validate()
        self.state: SimState = init_state(self.cfg, seed=seed)
        self._root_key = jax.random.PRNGKey(seed)
        self._alive = np.ones((num_nodes,), bool)
        self._part = np.zeros((num_nodes,), np.int32)
        self._pending: list = [collections.deque() for _ in range(num_nodes)]
        self._staging: list | None = None  # execute()'s in-flight batch
        # in-flight batch overlay: ((slot -> live), ((slot, plane) -> rank))
        # — later statements in one transaction see earlier ones' effects,
        # like the reference's single SQLite tx (api/public/mod.rs:104-131)
        self._staging_overlay: tuple[dict, dict] | None = None
        self._rounds_ticked = 0
        self._totals: dict[str, float] = {}
        self._lasts: dict[str, float] = {}  # last-round gauge snapshots
        # per-stage wall-clock (ms): {stage: (ewma, last)} — the live
        # analog of tools/profile_round.py, cheap enough to always keep on
        # (one perf_counter pair per stage per tick). Exposed on /metrics
        # so BENCH regressions are explainable without re-profiling.
        self._stage_ms: dict[str, tuple[float, float]] = {}
        self._gap = 0.0  # last round's convergence gap (metrics reuse)
        self._prev_swim: dict[str, float] = {}  # transition-counter state
        self._probe_p99 = None  # worst per-probe p99 delivery lag seen
        self._probe_infected_last = -1.0  # change-detector for the check
        self._api_requests = 0  # served API requests (io_driver analog)
        self._api_req_lock = threading.Lock()
        self._chunk_dispatches = 0  # chunked tick batches executed
        self._log_poisoned = False  # ring-wrap tripwire latched
        self._partials = 0.0  # last round's buffered-partial gauge
        self._scenario = None  # active chaos scenario (load_scenario)
        self._scenario_base = 0  # round the scenario was loaded at
        self._scenario_events = 0  # events already annotated
        # the fault knobs the cluster was constructed with (cfg_overrides)
        # — scenarios apply RELATIVE to this baseline, so switching
        # scenarios never leaks the previous one's knobs
        self._baseline_faults = self.cfg.faults
        self._sub_queues: dict[str, list] = {}  # sub_id -> [deque]
        self.workload_report: dict | None = None  # last load-harness run
        # (corro_sim/workload/harness.py) — served at GET /v1/workload
        # per-queue health counters (corro.runtime.channel.* analog)
        from corro_sim.utils.metrics import ChannelMetrics

        # flight recorder: the durable per-round telemetry timeline
        # (GET /v1/flight, `corro-sim flight`, bench NDJSON artifacts)
        from corro_sim.obs.flight import FlightRecorder

        self.flight = FlightRecorder(capacity=16384)
        self.flight.set_meta(
            driver="live_cluster", nodes=num_nodes, seed=seed,
        )
        self.channels = ChannelMetrics(histograms=self.histograms)
        self.channels.set_capacity("write_queue", 0)  # unbounded deques
        self.channels.set_capacity("subs_events", 0)

        self.subs = SubsManager(
            LayoutAdapter(layout=self.layout), self.universe
        )
        self._query_cache: dict[tuple, Matcher] = {}
        self.universe.on_remap(self._on_remap)
        self._build_step()

    # ------------------------------------------------------------- plumbing
    def _build_step(self):
        cfg = self.cfg

        @functools.partial(jax.jit, static_argnames=())
        def step(state, key, alive, part, writes):
            return sim_step(
                cfg, state, key, alive, part, jnp.asarray(False), writes=writes
            )

        # Multi-round dispatch: `lax.scan` _CHUNK rounds inside ONE jitted
        # call, draining one queued changeset per node per round exactly
        # like the per-round path (keys derived identically via fold_in on
        # the absolute round number). This is the reference's cost-batched
        # apply loop (≤100 cost units across ≤5 jobs per 50 ms tick,
        # ``agent/handlers.rs:739-752``) in TPU form: the host pays one
        # dispatch + one metrics transfer per _CHUNK rounds instead of per
        # round — the difference between ~9 and >200 inserts/s through the
        # live path on a tunneled device.
        @functools.partial(jax.jit, static_argnames=())
        def multi_step(state, root_key, start_round, alive, part, writes_k):
            def body(st, inp):
                r, w = inp
                key = round_key(root_key, r)
                return sim_step(
                    cfg, st, key, alive, part, jnp.asarray(False), writes=w
                )

            k = writes_k[0].shape[0]
            rs = start_round + jnp.arange(k, dtype=jnp.uint32)
            return jax.lax.scan(body, state, (rs, writes_k))

        self._step = step
        self._multi_step = multi_step

    def _on_remap(self, old, new):
        """Translate every rank-typed tensor to the re-spaced universe.

        Order-preserving, so merge outcomes are untouched; this is pure
        re-labelling (like SQLite swapping its interned value ids)."""
        def remap(v):
            return translate_ranks(v, old, new, xp=jnp)

        from corro_sim.core.changelog import CELL_VR

        st = self.state
        log_cells = st.log.cells.at[..., CELL_VR].set(remap(st.log.vr))
        self.state = st.replace(
            table=st.table.replace(vr=remap(st.table.vr)),
            log=st.log.replace(cells=log_cells),
            own=st.own.replace(vr=remap(st.own.vr)),
        )
        # Queued-but-uncommitted changesets carry ranks too (including the
        # batch still being planned inside execute()).
        trans = rank_map(old, new)
        batches = list(self._pending)
        if self._staging is not None:
            batches.append(self._staging)
        for q in batches:
            for cs in q:
                cs.cells = [
                    (slot, plane, trans.get(rank, rank))
                    for slot, plane, rank in cs.cells
                ]
        if self._staging_overlay is not None:
            _, cells = self._staging_overlay
            for k, rank in cells.items():
                cells[k] = trans.get(rank, rank)
        self.subs.rebind_all(old, new)
        for m in self._query_cache.values():
            m.rebind(old, new)

    # ------------------------------------------------------------ write path
    def execute(self, statements, node: int = 0, wait: bool = True) -> dict:
        """POST /v1/transactions analog: one changeset per statement batch.

        Returns the ``ExecResponse`` shape (``corro-api-types:209-214``):
        per-statement results plus the committed version.

        ``wait=False`` plans and enqueues without draining: the caller
        ticks later (or lets the background ticker run), and queues of
        SEVERAL nodes drain together — one changeset per node per round,
        the true concurrent-clients shape. ``version`` is then None."""
        self._check_node(node)
        import time as _time

        t0 = _time.perf_counter()
        results = []
        with self.locks.tracked(self._lock, f"execute node={node}", "write"):
            if not self._alive[node]:
                # A down agent's API is unreachable in the reference; a
                # silent success for a write the step masks out would lie.
                raise ExecError(f"node {node} is down")
            changesets: list[_PendingChangeset] = []
            overlay: tuple[dict, dict] = ({}, {})
            self._staging = changesets
            self._staging_overlay = overlay
            self._bulk_intern(statements)
            try:
                for stmt in statements:
                    st0 = _time.perf_counter()
                    try:
                        op = parse_write(stmt)
                        n_rows = self._plan_write(
                            op, node, changesets, overlay
                        )
                    except (StatementError, SchemaError, QueryError) as e:
                        raise ExecError(str(e)) from None
                    results.append(
                        {
                            "rows_affected": n_rows,
                            "time": _time.perf_counter() - st0,
                        }
                    )
            finally:
                self._staging = None
                self._staging_overlay = None
            for cs in changesets:
                self._pending[node].append(cs)
                self.channels.on_send("write_queue")
            version = None
            if wait:
                # Commit synchronously: tick until this node's queue
                # drains — the API returns only after its transaction is
                # durable, like the reference's in-tx HTTP handler. Deep
                # queues drain through the chunked multi-round dispatch.
                while self._pending[node]:
                    if (
                        len(self._pending[node]) >= _CHUNK // 2
                        and not self._subs_active()
                    ):
                        self._tick_chunk_locked()
                    else:
                        self._tick_locked(1)
                version = int(np.asarray(self.state.book.head)[node, node])
        return {
            "results": results,
            "time": _time.perf_counter() - t0,
            "version": version,
        }

    def plan_overlay(self, statements, node: int = 0, base=None):
        """Plan a statement batch WITHOUT enqueueing it: returns
        ``(overlay, rows_affected_per_stmt)``.

        The overlay is the same staged-effects structure ``execute()``
        uses for in-batch read-your-writes; pgwire holds one for an open
        ``BEGIN … COMMIT`` transaction so reads and rows-affected counts
        inside the tx observe the tx's own buffered writes (the
        reference's single SQLite tx visibility, api/public/mod.rs:104-131).

        ``base``: an overlay from a previous call to extend IN PLACE —
        the incremental path that keeps an open transaction's planning
        O(1) per statement instead of replanning the whole buffer.

        Side effect (accepted, like SQLite burning rowids on rolled-back
        inserts): planning may allocate row slots and intern values for
        rows that never commit."""
        self._check_node(node)
        with self.locks.tracked(
            self._lock, f"plan_overlay node={node}", "write"
        ):
            changesets: list[_PendingChangeset] = []
            overlay: tuple[dict, dict] = base if base is not None else ({}, {})
            self._staging = changesets
            self._staging_overlay = overlay
            self._bulk_intern(statements)
            counts = []
            try:
                for stmt in statements:
                    try:
                        op = parse_write(stmt)
                        counts.append(
                            self._plan_write(op, node, changesets, overlay)
                        )
                    except (StatementError, SchemaError, QueryError) as e:
                        raise ExecError(str(e)) from None
            finally:
                self._staging = None
                self._staging_overlay = None
            return overlay, counts

    def _bulk_intern(self, statements) -> None:
        """Pre-intern every value a statement batch will rank, in bulk.

        Collection over-approximates (_plan_write decides exactly which
        cells commit); extra interned values only occupy rank space. Parse
        errors are ignored here — the planning loop re-parses in order and
        raises them with per-statement attribution."""
        vals: list = []
        for stmt in statements:
            try:
                op = parse_write(stmt)
            except StatementError:
                continue
            t = self.layout.schema.tables.get(op.table)
            if t is None:
                continue
            pk = set(t.pk)
            if op.kind == "upsert":
                vals.append(None)
                if t.value_columns:
                    vals.append(t.value_columns[0].default_value)
                for row in op.rows:
                    vals.extend(v for c, v in row.items() if c not in pk)
            elif op.kind == "update":
                # expression SETs (ASTs) evaluate per row at plan time —
                # their results intern lazily; only plain values prefetch
                vals.extend(
                    v for v in op.sets.values()
                    if isinstance(v, (type(None), bool, int, float, str,
                                      bytes))
                )
        if vals:
            self.universe.intern_many(vals)

    def _plan_write(
        self, op: WriteOp, node: int, out: list, overlay: tuple[dict, dict]
    ) -> int:
        """Expand one WriteOp into pending changesets; returns rows affected.

        ``overlay`` accumulates the batch's staged effects (liveness + cell
        values) so later statements in the same transaction observe earlier
        ones, matching the reference's single-SQLite-tx visibility."""
        t = self.layout.schema.tables.get(op.table)
        if t is None:
            raise StatementError(f"no such table {op.table!r}")
        s_cap = self.cfg.seqs_per_version
        live_ov, cell_ov = overlay

        if op.kind == "insert_select":
            # INSERT … SELECT: the source SELECT evaluates against the
            # writing node's current view (batch overlay included — same
            # single-tx visibility SQLite gives the reference), its rows
            # become the VALUES of a plain upsert.
            from corro_sim.api.exprs import eval_expr

            src_name, items = op.select
            src = self.layout.schema.tables.get(src_name)
            if src is None:
                raise StatementError(f"no such table {src_name!r}")
            if len(items) != len(op.cols):
                raise StatementError(
                    f"INSERT…SELECT arity mismatch: {len(op.cols)} columns "
                    f"vs {len(items)} selected"
                )
            sel_op = WriteOp(
                kind="select", table=src_name, where=op.where,
                where_expr=op.where_expr,
            )
            if op.where is None and op.where_expr is None:
                slots = self._live_slots(src, node, overlay)
            else:
                slots = self._resolve_rows(sel_op, src, node, overlay)
            envs = self._row_envs(src, node, slots, overlay)
            rows = [
                [eval_expr(e, env) for e in items] for env in envs
            ]
            op = WriteOp(
                kind="upsert", table=op.table,
                rows=[dict(zip(op.cols, r)) for r in rows],
            )

        if op.kind == "upsert":
            # last-occurrence-wins per (row, col): SQLite upsert semantics,
            # and local_write's invariant that one changeset never carries
            # duplicate (row, col) cells (core/crdt.py local_write).
            dedup: dict[tuple[int, int], int] = {}
            touched_slots = []
            for row in op.rows:
                missing = [c for c in t.pk if c not in row]
                if missing:
                    raise StatementError(
                        f"INSERT into {t.name!r} must provide pk column(s) "
                        f"{missing}"
                    )
                pk = tuple(row[c] for c in t.pk)
                slot = self.layout.row_slot(t.name, pk)
                touched_slots.append(slot)
                wrote = False
                for c in t.value_columns:
                    if c.name in row:
                        key = (slot, self.layout.col_index(t.name, c.name))
                        dedup[key] = self.universe.rank(row[c.name])
                        wrote = True
                if not wrote:
                    # pk-only insert: row existence is carried by the causal
                    # length; write the first value column's default/NULL.
                    if t.value_columns:
                        c = t.value_columns[0]
                        key = (slot, self.layout.col_index(t.name, c.name))
                        dedup.setdefault(
                            key, self.universe.rank(c.default_value)
                        )
                    else:
                        dedup.setdefault((slot, 0), self.universe.rank(None))
            cells = [(r, c, v) for (r, c), v in dedup.items()]
            for i in range(0, len(cells), s_cap):
                out.append(_PendingChangeset(
                    False, cells[i:i + s_cap], time.perf_counter()
                ))
            for slot in touched_slots:
                live_ov[slot] = True
            cell_ov.update(dedup)
            return len(op.rows)

        slots = self._resolve_rows(op, t, node, overlay)
        if op.kind == "update":
            from corro_sim.api.exprs import eval_expr

            for c in op.sets:
                self.layout.col_index(t.name, c)  # validate
            plain = all(
                isinstance(v, (type(None), bool, int, float, str, bytes))
                for v in op.sets.values()
            )
            if plain:
                cells = [
                    (slot, self.layout.col_index(t.name, c),
                     self.universe.rank(v))
                    for slot in slots
                    for c, v in op.sets.items()
                ]
            else:
                # expression SETs (SET v = v + 1, CASE …): evaluate per
                # target row against its current values + the batch
                # overlay — the reference gets this from SQLite executing
                # the statement inside the write tx (mod.rs:104-131)
                envs = self._row_envs(t, node, slots, overlay)
                cells = []
                for slot, env in zip(slots, envs):
                    for c, v in op.sets.items():
                        val = (
                            v if isinstance(
                                v, (type(None), bool, int, float, str,
                                    bytes)
                            ) else eval_expr(v, env)
                        )
                        cells.append((
                            slot, self.layout.col_index(t.name, c),
                            self.universe.rank(val),
                        ))
            for i in range(0, len(cells), s_cap):
                out.append(_PendingChangeset(
                False, cells[i:i + s_cap], time.perf_counter()
            ))
            for slot, plane, rank in cells:
                cell_ov[(slot, plane)] = rank
            return len(slots)

        # delete: one cl-only changeset per row (a DELETE bumps the row's
        # causal length; CR-SQLite emits no value changes for it).
        for slot in slots:
            out.append(_PendingChangeset(True, [(slot, 0, 0)], time.perf_counter()))
            live_ov[slot] = False
        return len(slots)

    def _resolve_rows(
        self, op: WriteOp, t, node: int, overlay: tuple[dict, dict]
    ) -> list[int]:
        """Row slots an UPDATE/DELETE targets: pk fast path or predicate.

        Both paths only select rows that are *live on the target node*
        (odd causal length) — SQL UPDATE/DELETE of an absent row affects 0
        rows; a CRDT resurrect requires an INSERT. Rows staged earlier in
        the same batch count as live/dead per the overlay."""
        live_ov, _ = overlay
        if op.where_expr is not None:
            # Scalar-expression WHERE (arithmetic, functions, CASE): the
            # vectorized predicate grammar could not express it, so the
            # live rows of the table filter row-wise through the
            # expression evaluator (SQL semantics: UNKNOWN → excluded).
            from corro_sim.api.exprs import eval_expr

            cands = self._live_slots(t, node, overlay)
            envs = self._row_envs(t, node, cands, overlay)
            return [
                s for s, env in zip(cands, envs)
                if eval_expr(op.where_expr, env) is True
            ]
        pk = pk_equalities(op.where, t.pk)
        if pk is not None:
            slot = self.layout._slots.get((t.name, pk))
            if slot is None:
                return []
            if slot in live_ov:
                return [slot] if live_ov[slot] else []
            cl = int(np.asarray(self.state.table.cl[node, slot]))
            return [slot] if cl % 2 == 1 else []
        # General predicate: evaluate against the node's current view
        # (liveness + pk-term mask applied by Matcher._evaluate), overlaid
        # with the batch's staged writes.
        from corro_sim.subs.query import Select

        sel = Select(table=t.name, columns=(), where=op.where)
        matcher = self._matcher_for(sel, node)
        view = self._overlaid_table(node, overlay)
        if hasattr(matcher, "_rows"):
            # semi-join matcher (WHERE … IN (SELECT …)): its row map IS
            # the slot set (DML over subquery predicates)
            return sorted(matcher._rows(view).keys())
        match, _ = matcher._evaluate(view)
        return [int(s) + matcher._start for s in np.nonzero(match)[0]]

    def _live_slots(self, t, node: int, overlay) -> list[int]:
        """Allocated row slots of ``t`` live on ``node`` (overlay-aware)."""
        live_ov, _ = overlay
        start, cap = self.layout._range(t.name)
        used = self.layout._used[t.name]
        if not used:
            return []
        cl = np.asarray(self.state.table.cl[node, start:start + used])
        out = []
        for i in range(used):
            slot = start + i
            if slot in live_ov:
                if live_ov[slot]:
                    out.append(slot)
            elif cl[i] % 2 == 1:
                out.append(slot)
        return out

    def _row_envs(self, t, node: int, slots, overlay) -> list[dict]:
        """{column: value} environments for row slots on ``node``, with
        the batch overlay's staged cells applied — one batched device
        read per statement, not one per row."""
        from corro_sim.core.crdt import NEG as _NEG

        _, cell_ov = overlay
        if not slots:
            return []
        slots_a = np.asarray(slots, np.int32)
        vr = np.asarray(self.state.table.vr[node, slots_a])  # (k, C)
        envs = []
        neg = int(_NEG)
        for j, slot in enumerate(slots):
            key = self.layout.key_of(slot)
            env = dict(zip(t.pk, key[1])) if key else {}
            for c in t.value_columns:
                plane = self.layout.col_index(t.name, c.name)
                rank = cell_ov.get((slot, plane))
                if rank is None:
                    rank = int(vr[j, plane])
                env[c.name] = (
                    None if rank == neg else self.universe.decode(int(rank))
                )
            envs.append(env)
        return envs


    def _overlaid_table(self, node: int, overlay: tuple[dict, dict]):
        """The committed table state with the batch's staged cells applied
        on the target node — the transaction's own-writes view. Device-side
        scatter of the few staged coordinates; no host round-trip."""
        live_ov, cell_ov = overlay
        st = self.state.table
        if not live_ov and not cell_ov:
            return st
        vr, cl = st.vr, st.cl
        if cell_ov:
            slots = np.fromiter(
                (s for s, _ in cell_ov), np.int32, len(cell_ov)
            )
            planes = np.fromiter(
                (p for _, p in cell_ov), np.int32, len(cell_ov)
            )
            ranks = np.fromiter(cell_ov.values(), np.int32, len(cell_ov))
            vr = vr.at[node, slots, planes].set(ranks)
        if live_ov:
            ls = np.fromiter(live_ov, np.int32, len(live_ov))
            want = np.fromiter(
                (1 if v else 0 for v in live_ov.values()),
                np.int32, len(live_ov),
            )
            bump = ((cl[node, ls] % 2) != want).astype(cl.dtype)
            cl = cl.at[node, ls].add(bump)
        return st.replace(vr=vr, cl=cl)

    # ------------------------------------------------------------ query path
    def _matcher_for(self, select, node: int) -> Matcher:
        # Remaps don't invalidate entries — _on_remap rebinds them in place.
        key = (select.normalized(), node)
        m = self._query_cache.get(key)
        if m is None:
            m = make_matcher(
                f"query-{len(self._query_cache)}", select, node,
                LayoutAdapter(layout=self.layout), self.universe,
            )
            self._query_cache[key] = m
            if len(self._query_cache) > 128:  # bounded compile cache
                self._query_cache.pop(next(iter(self._query_cache)))
        return m

    def query(self, sql: str, node: int = 0, overlay=None) -> list:
        """POST /v1/queries analog: QueryEvent stream as a list of dicts
        (``{"columns"}``, ``{"row"}``…, ``{"eoq"}``).

        ``overlay`` (from :meth:`plan_overlay`) evaluates the query
        against the committed state plus a transaction's staged writes —
        read-your-writes for open pgwire transactions."""
        self._check_node(node)
        with self.locks.tracked(self._lock, f"query node={node}", "read"):
            select = parse_query(sql)
            # matcher evaluates the match+project core; GROUP BY /
            # aggregates / ORDER BY / LIMIT post-process host-side
            m = self._matcher_for(select.base(), node)
            table = (
                self.state.table if overlay is None
                else self._overlaid_table(node, overlay)
            )
            events = m.prime(table)
            if select.has_extras():
                events = post_process(select, events)
            return events

    def query_rows(
        self, sql: str, node: int = 0, overlay=None
    ) -> tuple[list, list]:
        """(columns, rows) convenience over :meth:`query`."""
        events = self.query(sql, node, overlay=overlay)
        cols, rows = [], []
        for e in events:
            if "columns" in e:
                cols = e["columns"]
            elif "row" in e:
                rows.append(e["row"][1])
        return cols, rows

    # ----------------------------------------------------------- subs path
    def subscribe(self, sql: str, node: int = 0):
        """POST /v1/subscriptions analog → (sub_id, initial events)."""
        sub_id, initial, q = self.subscribe_attached(sql, node)
        self.sub_detach_queue(sub_id, q)
        return sub_id, initial

    def subscribe_attached(self, sql: str, node: int = 0):
        """Subscribe AND attach a live queue atomically (no event can land
        between the initial snapshot and the queue registration).

        Returns (sub_id, initial_events, queue)."""
        self._check_node(node)
        with self.locks.tracked(self._lock, f"subscribe node={node}", "write"):
            m, initial = self.subs.get_or_insert(sql, node, self.state.table)
            if initial is None:
                # deduped — replay the initial state from the matcher
                initial = m.prime(self.state.table)
            q: collections.deque = collections.deque()
            self._sub_queues.setdefault(m.id, []).append(q)
            return m.id, initial, q

    def sub_attach(
        self, sub_id: str, from_change_id: int | None = None,
        skip_rows: bool = False,
    ):
        """Re-attach to an existing sub atomically: catch-up (or re-prime)
        and queue registration under one lock, so no event is lost or
        duplicated across the boundary.

        Returns (initial_events, queue). Raises KeyError for an unknown
        sub; returns (None, None) when ``from_change_id`` was compacted
        past (the reference 404s — subscriber must re-subscribe)."""
        with self.locks.tracked(self._lock, f"sub_attach {sub_id}", "write"):
            m = self.subs.get(sub_id)
            if m is None:
                raise KeyError(sub_id)
            if from_change_id is not None:
                caught = m.catch_up(from_change_id)
                if caught is None:
                    return None, None
                initial = [e.as_json() for e in caught]
            elif skip_rows:
                # still announce the feed position (the eoq carries the
                # current change id) so the client knows where it attached
                initial = [{"eoq": {"change_id": m.change_id}}]
            else:
                initial = m.prime(self.state.table)
            q: collections.deque = collections.deque()
            self._sub_queues.setdefault(sub_id, []).append(q)
            return initial, q

    def sub_catch_up(self, sub_id: str, from_change_id: int):
        m = self.subs.get(sub_id)
        if m is None:
            return None
        return m.catch_up(from_change_id)

    def sub_attach_queue(self, sub_id: str) -> collections.deque | None:
        """Register a live event queue for a subscriber stream."""
        if self.subs.get(sub_id) is None:
            return None
        q: collections.deque = collections.deque()
        self._sub_queues.setdefault(sub_id, []).append(q)
        return q

    def sub_detach_queue(self, sub_id: str, q) -> None:
        queues = self._sub_queues.get(sub_id)
        if queues and q in queues:
            queues.remove(q)

    def unsubscribe(self, sub_id: str) -> None:
        with self._lock:
            self.subs.remove(sub_id)
            self._sub_queues.pop(sub_id, None)

    # ------------------------------------------------------------- stepping
    def _dequeue_writes(self):
        """≤1 pending changeset per node → padded write arrays (or None)."""
        n, s = self.cfg.num_nodes, self.cfg.seqs_per_version
        if not any(self._pending):
            return None
        writers = np.zeros((n,), bool)
        rows = np.zeros((n, s), np.int32)
        cols = np.zeros((n, s), np.int32)
        vals = np.zeros((n, s), np.int32)
        dels = np.zeros((n,), bool)
        ncells = np.zeros((n,), np.int32)
        _qwaits: list[float] = []
        now = time.perf_counter()
        for i in range(n):
            if not self._pending[i]:
                continue
            cs: _PendingChangeset = self._pending[i].popleft()
            self.channels.on_recv("write_queue")
            if cs.queued_at:
                _qwaits.append(now - cs.queued_at)
            writers[i] = True
            dels[i] = cs.is_delete
            ncells[i] = len(cs.cells)
            for j, (slot, plane, rank) in enumerate(cs.cells):
                rows[i, j], cols[i, j], vals[i, j] = slot, plane, rank
        self._observe_qwaits(_qwaits)
        return writers, rows, cols, vals, dels, ncells

    def _dequeue_writes_chunk(self, k: int):
        """Up to k changesets per node → round-major (k, ...) write arrays.

        Round r of the chunk commits each node's r-th queued changeset —
        the same one-per-node-per-round discipline as the per-round path,
        just packed ahead of time."""
        n, s = self.cfg.num_nodes, self.cfg.seqs_per_version
        writers = np.zeros((k, n), bool)
        rows = np.zeros((k, n, s), np.int32)
        cols = np.zeros((k, n, s), np.int32)
        vals = np.zeros((k, n, s), np.int32)
        dels = np.zeros((k, n), bool)
        ncells = np.zeros((k, n), np.int32)
        _qwaits: list[float] = []
        now = time.perf_counter()
        for i in range(n):
            q = self._pending[i]
            take = min(k, len(q))
            for r in range(take):
                cs: _PendingChangeset = q.popleft()
                self.channels.on_recv("write_queue")
                if cs.queued_at:
                    _qwaits.append(now - cs.queued_at)
                writers[r, i] = True
                dels[r, i] = cs.is_delete
                ncells[r, i] = len(cs.cells)
                for j, (slot, plane, rank) in enumerate(cs.cells):
                    rows[r, i, j], cols[r, i, j], vals[r, i, j] = (
                        slot, plane, rank,
                    )
        self._observe_qwaits(_qwaits)
        return writers, rows, cols, vals, dels, ncells

    def _observe_qwaits(self, waits: list) -> None:
        """One batched registry touch per drain (hot path)."""
        self.histograms.observe_many(
            "corro_agent_changes_queued_seconds", waits,
            help_="time a committed changeset waited in the write queue "
                  "(corro.agent.changes.queued.seconds)",
        )

    def _record_metrics(self, packed: np.ndarray, names: list) -> None:
        """Fold a (num_metrics, rounds) block into the running totals."""
        # `k` is rebound by the metric-name loops below — keep the chunk
        # round-count under its own name for the annotations at the end
        k_rounds = packed.shape[1]
        self.flight.record_rounds(
            self._rounds_ticked - k_rounds + 1,
            dict(zip(names, packed)),
        )
        sums = packed.sum(axis=1)
        for k, v in zip(names, sums):
            self._totals[k] = self._totals.get(k, 0.0) + float(v)
        for k in ("pend_live", "queue_overflow", "swim_suspects",
                  "swim_down", "sync_pairs", "fault_burst_nodes"):
            if k in names:
                self._lasts[k] = float(packed[names.index(k), -1])
        # SWIM membership transition counters (corro.swim.notification):
        # positive deltas of the belief-state gauges, round by round
        for k, ev in (("swim_suspects", "swim_suspect_events"),
                      ("swim_down", "swim_down_events")):
            if k in names:
                series = packed[names.index(k)]
                prev = self._prev_swim.get(k, 0.0)
                up_k = 0.0
                down_k = 0.0
                for v in series:
                    d = float(v) - prev
                    if d > 0:
                        up_k += d
                    else:
                        down_k -= d
                    prev = float(v)
                self._prev_swim[k] = prev
                self._totals[ev] = self._totals.get(ev, 0.0) + up_k
                if k == "swim_down":
                    # a shrinking down-count = members back up
                    self._totals["swim_up_events"] = (
                        self._totals.get("swim_up_events", 0.0) + down_k
                    )
        self._gap = float(packed[names.index("gap"), -1])
        self._partials = float(packed[names.index("buffered_partials"), -1])
        if "probe_infected" in names:
            self._lasts["probe_infected"] = float(
                packed[names.index("probe_infected"), -1]
            )
            self._lasts["probe_dups"] = float(
                packed[names.index("probe_dups"), -1]
            )
            self._probe_p99_check()
        if "log_wrapped" in names and packed[names.index("log_wrapped")].any():
            # ring-wrap tripwire (engine/step.py): state may be silently
            # wrong from here on — convergence must never be reported
            if not self._log_poisoned:
                row = packed[names.index("log_wrapped")]
                self.flight.annotate(
                    self._rounds_ticked - k_rounds + 1
                    + int(np.argmax(row != 0)),
                    "log_wrapped",
                )
            self._log_poisoned = True
        self._totals["rounds"] = self._rounds_ticked
        # changes applied per round → the reference's chunk-size histogram
        # (corro.agent.changes.processing.chunk_size; its own buckets)
        if "fresh" in names:
            from corro_sim.utils.metrics import CHUNK_SIZE_BUCKETS

            per_round = packed[names.index("fresh")]
            if "writes" in names:
                per_round = per_round + packed[names.index("writes")]
            self.histograms.observe_many(
                "corro_agent_changes_processing_chunk_size",
                [float(v) for v in per_round],
                help_="changes applied per processing round "
                      "(corro.agent.changes.processing.chunk_size)",
                buckets=CHUNK_SIZE_BUCKETS,
            )

    _STAGE_HISTO = {
        "step": "corro_agent_changes_processing_time_seconds",
        "chunk_step": "corro_agent_changes_processing_time_seconds",
        "subs": "corro_subs_changes_processing_duration_seconds",
    }

    def _observe_stage(self, stage: str, seconds: float, per: int = 1) -> None:
        ms = seconds * 1000.0 / max(per, 1)
        ewma, _ = self._stage_ms.get(stage, (ms, ms))
        self._stage_ms[stage] = (ewma + 0.2 * (ms - ewma), ms)
        name = self._STAGE_HISTO.get(stage)
        if name is not None:
            self.histograms.observe(
                name, seconds / max(per, 1),
                help_=f"per-round {stage} wall (reference histogram analog)",
            )

    def stage_timings(self) -> dict:
        """{stage: {"ewma_ms": .., "last_ms": ..}} per-round wall by stage."""
        with self._lock:
            return {
                k: {"ewma_ms": round(e, 3), "last_ms": round(l, 3)}
                for k, (e, l) in self._stage_ms.items()
            }

    def _tick_locked(self, rounds: int) -> None:
        from corro_sim.utils.metrics import counters

        if rounds > 0:
            counters.inc(
                "corro_chunk_dispatch_total", n=rounds,
                labels='{runner="live_step"}',
                help_="chunk dispatches by program",
            )
        for _ in range(rounds):
            self._apply_scenario_round()
            t0 = time.perf_counter()
            w = self._dequeue_writes()
            if w is None:
                n, s = self.cfg.num_nodes, self.cfg.seqs_per_version
                w = (
                    np.zeros((n,), bool),
                    np.zeros((n, s), np.int32),
                    np.zeros((n, s), np.int32),
                    np.zeros((n, s), np.int32),
                    np.zeros((n,), bool),
                    np.zeros((n,), np.int32),
                )
            self._observe_stage("dequeue", time.perf_counter() - t0)
            t0 = time.perf_counter()
            key = round_key(self._root_key, self._rounds_ticked)
            self.state, metrics = self._step(
                self.state,
                key,
                jnp.asarray(self._alive),
                jnp.asarray(self._part),
                tuple(jnp.asarray(x) for x in w),
            )
            self._rounds_ticked += 1
            self._finish_tick(metrics, t0, mode="live_step", per=1,
                              stage="step")

    def _tick_chunk_locked(self) -> None:
        """Advance _CHUNK rounds in ONE jitted dispatch (`lax.scan`).

        Per-round semantics are identical to _tick_locked (same keys, same
        one-changeset-per-node-per-round drain); only the host round-trip
        count changes. Subscription matchers see the chunk-final state —
        diff-based, so events coalesce exactly like the reference's
        candidate batching (1000 rows / 600 ms, ``pubsub.rs:1154-1296``) —
        but callers gate on _subs_active() to preserve per-round event
        granularity whenever someone is actually watching."""
        if self._scenario is not None and not self._scenario_uniform(_CHUNK):
            # the scenario timeline changes topology inside this window —
            # alive/part are per-chunk constants here, so run the rounds
            # singly (identical keys/semantics, just more dispatches)
            self._tick_locked(_CHUNK)
            return
        self._apply_scenario_round()
        self._chunk_dispatches += 1
        from corro_sim.utils.metrics import counters

        counters.inc(
            "corro_chunk_dispatch_total", labels='{runner="live_chunk"}',
            help_="chunk dispatches by program",
        )
        t0 = time.perf_counter()
        w = self._dequeue_writes_chunk(_CHUNK)
        self._observe_stage("dequeue", time.perf_counter() - t0, per=_CHUNK)
        t0 = time.perf_counter()
        self.state, ms = self._multi_step(
            self.state,
            self._root_key,
            np.uint32(self._rounds_ticked),
            jnp.asarray(self._alive),
            jnp.asarray(self._part),
            tuple(jnp.asarray(x) for x in w),
        )
        self._rounds_ticked += _CHUNK
        self._finish_tick(ms, t0, mode="live_chunk", per=_CHUNK,
                          stage="chunk_step")

    def _finish_tick(self, metrics, t0: float, mode: str, per: int,
                     stage: str) -> None:
        """Shared tail of both tick paths: pack the step metrics into
        ONE device array (per-leaf asarray costs a full ~80 ms tunnel
        round-trip each on the axon platform), start its device→host
        copy async, run the subscription diff UNDER the transfer, then
        resolve + record. The subs diff reads state, not the metric
        stack, so the reorder changes nothing observable — it just
        stops the copy stalling ahead of host work (the driver-side
        chunk pipeline's async-fetch half; doc/performance.md).
        ``t0`` is the dispatch start; ``per`` rounds covered."""
        names = sorted(metrics)
        stack = jnp.stack(
            [metrics[k].astype(jnp.float32) for k in names]
        )
        _start_async_fetch(stack)
        t_dispatch = time.perf_counter() - t0
        t1 = time.perf_counter()
        self._notify_subs()
        subs_s = time.perf_counter() - t1
        t1 = time.perf_counter()
        packed = np.asarray(stack)
        fetch_wait = time.perf_counter() - t1
        _global_histograms.observe(
            PIPELINE_FETCH_WAIT, fetch_wait,
            labels=f'{{mode="{mode}"}}',
            help_=PIPELINE_FETCH_WAIT_HELP,
        )
        self._observe_stage(stage, t_dispatch + fetch_wait, per=per)
        # scalar-per-metric ticks widen to one (metrics, 1) column
        self._record_metrics(
            packed if packed.ndim > 1 else packed[:, None], names
        )
        self._observe_stage("subs", subs_s)

    def _subs_active(self) -> bool:
        return len(self.subs) > 0 or bool(self._sub_queues)

    def warmup(self) -> None:
        """Compile the hot paths before real traffic arrives.

        Covers the single-round step, the chunked multi-round step, and
        the rank-remap kernels (an identity remap traces the same programs
        a respace does). First XLA compile through the TPU tunnel is tens
        of seconds — an agent serving an API should pay it at boot, not on
        the first client transaction."""
        from corro_sim.utils.metrics import counters, histograms
        from corro_sim.utils.tracing import tracer

        with self.locks.tracked(self._lock, "warmup", "write"):
            t0 = time.perf_counter()
            with tracer.span("warmup", program="live", slow_warn=False):
                self._tick_locked(1)
                if not self._subs_active():
                    self._tick_chunk_locked()
                ranks = list(self.universe._ranks)
                if ranks:
                    self._on_remap(ranks, ranks)
            counters.inc(
                "corro_compile_total", labels='{program="live"}',
                help_="XLA chunk-program compiles by program",
            )
            histograms.observe(
                "corro_compile_seconds", time.perf_counter() - t0,
                labels='{program="live"}',
                help_="AOT lower+compile wall by program",
            )

    def tick(self, rounds: int = 1) -> None:
        """Advance the cluster `rounds` gossip rounds (no new writes)."""
        with self.locks.tracked(self._lock, "tick", "write"):
            remaining = rounds
            while remaining >= _CHUNK and not self._subs_active():
                self._tick_chunk_locked()
                remaining -= _CHUNK
            self._tick_locked(remaining)

    def _notify_subs(self) -> None:
        events = self.subs.step(self.state.table)
        delivered = False
        for sub_id, evs in events.items():
            for ev in evs:
                # emit-round stamp: the workload engine's delivery-latency
                # clock (change commit round -> this round); exact even
                # when a subscriber drains its queue rounds later
                ev.round = self._rounds_ticked
            queues = self._sub_queues.get(sub_id, ())
            for q in queues:  # live streams
                q.extend(evs)
            if queues:
                delivered = True
                self.channels.on_send("subs_events", len(evs) * len(queues))
        if delivered:
            # depth from ground truth, once per tick: attached consumers
            # drain their deques directly, so the running send-recv
            # difference would report a phantom backlog
            self.channels.set_depth(
                "subs_events",
                sum(
                    len(q)
                    for qs in self._sub_queues.values()
                    for q in qs
                ),
            )

    @property
    def converged(self) -> bool:
        """Every live node caught up RIGHT NOW: version-head gap 0 AND
        no buffered partial versions AND no host-side pending
        changesets — THE convergence predicate (``run_until_converged``
        and the workload load harness both gate on this; keep them on
        one definition)."""
        return (
            self._gap == 0.0
            and self._partials == 0.0
            and not any(self._pending)
        )

    def run_until_converged(self, max_rounds: int = 512) -> int | None:
        """Tick until every live node caught up; returns the round count.

        Convergence = version-head gap 0 AND no buffered partial versions
        AND no host-side pending changesets (tightened from gap-only: a
        seq-incomplete version in the window has head unmoved but is
        in-flight state, not convergence — ``agent.rs:1101-1119``).

        The first few rounds run singly (small clusters converge there
        without ever compiling the chunked program); long runs switch to
        _CHUNK-round dispatches."""
        with self.locks.tracked(self._lock, "run_until_converged", "write"):
            done = 0
            while done < max_rounds:
                if (
                    done >= 4
                    and max_rounds - done >= _CHUNK
                    and not self._subs_active()
                ):
                    self._tick_chunk_locked()
                    done += _CHUNK
                else:
                    self._tick_locked(1)
                    done += 1
                # the step already computed the gap/partial metrics —
                # reuse the packed transfer instead of re-reading state
                if self._log_poisoned:
                    return None  # permanent: check .log_poisoned, don't retry
                if self.converged:
                    return done
        return None

    # ------------------------------------------------------- introspection
    @property
    def log_poisoned(self) -> bool:
        """Ring-wrap tripwire latched (engine/step.py): state may be
        silently wrong; ``run_until_converged`` will return None forever.
        Distinguishes a corrupt run from one that needs more rounds."""
        return self._log_poisoned

    def table_stats(self) -> dict:
        """GET /v1/table_stats analog (``api/public/mod.rs:535-590``)."""
        cl = np.asarray(self.state.table.cl)
        out = {}
        for name in self.layout.schema.tables:
            start, cap = self.layout._range(name)
            live = (cl[:, start:start + cap] % 2 == 1).sum(axis=1)
            out[name] = {
                "allocated_pks": self.layout._used[name],
                "capacity": cap,
                "live_rows_per_node": live.tolist(),
            }
        return out

    def actor_versions(self, actor: int) -> dict:
        """Admin `actor version` analog: bookkeeping for one actor
        (``corro-admin`` Actor Version command)."""
        self._check_node(actor)
        head = np.asarray(self.state.book.head)[:, actor]
        written = int(np.asarray(self.state.log.head)[actor])
        cleared = int(np.asarray(self.state.log.cleared)[actor].sum())
        return {
            "actor": actor,
            "versions_written": written,
            "versions_cleared": cleared,
            "applied_head_per_node": head.tolist(),
        }

    def members(self) -> list[dict]:
        """Cluster membership view (admin `cluster members` analog)."""
        out = []
        inc = None
        if self.cfg.swim_enabled:
            sw = self.state.swim
            # windowed SWIM keeps self in slot 0; the full plane on the
            # diagonal
            inc = np.asarray(
                sw.self_inc if hasattr(sw, "self_inc")
                else np.asarray(sw.inc).diagonal()
            )
        for i in range(self.cfg.num_nodes):
            out.append(
                {
                    "id": i,
                    "alive": bool(self._alive[i]),
                    "partition": int(self._part[i]),
                    "pending_writes": len(self._pending[i]),
                    **({"incarnation": int(inc[i])} if inc is not None else {}),
                }
            )
        return out

    def probe_trace(self):
        """The run's probe provenance (obs.probes.ProbeTrace); None when
        ``cfg.probes == 0``."""
        if not self.cfg.probes:
            return None
        from corro_sim.obs.probes import ProbeTrace

        return ProbeTrace.from_state(
            self.cfg, self.state, driver="live_cluster",
            rounds=self._rounds_ticked,
        )

    def _suspected_by(self) -> np.ndarray:
        """(N,) — how many observers currently suspect each node (SWIM
        belief planes; zeros when SWIM is off)."""
        n = self.cfg.num_nodes
        out = np.zeros(n, np.int64)
        if not self.cfg.swim_enabled:
            return out
        sw = self.state.swim
        status = np.asarray(sw.status)
        if hasattr(sw, "member"):  # windowed O(N·K) belief state
            member = np.asarray(sw.member)
            tracked = member >= 0
            np.add.at(out, member[tracked & (status == 1)], 1)
        else:
            out += (status == 1).sum(axis=0)
        return out

    def node_lag(self, top_k: int = 8) -> dict:
        """The per-node lag observatory (obs.probes.node_lag_observatory):
        rows-behind, last-sync age (probe-tracked), SWIM suspicion, and
        the top-k laggards. Works with probes off — only the sync-age
        column needs the tracer."""
        from corro_sim.obs.probes import node_lag_observatory

        last_sync = (
            np.asarray(self.state.probe.last_sync)
            if self.cfg.probes else None
        )
        return node_lag_observatory(
            np.asarray(self.state.log.head),
            np.asarray(self.state.book.head),
            self._alive,
            self._rounds_ticked,
            last_sync=last_sync,
            suspected_by=self._suspected_by(),
            top_k=top_k,
        )

    def probe_report(self) -> dict:
        """The GET /v1/probes body: per-probe summaries + infection
        trees (stretch vs the current ground-truth peer graph) plus the
        lag observatory."""
        from corro_sim.obs.probes import ground_truth_adjacency

        tr = self.probe_trace()
        out = {"node_lag": self.node_lag()}
        if tr is None:
            out["probes"] = None
            out["note"] = (
                "probe tracer disabled — start the cluster with "
                "cfg_overrides={'probes': K}"
            )
            return out
        adj = ground_truth_adjacency(
            self._alive, self._part,
            blackhole=self.cfg.faults.blackhole,
        )
        out.update(tr.report(adj=adj))
        return out

    def _probe_p99_check(self) -> None:
        """Flight annotation when a probe's p99 delivery lag worsens —
        called from the metrics fold, but only when the infected count
        moved (p99 can only change on a new infection, and the check
        costs a (K, N) device read)."""
        cur = self._lasts.get("probe_infected")
        if cur is None or cur == self._probe_infected_last:
            return
        self._probe_infected_last = cur
        tr = self.probe_trace()
        if tr is None:
            return
        p99 = tr.delivery_p99()
        if (
            p99 is not None
            and self._probe_p99 is not None
            and p99 > self._probe_p99
        ):
            self.flight.annotate(
                self._rounds_ticked, "probe_p99_regression",
                p99=p99, prev=self._probe_p99,
            )
        if p99 is not None:
            self._probe_p99 = p99

    def metrics_lasts(self) -> dict:
        """Last-round gauge snapshots (ring depth, cumulative overflow)."""
        with self._lock:
            return dict(self._lasts)

    def metrics_totals(self) -> dict:
        with self._lock:
            return dict(self._totals)

    # ---------------------------------------------------- fault injection
    def load_scenario(self, spec: str, rounds: int = 128,
                      seed: int | None = None) -> dict:
        """Arm a chaos scenario (faults/scenarios.py) on the live cluster.

        The scenario's alive/partition timeline replays relative to the
        CURRENT round — each subsequent tick applies the matching row
        (holding the last row once the timeline ends) and its fault-knob
        overrides are compiled into the step programs. Scheduled events
        annotate the flight record as the rounds pass. Returns a summary
        dict (the POST /v1/faults body)."""
        import dataclasses as _dc

        from corro_sim.faults import make_scenario

        with self.locks.tracked(self._lock, "load_scenario", "write"):
            sc = make_scenario(
                spec, self.cfg.num_nodes, rounds=rounds,
                write_rounds=0,  # live writes come from the API, not a
                # synthetic write phase
                seed=self._seed if seed is None else seed,
            )
            # apply relative to the construction-time baseline, never to
            # a previously armed scenario's knobs (no fault leak between
            # scenarios)
            new_cfg = sc.apply(_dc.replace(
                self.cfg, faults=self._baseline_faults
            ))
            if new_cfg != self.cfg:
                self.cfg = new_cfg
                self._resize_fault_burst()
                self._build_step()  # fault knobs are compiled in
            self._scenario = sc
            self._scenario_base = self._rounds_ticked
            self._scenario_events = 0
            self.flight.annotate(
                self._rounds_ticked + 1, "scenario_loaded",
                scenario=sc.spec, rounds=rounds,
            )
            self.flight.set_meta(scenario=sc.spec)
            return self.fault_report()

    def clear_scenario(self) -> dict:
        """Disarm the scenario: restore full liveness, one partition and
        the construction-time baseline fault knobs."""
        import dataclasses as _dc

        with self.locks.tracked(self._lock, "clear_scenario", "write"):
            self._scenario = None
            self._alive[:] = True
            self._part[:] = 0
            if self.cfg.faults != self._baseline_faults:
                self.cfg = _dc.replace(
                    self.cfg, faults=self._baseline_faults
                )
                self._resize_fault_burst()
                self._build_step()
            self.flight.annotate(
                self._rounds_ticked + 1, "scenario_cleared",
            )
            return self.fault_report()

    def _resize_fault_burst(self) -> None:
        """Match ``state.fault_burst`` to the (possibly re-armed) fault
        config: the Gilbert burst state is per-node (N,) when the knob is
        on, the (1,) placeholder when off. Without this, a cluster built
        with burst off that arms a burst scenario would evolve a single
        shared coin (index clamping) instead of per-node burst state."""
        want = (
            (self.cfg.num_nodes,) if self.cfg.faults.burst_enter > 0
            else (1,)
        )
        if tuple(self.state.fault_burst.shape) != want:
            self.state = self.state.replace(
                fault_burst=jnp.zeros(want, bool)
            )

    def _apply_scenario_round(self) -> None:
        """Set alive/partition ground truth for the round about to run
        from the armed scenario's timeline; annotate passing events."""
        sc = self._scenario
        if sc is None:
            return
        r = self._rounds_ticked - self._scenario_base
        if sc.alive is not None:
            self._alive = np.asarray(
                sc.alive[min(r, len(sc.alive) - 1)], bool
            ).copy()
        if sc.part is not None:
            self._part = np.asarray(
                sc.part[min(r, len(sc.part) - 1)], np.int32
            ).copy()
        while self._scenario_events < len(sc.events):
            ev_r, ev_name, ev_attrs = sc.events[self._scenario_events]
            if ev_r > r:
                break
            self.flight.annotate(
                self._scenario_base + ev_r + 1, "fault_event",
                kind=ev_name, **ev_attrs,
            )
            self._scenario_events += 1

    def _scenario_uniform(self, k: int) -> bool:
        """Whether the next ``k`` scenario rows are identical — the
        chunked multi-round dispatch passes alive/part as per-chunk
        constants, so a varying window must fall back to single rounds."""
        sc = self._scenario
        if sc is None:
            return True
        r = self._rounds_ticked - self._scenario_base
        for arr in (sc.alive, sc.part):
            if arr is None:
                continue
            lo = min(r, len(arr) - 1)
            hi = min(r + k - 1, len(arr) - 1)
            window = arr[lo:hi + 1]
            if len(window) and (window != window[0]).any():
                return False
        return True

    def fault_report(self) -> dict:
        """The GET /v1/faults body: armed scenario, compiled fault knobs,
        injected-fault totals and the burst gauge."""
        import dataclasses as _dc

        with self._lock:
            sc = self._scenario
            totals = {
                k: int(v) for k, v in sorted(self._totals.items())
                if k.startswith("fault_") and k != "fault_burst_nodes"
            }
            faults = _dc.asdict(self.cfg.faults)
            faults["blackhole"] = [
                list(p) for p in self.cfg.faults.blackhole
            ]
            return {
                "scenario": sc.spec if sc is not None else None,
                "scenario_round": (
                    self._rounds_ticked - self._scenario_base
                    if sc is not None else None
                ),
                "heal_round": sc.heal_round if sc is not None else None,
                "faults": faults,
                "enabled": self.cfg.faults.enabled,
                "totals": totals,
                "burst_nodes": int(
                    self._lasts.get("fault_burst_nodes", 0)
                ),
                "alive": int(self._alive.sum()),
                "partitions": int(len(set(self._part.tolist()))),
            }

    def set_alive(self, node: int, alive: bool) -> None:
        self._check_node(node)
        with self._lock:
            self._alive[node] = alive
            self.flight.annotate(
                self._rounds_ticked + 1, "schedule_transition",
                kind="set_alive", node=node, alive=bool(alive),
            )

    def set_partition(self, part: list[int]) -> None:
        with self._lock:
            assert len(part) == self.cfg.num_nodes
            self._part = np.asarray(part, np.int32)
            self.flight.annotate(
                self._rounds_ticked + 1, "schedule_transition",
                kind="set_partition",
                partitions=int(len(set(int(p) for p in part))),
            )

    def rejoin(self, node: int) -> dict:
        """Admin `cluster rejoin` analog: revive with a *renewed identity*.

        The reference sends ``FocaCmd::Rejoin`` — foca re-announces with a
        fresh timestamp so peers that declared the node down accept it
        back (``actor.rs:199-210``, ``corro-admin/src/lib.rs:364-383``).
        Here: mark the node alive and bump its self-incarnation, the
        SWIM refutation that overrides any DOWN belief as it gossips out.
        """
        self._check_node(node)
        with self.locks.tracked(self._lock, f"rejoin node={node}", "write"):
            self._alive[node] = True
            inc = None
            if self.cfg.swim_enabled:
                from corro_sim.membership.swim import pack_swim, swim_layout

                swim = self.state.swim
                if hasattr(swim, "member"):  # windowed: self = slot 0
                    lo = swim_layout(swim.belief.dtype)
                    new_inc = min(
                        int(swim.self_inc[node]) + 1, lo.inc_max
                    )
                    swim = swim.replace(
                        belief=swim.belief.at[node, 0].set(
                            pack_swim(0, new_inc, 0, dtype=lo.dtype)
                        )
                    )
                else:
                    # saturate like swim_step's refutation — wrapping the
                    # packed inc field would reset precedence to zero
                    lo = swim_layout(swim.p.dtype)
                    new_inc = min(
                        int(swim.inc[node, node]) + 1, lo.inc_max
                    )
                    # packed self-entry: ALIVE at the bumped incarnation
                    swim = swim.replace(
                        p=swim.p.at[node, node].set(
                            pack_swim(0, new_inc, 0, dtype=lo.dtype)
                        )
                    )
                self.state = self.state.replace(swim=swim)
                inc = new_inc
            return {"node": node, "alive": True, "incarnation": inc}

    def set_cluster_id(self, node: int, cluster_id: int) -> dict:
        """Admin `cluster set-id` analog.

        The reference stores a ``ClusterId(u16)`` per agent and refuses
        gossip/sync across different ids (``actor.rs:222``, sync
        ``Rejection::DifferentCluster``, ``api/peer.rs:1488-1499``). The
        simulator's partition plane IS that wall — nodes with different
        partition ids exchange nothing — so cluster ids map onto it."""
        self._check_node(node)
        if not (0 <= cluster_id < 2**16):
            raise ExecError(f"cluster id {cluster_id} out of u16 range")
        with self.locks.tracked(
            self._lock, f"set_cluster_id node={node}", "write"
        ):
            self._part[node] = cluster_id
            return {"node": node, "cluster_id": cluster_id}

    def reconcile_gaps(self) -> dict:
        """Admin `sync reconcile-gaps` analog: collapse bookkeeping state.

        The reference's ``collapse_gaps`` rewrites
        ``__corro_bookkeeping_gaps`` so adjacent/overlapping ranges merge
        (``corro-admin/src/lib.rs:315-341``). The tensor bookkeeping
        equivalent: re-absorb any window bits contiguous with the head
        into the head counter. Normally a no-op — the step function
        absorbs eagerly — so a nonzero head delta means drift repair."""
        from corro_sim.core.bookkeeping import Bookkeeping
        from corro_sim.utils.bits import absorb

        with self.locks.tracked(self._lock, "reconcile gaps", "write"):
            book = self.state.book
            head, win = absorb(
                book.head, book.win, self.cfg.chunks_per_version
            )
            changed = np.asarray(head != book.head)
            self.state = self.state.replace(
                book=Bookkeeping(head=head, win=win)
            )
            return {
                # (node, actor) head entries that moved, and how many
                # distinct actors they span
                "entries_reconciled": int(changed.sum()),
                "actors_reconciled": int(changed.any(axis=0).sum()),
            }

    # --------------------------------------------------------- migrations
    def migrate(self, schema_sql: str, capacities: dict | None = None) -> dict:
        """POST /v1/migrations analog: diff-based, additive-only
        (``apply_schema``, ``corro-types/src/schema.rs:274-646``).

        Merge semantics, like the reference's ``execute_schema``
        (``api/public/mod.rs:443-528``): the DDL is *merged* into the
        current schema — tables it doesn't mention are retained (drops are
        refused anyway), tables it does mention must be additive."""
        with self.locks.tracked(self._lock, "migrate", "write"):
            new_schema = parse_and_constrain(schema_sql)
            merged = dataclasses.replace(
                new_schema,
                tables={**self.layout.schema.tables, **new_schema.tables},
            )
            plan = self.layout.migrate(merged, capacities=capacities)
            self._schema_history.append(schema_sql)
            new_rows = self.layout.num_rows
            new_cols = max(self.layout.num_cols, 1)
            grew = (
                new_rows > self.cfg.num_rows or new_cols > self.cfg.num_cols
            )
            if grew:
                self._grow(new_rows, new_cols)
            self._query_cache.clear()
            return {
                "new_tables": sorted(plan.new_tables),
                "new_columns": sorted(plan.new_columns),
                "resized": grew,
            }

    def _grow(self, new_rows: int, new_cols: int) -> None:
        """Pad the row/col axes of every table-shaped tensor; recompile."""
        cfg = dataclasses.replace(
            self.cfg, num_rows=new_rows, num_cols=new_cols
        ).validate()
        st = self.state
        dr = new_rows - self.cfg.num_rows
        dc = new_cols - self.cfg.num_cols

        def pad_rc(x, fill):
            return jnp.pad(
                x, ((0, 0), (0, dr), (0, dc)), constant_values=fill
            )

        table = st.table.replace(
            cv=pad_rc(st.table.cv, 0),
            vr=pad_rc(st.table.vr, int(NEG)),
            site=pad_rc(st.table.site, -1),
            cl=jnp.pad(st.table.cl, ((0, 0), (0, dr)), constant_values=0),
        )
        own = st.own
        own_pads = {}
        for f in dataclasses.fields(own):
            v = getattr(own, f.name)
            if v.ndim == 2:  # (R, C) planes
                fill = int(NEG) if f.name == "vr" else (
                    -1 if f.name in ("site", "actor", "ractor") else 0
                )
                own_pads[f.name] = jnp.pad(
                    v, ((0, dr), (0, dc)), constant_values=fill
                )
            elif v.ndim == 1:  # (R,) rows
                fill = -1 if f.name in ("ractor", "rsite") else 0
                own_pads[f.name] = jnp.pad(
                    v, ((0, dr),), constant_values=fill
                )
            else:
                own_pads[f.name] = v
        row_cdf = jnp.pad(st.row_cdf, ((0, dr),), constant_values=1.0)
        self.state = st.replace(
            table=table, own=own.replace(**own_pads), row_cdf=row_cdf
        )
        self.cfg = cfg
        self._build_step()

    def schema_sql(self) -> dict:
        """The current schema, rendered table-by-table."""
        return {
            name: {
                "pk": list(t.pk),
                "columns": [
                    {
                        "name": c.name,
                        "type": c.type,
                        "nullable": c.nullable,
                        "pk": c.primary_key,
                    }
                    for c in t.columns
                ],
            }
            for name, t in self.layout.schema.tables.items()
        }

    def _check_node(self, node: int) -> None:
        if not (0 <= node < self.cfg.num_nodes):
            raise ExecError(
                f"node {node} out of range (cluster size "
                f"{self.cfg.num_nodes})"
            )
