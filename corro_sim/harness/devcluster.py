"""Devcluster: the topology-file harness (corro-devcluster analog).

The reference's dev harness parses a ``Simple`` topology file of
``A -> B`` edges (`corro-devcluster/src/topology/mod.rs`), assigns each
named node a port + state directory, generates per-node configs whose
``bootstrap`` lists implement the edges, and spawns one real agent
process per node (`src/main.rs:104-216`).

The TPU-native unit of deployment is one *cluster* process (see
`corro_sim/harness/cluster.py`), so the backend here maps the topology
onto a single LiveCluster:

- every named node becomes an ordinal (sorted by name, deterministic);
- bootstrap edges only seed SWIM membership in the reference — once
  membership converges, gossip targets any member, so steady-state
  connectivity is the *connected component* of the bootstrap graph.
  Components map onto the simulator's partition ids: nodes in different
  components never exchange gossip or sync, exactly like agents whose
  bootstrap chains never meet;
- per-node state directories are still created, each holding a
  ``node.json`` with the name → ordinal/API mapping (the "which agent is
  this" role the reference's per-node config.toml plays).
"""

from __future__ import annotations

import json
import os
import re

_EDGE = re.compile(
    r"^\s*([A-Za-z][A-Za-z0-9_-]*)\s*->\s*([A-Za-z][A-Za-z0-9_-]*)\s*$"
)


class TopologyError(ValueError):
    pass


def parse_topology(text: str) -> dict[str, list[str]]:
    """``A -> B`` lines → adjacency {node: [bootstrap targets]}.

    Nodes appearing only on the right are registered with no edges, like
    the reference's ``parse_edge`` (topology/mod.rs:22-38). Blank lines
    and ``#`` comments are skipped."""
    adj: dict[str, list[str]] = {}
    for i, line in enumerate(text.splitlines(), 1):
        s = line.strip()
        if not s or s.startswith("#"):
            continue
        m = _EDGE.match(s)
        if not m:
            raise TopologyError(f"syntax error in topology line {i}: {s!r}")
        a, b = m.group(1), m.group(2)
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    return adj


def all_nodes(adj: dict[str, list[str]]) -> list[str]:
    """Sorted node names (``get_all_nodes``, topology/mod.rs:40-52)."""
    names = set(adj)
    for targets in adj.values():
        names.update(targets)
    return sorted(names)


def components(adj: dict[str, list[str]]) -> dict[str, int]:
    """Name → connected-component id (undirected reachability).

    Gossip connectivity is symmetric once membership converges, so the
    undirected component is the right equivalence — a lone ``A -> B``
    edge makes A and B one cluster."""
    names = all_nodes(adj)
    undirected: dict[str, set] = {n: set() for n in names}
    for a, targets in adj.items():
        for b in targets:
            undirected[a].add(b)
            undirected[b].add(a)
    comp: dict[str, int] = {}
    next_id = 0
    for n in names:
        if n in comp:
            continue
        stack = [n]
        comp[n] = next_id
        while stack:
            cur = stack.pop()
            for other in undirected[cur]:
                if other not in comp:
                    comp[other] = next_id
                    stack.append(other)
        next_id += 1
    return comp


def build_cluster(
    topology_text: str,
    schema_sql: str,
    state_dir: str | None = None,
    seed: int = 0,
    default_capacity: int = 256,
    tripwire=None,
):
    """Topology + schema → (LiveCluster, name→ordinal map).

    The cluster's partition vector encodes the topology's connected
    components, so cross-component convergence never happens (the same
    outcome as reference agents whose bootstrap sets never link up)."""
    from corro_sim.harness.cluster import LiveCluster

    adj = parse_topology(topology_text)
    names = all_nodes(adj)
    if not names:
        raise TopologyError("topology has no nodes")
    comp = components(adj)
    ordinal = {name: i for i, name in enumerate(names)}
    cluster = LiveCluster(
        schema_sql,
        num_nodes=len(names),
        seed=seed,
        default_capacity=default_capacity,
        tripwire=tripwire,
    )
    cluster.set_partition([comp[n] for n in names])
    if state_dir:
        from corro_sim.membership.bootstrap import generate_bootstrap

        # every named node gets a deterministic gossip address, and each
        # node's bootstrap edges resolve through the same pipeline a real
        # agent uses (generate_bootstrap: parse → resolve → ≤10), exactly
        # how the reference devcluster writes per-node config bootstrap
        # lists (corro-devcluster/src/main.rs:104-216)
        gossip_addr = {
            n: ("127.0.0.1", 9000 + ordinal[n]) for n in names
        }

        def name_resolver(host, port, dns):
            return [gossip_addr[host]] if host in gossip_addr else []

        for name in names:
            node_state = os.path.join(state_dir, name)
            os.makedirs(node_state, exist_ok=True)
            boots = generate_bootstrap(
                [f"{b}:{gossip_addr[b][1]}" for b in adj.get(name, [])],
                resolve=name_resolver,
            )
            with open(os.path.join(node_state, "node.json"), "w") as f:
                json.dump(
                    {
                        "name": name,
                        "node": ordinal[name],
                        "component": comp[name],
                        "gossip_addr": list(gossip_addr[name]),
                        "bootstrap": adj.get(name, []),
                        "bootstrap_addrs": [list(a) for a in boots],
                    },
                    f,
                    indent=2,
                )
    return cluster, ordinal
