"""Host-side harness: live cluster runtime, snapshots, devcluster backend."""

from corro_sim.harness.cluster import LiveCluster  # noqa: F401
