"""TLS helpers: CA / server / client certificate generation + contexts.

Mirrors the reference's cert tooling (``corro-types/src/tls.rs`` — rcgen
ECDSA P-384/SHA-384 certs; CA valid 5 years with keyCertSign/cRLSign,
server cert with an IP SAN valid 1 year, client cert with an empty DN for
mutual TLS) and the ``corrosion tls ca|server|client generate`` CLI
(``corrosion/src/command/tls.rs``, file names ``ca_cert.pem``,
``ca_key.pem``, ``server_cert.pem``…).

Where the reference feeds these into quinn's rustls config for the QUIC
gossip transport (``api/peer.rs:129-343``), the TPU-native framework has
no gossip wire — its network surfaces are the HTTP API and the pg wire
listener — so the context builders here produce ``ssl.SSLContext``s for
those servers (server-side, with optional required client auth = mTLS)
and for clients (custom CA, optional client cert, ``insecure`` analog of
the reference's ``InsecureVerifier``).
"""

from __future__ import annotations

import datetime
import ipaddress
import ssl

try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    _CRYPTOGRAPHY_ERROR = None
except ModuleNotFoundError as _e:  # optional dep: fail at USE time with
    # a clear message, not at import time (importing corro_sim.tls must
    # stay safe for environments without the package)
    x509 = hashes = serialization = ec = NameOID = None
    _CRYPTOGRAPHY_ERROR = _e

_DAY = datetime.timedelta(days=1)


def _require_cryptography() -> None:
    if _CRYPTOGRAPHY_ERROR is not None:
        raise RuntimeError(
            "corro_sim.tls certificate generation requires the "
            "'cryptography' package (pip install cryptography)"
        ) from _CRYPTOGRAPHY_ERROR


def _keypair():
    _require_cryptography()
    return ec.generate_private_key(ec.SECP384R1())


def _pem_key(key) -> str:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    ).decode()


def _pem_cert(cert) -> str:
    return cert.public_bytes(serialization.Encoding.PEM).decode()


def generate_ca() -> tuple[str, str]:
    """Self-signed root CA → (cert_pem, key_pem). 5-year validity,
    keyCertSign + cRLSign key usage (tls.rs:17-39)."""
    key = _keypair()
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "Corro-Sim Root CA")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + 365 * 5 * _DAY)
        .add_extension(
            x509.BasicConstraints(ca=True, path_length=None), critical=True
        )
        .add_extension(
            x509.KeyUsage(
                digital_signature=False, content_commitment=False,
                key_encipherment=False, data_encipherment=False,
                key_agreement=False, key_cert_sign=True, crl_sign=True,
                encipher_only=False, decipher_only=False,
            ),
            critical=True,
        )
        .add_extension(
            x509.SubjectKeyIdentifier.from_public_key(key.public_key()),
            critical=False,
        )
        .sign(key, hashes.SHA384())
    )
    return _pem_cert(cert), _pem_key(key)


def _load_ca(ca_cert_pem: str, ca_key_pem: str):
    _require_cryptography()
    ca_cert = x509.load_pem_x509_certificate(ca_cert_pem.encode())
    ca_key = serialization.load_pem_private_key(ca_key_pem.encode(), None)
    return ca_cert, ca_key


def _signed(builder, ca_cert, ca_key, key) -> str:
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        builder.issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now)
        .not_valid_after(now + 365 * _DAY)
        .add_extension(
            x509.SubjectKeyIdentifier.from_public_key(key.public_key()),
            critical=False,
        )
        .sign(ca_key, hashes.SHA384())
    )
    return _pem_cert(cert)


def generate_server_cert(
    ca_cert_pem: str, ca_key_pem: str, ip: str
) -> tuple[str, str]:
    """CA-signed server cert with an IP SAN → (cert_pem, key_pem).
    1-year validity (tls.rs:42-72)."""
    ca_cert, ca_key = _load_ca(ca_cert_pem, ca_key_pem)
    key = _keypair()
    builder = (
        x509.CertificateBuilder()
        .subject_name(
            x509.Name(
                [x509.NameAttribute(NameOID.COMMON_NAME, "corro-sim.local")]
            )
        )
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.IPAddress(ipaddress.ip_address(ip))]
            ),
            critical=False,
        )
    )
    return _signed(builder, ca_cert, ca_key, key), _pem_key(key)


def generate_client_cert(
    ca_cert_pem: str, ca_key_pem: str
) -> tuple[str, str]:
    """CA-signed client cert (empty DN, for mutual TLS) →
    (cert_pem, key_pem). 1-year validity (tls.rs:80-105)."""
    ca_cert, ca_key = _load_ca(ca_cert_pem, ca_key_pem)
    key = _keypair()
    builder = x509.CertificateBuilder().subject_name(x509.Name([]))
    return _signed(builder, ca_cert, ca_key, key), _pem_key(key)


# ----------------------------------------------------------- ssl contexts


def server_ssl_context(
    cert_file: str,
    key_file: str,
    ca_file: str | None = None,
    require_client_auth: bool = False,
) -> ssl.SSLContext:
    """Server-side context; with ``require_client_auth`` this is the mTLS
    posture of the reference's gossip server (peer.rs:168-204)."""
    if require_client_auth and not ca_file:
        raise ValueError(
            "require_client_auth needs a CA bundle (ca_file) — an empty "
            "trust store would reject every client"
        )
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_file, key_file)
    if ca_file:
        ctx.load_verify_locations(ca_file)
    if require_client_auth:
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_ssl_context(
    ca_file: str | None = None,
    cert_file: str | None = None,
    key_file: str | None = None,
    insecure: bool = False,
) -> ssl.SSLContext:
    """Client-side context. ``insecure`` skips verification — the
    reference's ``InsecureVerifier`` (peer.rs:236-273)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if insecure:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    elif ca_file:
        ctx.load_verify_locations(ca_file)
    else:
        ctx.load_default_certs()
    if cert_file:
        ctx.load_cert_chain(cert_file, key_file)
    return ctx
