"""Simulation configuration — the analog of the reference's typed `Config`.

The reference loads a TOML ``Config{db, api, gossip, perf, ...}`` with
env-var overrides (``corro-types/src/config.rs:44-62,284-291``) whose
``PerfConfig`` exposes every channel capacity and queue threshold
(``config.rs:168-215``). Here the same role is played by :class:`SimConfig`:
every buffer size, fanout, cadence and cap is a static field (XLA needs
static shapes — cluster size, fanout and buffer caps are compile-time per
run, churn changes membership *state*, not shapes).

TOML loading + ``CORRO_SIM__``-prefixed env overrides live in
:mod:`corro_sim.io.config_file`.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Chaos-injection knobs (corro_sim/faults/): stochastic link faults
    applied on-device at the two transport points of ``engine/step.py`` —
    the broadcast emission/delivery split and the anti-entropy lane grant
    — exactly where the reference's UDP datagrams and QUIC sync streams
    would fail. Static like everything else on :class:`SimConfig`: with
    every knob at its default (``enabled`` False) the step program traces
    ZERO extra ops and is bit-identical to the fault-free one
    (tests/test_faults.py guards this, the ``cfg.probes`` discipline).

    The fault surface is the DATA plane (gossip chunks + sync grants).
    SWIM probe traffic is modeled as control-plane and not fault-gated —
    membership false positives come from the *schedule* (nodes actually
    down / partitioned), not from datagram loss, so the SWIM false-DOWN
    invariant (faults/invariants.py) stays checkable under any fault mix.
    """

    loss: float = 0.0  # P(a deliverable gossip chunk is dropped) — the
    # per-link Bernoulli UDP-loss analog, applied at DELIVERY time so it
    # hits eager ring-0 sends, random gossip and matured in-flight lanes
    # alike (reference transport would drop on the wire the same way)
    dup: float = 0.0  # P(a delivered chunk arrives twice). The second
    # copy is accounted (fault_dup metric, conservation checker) but not
    # re-merged: every merge path is idempotent per (dst, actor, ver,
    # chunk), so a duplicate datagram's only real-world effect here is
    # wasted accounting — the same reason the reference tolerates UDP
    # duplication without a dedupe layer.
    burst_enter: float = 0.0  # Gilbert burst-loss Markov knob: P(a node's
    # receive path enters the burst state) per round. 0 disables the
    # burst machinery entirely (no state, no draws).
    burst_exit: float = 0.5  # P(leaving the burst state) per round
    burst_loss: float = 1.0  # loss probability while in the burst state
    # (applied as max(loss, burst_loss) on the victim's incoming links)
    sync_loss: float | None = None  # P(an admitted sync connection drops
    # before serving) — the QUIC stream-failure analog, applied at the
    # lane grant in sync/sync.py. None = same as ``loss``.
    blackhole: tuple = ()  # asymmetric blackhole masks: directed
    # (src, dst) node pairs whose messages silently vanish; -1 is a
    # wildcard (``(3, -1)`` = everything node 3 sends is dropped while it
    # still receives — the one-way-partition failure gossip must survive).
    # Also constrains sync (a grant over a blackholed edge fails).
    trace_vacuous: bool = False  # force the fault program to TRACE with
    # every knob at zero effect — the non-perturbation guard's lever
    # (tests/test_faults.py): the injection points themselves must not
    # change state, metrics or key derivation.

    @property
    def enabled(self) -> bool:
        """Static gate: False traces zero fault ops (the cfg.probes
        discipline)."""
        return bool(
            self.loss > 0.0
            or self.dup > 0.0
            or self.burst_enter > 0.0
            or self.blackhole
            or self.trace_vacuous
        )

    @property
    def burst_on(self) -> bool:
        """Static burst-machinery gate. The inject kernels branch on
        THIS (never on ``burst_enter`` numerically), so a sweep can
        substitute per-lane traced thresholds behind the same gate
        (corro_sim/sweep/: ``burst_on`` is a static bool on the lane
        knob object too)."""
        return self.burst_enter > 0.0

    @property
    def resolved_sync_loss(self) -> float:
        return self.loss if self.sync_loss is None else self.sync_loss

    def validate(self, num_nodes: int) -> "FaultConfig":
        for name in ("loss", "dup", "burst_enter", "burst_exit",
                     "burst_loss"):
            v = getattr(self, name)
            assert 0.0 <= v <= 1.0, f"faults.{name} must be in [0, 1]"
        if self.sync_loss is not None:
            assert 0.0 <= self.sync_loss <= 1.0, (
                "faults.sync_loss must be in [0, 1]"
            )
        if self.blackhole:
            # vectorized: topology scenarios carry O(N^2) pairs
            import numpy as _np

            pairs = _np.asarray(self.blackhole, dtype=_np.int64)
            assert pairs.ndim == 2 and pairs.shape[1] == 2, (
                "blackhole entries are (src, dst) pairs"
            )
            assert ((pairs >= -1) & (pairs < num_nodes)).all(), (
                f"blackhole pairs out of range for {num_nodes} nodes"
            )
        return self


@dataclasses.dataclass(frozen=True)
class NodeFaultConfig:
    """Node-lifecycle fault knobs (corro_sim/faults/nodes.py): crashes
    that lose state, restarts from stale snapshots, per-node clock skew
    and stragglers — the *agent*-level failure modes, where
    :class:`FaultConfig` above models the *link*-level ones. Corrosion's
    production failure mode is exactly this: an agent restarts with an
    empty or stale SQLite DB and must full-resync via anti-entropy
    (PAPER.md §survey). Everything is a static schedule over the round
    counter, so both step programs (full and repair-specialized) derive
    identical masks from ``state.round`` with ZERO new random draws —
    the repair-program equivalence the driver's post-quiesce switch
    depends on. Disabled (the default) traces zero extra ops and
    contributes zero SimState leaves (the ``engine/features.py``
    registry: ``node_epoch``/``node_snapshot`` appear only for enabling
    configs, so every non-enabling config's pytree/jaxpr/cache keys stay
    byte-identical).

    Amnesia recoverability bound: a wiped node full-resyncs from the
    change log, which is a ring of ``log_capacity`` versions per actor —
    if any actor has written more than that when the wipe lands, the
    ring-wrap tripwire fires and the run is POISONED. That is correct
    physics, not a bug: history evicted from every surviving replica is
    unrecoverable (doc/fault_injection.md §node faults).
    """

    crash: tuple = ()  # (node, round) pairs — crash-restart with
    # AMNESIA: at the start of `round` the node's replica state (table
    # rows, bookkeeping row, gossip rings, SWIM beliefs, HLC) is wiped
    # to the empty-DB state and the node rejoins with an epoch-bumped
    # HLC + SWIM incarnation; anti-entropy must full-resync it. The
    # global change log survives (peers hold the actor's history — the
    # reference's surviving replicas serve a rejoining node its own
    # rows back). Schedule the wipe round at the node's scheduled
    # *rejoin* (scenarios.crash_amnesia pairs it with a down window).
    stale: tuple = ()  # (node, snap_round, round) triples — STALE
    # REJOIN: at `snap_round` the node's (table, bookkeeping) rows are
    # captured into the ``node_snapshot`` feature leaf; at `round` the
    # wipe restores FROM that snapshot instead of zero (restart from an
    # old backup), and sync repays only the delta (resync_rows).
    skew: tuple = ()  # (node, offset) pairs — per-node wall-clock
    # offset plane perturbing HLC timestamp generation (the physical
    # floor becomes round + offset), exercising LWW tie-breaks and the
    # EmptySet ts gating under clock skew. Static for the run.
    straggle: tuple = ()  # (node, period, active) triples — per-node
    # activation slowdown: the node participates in broadcast emission
    # and anti-entropy sweeps only on rounds with
    # ``(round + node) % period < active`` (duty cycle active/period).
    # It still receives, still answers SWIM probes (it is alive, just
    # slow) and still commits local writes — they disseminate on its
    # next active round, exactly like an overloaded agent whose flush
    # loop falls behind.
    epoch_jump: int = 0  # HLC jump a rejoining node boots with:
    # hlc = round + epoch_jump * restart_epoch (uhlc seeds from the
    # wall clock; a restarted node's clock may be ahead). 0 = clean
    # wall-clock reboot.
    trace_vacuous: bool = False  # force the node-fault program to TRACE
    # with zero scheduled effect — the non-perturbation guard's lever
    # (tests/test_node_faults.py): the injection points themselves must
    # not change state, metrics or key derivation.

    @property
    def enabled(self) -> bool:
        """Static gate: False traces zero node-fault ops (the
        cfg.probes discipline)."""
        return bool(
            self.crash or self.stale or self.skew or self.straggle
            or self.trace_vacuous
        )

    @property
    def wipe_enabled(self) -> bool:
        """Whether any wipe (amnesia or stale restore) is scheduled —
        the ``node_epoch`` leaf's enabling condition rides
        ``enabled`` so the vacuous trace threads the plane too."""
        return bool(self.crash or self.stale)

    def wipe_schedule(self) -> tuple:
        """Every scheduled ``(node, round)`` wipe, amnesia and stale
        alike — the host-side consumers' one source of truth (invariant
        checker exemptions, scorecard resync accounting)."""
        return tuple(
            [(int(n), int(r)) for n, r in self.crash]
            + [(int(n), int(r)) for n, _s, r in self.stale]
        )

    def validate(self, num_nodes: int) -> "NodeFaultConfig":
        for n, r in self.crash:
            assert 0 <= int(n) < num_nodes, (
                f"node_faults.crash node {n} out of range"
            )
            assert int(r) >= 0, "node_faults.crash round must be >= 0"
        for n, s, r in self.stale:
            assert 0 <= int(n) < num_nodes, (
                f"node_faults.stale node {n} out of range"
            )
            assert 0 <= int(s) < int(r), (
                "node_faults.stale snapshots must predate the restore "
                f"round (got snap={s}, restore={r})"
            )
        for n, _off in self.skew:
            assert 0 <= int(n) < num_nodes, (
                f"node_faults.skew node {n} out of range"
            )
        for n, period, active in self.straggle:
            assert 0 <= int(n) < num_nodes, (
                f"node_faults.straggle node {n} out of range"
            )
            assert int(period) >= 1 and 1 <= int(active) <= int(period), (
                "node_faults.straggle needs 1 <= active <= period "
                f"(got period={period}, active={active}) — a node with "
                "no active rounds never drains its rings"
            )
        return self


def node_faults_from_dict(d: dict) -> NodeFaultConfig:
    """Rebuild a NodeFaultConfig from its JSON-round-tripped asdict form
    (checkpoint headers, resume tokens): the schedule tuples come back
    as lists-of-lists and must re-tuple, like FaultConfig.blackhole."""
    d = dict(d)
    for key in ("crash", "stale", "skew", "straggle"):
        d[key] = tuple(
            tuple(int(x) for x in row) for row in d.get(key, ())
        )
    return NodeFaultConfig(**d)


def shift_node_faults(nf: "NodeFaultConfig", offset: int) -> "NodeFaultConfig":
    """``nf`` with every round-scheduled fault shifted ``offset`` rounds
    later — the what-if fork's frame adapter (corro_sim/engine/twin.py).

    Node-fault schedules compare against ``state.round``, which is
    ABSOLUTE: a twin forked at round R carries ``round == R`` into every
    forecast lane, so a scenario whose wipe is authored "at relative
    round k" must schedule it at R + k. Only the wipe/snapshot rounds
    shift; ``skew`` carries no round and a straggler's duty phase is a
    function of the absolute round by design (``(round + node) %
    period`` — the phase an overloaded agent is in does not reset
    because an operator forked a forecast)."""
    offset = int(offset)
    if offset == 0 or not (nf.crash or nf.stale):
        return nf
    return dataclasses.replace(
        nf,
        crash=tuple((int(n), int(r) + offset) for n, r in nf.crash),
        stale=tuple(
            (int(n), int(s) + offset, int(r) + offset)
            for n, s, r in nf.stale
        ),
    )


@dataclasses.dataclass(frozen=True)
class TwinConfig:
    """Digital-twin driver knobs (corro_sim/engine/twin.py): how the
    shadow consumes a changeset feed. HOST-side orchestration only — a
    twin run dispatches the exact same compiled step/inject programs a
    plain replay of the same shape would, so this block contributes ZERO
    SimState leaves and ZERO traced ops whether enabled or not
    (tests/test_twin.py pins pytree + jaxpr identity across the gate;
    the acceptance bar: golden 4253/2153 and every primed program stay
    byte-identical for non-twin configs — and for twin ones too)."""

    enabled: bool = False  # provenance gate: a twin run's config says so
    # (reports, checkpoint headers); nothing on-device reads it
    scan_lines: int = 0  # universe scan window in feed lines; 0 = the
    # whole feed (file mode — a live tail must bound it)
    chunk_lines: int = 64  # feed lines consumed per shadow chunk (the
    # checkpoint-cursor granularity)
    skip_bad: bool = False  # quarantine hostile feed lines (counted in
    # corro_twin_bad_lines_total{reason}) instead of refusing the feed
    # with one up-front ValueError
    drain_rounds: int = 256  # post-feed round budget chasing gap -> 0
    checkpoint_every: int = 1  # feed chunks between cursor checkpoints

    # ---- live-tail bounds (corro_sim/io/feedsource.py): how hard a
    # `corro-sim twin --tail` shadow chases a source that stalls, moves
    # or dies. All host-side; none of these touch the step program.
    tail_poll_ms: int = 250  # base poll cadence; also the backoff floor
    reconnect_max_s: float = 30.0  # cumulative retry budget against a
    # missing file / failing endpoint before the source is declared dead
    idle_timeout_s: float = 10.0  # a source that yields no new complete
    # line for this long is dead (a live tail's only natural exit)
    max_lag_lines: int = 65536  # backpressure bound: the source stops
    # reading ahead once this many undelivered lines are buffered

    # ---- stale-universe refresh: when the windowed unknown_actor +
    # unknown_value quarantine rate crosses the threshold, the closed
    # world re-freezes from a trailing scan window at the next chunk
    # boundary (a scheduled re-key event; engine/twin.py).
    refresh_threshold: float = 0.0  # quarantine-rate trigger; 0 = never
    refresh_window_lines: int = 256  # trailing lines rescanned per
    # refresh (also the rate window the trigger is measured over)

    forecast_every: int = 0  # run a fork -> forecast cycle every N feed
    # chunks (0 = only the explicit final --forecast, if any)

    def validate(self) -> "TwinConfig":
        assert self.scan_lines >= 0, "twin.scan_lines must be >= 0"
        assert self.chunk_lines >= 1, "twin.chunk_lines must be >= 1"
        assert self.drain_rounds >= 0, "twin.drain_rounds must be >= 0"
        assert self.checkpoint_every >= 0, (
            "twin.checkpoint_every must be >= 0 (0 = no cursor "
            "checkpoints)"
        )
        assert self.tail_poll_ms >= 1, "twin.tail_poll_ms must be >= 1"
        assert self.reconnect_max_s >= 0, (
            "twin.reconnect_max_s must be >= 0"
        )
        assert self.idle_timeout_s > 0, "twin.idle_timeout_s must be > 0"
        assert self.max_lag_lines >= 1, "twin.max_lag_lines must be >= 1"
        assert 0.0 <= self.refresh_threshold <= 1.0, (
            "twin.refresh_threshold must be in [0, 1]"
        )
        assert self.refresh_window_lines >= 1, (
            "twin.refresh_window_lines must be >= 1"
        )
        assert self.refresh_threshold == 0.0 or self.skip_bad, (
            "twin.refresh_threshold needs skip_bad: the refresh trigger "
            "is the windowed quarantine rate, and strict mode refuses "
            "the feed before anything can quarantine"
        )
        assert self.forecast_every >= 0, (
            "twin.forecast_every must be >= 0 (0 = no cadence re-forks)"
        )
        return self


@dataclasses.dataclass(frozen=True)
class SweepConfig:
    """Static descriptor of a fleet-of-clusters sweep program
    (corro_sim/sweep/): ``lanes`` simulated clusters race in ONE jitted
    dispatch — the scan carry gains a leading lane axis and
    ``make_step``/``make_workload_step`` run under ``jax.vmap``.

    Everything that VARIES across lanes (link-fault scalars, node-fault
    schedules, the sampler-vs-schedule write source) moves from baked
    config constants into per-lane DATA riding the ``sweep_knobs``
    registry feature leaf (engine/features.py — the PR 10 contract:
    disabled configs contribute zero leaves, so every non-sweeping
    config's pytree/jaxpr/cache keys stay byte-identical). The fields
    here are the static GATES: which fault machinery the union program
    must trace at all. A gate is on when ANY lane needs it; lanes that
    don't carry value-neutral knobs (loss 0, wipe round -1, duty 1/1),
    which the vacuity guards (tests/test_faults.py,
    tests/test_node_faults.py) already prove bit-identical to the
    untraced path — that equivalence is exactly what makes a mixed
    scenario matrix collapse into one program whose every lane equals
    its serial ``run_sim`` twin (tests/test_sweep.py).
    """

    lanes: int = 0  # sweep width; 0 = sweeping off (every existing
    # config — the enabled-gate for the sweep_knobs feature leaf)
    link_faults: bool = False  # trace the link-fault machinery with
    # per-lane traced thresholds (loss/dup/sync_loss ride the knob leaf)
    burst: bool = False  # trace the Gilbert burst machinery (per-lane
    # enter/exit/loss thresholds; arms the (N,) fault_burst plane)
    wipes: bool = False  # per-lane crash-restart wipe planes
    # (wipe_round/wipe_stale/epoch_jump)
    stale: bool = False  # per-lane stale-rejoin snapshot planes
    # (snap_round; arms the node_snapshot leaf)
    skew: bool = False  # per-lane HLC skew plane
    straggle: bool = False  # per-lane duty-cycle planes
    workload: bool = False  # the program takes the write-schedule scan
    # inputs AND traces the sampler, selecting per lane by the
    # use_workload knob — so schedule-driven and sampler-driven lanes
    # mix in one dispatch
    sim_knobs: bool = False  # per-lane SimConfig scalars beyond the
    # link-fault set: write_rate / delete_rate as traced f32 thresholds
    # and sync_interval / swim_suspect_rounds as traced i32 cadences
    # (knobs.SIM_KNOB_FIELDS). zipf_alpha needs no gate at all — it
    # only shapes the host-precomputed row_cdf plane, so a zipf axis is
    # a pure per-lane data swap with zero program change.

    @property
    def enabled(self) -> bool:
        return self.lanes > 0

    @property
    def node_faults(self) -> bool:
        """Whether any node-lifecycle plane is armed."""
        return self.wipes or self.stale or self.skew or self.straggle

    @property
    def wipe_planes(self) -> bool:
        """Whether the wipe planes (and the node_epoch leaf) exist."""
        return self.wipes or self.stale

    def validate(self) -> "SweepConfig":
        assert self.lanes >= 0, "sweep.lanes must be >= 0"
        if not self.enabled:
            assert not (
                self.link_faults or self.burst or self.wipes or self.stale
                or self.skew or self.straggle or self.workload
                or self.sim_knobs
            ), "sweep gates need lanes > 0"
        return self


@dataclasses.dataclass(frozen=True)
class SimConfig:
    # --- cluster shape ---
    num_nodes: int = 64
    num_rows: int = 256  # table row slots (pk universe)
    num_cols: int = 4  # columns per row
    log_capacity: int = 1024  # max versions per actor per run (ring)
    seqs_per_version: int = 1  # max cells per changeset (CrsqlSeq axis;
    # one version = one transaction's changeset, corro-api-types/lib.rs:235-245)
    chunks_per_version: int = 1  # gossip chunks per changeset — the
    # ChunkedChanges ≤8 KiB split (corro-types/src/change.rs:16-122); a
    # version applies only when all chunks arrived (partial buffering,
    # agent/util.rs:1065-1190). Must divide 32 (window bits per version).

    # --- workload ---
    write_rate: float = 0.5  # P(node writes) per round while writes enabled
    delete_rate: float = 0.0  # P(write is a DELETE)
    zipf_alpha: float = 0.0  # 0 = uniform rows; >0 = Zipf hot-row contention
    value_universe: int = 1 << 20  # interned value id space

    # --- gossip (reference broadcast/mod.rs) ---
    pend_slots: int = 16  # pending-broadcast ring per node
    emit_slots: int = 0  # egress cap: pending slots serviced per node per
    # round (0 = all of them). The reference bounds egress per flush — 64
    # KiB or 500 ms, whichever first (broadcast/mod.rs:378,394,446-455) —
    # so a saturated pending queue DELAYS sends rather than fanning out
    # unbounded; slots beyond the cap keep their transmission budget and
    # wait. Also the emission lane count (the dominant per-round compute
    # at 10k nodes) scales with this, not with ring capacity.
    fanout: int = 3  # random members per dissemination round
    max_transmissions: int = 4  # re-send budget (foca-style)
    rebroadcast_transmissions: int = 2  # budget for relayed changes
    ring0_size: int = 4  # eager low-latency peer set size

    # --- anti-entropy sync (reference api/peer.rs, agent/handlers.rs) ---
    sync_interval: int = 8  # rounds between sync sweeps (1-15 s backoff analog)
    sync_adaptive: bool = False  # accelerated repair cadence: a round with
    # zero cluster-wide writes and a nonzero gap syncs on the FLOOR cadence
    # below instead of the lean sync_interval, so repair accelerates when
    # gossip stops carrying new data.
    sync_floor_rounds: int = 1  # adaptive floor, in rounds. The reference's
    # sync_loop fires on a growing 1 s → 15 s backoff (util.rs:327-371,
    # MAX_SYNC_BACKOFF agent/mod.rs:34-36) — at round_ms=200 the 1 s floor
    # is 5 rounds; 1 keeps the (more aggressive than reference)
    # sync-every-round tail.
    sync_candidates: int = 10  # RANDOM_NODES_CHOICES (agent/mod.rs:38)
    sync_server_cap: int = 3  # inbound sync semaphore (corro-types/agent.rs:132)
    sync_peers: int | None = None  # concurrent sync peers per node per sweep;
    # None = the reference's max(min(n/100, 10), 3) (handlers.rs:1008-1015)
    sync_actor_topk: int = 32  # actors repaired per node per PEER per sweep
    # (a per-connection chunk budget, peer.rs:1207 — parallel peers each
    # carry a full budget, so sweep bandwidth scales with sync_peers)
    sync_cap_per_actor: int = 8  # versions per actor per sync round
    sync_req_actors: int | None = None  # total request lanes (actors) a
    # node schedules per sweep across all its peers; None = 2× the
    # per-connection budget (parallel headroom without paying full P×
    # lane memory/compute every sweep — lanes are padded to this shape
    # whether needed or not). Clamped to sync_actor_topk × peers.
    # NOTE (per-connection budget bound under probing): with probes >= 1,
    # a lane's budget rank comes from the PRIMARY dealing while its slot
    # may be reassigned by a probe, so one connection can serve up to
    # probes x sync_actor_topk lanes (vs exactly sync_actor_topk under
    # the exact-argmax policy) — a deliberate fidelity trade for the
    # cheaper schedule; size server-side budgets accordingly.
    sync_deal_probes: int = 0  # serving-slot assignment policy. 0 = exact
    # argmax over every granted peer's capability per lane (full
    # (N, P, K') head gather + argsort budget rank — best repair depth,
    # needed when per-actor backlogs are deep and asymmetric). k >= 1 =
    # deal lanes round-robin across granted slots (the reference's
    # shuffled request dealing, api/peer.rs:1241-1372) and probe only k
    # candidate slots per lane — with shallow per-actor needs (the
    # convergence-tail regime) k=2 matches argmax throughput at ~1/6 the
    # sweep-schedule cost on the real chip.
    sync_need_sample: int = 256  # actors sampled for need estimation
    sync_hot_actors: int = 1024  # dense-schedule hot-actor axis width: per
    # sweep, the actors that could possibly be needed by anyone (their
    # written head exceeds some node's applied head) are compacted to at
    # most this many (rotating fairly when more are hot), and the whole
    # request schedule — needs, per-peer capability, serving assignment —
    # runs as dense elementwise work over (N, P, A') instead of
    # per-element gathers over (N, P, K') + an (N, A, K') compare-reduce.
    # Exact, not approximate: a non-hot actor has zero need at every
    # node. 0 = the legacy full-axis schedule.

    # --- SWIM membership (foca analog) ---
    swim_enabled: bool = False
    swim_interval: int = 1  # rounds between SWIM ticks. foca's probe
    # period (1-5 s) is several broadcast flushes long (broadcast flush =
    # 500 ms, mod.rs:378) — ticking SWIM every gossip round is FASTER
    # failure detection than the reference's; >1 restores the ratio and
    # cuts the (N, N)-plane traffic proportionally. Suspicion timeouts
    # (swim_suspect_rounds) count gossip rounds either way.
    swim_indirect_probes: int = 3  # num_indirect_probes
    swim_suspect_rounds: int = 6  # suspicion timeout, in rounds
    swim_gossip_peers: int = 3  # view-exchange peers per round
    swim_announce_interval: int = 4  # belief-independent announce cadence
    # (ANNOUNCE_INTERVAL analog, agent/mod.rs:32 — heals mutual-down splits)
    swim_view_size: int = 0  # > 0: the windowed O(N·K) belief state
    # (membership/swim_window.py) — each node tracks at most this many
    # members instead of the full (N, N) plane (10 GB at 50k, why config
    # 5 historically ran SWIM off). foca's per-node state is O(members
    # known) the same way. 0 = the full-view automaton.
    swim_payload_members: int = 64  # member entries per exchange datagram —
    # the ≤1178-byte SWIM packet bound (broadcast/mod.rs:743) at ~18 B per
    # piggybacked update; >= num_nodes disables the bound (full views)

    # --- state packing (doc/performance.md "state packing & op budget") ---
    narrow_state: bool = False  # pack the widest per-node planes into
    # narrow dtypes (the `rtt: uint8` precedent): SWIM belief planes —
    # full-view (N, N) and windowed (N, K) — drop from uint32 to uint16
    # (inc 6 bits saturating at 63, status 2 bits, since 8 bits mod-2^8)
    # and the probe hop plane drops to int8 (saturating at 127), halving
    # HBM traffic on the biggest state tensor at 10k nodes (400 MB →
    # 200 MB). Bit-exact against the wide reference while incarnations
    # stay under 63, suspicions resolve within 256 rounds (validated:
    # swim_suspect_rounds bound below), gossip paths stay under 127
    # hops, and concurrent suspicions of one member don't straddle a
    # multiple of 256 rounds (the wide layout's mod-2^16 wrap caveat,
    # shrunk with the since field — membership/swim.py). Default off: the
    # switch changes SimState leaf dtypes, which re-keys every compiled
    # step program (cold .jax_cache — see doc/performance.md).

    # --- device-mesh placement (engine/sharding.py) ---
    shard_log: bool | None = None  # change-log placement on a device mesh:
    # True = actor-sharded (each device owns its actors' write history;
    # delivery/sync gathers become collectives, per-device log HBM drops
    # by the mesh size), False = replicated (every gather device-local),
    # None = the SHARD_LOG_ACTORS shape heuristic (sharded at >= 2048
    # actors). Surfaced as `run --shard-log on|off|auto`,
    # CORRO_SIM__SHARD_LOG, and `[sim] shard_log` (doc/multichip.md).
    # Irrelevant off-mesh: single-device runs ignore it.

    # --- merge execution (TPU Pallas kernel, core/merge_kernel.py) ---
    merge_kernel: str = "auto"  # "auto" = Pallas dst-grouped merge for the
    # SYNC sweep on real TPU (single device, 128-aligned cell space;
    # measured ~120 ms/sweep saved at 10k nodes) while gossip delivery
    # keeps the XLA scatter (neutral there — mostly-invalid lanes make
    # the in-situ scatter cheap); "on" forces the kernel on BOTH merge
    # paths (equivalence tests; interpret mode off-TPU); "off" keeps the
    # XLA scatter path everywhere (sharded runs force this — pallas_call
    # does not partition over a mesh).
    apply_queue_cap: int = 128  # max deliveries merged per node per round
    # under the kernel path — the reference's bounded apply channel
    # (config.rs:10-41: change-apply cost threshold + drop queue); lanes
    # beyond the cap are dropped BEFORE bookkeeping (counted in
    # dropped_window) and anti-entropy repairs them, exactly like queue
    # overflow drops (handlers.rs:866-884). Must be a multiple of 128.

    # --- probe tracer (obs/probes.py; the sim-world analog of the
    # reference's distributed tracing) ---
    probes: int = 0  # K sampled versions tracked through the gossip
    # fabric entirely on-device: per (probe, node) first-seen round,
    # infector and hop count, plus duplicate-delivery counts and a
    # per-node last-sync stamp (engine/probe.py). Static, so 0 traces
    # ZERO extra ops — the step program is bit-identical to the
    # uninstrumented one (tests/test_probes.py guards this). Probe k
    # tracks version 1 of actor k*N//K by default; drivers may re-aim
    # probes by replacing state.probe before running.

    # --- chaos injection (corro_sim/faults/) ---
    faults: FaultConfig = FaultConfig()  # stochastic link faults at the
    # two transport points (broadcast delivery + sync grant). Defaults
    # disabled: zero extra traced ops, bit-identical step program
    # (tests/test_faults.py non-perturbation guard).

    # --- node-lifecycle faults (corro_sim/faults/nodes.py) ---
    node_faults: NodeFaultConfig = NodeFaultConfig()  # crash-restart
    # with amnesia, stale rejoin from a snapshot leaf, HLC clock skew
    # and straggler duty cycles — agent-level failures where `faults`
    # above is link-level. Defaults disabled: zero extra traced ops,
    # zero extra SimState leaves (registry features), bit-identical
    # step program (tests/test_node_faults.py non-perturbation guard).

    # --- digital twin (corro_sim/engine/twin.py) ---
    twin: TwinConfig = TwinConfig()  # feed-shadow driver knobs (scan
    # window, chunk size, hostile-line posture, cursor cadence). Pure
    # host orchestration: zero SimState leaves, zero traced ops, the
    # step program byte-identical with the block enabled OR disabled
    # (tests/test_twin.py pins it at the pytree and jaxpr layers).

    # --- fleet-of-clusters sweep (corro_sim/sweep/) ---
    sweep: SweepConfig = SweepConfig()  # static gates of the vmapped
    # chaos-matrix program: lanes > 0 stacks the scan carry over a
    # leading lane axis and the per-lane fault knobs ride the
    # sweep_knobs registry feature leaf. Default disabled: zero extra
    # traced ops, zero extra SimState leaves, byte-identical step
    # program (the engine/features.py contract).

    # --- host-side driver (engine/driver.py) ---
    pipeline: bool = True  # pipelined chunk dispatch: overlap device
    # compute with host-side control/transfers/bookkeeping (speculative
    # next-chunk dispatch + async metric fetch; doc/performance.md).
    # Purely host-side restructuring — the chunk programs, keys and
    # schedule rows are identical either way, and results are
    # bit-identical to the sequential loop (tests/test_pipeline.py).
    # `corro-sim run --no-pipeline` / `CORRO_SIM__PIPELINE=0` opt out;
    # donated-buffer runs (run_sim(donate=True)) force it off.

    # --- timing model ---
    round_ms: float = 200.0  # simulated wall-clock per round (broadcast
    # flush cadence is 500 ms in the reference, broadcast/mod.rs:378; one
    # sim round ≈ one flush+delivery hop)

    # --- link latency + RTT rings (members.rs:40,140-188) ---
    latency_regions: int = 1  # >1 enables the delay model (contiguous
    # node-id regions; think racks/DCs)
    latency_intra: int = 1  # rounds-to-deliver within a region (must be 1
    # while the in-flight ring buffers only the inter class)
    latency_inter: int = 4  # rounds-to-deliver across regions: a message
    # emitted in round r is DELIVERED in round r + latency_inter - 1 via
    # the in-flight ring (real delay, not loss — transport.rs:199-233)
    rtt_rings: bool = False  # measure per-edge RTT on delivery and
    # recompute ring0 from observations (else ring0 stays static)
    ring_update_interval: int = 8  # rounds between ring recomputations

    @property
    def num_actors(self) -> int:
        return self.num_nodes

    @property
    def lanes_per_round(self) -> int:
        """Message lanes one round emits: eager ring-0 chunks + gossip."""
        return self.num_nodes * (
            self.ring0_size * self.chunks_per_version
            + self.pend_slots * self.fanout
        )

    @property
    def inflight_slots(self) -> int:
        """Ring depth of the in-flight delay buffer (0 = disabled)."""
        if self.latency_regions > 1 and self.latency_inter > 1:
            return self.latency_inter - 1
        return 0

    @property
    def resolved_sync_peers(self) -> int:
        """Concurrent sync peers per sweep — max(min(n/100, 10), 3), the
        reference's parallel_sync peer count (``handlers.rs:1008-1015``),
        clamped to the candidate pool."""
        p = self.sync_peers
        if p is None:
            p = max(min(self.num_nodes // 100, 10), 3)
        return max(1, min(p, self.sync_candidates, self.num_nodes - 1))

    def validate(self) -> "SimConfig":
        assert self.num_nodes >= 2
        assert self.fanout >= 1 and self.pend_slots >= 1
        assert self.log_capacity >= 1
        assert self.sync_candidates >= 1
        assert self.seqs_per_version >= 1
        assert 0 <= self.probes <= self.num_nodes, (
            "probes samples distinct origin actors — at most one per node"
        )
        assert self.chunks_per_version in (1, 2, 4, 8, 16, 32), (
            "chunks_per_version must divide the 32-bit version window"
        )
        assert self.shard_log in (None, True, False), (
            "shard_log is tri-state: True (actor-sharded), False "
            "(replicated), or None (the SHARD_LOG_ACTORS heuristic)"
        )
        if self.narrow_state:
            # the narrow since field is 8 bits: a suspicion must start,
            # time out and resolve well inside one mod-2^8 window for
            # the packed-max merge to stay bit-exact with the wide plane
            assert self.swim_suspect_rounds < 128, (
                "narrow_state packs the suspicion clock into 8 bits — "
                "swim_suspect_rounds must stay under 128 rounds"
            )
        assert self.latency_regions <= 1 or self.latency_intra == 1, (
            "the in-flight delay ring buffers the inter-region class only; "
            "intra-region delivery is same-round (latency_intra must be 1)"
        )
        self.faults.validate(self.num_nodes)
        self.node_faults.validate(self.num_nodes)
        self.twin.validate()
        self.sweep.validate()
        if self.sweep.enabled:
            assert not self.node_faults.enabled, (
                "a sweep union config carries node faults as per-lane "
                "planes (sweep_knobs leaf), never as static schedules"
            )
        return self
