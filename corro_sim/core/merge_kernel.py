"""Pallas TPU kernel: dst-grouped CRDT cell merge without global scatters.

:func:`corro_sim.core.crdt.apply_cell_changes` expresses the CR-SQLite
lexicographic merge as four masked scatter-max passes plus three per-lane
gathers over the (N, R, C) table planes. On TPU every scatter/gather lane
is a descriptor (~30 ns each regardless of validity — measured in the
round-5 ablations), so the merge runs at ~35 M lanes/s and dominates the
10k-node round (~57 ms on the 520k-lane delivery batch, ~150 ms on the
1.28M-lane sync sweep).

This kernel exploits what the scatters cannot: lanes can be grouped by
destination node (the step's hoisted lane sort; sync lanes are built
node-major). Lanes live in a dense per-node mailbox — ``(8, N * cap)``
int32, node ``n``'s lanes at columns ``[n*cap, (n+1)*cap)`` — so every
block is 128-aligned and the pallas pipeline streams both the mailbox and
the table planes through VMEM with no manual DMA. Each grid program
merges a block of nodes with dense one-hot compare/max reduces over the
(cells, cap) plane — pure VPU work, zero per-lane HBM descriptors — and
writes the planes back aliased in place. Semantics are bit-for-bit
`apply_cell_changes` (equivalence-tested in tests/test_merge_kernel.py);
reference semantics as documented there (``doc/crdts.md:15-17,237``,
``agent/util.rs:721-1062``).

The per-node lane cap is the bounded apply-queue analog (reference
``config.rs:10-41``): the delivery router drops beyond-cap lanes BEFORE
bookkeeping (counted as drops; anti-entropy repairs them, like queue
overflow ``handlers.rs:866-884``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from corro_sim.core.crdt import NEG
from corro_sim.utils.slots import ranks_within_group_masked

NEG_I = -(2 ** 31)  # python-int NEG: kernels cannot capture device arrays

# lane field rows of the packed (8, N*cap) mailbox tensor
LANE_CELL, LANE_CV, LANE_VR, LANE_SITE, LANE_CL, LANE_VALID = range(6)
LANE_FIELDS = 8  # padded to a power of two for clean strides


def route_lanes(
    dst: jnp.ndarray,  # (M,) int32 destination node per lane
    rank: jnp.ndarray,  # (M,) int32 rank of the lane within its dst
    cell: jnp.ndarray,  # (M,) int32 row * C + col
    cv: jnp.ndarray,
    vr: jnp.ndarray,
    site: jnp.ndarray,
    cl: jnp.ndarray,
    valid: jnp.ndarray,  # (M,) bool
    num_nodes: int,
    cap: int,
) -> jnp.ndarray:
    """Scatter flat lanes into the dense (8, N*cap) per-node mailbox.

    One scatter of M descriptors (each an (8,)-field column) replaces the
    ~7 scatter/gather passes the XLA merge pays per lane. Lanes with
    ``rank >= cap`` or ``~valid`` drop (out-of-bounds sentinel).
    """
    fields = jnp.stack([
        cell.astype(jnp.int32),
        cv.astype(jnp.int32),
        vr.astype(jnp.int32),
        site.astype(jnp.int32),
        cl.astype(jnp.int32),
        jnp.ones_like(cell, jnp.int32),  # routed lanes are valid
        jnp.zeros_like(cell, jnp.int32),
        jnp.zeros_like(cell, jnp.int32),
    ], axis=1)  # (M, 8)
    keep = valid & (rank < cap)
    pos = jnp.where(keep, dst * cap + rank, num_nodes * cap)
    box = jnp.zeros((num_nodes * cap, LANE_FIELDS), jnp.int32)
    box = box.at[pos].set(fields, mode="drop")
    return box.T  # (8, N*cap)


def _kernel(cells, bn, cap, cols, lanes_ref,
            cv_ref, vr_ref, site_ref, cl_ref,
            ocv_ref, ovr_ref, osite_ref, ocl_ref):
    """Merge a block of nodes' lane mailboxes into their table planes.

    Orientation: the hot matrices are (cap, cells) — lanes on the
    SUBLANE axis — so every masked-max reduce over lanes lowers to ~16
    elementwise (8, cells) tile-row maxes instead of a log2(cap)
    cross-lane shuffle tree. All per-lane tie-break conditions are
    evaluated *inside* the hot matrix: at a hot (lane, cell) pair the
    broadcast ``cv1[None, :]`` is exactly ``cv1`` at the lane's target
    cell, so no lane-side gather of merged results is ever needed.
    """
    neg = jnp.int32(NEG_I)
    cell_row = jax.lax.broadcasted_iota(
        jnp.int32, (1, cells), 1
    ) // jnp.int32(cols)
    for j in range(bn):
        lane = lanes_ref[:, j * cap:(j + 1) * cap]  # (8, cap)

        def col(f, lane=lane):
            return lane[f].reshape(cap, 1)  # lane field on sublanes

        lcell = col(LANE_CELL)
        lcv = col(LANE_CV)
        lvr = col(LANE_VR)
        lsite = col(LANE_SITE)
        lcl = col(LANE_CL)
        ok = col(LANE_VALID) != 0

        iota_c = jax.lax.broadcasted_iota(jnp.int32, (1, cells), 1)
        hot_c = lcell == iota_c  # (cap, cells)
        # row-hot: every cell of the lane's row (cl is a per-row CRDT)
        hot_r = (lcell // jnp.int32(cols)) == cell_row

        def seg_max(mat, val):
            return jnp.max(jnp.where(mat, val, neg), axis=0)

        # Pass 0: causal length (per row) + generation wipe.
        cl0 = cl_ref[j]
        cl1 = jnp.maximum(cl0, seg_max(hot_r & ok, lcl))
        bumped = cl1 > cl0
        cv0 = jnp.where(bumped, 0, cv_ref[j])
        vr0 = jnp.where(bumped, neg, vr_ref[j])
        site0 = jnp.where(bumped, -1, site_ref[j])

        # A value lane participates only at the row's current generation
        # (cl1 is row-uniform in cell space, so the broadcast compare at
        # the lane's hot cell IS the lane's-row comparison).
        val = hot_c & ok & (lvr != neg) & (lcl == cl1[None, :])

        # Pass 1: col_version.
        cv1 = jnp.maximum(cv0, seg_max(val, lcv))

        # Pass 2: value rank (stored value competes only if cv survived).
        win1 = val & (lcv == cv1[None, :])
        vr_base = jnp.where(cv1 > cv0, neg, vr0)
        vr1 = jnp.maximum(vr_base, seg_max(win1, lvr))

        # Pass 3: site (stored site survives only if (cv, vr) survived).
        win2 = win1 & (lvr == vr1[None, :])
        site_base = jnp.where((cv1 != cv0) | (vr1 != vr0), neg, site0)
        site1 = jnp.maximum(site_base, seg_max(win2, lsite))

        ocv_ref[j] = cv1
        ovr_ref[j] = vr1
        osite_ref[j] = site1
        ocl_ref[j] = cl1


def grouped_merge(
    cvf: jnp.ndarray,  # (N, cells) int32 — col_version, flat cell space
    vrf: jnp.ndarray,  # (N, cells) int32 — value rank
    sitef: jnp.ndarray,  # (N, cells) int32 — site
    clf: jnp.ndarray,  # (N, cells) int32 — causal length (row-broadcast)
    lanes: jnp.ndarray,  # (8, N*cap) int32 — per-node lane mailbox
    cap: int,  # static lanes per node (multiple of 128)
    cols: int,  # C — cells per row (for the causal-length row-hot mask)
    block_nodes: int = 8,
    interpret: bool = False,
):
    """Merge the per-node lane mailbox into flat table planes, in place.

    Returns updated ``(cvf, vrf, sitef, clf)``. ``cells`` and ``cap``
    must be multiples of 128 and ``block_nodes`` must divide N.
    """
    n, cells = cvf.shape
    assert cells % 128 == 0 and cap % 128 == 0
    assert n % block_nodes == 0
    assert lanes.shape == (LANE_FIELDS, n * cap)
    grid = (n // block_nodes,)

    plane = pl.BlockSpec((block_nodes, cells), lambda i: (i, 0))
    lane_spec = pl.BlockSpec(
        (LANE_FIELDS, block_nodes * cap), lambda i: (0, i)
    )
    kern = functools.partial(_kernel, cells, block_nodes, cap, cols)
    shape = jax.ShapeDtypeStruct((n, cells), jnp.int32)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[lane_spec, plane, plane, plane, plane],
        out_specs=(plane, plane, plane, plane),
        out_shape=(shape, shape, shape, shape),
        # alias the four table planes in place (lanes operand is index 0)
        input_output_aliases={1: 0, 2: 1, 3: 2, 4: 3},
        interpret=interpret,
    )(lanes, cvf, vrf, sitef, clf)


def merge_grouped(
    state,  # TableState
    lanes: jnp.ndarray,  # (8, N*cap) mailbox (route_lanes / reshape)
    cap: int,
    block_nodes: int = 8,
    interpret: bool = False,
    mesh=None,
    axis_name: str = "nodes",
):
    """`apply_cell_changes` on a dense per-node lane mailbox, via Pallas.

    Returns the merged :class:`TableState`.

    ``mesh``: partition the kernel over the node axis (ISSUE 8) — the
    mailbox's column axis and the table planes' leading axis are both
    node-major, so a ``shard_map`` over the mesh hands every device its
    own ``(N/D, cells)`` planes + ``(8, N/D*cap)`` mailbox slice and the
    Pallas grid runs per-shard with NO collectives: lanes must already
    be grouped by a destination the caller placed on the right shard
    (sync lanes are built node-major; delivery lanes route through
    :func:`route_merge_sharded`'s all_to_all first). ``block_nodes`` is
    recomputed from the per-shard node count under a mesh.
    """
    from corro_sim.core.crdt import TableState

    n, r, c = state.cv.shape
    cells = r * c
    clf = jnp.repeat(state.cl, c, axis=1)
    if mesh is None:
        merge = functools.partial(
            grouped_merge, cap=cap, cols=c,
            block_nodes=block_nodes, interpret=interpret,
        )
    else:
        nl = n // mesh.shape[axis_name]

        def local_merge(cvf, vrf, sitef, clf_, lanes_):
            return grouped_merge(
                cvf, vrf, sitef, clf_, lanes_, cap, c,
                block_nodes=pick_block_nodes(nl), interpret=interpret,
            )

        merge = shard_map(
            local_merge, mesh=mesh,
            in_specs=(
                P(axis_name), P(axis_name), P(axis_name), P(axis_name),
                P(None, axis_name),
            ),
            out_specs=(P(axis_name),) * 4,
            # pallas_call has no shard_map replication rule; every
            # operand/output here is node-sharded, nothing replicated
            check_rep=False,
        )
    ncv, nvr, nsite, nclf = merge(
        state.cv.reshape(n, cells),
        state.vr.reshape(n, cells),
        state.site.reshape(n, cells),
        clf,
        lanes,
    )
    return TableState(
        cv=ncv.reshape(n, r, c),
        vr=nvr.reshape(n, r, c),
        site=nsite.reshape(n, r, c),
        cl=nclf.reshape(n, r, c)[:, :, 0],
    )


def route_merge_sharded(
    state,  # TableState — (N, R, C) planes, node-sharded over the mesh
    dst: jnp.ndarray,  # (M,) int32 destination node per cell lane
    rank: jnp.ndarray,  # (M,) int32 mailbox rank within dst (< cap kept)
    cell: jnp.ndarray,  # (M,) int32 row * C + col
    cv: jnp.ndarray,
    vr: jnp.ndarray,
    site: jnp.ndarray,
    cl: jnp.ndarray,
    valid: jnp.ndarray,  # (M,) bool
    cap: int,
    mesh,
    axis_name: str = "nodes",
    interpret: bool = False,
):
    """Delivery-site sharded merge: route cross-shard lanes with ONE
    explicit ``all_to_all``, then run the Pallas kernel per shard.

    The flat cell-lane stream arrives evenly sliced over the mesh (the
    emission layout — lanes are src-major), but a lane's destination is
    arbitrary: gossip crosses shards. Inside one ``shard_map`` region,
    each device buckets its slice by destination shard (a stable local
    sort + within-bucket ranks), the ``(D, m/D)`` bucket tensor rides
    ``jax.lax.all_to_all`` — the ICI hop that replaces the reference's
    QUIC fabric for cross-shard gossip — and the receiving shard
    scatters its now-local lanes into the per-node mailbox at the
    GLOBALLY precomputed ``(dst, rank)`` slot. Mailbox positions are a
    pure function of the upstream dst-sorted order, so the merged planes
    are bit-for-bit the single-device kernel's (and the XLA scatter
    path's) regardless of which shard sourced a lane.

    Bucket capacity is the per-shard slice length (the worst case: every
    local lane targets one shard), so no lane is ever dropped by
    routing; invalid/over-cap lanes park in the drop sentinel row.
    """
    from corro_sim.core.crdt import TableState

    n, r, c = state.cv.shape
    cells = r * c
    d = mesh.shape[axis_name]
    nl = n // d
    m = dst.shape[0]
    pad = (-m) % d
    if pad:
        # pad to an even per-shard slice with parked (invalid) lanes
        z = jnp.zeros((pad,), jnp.int32)
        dst = jnp.concatenate([dst, z])
        rank = jnp.concatenate([rank, z])
        cell = jnp.concatenate([cell, z])
        cv = jnp.concatenate([cv, z])
        vr = jnp.concatenate([vr, z])
        site = jnp.concatenate([site, z])
        cl = jnp.concatenate([cl, z])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), bool)])

    def local(dstl, rankl, celll, cvl, vrl, sitel, cll, validl,
              cvf, vrf, sitef, clf):
        ml = dstl.shape[0]
        keep = validl & (rankl < cap)
        tgt = jnp.where(keep, dstl // jnp.int32(nl), jnp.int32(d))
        order = jnp.argsort(tgt, stable=True)
        # lane fields ride the exchange packed (8, lane)-column style:
        # rows 0-5 are the mailbox fields, the two pad rows carry the
        # global dst + rank needed for final mailbox placement
        fields = jnp.stack([
            celll, cvl, vrl, sitel, cll, keep.astype(jnp.int32),
            dstl, rankl,
        ], axis=1)[order]  # (ml, 8)
        tgt_s = tgt[order]
        routed = tgt_s < jnp.int32(d)
        pos = ranks_within_group_masked(tgt_s, routed)
        buckets = jnp.zeros((d, ml, LANE_FIELDS), jnp.int32)
        buckets = buckets.at[
            jnp.where(routed, tgt_s, jnp.int32(d)), pos
        ].set(fields, mode="drop")
        ex = jax.lax.all_to_all(
            buckets, axis_name, split_axis=0, concat_axis=0, tiled=True
        ).reshape(-1, LANE_FIELDS)
        me = jax.lax.axis_index(axis_name)
        local_dst = ex[:, 6] - me * jnp.int32(nl)
        ok = ex[:, 5] != 0
        slot = jnp.where(
            ok, local_dst * jnp.int32(cap) + ex[:, 7], jnp.int32(nl * cap)
        )
        lbox = (
            jnp.zeros((nl * cap, LANE_FIELDS), jnp.int32)
            .at[slot]
            .set(ex.at[:, 6].set(0).at[:, 7].set(0), mode="drop")
            .T
        )
        return grouped_merge(
            cvf, vrf, sitef, clf, lbox, cap, c,
            block_nodes=pick_block_nodes(nl), interpret=interpret,
        )

    clf = jnp.repeat(state.cl, c, axis=1)
    ncv, nvr, nsite, nclf = shard_map(
        local, mesh=mesh,
        in_specs=(P(axis_name),) * 8 + (P(axis_name),) * 4,
        out_specs=(P(axis_name),) * 4,
        check_rep=False,  # pallas_call has no replication rule
    )(
        dst, rank, cell, cv, vr, site, cl, valid,
        state.cv.reshape(n, cells),
        state.vr.reshape(n, cells),
        state.site.reshape(n, cells),
        clf,
    )
    return TableState(
        cv=ncv.reshape(n, r, c),
        vr=nvr.reshape(n, r, c),
        site=nsite.reshape(n, r, c),
        cl=nclf.reshape(n, r, c)[:, :, 0],
    )


def pick_block_nodes(n: int) -> int:
    for bn in (16, 8, 4, 2):
        if n % bn == 0:
            return bn
    return 1


def kernel_interpret() -> bool:
    """Interpret mode off-TPU (tests force the kernel on CPU)."""
    import jax

    return jax.default_backend() != "tpu"


def kernel_supported(cfg, path: str = "sync") -> bool:
    """Static gate for routing merges through the kernel.

    The kernel needs: a real TPU backend (Mosaic; the interpret path is
    for tests) and a 128-aligned flat cell space. Sharded runs are the
    FAST path since ISSUE 8, not the degraded one: under a mesh the
    kernel runs per-shard inside a ``shard_map`` region
    (:func:`merge_grouped` with ``mesh=``, delivery routing via
    :func:`route_merge_sharded`'s all_to_all) — the driver gates mesh
    runs through :func:`sharded_kernel_downgrade` and downgrades
    EXPLICITLY (flight annotation + counter) when the backend cannot,
    instead of the old silent ``merge_kernel="off"`` force.

    ``path``: which merge site is asking. Under ``merge_kernel="auto"``
    only the SYNC sweep uses the kernel — its 1.28M node-major lanes
    save ~120 ms/sweep on the real chip — while the gossip-delivery
    merge keeps the XLA scatter (mostly-invalid lanes make the in-situ
    scatter cheap; the kernel's fixed cost measured ~neutral there).
    ``"on"`` forces the kernel on both paths (equivalence tests).
    """
    if cfg.merge_kernel == "off":
        return False
    cells = cfg.num_rows * cfg.num_cols
    if not (cells % 128 == 0 and cells <= 8192):
        return False
    if cfg.merge_kernel == "on":
        return True
    if path != "sync":
        return False
    import jax

    return jax.default_backend() == "tpu"


def sharded_kernel_downgrade(cfg, n_devices: int) -> str | None:
    """Why a MESH run cannot keep its Pallas merge kernel, or None.

    The driver's explicit-downgrade gate (ISSUE 8): a non-None reason
    means the run must fall back to the GSPMD scatter path
    (``merge_kernel="off"``) — and say so (flight ``config_downgrade``
    annotation + ``corro_config_downgrade_total{reason}``), never
    silently. ``merge_kernel="off"`` itself is an explicit operator
    choice, not a downgrade.
    """
    if cfg.merge_kernel == "off":
        return None
    cells = cfg.num_rows * cfg.num_cols
    if not (cells % 128 == 0 and cells <= 8192):
        return "cell_space_unaligned"
    if cfg.num_nodes % max(n_devices, 1) != 0:
        return "uneven_node_shards"
    if cfg.merge_kernel == "on":
        return None  # forced: interpret per shard off-TPU (tests)
    import jax

    if jax.default_backend() != "tpu":
        return "sharded_non_tpu"
    return None