"""Version bookkeeping: who has applied which versions of which actor.

The reference keeps, per (node, actor), a ``BookedVersions``: the set of
applied changeset versions, the ``needed`` gap set (``RangeInclusiveSet``),
partial-seq buffers, and the max seen version
(``corro-types/src/agent.rs:1310-1496``). Gap ranges are collapsed
transactionally by ``compute_gaps_change`` (``agent.rs:1220-1285``).

A ragged range-set per (node, actor) cannot live on a TPU. Instead:

- ``head[N, A] int32`` — the contiguously-applied prefix: every version of
  actor ``a`` up to ``head[n, a]`` has been applied at node ``n``.
- ``win[N, A] uint32`` — a 32-slot out-of-order window: bit ``k`` set means
  version ``head + 1 + k`` was applied ahead of a gap.

A delivery inside the window sets its bit; the contiguous prefix is then
absorbed (count-trailing-ones + shift, :mod:`corro_sim.utils.bits`). A
delivery *beyond* the window is dropped — deliberately. That is the
reference's own escape hatch: ``handle_changes`` drops when its queue
overflows and anti-entropy sync repairs the loss
(``corro-agent/src/agent/handlers.rs:866-884``). Here "window overflow"
plays the role of queue overflow, and :mod:`corro_sim.sync` repairs it.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from corro_sim.utils.bits import WINDOW_BITS, absorb, window_shift_right
from corro_sim.utils.slots import dedupe_sorted_mask


@flax.struct.dataclass
class Bookkeeping:
    head: jnp.ndarray  # (N, A) int32
    win: jnp.ndarray  # (N, A) uint32


def make_bookkeeping(num_nodes: int, num_actors: int) -> Bookkeeping:
    return Bookkeeping(
        head=jnp.zeros((num_nodes, num_actors), jnp.int32),
        win=jnp.zeros((num_nodes, num_actors), jnp.uint32),
    )


def deliver_versions(
    book: Bookkeeping,
    dst: jnp.ndarray,
    actor: jnp.ndarray,
    ver: jnp.ndarray,
    valid: jnp.ndarray,
):
    """Record a flat batch of (dst, actor, version) deliveries.

    Returns ``(new_book, fresh, dropped)`` where ``fresh[m]`` is True iff
    message ``m`` was the first in this batch to deliver a not-yet-applied
    version (these are the changes worth merging and re-broadcasting — the
    reference's seen-cache + ``booked.contains_all`` check,
    ``handlers.rs:886-934``), and ``dropped[m]`` marks beyond-window drops
    for metrics (``corro.agent.changes.dropped`` analog).

    Within-batch duplicates are removed by sorting on (dst, actor, ver); the
    window bits are then applied with a plain scatter-add of ``1 << offset``
    (safe once unique).

    Batch semantics: window offsets are computed against the head *before*
    the batch — a batch models one round's concurrent deliveries, so a
    version more than WINDOW_BITS ahead of the pre-round head is dropped
    even if the same batch also fills the gap. (Sequential processing would
    accept it; the batched rule drops slightly more aggressively, which is
    safe — drops are exactly what anti-entropy repairs.)
    """
    m = dst.shape[0]
    n, a = book.head.shape

    # Sort by (dst, actor, ver); invalid lanes sort to the end via huge dst.
    big = jnp.int32(n + 1)
    sdst = jnp.where(valid, dst, big)
    order = jnp.lexsort((ver, actor, sdst))
    s_dst = sdst[order]
    s_actor = actor[order]
    s_ver = ver[order]
    s_valid = valid[order]

    first = dedupe_sorted_mask(s_dst, s_actor, s_ver) & s_valid

    pair_idx = (jnp.where(s_valid, s_dst, -1), s_actor)
    head_g = book.head[pair_idx]
    win_g = book.win[pair_idx]
    off = s_ver - head_g - 1  # window bit offset; <0 = already applied
    in_window = (off >= 0) & (off < WINDOW_BITS)
    already = (off >= 0) & (off < WINDOW_BITS) & (
        (win_g >> off.clip(0, WINDOW_BITS - 1).astype(jnp.uint32)) & jnp.uint32(1)
    ).astype(bool)
    fresh_sorted = first & in_window & ~already
    dropped_sorted = first & (off >= WINDOW_BITS)

    bit = jnp.where(
        fresh_sorted,
        jnp.left_shift(
            jnp.uint32(1), off.clip(0, WINDOW_BITS - 1).astype(jnp.uint32)
        ),
        jnp.uint32(0),
    )
    new_win = book.win.at[pair_idx].add(bit, mode="drop")
    new_head, new_win = absorb(book.head, new_win)

    # Un-sort the masks back to caller order.
    inv = jnp.zeros((m,), jnp.int32).at[order].set(jnp.arange(m, dtype=jnp.int32))
    return (
        Bookkeeping(head=new_head, win=new_win),
        fresh_sorted[inv],
        dropped_sorted[inv],
    )


def advance_heads(book: Bookkeeping, new_floor: jnp.ndarray) -> Bookkeeping:
    """Raise heads to at least ``new_floor`` (N, A) — the sync fast-path.

    After an anti-entropy transfer the contiguous prefix extends to the
    synced range's end; any window bits now below the head are re-absorbed.
    Window bits are *about* offsets from the old head, so shift them by the
    head delta before absorbing.
    """
    floor = jnp.maximum(book.head, new_floor)
    delta = (floor - book.head).astype(jnp.uint32)
    head, win = absorb(floor, window_shift_right(book.win, delta))
    return Bookkeeping(head=head, win=win)
