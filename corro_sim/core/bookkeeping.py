"""Version bookkeeping: who has applied which versions of which actor.

The reference keeps, per (node, actor), a ``BookedVersions``: the set of
applied changeset versions, the ``needed`` gap set (``RangeInclusiveSet``),
partial-seq buffers, and the max seen version
(``corro-types/src/agent.rs:1310-1496``). Gap ranges are collapsed
transactionally by ``compute_gaps_change`` (``agent.rs:1220-1285``).

A ragged range-set per (node, actor) cannot live on a TPU. Instead:

- ``head[N, A] int32`` — the contiguously-applied prefix: every version of
  actor ``a`` up to ``head[n, a]`` has been applied at node ``n``.
- ``win[N, A] uint32`` — an out-of-order window over the next
  ``32 // bits_per_version`` versions. Each version owns a group of
  ``bits_per_version`` adjacent bits, one per changeset *chunk*: bit
  ``v * bpv + c`` set means chunk ``c`` of version ``head + 1 + v`` has
  arrived. A version is *applied* only once its whole group is set — a
  partially-set group is a buffered partial version, the dense analog of
  ``__corro_buffered_changes`` + ``__corro_seq_bookkeeping``
  (``agent/util.rs:1065-1190``).

A delivery inside the window sets its bit; the contiguous prefix of
*complete* versions is then absorbed (count-trailing-ones rounded down to a
whole group + shift, :mod:`corro_sim.utils.bits`). A delivery *beyond* the
window is dropped — deliberately. That is the reference's own escape hatch:
``handle_changes`` drops when its queue overflows and anti-entropy sync
repairs the loss (``corro-agent/src/agent/handlers.rs:866-884``). Here
"window overflow" plays the role of queue overflow, and
:mod:`corro_sim.sync` repairs it.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from corro_sim.utils.bits import WINDOW_BITS, absorb, window_shift_right
from corro_sim.utils.slots import dedupe_sorted_mask


@flax.struct.dataclass
class Bookkeeping:
    head: jnp.ndarray  # (N, A) int32
    win: jnp.ndarray  # (N, A) uint32


def make_bookkeeping(num_nodes: int, num_actors: int) -> Bookkeeping:
    return Bookkeeping(
        head=jnp.zeros((num_nodes, num_actors), jnp.int32),
        win=jnp.zeros((num_nodes, num_actors), jnp.uint32),
    )


def version_window(bits_per_version: int) -> int:
    """How many versions ahead of the head the window can buffer."""
    return WINDOW_BITS // bits_per_version


def deliver_versions(
    book: Bookkeeping,
    dst: jnp.ndarray,
    actor: jnp.ndarray,
    ver: jnp.ndarray,
    valid: jnp.ndarray,
    chunk: jnp.ndarray | None = None,
    bits_per_version: int = 1,
    presorted: bool = False,
):
    """Record a flat batch of (dst, actor, version[, chunk]) deliveries.

    Returns ``(new_book, fresh_chunk, complete, dropped)``:

    - ``fresh_chunk[m]`` — message ``m`` was the first in this batch to
      deliver a not-yet-seen chunk (worth re-broadcasting — the reference's
      seen-cache + ``booked.contains_all`` check, ``handlers.rs:886-934``);
    - ``complete[m]`` — message ``m`` completed its version: every chunk of
      that version is now present and it was not complete before. These are
      the lanes whose changesets get merged into table state (the reference
      applies a version only once seq-complete, ``util.rs:458-501``); the
      mask is set on exactly one lane per completed (dst, actor, ver);
    - ``dropped[m]`` — beyond-window drops for metrics
      (``corro.agent.changes.dropped`` analog).

    Within-batch duplicates are removed by sorting on (dst, actor, ver,
    chunk); the window bits are then applied with a plain scatter-add of
    ``1 << offset`` (safe once unique).

    ``presorted=True`` skips the sort AND the inverse permutation: the
    caller promises the lanes are already ordered by
    ``(where(valid, dst, n+1), actor, ver, chunk)`` (the step function
    hoists ONE sort for the whole per-lane pipeline) and receives the
    result masks in the given (sorted) lane order.

    Batch semantics: window offsets are computed against the head *before*
    the batch — a batch models one round's concurrent deliveries, so a
    version more than ``window`` ahead of the pre-round head is dropped
    even if the same batch also fills the gap. (Sequential processing would
    accept it; the batched rule drops slightly more aggressively, which is
    safe — drops are exactly what anti-entropy repairs.)
    """
    m = dst.shape[0]
    n, a = book.head.shape
    bpv = bits_per_version
    vwin = WINDOW_BITS // bpv
    # single-chunk fast path: with one chunk per version the chunk key
    # is identically zero — skip its dedupe key, the second dedupe pass
    # (first_chunk == first_ver) and the offset arithmetic entirely
    chunkless = bpv == 1 and chunk is None
    if chunk is None:
        chunk = jnp.zeros((m,), jnp.int32)

    # Sort by (dst, actor, ver, chunk); invalid lanes sort to the end.
    big = jnp.int32(n + 1)
    sdst = jnp.where(valid, dst, big)
    if presorted:
        order = None
        s_dst, s_actor, s_ver, s_chunk, s_valid = (
            sdst, actor, ver, chunk, valid
        )
    else:
        order = jnp.lexsort((chunk, ver, actor, sdst))
        s_dst = sdst[order]
        s_actor = actor[order]
        s_ver = ver[order]
        s_chunk = chunk[order]
        s_valid = valid[order]

    if chunkless:
        first_chunk = first_ver = (
            dedupe_sorted_mask(s_dst, s_actor, s_ver) & s_valid
        )
    else:
        first_chunk = (
            dedupe_sorted_mask(s_dst, s_actor, s_ver, s_chunk) & s_valid
        )
        first_ver = dedupe_sorted_mask(s_dst, s_actor, s_ver) & s_valid

    pair_idx = (jnp.where(s_valid, s_dst, -1), s_actor)
    head_g = book.head[pair_idx]
    win_g = book.win[pair_idx]
    voff = s_ver - head_g - 1  # version offset in window; <0 = absorbed
    in_window = (voff >= 0) & (voff < vwin)
    if chunkless:
        off = voff.clip(0, WINDOW_BITS - 1).astype(jnp.uint32)
    else:
        off = (
            (voff * bpv + s_chunk).clip(0, WINDOW_BITS - 1)
            .astype(jnp.uint32)
        )
    already = in_window & ((win_g >> off) & jnp.uint32(1)).astype(bool)
    fresh_sorted = first_chunk & in_window & ~already
    dropped_sorted = first_chunk & (voff >= vwin)

    bit = jnp.where(fresh_sorted, jnp.left_shift(jnp.uint32(1), off), jnp.uint32(0))
    new_win = book.win.at[pair_idx].add(bit, mode="drop")

    # Version completion: all bpv bits of the version's group set *now* and
    # not all set before this batch. Reported once per (dst, actor, ver).
    if bpv == 1:
        complete_sorted = fresh_sorted
    else:
        group_mask = jnp.uint32((1 << bpv) - 1)
        gshift = (voff.clip(0, vwin - 1) * bpv).astype(jnp.uint32)
        vmask = jnp.left_shift(group_mask, gshift)
        now_g = new_win[pair_idx]
        complete_sorted = (
            first_ver
            & in_window
            & ((now_g & vmask) == vmask)
            & ((win_g & vmask) != vmask)
        )

    new_head, new_win = absorb(book.head, new_win, bpv)

    if presorted:
        return (
            Bookkeeping(head=new_head, win=new_win),
            fresh_sorted,
            complete_sorted,
            dropped_sorted,
        )
    # Un-sort the masks back to caller order.
    inv = jnp.zeros((m,), jnp.int32).at[order].set(jnp.arange(m, dtype=jnp.int32))
    return (
        Bookkeeping(head=new_head, win=new_win),
        fresh_sorted[inv],
        complete_sorted[inv],
        dropped_sorted[inv],
    )


def partial_versions(book: Bookkeeping, bits_per_version: int) -> jnp.ndarray:
    """() int32 — count of buffered partial versions across the cluster.

    The gauge analog of the reference's ``__corro_buffered_changes`` row
    count (``agent/metrics.rs:47-60``): window groups with some but not all
    chunk bits set.
    """
    bpv = bits_per_version
    if bpv == 1:
        return jnp.int32(0)  # single-chunk versions are never partial
    vwin = WINDOW_BITS // bpv
    group_mask = jnp.uint32((1 << bpv) - 1)
    total = jnp.int32(0)
    win = book.win
    for v in range(vwin):
        g = (win >> jnp.uint32(v * bpv)) & group_mask
        total = total + ((g != 0) & (g != group_mask)).sum(dtype=jnp.int32)
    return total


def advance_heads(
    book: Bookkeeping, new_floor: jnp.ndarray, bits_per_version: int = 1
) -> Bookkeeping:
    """Raise heads to at least ``new_floor`` (N, A) — the sync fast-path.

    After an anti-entropy transfer the contiguous prefix extends to the
    synced range's end; any window bits now below the head are re-absorbed.
    Window bits are *about* offsets from the old head, so shift them by the
    head delta before absorbing.
    """
    floor = jnp.maximum(book.head, new_floor)
    delta = (floor - book.head).astype(jnp.uint32) * jnp.uint32(bits_per_version)
    head, win = absorb(floor, window_shift_right(book.win, delta), bits_per_version)
    return Bookkeeping(head=head, win=win)
