"""CR-SQLite's LWW CRDT merge as batched TPU array ops.

Reference semantics (``doc/crdts.md:15-17,237``, enforced by the CR-SQLite
extension the reference bundles at ``crates/corro-types/crsqlite-*.so``):

  For an existing (row, column) cell, an incoming change wins iff its
  ``(col_version, value, site_id)`` triple is lexicographically larger than
  the stored one. ("Biggest ``col_version`` wins; tie → biggest value";
  final tie broken on site_id — ``doc/crdts.md:237``.)

Per-row *causal length* ``cl`` (delete/resurrect counter) merges by max
(cl CRDT, ``doc/crdts.md:13``; odd = live row, even = deleted).

TPU design
----------
Node-local SQLite B-trees become one structure-of-arrays *TableState*: three
int32 planes of shape (nodes, rows, cols) holding ``col_version``,
``value_rank`` (values interned to a total order preserving SQLite value
comparison, see :mod:`corro_sim.io.values`) and ``site``. Merging a batch of
changes is then a *lexicographic scatter-max*. XLA has no lexicographic
scatter combinator and we avoid 64-bit packed keys (int64 is emulated on
TPU), so the merge runs as three masked int32 scatter-max passes:

1. scatter-max ``col_version``;
2. among changes whose col_version equals the post-merge winner, scatter-max
   ``value_rank`` (existing value participates in the tie only if the stored
   col_version survived);
3. among changes matching both, scatter-max ``site``.

All passes are dense, batched over every node at once — the per-node merge
loop of ``process_multiple_changes`` (reference
``corro-agent/src/agent/util.rs:721-1062``) vanishes into three scatters.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

NEG = jnp.int32(-(2**31))


@flax.struct.dataclass
class TableState:
    """Per-node CRDT cell state; every field shape (N, R, C) except cl (N, R)."""

    cv: jnp.ndarray  # col_version, int32, starts at 0 (= never written)
    vr: jnp.ndarray  # value rank, int32, NEG when never written
    site: jnp.ndarray  # writer site ordinal, int32, -1 when never written
    cl: jnp.ndarray  # causal length per row, int32, 0 = never existed


def make_table_state(num_nodes: int, num_rows: int, num_cols: int) -> TableState:
    shape = (num_nodes, num_rows, num_cols)
    return TableState(
        cv=jnp.zeros(shape, jnp.int32),
        vr=jnp.full(shape, NEG, jnp.int32),
        site=jnp.full(shape, -1, jnp.int32),
        cl=jnp.zeros((num_nodes, num_rows), jnp.int32),
    )


def apply_cell_changes(
    state: TableState,
    dst: jnp.ndarray,
    row: jnp.ndarray,
    col: jnp.ndarray,
    ch_cv: jnp.ndarray,
    ch_vr: jnp.ndarray,
    ch_site: jnp.ndarray,
    ch_cl: jnp.ndarray,
    valid: jnp.ndarray,
) -> TableState:
    """Merge a flat batch of cell changes into the cluster's table state.

    Args are parallel (M,) arrays: destination node, row slot, column, and the
    change triple. ``valid`` masks out padding lanes (ragged batches are the
    norm: every round produces a different number of deliveries, but shapes
    must be static under jit).

    This is the TPU analog of the reference's per-change
    ``INSERT INTO crsql_changes`` loop in ``process_complete_version``
    (``corro-agent/src/agent/util.rs:1193-1307``) — except it applies every
    change for every node in one shot.

    Causal-generation semantics (CR-SQLite's causal-length CRDT,
    ``doc/crdts.md:13``): the row's causal length merges first; a row whose
    cl *increases* changes generation and physically loses its cells (a
    DELETE drops the row and its clock rows in CR-SQLite — values don't
    survive the tombstone; a resurrect starts a fresh generation). Value
    changes then apply only if their cl matches the row's post-merge
    generation — a stale-generation update loses to a concurrent delete.
    """
    # Invalid lanes scatter out of bounds and get dropped.
    dst = jnp.where(valid, dst, -1)

    # Pass 0: causal length — per-row max (cl CRDT), then generation wipe.
    cl0 = state.cl
    cl1 = cl0.at[dst, row].max(jnp.where(valid, ch_cl, NEG), mode="drop")
    bumped = (cl1 > cl0)[:, :, None]  # (N, R, 1) — generation changed
    cv0 = jnp.where(bumped, 0, state.cv)
    vr0 = jnp.where(bumped, NEG, state.vr)
    site0 = jnp.where(bumped, -1, state.site)

    idx = (dst, row, col)
    # A value lane participates only at the row's current generation.
    val = valid & (ch_vr != NEG) & (ch_cl == cl1[dst, row])

    # Pass 1: col_version.
    cv1 = cv0.at[idx].max(jnp.where(val, ch_cv, NEG), mode="drop")

    # Pass 2: value rank. The stored value only competes if the stored
    # col_version is still the winner; otherwise the cell was superseded and
    # its value is reset before the tie-break.
    vr_base = jnp.where(cv1 > cv0, NEG, vr0)
    win1 = val & (ch_cv == cv1[idx])
    vr1 = vr_base.at[idx].max(jnp.where(win1, ch_vr, NEG), mode="drop")

    # Pass 3: site. Stored site survives only if (cv, vr) both survived.
    site_base = jnp.where((cv1 != cv0) | (vr1 != vr0), NEG, site0)
    win2 = win1 & (ch_vr == vr1[idx])
    site1 = site_base.at[idx].max(jnp.where(win2, ch_site, NEG), mode="drop")

    return TableState(cv=cv1, vr=vr1, site=site1, cl=cl1)


def local_write(
    state: TableState,
    writer: jnp.ndarray,  # (n,) int32
    row: jnp.ndarray,  # (n, S) int32 — row slot per cell
    col: jnp.ndarray,  # (n, S) int32 — column per cell
    vr: jnp.ndarray,  # (n, S) int32 — new value rank per cell
    is_delete: jnp.ndarray,  # (n,) bool — changeset is a row DELETE
    ncells: jnp.ndarray,  # (n,) int32 — live cells per changeset
    valid: jnp.ndarray,  # (n,) bool
):
    """Apply one multi-cell changeset per writer; return its change records.

    A changeset is one transaction's worth of cell writes (up to S cells,
    each a seq-numbered ``Change`` row in the reference,
    ``corro-api-types/src/lib.rs:235-245``). A local UPDATE bumps each
    touched cell's col_version to (stored + 1) — exactly what the CR-SQLite
    triggers do on a tracked table (``doc/crdts.md:82``). A DELETE instead
    bumps the row's causal length to the next even number and a fresh
    INSERT after a delete bumps it to the next odd number (causal-length
    CRDT). Cells within one changeset must target distinct (row, col)
    pairs — the same invariant SQLite gives the reference, where a tx's
    changes coalesce per cell before extraction.

    Returns ``(new_state, ch_cv, ch_cl, ch_vr)``, each (n, S) — the
    per-cell col_version / causal length / value rank to record in the
    change log and gossip out.
    """
    n, s = row.shape
    cell_live = (
        valid[:, None]
        & (jnp.arange(s, dtype=jnp.int32)[None, :] < ncells[:, None])
    )
    widx = jnp.where(valid, writer, -1)[:, None]
    cur_cv = state.cv[widx, row, col]
    cur_cl = state.cl[widx, row]

    # Next causal length: resurrect (or first insert) → odd; delete → even.
    alive = (cur_cl % 2) == 1
    del_b = is_delete[:, None]
    ch_cl = jnp.where(
        del_b,
        jnp.where(alive, cur_cl + 1, cur_cl),
        jnp.where(alive, cur_cl, cur_cl + 1),
    ).astype(jnp.int32)
    ch_cv = jnp.where(del_b, cur_cv, cur_cv + 1).astype(jnp.int32)
    # A DELETE only bumps the causal length — it must not touch column
    # values (CR-SQLite deletes never produce value changes, only clock
    # rows). Neutralize the value/site lanes so the merge is a cl-only op.
    ch_vr = jnp.where(del_b, NEG, vr).astype(jnp.int32)
    ch_site = jnp.where(
        del_b, NEG, jnp.broadcast_to(writer[:, None], (n, s))
    ).astype(jnp.int32)

    new_state = apply_cell_changes(
        state,
        jnp.broadcast_to(writer[:, None], (n, s)).reshape(-1),
        row.reshape(-1),
        col.reshape(-1),
        ch_cv.reshape(-1),
        ch_vr.reshape(-1),
        ch_site.reshape(-1),
        ch_cl.reshape(-1),
        cell_live.reshape(-1),
    )
    return new_state, ch_cv, ch_cl, ch_vr
