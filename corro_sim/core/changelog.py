"""The global change log: every actor's write history as dense tensors.

In the reference each agent's writes live in its SQLite ``crsql_changes``
virtual table, keyed by (version, seq) and re-read at broadcast and sync
time (``corro-types/src/broadcast.rs:480-544``,
``corro-agent/src/api/peer.rs:351-762``). In the simulator the whole
cluster shares one address space, so the authoritative write history is a
single replicated structure-of-arrays indexed by (actor, version % L):

    log_row[A, L]   row slot written
    log_col[A, L]   column index
    log_vr[A, L]    interned value rank
    log_cv[A, L]    col_version assigned at write time
    log_cl[A, L]    causal length assigned at write time

``L`` caps versions per actor per run (static shape); the ring wraps, which
is safe as long as no node lags more than ``L`` versions — the same flavor
of bound as the reference's bounded queues. What stays *per node* is only
the bookkeeping of which (actor, version) pairs have been applied
(:mod:`corro_sim.core.bookkeeping`) — delivery state, not data.

One version == one cell change here (the reference batches a transaction
into one version with many seqs, ``corro-api-types/src/lib.rs:235-245``;
multi-cell changesets are modeled by emitting consecutive versions).
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp


@flax.struct.dataclass
class ChangeLog:
    row: jnp.ndarray  # (A, L) int32
    col: jnp.ndarray  # (A, L) int32
    vr: jnp.ndarray  # (A, L) int32
    cv: jnp.ndarray  # (A, L) int32
    cl: jnp.ndarray  # (A, L) int32
    head: jnp.ndarray  # (A,) int32 — number of versions each actor has written

    @property
    def capacity(self) -> int:
        return self.row.shape[1]


def make_changelog(num_actors: int, capacity: int) -> ChangeLog:
    # Distinct buffers per field — sharing one zeros array across fields
    # makes buffer donation reject the state ("same buffer donated twice").
    shape = (num_actors, capacity)
    return ChangeLog(
        row=jnp.zeros(shape, jnp.int32),
        col=jnp.zeros(shape, jnp.int32),
        vr=jnp.zeros(shape, jnp.int32),
        cv=jnp.zeros(shape, jnp.int32),
        cl=jnp.zeros(shape, jnp.int32),
        head=jnp.zeros((num_actors,), jnp.int32),
    )


def append_writes(
    log: ChangeLog,
    actor: jnp.ndarray,
    row: jnp.ndarray,
    col: jnp.ndarray,
    vr: jnp.ndarray,
    cv: jnp.ndarray,
    cl: jnp.ndarray,
    valid: jnp.ndarray,
):
    """Append one write per listed actor; returns (log, version) per lane.

    Each lane is a distinct actor (a node writes at most one changeset per
    round — the reference serializes local writes through a single write
    connection + ``Semaphore(1)``, ``corro-types/src/agent.rs:500-731``, so
    per-round-per-actor writes are naturally ordered).
    """
    aidx = jnp.where(valid, actor, -1)
    ver = log.head[aidx] + 1  # versions are 1-based (Version(u64) newtype)
    slot = (ver - 1) % log.capacity
    idx = (aidx, slot)
    return (
        ChangeLog(
            row=log.row.at[idx].set(row, mode="drop"),
            col=log.col.at[idx].set(col, mode="drop"),
            vr=log.vr.at[idx].set(vr, mode="drop"),
            cv=log.cv.at[idx].set(cv, mode="drop"),
            cl=log.cl.at[idx].set(cl, mode="drop"),
            head=log.head.at[aidx].add(jnp.where(valid, 1, 0), mode="drop"),
        ),
        ver.astype(jnp.int32),
    )


def gather_changes(log: ChangeLog, actor: jnp.ndarray, ver: jnp.ndarray):
    """Fetch the (row, col, vr, cv, cl) tuple for (actor, version) lanes."""
    slot = (ver - 1) % log.capacity
    idx = (actor, slot)
    return log.row[idx], log.col[idx], log.vr[idx], log.cv[idx], log.cl[idx]
