"""The global change log: every actor's write history as dense tensors.

In the reference each agent's writes live in its SQLite ``crsql_changes``
virtual table, keyed by (version, seq) and re-read at broadcast and sync
time (``corro-types/src/broadcast.rs:480-544``,
``corro-agent/src/api/peer.rs:351-762``). In the simulator the whole
cluster shares one address space, so the authoritative write history is a
single replicated structure-of-arrays indexed by (actor, version % L, seq):

    log_row[A, L, S]   row slot written by each cell
    log_col[A, L, S]   column index
    log_vr[A, L, S]    interned value rank
    log_cv[A, L, S]    col_version assigned at write time
    log_cl[A, L, S]    causal length assigned at write time
    ncells[A, L]       cells actually used (last_seq + 1 analog,
                       ``corro-api-types/src/lib.rs:235-245``)

``L`` caps versions per actor per run (static shape); the ring wraps, which
is safe as long as no node lags more than ``L`` versions — the same flavor
of bound as the reference's bounded queues. ``S`` caps cells per changeset
(one version == one transaction's changeset; its cells are the reference's
seq-numbered ``Change`` rows). What stays *per node* is only the
bookkeeping of which (actor, version, chunk) triples have been applied
(:mod:`corro_sim.core.bookkeeping`) — delivery state, not data.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp


# cells[..., k] layout of the packed cell tensor
CELL_ROW, CELL_COL, CELL_VR, CELL_CV, CELL_CL = range(5)


@flax.struct.dataclass
class ChangeLog:
    # One packed tensor for the five per-cell fields — a gather/scatter of
    # (actor, slot) lanes then moves one contiguous (S, 5) block per lane
    # instead of five scattered words (TPU gathers are per-descriptor, so
    # packing the minor dim is ~5x fewer descriptors on the hot path).
    cells: jnp.ndarray  # (A, L, S, 5) int32 — [row, col, vr, cv, cl]
    ncells: jnp.ndarray  # (A, L) int32
    live: jnp.ndarray  # (A, L) int32 — cells still globally winning
    cleared: jnp.ndarray  # (A, L) bool — fully superseded (empty changeset)
    head: jnp.ndarray  # (A,) int32 — number of versions each actor has written

    @property
    def capacity(self) -> int:
        return self.cells.shape[1]

    @property
    def seqs(self) -> int:
        return self.cells.shape[2]

    # read-only views (introspection/tests; hot paths use `cells` directly)
    @property
    def row(self) -> jnp.ndarray:
        return self.cells[..., CELL_ROW]

    @property
    def col(self) -> jnp.ndarray:
        return self.cells[..., CELL_COL]

    @property
    def vr(self) -> jnp.ndarray:
        return self.cells[..., CELL_VR]

    @property
    def cv(self) -> jnp.ndarray:
        return self.cells[..., CELL_CV]

    @property
    def cl(self) -> jnp.ndarray:
        return self.cells[..., CELL_CL]


def make_changelog(num_actors: int, capacity: int, seqs: int = 1) -> ChangeLog:
    shape = (num_actors, capacity, seqs, 5)
    return ChangeLog(
        cells=jnp.zeros(shape, jnp.int32),
        ncells=jnp.zeros((num_actors, capacity), jnp.int32),
        live=jnp.zeros((num_actors, capacity), jnp.int32),
        cleared=jnp.zeros((num_actors, capacity), bool),
        head=jnp.zeros((num_actors,), jnp.int32),
    )


def append_changesets(
    log: ChangeLog,
    actor: jnp.ndarray,  # (n,) int32
    row: jnp.ndarray,  # (n, S) int32
    col: jnp.ndarray,  # (n, S) int32
    vr: jnp.ndarray,  # (n, S) int32
    cv: jnp.ndarray,  # (n, S) int32
    cl: jnp.ndarray,  # (n, S) int32
    ncells: jnp.ndarray,  # (n,) int32
    valid: jnp.ndarray,  # (n,) bool
):
    """Append one changeset per listed actor; returns (log, version) per lane.

    Each lane is a distinct actor (a node writes at most one changeset per
    round — the reference serializes local writes through a single write
    connection + ``Semaphore(1)``, ``corro-types/src/agent.rs:500-731``, so
    per-round-per-actor writes are naturally ordered).
    """
    # OOB-positive sentinel: JAX scatter mode="drop" drops indices >= size,
    # but a -1 wraps to the last actor and corrupts it.
    aidx = jnp.where(valid, actor, log.head.shape[0])
    ver = log.head[jnp.where(valid, actor, 0)] + 1  # 1-based (Version newtype)
    slot = (ver - 1) % log.capacity
    idx = (aidx, slot)
    packed = jnp.stack([row, col, vr, cv, cl], axis=-1)  # (n, S, 5)
    return (
        ChangeLog(
            cells=log.cells.at[idx].set(packed, mode="drop"),
            ncells=log.ncells.at[idx].set(ncells, mode="drop"),
            live=log.live.at[idx].set(ncells, mode="drop"),
            cleared=log.cleared.at[idx].set(False, mode="drop"),
            head=log.head.at[aidx].add(jnp.where(valid, 1, 0), mode="drop"),
        ),
        ver.astype(jnp.int32),
    )


def gather_changesets(log: ChangeLog, actor: jnp.ndarray, ver: jnp.ndarray):
    """Fetch the full cell arrays for (actor, version) lanes.

    Returns ``(row, col, vr, cv, cl, ncells)`` where the cell planes have
    shape ``lanes + (S,)`` and ``ncells`` has the lane shape — the analog of
    re-reading ``crsql_changes WHERE db_version = ? ORDER BY seq``
    (``corro-types/src/broadcast.rs:492-500``).
    """
    slot = (ver - 1) % log.capacity
    idx = (actor, slot)
    g = log.cells[idx]  # lanes + (S, 5) — ONE gather for all five fields
    return (
        g[..., CELL_ROW],
        g[..., CELL_COL],
        g[..., CELL_VR],
        g[..., CELL_CV],
        g[..., CELL_CL],
        log.ncells[idx],
    )
