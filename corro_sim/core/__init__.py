from corro_sim.core.crdt import TableState, apply_cell_changes, make_table_state
from corro_sim.core.bookkeeping import Bookkeeping, deliver_versions, make_bookkeeping
from corro_sim.core.changelog import ChangeLog, append_changesets, make_changelog

__all__ = [
    "TableState",
    "apply_cell_changes",
    "make_table_state",
    "Bookkeeping",
    "deliver_versions",
    "make_bookkeeping",
    "ChangeLog",
    "make_changelog",
    "append_changesets",
]
