"""Fused broadcast-delivery pass: one sorted stream, every consumer.

The delivery pipeline used to live inline in ``engine/step.py`` as a
sequence of independent stages — a lane lexsort, the HLC scatter-max,
the apply-queue rank, bookkeeping dedupe + window bits, the probe
first-seen/infector scatters, changeset gathers and the CRDT merge —
each re-deriving masks over the same ``(dst, actor, ver)`` stream. This
module is that pipeline fused into ONE pass (ISSUE 6 tentpole):

- the lane sort is hoisted once and every stage consumes the sorted
  stream (bookkeeping's ``presorted`` fast path, the grouped enqueue,
  the dst-coalesced merge scatter);
- single-chunk configs (``chunks_per_version == 1``, every tier-1 and
  bench config) collapse the chunk axis statically: the sort key packs
  ``(dst, actor)`` into one int, the chunk plane is a constant, and
  bookkeeping runs its chunkless dedupe (one dedupe pass, no offset
  arithmetic) — the dead eqns the jaxpr audit exposed;
- the probe tracer's delivery merge point rides the same stream
  instead of bracketing it (link-fault masking stays upstream in
  ``engine/step.py``: the fault draws are keyed by emission lane order
  and must not see the permuted stream);
- the CRDT merge routes through the Pallas dst-grouped kernel
  (``core/merge_kernel.py``: route the lanes into the per-node mailbox
  with one scatter, merge in VMEM) when ``kernel_supported`` says the
  backend can, and through the ``lax``-composite scatter fallback
  otherwise (CPU, sharded meshes).

Semantics are bit-for-bit the unfused pipeline's — the step-program
equivalence tests (tests/test_engine.py driver/repair, tests/
test_pipeline.py) and the golden fingerprint pin it.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from corro_sim.core.bookkeeping import deliver_versions
from corro_sim.core.changelog import gather_changesets
from corro_sim.core.crdt import NEG, apply_cell_changes
from corro_sim.core.merge_kernel import (
    kernel_interpret,
    kernel_supported,
    merge_grouped,
    pick_block_nodes,
    route_lanes,
    route_merge_sharded,
)
from corro_sim.utils.slots import ranks_within_group_masked


class DeliveryResult(NamedTuple):
    """Everything the rest of the round consumes from the fused pass.
    Lane arrays are in SORTED order (delivered lanes grouped by dst)."""

    table: object  # merged TableState
    book: object  # updated Bookkeeping
    probe: object  # updated ProbeState (untouched when probes off)
    hlc_recv: jnp.ndarray  # (N,) per-node max sender clock this round
    dst: jnp.ndarray
    src: jnp.ndarray
    actor: jnp.ndarray
    ver: jnp.ndarray
    chunk: jnp.ndarray
    delivered: jnp.ndarray  # post-cap delivery mask
    delivered_precap: jnp.ndarray  # pre-apply-queue-cap mask (RTT samples
    # observe every landed packet, capped or not — transport.rs:199-233)
    fresh_chunk: jnp.ndarray  # first delivery of a not-yet-seen chunk
    complete: jnp.ndarray  # lane completed its version (merge trigger)
    dropped: jnp.ndarray  # window/caps drops (metrics)
    c_cleared: jnp.ndarray  # gathered cleared flag per lane
    g_actor: jnp.ndarray  # complete-masked actor (changeset gather key)
    g_slot: jnp.ndarray  # version ring slot per lane
    cell_live: jnp.ndarray  # (m, S) cells actually merged


def delivery_pass(
    cfg,
    table,
    book,
    log,
    probe,
    hlc: jnp.ndarray,  # (N,) current clocks (sender stamps)
    dst: jnp.ndarray,
    src: jnp.ndarray,
    actor: jnp.ndarray,
    ver: jnp.ndarray,
    chunk: jnp.ndarray,
    delivered: jnp.ndarray,
    round_,
    mesh=None,
) -> DeliveryResult:
    """Sort once; deliver, account, trace and merge off that one order.

    ``mesh``: the run is sharded over this device mesh (ISSUE 8). Only
    the kernel merge site changes: the per-node mailbox routes through
    :func:`route_merge_sharded`'s explicit ``all_to_all`` (cross-shard
    lanes hop the ICI once) and the Pallas kernel runs per shard inside
    a ``shard_map`` region. Everything else — the hoisted sort, HLC
    scatter-max, bookkeeping, probes — partitions under GSPMD exactly
    as before, and ``mesh=None`` traces the byte-identical single-device
    program (the jaxpr golden pins it)."""
    n = cfg.num_nodes
    s = cfg.seqs_per_version
    cpv = cfg.chunks_per_version

    # ONE lane sort for the whole delivery pipeline: bookkeeping dedupe
    # (deliver_versions presorted path), changeset gathers, the merge
    # scatter (coalesced by dst), and ring enqueue (grouped path) all run
    # in this order — instead of each stage sorting for itself.
    big = jnp.int32(n + 1)
    sort_dst = jnp.where(delivered, dst, big)
    if cpv == 1 and (n + 2) * (n + 2) < 2**31:
        # pack (dst, actor) into one key; chunk is identically 0
        order = jnp.lexsort((ver, sort_dst * jnp.int32(n + 2) + actor))
    else:
        order = jnp.lexsort((chunk, ver, actor, sort_dst))
    dst = dst[order]
    src = src[order]
    actor = actor[order]
    ver = ver[order]
    delivered = delivered[order]
    if cpv == 1:
        # single-chunk ring entries always carry chunk 0 — the plane is
        # a constant, not a permuted gather
        chunk = jnp.zeros(dst.shape, jnp.int32)
    else:
        chunk = chunk[order]

    # ------------------------------------------------------------ HLC merge
    # Every delivered message carries the sender's clock; the receiver
    # merges max(local, remote) and ticks at end of round — the uhlc
    # exchange the reference performs on every contact (broadcast
    # timestamps, sync Clock messages; setup.rs:91-96, peer.rs:1502-1521).
    hlc_recv = (
        jnp.zeros((n,), jnp.int32)
        .at[jnp.where(delivered, dst, n)]
        .max(hlc[src], mode="drop")
    )

    # ------------------------------------- delivery: bookkeeping + merge
    use_kernel = kernel_supported(cfg, path="delivery")
    # Bounded apply queue (reference config.rs:10-41): each node processes
    # at most apply_queue_cap deliveries per round; overflow drops BEFORE
    # bookkeeping (counted below) and sync repairs it, like the
    # reference's queue-overflow drops (handlers.rs:866-884). Applied on
    # BOTH merge paths — a simulation-model bound, not an execution
    # detail, so results are backend-independent. Lanes are sorted
    # delivered-first-per-dst, so the masked rank is exact.
    rankd = ranks_within_group_masked(dst, delivered)
    delivered_precap = delivered
    overcap = delivered & (rankd >= cfg.apply_queue_cap)
    delivered = delivered & ~overcap
    book, fresh_chunk, complete, dropped = deliver_versions(
        book, dst, actor, ver, delivered,
        chunk=None if cpv == 1 else chunk, bits_per_version=cpv,
        presorted=True,
    )
    dropped = dropped | overcap
    # ------------------------------------------------------- probe tracer
    # The broadcast merge point (engine/probe.py) rides the same sorted
    # stream. The flag is static: probes == 0 traces ZERO extra ops and
    # the step program stays bit-identical to the uninstrumented one.
    if cfg.probes:
        # deferred import: engine.probe pulls in the engine package,
        # which imports engine.step, which imports this module — the
        # same lazy-import pattern step.py uses for swim_window
        from corro_sim.engine.probe import probe_delivery_update

        probe = probe_delivery_update(
            probe, round_, dst, src, actor, ver, delivered, complete
        )
    g_actor = jnp.where(complete, actor, 0)
    g_slot = (jnp.maximum(ver, 1) - 1) % log.capacity
    c_row, c_col, c_vr, c_cv, c_cl, c_n = gather_changesets(
        log, g_actor, jnp.maximum(ver, 1)
    )
    m = dst.shape[0]
    # Cleared versions deliver no cells — the receiver of an emptied
    # changeset just fast-forwards bookkeeping (handle_emptyset analog).
    c_cleared = log.cleared[g_actor, g_slot]
    cell_live = (
        complete[:, None]
        & ~c_cleared[:, None]
        & (jnp.arange(s, dtype=jnp.int32)[None, :] < c_n[:, None])
    )
    # The writing site is the actor — except for DELETE entries (logged with
    # vr == NEG), which are cl-only and must not claim the site slot either.
    c_site = jnp.where(
        c_vr == NEG, NEG, jnp.broadcast_to(actor[:, None], (m, s))
    )
    if use_kernel:
        # Pallas dst-grouped merge: route cell lanes into the per-node
        # mailbox (one scatter) and merge in VMEM — no per-lane
        # scatter/gather descriptors (core/merge_kernel.py).
        cap_lanes = cfg.apply_queue_cap * s
        rank_cell = (rankd[:, None] * s
                     + jnp.arange(s, dtype=jnp.int32)[None, :])
        # ONE flat cell-lane field list feeds both routing paths — the
        # two arms cannot diverge on lane packing
        lane_fields = (
            jnp.broadcast_to(dst[:, None], (m, s)).reshape(-1),
            rank_cell.reshape(-1),
            (c_row * cfg.num_cols + c_col).reshape(-1),
            c_cv.reshape(-1),
            c_vr.reshape(-1),
            c_site.reshape(-1),
            c_cl.reshape(-1),
            cell_live.reshape(-1),
        )
        if mesh is not None:
            # mesh-partitioned kernel: cross-shard lanes all_to_all to
            # their dst's shard, then merge per shard — mailbox slots
            # (dst, rank) are globally precomputed, so the result is
            # bit-for-bit the single-device kernel's
            table = route_merge_sharded(
                table, *lane_fields, cap_lanes, mesh,
                interpret=kernel_interpret(),
            )
        else:
            box = route_lanes(*lane_fields, n, cap_lanes)
            table = merge_grouped(
                table, box, cap_lanes,
                block_nodes=pick_block_nodes(n),
                interpret=kernel_interpret(),
            )
    else:
        table = apply_cell_changes(
            table,
            jnp.broadcast_to(dst[:, None], (m, s)).reshape(-1),
            c_row.reshape(-1),
            c_col.reshape(-1),
            c_cv.reshape(-1),
            c_vr.reshape(-1),
            c_site.reshape(-1),
            c_cl.reshape(-1),
            cell_live.reshape(-1),
        )

    return DeliveryResult(
        table=table, book=book, probe=probe, hlc_recv=hlc_recv,
        dst=dst, src=src, actor=actor, ver=ver, chunk=chunk,
        delivered=delivered, delivered_precap=delivered_precap,
        fresh_chunk=fresh_chunk, complete=complete,
        dropped=dropped, c_cleared=c_cleared, g_actor=g_actor,
        g_slot=g_slot, cell_live=cell_live,
    )
