"""Overwritten-version clearing — the reference's changeset compaction.

In the reference, clock-table triggers record which (actor, version) pairs
lost rows to a new transaction (``__corro_versions_impacted``,
``corro-types/src/agent.rs:265-279,554-596``); after commit,
``find_overwritten_versions`` drains that table and ``store_empty_changeset``
replaces fully-superseded versions with *cleared ranges*
(``agent.rs:1662-1721``, ``change.rs:267-389``). Cleared versions carry no
data: anti-entropy serves them as ``SyncNeedV1::Empty`` → ``EmptySet``
messages, and peers fast-forward their bookkeeping without any row transfer
(``api/peer.rs:716-758``, ``handlers.rs:524-719``).

TPU model. The authoritative write history is the global change log, so
supersession is global too:

- ``CellOwnership`` tracks, per table cell, the currently-winning change's
  ``(col_version, value_rank, site)`` triple and the (actor, version) that
  produced it — the dense analog of the ``<tbl>__crsql_clock`` tables
  (``doc/crdts.md:9-40``). Per-row planes do the same for the causal
  length (delete-tombstone ownership).
- The change log keeps ``live[A, L]`` — how many of a version's cells are
  still a winner — and ``cleared[A, L]``. When a round's writes steal a
  cell from its previous owner (or a generation change wipes a whole row),
  the owner's ``live`` decrements; at zero the version is cleared.

Cleared versions still occupy their slot in version order (bookkeeping
heads must pass through them) but deliver no cells: both the gossip-apply
and the sync-transfer paths mask cell application with ``cleared`` — the
moral equivalent of a sync peer answering "that range is empty now".

Semantics mirror :func:`corro_sim.core.crdt.apply_cell_changes` exactly
(causal-generation merge): row cl merges first; a generation bump wipes the
row's value cells and their ownership; value lanes contest only at the
row's current generation.
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp

from corro_sim.core.changelog import ChangeLog
from corro_sim.core.crdt import NEG
from corro_sim.utils.slots import dedupe_sorted_mask


@flax.struct.dataclass
class CellOwnership:
    # per-cell winning change (R, C)
    cv: jnp.ndarray  # int32 col_version
    vr: jnp.ndarray  # int32 value rank
    site: jnp.ndarray  # int32 writer site
    actor: jnp.ndarray  # int32 owning actor, -1 = none
    ver: jnp.ndarray  # int32 owning version, 0 = none
    # per-row causal-length state (R,)
    rcl: jnp.ndarray  # int32 causal length (global max)
    ractor: jnp.ndarray  # int32 tombstone-owning DELETE actor, -1 = none
    rver: jnp.ndarray  # int32 tombstone-owning DELETE version, 0 = none
    rsite: jnp.ndarray  # int32 tombstone tie-break site


def make_ownership(num_rows: int, num_cols: int) -> CellOwnership:
    shape = (num_rows, num_cols)
    return CellOwnership(
        cv=jnp.zeros(shape, jnp.int32),
        vr=jnp.full(shape, NEG, jnp.int32),
        site=jnp.full(shape, -1, jnp.int32),
        actor=jnp.full(shape, -1, jnp.int32),
        ver=jnp.zeros(shape, jnp.int32),
        rcl=jnp.zeros((num_rows,), jnp.int32),
        ractor=jnp.full((num_rows,), -1, jnp.int32),
        rver=jnp.zeros((num_rows,), jnp.int32),
        rsite=jnp.full((num_rows,), -1, jnp.int32),
    )


def _decrement_live(log: ChangeLog, actor, ver, valid):
    """live[actor, ver] -= 1 where valid; set cleared at zero.

    Guards the log ring: a version older than capacity has been overwritten
    by the ring wrap and must not be touched.
    """
    in_ring = valid & (
        ver > log.head[jnp.where(valid, actor, 0)] - log.capacity
    )
    aidx = jnp.where(in_ring, actor, log.head.shape[0])
    slot = (jnp.maximum(ver, 1) - 1) % log.capacity
    live = log.live.at[aidx, slot].add(jnp.where(in_ring, -1, 0), mode="drop")
    cleared = log.cleared | ((live <= 0) & (log.ncells > 0))
    return log.replace(live=live, cleared=cleared)


def _first_per_key(key: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Mask of the first valid lane per key value (in caller order)."""
    k = jnp.where(valid, key, jnp.int32(2**30))
    order = jnp.argsort(k, stable=True)
    inv = jnp.zeros(order.shape, jnp.int32).at[order].set(
        jnp.arange(order.shape[0], dtype=jnp.int32)
    )
    return (dedupe_sorted_mask(k[order]) & valid[order])[inv]


def update_ownership(
    own: CellOwnership,
    log: ChangeLog,
    actor: jnp.ndarray,  # (M,) int32 — writing actor per cell lane
    ver: jnp.ndarray,  # (M,) int32 — version per cell lane
    row: jnp.ndarray,  # (M,) int32
    col: jnp.ndarray,  # (M,) int32
    cv: jnp.ndarray,  # (M,) int32
    vr: jnp.ndarray,  # (M,) int32 (NEG for cl-only DELETE lanes)
    site: jnp.ndarray,  # (M,) int32 (NEG for cl-only lanes)
    cl: jnp.ndarray,  # (M,) int32
    valid: jnp.ndarray,  # (M,) bool — live cell lanes
    is_delete: jnp.ndarray,  # (M,) bool — lane belongs to a DELETE changeset
):
    """Fold one round of freshly-written cells into global ownership.

    Every losing side of each contested cell — the previous owner, any
    same-round lane beaten at scatter time, and every value cell of a row
    that changed generation — has its version's ``live`` count
    decremented; versions at zero live cells become ``cleared``.

    Lanes must be unique per (row, col) among value lanes and unique per
    row among DELETE lanes (one changeset writes a cell at most once — the
    same invariant SQLite's per-tx coalescing gives the reference).
    """
    num_rows, num_cols = own.cv.shape
    rowm = jnp.where(valid, row, num_rows)  # OOB-positive: -1 wraps

    # --- 1) row causal length: merge from every lane ----------------------
    rcl0 = own.rcl
    rcl1 = rcl0.at[rowm].max(jnp.where(valid, cl, NEG), mode="drop")
    bumped = rcl1 > rcl0  # (R,) generation changed

    # --- 2) generation wipe: bumped rows lose cells + their owners --------
    wipe = bumped[:, None] & (own.actor >= 0)  # (R, C)
    log = _decrement_live(
        log, own.actor.reshape(-1), own.ver.reshape(-1), wipe.reshape(-1)
    )
    bump2 = bumped[:, None]
    cv0 = jnp.where(bump2, 0, own.cv)
    vr0 = jnp.where(bump2, NEG, own.vr)
    site0 = jnp.where(bump2, -1, own.site)
    oactor = jnp.where(bump2, -1, own.actor)
    over = jnp.where(bump2, 0, own.ver)

    # --- 3) tombstone ownership ------------------------------------------
    # Old tombstone superseded by any generation bump (resurrect or newer
    # delete). At an unchanged even generation, a concurrent delete with a
    # higher site outbids the owner (deterministic tie-break).
    old_tomb_lost = bumped & (own.ractor >= 0)
    log = _decrement_live(log, own.ractor, own.rver, old_tomb_lost)
    ractor0 = jnp.where(bumped, -1, own.ractor)
    rver0 = jnp.where(bumped, 0, own.rver)
    rsite0 = jnp.where(bumped, -1, own.rsite)

    del_lane = valid & is_delete & (cl == rcl1[jnp.where(valid, row, 0)])
    drow = jnp.where(del_lane, row, num_rows)
    rsite1 = rsite0.at[drow].max(jnp.where(del_lane, site_of(actor), NEG),
                                 mode="drop")
    dwin = del_lane & (site_of(actor) == rsite1[jnp.where(del_lane, row, 0)])
    # Only winning lanes may scatter ownership — a losing lane must drop,
    # not write a sentinel (two lanes on one row race the scatter winner).
    dwrow = jnp.where(dwin, row, num_rows)
    tomb_changed = rsite1 != rsite0
    ractor1 = ractor0.at[dwrow].set(actor, mode="drop")
    rver1 = rver0.at[dwrow].set(ver, mode="drop")
    # outbid previous same-generation tombstone owner
    drow_g = jnp.where(del_lane, row, 0)  # clamped gather index
    outbid = (
        _first_per_key(drow, del_lane)
        & ~bumped[drow_g]
        & (ractor0[drow_g] >= 0)
        & tomb_changed[drow_g]
    )
    log = _decrement_live(log, ractor0[drow_g], rver0[drow_g], outbid)
    # delete lanes beaten at scatter time (stale generation or lower site)
    dself_lost = valid & is_delete & ~dwin
    log = _decrement_live(log, actor, ver, dself_lost)

    # --- 4) value cells: contest at the current generation ----------------
    val = valid & (vr != NEG) & (cl == rcl1[jnp.where(valid, row, 0)])
    r_idx = jnp.where(val, row, num_rows)
    idx = (r_idx, col)
    gidx = (jnp.where(val, row, 0), col)  # clamped gather twin of idx
    cv1 = cv0.at[idx].max(jnp.where(val, cv, NEG), mode="drop")
    vr_base = jnp.where(cv1 > cv0, NEG, vr0)
    w1 = val & (cv == cv1[idx])
    vr1 = vr_base.at[idx].max(jnp.where(w1, vr, NEG), mode="drop")
    site_base = jnp.where((cv1 != cv0) | (vr1 != vr0), NEG, site0)
    w2 = w1 & (vr == vr1[idx])
    site1 = site_base.at[idx].max(jnp.where(w2, site, NEG), mode="drop")
    winner = w2 & (site == site1[idx])

    changed = (cv1 != cv0) | (vr1 != vr0) | (site1 != site0)
    # Only winning lanes scatter ownership (losers drop — see tombstone).
    widx = (jnp.where(winner, row, num_rows), col)
    actor1 = oactor.at[widx].set(actor, mode="drop")
    ver1 = over.at[widx].set(ver, mode="drop")

    # previous owner superseded → one decrement per unique contested cell
    cell_key = jnp.where(val, row * num_cols + col, jnp.int32(2**30))
    first_cell = _first_per_key(cell_key, val)
    old_lost = first_cell & (oactor[idx] >= 0) & changed[idx]
    log = _decrement_live(log, oactor[idx], over[idx], old_lost)
    # same-round losers and stale-generation value lanes die at birth
    self_lost = valid & (vr != NEG) & ~winner
    log = _decrement_live(log, actor, ver, self_lost)

    own = CellOwnership(
        cv=cv1,
        vr=vr1,
        site=site1,
        actor=actor1,
        ver=ver1,
        rcl=rcl1,
        ractor=ractor1,
        rver=rver1,
        rsite=rsite1,
    )
    return own, log


def site_of(actor: jnp.ndarray) -> jnp.ndarray:
    """Site ordinal of an actor — identical in the simulator (ActorId is
    the crsql site id, ``corro-types/src/actor.rs:26``)."""
    return actor
