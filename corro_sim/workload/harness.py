"""The live load harness: a compiled workload driven through the agent.

Where ``run_sim(workload=...)`` measures the *dissemination* half of
production load (convergence while writes storm), this harness measures
the *serving* half: the same schedule mapped to SQL against a
:class:`~corro_sim.harness.cluster.LiveCluster` — the write path the HTTP
API serializes — while hundreds of concurrent subscriptions watch through
:mod:`corro_sim.subs.manager` and one-shot queries fan through the
public surfaces (direct / HTTP / pgwire). The question every round
answers: **how late do subscribers learn about a committed change while
the cluster is busy?**

Latency clock (``corro_sub_latency_rounds``/``_seconds``): a write
accepted at round *t* commits in tick *t+1* (the one-changeset-per-node-
per-round drain); the subscriber-side matcher emits the corresponding
``SubEvent`` at some round *T* (stamped on the event by the notify
path). Delivery latency = *T − (t+1)* rounds — 0 when the observer is
the writer's own node, gossip/sync propagation otherwise. Writes whose
value never surfaces (overwritten before the matcher diff saw them)
count as *coalesced*, exactly the batching the reference's candidate
accumulation does (``pubsub.rs:1154-1296``).

Schema: the canonical service-discovery table (corrosion's actual job) —
``services(id, node, val)``; workload key ids are pk ordinals, every
committed write carries a process-unique ``val`` so events correlate
back to their write without guessing.
"""

from __future__ import annotations

import dataclasses
import time

from corro_sim.utils.metrics import (
    ROUNDS_BUCKETS,
    SUB_LATENCY_ROUNDS,
    SUB_LATENCY_ROUNDS_HELP,
    SUB_LATENCY_SECONDS,
    SUB_LATENCY_SECONDS_HELP,
    WORKLOAD_COALESCED_TOTAL,
    WORKLOAD_QUERIES_TOTAL,
    WORKLOAD_ROUNDS_TOTAL,
    WORKLOAD_WRITES_TOTAL,
    counters,
)

SERVICES_SCHEMA = """
CREATE TABLE services (
    id INTEGER NOT NULL PRIMARY KEY,
    node INTEGER NOT NULL DEFAULT 0,
    val INTEGER NOT NULL DEFAULT 0
);
"""

# the query-fan rotation: the shapes a service-discovery consumer runs
# (full scans, health filters, per-node views, pk ranges)
_SUB_QUERIES = (
    "SELECT id, val FROM services",
    "SELECT id, val FROM services WHERE val >= 0",
    "SELECT id, node, val FROM services WHERE node = {node}",
    "SELECT id, val FROM services WHERE id >= {lo} AND id < {hi}",
)


@dataclasses.dataclass
class LoadReport:
    """One live-load run's result (the ``workload-report`` artifact body
    and the GET /v1/workload payload)."""

    spec: str
    nodes: int
    rounds: int  # load-phase rounds driven
    settle_rounds: int  # extra rounds until drained (or budget)
    matchers: int  # distinct registered matchers
    subscriptions: int  # live subscriber streams (≥ matchers)
    writes: int
    deletes: int
    observed: int  # (write, subscriber) deliveries measured
    coalesced: int  # writes a subscriber never saw individually
    queries: dict  # surface -> one-shot queries issued
    latency_rounds: dict  # {p50, p90, p99, max, count}
    latency_seconds: dict  # {p50, p99, max, count}
    drained: bool  # cluster reached gap 0 inside the settle budget
    wall_seconds: float

    def as_json(self) -> dict:
        return dataclasses.asdict(self)


class _SubProbe:
    """One latency-tracked subscriber stream: its queue, its val→commit
    bookkeeping, and the position of ``val`` in the query's row shape."""

    def __init__(self, sub_id: str, node: int, queue, columns: list):
        self.sub_id = sub_id
        self.node = node
        self.queue = queue
        # event cells and the initial columns header share one layout
        # (pk prefix + selected value columns), so the header position
        # of `val` indexes the cells directly
        self.val_pos = columns.index("val") if "val" in columns else None
        self.pending: dict[int, list] = {}  # key -> [(val, commit_round,
        # wall)] oldest-first

    def expect(self, key: int, val: int, commit_round: int,
               wall: float) -> None:
        self.pending.setdefault(key, []).append((val, commit_round, wall))

    def drop_key(self, key: int) -> int:
        """A DELETE landed: everything still pending on the key will
        never surface as a value — count it coalesced."""
        return len(self.pending.pop(key, ()))


def _quantiles(hist) -> dict:
    if hist is None or not hist.count:
        return {"count": 0, "p50": None, "p90": None, "p99": None,
                "max": None}
    return {
        "count": hist.count,
        "p50": hist.quantile(0.50),
        "p90": hist.quantile(0.90),
        "p99": hist.quantile(0.99),
        "max": round(hist.max, 6),
    }


def run_live_load(
    workload,
    *,
    cluster=None,
    subs: int = 8,
    subscribers_per_sub: int = 1,
    latency_subs: int = 32,
    queries_per_round: int = 0,
    http: bool = False,
    pg: bool = False,
    seed: int = 0,
    settle_rounds: int = 256,
    cfg_overrides: dict | None = None,
    default_capacity: int | None = None,
) -> LoadReport:
    """Drive ``workload`` through a live cluster end to end.

    ``subs`` distinct matchers spread over observer nodes (each opened
    ``subscribers_per_sub`` times — live subscriber streams dedupe onto
    one matcher exactly like the reference's ``get_or_insert``);
    the first ``latency_subs`` streams are latency-tracked (bounding the
    val→commit bookkeeping at fleet scale). ``queries_per_round``
    one-shot queries fan through the enabled surfaces round-robin
    (direct always; ``http``/``pg`` spin real servers on loopback).

    Returns a :class:`LoadReport`; also installed as
    ``cluster.workload_report`` (GET /v1/workload) and observed into the
    cluster's ``corro_sub_latency_*`` histograms + the process-wide
    ``corro_workload_*`` counters.
    """
    from corro_sim.harness.cluster import LiveCluster

    t_start = time.perf_counter()
    own_cluster = cluster is None
    if own_cluster:
        cap = default_capacity or max(16, workload.key_universe())
        cluster = LiveCluster(
            SERVICES_SCHEMA, num_nodes=workload.n, seed=seed,
            default_capacity=cap, cfg_overrides=cfg_overrides,
        )
        # compile the tick programs before traffic arrives — otherwise
        # round-0 writes carry XLA compile wall in their seconds latency
        cluster.warmup()
    n = cluster.cfg.num_nodes
    assert workload.n == n, (
        f"workload compiled for {workload.n} nodes, cluster has {n}"
    )

    # ---- subscription fan ------------------------------------------------
    probes: list[_SubProbe] = []
    streams = 0
    matcher_ids: set = set()
    kspan = max(workload.key_universe(), 1)
    for j in range(subs):
        node = j % n
        tmpl = _SUB_QUERIES[j % len(_SUB_QUERIES)]
        lo = (j * 7) % kspan
        sql = tmpl.format(node=node, lo=lo, hi=lo + max(kspan // 2, 1))
        sub_id, initial, q = cluster.subscribe_attached(sql, node=node)
        matcher_ids.add(sub_id)
        streams += 1
        cols = next(
            (e["columns"] for e in initial if "columns" in e), []
        )
        # only full-coverage queries are latency-tracked: a filtered sub
        # (per-node view, pk range) legitimately never sees most writes,
        # which would read as phantom coalescing
        track = j % len(_SUB_QUERIES) < 2
        if track and len(probes) < latency_subs:
            probes.append(_SubProbe(sub_id, node, q, cols))
        for _ in range(subscribers_per_sub - 1):
            q2 = cluster.sub_attach_queue(sub_id)
            streams += 1
            if track and q2 is not None and len(probes) < latency_subs:
                probes.append(_SubProbe(sub_id, node, q2, cols))

    # ---- query-fan surfaces ---------------------------------------------
    api_srv = pg_srv = api_client = pg_client = None
    surfaces = ["direct"]
    if http:
        from corro_sim.api.http import ApiServer
        from corro_sim.client import ApiClient

        api_srv = ApiServer(cluster).start()
        api_client = ApiClient(api_srv.addr)
        surfaces.append("http")
    if pg:
        from corro_sim.api.pg import PgServer, SimplePgClient

        pg_srv = PgServer(cluster).start()
        pg_client = SimplePgClient(*pg_srv.addr)
        surfaces.append("pg")
    queries = {s: 0 for s in surfaces}
    qi = 0

    key_of = cluster.layout.key_of  # slot -> (table, (pk,)) | None
    hist = cluster.histograms
    next_val = 1
    writes = deletes = observed = coalesced = 0
    lat_rounds: list = []
    lat_secs: list = []

    def drain() -> None:
        nonlocal observed, coalesced
        now = time.perf_counter()
        for p in probes:
            while p.queue:
                ev = p.queue.popleft()
                key_t = key_of(ev.rowid)
                key = int(key_t[1][0]) if key_t else ev.rowid
                if ev.kind == "delete":
                    coalesced += p.drop_key(key)
                    continue
                if p.val_pos is None:
                    continue
                cells = ev.cells
                val = (
                    cells[p.val_pos] if len(cells) > p.val_pos else None
                )
                waiting = p.pending.get(key)
                if not waiting or val is None:
                    continue
                hit = next(
                    (i for i, (v, _, _) in enumerate(waiting)
                     if v == val), None,
                )
                if hit is None:
                    continue
                # older writes to the key were coalesced into this one
                coalesced += hit
                v, commit_round, wall0 = waiting[hit]
                del waiting[: hit + 1]
                if not waiting:
                    p.pending.pop(key, None)
                emit_round = (
                    ev.round if ev.round is not None
                    else cluster._rounds_ticked
                )
                lat_rounds.append(float(max(emit_round - commit_round, 0)))
                lat_secs.append(max(now - wall0, 0.0))
                observed += 1

    def fan_queries() -> None:
        nonlocal qi
        for _ in range(queries_per_round):
            surface = surfaces[qi % len(surfaces)]
            node = qi % n
            sql = "SELECT id, val FROM services WHERE val >= 0"
            qi += 1
            if surface == "direct":
                cluster.query_rows(sql, node=node)
            elif surface == "http":
                api_client.query_rows(sql, node=node)
            else:
                pg_client.query(sql)
            queries[surface] += 1

    # ---- the load loop ---------------------------------------------------
    try:
        for r in range(workload.rounds):
            t0 = cluster._rounds_ticked
            commit_round = t0 + 1
            wall0 = time.perf_counter()
            for i in range(n):
                if not workload.writers[r, i]:
                    continue
                key = int(workload.rows[r, i])
                if workload.dels[r, i]:
                    cluster.execute(
                        [f"DELETE FROM services WHERE id = {key}"],
                        node=i, wait=False,
                    )
                    deletes += 1
                    writes += 1
                    continue
                val = next_val
                next_val += 1
                cluster.execute(
                    [
                        f"INSERT INTO services (id, node, val) "
                        f"VALUES ({key}, {i}, {val})"
                    ],
                    node=i, wait=False,
                )
                writes += 1
                for p in probes:
                    p.expect(key, val, commit_round, wall0)
            cluster.tick(1)
            drain()
            fan_queries()
        # ---- settle: drain the cluster, keep harvesting deliveries ------
        settled = 0
        drained = False
        while settled < settle_rounds:
            cluster.tick(1)
            settled += 1
            drain()
            if cluster.converged:
                drained = True
                break
    finally:
        for c in (api_client, pg_client):
            if c is not None:
                try:
                    c.close()
                except Exception:
                    pass
        for srv in (api_srv, pg_srv):
            if srv is not None:
                srv.close()

    # everything still pending after the settle phase was coalesced away
    for p in probes:
        coalesced += sum(len(v) for v in p.pending.values())
        p.pending.clear()

    # ---- metrics + report ------------------------------------------------
    hist.observe_many(SUB_LATENCY_ROUNDS, lat_rounds,
                      help_=SUB_LATENCY_ROUNDS_HELP,
                      buckets=ROUNDS_BUCKETS)
    hist.observe_many(SUB_LATENCY_SECONDS, lat_secs,
                      help_=SUB_LATENCY_SECONDS_HELP)
    counters.inc(WORKLOAD_WRITES_TOTAL, n=writes - deletes,
                 labels='{kind="write"}',
                 help_="workload schedule ops committed through the live "
                       "write path, by kind")
    counters.inc(WORKLOAD_WRITES_TOTAL, n=deletes,
                 labels='{kind="delete"}',
                 help_="workload schedule ops committed through the live "
                       "write path, by kind")
    counters.inc(WORKLOAD_ROUNDS_TOTAL, n=workload.rounds,
                 help_="load-phase rounds driven by the live harness")
    counters.inc(WORKLOAD_COALESCED_TOTAL, n=coalesced,
                 help_="writes a subscriber never saw individually "
                       "(matcher-diff coalescing)")
    for s, cnt in queries.items():
        counters.inc(WORKLOAD_QUERIES_TOTAL, n=cnt,
                     labels=f'{{surface="{s}"}}',
                     help_="one-shot queries fanned by the load harness, "
                           "by surface")
    rounds_h = hist.get(SUB_LATENCY_ROUNDS)
    secs_h = hist.get(SUB_LATENCY_SECONDS)
    report = LoadReport(
        spec=workload.spec,
        nodes=n,
        rounds=workload.rounds,
        settle_rounds=settled,
        matchers=len(matcher_ids),
        subscriptions=streams,
        writes=writes,
        deletes=deletes,
        observed=observed,
        coalesced=coalesced,
        queries=queries,
        latency_rounds=_quantiles(rounds_h),
        latency_seconds=_quantiles(secs_h),
        drained=drained,
        wall_seconds=round(time.perf_counter() - t_start, 3),
    )
    cluster.workload_report = {"live": report.as_json()}
    return report
