"""Production-shaped traffic generators: the synthetic-workload engine.

PAPER.md's north star is a simulator serving "heavy traffic from millions
of users"; the convergence bench only ever measured a uniform Bernoulli
write phase. This module synthesizes the traffic shapes a production
corrosion cluster actually sees — Zipf-skewed key popularity, bursty
MMPP/on-off write arrival, multi-writer contention on hot keys, and
service-discovery churn storms (register/deregister waves, corrosion's
actual job at Fly.io) — and compiles each into a **precomputed per-round
write schedule**, the same pattern :mod:`corro_sim.faults.scenarios` uses
for fault schedules: the same ``(name, params, n, rounds, seed)`` always
produces the same arrays, chunk boundaries never change what a round
carries, and the hot step program stays untouched when no workload is
armed (the write schedule rides the scan inputs only when one is — the
jaxpr golden pins the workload-off program byte for byte).

A compiled :class:`Workload` drives BOTH execution paths:

- the batched dissemination path — ``run_sim(..., workload=w)`` threads
  the schedule through ``sim_step``'s explicit ``writes=`` port (the same
  port the live agent and :mod:`corro_sim.engine.replay` feed, so
  synthetic load, replayed traces and API traffic share one code path);
- the live path — :mod:`corro_sim.workload.harness` maps the same
  schedule to SQL statements against a :class:`LiveCluster`, with
  hundreds of concurrent subscriptions and query fans measuring
  subscription delivery latency under load.

Spec strings reuse the shared ``name[:k=v,...]`` grammar
(:mod:`corro_sim.utils.spec`); ``+`` composes generators::

    zipf:alpha=1.1,rate=0.4
    burst:on=8,off=24,rate_hi=0.9
    churn_storm:waves=4,batch=8
    zipf:alpha=1.1+churn_storm:waves=2

Composition merges schedules lane-wise: the SPARSER part wins a
contended ``(round, node)`` write slot (a churn wave's semantic
register/deregister ops must survive under a bulk Zipf background),
denser parts fill the lanes left idle — one changeset per node per
round is the write discipline the whole pipeline serializes on
(agent.rs:500-731).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from corro_sim.utils.spec import format_spec, parse_spec

# PRNG domain declaration for the key-lineage auditor (analysis/keys.py,
# doc/static_analysis.md §4): workload schedule GENERATION draws from a
# host-side numpy Generator only — it owns zero jax key streams, so the
# auditor expects no workload-tagged fold_in in any program. On device
# the schedule rides the step's explicit ``writes=`` port and consumes
# the step's OWN write-side lanes (STEP_KEY_STREAMS[0..5]); a generator
# that starts drawing from a jax key must claim a declared tag here and
# re-baseline key_lineage.json, or `audit --keys` fails K2.
WORKLOAD_HOST_RNG = "numpy:PCG64"

__all__ = [
    "WORKLOADS",
    "Workload",
    "empty_slice",
    "make_workload",
    "parse_workload_spec",
]


@dataclasses.dataclass
class Workload:
    """A compiled traffic schedule: per-round write arrays + event markers.

    ``writers[r, i]`` — node ``i`` commits a changeset in round ``r``;
    ``rows[r, i]`` — the key (row slot / pk ordinal) it writes;
    ``cols``/``vals[r, i, c]`` — the written cells (``ncells`` live);
    ``dels[r, i]`` — the changeset is a causal-length DELETE (deregister).

    Events are sparse ``(round, kind, attrs)`` markers (burst onsets,
    churn waves) — the drivers annotate them into the flight recorder.
    """

    name: str
    params: dict
    rounds: int  # rounds carrying scheduled writes (the load phase)
    n: int
    writers: np.ndarray  # (R, N) bool
    rows: np.ndarray  # (R, N) int32 key ids
    cols: np.ndarray  # (R, N, S) int32 column planes
    vals: np.ndarray  # (R, N, S) int32 cell values (identity universe)
    dels: np.ndarray  # (R, N) bool
    ncells: np.ndarray  # (R, N) int32
    events: list = dataclasses.field(default_factory=list)

    def __post_init__(self):
        self.events.sort(key=lambda ev: ev[0])

    @property
    def spec(self) -> str:
        return format_spec(self.name, self.params)

    @property
    def cells_width(self) -> int:
        return self.cols.shape[2]

    @property
    def total_writes(self) -> int:
        return int(self.writers.sum())

    @property
    def total_deletes(self) -> int:
        return int((self.writers & self.dels).sum())

    def key_universe(self) -> int:
        """Distinct key ids the schedule can touch (row-slot capacity the
        consuming config/layout must provide)."""
        if not self.writers.any():
            return 1
        return int(self.rows[self.writers].max()) + 1

    def validate(self, cfg) -> "Workload":
        """Shape/bounds check against a :class:`SimConfig` consumer."""
        r, n = self.writers.shape
        assert n == cfg.num_nodes, (
            f"workload compiled for {n} nodes, config has {cfg.num_nodes}"
        )
        assert self.key_universe() <= cfg.num_rows, (
            f"workload touches key {self.key_universe() - 1} but "
            f"cfg.num_rows={cfg.num_rows}"
        )
        assert self.cells_width <= cfg.seqs_per_version, (
            f"workload writes {self.cells_width} cells per changeset; "
            f"cfg.seqs_per_version={cfg.seqs_per_version} is too small"
        )
        if self.writers.any():
            live_cols = self.cols[self.writers]
            assert int(live_cols.max()) < cfg.num_cols, (
                f"workload writes column {int(live_cols.max())} but "
                f"cfg.num_cols={cfg.num_cols}"
            )
        return self

    def writes_at(self, r: int, s: int):
        """One round's ``sim_step`` writes tuple — zero writers past the
        schedule's end (load ends; it never holds-last like fault rows,
        which would repeat the final round's writes forever)."""
        n = self.n
        if r >= self.rounds:
            return (
                np.zeros((n,), bool), np.zeros((n, s), np.int32),
                np.zeros((n, s), np.int32), np.zeros((n, s), np.int32),
                np.zeros((n,), bool), np.zeros((n,), np.int32),
            )
        pad = s - self.cells_width
        cols = np.pad(self.cols[r], ((0, 0), (0, pad)))
        vals = np.pad(self.vals[r], ((0, 0), (0, pad)))
        rows = np.broadcast_to(self.rows[r][:, None], (n, s))
        return (
            self.writers[r].copy(), np.ascontiguousarray(rows, np.int32),
            cols.astype(np.int32), vals.astype(np.int32),
            (self.writers[r] & self.dels[r]).copy(),
            self.ncells[r].astype(np.int32),
        )

    def slice(self, start: int, length: int, s: int):
        """Round-major ``(length, ...)`` write arrays for one scan chunk —
        the workload analog of :meth:`engine.driver.Schedule.slice`."""
        out = empty_slice(self.n, length, s)
        lo, hi = start, min(start + length, self.rounds)
        if lo < hi:
            k = hi - lo
            w = self.writers[lo:hi]
            out[0][:k] = w
            out[1][:k] = self.rows[lo:hi][:, :, None]  # broadcast over S
            out[2][:k, :, : self.cells_width] = self.cols[lo:hi]
            out[3][:k, :, : self.cells_width] = self.vals[lo:hi]
            out[4][:k] = w & self.dels[lo:hi]
            out[5][:k] = self.ncells[lo:hi]
        return out

    def writes_in(self, start: int, length: int) -> bool:
        """Whether rounds ``[start, start+length)`` schedule any write —
        the driver's repair-program veto."""
        lo, hi = start, min(start + length, self.rounds)
        return lo < hi and bool(self.writers[lo:hi].any())

    def events_in(self, start: int, length: int) -> list:
        return [
            ev for ev in self.events if start <= ev[0] < start + length
        ]


def empty_slice(n: int, length: int, s: int) -> tuple:
    """All-idle round-major write arrays in the exact ``slice`` shape —
    what a sweep lane with no coupled workload stages (the write source
    its per-lane ``use_workload`` knob then ignores; corro_sim/sweep/)."""
    return (
        np.zeros((length, n), bool),
        np.zeros((length, n, s), np.int32),
        np.zeros((length, n, s), np.int32),
        np.zeros((length, n, s), np.int32),
        np.zeros((length, n), bool),
        np.zeros((length, n), np.int32),
    )


def _alloc(rounds: int, n: int, s: int):
    return dict(
        writers=np.zeros((rounds, n), bool),
        rows=np.zeros((rounds, n), np.int32),
        cols=np.zeros((rounds, n, s), np.int32),
        vals=np.zeros((rounds, n, s), np.int32),
        dels=np.zeros((rounds, n), bool),
        ncells=np.ones((rounds, n), np.int32),
    )


def _zipf_cdf(keys: int, alpha: float) -> np.ndarray:
    """Cumulative Zipf(alpha) key-popularity distribution over ``keys``
    ranks — the engine/state.py ``_row_cdf`` law, host-side."""
    if alpha <= 0.0:
        w = np.ones(keys, np.float64)
    else:
        w = 1.0 / np.power(np.arange(1, keys + 1, dtype=np.float64), alpha)
    cdf = np.cumsum(w / w.sum())
    cdf[-1] = 1.0
    return cdf


def _sample_keys(rng, cdf: np.ndarray, shape) -> np.ndarray:
    return np.searchsorted(cdf, rng.random(shape)).astype(np.int32).clip(
        0, len(cdf) - 1
    )


def _fill_writes(a: dict, rng, mask: np.ndarray, cdf: np.ndarray,
                 values: int, delete_rate: float = 0.0) -> None:
    """Populate schedule lanes under ``mask`` with Zipf-sampled keys and
    uniform cell values (single-cell changesets, column 0)."""
    a["writers"] |= mask
    a["rows"][mask] = _sample_keys(rng, cdf, int(mask.sum()))
    a["vals"][mask, 0] = rng.integers(0, values, int(mask.sum()))
    if delete_rate > 0.0:
        a["dels"][mask] = rng.random(int(mask.sum())) < delete_rate


def zipf(n, rounds, seed, alpha: float = 1.1, rate: float = 0.5,
         keys: int = 0, values: int = 1 << 20, delete_rate: float = 0.0):
    """Zipf-skewed key popularity at a steady Bernoulli arrival rate —
    the read/write shape of real KV traffic (a few hot keys absorb most
    writes; the long tail trickles)."""
    keys = int(keys) or max(16, n // 4)
    rng = np.random.default_rng(int(seed) ^ 0x21BF)
    a = _alloc(rounds, n, 1)
    cdf = _zipf_cdf(keys, float(alpha))
    mask = rng.random((rounds, n)) < float(rate)
    _fill_writes(a, rng, mask, cdf, int(values), float(delete_rate))
    # params record EVERY schedule-shaping knob: the canonical spec must
    # reproduce this exact schedule when fed back with the same seed
    return Workload(
        name="zipf",
        params={"alpha": alpha, "rate": rate, "keys": keys,
                "values": values, "delete_rate": delete_rate},
        rounds=rounds, n=n, events=[], **a,
    )


def uniform(n, rounds, seed, rate: float = 0.5, keys: int = 0,
            values: int = 1 << 20):
    """Uniform keys at a steady rate — the legacy bench write phase as an
    explicit schedule (the baseline every skewed shape compares to)."""
    w = zipf(n, rounds, seed, alpha=0.0, rate=rate, keys=keys,
             values=values)
    return dataclasses.replace(
        w, name="uniform",
        params={"rate": rate, "keys": w.params["keys"], "values": values},
    )


def burst(n, rounds, seed, on: int = 4, off: int = 12,
          rate_hi: float = 0.9, rate_lo: float = 0.05,
          alpha: float = 0.0, keys: int = 0, values: int = 1 << 20):
    """Bursty MMPP/on-off arrival: the cluster idles at ``rate_lo`` then
    slams to ``rate_hi`` for ``on``-round bursts on a seeded on/off
    Markov alternation (mean sojourns ``on``/``off`` rounds) — deploy
    fanouts, thundering herds, cron storms. Burst onsets are events."""
    keys = int(keys) or max(16, n // 4)
    rng = np.random.default_rng(int(seed) ^ 0x8057)
    a = _alloc(rounds, n, 1)
    cdf = _zipf_cdf(keys, float(alpha))
    on_p = 1.0 / max(float(off), 1.0)  # P(off -> on) per round
    off_p = 1.0 / max(float(on), 1.0)  # P(on -> off) per round
    state_on = False
    events = []
    rate_rounds = np.empty(rounds, np.float64)
    for r in range(rounds):
        if state_on and rng.random() < off_p:
            state_on = False
            events.append((r, "burst_off", {}))
        elif not state_on and rng.random() < on_p:
            state_on = True
            events.append((r, "burst_on", {"phase": "burst"}))
        rate_rounds[r] = float(rate_hi) if state_on else float(rate_lo)
    mask = rng.random((rounds, n)) < rate_rounds[:, None]
    _fill_writes(a, rng, mask, cdf, int(values))
    return Workload(
        name="burst",
        params={"on": on, "off": off, "rate_hi": rate_hi,
                "rate_lo": rate_lo, "alpha": alpha, "keys": keys,
                "values": values},
        rounds=rounds, n=n, events=events, **a,
    )


def multiwriter(n, rounds, seed, hot: int = 4, rate: float = 0.7,
                writers: int = 0, values: int = 1 << 20):
    """Multi-writer contention: ``writers`` nodes (default: all) hammer
    the same ``hot`` keys — every write races another replica's write to
    the identical cell, the pure CRDT-conflict regime (equal-col_version
    biggest-value-wins resolution runs constantly)."""
    hot = max(1, int(hot))
    writers_n = int(writers) or n
    rng = np.random.default_rng(int(seed) ^ 0x3417)
    a = _alloc(rounds, n, 1)
    mask = np.zeros((rounds, n), bool)
    mask[:, :writers_n] = rng.random((rounds, writers_n)) < float(rate)
    a["writers"] |= mask
    a["rows"][mask] = rng.integers(0, hot, int(mask.sum()))
    a["vals"][mask, 0] = rng.integers(0, values, int(mask.sum()))
    return Workload(
        name="multiwriter",
        params={"hot": hot, "rate": rate, "writers": writers_n,
                "values": values},
        rounds=rounds, n=n, events=[], **a,
    )


def churn_storm(n, rounds, seed, waves: int = 4, batch: int = 0,
                keys: int = 0, gap: int = 0, values: int = 1 << 20):
    """Service-discovery churn storms — corrosion's actual job at Fly.io:
    every ``gap`` rounds a wave deregisters (causal-length DELETE) a
    batch of live service keys and registers a fresh batch, spread over
    the nodes. Between waves a background trickle re-touches live keys
    (health-check timestamp refresh)."""
    keys = int(keys) or max(16, n // 2)
    batch = int(batch) or max(1, keys // 8)
    waves = max(1, int(waves))
    gap = int(gap) or max(2, rounds // (waves + 1))
    rng = np.random.default_rng(int(seed) ^ 0xC402)
    a = _alloc(rounds, n, 1)
    events = []
    live = list(range(min(batch, keys)))  # seed registrations land wave 0
    next_key = len(live)
    for w in range(waves):
        r0 = (w + 1) * gap - gap // 2 if w == 0 else w * gap + gap // 2
        r0 = min(max(r0, 0), rounds - 1)
        # one wave = deregister `batch` live keys + register `batch` new
        # ones, each op one changeset on a rotating writer node; ops pack
        # into consecutive rounds at one-write-per-node-per-round
        ops = []
        dereg = [
            live.pop(int(rng.integers(0, len(live))))
            for _ in range(min(batch, max(len(live) - 1, 0)))
        ]
        ops += [(k, True) for k in dereg]
        for _ in range(batch):
            k = next_key % keys
            next_key += 1
            if k not in live:
                live.append(k)
            ops.append((k, False))
        ops = [ops[i] for i in rng.permutation(len(ops))]
        r, node = r0, int(rng.integers(0, n))
        placed = 0
        for k, is_del in ops:
            # next free (round, node) lane at/after the wave onset
            tries = 0
            while r < rounds and a["writers"][r, node]:
                node = (node + 1) % n
                tries += 1
                if tries >= n:
                    r, tries = r + 1, 0
            if r >= rounds:
                break
            a["writers"][r, node] = True
            a["rows"][r, node] = k
            a["dels"][r, node] = is_del
            a["vals"][r, node, 0] = int(rng.integers(0, values))
            placed += 1
            node = (node + 1) % n
        events.append(
            (r0, "churn_wave", {"wave": w, "ops": placed,
                                "phase": "storm"})
        )
    # background refresh trickle on live keys between waves
    trickle = rng.random((rounds, n)) < 0.02
    trickle &= ~a["writers"]
    if live:
        live_arr = np.asarray(sorted(live), np.int32)
        a["writers"] |= trickle
        a["rows"][trickle] = live_arr[
            rng.integers(0, len(live_arr), int(trickle.sum()))
        ]
        a["vals"][trickle, 0] = rng.integers(0, values, int(trickle.sum()))
    return Workload(
        name="churn_storm",
        params={"waves": waves, "batch": batch, "keys": keys, "gap": gap,
                "values": values},
        rounds=rounds, n=n, events=events, **a,
    )


def empty_workload(n: int, rounds: int = 8) -> Workload:
    """An all-idle schedule — the vacuity oracle's ON-side input (the
    write-schedule program fed zero writers must be bit-identical to the
    sampler program with writes disabled)."""
    return Workload(
        name="empty", params={}, rounds=rounds, n=n,
        **_alloc(rounds, n, 1),
    )


WORKLOADS = {
    "zipf": zipf,
    "uniform": uniform,
    "burst": burst,
    "multiwriter": multiwriter,
    "churn_storm": churn_storm,
}


def parse_workload_spec(spec: str) -> list[tuple[str, dict]]:
    """``name[:k=v,...][+name2[:...]]`` → ordered (name, params) parts,
    each validated against the workload table."""
    parts = []
    for piece in spec.split("+"):
        name, params = parse_spec(piece)
        if name not in WORKLOADS:
            raise ValueError(
                f"unknown workload {name!r} "
                f"(have: {', '.join(sorted(WORKLOADS))})"
            )
        parts.append((name, params))
    return parts


def _merge(parts: list[Workload]) -> Workload:
    """Lane-wise composition: sparse parts win contended (round, node)
    slots — a churn wave's register/deregister ops must survive under a
    bulk Zipf background, not be sampled away by it — and denser parts
    fill the lanes left idle (one changeset per node per round stays the
    invariant). Deterministic: fill order is ascending scheduled-write
    count, ties in spec order."""
    base = parts[0]
    s = max(p.cells_width for p in parts)
    rounds = max(p.rounds for p in parts)
    n = base.n
    a = _alloc(rounds, n, s)
    a["ncells"][:] = 1
    events: list = []
    fill_order = sorted(
        range(len(parts)), key=lambda i: (parts[i].total_writes, i)
    )
    for i in fill_order:
        p = parts[i]
        free = ~a["writers"][: p.rounds]
        take = p.writers & free
        a["writers"][: p.rounds] |= take
        a["rows"][: p.rounds][take] = p.rows[take]
        a["cols"][: p.rounds, :, : p.cells_width][take] = p.cols[take]
        a["vals"][: p.rounds, :, : p.cells_width][take] = p.vals[take]
        a["dels"][: p.rounds][take] = p.dels[take]
        a["ncells"][: p.rounds][take] = p.ncells[take]
        events.extend(p.events)
    return Workload(
        name="+".join(p.name for p in parts),
        params={}, rounds=rounds, n=n, events=events, **a,
    )


def make_workload(
    spec: str,
    n: int,
    rounds: int = 16,
    seed: int = 0,
) -> Workload:
    """Compile a (possibly composed) spec for an ``n``-node cluster's
    ``rounds``-round load phase."""
    compiled = [
        WORKLOADS[name](n, rounds, seed + i, **params)
        for i, (name, params) in enumerate(parse_workload_spec(spec))
    ]
    if len(compiled) == 1:
        return compiled[0]
    merged = _merge(compiled)
    # the composed spec round-trips as the join of the parts' canonical
    # specs (params live inside each part, not on the composite)
    merged.name = "+".join(p.spec for p in compiled)
    merged.params = {}
    return merged
