"""Shared changeset injection: one code path for replay and synthetic load.

Two producers feed committed changesets into the dissemination machinery
from outside the step's own write sampler:

- **trace replay** (:mod:`corro_sim.engine.replay`) — real-cluster
  changesets carrying authoritative ``cv``/``cl``/``vr`` stamps, injected
  between rounds via :func:`inject_round`;
- **the synthetic workload engine** (:mod:`corro_sim.workload`) — compiled
  write schedules threaded through ``sim_step``'s explicit ``writes=``
  port (the live agent's port), where the step's own ``local_write``
  derives the stamps from the writer's current causal state.

Both used to live apart (replay owned a private ``inject_round``; the
docstring disclaimed the divergence as a "fidelity note"). This module is
now the single home: replay imports :func:`inject_round` from here, and
:func:`workload_as_injection` maps a workload schedule into the exact
trace form — so "replay a synthesized workload" and "run the workload
through the step's write port" are provably the same path
(tests/test_workload.py pins final-state identity between the two).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from corro_sim.config import SimConfig
from corro_sim.core.changelog import append_changesets
from corro_sim.core.compaction import update_ownership
from corro_sim.core.crdt import NEG, apply_cell_changes
from corro_sim.engine.state import SimState
from corro_sim.gossip.broadcast import enqueue_broadcasts

__all__ = [
    "inject_round",
    "pad_trace_cells",
    "trace_round_args",
    "trace_workload",
    "workload_as_injection",
]


def pad_trace_cells(block, seqs_per_version: int) -> dict:
    """Pad an encoded trace block's cell planes (``row/col/vr/cv/cl``,
    shape ``(rounds, A, S)``) up to the config's seq capacity — extra
    lanes are dead, ``ncells`` masks them out everywhere. ``block`` is
    an :class:`~corro_sim.io.traces.EncodedTrace` or a streaming
    :class:`~corro_sim.io.traces.StreamChunk` (same plane names); shared
    by one-shot replay and the digital twin's chunk loop."""
    pad = seqs_per_version - block.row.shape[2]
    assert pad >= 0, (
        f"trace changesets carry up to {block.row.shape[2]} cells; "
        f"cfg.seqs_per_version={seqs_per_version} is too small"
    )
    return {
        name: np.pad(getattr(block, name), ((0, 0), (0, 0), (0, pad)))
        for name in ("row", "col", "vr", "cv", "cl")
    }


def trace_round_args(block, cells: dict, r: int) -> tuple:
    """Round ``r``'s staged :func:`inject_round` argument tuple off an
    encoded block + its :func:`pad_trace_cells` planes."""
    return (
        jnp.asarray(block.valid[r]),
        jnp.asarray(block.empty[r]),
        jnp.asarray(block.ts[r]),
        jnp.asarray(block.ncells[r]),
        jnp.asarray(cells["row"][r]),
        jnp.asarray(cells["col"][r]),
        jnp.asarray(cells["vr"][r]),
        jnp.asarray(cells["cv"][r]),
        jnp.asarray(cells["cl"][r]),
    )


def inject_round(
    cfg: SimConfig,
    state: SimState,
    valid: jnp.ndarray,  # (A,) bool
    empty: jnp.ndarray,  # (A,) bool
    ts: jnp.ndarray,  # (A,) int32 — EmptySet ts for cleared lanes (-1 none)
    ncells: jnp.ndarray,  # (A,) int32
    row: jnp.ndarray,  # (A, S) int32
    col: jnp.ndarray,  # (A, S) int32
    vr: jnp.ndarray,  # (A, S) int32
    cv: jnp.ndarray,  # (A, S) int32
    cl: jnp.ndarray,  # (A, S) int32
) -> SimState:
    """Commit one changeset round: local apply + log append + gossip enqueue.

    ``A`` (the trace's actor count) may be smaller than ``cfg.num_nodes``;
    actor ordinal == node ordinal (ActorId is the crsql site id,
    ``corro-types/src/actor.rs:26``). Delete lanes are identified per cell
    (``vr == NEG`` — cl-only changes), so one changeset may mix a row
    tombstone with value writes to other rows, as one reference transaction
    can.
    """
    from corro_sim.engine.step import _tile_chunks

    a, s = row.shape
    actor = jnp.arange(a, dtype=jnp.int32)
    has_cells = valid & ~empty

    cell_live = (
        has_cells[:, None]
        & (jnp.arange(s, dtype=jnp.int32)[None, :] < ncells[:, None])
    )
    site = jnp.where(vr == NEG, NEG, jnp.broadcast_to(actor[:, None], (a, s)))

    # Local apply on the writer's own table (trace carries authoritative
    # cv/cl — no recomputation, unlike the synthetic local_write path).
    table = apply_cell_changes(
        state.table,
        jnp.broadcast_to(actor[:, None], (a, s)).reshape(-1),
        row.reshape(-1),
        col.reshape(-1),
        cv.reshape(-1),
        vr.reshape(-1),
        site.reshape(-1),
        cl.reshape(-1),
        cell_live.reshape(-1),
    )

    log, ver = append_changesets(
        state.log, actor, row, col, vr, cv, cl,
        jnp.where(empty, 0, ncells), valid,
    )
    # Cleared versions occupy their slot but deliver nothing; each keeps
    # the ts its EmptySet carried (message-granular, handlers.rs:524-719).
    # Ownership-fold clearings during replay stay unstamped (-1): the
    # trace carries no clock for them, and an unstamped EmptySet simply
    # never advances a receiver's last_cleared (conservative).
    aidx = jnp.where(valid & empty, actor, log.head.shape[0])
    slot = (ver - 1) % log.capacity
    log = log.replace(cleared=log.cleared.at[aidx, slot].set(True, mode="drop"))
    cleared_hlc = state.cleared_hlc.at[aidx, slot].max(ts, mode="drop")

    book = state.book.replace(
        head=state.book.head.at[actor, actor].add(valid.astype(jnp.int32))
    )

    own, log = update_ownership(
        state.own,
        log,
        jnp.broadcast_to(actor[:, None], (a, s)).reshape(-1),
        jnp.broadcast_to(ver[:, None], (a, s)).reshape(-1),
        row.reshape(-1),
        col.reshape(-1),
        cv.reshape(-1),
        vr.reshape(-1),
        site.reshape(-1),
        cl.reshape(-1),
        cell_live.reshape(-1),
        (vr == NEG).reshape(-1),  # per-lane tombstone marker
    )

    # Enqueue every chunk of the fresh version into the writer's own ring.
    q_dst, q_src, q_ver, q_valid, q_chunk = _tile_chunks(
        cfg.chunks_per_version, actor, actor, ver, valid
    )
    gossip = enqueue_broadcasts(
        state.gossip, q_dst, q_src, q_ver, q_chunk, q_valid,
        cfg.max_transmissions,
    )

    return state.replace(
        table=table, book=book, log=log, own=own, gossip=gossip,
        cleared_hlc=cleared_hlc,
    )


def trace_workload(chunks, cfg: SimConfig):
    """The inverse of :func:`workload_as_injection`: fold a live feed's
    encoded chunks (:class:`~corro_sim.io.traces.StreamChunk`) back into
    a :class:`~corro_sim.workload.generators.Workload` tape — the
    coupled-load half of the twin's cadence re-fork loop
    (``corro-sim twin --tail --forecast-load``): the trailing window the
    shadow just absorbed replays INTO every forecast lane, so recovery
    is graded under the live traffic, not against a quiet cluster.

    The workload write port is narrower than a raw changeset, so the
    fold is lossy at the edges — each loss is dropped and COUNTED (the
    ``trace_window`` event carries the tallies), never silently kept
    wrong:

    - EmptySets and pure-DELETE changesets carry causal history the
      port cannot stamp; the changeset is dropped (``dropped_sets``).
    - a changeset spans several rows but the port writes one row per
      changeset; cells off the first row are dropped
      (``dropped_cells``), as are tombstone lanes (``vr == NEG``) mixed
      into a value changeset.

    Returns ``None`` when the window folds to zero writes (nothing to
    couple — the caller forecasts uncoupled rather than replaying an
    empty tape).
    """
    from corro_sim.workload.generators import Workload

    n = cfg.num_nodes
    rows_out: list = []  # per round: (writers, rows, cells[a] lists)
    dropped_sets = dropped_cells = 0
    for ch in chunks:
        a_n = ch.valid.shape[1]
        for r in range(ch.rounds):
            writers = np.zeros((n,), bool)
            rrow = np.zeros((n,), np.int32)
            cells: dict = {}
            for a in range(a_n):
                if not ch.valid[r, a] or ch.empty[r, a]:
                    dropped_sets += int(bool(ch.valid[r, a]))
                    continue
                nc = int(ch.ncells[r, a])
                keep = [
                    (int(ch.col[r, a, c]), int(ch.vr[r, a, c]))
                    for c in range(nc)
                    if ch.vr[r, a, c] != NEG
                    and ch.row[r, a, c] == ch.row[r, a, 0]
                ]
                dropped_cells += nc - len(keep)
                if not keep:
                    dropped_sets += 1
                    continue
                writers[a] = True
                rrow[a] = int(ch.row[r, a, 0])
                cells[a] = keep
            if writers.any():
                rows_out.append((writers, rrow, cells))
    if not rows_out:
        return None
    rounds = len(rows_out)
    s = max(
        max(len(c) for _, _, cells in rows_out for c in cells.values()),
        1,
    )
    writers = np.zeros((rounds, n), bool)
    rows = np.zeros((rounds, n), np.int32)
    cols = np.zeros((rounds, n, s), np.int32)
    vals = np.zeros((rounds, n, s), np.int32)
    ncells = np.zeros((rounds, n), np.int32)
    for r, (w, rrow, cells) in enumerate(rows_out):
        writers[r] = w
        rows[r] = rrow
        for a, keep in cells.items():
            ncells[r, a] = len(keep)
            for c, (col, vr) in enumerate(keep):
                cols[r, a, c] = col
                vals[r, a, c] = vr
    return Workload(
        name="trace_window",
        params={"rounds": rounds, "writes": int(writers.sum())},
        rounds=rounds, n=n, writers=writers, rows=rows, cols=cols,
        vals=vals, dels=np.zeros((rounds, n), bool), ncells=ncells,
        events=[(0, "trace_window", {
            "dropped_sets": dropped_sets,
            "dropped_cells": dropped_cells,
        })],
    )


def workload_as_injection(workload, cfg: SimConfig):
    """Map a first-write workload schedule into :func:`inject_round`'s
    trace form — per round: (valid, empty, ts, ncells, row, col, vr, cv,
    cl) arrays.

    Valid only for schedules where every ``(node, row, col)`` cell is
    written at most once and no changeset is a DELETE: the authoritative
    stamps are then statically known (first write ⇒ ``cv = 1``,
    ``cl = 1``, ``vr =`` the written value), exactly what ``local_write``
    would derive in the step's write port. That restriction is what makes
    the two paths comparable bit for bit — the path-identity test
    (tests/test_workload.py) drives one such schedule through BOTH and
    asserts the converged state matches.
    """
    if (workload.writers & workload.dels).any():
        raise ValueError(
            "workload_as_injection: DELETE changesets need causal history "
            "the trace form cannot stamp statically"
        )
    seen: set = set()
    for r in range(workload.rounds):
        for i in np.nonzero(workload.writers[r])[0]:
            nc = int(workload.ncells[r, i])
            for c in range(nc):
                key = (int(i), int(workload.rows[r, i]),
                       int(workload.cols[r, i, c]))
                if key in seen:
                    raise ValueError(
                        "workload_as_injection requires first-write-only "
                        f"schedules; cell {key} written twice"
                    )
                seen.add(key)
    n, s = workload.n, max(workload.cells_width, 1)
    out = []
    for r in range(workload.rounds):
        valid = workload.writers[r].copy()
        rows = np.broadcast_to(
            workload.rows[r][:, None], (n, s)
        ).astype(np.int32)
        cols = workload.cols[r].astype(np.int32)
        vr = workload.vals[r].astype(np.int32)
        out.append((
            valid,
            np.zeros((n,), bool),  # no EmptySets in a synthetic schedule
            np.full((n,), -1, np.int32),
            workload.ncells[r].astype(np.int32),
            np.ascontiguousarray(rows),
            cols,
            vr,
            np.ones((n, s), np.int32),  # first write: col_version 1
            np.ones((n, s), np.int32),  # live row: causal length 1
        ))
    return out
