"""Production workload engine: traffic generators + load harness.

See :mod:`corro_sim.workload.generators` for the generator catalog and
doc/workloads.md for the spec grammar, latency metrics and bench
workflow. The load harness (:mod:`corro_sim.workload.harness`) imports
lazily — pulling in the generators must not drag the live-cluster stack
into jitted contexts.
"""

from corro_sim.workload.generators import (
    WORKLOADS,
    Workload,
    empty_workload,
    make_workload,
    parse_workload_spec,
)

__all__ = [
    "WORKLOADS",
    "Workload",
    "assert_workload_vacuous",
    "empty_workload",
    "make_workload",
    "parse_workload_spec",
]


def assert_workload_vacuous(cfg=None, rounds: int = 10) -> None:
    """The workload engine's vacuity claim, runnable anywhere (CLI
    ``corro-sim load --verify-vacuous``, tests): the write-schedule
    program is a DISTINCT program, and fed an all-idle schedule it is
    bit-identical — every state leaf, every metric — to the sampler
    program with writes disabled. The workload-OFF program itself is
    pinned byte-for-byte by the jaxpr golden (``corro-sim audit``)."""
    from corro_sim.analysis.jaxpr_audit import assert_feature_vacuous

    if cfg is None:
        from corro_sim.config import SimConfig

        # the exact shape tests/test_faults.py's vacuity oracle runs —
        # the base-side per-round program is then one shared compile
        # across every vacuity caller (warm .jax_cache discipline)
        cfg = SimConfig(
            num_nodes=12, num_rows=16, num_cols=2, log_capacity=128,
            write_rate=0.6,
        ).validate()
    assert_feature_vacuous(
        cfg, cfg, on_workload=empty_workload(cfg.num_nodes, rounds),
        write_rounds=0, rounds=rounds,
    )
