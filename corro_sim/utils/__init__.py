from corro_sim.utils.bits import trailing_ones_u32, window_shift_right
from corro_sim.utils.slots import dedupe_sorted_mask, ranks_within_group

__all__ = [
    "trailing_ones_u32",
    "window_shift_right",
    "dedupe_sorted_mask",
    "ranks_within_group",
]
