"""The shared ``name[:k=v,...]`` spec grammar.

One string names a parameterized generator — fault scenarios
(:mod:`corro_sim.faults.scenarios`) and traffic workloads
(:mod:`corro_sim.workload.generators`) both speak it, so CLI flags, env
vars, TOML fields and HTTP bodies carry the same shape everywhere::

    lossy:p=0.1
    rolling_restart:batch=4,down=8
    zipf:alpha=1.1,rate=0.4

Values parse as int, then float, then bare string. The parser is
registry-agnostic; callers validate ``name`` against their own table
(the error message can then list what IS available).
"""

from __future__ import annotations

__all__ = ["format_spec", "parse_spec"]


def parse_spec(spec: str) -> tuple[str, dict]:
    """``name[:k=v,...]`` → ``(name, params)``."""
    name, _, kv = spec.partition(":")
    name = name.strip()
    if not name:
        raise ValueError(f"spec {spec!r} has no generator name")
    params: dict = {}
    if kv.strip():
        for item in kv.split(","):
            k, eq, v = item.partition("=")
            if not eq:
                raise ValueError(f"spec param {item!r} must be key=value")
            v = v.strip()
            try:
                parsed: object = int(v)
            except ValueError:
                try:
                    parsed = float(v)
                except ValueError:
                    parsed = v
            params[k.strip()] = parsed
    return name, params


def format_spec(name: str, params: dict) -> str:
    """The canonical rendering ``parse_spec`` round-trips."""
    if not params:
        return name
    kv = ",".join(f"{k}={v}" for k, v in sorted(params.items()))
    return f"{name}:{kv}"
