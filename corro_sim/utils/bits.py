"""Branch-free bit utilities for the out-of-order version window.

The reference tracks per-actor applied-version gaps with a
``RangeInclusiveSet`` (``corro-types/src/agent.rs:1310-1496``). On TPU that
ragged structure becomes a fixed 32-bit window per (node, actor): bit ``k``
means version ``head + 1 + k`` has been applied out of order. Absorbing the
contiguous prefix after a delivery is "count trailing ones, shift right".
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

U32_ONE = jnp.uint32(1)
WINDOW_BITS = 32


def trailing_ones_u32(x: jnp.ndarray) -> jnp.ndarray:
    """Count of consecutive set low bits of ``x`` (uint32), elementwise.

    trailing_ones(x) == trailing_zeros(~x); computed via the classic
    ``popcount((y & -y) - 1)`` ctz identity on ``y = ~x`` with an all-ones
    fixup (``~x == 0`` means all 32 bits set).
    """
    x = x.astype(jnp.uint32)
    y = ~x
    # two's complement negate in uint32
    neg_y = (~y) + U32_ONE
    lowbit = y & neg_y
    ctz = lax.population_count(lowbit - U32_ONE)
    return jnp.where(y == 0, jnp.uint32(WINDOW_BITS), ctz.astype(jnp.uint32))


def window_shift_right(win: jnp.ndarray, t: jnp.ndarray) -> jnp.ndarray:
    """Logical right-shift of each uint32 window by per-element ``t`` bits.

    ``t`` may be 32 (full absorb), which wraps around in XLA's shift, so
    clamp-and-mask: shift >= 32 yields 0.
    """
    win = win.astype(jnp.uint32)
    t32 = jnp.minimum(t.astype(jnp.uint32), jnp.uint32(WINDOW_BITS))
    shifted = lax.shift_right_logical(win, jnp.minimum(t32, jnp.uint32(31)))
    # if t in [1,31] we already shifted correctly; handle t == 32 → 0,
    # and t == 31 path above is exact; for t==32 we shifted by 31, fix:
    shifted = jnp.where(t32 >= jnp.uint32(WINDOW_BITS), jnp.uint32(0), shifted)
    return shifted


def absorb(head: jnp.ndarray, win: jnp.ndarray, bits_per_version: int = 1):
    """Advance contiguous heads: head += trailing-complete versions.

    Mirrors ``BookedVersions`` collapsing a gap range once the missing
    versions arrive (reference ``corro-types/src/agent.rs:1220-1285``).

    With ``bits_per_version > 1`` each version owns a group of adjacent
    window bits — one per changeset *chunk* (the reference splits a
    changeset into ≤8 KiB seq-range chunks, ``corro-types/src/change.rs:
    16-122``, and buffers partial versions until every seq arrived,
    ``agent/util.rs:1065-1190``). Only fully-set groups are absorbed; a
    partially-set group is exactly a buffered partial version.
    """
    t = trailing_ones_u32(win)
    if bits_per_version > 1:
        t = (t // jnp.uint32(bits_per_version)) * jnp.uint32(bits_per_version)
    new_head = head + (t // jnp.uint32(bits_per_version)).astype(head.dtype)
    new_win = window_shift_right(win, t)
    return new_head, new_win
