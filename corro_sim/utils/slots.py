"""Vectorized helpers for message dedupe and per-destination slot allocation.

The broadcast path needs two primitives that tokio gives the reference for
free (per-connection ordering + bounded mpsc channels,
``corro-types/src/channel.rs``):

- dedupe of identical (dst, actor, version) deliveries within a round (the
  reference's seen-cache in ``handle_changes``,
  ``corro-agent/src/agent/handlers.rs:886-934``), and
- appending a variable number of accepted messages to each destination's
  bounded pending-broadcast ring (``broadcast/mod.rs:446-455``).

Both are built on one sort: order messages by destination key, then
first-occurrence masks and within-group ranks are elementwise ops.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def dedupe_sorted_mask(*keys: jnp.ndarray) -> jnp.ndarray:
    """Given already-sorted parallel key arrays, mask of first occurrences."""
    first = jnp.ones(keys[0].shape, dtype=bool)
    neq = jnp.zeros(keys[0].shape[:-1] + (keys[0].shape[-1] - 1,), dtype=bool)
    for k in keys:
        neq = neq | (k[..., 1:] != k[..., :-1])
    return first.at[..., 1:].set(neq)


def ranks_within_group(group_sorted: jnp.ndarray) -> jnp.ndarray:
    """For a sorted group-id array, the rank of each element in its group.

    e.g. [2,2,2,5,5,9] → [0,1,2,0,1,0]. Used to hand out ring-buffer slots:
    slot = (cursor[group] + rank) % capacity.
    """
    n = group_sorted.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    starts = jnp.searchsorted(group_sorted, group_sorted, side="left")
    return idx - starts.astype(jnp.int32)


def group_counts(group_sorted: jnp.ndarray, num_groups: int) -> jnp.ndarray:
    """Counts per group id for a sorted group array (drop-invalid ids)."""
    ones = jnp.ones(group_sorted.shape, dtype=jnp.int32)
    return jnp.zeros((num_groups,), jnp.int32).at[group_sorted].add(
        ones, mode="drop"
    )


def ranks_within_group_masked(
    group: jnp.ndarray, mask: jnp.ndarray
) -> jnp.ndarray:
    """Rank of each mask-selected element among selected elements of its
    group — WITHOUT sorting, for lanes already grouped.

    Requires: the subsequence of ``group`` where ``mask`` is set is
    nondecreasing (unselected lanes may hold anything, anywhere). This is
    exactly the state of a lane batch sorted by a validity-masked key
    whose valid subset shrank afterwards. Sort-free: exclusive-cumsum of
    the mask gives global selected-counts; a cummax over run starts
    rebases them per group."""
    m = mask.astype(jnp.int32)
    ex = jnp.cumsum(m) - m  # selected lanes before me, globally
    gdst = jnp.where(mask, group, -1)
    run = lax.cummax(gdst)  # group id of the latest selected lane <= i
    prev_run = jnp.concatenate(
        [jnp.full((1,), -1, run.dtype), run[:-1]]
    )
    is_start = mask & (prev_run != group)
    base = lax.cummax(jnp.where(is_start, ex, -1))  # ex at my group's start
    return jnp.where(mask, ex - base, 0).astype(jnp.int32)
