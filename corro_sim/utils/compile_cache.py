"""Persistent XLA compile cache for non-test entry points.

The 10k-node chunk program costs tens of seconds to compile; tests already
cache compiles on disk (tests/conftest.py) but the bench / CLI / tools
entry points paid it on every process launch. One shared cache directory
keeps bench re-runs and tool iterations warm. Safe to call repeatedly;
honors an explicit JAX_COMPILATION_CACHE_DIR if the user set one.
"""

from __future__ import annotations

import os


def enable_compile_cache() -> None:
    import jax

    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return  # user already configured it via env
    cache_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        ".jax_cache",
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # older jax without these flags: compile cache is best-effort
