"""Persistent XLA compile cache + compile-cost observability.

The 10k-node chunk program costs tens of seconds to compile; tests already
cache compiles on disk (tests/conftest.py) but the bench / CLI / tools
entry points paid it on every process launch. One shared cache directory
keeps bench re-runs and tool iterations warm. Safe to call repeatedly;
honors an explicit JAX_COMPILATION_CACHE_DIR if the user set one.

Compile cost used to be an *invisible tax*: a SimState leaf change
cold-invalidated every cache entry and the ~30 min of recompiles smeared
into whatever ran first (doc/performance.md "compile-cache lifecycle").
This module makes it a measured quantity:

- :func:`program_cache_key` — a deterministic fingerprint of a lowered
  chunk program (sha-256 of its StableHLO text). Two lowerings share a
  persistent-cache entry iff their program text matches, so this key
  *is* the unit of cache identity the manifest in
  ``analysis/golden/cache_keys.json`` pins (tools/prime_cache.py), the
  ``audit --diff`` analog for cache keys instead of jaxprs.
- :class:`CompileCacheProbe` — hit/miss detection around a compile,
  riding jax's own monitoring events (a cache request served = hit; a
  request NOT served = cold compile, even one jax skips persisting).
  Feeds ``corro_compile_cache_hits_total`` /
  ``corro_compile_cache_misses_total`` and the
  ``corro_compile_cold_seconds`` histogram (utils/metrics.py), and the
  per-run ``RunResult.compile_cache`` block.
"""

from __future__ import annotations

import hashlib
import os


def enable_compile_cache() -> None:
    import jax

    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return  # user already configured it via env
    cache_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        ".jax_cache",
    )
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass  # older jax without these flags: compile cache is best-effort
    # Un-latch jax's once-only cache initialization. Importing
    # corro_sim.utils triggers module-scope jits (utils/bits.py weak
    # constants) BEFORE any entry point can run this function, and
    # jax's _initialize_cache latches permanently on that first compile
    # — with no dir configured yet, every later lookup AND write is
    # silently disabled for the whole process (the compile-cache
    # hit/miss events exposed this: bench/CLI processes cold-compiled
    # on every launch while the directory sat warm). reset_cache()
    # clears the latch so the next compile re-initializes against the
    # directory configured above. Only needed when nothing is cached
    # yet — a live cache object means initialization already saw a dir.
    try:
        from jax._src import compilation_cache as _cc

        if _cc._cache is None:
            _cc.reset_cache()
    except Exception:
        pass  # private API moved: worst case is the pre-fix behavior


def cache_dir() -> str | None:
    """The ACTIVE persistent-cache directory, or None when no cache is
    configured (hit/miss detection is then unavailable)."""
    env = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if env:
        return env
    try:
        import jax

        return jax.config.jax_compilation_cache_dir or None
    except Exception:
        return None


def program_cache_key(lowered) -> str:
    """Deterministic fingerprint of a ``jit(...).lower(...)`` result:
    sha-256 over the StableHLO module text. Stable across processes for
    a fixed (program, jax version, visible-device layout); changes
    exactly when the compiled program would re-key the persistent cache.
    Truncated to 16 hex chars — collision-safe at manifest scale and
    short enough to diff by eye."""
    return hashlib.sha256(
        lowered.as_text().encode()
    ).hexdigest()[:16]


# jax's own persistent-cache instrumentation (jax._src.compiler /
# compilation_cache): every compile that CONSULTS the cache records
# compile_requests_use_cache, and every retrieval records cache_hits —
# so "consulted but not served" is an exact cold-compile signal, with
# no directory heuristics and no persistence-threshold blind spot (a
# fast cold compile that jax chooses not to persist still shows up as
# request-without-hit). The listener is process-global and counts
# forever; probes read deltas.
_EVENT_HITS = "/jax/compilation_cache/cache_hits"
_EVENT_REQUESTS = "/jax/compilation_cache/compile_requests_use_cache"
_CACHE_EVENTS = {"hits": 0, "requests": 0}
_LISTENER_STATE = {"registered": False}


def _on_jax_event(event, **kwargs) -> None:
    if event == _EVENT_HITS:
        _CACHE_EVENTS["hits"] += 1
    elif event == _EVENT_REQUESTS:
        _CACHE_EVENTS["requests"] += 1


def _ensure_listener() -> bool:
    if _LISTENER_STATE["registered"]:
        return True
    try:
        import jax.monitoring

        jax.monitoring.register_event_listener(_on_jax_event)
        _LISTENER_STATE["registered"] = True
    except Exception:
        pass
    return _LISTENER_STATE["registered"]


class CompileCacheProbe:
    """Hit/miss observation around persistent-cache compiles.

    Usage::

        probe = CompileCacheProbe()
        ...
        probe.begin()
        compiled = lowered.compile()
        status = probe.end("full", seconds)   # "hit"|"miss"|"unknown"

    Detection rides jax's monitoring events (above): zero cache
    requests between begin/end means the persistent cache was not in
    play (``"unknown"``); a request served from the cache is a
    ``"hit"``; any consulted compile NOT served is a ``"miss"`` (the
    conservative reading when one program triggers several backend
    compiles — any cold one makes the compile cold). Compiles are
    assumed serial between begin/end (the driver's are). Counters land
    in the process-wide registries (utils/metrics.py) under
    ``corro_compile_cache_{hits,misses}_total{program=...}`` and cold
    walls in ``corro_compile_cold_seconds{program=...}``.
    """

    def __init__(self, emit_metrics: bool = True):
        self.emit_metrics = emit_metrics
        self.hits = 0
        self.misses = 0
        self.unknown = 0
        self.cold_seconds = 0.0
        self.by_program: dict[str, dict] = {}
        self._before: tuple[int, int] | None = None

    def begin(self) -> None:
        if _ensure_listener():
            self._before = (
                _CACHE_EVENTS["requests"], _CACHE_EVENTS["hits"]
            )
        else:
            self._before = None

    def end(self, program: str, seconds: float) -> str:
        before, self._before = self._before, None
        if before is None:
            status = "unknown"
            self.unknown += 1
        else:
            d_req = _CACHE_EVENTS["requests"] - before[0]
            d_hit = _CACHE_EVENTS["hits"] - before[1]
            if d_req == 0:
                status = "unknown"  # cache disabled / not consulted
                self.unknown += 1
            elif d_hit >= d_req:
                status = "hit"
                self.hits += 1
            else:
                status = "miss"
                self.misses += 1
                self.cold_seconds += seconds
        prog = self.by_program.setdefault(
            program, {"hits": 0, "misses": 0, "unknown": 0,
                      "cold_seconds": 0.0},
        )
        if status == "miss":
            prog["misses"] += 1
            prog["cold_seconds"] = round(
                prog["cold_seconds"] + seconds, 6
            )
        elif status == "hit":
            prog["hits"] += 1
        else:
            prog["unknown"] += 1
        if self.emit_metrics and status != "unknown":
            from corro_sim.utils.metrics import (
                COMPILE_CACHE_HITS_TOTAL,
                COMPILE_CACHE_MISSES_TOTAL,
                COMPILE_COLD_SECONDS,
                COMPILE_COLD_SECONDS_HELP,
                SECONDS_BUCKETS,
                counters,
                histograms,
            )

            counters.inc(
                COMPILE_CACHE_HITS_TOTAL if status == "hit"
                else COMPILE_CACHE_MISSES_TOTAL,
                labels=f'{{program="{program}"}}',
                help_="persistent XLA compile-cache "
                      f"{'hits' if status == 'hit' else 'misses'} by "
                      "chunk program",
            )
            if status == "miss":
                histograms.observe(
                    COMPILE_COLD_SECONDS, seconds,
                    labels=f'{{program="{program}"}}',
                    help_=COMPILE_COLD_SECONDS_HELP,
                    buckets=SECONDS_BUCKETS,
                )
        return status

    def summary(self) -> dict:
        """The ``RunResult.compile_cache`` / bench-artifact block."""
        return {
            "hits": self.hits,
            "misses": self.misses,
            "unknown": self.unknown,
            "cold_seconds": round(self.cold_seconds, 6),
            "by_program": {k: dict(v) for k, v in self.by_program.items()},
        }
