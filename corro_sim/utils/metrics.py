"""Prometheus text exposition for a LiveCluster.

The reference installs a `metrics` Prometheus exporter with ~120 series
under ``corro.*`` (``corrosion/src/command/agent.rs:95-117``; inventory in
SURVEY §5). The simulator's per-round metrics come out of the jitted step
as a dict; this module renders their running totals plus live gauges in
the exposition format so the same dashboards/scrapers point here.

Metric names follow the reference's (dots become underscores, the
Prometheus exporter does the same mangling): e.g.
``corro_broadcast_recv_count`` ← `corro.broadcast.recv.count`.
"""

from __future__ import annotations

import bisect

import numpy as np

# step-metric key → (prometheus name, type, help)
_SERIES = {
    "writes": (
        "corro_changes_committed_total", "counter",
        "local versions committed (make_broadcastable_changes analog)",
    ),
    "fresh": (
        "corro_changes_applied_total", "counter",
        "remote broadcast changes applied (process_multiple_changes analog)",
    ),
    "sync_versions": (
        "corro_sync_changes_recv_total", "counter",
        "versions repaired by anti-entropy sync",
    ),
    "dropped_window": (
        "corro_broadcast_dropped_total", "counter",
        "broadcasts dropped by bounded inboxes (handlers.rs:866-884 analog)",
    ),
    "deletes": (
        "corro_deletes_applied_total", "counter",
        "causal-length delete merges applied",
    ),
    "rounds": (
        "corro_sim_rounds_total", "counter",
        "simulation rounds executed",
    ),
    "gossip_cells": (
        "corro_broadcast_recv_cells_total", "counter",
        "cell lanes merged off the gossip delivery path",
    ),
    "sync_cells": (
        "corro_sync_recv_cells_total", "counter",
        "cell lanes shipped by anti-entropy sweeps",
    ),
}

# Byte-volume model for the wire counters below: one cell rides the wire
# as a `Change` row — table + pk + cid + val + col_version/db_version/seq
# ints + 16-byte site_id + cl (corro-api-types/src/lib.rs:235-245); ~128 B
# is the round JSON/speedy midpoint the reference's chunker assumes when
# it splits at ~8 KiB (change.rs:16-122). Chunk framing adds ~32 B.
CHANGE_WIRE_BYTES = 128
CHUNK_HEADER_BYTES = 32


# the reference exporter's bucket config (command/agent.rs:95-117):
# seconds-scale metrics share one ladder; *chunk_size gets its own
SECONDS_BUCKETS = (
    0.001, 0.005, 0.025, 0.050, 0.100, 0.200,
    1.0, 2.0, 3.0, 4.0, 5.0, 10.0, 30.0, 60.0,
)
CHUNK_SIZE_BUCKETS = (1.0, 10.0, 75.0, 250.0, 375.0, 500.0, 650.0)

# ---- corro_pipeline_*: chunk-pipeline observability ------------------
# The pipelined chunk dispatch (engine/driver.py, doc/performance.md)
# and the harness tick paths (harness/cluster.py) share one histogram
# for the host wall spent BLOCKED resolving a chunk's packed metric
# stacks, labeled by mode:
#   mode="sequential"  — run_sim --no-pipeline's blocking read (the
#                        stall the pipeline exists to hide)
#   mode="pipelined"   — run_sim's resolve of an async fetch started at
#                        dispatch time, one chunk behind
#   mode="live_chunk"/"live_step" — LiveCluster tick paths (async fetch
#                        overlapped with subscription notification)
# Companion counters written by the driver:
#   corro_pipeline_speculative_total        chunks dispatched ahead of
#                                           the convergence scalar
#   corro_pipeline_speculative_wasted_total discarded results, by
#                                           reason= converged|poisoned|
#                                           program_switch
#   corro_pipeline_overlap_seconds_total    host control/bookkeeping
#                                           wall concurrent with device
#                                           chunk execution
PIPELINE_FETCH_WAIT = "corro_pipeline_fetch_wait_seconds"
PIPELINE_FETCH_WAIT_HELP = (
    "host wall blocked resolving a chunk's packed metric stacks "
    "(device->host), by dispatch mode; sequential mode is the "
    "blocking-read stall the chunk pipeline hides"
)

# ---- corro_config_downgrade_total: explicit config fallbacks ---------
# A run that cannot honor a requested config knob on this backend must
# SAY so (ISSUE 8: the old driver silently forced merge_kernel="off"
# under a sharded mesh). Every such fallback increments this counter
# and lands a `config_downgrade` flight annotation:
#   corro_config_downgrade_total{field,reason}
# known reasons: sharded_non_tpu (Pallas merge under a mesh needs TPU
# or the forced "on" interpret mode), cell_space_unaligned,
# uneven_node_shards (core/merge_kernel.py sharded_kernel_downgrade).
CONFIG_DOWNGRADE_TOTAL = "corro_config_downgrade_total"
CONFIG_DOWNGRADE_HELP = (
    "config knobs downgraded at run time because the backend cannot "
    "honor them, by field and reason (flight `config_downgrade` "
    "annotations carry the same provenance)"
)

# ---- corro_compile_cache_*: compile-cost observability ----------------
# Compile cost used to be an invisible tax (a SimState leaf change cold
# the whole .jax_cache and the ~30 min smeared into whatever ran first).
# Every AOT lower+compile in the driver and the prime-cache warm layer
# (tools/prime_cache.py) now reports against the persistent cache
# (utils/compile_cache.py CompileCacheProbe — detection via jax's own
# cache-request/cache-hit monitoring events):
#   corro_compile_cache_hits_total{program}    compiles served from the
#                                              persistent cache
#   corro_compile_cache_misses_total{program}  cold compiles (a new
#                                              cache entry was written)
#   corro_compile_cold_seconds{program}        wall of the cold compiles
#                                              only — the COLD share of
#                                              corro_compile_seconds, so
#                                              bench trajectories can
#                                              separate compile wall
#                                              from sim wall
# The same numbers ride RunResult.compile_cache, flight `compile`
# annotations, and every bench artifact (ISSUE 10).
COMPILE_CACHE_HITS_TOTAL = "corro_compile_cache_hits_total"
COMPILE_CACHE_MISSES_TOTAL = "corro_compile_cache_misses_total"
COMPILE_COLD_SECONDS = "corro_compile_cold_seconds"
COMPILE_COLD_SECONDS_HELP = (
    "cold (cache-missing) AOT compile wall by chunk program — the "
    "persistent-cache-miss share of corro_compile_seconds"
)

# ---- corro_subs_matcher_*: batched subscription matcher evals ---------
# SubsManager.step used to dispatch ONE jit per registered matcher per
# tick (1k subscribers = 1k dispatches + 2k device->host reads). Plain
# single-table matchers whose device predicates share a structure
# skeleton now evaluate as ONE vmapped jit per skeleton
# (subs/manager.py SubsManager._batched_precompute):
#   corro_subs_matcher_evals_total{mode="batched"|"single"}  matcher
#       evaluations by dispatch mode (batched = rode a group jit)
#   corro_subs_batch_groups_total    batched group dispatches
SUBS_MATCHER_EVALS_TOTAL = "corro_subs_matcher_evals_total"
SUBS_BATCH_GROUPS_TOTAL = "corro_subs_batch_groups_total"

# ---- corro_lint_*: static analysis + transfer-guard observability ----
# The corro-lint analyzer (corro_sim/analysis/, `corro-sim lint`)
# exports its run profile as info counters so a scrape of any process
# that ran it (CI harness, agent admin) carries the findings picture:
#   corro_lint_runs_total                    analyzer invocations
#   corro_lint_files_scanned_total           files parsed
#   corro_lint_findings_total{rule,severity} findings by rule (CL1xx)
#   corro_lint_suppressions_total{rule}      `# corro-lint: ignore[...]`
#                                            hits
# The transfer guard (analysis/transfer_guard.py, armed by
# CORRO_SIM_TRANSFER_GUARD=1 / run_sim(transfer_guard=True)) counts
# every transfer through the chunk loop's sanctioned points:
#   corro_lint_sanctioned_transfers_total{point=chunk_stage|
#       metric_fetch_start|metric_resolve|probe_extract|invariants}
LINT_RUNS_TOTAL = "corro_lint_runs_total"
LINT_FILES_SCANNED_TOTAL = "corro_lint_files_scanned_total"
LINT_FINDINGS_TOTAL = "corro_lint_findings_total"
LINT_SUPPRESSIONS_TOTAL = "corro_lint_suppressions_total"
LINT_SANCTIONED_TRANSFERS_TOTAL = "corro_lint_sanctioned_transfers_total"

# ---- corro_audit_contract_*: the program-contract auditor
# (analysis/contracts.py, `corro-sim audit --contracts`) counts every
# statically-checked contract and every violation/drift row, labeled by
# family (vacuity | determinism | memory | collectives):
#   corro_audit_contract_checks_total{family}      contracts evaluated
#   corro_audit_contract_violations_total{family}  budget violations +
#                                                  manifest drift
AUDIT_CONTRACT_CHECKS_TOTAL = "corro_audit_contract_checks_total"
AUDIT_CONTRACT_VIOLATIONS_TOTAL = "corro_audit_contract_violations_total"

# ---- corro_audit_key_*: the key-lineage auditor (analysis/keys.py,
# `corro-sim audit --keys`) counts every proven stream-disjointness
# check and every violation/drift row, labeled by contract family
# (k1 single-consumption | k2 stream disjointness | k3 lane/fork
# independence | manifest = structural golden drift):
#   corro_audit_key_checks_total{family}      lineage checks evaluated
#   corro_audit_key_violations_total{family}  violations + drift
AUDIT_KEY_CHECKS_TOTAL = "corro_audit_key_checks_total"
AUDIT_KEY_VIOLATIONS_TOTAL = "corro_audit_key_violations_total"

# ---- corro_workload_* / corro_sub_latency_*: the production workload
# engine (corro_sim/workload/, doc/workloads.md). The load harness
# drives a compiled traffic schedule through a LiveCluster with
# concurrent subscriptions + query fans and records:
#   corro_workload_writes_total{kind="write"|"delete"}  schedule ops
#                                                       committed
#   corro_workload_rounds_total                         load rounds driven
#   corro_workload_coalesced_total     writes whose value never reached a
#                                      subscriber (overwritten before the
#                                      matcher diff ran — the reference's
#                                      candidate batching coalesces the
#                                      same way, pubsub.rs:1154-1296)
#   corro_workload_queries_total{surface="direct"|"http"|"pg"}
#                                      one-shot queries fanned per surface
#   corro_workload_events_total{kind}  burst onsets / churn waves executed
#                                      (batched path, engine/driver.py)
# and two delivery-latency histograms, change COMMIT → SubEvent emit:
#   corro_sub_latency_rounds   in simulation rounds (exact: events carry
#                              their emit round)
#   corro_sub_latency_seconds  host wall from API accept to queue drain
SUB_LATENCY_ROUNDS = "corro_sub_latency_rounds"
SUB_LATENCY_ROUNDS_HELP = (
    "subscription delivery latency in simulation rounds "
    "(change commit -> SubEvent emit)"
)
SUB_LATENCY_SECONDS = "corro_sub_latency_seconds"
SUB_LATENCY_SECONDS_HELP = (
    "subscription delivery wall latency (write accepted -> event "
    "drained by the subscriber)"
)
# ---- corro_node_fault_* / corro_resilience_*: the node-lifecycle
# fault domain + resilience scorecard (corro_sim/faults/nodes.py,
# faults/scorecard.py; doc/fault_injection.md §node faults). Step
# metrics (additive node-round counters, emitted only while
# SimConfig.node_faults is enabled):
#   corro_node_fault_wipes_total        crash-restart wipes executed
#                                       (amnesia + stale restores)
#   corro_node_fault_straggling_total   straggler node-rounds parked by
#                                       the duty cycle
#   corro_node_fault_recovering_total   node-rounds spent resyncing a
#                                       wiped write cursor
# Scorecard families (one finalized block per graded run, labeled by
# scenario):
#   corro_resilience_runs_total             graded runs
#   corro_resilience_rows_lost_total        cells diverging from the
#                                           partition reference at the
#                                           convergence report
#   corro_resilience_resync_rows_total      version-applications repaid
#                                           to wiped nodes
#   corro_resilience_swim_false_down_total  belief pairs marking a live
#                                           node DOWN
#   corro_resilience_swim_flaps_total       false-DOWN pairs relapsing
#   corro_resilience_recovery_rounds        histogram: heal →
#                                           re-convergence (ROUNDS_BUCKETS)
NODE_FAULT_WIPES_TOTAL = "corro_node_fault_wipes_total"
RESILIENCE_RUNS_TOTAL = "corro_resilience_runs_total"
RESILIENCE_RECOVERY_ROUNDS = "corro_resilience_recovery_rounds"

WORKLOAD_WRITES_TOTAL = "corro_workload_writes_total"
WORKLOAD_ROUNDS_TOTAL = "corro_workload_rounds_total"
WORKLOAD_COALESCED_TOTAL = "corro_workload_coalesced_total"
WORKLOAD_QUERIES_TOTAL = "corro_workload_queries_total"

# ---- corro_sweep_*: fleet observatory (corro_sim/obs/lanes.py over
# corro_sim/sweep/engine.py; doc/observability.md §lane-observatory).
# The lane-batched chunk loop publishes per-dispatch lane-state gauges
# (how many lanes are still racing vs bit-frozen vs poisoned), a
# counter of FLOPs burned on already-settled lanes (a settled lane
# still rides every later dispatch through the freeze select — the
# number that motivates ROADMAP on-device lane freezing), and a
# per-cell recovery-rounds histogram so the quantiles the frontier
# grades are scrape-visible too:
#   corro_sweep_lanes_active        lanes still racing (gauge)
#   corro_sweep_lanes_converged     lanes bit-frozen at convergence
#   corro_sweep_lanes_poisoned      lanes frozen by the ring-wrap
#                                   tripwire
#   corro_sweep_wasted_lane_rounds_total  rounds dispatched for lanes
#                                   that had already settled
#   corro_sweep_recovery_rounds{cell}     histogram: heal →
#                                   re-convergence per frontier cell
#                                   (ROUNDS_BUCKETS)
# Emission and the exposition-validator coverage (tests/test_metrics.py)
# both use THESE constants, so coverage cannot drift from emission.
SWEEP_LANES_ACTIVE = "corro_sweep_lanes_active"
SWEEP_LANES_ACTIVE_HELP = (
    "sweep lanes still racing (not yet converged or poisoned; "
    "corro_sim/sweep/engine.py)"
)
SWEEP_LANES_CONVERGED = "corro_sweep_lanes_converged"
SWEEP_LANES_CONVERGED_HELP = (
    "sweep lanes bit-frozen at their convergence chunk"
)
SWEEP_LANES_POISONED = "corro_sweep_lanes_poisoned"
SWEEP_LANES_POISONED_HELP = (
    "sweep lanes frozen by the ring-wrap poison tripwire"
)
SWEEP_WASTED_LANE_ROUNDS_TOTAL = "corro_sweep_wasted_lane_rounds_total"
SWEEP_WASTED_LANE_ROUNDS_HELP = (
    "lane-rounds dispatched for already-settled (frozen) lanes — the "
    "FLOP waste on-device lane freezing would reclaim "
    "(corro_sim/obs/lanes.py fleet occupancy)"
)
SWEEP_RECOVERY_ROUNDS = "corro_sweep_recovery_rounds"
SWEEP_RECOVERY_ROUNDS_HELP = (
    "per-lane heal -> re-convergence rounds by frontier cell "
    "(scenario spec + knob suffix; corro_sim/sweep/engine.py)"
)

# Digital-twin shadow (corro_sim/engine/twin.py; doc/twin.md):
#   corro_twin_feed_lines_total        feed lines consumed (good + bad)
#   corro_twin_bad_lines_total{reason} quarantined hostile feed lines by
#                                      reason (io/traces.py BAD_REASONS)
#   corro_twin_chunks_total            feed chunks shadowed
#   corro_twin_rounds_total            shadow sim rounds (feed + drain)
#   corro_twin_checkpoints_total       feed-cursor checkpoints written
#   corro_twin_resumes_total           shadows resumed from a cursor
#   corro_twin_forecast_lanes_total{scenario}
#                                      what-if lanes raced from a fork
#   corro_twin_delivery_rounds         histogram: shadowed delivery p99
#                                      in rounds (ROUNDS_BUCKETS)
TWIN_BAD_LINES_TOTAL = "corro_twin_bad_lines_total"
TWIN_BAD_LINES_HELP = (
    "hostile feed lines quarantined by the twin shadow, by reason "
    "(corro_sim/io/traces.py)"
)
TWIN_FEED_LINES_TOTAL = "corro_twin_feed_lines_total"
TWIN_DELIVERY_ROUNDS = "corro_twin_delivery_rounds"
TWIN_FORECAST_LANES_TOTAL = "corro_twin_forecast_lanes_total"

# Live tail + stale-universe refresh (corro_sim/io/feedsource.py,
# corro_sim/engine/twin.py; doc/twin.md §9):
#   corro_twin_tail_polls_total{source}    feed polls issued by a live
#                                          source (file|http)
#   corro_twin_tail_retries_total{source}  jittered-backoff retries after
#                                          a missing file / failed request
#   corro_twin_tail_rotations_total        feed rotations re-bound
#                                          (inode moved under the tail)
#   corro_twin_tail_source_deaths_total{reason}
#                                          sources declared dead past the
#                                          backoff/idle budget
#   corro_twin_tail_lag_lines              gauge: lines buffered ahead of
#                                          the shadow's cursor
#   corro_twin_refresh_total{trigger}      closed-world re-freezes (the
#                                          scheduled re-key events)
#   corro_twin_refresh_epoch               gauge: current refresh epoch
TWIN_TAIL_POLLS_TOTAL = "corro_twin_tail_polls_total"
TWIN_TAIL_POLLS_HELP = (
    "live feed polls issued, by source kind (corro_sim/io/feedsource.py)"
)
TWIN_TAIL_RETRIES_TOTAL = "corro_twin_tail_retries_total"
TWIN_TAIL_RETRIES_HELP = (
    "jittered exponential-backoff retries against a missing or failing "
    "live feed source (corro_sim/io/feedsource.py)"
)
TWIN_TAIL_ROTATIONS_TOTAL = "corro_twin_tail_rotations_total"
TWIN_TAIL_ROTATIONS_HELP = (
    "feed-file rotations the tail re-bound to (inode changed under the "
    "consumed-prefix sha guard; corro_sim/io/feedsource.py)"
)
TWIN_TAIL_SOURCE_DEATHS_TOTAL = "corro_twin_tail_source_deaths_total"
TWIN_TAIL_SOURCE_DEATHS_HELP = (
    "live feed sources declared dead, by reason (idle_timeout|"
    "source_gone|reconnect_budget|truncated; corro_sim/io/feedsource.py)"
)
TWIN_TAIL_LAG_LINES = "corro_twin_tail_lag_lines"
TWIN_TAIL_LAG_LINES_HELP = (
    "feed lines buffered ahead of the shadow's cursor (bounded by "
    "twin.max_lag_lines; corro_sim/engine/twin.py)"
)
TWIN_REFRESH_TOTAL = "corro_twin_refresh_total"
TWIN_REFRESH_HELP = (
    "stale-universe re-freezes (scheduled re-key events), by trigger "
    "(corro_sim/engine/twin.py)"
)
TWIN_REFRESH_EPOCH = "corro_twin_refresh_epoch"
TWIN_REFRESH_EPOCH_HELP = (
    "current closed-world refresh epoch of the running twin shadow "
    "(corro_sim/engine/twin.py)"
)
ROUNDS_BUCKETS = (
    0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0, 48.0,
    64.0, 96.0, 128.0,
)

# Perf ledger & regression sentinel (corro_sim/obs/ledger.py;
# doc/performance.md §9):
#   corro_perf_ledger_records          records in the loaded ledger
#   corro_perf_ledger_series           distinct (config, platform) series
#   corro_perf_latest_value{series}    latest measured value per series
#   corro_perf_check_breaches          band breaches at the last --check
#   corro_perf_check_skipped_cross_platform
#                                      series honest-skipped (capture
#                                      platform != band platform)
#   corro_perf_unmeasured_records      explicit unmeasured records (the
#                                      r05 preflight-failure shape)
PERF_LEDGER_RECORDS = "corro_perf_ledger_records"
PERF_LEDGER_RECORDS_HELP = (
    "records in the loaded performance ledger "
    "(corro_sim/obs/ledger.py; doc/performance.md section 9)"
)
PERF_LEDGER_SERIES = "corro_perf_ledger_series"
PERF_LEDGER_SERIES_HELP = (
    "distinct (config, platform) series in the performance ledger"
)
PERF_LATEST_VALUE = "corro_perf_latest_value"
PERF_LATEST_VALUE_HELP = (
    "latest measured value per ledger series (label: series = "
    "config@platform)"
)
PERF_CHECK_BREACHES = "corro_perf_check_breaches"
PERF_CHECK_BREACHES_HELP = (
    "series breaching their perf_bands.json tolerance band at the last "
    "`perf --check` (the exit-6 regression sentinel)"
)
PERF_CHECK_SKIPPED = "corro_perf_check_skipped_cross_platform"
PERF_CHECK_SKIPPED_HELP = (
    "series honest-skipped at the last check: the capture's platform "
    "differs from every banded platform for its config — CPU-relative "
    "numbers are never graded against device baselines"
)
PERF_UNMEASURED_RECORDS = "corro_perf_unmeasured_records"
PERF_UNMEASURED_RECORDS_HELP = (
    "explicit unmeasured ledger records (device preflight failures, "
    "the BENCH_r05 shape) — holes the trajectory shows, never grades"
)

# Run-diagnosis doctor (corro_sim/obs/doctor.py; doc/observability.md
# §8):
#   corro_doctor_findings_total{rule,severity}
#                                      findings at the last diagnosis,
#                                      per rule and severity
#   corro_doctor_artifacts_scanned     artifacts the last diagnosis read
#   corro_doctor_artifacts_skipped     artifacts honest-skipped with a
#                                      reason (unreadable/unrecognized)
#   corro_doctor_critical_findings     critical findings at the last
#                                      diagnosis (the --check exit-6
#                                      tripwire)
DOCTOR_FINDINGS_TOTAL = "corro_doctor_findings_total"
DOCTOR_FINDINGS_TOTAL_HELP = (
    "findings at the last doctor diagnosis, labeled by rule and "
    "severity (corro_sim/obs/doctor.py; doc/observability.md "
    "section 8)"
)
DOCTOR_ARTIFACTS_SCANNED = "corro_doctor_artifacts_scanned"
DOCTOR_ARTIFACTS_SCANNED_HELP = (
    "telemetry artifacts the last doctor diagnosis classified and read"
)
DOCTOR_ARTIFACTS_SKIPPED = "corro_doctor_artifacts_skipped"
DOCTOR_ARTIFACTS_SKIPPED_HELP = (
    "artifacts the last doctor diagnosis honest-skipped with a counted "
    "reason (unreadable, unrecognized, torn) — visible, never fatal"
)
DOCTOR_CRITICAL_FINDINGS = "corro_doctor_critical_findings"
DOCTOR_CRITICAL_FINDINGS_HELP = (
    "critical findings at the last doctor diagnosis — nonzero trips "
    "`doctor --check` exit 6, the shared regression tripwire code"
)


class Histogram:
    """A Prometheus histogram with the reference exporter's buckets
    (``command/agent.rs:95-117``) — cumulative bucket counts, sum, count.
    Replaces the r4 EWMA-only timings (VERDICT r4 #7)."""

    __slots__ = ("buckets", "counts", "sum", "count", "max")

    def __init__(self, buckets=SECONDS_BUCKETS):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +Inf tail
        self.sum = 0.0
        self.count = 0
        self.max = 0.0

    def observe(self, value: float) -> None:
        # first bucket with value <= bound (bisect: this sits on hot
        # instrumentation paths — per-chunk, per-drain, per-request)
        self.counts[bisect.bisect_left(self.buckets, value)] += 1
        self.sum += value
        self.count += 1
        if value > self.max:
            self.max = value

    def quantile(self, q: float) -> float | None:
        """Bucket-resolution quantile estimate (upper bound of the bucket
        the q-th sample falls in; the observed max for the +Inf tail) —
        what the workload bench reports as sub-delivery p50/p99."""
        if not self.count:
            return None
        target = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= target and c:
                if i >= len(self.buckets):
                    return self.max
                # bucket upper bound, clamped to the observed max (a
                # sparse tail bucket must not report past reality)
                return min(float(self.buckets[i]), self.max)
        return self.max

class HistogramRegistry:
    """Process-wide named histograms ((name, labels) → Histogram). The
    instrumentation points (cluster tick stages, lock waits, write-queue
    latency, checkpoint/respace walls, API connect times, consul calls)
    observe here; /metrics renders every registered series."""

    def __init__(self):
        import threading

        self._h: dict[tuple, Histogram] = {}
        self._help: dict[str, str] = {}
        self._lock = threading.Lock()

    def observe(self, name: str, value: float, labels: str = "",
                help_: str = "", buckets=SECONDS_BUCKETS) -> None:
        with self._lock:
            h = self._h.get((name, labels))
            if h is None:
                h = self._h[(name, labels)] = Histogram(buckets)
                if help_:
                    self._help.setdefault(name, help_)
            h.observe(value)

    def observe_many(self, name: str, values, labels: str = "",
                     help_: str = "", buckets=SECONDS_BUCKETS) -> None:
        """Batch form: ONE lock acquisition for a whole drain/dispatch
        worth of samples (hot loops must not take the registry lock per
        event)."""
        if not values:
            return
        with self._lock:
            h = self._h.get((name, labels))
            if h is None:
                h = self._h[(name, labels)] = Histogram(buckets)
                if help_:
                    self._help.setdefault(name, help_)
            for v in values:
                h.observe(v)

    def get(self, name: str, labels: str = "") -> Histogram | None:
        """The registered histogram for (name, labels), or None — the
        public read path for report builders (quantiles, max, count)."""
        with self._lock:
            return self._h.get((name, labels))

    def quantile(self, name: str, q: float, labels: str = "") -> float | None:
        """Bucket-resolution quantile of one registered series (None when
        the series has no samples) — the bench's p50/p99 reader."""
        h = self.get(name, labels)
        return h.quantile(q) if h is not None else None

    def render(self) -> list[str]:
        with self._lock:
            items = sorted(self._h.items())
            out = []
            seen = set()
            for (name, labels), h in items:
                if name not in seen:
                    seen.add(name)
                    out.append(
                        f"# HELP {name} {self._help.get(name, name)}"
                    )
                    out.append(f"# TYPE {name} histogram")
                base = labels[1:-1] if labels else ""
                cum = 0
                for b, c in zip(h.buckets, h.counts):
                    cum += c
                    lbl = f'{base},le="{b}"' if base else f'le="{b}"'
                    out.append(f"{name}_bucket{{{lbl}}} {cum}")
                lbl = f'{base},le="+Inf"' if base else 'le="+Inf"'
                out.append(f"{name}_bucket{{{lbl}}} {h.count}")
                sfx = f"{{{base}}}" if base else ""
                out.append(f"{name}_sum{sfx} {round(h.sum, 6)}")
                out.append(f"{name}_count{sfx} {h.count}")
            return out


histograms = HistogramRegistry()


class CounterRegistry:
    """Process-wide named counters for instrumentation points outside the
    cluster's step-metric fold (e.g. consul client errors)."""

    def __init__(self):
        import threading

        self._c: dict[tuple, float] = {}
        self._help: dict[str, str] = {}
        self._lock = threading.Lock()

    def inc(self, name: str, n: float = 1, labels: str = "",
            help_: str = "") -> None:
        with self._lock:
            self._c[(name, labels)] = self._c.get((name, labels), 0) + n
            if help_:
                self._help.setdefault(name, help_)

    def render(self) -> list[str]:
        with self._lock:
            out = []
            seen = set()
            for (name, labels), v in sorted(self._c.items()):
                if name not in seen:
                    seen.add(name)
                    out.append(f"# HELP {name} {self._help.get(name, name)}")
                    out.append(f"# TYPE {name} counter")
                out.append(f"{name}{labels} {v}")
            return out


counters = CounterRegistry()


class GaugeRegistry:
    """Process-wide named gauges for LAST-VALUE instrumentation outside
    any cluster (the counter registry's set-valued sibling): headless
    drivers like the sweep engine have no LiveCluster to render from,
    so their live state (lanes racing/frozen/poisoned) lands here and
    rides every /metrics scrape in the process."""

    def __init__(self):
        import threading

        self._g: dict[tuple, float] = {}
        self._help: dict[str, str] = {}
        self._lock = threading.Lock()

    def set(self, name: str, value: float, labels: str = "",
            help_: str = "") -> None:
        with self._lock:
            self._g[(name, labels)] = value
            if help_:
                self._help.setdefault(name, help_)

    def get(self, name: str, labels: str = "") -> float | None:
        with self._lock:
            return self._g.get((name, labels))

    def render(self) -> list[str]:
        with self._lock:
            out = []
            seen = set()
            for (name, labels), v in sorted(self._g.items()):
                if name not in seen:
                    seen.add(name)
                    out.append(f"# HELP {name} {self._help.get(name, name)}")
                    out.append(f"# TYPE {name} gauge")
                out.append(f"{name}{labels} {v}")
            return out


gauges = GaugeRegistry()


class ChannelMetrics:
    """Per-queue health counters — the ``corro.runtime.channel.*`` series
    (reference ``corro-types/src/channel.rs:16-184``): send / recv /
    failed-send counts, depth + max-capacity gauges, and a send-delay
    EWMA per named channel. The reference wraps every tokio channel in a
    counting sender/receiver; here the host-side queues (write queue, sub
    event queues) count at their touch points and the device-side gossip
    rings derive their series from step metrics.

    ``histograms``: the registry the send-delay histogram lands in —
    cluster-scoped when owned by a LiveCluster (a process can host
    several clusters; mixing their observations would lie)."""

    def __init__(self, histograms: "HistogramRegistry | None" = None):
        import threading

        self._ch: dict[str, dict] = {}
        self._labels: dict[str, str] = {}  # cached per-channel label text
        self.histograms = histograms
        self._lock = threading.Lock()  # touch points span HTTP handler
        # threads and the tick thread; += on a dict entry is not atomic

    def _c(self, name: str) -> dict:
        return self._ch.setdefault(
            name,
            {"send": 0, "recv": 0, "failed": 0, "depth": 0,
             "capacity": 0, "send_delay_ewma_ms": 0.0,
             "delay_samples": 0},
        )

    def set_capacity(self, name: str, capacity: int) -> None:
        with self._lock:
            self._c(name)["capacity"] = int(capacity)

    def set_depth(self, name: str, depth: int) -> None:
        with self._lock:
            self._c(name)["depth"] = int(depth)

    def on_send(self, name: str, n: int = 1, delay_s: float | None = None):
        with self._lock:
            c = self._c(name)
            c["send"] += n
            c["depth"] += n
            if delay_s is not None:
                ms = delay_s * 1000.0
                c["send_delay_ewma_ms"] += 0.2 * (
                    ms - c["send_delay_ewma_ms"]
                )
                c["delay_samples"] += 1
        if delay_s is not None:
            # bucketed per-channel send delay (corro.runtime.channel.
            # send_delay is a HISTOGRAM in the reference, channel.rs;
            # the EWMA gauge above stays for cheap dashboards)
            lbl = self._labels.get(name)
            if lbl is None:
                lbl = self._labels[name] = f'{{channel_name="{name}"}}'
            (self.histograms or histograms).observe(
                "corro_runtime_channel_send_delay_seconds", delay_s,
                labels=lbl,
                help_="send delay per channel "
                      "(corro.runtime.channel.send_delay)",
            )

    def on_recv(self, name: str, n: int = 1) -> None:
        with self._lock:
            c = self._c(name)
            c["recv"] += n
            c["depth"] = max(0, c["depth"] - n)

    def on_failed(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._c(name)["failed"] += n

    def snapshot(self) -> dict:
        with self._lock:
            return {k: dict(v) for k, v in self._ch.items()}


def render_prometheus(cluster) -> str:
    lines: list[str] = []

    def emit(name, kind, help_, value, labels=""):
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name}{labels} {value}")

    totals = cluster.metrics_totals()
    for key, (name, kind, help_) in _SERIES.items():
        if key in totals:
            emit(name, kind, help_, int(totals[key]))
    # remaining step metrics, generically. probe_* step metrics are
    # GAUGES (infected count, cumulative dups) — summing them across
    # rounds would lie; they render in the probe block below instead.
    # fault_* metrics render in their own corro_fault_* block.
    for key, v in sorted(totals.items()):
        if (
            key not in _SERIES
            and not key.startswith(("probe_", "fault_", "node_fault_"))
        ):
            emit(
                f"corro_sim_{key}_total", "counter",
                f"sim step metric {key}", v,
            )

    # ---- node-lifecycle faults (corro_sim/faults/nodes.py): additive
    # node-round counters, named corro_node_fault_* so the driver-side
    # counters and soak dashboards line up.
    for key in sorted(k for k in totals if k.startswith("node_fault_")):
        emit(
            f"corro_{key}_total", "counter",
            f"node-lifecycle fault flow {key[11:]} "
            "(corro_sim/faults/nodes.py)",
            int(totals[key]),
        )

    # ---- chaos injection (corro_sim/faults/): injected-fault flow
    # counters + the burst-state gauge, named corro_fault_* so soak
    # dashboards and the driver-side counters line up.
    fault_totals = {
        k: int(v) for k, v in totals.items()
        if k.startswith("fault_") and k != "fault_burst_nodes"
    }
    _faults = getattr(getattr(cluster, "cfg", None), "faults", None)
    if fault_totals or (_faults is not None and _faults.enabled):
        for key in sorted(fault_totals):
            emit(
                f"corro_{key}_total", "counter",
                f"injected fault effect {key[6:]} (corro_sim/faults/)",
                fault_totals[key],
            )
        emit(
            "corro_fault_burst_nodes", "gauge",
            "nodes currently in the burst-loss state",
            int(cluster.metrics_lasts().get("fault_burst_nodes", 0)),
        )

    # ---- wire byte volume (corro.broadcast.recv.bytes /
    # corro.sync.chunk.sent.bytes analogs, agent/metrics.rs): modeled from
    # the cell/chunk counters via the Change wire-size constants above.
    g_cells = int(totals.get("gossip_cells", 0))
    g_chunks = int(totals.get("delivered", 0))
    bcast_bytes = g_cells * CHANGE_WIRE_BYTES + g_chunks * CHUNK_HEADER_BYTES
    emit(
        "corro_broadcast_recv_bytes_total", "counter",
        "modeled broadcast bytes received "
        f"(cells*{CHANGE_WIRE_BYTES} + chunks*{CHUNK_HEADER_BYTES})",
        bcast_bytes,
    )
    s_cells = int(totals.get("sync_cells", 0))
    s_versions = int(totals.get("sync_versions", 0))
    emit(
        "corro_sync_chunk_sent_bytes_total", "counter",
        "modeled anti-entropy bytes shipped "
        f"(cells*{CHANGE_WIRE_BYTES} + versions*{CHUNK_HEADER_BYTES})",
        s_cells * CHANGE_WIRE_BYTES + s_versions * CHUNK_HEADER_BYTES,
    )

    # ---- per-stage round timing (tools/profile_round.py's live analog;
    # VERDICT r2 #9): wall-clock per simulation round by host stage.
    stages = cluster.stage_timings()
    if stages:
        lines.append(
            "# HELP corro_round_stage_ms per-round wall-clock by stage (ewma)"
        )
        lines.append("# TYPE corro_round_stage_ms gauge")
        for stage, t in sorted(stages.items()):
            lines.append(
                f'corro_round_stage_ms{{stage="{stage}"}} {t["ewma_ms"]}'
            )
            lines.append(
                f'corro_round_stage_ms{{stage="{stage}",window="last"}} '
                f'{t["last_ms"]}'
            )

    # live gauges (agent/metrics.rs:18-108 analog: rows, gaps, members)
    head = np.asarray(cluster.state.log.head)
    book = np.asarray(cluster.state.book.head)
    gap = np.maximum(head[None, :] - book, 0).sum()
    emit(
        "corro_sync_gaps_count", "gauge",
        "total unapplied (node, actor) version gap", int(gap),
    )
    alive = int(cluster._alive.sum())
    emit(
        "corro_members_alive", "gauge",
        "nodes marked alive by the harness", alive,
    )

    # ---- reference-named series (agent/metrics.rs and friends) mapped
    # from the same underlying data, so dashboards built against the
    # reference's names point here unchanged.
    lasts = cluster.metrics_lasts()
    emit("corro_gossip_cluster_size", "gauge",
         "configured cluster size (corro.gossip.cluster_size)",
         cluster.cfg.num_nodes)
    emit("corro_gossip_members", "gauge",
         "live members (corro.gossip.members)", alive)
    lines.append("# HELP corro_gossip_member_states members by SWIM state "
                 "(corro.gossip.member.states)")
    lines.append("# TYPE corro_gossip_member_states gauge")
    suspects = int(lasts.get("swim_suspects", 0))
    downs = int(lasts.get("swim_down", 0))
    lines.append(f'corro_gossip_member_states{{state="alive"}} '
                 f"{max(alive - suspects - downs, 0)}")
    lines.append(f'corro_gossip_member_states{{state="suspect"}} {suspects}')
    lines.append(f'corro_gossip_member_states{{state="down"}} {downs}')
    emit("corro_gossip_config_max_transmissions", "gauge",
         "broadcast re-send budget (corro.gossip.config.max_transmissions)",
         cluster.cfg.max_transmissions)
    emit("corro_gossip_config_num_indirect_probes", "gauge",
         "SWIM indirect probes (corro.gossip.config.num_indirect_probes)",
         cluster.cfg.swim_indirect_probes)
    emit("corro_broadcast_pending_count", "gauge",
         "live pending-broadcast slots (corro.broadcast.pending.count)",
         int(lasts.get("pend_live", 0)))
    emit("corro_broadcast_recv_count_total", "counter",
         "broadcast datagrams delivered (corro.broadcast.recv.count)",
         int(totals.get("delivered", 0)))
    emit("corro_agent_changes_recv_total", "counter",
         "change messages received (corro.agent.changes.recv)",
         int(totals.get("delivered", 0)))
    emit("corro_agent_changes_in_queue", "gauge",
         "buffered partial versions (corro.agent.changes.in_queue)",
         int(cluster._partials))
    emit("corro_db_buffered_changes_rows_total", "gauge",
         "buffered seq-incomplete rows (corro.db.buffered.changes.rows)",
         int(cluster._partials))
    emit("corro_db_gaps_sum", "gauge",
         "unapplied version gap total (corro.db.gaps.sum)", int(gap))
    emit("corro_sync_client_needed", "gauge",
         "versions the cluster still needs (corro.sync.client.needed)",
         int(gap))
    emit("corro_sync_client_head", "gauge",
         "max version head written (corro.sync.client.head)",
         int(head.max()) if head.size else 0)
    emit("corro_sync_changes_sent_total", "counter",
         "versions served by sync (corro.sync.changes.sent; symmetric to "
         "recv in-process)", int(totals.get("sync_versions", 0)))
    emit("corro_sync_client_req_sent_total", "counter",
         "sync requests sent (corro.sync.client.req.sent)",
         int(totals.get("sync_requests", 0)))
    lines.append("# HELP corro_sync_client_member sync admissions by result "
                 "(corro.sync.client.member)")
    lines.append("# TYPE corro_sync_client_member counter")
    lines.append(f'corro_sync_client_member{{result="accepted"}} '
                 f"{int(totals.get('sync_pairs', 0))}")
    lines.append(f'corro_sync_client_member{{result="rejected"}} '
                 f"{int(totals.get('sync_rejections', 0))}")
    emit("corro_sync_empties_count_total", "counter",
         "cleared versions served as empties (corro.sync.empties.count)",
         int(totals.get("sync_empties", 0)))
    emit("corro_peer_datagram_sent_total", "counter",
         "gossip datagrams emitted (corro.peer.datagram.sent.total)",
         int(totals.get("msgs_sent", 0)))
    emit("corro_peer_datagram_recv_total", "counter",
         "gossip datagrams delivered (corro.peer.datagram.recv.total)",
         int(totals.get("delivered", 0)))
    emit("corro_peer_datagram_bytes_recv_total", "counter",
         "modeled datagram bytes received (corro.peer.datagram.bytes.recv; "
         "same wire model as corro_broadcast_recv_bytes_total)",
         bcast_bytes)
    emit("corro_peer_connection_accept_total", "counter",
         "sync connections admitted (corro.peer.connection.accept.total)",
         int(totals.get("sync_pairs", 0)))
    _ch = getattr(cluster, "channels", None)
    emit("corro_subs_changes_matched_count_total", "counter",
         "subscription events matched+queued "
         "(corro.subs.changes.matched.count)",
         int(_ch.snapshot().get("subs_events", {}).get("send", 0))
         if _ch is not None else 0)
    # modeled database footprint (corro.db.size analog): resident bytes of
    # the cluster state tensors
    try:
        from corro_sim.engine.sharding import state_bytes

        total_bytes, _ = state_bytes(cluster.cfg)
        emit("corro_db_size_bytes", "gauge",
             "modeled resident state bytes (corro.db.size analog)",
             int(total_bytes))
    except Exception:
        pass
    emit(
        "corro_subs_count", "gauge",
        "registered live-query matchers", len(cluster.subs),
    )
    stats = cluster.table_stats()
    lines.append(
        "# HELP corro_db_table_rows live rows per table (max over nodes)"
    )
    lines.append("# TYPE corro_db_table_rows gauge")
    for t, s in stats.items():
        rows = max(s["live_rows_per_node"], default=0)
        lines.append(f'corro_db_table_rows{{table="{t}"}} {rows}')
    pending = sum(len(q) for q in cluster._pending)
    emit(
        "corro_write_queue_pending", "gauge",
        "queued uncommitted changesets (SplitPool write queue analog)",
        pending,
    )

    # ---- per-channel queue health (corro.runtime.channel.*,
    # channel.rs:16-184): host-side queues count at their touch points;
    # the device-side gossip pending rings derive theirs from step
    # metrics (sends = enqueued chunks, recvs = emissions, failed =
    # overflow clobbers, depth = live slots after the last round).
    chans = getattr(cluster, "channels", None)
    if chans is not None:
        snap = chans.snapshot()
        lasts = getattr(cluster, "metrics_lasts", lambda: {})()
        snap["gossip_pending"] = {
            "send": int(
                totals.get("fresh_chunks", 0) + totals.get("writes", 0)
            ),
            "recv": int(totals.get("msgs_sent", 0)),
            "failed": int(lasts.get("queue_overflow", 0)),
            "depth": int(lasts.get("pend_live", 0)),
            "capacity": cluster.cfg.num_nodes * cluster.cfg.pend_slots,
            "send_delay_ewma_ms": 0.0,
        }
        series = [
            ("send", "corro_runtime_channel_send_count_total", "counter",
             "items enqueued per channel"),
            ("recv", "corro_runtime_channel_recv_count_total", "counter",
             "items dequeued per channel"),
            ("failed", "corro_runtime_channel_failed_send_count_total",
             "counter", "failed/overflowing sends per channel"),
            ("depth", "corro_runtime_channel_depth", "gauge",
             "current queued items per channel"),
            ("capacity", "corro_runtime_channel_max_capacity", "gauge",
             "channel capacity (0 = unbounded)"),
            ("send_delay_ewma_ms", "corro_runtime_channel_send_delay_ms",
             "gauge", "EWMA send delay per channel (only channels with "
             "observed samples; host queues are unbounded deques that "
             "never block)"),
        ]
        for field, name, kind, help_ in series:
            rows_out = []
            for cname in sorted(snap):
                if (
                    field == "send_delay_ewma_ms"
                    and not snap[cname].get("delay_samples")
                ):
                    continue  # never measured — don't fake a healthy 0
                rows_out.append(
                    f'{name}{{channel_name="{cname}"}} '
                    f"{snap[cname][field]}"
                )
            if rows_out:
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} {kind}")
                lines.extend(rows_out)

    # ---- per-table live rows per node (agent/metrics.rs per-table rows).
    # Per-node breakdown only below a cardinality cap: tables x N series
    # at simulator scale (10k+) would be a classic Prometheus explosion;
    # corro_db_table_rows (max over nodes) always covers the signal.
    if cluster.cfg.num_nodes <= 64:
        lines.append(
            "# HELP corro_db_table_rows_node live rows per table per node"
        )
        lines.append("# TYPE corro_db_table_rows_node gauge")
        for t, s in stats.items():
            for node, rows in enumerate(s["live_rows_per_node"]):
                lines.append(
                    f'corro_db_table_rows_node'
                    f'{{table="{t}",node="{node}"}} {rows}'
                )

    # ---- versioning / bookkeeping gauges (agent/metrics.rs:18-108)
    emit(
        "corro_db_versions_written", "gauge",
        "changeset versions written across all actors (log heads sum)",
        int(head.sum()),
    )
    emit(
        "corro_db_versions_applied", "gauge",
        "applied (node, actor) version count (booked heads sum)",
        int(book.sum()),
    )
    cleared = int(np.asarray(cluster.state.log.cleared).sum())
    emit(
        "corro_db_cleared_versions", "gauge",
        "versions fully superseded (empty changesets; compaction analog)",
        cleared,
    )
    emit(
        "corro_db_log_capacity", "gauge",
        "change-log ring capacity per actor", cluster.state.log.capacity,
    )

    # ---- gossip ring occupancy (broadcast buffer gauges analog)
    pend_tx = np.asarray(cluster.state.gossip.pend_tx)
    emit(
        "corro_broadcast_pending_slots", "gauge",
        "live pending-broadcast ring slots across the cluster",
        int((pend_tx > 0).sum()),
    )
    emit(
        "corro_broadcast_ring_capacity", "gauge",
        "pending-broadcast ring slots total", int(pend_tx.size),
    )

    # ---- value universe / layout (sqlite freelist + db size analog)
    uni = cluster.universe
    emit(
        "corro_db_interned_values", "gauge",
        "distinct interned SQLite values (rank universe size)", len(uni),
    )
    layout = cluster.layout
    used = sum(layout._used.values())
    cap = sum(c for _, c in layout._ranges.values())
    emit(
        "corro_db_row_slots_used", "gauge",
        "allocated row slots across tables", used,
    )
    emit(
        "corro_db_row_slots_capacity", "gauge",
        "row slot capacity across tables", cap,
    )

    # ---- lock registry (lock queue timing gauges, agent.rs:716-723)
    snap = cluster.locks.snapshot()
    emit(
        "corro_lock_registry_active", "gauge",
        "currently tracked lock acquisitions", len(snap),
    )

    # ---- subscription queue depths (channel capacity gauges analog)
    qdepth = sum(
        len(q) for qs in cluster._sub_queues.values() for q in qs
    )
    emit(
        "corro_subs_queued_events", "gauge",
        "events buffered in subscriber queues", qdepth,
    )
    lines.append(
        "# HELP corro_subs_change_id latest change id per matcher"
    )
    lines.append("# TYPE corro_subs_change_id gauge")
    for sub_id, m in cluster.subs._by_id.items():
        lines.append(
            f'corro_subs_change_id{{id="{sub_id}"}} {m.change_id}'
        )

    # ---- SWIM state breakdown (gossip/SWIM counts, broadcast/mod.rs)
    if cluster.cfg.swim_enabled:
        sw = cluster.state.swim
        if hasattr(sw, "member"):  # windowed O(N·K) belief state
            tracked = np.asarray(sw.member) >= 0
            status = np.asarray(sw.status) * tracked
            self_inc = np.asarray(sw.self_inc).max()
        else:
            status = np.asarray(sw.status)
            self_inc = np.asarray(sw.inc).diagonal().max()
        emit(
            "corro_swim_suspected_entries", "gauge",
            "suspect beliefs across all (observer, member) pairs",
            int((status == 1).sum()),
        )
        emit(
            "corro_swim_down_entries", "gauge",
            "down beliefs across all (observer, member) pairs",
            int((status >= 2).sum()),
        )
        emit(
            "corro_swim_incarnation_max", "gauge",
            "highest self-incarnation (refutation count)",
            int(self_inc),
        )

    # ---- flight recorder summary gauges (the durable per-round
    # timeline; full curve via GET /v1/flight / `corro-sim flight`)
    fl = getattr(cluster, "flight", None)
    if fl is not None:
        diag = fl.diagnostics()
        emit("corro_flight_rounds_recorded", "gauge",
             "rounds held in the flight-recorder ring",
             diag["rounds_recorded"])
        emit("corro_flight_events_recorded", "gauge",
             "annotation events held in the flight recorder",
             diag["events_recorded"])
        emit("corro_flight_converged_round", "gauge",
             "first round of the trailing gap==0 run (-1: not converged)",
             diag["converged_round"]
             if diag["converged_round"] is not None else -1)
        if diag["gap_half_life_rounds"] is not None:
            emit("corro_flight_gap_half_life_rounds", "gauge",
                 "gossip mixing rate: rounds for the gap to halve "
                 "(log-linear fit over the decay tail)",
                 diag["gap_half_life_rounds"])
        if diag["epidemic_window_rounds"] is not None:
            emit("corro_flight_epidemic_window_rounds", "gauge",
                 "rounds the gap spent above 10% of its peak",
                 diag["epidemic_window_rounds"])

    # ---- probe tracer + per-node lag observatory (obs/probes.py): the
    # /metrics face of the gossip provenance — full infection trees ride
    # GET /v1/probes / `corro-sim probes`. The lag observatory renders
    # with probes off too (only its sync-age column needs the tracer).
    if hasattr(cluster, "node_lag"):
        lag = cluster.node_lag()
        emit("corro_node_lag_rows_behind_sum", "gauge",
             "versions written cluster-wide not yet applied, summed over "
             "live nodes", lag["rows_behind_total"])
        emit("corro_node_lag_rows_behind_max", "gauge",
             "worst live node's unapplied-version backlog",
             lag["rows_behind_max"])
        emit("corro_node_lag_nodes_lagging", "gauge",
             "live nodes with a nonzero unapplied-version backlog",
             lag["lagging_nodes"])
        if lag["last_sync_age_max"] is not None:
            emit("corro_node_lag_last_sync_age_max", "gauge",
                 "rounds since the stalest live node took part in an "
                 "anti-entropy sweep", lag["last_sync_age_max"])
        if lag["top_laggards"]:
            lines.append("# HELP corro_node_lag_rows_behind top-k "
                         "laggards: unapplied-version backlog per node")
            lines.append("# TYPE corro_node_lag_rows_behind gauge")
            for row in lag["top_laggards"]:
                lines.append(
                    f'corro_node_lag_rows_behind{{node="{row["node"]}"}} '
                    f'{row["rows_behind"]}'
                )
            if "suspected_by" in lag["top_laggards"][0]:
                lines.append("# HELP corro_node_lag_suspected_by top-k "
                             "laggards: SWIM observers suspecting the node")
                lines.append("# TYPE corro_node_lag_suspected_by gauge")
                for row in lag["top_laggards"]:
                    lines.append(
                        f'corro_node_lag_suspected_by'
                        f'{{node="{row["node"]}"}} {row["suspected_by"]}'
                    )
            if "last_sync_age" in lag["top_laggards"][0]:
                lines.append("# HELP corro_node_lag_last_sync_age top-k "
                             "laggards: rounds since the node's last "
                             "anti-entropy sweep (-1 = never)")
                lines.append("# TYPE corro_node_lag_last_sync_age gauge")
                for row in lag["top_laggards"]:
                    lines.append(
                        f'corro_node_lag_last_sync_age'
                        f'{{node="{row["node"]}"}} {row["last_sync_age"]}'
                    )
    tr = cluster.probe_trace() if hasattr(cluster, "probe_trace") else None
    if tr is not None:
        emit("corro_probe_count", "gauge",
             "versions tracked by the on-device probe tracer",
             tr.num_probes)
        fams = (
            ("coverage", "corro_probe_coverage",
             "fraction of the cluster holding the probe's version"),
            ("infected", "corro_probe_infected",
             "nodes holding the probe's version"),
            ("dup_deliveries", "corro_probe_dup_total",
             "delivered probe chunks that landed on already-infected "
             "nodes (redundancy)"),
            ("delivery_round_p50", "corro_probe_delivery_round_p50",
             "median delivery round relative to the origin commit"),
            ("delivery_round_p99", "corro_probe_delivery_round_p99",
             "p99 delivery round relative to the origin commit"),
            ("hop_max", "corro_probe_hop_max",
             "longest gossip path from the origin, in hops"),
            ("redundancy_ratio", "corro_probe_redundancy_ratio",
             "duplicate deliveries per non-origin infection"),
        )
        summaries = [tr.summary(k) for k in range(tr.num_probes)]
        for field, name, help_ in fams:
            rows_out = [
                f'{name}{{probe="{s["probe"]}"}} {s[field]}'
                for s in summaries if s[field] is not None
            ]
            if rows_out:
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} gauge")
                lines.extend(rows_out)

    # ---- tracing (tokio-metrics / runtime introspection analog)
    from corro_sim.utils.tracing import tracer as _tracer

    spans = _tracer.recent(10**9)
    emit(
        "corro_trace_spans_buffered", "gauge",
        "finished spans held in the tracer ring", len(spans),
    )
    if spans:
        emit(
            "corro_trace_span_max_ms", "gauge",
            "slowest buffered span duration (ms)",
            round(max(s.duration for s in spans) * 1000, 3),
        )

    # ---- transport path stats (corro.transport.*, transport.rs +
    # quinn path stats): the sim's wire is the delivery pipeline — sends,
    # deliveries, losses (sends into dead/partitioned links), in-flight
    # occupancy, and the modeled byte volume. frame_tx/rx split by the
    # protocol that produced the lane, like the reference's per-frame-type
    # gauges.
    sent = int(totals.get("msgs_sent", 0))
    delivered = int(totals.get("delivered", 0))
    lost = max(sent - delivered, 0)
    sync_pairs_t = int(totals.get("sync_pairs", 0))
    lines.append("# HELP corro_transport_frame_tx frames sent by type "
                 "(corro.transport.frame_tx)")
    lines.append("# TYPE corro_transport_frame_tx gauge")
    lines.append(f'corro_transport_frame_tx{{frame_type="changes"}} {sent}')
    lines.append(
        f'corro_transport_frame_tx{{frame_type="sync"}} {sync_pairs_t}'
    )
    lines.append("# HELP corro_transport_frame_rx frames received by type "
                 "(corro.transport.frame_rx)")
    lines.append("# TYPE corro_transport_frame_rx gauge")
    lines.append(
        f'corro_transport_frame_rx{{frame_type="changes"}} {delivered}'
    )
    lines.append(
        f'corro_transport_frame_rx{{frame_type="sync"}} {sync_pairs_t}'
    )
    emit("corro_transport_connections", "gauge",
         "sync connections granted in the last sweep "
         "(corro.transport.connections)",
         int(lasts.get("sync_pairs", 0)))
    emit("corro_transport_connect_errors_total", "counter",
         "sync admissions rejected by the server semaphore "
         "(corro.transport.connect.errors)",
         int(totals.get("sync_rejections", 0)))
    emit("corro_transport_path_sent_packets", "gauge",
         "gossip datagrams emitted (corro.transport.path.sent_packets)",
         sent)
    emit("corro_transport_path_lost_packets", "gauge",
         "sends into dead/partitioned links "
         "(corro.transport.path.lost_packets)", lost)
    emit("corro_transport_path_lost_bytes", "gauge",
         "modeled bytes of lost sends (corro.transport.path.lost_bytes)",
         lost * CHUNK_HEADER_BYTES)
    emit("corro_transport_path_congestion_events", "gauge",
         "pending-ring overflow clobbers "
         "(corro.transport.path.congestion_events)",
         int(lasts.get("queue_overflow", 0)))
    emit("corro_transport_path_cwnd", "gauge",
         "per-round emission budget, lanes "
         "(corro.transport.path.cwnd analog)",
         cluster.cfg.num_nodes
         * (cluster.cfg.emit_slots or cluster.cfg.pend_slots)
         * cluster.cfg.fanout)
    emit("corro_transport_path_black_holes_detected", "gauge",
         "nodes believed up that ground truth says are unreachable "
         "(corro.transport.path.black_holes_detected analog)",
         int(lasts.get("swim_down", 0)))
    udp_tx_b = sent * CHUNK_HEADER_BYTES + int(
        totals.get("cells_written", 0)
    ) * CHANGE_WIRE_BYTES
    for d, dat, byt in (
        ("tx", sent, udp_tx_b),
        ("rx", delivered, bcast_bytes),
    ):
        emit(f"corro_transport_udp_{d}_datagrams", "gauge",
             f"modeled UDP datagrams {d} (corro.transport.udp_{d})", dat)
        emit(f"corro_transport_udp_{d}_bytes", "gauge",
             f"modeled UDP bytes {d}", byt)
        emit(f"corro_transport_udp_{d}_transmits", "gauge",
             f"modeled UDP transmit ops {d} (batched sends count once)",
             dat)
    # PLPMTUD probes: the transport runs on modeled links with a fixed
    # MTU — the probe machinery exists in the reference's quinn stack
    # only; emitted as explicit zeros so dashboards resolve.
    emit("corro_transport_path_sent_plpmtud_probes", "gauge",
         "path-MTU probes sent (no analog: fixed-MTU modeled links)", 0)
    emit("corro_transport_path_lost_plpmtud_probes", "gauge",
         "path-MTU probes lost (no analog: fixed-MTU modeled links)", 0)

    # ---- SWIM notification counters (corro.swim.notification, foca
    # event granularity): transitions accumulated per round by the
    # metrics fold (positive deltas of the belief-state gauges).
    lines.append("# HELP corro_swim_notification_total membership "
                 "notifications by event (corro.swim.notification)")
    lines.append("# TYPE corro_swim_notification_total counter")
    lines.append(
        f'corro_swim_notification_total{{event="probe_failed"}} '
        f"{int(totals.get('swim_probe_failures', 0))}"
    )
    lines.append(
        f'corro_swim_notification_total{{event="member_down"}} '
        f"{int(totals.get('swim_down_events', 0))}"
    )
    lines.append(
        f'corro_swim_notification_total{{event="member_suspect"}} '
        f"{int(totals.get('swim_suspect_events', 0))}"
    )
    lines.append(
        f'corro_swim_notification_total{{event="member_up"}} '
        f"{int(totals.get('swim_up_events', 0))}"
    )

    # ---- host-runtime introspection (corro.tokio.* analogs; the
    # reference reports tokio worker stats, command/agent.rs:122-204).
    # This runtime is a single tick thread + API handler threads — the
    # honest analogs are below; min/max/total collapse to the same value
    # where the stat is process-global. Work-stealing stats have no
    # analog (no stealing scheduler) and are omitted — see
    # doc/metrics_parity.md.
    import threading as _threading

    emit("corro_tokio_workers_count", "gauge",
         "live threads (tick + API handlers; corro.tokio.workers_count "
         "analog)", _threading.active_count())
    try:
        import resource as _resource

        ru = _resource.getrusage(_resource.RUSAGE_SELF)
        emit("corro_tokio_total_busy_seconds", "gauge",
             "process CPU seconds (corro.tokio.*_busy_seconds analog)",
             round(ru.ru_utime + ru.ru_stime, 3))
    except Exception:
        pass
    rounds_t = int(totals.get("rounds", 0))
    emit("corro_tokio_total_polls_count", "gauge",
         "device dispatches (rounds ticked; corro.tokio.total_polls_count "
         "analog)", rounds_t)
    emit("corro_tokio_total_park_count", "gauge",
         "tick-loop iterations (corro.tokio.total_park_count analog)",
         rounds_t)
    emit("corro_tokio_total_noop_count", "gauge",
         "rounds with no local writes (corro.tokio.total_noop_count "
         "analog)", max(rounds_t - int(totals.get("writes", 0)), 0))
    emit("corro_tokio_total_local_queue_depth", "gauge",
         "queued changesets across write queues "
         "(corro.tokio.total_local_queue_depth analog)", pending)
    emit("corro_tokio_injection_queue_depth", "gauge",
         "events buffered for subscribers "
         "(corro.tokio.injection_queue_depth analog)", qdepth)
    emit("corro_tokio_total_local_schedule_count", "gauge",
         "changesets enqueued (corro.tokio.total_local_schedule_count "
         "analog)",
         int(_ch.snapshot().get("write_queue", {}).get("send", 0))
         if _ch is not None else 0)
    emit("corro_tokio_num_remote_schedules", "gauge",
         "cross-thread event deliveries "
         "(corro.tokio.num_remote_schedules analog)",
         int(_ch.snapshot().get("subs_events", {}).get("send", 0))
         if _ch is not None else 0)
    emit("corro_tokio_total_overflow_count", "gauge",
         "bounded-queue overflows (corro.tokio.total_overflow_count "
         "analog)", int(totals.get("queue_overflow", 0)))
    emit("corro_tokio_io_driver_ready_count", "gauge",
         "API requests served (corro.tokio.io_driver_ready_count analog)",
         int(getattr(cluster, "_api_requests", 0)))
    emit("corro_tokio_budget_forced_yield_count", "gauge",
         "chunked tick dispatches "
         "(corro.tokio.budget_forced_yield_count analog)",
         int(getattr(cluster, "_chunk_dispatches", 0)))

    # ---- bucketed histograms (VERDICT r4 #7: real histograms, not EWMA).
    # The cluster-scoped registry first (tick stages, queue waits, lock
    # waits, connect times); the process-global one carries only
    # cluster-less instrumentation (consul client).
    ch_reg = getattr(cluster, "histograms", None)
    if ch_reg is not None and ch_reg is not histograms:
        lines.extend(ch_reg.render())
    lines.extend(histograms.render())
    lines.extend(counters.render())
    lines.extend(gauges.render())
    return "\n".join(lines) + "\n"
