"""Prometheus text exposition for a LiveCluster.

The reference installs a `metrics` Prometheus exporter with ~120 series
under ``corro.*`` (``corrosion/src/command/agent.rs:95-117``; inventory in
SURVEY §5). The simulator's per-round metrics come out of the jitted step
as a dict; this module renders their running totals plus live gauges in
the exposition format so the same dashboards/scrapers point here.

Metric names follow the reference's (dots become underscores, the
Prometheus exporter does the same mangling): e.g.
``corro_broadcast_recv_count`` ← `corro.broadcast.recv.count`.
"""

from __future__ import annotations

import numpy as np

# step-metric key → (prometheus name, type, help)
_SERIES = {
    "writes": (
        "corro_changes_committed_total", "counter",
        "local versions committed (make_broadcastable_changes analog)",
    ),
    "fresh": (
        "corro_changes_applied_total", "counter",
        "remote broadcast changes applied (process_multiple_changes analog)",
    ),
    "sync_versions": (
        "corro_sync_changes_recv_total", "counter",
        "versions repaired by anti-entropy sync",
    ),
    "dropped_window": (
        "corro_broadcast_dropped_total", "counter",
        "broadcasts dropped by bounded inboxes (handlers.rs:866-884 analog)",
    ),
    "deletes": (
        "corro_deletes_applied_total", "counter",
        "causal-length delete merges applied",
    ),
    "rounds": (
        "corro_sim_rounds_total", "counter",
        "simulation rounds executed",
    ),
}


def render_prometheus(cluster) -> str:
    lines: list[str] = []

    def emit(name, kind, help_, value, labels=""):
        lines.append(f"# HELP {name} {help_}")
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name}{labels} {value}")

    totals = cluster.metrics_totals()
    for key, (name, kind, help_) in _SERIES.items():
        if key in totals:
            emit(name, kind, help_, int(totals[key]))
    # remaining step metrics, generically
    for key, v in sorted(totals.items()):
        if key not in _SERIES:
            emit(
                f"corro_sim_{key}_total", "counter",
                f"sim step metric {key}", v,
            )

    # live gauges (agent/metrics.rs:18-108 analog: rows, gaps, members)
    head = np.asarray(cluster.state.log.head)
    book = np.asarray(cluster.state.book.head)
    gap = np.maximum(head[None, :] - book, 0).sum()
    emit(
        "corro_sync_gaps_count", "gauge",
        "total unapplied (node, actor) version gap", int(gap),
    )
    alive = int(cluster._alive.sum())
    emit(
        "corro_members_alive", "gauge",
        "nodes marked alive by the harness", alive,
    )
    emit(
        "corro_subs_count", "gauge",
        "registered live-query matchers", len(cluster.subs),
    )
    stats = cluster.table_stats()
    lines.append(
        "# HELP corro_db_table_rows live rows per table (max over nodes)"
    )
    lines.append("# TYPE corro_db_table_rows gauge")
    for t, s in stats.items():
        rows = max(s["live_rows_per_node"], default=0)
        lines.append(f'corro_db_table_rows{{table="{t}"}} {rows}')
    pending = sum(len(q) for q in cluster._pending)
    emit(
        "corro_write_queue_pending", "gauge",
        "queued uncommitted changesets (SplitPool write queue analog)",
        pending,
    )
    return "\n".join(lines) + "\n"
