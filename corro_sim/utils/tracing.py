"""Tracing: spans + W3C trace-context propagation (SURVEY §5).

The reference instruments everything with ``tracing`` spans, exports via
OpenTelemetry (``corrosion/src/main.rs:72-104``), carries W3C
traceparent/tracestate across the sync protocol
(``SyncTraceContextV1``, ``corro-types/src/sync.rs:33-67``), and warns
when a hot-loop branch runs long (``broadcast/mod.rs:317-321``).

The TPU-native equivalents here:

- :class:`Tracer` — a process-local span recorder: bounded ring of
  finished spans (name, ids, wall times, attributes), queryable through
  the admin socket (``corro-sim traces``) the way the reference's spans
  flow to an OTLP collector;
- :func:`parse_traceparent` / :meth:`TraceContext.to_traceparent` — the
  W3C header codec; the HTTP API extracts an incoming ``traceparent``
  and parents its request span under it, so a caller's distributed trace
  continues through the cluster exactly as the reference's does through
  ``BiPayloadV1::SyncStart``;
- slow-span warnings — spans longer than ``slow_warn_s`` log a warning,
  the foca-loop watchdog analog.
"""

from __future__ import annotations

import collections
import contextlib
import logging
import os
import threading
import time

log = logging.getLogger("corro_sim.tracing")

_TRACEPARENT_LEN = 55  # 00-<32 hex>-<16 hex>-<2 hex>


class TraceContext:
    __slots__ = ("trace_id", "span_id", "flags")

    def __init__(self, trace_id: str, span_id: str, flags: int = 1):
        self.trace_id = trace_id
        self.span_id = span_id
        self.flags = flags

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags:02x}"

    def __repr__(self):
        return f"TraceContext({self.to_traceparent()})"


def parse_traceparent(header: str | None) -> TraceContext | None:
    """W3C traceparent: ``00-{trace_id:32x}-{span_id:16x}-{flags:02x}``.
    Malformed headers are ignored (the spec says restart the trace)."""
    if not header or len(header) != _TRACEPARENT_LEN:
        return None
    parts = header.split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(trace_id) != 32 or len(span_id) != 16 or version == "ff":
        return None
    try:
        int(version, 16), int(trace_id, 16), int(span_id, 16)
        f = int(flags, 16)
    except ValueError:
        return None
    if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
        return None
    return TraceContext(trace_id, span_id, f)


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


class Span:
    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start", "duration",
        "attrs",
    )

    def __init__(self, name, trace_id, span_id, parent_id, start,
                 duration, attrs):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.duration = duration
        self.attrs = attrs

    def as_json(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "duration_ms": round(self.duration * 1000, 3),
            "attrs": self.attrs,
        }

    @property
    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)


class Tracer:
    """Bounded recorder of finished spans; thread-safe."""

    def __init__(self, capacity: int = 2048, slow_warn_s: float = 1.0):
        self.capacity = capacity
        self.slow_warn_s = slow_warn_s
        # deque(maxlen=...) evicts in O(1) per append; the old list +
        # del-slicing ring paid an O(capacity) shift on every overflow —
        # this sits on the hot instrumentation path (every span end)
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=capacity
        )
        self._lock = threading.Lock()
        self._local = threading.local()

    # --------------------------------------------------------- recording
    @contextlib.contextmanager
    def span(self, name: str, parent: TraceContext | None = None,
             slow_warn: bool = True, **attrs):
        """Context manager recording one span. Child spans inside inherit
        the current span's context unless ``parent`` overrides it.
        ``slow_warn=False`` opts out of the slow-span watchdog — for
        spans that are *expected* to run long (XLA compiles), where the
        warning would be noise rather than signal."""
        cur = getattr(self._local, "ctx", None)
        if parent is None:
            parent = cur
        trace_id = parent.trace_id if parent else _new_id(16)
        ctx = TraceContext(trace_id, _new_id(8))
        self._local.ctx = ctx
        t0 = time.time()
        p0 = time.perf_counter()
        try:
            yield ctx
        finally:
            dur = time.perf_counter() - p0
            self._local.ctx = cur
            sp = Span(
                name, trace_id, ctx.span_id,
                parent.span_id if parent else None, t0, dur, attrs,
            )
            with self._lock:
                self._spans.append(sp)  # maxlen evicts the oldest
            if slow_warn and dur > self.slow_warn_s:
                # foca-loop slow-branch watchdog (broadcast/mod.rs:317-321)
                log.warning("slow span %r took %.3fs", name, dur)

    def current(self) -> TraceContext | None:
        return getattr(self._local, "ctx", None)

    # ----------------------------------------------------------- reading
    def recent(self, n: int = 100, name: str | None = None) -> list[Span]:
        with self._lock:
            spans = list(self._spans)
        if name is not None:
            spans = [s for s in spans if s.name == name]
        return spans[-n:]

    def trace(self, trace_id: str) -> list[Span]:
        with self._lock:
            return [s for s in self._spans if s.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()


# The process-default tracer (the reference's global tracing subscriber).
tracer = Tracer()
