"""Host-runtime primitives: shutdown, counted tasks, backoff.

The reference builds its agent around three small crates: ``tripwire``
(a watch-channel future tripped by SIGTERM/SIGINT or handle drop,
``crates/tripwire/src/tripwire.rs:20-175``), ``spawn`` (a global counter of
outstanding tasks + a drain barrier, ``crates/spawn/src/lib.rs:13-45``), and
``backoff`` (iterator-style exponential backoff with a timeout range,
``crates/backoff/src/lib.rs``). The TPU framework's host side — the API
server, admin socket, template watcher, consul sync daemon — needs the same
discipline, but over threads instead of tokio tasks: device work is
dispatched from one driver thread; everything else is plain blocking I/O.
"""

from __future__ import annotations

import itertools
import random
import signal
import threading
import time


class Tripwire:
    """Cooperative shutdown signal shared by all host-side loops.

    ``tripped`` flips exactly once; waiters unblock immediately. Optionally
    wired to SIGTERM/SIGINT like the reference's ``Tripwire::new_signals``.
    """

    def __init__(self):
        self._event = threading.Event()
        self._callbacks: list = []
        self._lock = threading.Lock()

    @classmethod
    def new_signals(cls) -> "Tripwire":
        tw = cls()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, lambda *_: tw.trip())
            except ValueError:
                # not on the main thread (tests) — cooperative trip only
                break
        return tw

    @property
    def tripped(self) -> bool:
        return self._event.is_set()

    def trip(self) -> None:
        with self._lock:
            already = self._event.is_set()
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        if not already:
            for cb in callbacks:
                cb()

    def on_trip(self, callback) -> None:
        """Run ``callback`` once when tripped (immediately if already)."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(callback)
                return
        callback()

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)

    def sleep(self, seconds: float) -> bool:
        """Preemptible sleep: returns True if interrupted by the trip —
        the ``PreemptibleFutureExt`` analog (tripwire/src/preempt.rs)."""
        return self._event.wait(seconds)


# --- counted task spawn (crates/spawn analog) ---------------------------

_PENDING = 0
_PENDING_LOCK = threading.Lock()
_PENDING_ZERO = threading.Condition(_PENDING_LOCK)


def atomic_json_dump(path: str, obj, indent: int | None = None) -> bool:
    """Write-then-rename JSON dump so readers never see a torn file.

    The crash-path artifact idiom (bench progress trails, soak partial
    artifacts): these files exist precisely because the process may die,
    so a second kill mid-write must not corrupt them. Never raises —
    returns False on OSError (an artifact write must not kill the run
    it documents)."""
    import json
    import os

    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=indent)
        os.replace(tmp, path)
        return True
    except OSError:
        return False


def start_async_fetch(*bufs) -> None:
    """Begin device→host copies without blocking (resolved later by
    ``np.asarray``) — the chunk pipeline's async-fetch half
    (doc/performance.md): host work rides under the transfer. Duck-typed
    over jax arrays; platforms without per-array async copy just resolve
    everything at the blocking read, same semantics."""
    for b in bufs:
        try:
            b.copy_to_host_async()
        except (AttributeError, RuntimeError):
            pass


def spawn_counted(fn, *args, name: str | None = None, **kwargs) -> threading.Thread:
    """Run ``fn`` on a daemon thread tracked by the global pending counter."""
    global _PENDING
    with _PENDING_LOCK:
        _PENDING += 1

    def run():
        global _PENDING
        try:
            fn(*args, **kwargs)
        finally:
            with _PENDING_LOCK:
                _PENDING -= 1
                if _PENDING == 0:
                    _PENDING_ZERO.notify_all()

    t = threading.Thread(target=run, daemon=True, name=name or fn.__name__)
    t.start()
    return t


def pending_handles() -> int:
    with _PENDING_LOCK:
        return _PENDING


def wait_for_all_pending_handles(timeout: float | None = None) -> bool:
    """Drain-on-shutdown barrier: block until every counted task finished."""
    deadline = None if timeout is None else time.monotonic() + timeout
    with _PENDING_LOCK:
        while _PENDING > 0:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
            _PENDING_ZERO.wait(remaining)
    return True


class Backoff:
    """Iterator of sleep durations: exponential within [lo, hi], jittered.

    ``iter(Backoff(1, 15))`` yields 1, 2, 4, 8, 15, 15, … like the
    reference's sync_loop cadence (1 s → 15 s, ``agent/util.rs:345-348``).
    """

    def __init__(self, lo: float, hi: float, factor: float = 2.0,
                 jitter: float = 0.0, max_retries: int | None = None):
        assert lo > 0 and hi >= lo and factor > 1.0
        self.lo, self.hi, self.factor = lo, hi, factor
        self.jitter = jitter
        self.max_retries = max_retries

    def __iter__(self):
        it = (
            min(self.lo * self.factor**i, self.hi)
            for i in itertools.count()
        )
        if self.max_retries is not None:
            it = itertools.islice(it, self.max_retries)
        if self.jitter:
            it = (d * (1.0 + random.uniform(-self.jitter, self.jitter))
                  for d in it)
        return it

    def reset_after(self, delay: float) -> "BackoffClock":
        return BackoffClock(self, delay)


class BackoffClock:
    """Stateful view: ``next_delay()`` advances; quiet periods reset.

    Mirrors how the reference resets sync backoff once a round succeeds
    quickly.
    """

    def __init__(self, backoff: Backoff, reset_after: float):
        self._b = backoff
        self._reset_after = reset_after
        self._it = iter(backoff)
        self._last = time.monotonic()

    def next_delay(self) -> float:
        now = time.monotonic()
        if now - self._last > self._reset_after:
            self._it = iter(self._b)
        self._last = now
        return next(self._it)


class LockRegistry:
    """Labelled lock tracking — deadlock *diagnosis*, not prevention.

    Every acquisition through :meth:`tracked` is registered with a label,
    kind and start time; ``snapshot()`` powers the admin ``locks --top N``
    command the way the reference's ``LockRegistry`` does
    (``corro-types/src/agent.rs:890-1099``, dumped via corro-admin).
    """

    def __init__(self, histograms=None):
        self.histograms = histograms  # cluster-scoped wait histograms
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self._active: dict[int, dict] = {}

    def tracked(self, inner_lock, label: str, kind: str = "lock"):
        return _TrackedAcquire(self, inner_lock, label, kind)

    def _register(self, label: str, kind: str, state: str) -> int:
        with self._lock:
            lid = next(self._ids)
            self._active[lid] = {
                "id": lid,
                "label": label,
                "kind": kind,
                "state": state,
                "started": time.monotonic(),
            }
            return lid

    def _set_state(self, lid: int, state: str) -> None:
        with self._lock:
            if lid in self._active:
                self._active[lid]["state"] = state

    def _unregister(self, lid: int) -> None:
        with self._lock:
            self._active.pop(lid, None)

    def snapshot(self, top: int | None = None) -> list[dict]:
        now = time.monotonic()
        with self._lock:
            rows = [
                {**e, "held_for": now - e["started"]}
                for e in self._active.values()
            ]
        rows.sort(key=lambda e: -e["held_for"])
        return rows[:top] if top else rows


class _TrackedAcquire:
    def __init__(self, registry: LockRegistry, lock, label: str, kind: str):
        self._reg = registry
        self._lock = lock
        self._label = label
        self._kind = kind
        self._lid = None

    def __enter__(self):
        self._lid = self._reg._register(self._label, self._kind, "acquiring")
        t0 = time.perf_counter()
        self._lock.acquire()
        wait = time.perf_counter() - t0
        self._reg._set_state(self._lid, "locked")
        # lock-wait histograms (reference: write-permit acquisition and
        # pool queue times, corro.sqlite.*.seconds) — the cluster-scoped
        # registry when the LockRegistry belongs to a cluster
        if self._reg.histograms is not None:
            histograms = self._reg.histograms
        else:
            from corro_sim.utils.metrics import histograms

        histograms.observe(
            "corro_sqlite_write_permit_acquisition_seconds"
            if self._kind == "write"
            else "corro_sqlite_pool_queue_seconds",
            wait,
            help_=(
                "write-lock acquisition wait "
                "(corro.sqlite.write_permit.acquisition.seconds)"
                if self._kind == "write"
                else "read-path lock queue wait "
                     "(corro.sqlite.pool.queue.seconds)"
            ),
        )
        return self

    def __exit__(self, *exc):
        self._lock.release()
        self._reg._unregister(self._lid)
        return False
