"""Order-preserving rank translation for LiveUniverse respacing.

When the live value universe re-spaces (new values interleave the total
order), every tensor/snapshot/queued-cell holding old ranks must be
re-labelled. One implementation serves all three holders (state tensors,
matcher snapshots, pending changesets) so the semantics cannot diverge:
unknown/sentinel ranks (anything not in ``old``, e.g. the NEG fill) pass
through unchanged.
"""

from __future__ import annotations

import functools

import numpy as np

# Sorted-pad sentinel: larger than any real rank ((i+1)*GAP < 2^31), so a
# padded slot can never win the `o[idx] == values` match for a real rank.
_PAD = np.int32(2**31 - 1)


def translate_ranks(values, old, new, xp=np):
    """Map each element of `values` from old-rank space to new-rank space.

    ``xp`` is the array namespace (numpy or jax.numpy); `values` may be any
    integer dtype/shape. Elements not present in ``old`` are unchanged.

    The jax path pads the rank tables to a power-of-two bucket and runs a
    module-level jitted kernel: every remap grows the tables, and unbucketed
    shapes would force a fresh XLA compile per remap per tensor (~0.6 s each
    through the TPU tunnel — the dominant cost of a live remap otherwise).
    """
    if len(old) == 0:
        return values
    if xp is np:
        o = np.asarray(old, values.dtype)
        nw = np.asarray(new, values.dtype)
        idx = np.clip(np.searchsorted(o, values), 0, len(old) - 1)
        found = (values >= 0) & (o[idx] == values)
        return np.where(found, nw[idx], values)
    n = len(old)
    # floor of 4096: one compiled kernel serves every universe up to 4k
    # values (a warmed-up kernel stays warm as the universe grows)
    bucket = max(4096, 1 << (n - 1).bit_length())
    o = np.full((bucket,), _PAD, np.int32)
    o[:n] = old
    nw = np.full((bucket,), _PAD, np.int32)
    nw[:n] = new
    return _translate_jit(values, xp.asarray(o), xp.asarray(nw))


@functools.cache
def _get_translate_jit():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(values, o, nw):
        idx = jnp.clip(jnp.searchsorted(o, values), 0, o.shape[0] - 1)
        found = (values >= 0) & (o[idx] == values)
        return jnp.where(found, nw[idx], values)

    return kernel


def _translate_jit(values, o, nw):
    return _get_translate_jit()(values, o, nw)


def rank_map(old, new) -> dict:
    """Python-side translation dict for scalar rank fields."""
    return dict(zip(old, new))
