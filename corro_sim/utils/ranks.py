"""Order-preserving rank translation for LiveUniverse respacing.

When the live value universe re-spaces (new values interleave the total
order), every tensor/snapshot/queued-cell holding old ranks must be
re-labelled. One implementation serves all three holders (state tensors,
matcher snapshots, pending changesets) so the semantics cannot diverge:
unknown/sentinel ranks (anything not in ``old``, e.g. the NEG fill) pass
through unchanged.
"""

from __future__ import annotations

import numpy as np


def translate_ranks(values, old, new, xp=np):
    """Map each element of `values` from old-rank space to new-rank space.

    ``xp`` is the array namespace (numpy or jax.numpy); `values` may be any
    integer dtype/shape. Elements not present in ``old`` are unchanged.
    """
    if len(old) == 0:
        return values
    o = xp.asarray(old, values.dtype)
    nw = xp.asarray(new, values.dtype)
    idx = xp.clip(xp.searchsorted(o, values), 0, len(old) - 1)
    found = (values >= 0) & (o[idx] == values)
    return xp.where(found, nw[idx], values)


def rank_map(old, new) -> dict:
    """Python-side translation dict for scalar rank fields."""
    return dict(zip(old, new))
