"""Consul service-discovery sync — `consul-client` + `corrosion consul sync`.

The reference ships a small hyper client for the local Consul agent
(``crates/consul-client/src/lib.rs``: ``AgentService``/``AgentCheck``,
``/v1/agent/services`` + ``/v1/agent/checks``) and a sync daemon
(``corrosion/src/command/consul/sync.rs``) that polls every second,
hash-diffs each service/check against locally persisted hash tables
(``__corro_consul_services``/``__corro_consul_checks``, ``sync.rs:58-141``)
and, per changed entity, transactionally upserts into the replicated
``consul_services``/``consul_checks`` tables — with the ``app_id``
extracted from service meta — and deletes entities that disappeared
(``sync.rs:388-470``).

Same pipeline here: :class:`ConsulAgentClient` speaks the agent HTTP API
(or reads a JSON file — the test/devcluster source), :class:`ConsulSync`
keeps the hash state (persisted to a sidecar JSON, playing the role of
the reference's local non-replicated tables) and writes through the
framework's transaction API.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import time
import urllib.request


def hash_service(svc: dict) -> str:
    """Stable content hash of a service (``hash_service``, ``sync.rs:354``:
    seahash over the struct; any stable digest serves the diff)."""
    return _digest(
        [
            svc.get("ID", ""), svc.get("Service", ""),
            sorted(svc.get("Tags") or []),
            sorted((svc.get("Meta") or {}).items()),
            svc.get("Port", 0), svc.get("Address", ""),
        ]
    )


def hash_check(chk: dict) -> str:
    """``hash_check`` (``sync.rs:360``) — deliberately excludes ``output``
    like the reference (field order in the struct hash stops before the
    free-text output so flapping check output does not dirty the hash)."""
    return _digest(
        [
            chk.get("CheckID", ""), chk.get("Name", ""),
            chk.get("Status", ""), chk.get("ServiceID", ""),
            chk.get("ServiceName", ""),
        ]
    )


def _digest(obj) -> str:
    raw = json.dumps(obj, sort_keys=True, separators=(",", ":"))
    return hashlib.blake2b(raw.encode(), digest_size=8).hexdigest()


def app_id_of(svc: dict):
    """``app_id`` from service meta (``sync.rs:407-433`` extracts it into
    its own column for the Fly.io schema)."""
    meta = svc.get("Meta") or {}
    try:
        return int(meta["app_id"])
    except (KeyError, TypeError, ValueError):
        return None


class ConsulAgentClient:
    """`consul-client` analog: GET /v1/agent/services and /v1/agent/checks
    against a local Consul agent."""

    def __init__(self, base_url: str = "http://127.0.0.1:8500",
                 timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _get(self, path: str):
        import time as _time

        from corro_sim.utils.metrics import histograms as _histograms

        t0 = _time.perf_counter()
        try:
            with urllib.request.urlopen(
                self.base_url + path, timeout=self.timeout
            ) as r:
                out = json.loads(r.read())
        except Exception:
            from corro_sim.utils.metrics import counters as _counters

            _counters.inc(
                "corro_consul_consul_response_errors_total",
                help_="consul API errors "
                      "(corro_consul.consul.response.errors)",
            )
            raise
        _histograms.observe(
            "corro_consul_consul_response_time_seconds",
            _time.perf_counter() - t0,
            help_="consul API response time "
                  "(corro_consul.consul.response.time.seconds)",
        )
        return out

    def agent_services(self) -> dict:
        return self._get("/v1/agent/services")

    def agent_checks(self) -> dict:
        return self._get("/v1/agent/checks")


class FileConsulSource:
    """Test/devcluster source: the agent state as a JSON file
    ``{"services": {...}, "checks": {...}}`` (same shapes as the HTTP
    API). Lets the sync daemon run with zero external processes."""

    def __init__(self, path):
        self.path = str(path)

    def _load(self) -> dict:
        with open(self.path) as f:
            return json.load(f)

    def agent_services(self) -> dict:
        return self._load().get("services", {})

    def agent_checks(self) -> dict:
        return self._load().get("checks", {})


class ConsulSync:
    """The sync daemon (``corrosion consul sync``, ``sync.rs:1-975``)."""

    def __init__(self, source, api_client, node_name: str,
                 state_path=None, target_node: int | None = None):
        self.source = source
        self.client = api_client
        self.node_name = node_name
        self.state_path = str(state_path) if state_path else None
        self.target_node = target_node
        # id -> hash; the __corro_consul_{services,checks} hash tables
        self._svc_hashes: dict[str, str] = {}
        self._chk_hashes: dict[str, str] = {}
        self._load_state()

    # ------------------------------------------------------------ state
    def _load_state(self) -> None:
        if not self.state_path:
            return
        try:
            with open(self.state_path) as f:
                st = json.load(f)
            self._svc_hashes = dict(st.get("services", {}))
            self._chk_hashes = dict(st.get("checks", {}))
        except FileNotFoundError:
            pass
        except (ValueError, OSError):
            # truncated/corrupt sidecar (crash mid-write): start empty —
            # worst case is re-upserting everything, which is idempotent
            self._svc_hashes = {}
            self._chk_hashes = {}

    def _save_state(self) -> None:
        if not self.state_path:
            return
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"services": self._svc_hashes, "checks": self._chk_hashes},
                f,
            )
        os.replace(tmp, self.state_path)

    # ------------------------------------------------------------- sync
    def sync_once(self) -> dict:
        """One poll cycle. Returns counts like the reference's stats log
        (``sync.rs:620-640`` upserted/deleted tallies)."""
        services = self.source.agent_services()
        checks = self.source.agent_checks()
        statements: list = []
        stats = {
            "services_upserted": 0, "services_deleted": 0,
            "checks_upserted": 0, "checks_deleted": 0,
        }

        now = int(time.time())
        new_svc_hashes: dict[str, str] = {}
        for sid, svc in services.items():
            h = hash_service(svc)
            new_svc_hashes[sid] = h
            if self._svc_hashes.get(sid) == h:
                continue
            meta = dict(svc.get("Meta") or {})
            app_id = app_id_of(svc)
            if app_id is not None:
                meta["app_id"] = app_id
            statements.append(
                [
                    "INSERT INTO consul_services (node, id, name, tags, "
                    "meta, port, address, updated_at) VALUES "
                    "(?, ?, ?, ?, ?, ?, ?, ?)",
                    [
                        self.node_name, sid, svc.get("Service", ""),
                        json.dumps(svc.get("Tags") or []),
                        json.dumps(meta), svc.get("Port", 0),
                        svc.get("Address", ""), now,
                    ],
                ]
            )
            stats["services_upserted"] += 1
        for sid in set(self._svc_hashes) - set(new_svc_hashes):
            statements.append(
                [
                    "DELETE FROM consul_services WHERE node = ? AND id = ?",
                    [self.node_name, sid],
                ]
            )
            stats["services_deleted"] += 1

        new_chk_hashes: dict[str, str] = {}
        for cid, chk in checks.items():
            h = hash_check(chk)
            new_chk_hashes[cid] = h
            if self._chk_hashes.get(cid) == h:
                continue
            statements.append(
                [
                    "INSERT INTO consul_checks (node, id, service_id, "
                    "service_name, name, status, output, updated_at) "
                    "VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
                    [
                        self.node_name, cid, chk.get("ServiceID", ""),
                        chk.get("ServiceName", ""), chk.get("Name", ""),
                        chk.get("Status", ""), chk.get("Output", ""), now,
                    ],
                ]
            )
            stats["checks_upserted"] += 1
        for cid in set(self._chk_hashes) - set(new_chk_hashes):
            statements.append(
                [
                    "DELETE FROM consul_checks WHERE node = ? AND id = ?",
                    [self.node_name, cid],
                ]
            )
            stats["checks_deleted"] += 1

        if statements:
            resp = self.client.execute(statements, node=self.target_node)
            errors = [r for r in resp["results"] if "error" in r]
            if errors:
                raise RuntimeError(f"consul sync tx failed: {errors[0]}")
        # commit the hash state only after the tx landed (the reference
        # writes hashes in the same tx, sync.rs:388-470)
        self._svc_hashes = new_svc_hashes
        self._chk_hashes = new_chk_hashes
        self._save_state()
        return stats

    def run(self, tripwire, interval: float = 1.0) -> None:
        """1 s poll loop (``sync.rs`` main loop cadence)."""
        while not tripwire.tripped:
            try:
                self.sync_once()
            except Exception as e:
                # next tick retries; the reference logs and continues
                print(f"consul sync error (retrying): {e}", file=sys.stderr)
            if tripwire.sleep(interval):
                return
