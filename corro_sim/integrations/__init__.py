"""External-system integrations (the reference's consul-client +
`corrosion consul sync` daemon, SURVEY §2.4)."""
