"""Custom SQL scalar functions (sqlite-functions crate analog).

The reference registers ``corro_json_contains(a, b)`` on every SQLite
connection (``crates/sqlite-functions/src/lib.rs:14-51``): true iff the
first JSON value is fully contained in the second — recursive key-wise
containment for objects, strict equality for everything else. Consul
integration and templating queries filter on it.

Here the function is a host-evaluated predicate term of the query
language (see ``corro_sim/subs/query.py``): JSON containment has no
rank-interval form, so the matcher evaluates it over decoded column
values, like its pk terms.
"""

from __future__ import annotations

import json


def json_contains(selector, obj) -> bool:
    """True iff ``selector`` is fully contained in ``obj``
    (sqlite-functions/src/lib.rs:34-51)."""
    if isinstance(selector, dict) and isinstance(obj, dict):
        for k, sv in selector.items():
            if k not in obj or not json_contains(sv, obj[k]):
                return False
        return True
    return selector == obj


def json_contains_text(selector_text: str, obj_text) -> bool:
    """Containment over JSON *texts*; non-string or malformed ``obj_text``
    is False (the reference errors the query on malformed JSON — here a
    malformed stored value simply doesn't match)."""
    if not isinstance(obj_text, str):
        return False
    try:
        obj = json.loads(obj_text)
    except ValueError:
        return False
    return json_contains(json.loads(selector_text), obj)
