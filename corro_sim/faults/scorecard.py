"""Resilience scorecard: the numbers a chaos run is *graded* on.

The invariant checkers (:mod:`corro_sim.faults.invariants`) say whether a
run was CORRECT; this module says how well it RECOVERED — the SWARM-style
(PAPERS.md) replication-latency-under-load report for a run where faults
and traffic overlap:

- **recovery_rounds** — scheduled heal → re-convergence (the soak
  headline, recomputed here so the scorecard is self-contained);
- **rows_lost** — cells on which any live node still disagrees with its
  partition's reference replica at the moment convergence is reported
  (0 = the fault cost nothing durable; the crash-amnesia acceptance
  criterion);
- **resync_rows** — version-applications anti-entropy had to repay to
  rebuild wiped nodes: final applied count minus the post-wipe baseline
  (zero for amnesia, the snapshot's count for stale rejoins);
- **swim_false_down / swim_flaps** — (observer, subject) belief pairs
  that marked a ground-truth-alive node DOWN, and pairs that did so
  again after recovering (failure-detector churn under stress);
- **sub_delivery** — when a workload spec is coupled: write→apply
  delivery-latency p50/p99 during the fault window vs steady state, via
  the FIFO horizontal-distance read of the cumulative offered-work vs
  completed-work curves (the batched-path analog of the live harness's
  ``corro_sub_latency_rounds``; an aggregate-flow approximation, exact
  for FIFO service — stated in the block so nobody mistakes it for a
  per-event measurement).

Wired like the invariant checker: ``run_sim(..., scorecard=
ResilienceScorecard(cfg, scenario=sc, workload=wl))`` calls
:meth:`on_chunk` between chunks and :meth:`on_converged` at the
convergence report; the driver then attaches :meth:`finalize`'s block as
``RunResult.resilience``, annotates it into the flight record, and the
block's totals land in the ``corro_resilience_*`` metric families.
``corro-sim soak --scorecard`` writes the per-scenario blocks as a JSON
artifact and gates them against the committed threshold golden
(``corro_sim/analysis/golden/resilience_thresholds.json``) — breaches
exit 6, the CI tripwire (t1.yml chaos-scorecard leg).
"""

from __future__ import annotations

import json
import os

import numpy as np

__all__ = [
    "THRESHOLDS_PATH",
    "ResilienceScorecard",
    "check_thresholds",
    "fifo_delivery_quantiles",
    "load_thresholds",
]


def fifo_delivery_quantiles(
    applied: np.ndarray, gap: np.ndarray, lo: int, hi: int,
    first_round: int = 0,
) -> dict | None:
    """FIFO horizontal-distance latency quantiles for work entering
    ABSOLUTE rounds ``[lo, hi]``: unit k's entry round is where the
    cumulative offered-work curve reaches k, its completion round where
    the cumulative completed-work curve does. ``applied``/``gap`` are
    per-round series whose index 0 sits at absolute round
    ``first_round`` (nonzero on a resumed run).

    Offered work derives from the gap identity ``gap[r] = gap[r-1] +
    offered[r] - applied[r]`` rather than from the write count: that way
    a wipe's re-created backlog enters the offered curve at the wipe
    round (the re-applications that repay it are in the completed curve,
    so deriving offered from writes alone would understate fault-window
    latency — the one window the metric exists to grade). Negative
    deltas (a kill shrinking the live set's gap) clip to zero.

    Shared by the resilience scorecard (fault-window vs steady grading)
    and the digital twin's shadow delivery headline
    (corro_sim/engine/twin.py — the SWARM replication-latency read over
    a replayed feed). An aggregate-flow approximation, exact for FIFO
    service — stated wherever the number is published."""
    applied = np.asarray(applied, np.int64)
    gap = np.asarray(gap, np.float64)
    if applied.size == 0:
        return None
    gap_delta = np.diff(np.concatenate([[0.0], gap]))
    offered = np.maximum(
        gap_delta + applied.astype(np.float64), 0.0
    ).astype(np.int64)
    ca = np.cumsum(offered)
    cs = np.cumsum(applied)
    done = int(min(ca[-1], cs[-1]))
    if done <= 0:
        return None
    units = np.arange(1, done + 1)
    entry = np.searchsorted(ca, units) + first_round
    completion = np.searchsorted(cs, units) + first_round
    in_window = (entry >= lo) & (entry <= hi)
    if not in_window.any():
        return None
    lat = np.maximum(completion - entry, 0)[in_window]
    return {
        "p50": float(np.percentile(lat, 50)),
        "p99": float(np.percentile(lat, 99)),
        "units": int(in_window.sum()),
    }

THRESHOLDS_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "analysis", "golden", "resilience_thresholds.json",
)


class ResilienceScorecard:
    """Accumulating per-chunk resilience accountant for one run."""

    def __init__(self, cfg, scenario=None, workload=None,
                 round_offset: int = 0):
        self.cfg = cfg
        self.scenario = scenario
        self.workload = workload
        # what-if forks (corro_sim/engine/twin.py): node-fault schedules
        # on cfg are shifted to ABSOLUTE state rounds (fork round R +
        # relative round), while the driver's round frame — metrics,
        # converged_round, `rounds` — starts at 0. This offset maps the
        # schedule back into the driver frame wherever the two meet.
        self.round_offset = int(round_offset)
        self.heal_round = (
            scenario.heal_round if scenario is not None else None
        )
        self._fault_window = (
            scenario.fault_window() if scenario is not None else None
        )
        # per-round series for the delivery-latency read; _first_round
        # anchors series index 0 to its ABSOLUTE round (a resumed run's
        # first observed chunk starts mid-timeline, and the fault-window
        # bounds are absolute rounds)
        self._applied: list[np.ndarray] = []
        self._gap: list[np.ndarray] = []
        self._first_round: int | None = None
        self._wipes_seen = 0
        # SWIM belief churn
        self._prev_bad: np.ndarray | None = None
        self._ever_bad: np.ndarray | None = None
        self.swim_false_down = 0
        self.swim_flaps = 0
        self.rows_lost: int | None = None
        self.chunks_checked = 0

    # ------------------------------------------------------------ chunks
    def on_chunk(self, state, metrics, alive, part, start_round):
        """Fold one executed chunk in (driver-called, same cadence and
        sanction point as the invariant checker)."""
        self.chunks_checked += 1
        alive = np.asarray(alive, bool)
        if self._first_round is None:
            self._first_round = int(start_round)
        self._applied.append(
            np.asarray(metrics["fresh"], np.int64)
            + np.asarray(metrics["sync_versions"], np.int64)
        )
        self._gap.append(np.asarray(metrics["gap"], np.float64))
        if "node_fault_wipes" in metrics:
            self._wipes_seen += int(
                np.asarray(metrics["node_fault_wipes"]).sum()
            )
        if self.cfg.swim_enabled:
            from corro_sim.membership.swim import down_belief_matrix

            n = alive.shape[1]
            alive_now = alive[-1]
            bad = (
                down_belief_matrix(state.swim, n)
                & alive_now[None, :] & alive_now[:, None]
            )
            if self._prev_bad is None:
                self._prev_bad = np.zeros_like(bad)
                self._ever_bad = np.zeros_like(bad)
            entered = bad & ~self._prev_bad
            self.swim_false_down += int(entered.sum())
            self.swim_flaps += int((entered & self._ever_bad).sum())
            self._ever_bad |= bad
            self._prev_bad = bad

    def on_converged(self, state, alive_now, part_now):
        """Count the cells any live node disagrees with its partition's
        reference replica on, at the moment convergence is reported —
        rows_lost == 0 is the bit-exact self-heal claim."""
        alive_now = np.asarray(alive_now, bool)
        part_now = np.asarray(part_now)
        cv = np.asarray(state.table.cv)
        vr = np.asarray(state.table.vr)
        cl = np.asarray(state.table.cl)
        lost = 0
        for pid in np.unique(part_now[alive_now]):
            members = np.nonzero(alive_now & (part_now == pid))[0]
            if len(members) < 2:
                continue
            ref = members[0]
            for m in members[1:]:
                lost += int(
                    (cv[ref] != cv[m]).sum() + (vr[ref] != vr[m]).sum()
                    + (cl[ref] != cl[m]).sum()
                )
        self.rows_lost = lost

    # ---------------------------------------------------------- finalize
    def _resync_rows(self, final_state, rounds: int) -> int:
        """Version-applications repaid to wiped nodes: final applied
        count minus the post-wipe baseline (amnesia restarts from zero;
        stale rejoins from the snapshot leaf's captured bookkeeping).
        Counted once per wiped NODE over its EXECUTED wipes only — a
        wipe scheduled past the run's last round never happened and must
        not credit the node's whole history as repaid, and a node wiped
        twice still repays at most its final history."""
        nf = self.cfg.node_faults
        if not nf.wipe_enabled:
            return 0
        # the LAST EXECUTED wipe per node sets its baseline: an earlier
        # wipe's repayment is overwritten by the later restart, and a
        # scheduled-but-never-executed entry must not pick the baseline
        # (kind: True = amnesia/zero, False = stale/snapshot; amnesia
        # wins a same-round collision, matching apply_node_faults)
        last: dict[int, tuple[int, bool]] = {}
        executed = (
            [(int(n), int(r), True) for n, r in nf.crash]
            + [(int(n), int(r), False) for n, _s, r in nf.stale]
        )
        for node, r, amnesia in executed:
            if r - self.round_offset >= rounds:
                continue
            prev = last.get(node)
            if prev is None or (r, amnesia) > prev:
                last[node] = (r, amnesia)
        if not last:
            return 0
        head = np.asarray(final_state.book.head)
        snap_head = (
            np.asarray(final_state.features["node_snapshot"]["head"])
            if nf.stale else None
        )
        total = 0
        for node, (_r, amnesia) in sorted(last.items()):
            base = (
                0 if amnesia or snap_head is None
                else int(snap_head[node].sum())
            )
            total += max(int(head[node].sum()) - base, 0)
        return total

    def _delivery_quantiles(self, lo: int, hi: int) -> dict | None:
        """The shared FIFO horizontal-distance read
        (:func:`fifo_delivery_quantiles`) over this run's accumulated
        series — index 0 anchored to ``_first_round`` (nonzero on a
        resumed run)."""
        if not self._applied:
            return None
        return fifo_delivery_quantiles(
            np.concatenate(self._applied), np.concatenate(self._gap),
            lo, hi, first_round=self._first_round or 0,
        )

    def _sub_delivery(self, rounds: int) -> dict | None:
        if self.workload is None or self._fault_window is None:
            return None
        lo, hi = self._fault_window
        fault = self._delivery_quantiles(lo, hi)
        steady_windows = []
        if lo > 0:
            steady_windows.append((0, lo - 1))
        if hi + 1 < rounds:
            steady_windows.append((hi + 1, rounds - 1))
        steady = None
        for w in steady_windows:
            q = self._delivery_quantiles(*w)
            if q is not None:
                steady = q if steady is None else max(
                    steady, q, key=lambda x: x["units"]
                )
        block = {
            "method": "fifo_horizontal_distance",
            "fault_window": {"rounds": [lo, hi], **(fault or {})}
            if fault else None,
            "steady": steady,
        }
        if fault and steady and steady["p99"] > 0:
            block["degradation_p99"] = round(
                fault["p99"] / steady["p99"], 3
            )
        elif fault and steady:
            block["degradation_p99"] = None
        return block

    def finalize(self, converged_round, rounds: int, final_state) -> dict:
        """The resilience block (``RunResult.resilience``); also exports
        the ``corro_resilience_*`` metric families."""
        recovery = (
            converged_round - self.heal_round
            if converged_round is not None and self.heal_round is not None
            else None
        )
        resync = self._resync_rows(final_state, rounds)
        # executed wipes from the ABSOLUTE schedule, not the observed
        # metric sum — a resumed run only observes post-resume chunks,
        # but a wipe whose round already passed still happened
        wipes = sum(
            1 for _n, r in self.cfg.node_faults.wipe_schedule()
            if r - self.round_offset < rounds
        )
        block = {
            "scenario": (
                self.scenario.spec if self.scenario is not None else None
            ),
            "workload": (
                self.workload.spec if self.workload is not None else None
            ),
            "converged_round": converged_round,
            "heal_round": self.heal_round,
            "recovery_rounds": recovery,
            "rows_lost": self.rows_lost,
            "resync_rows": resync,
            "wipes": wipes,
            "wipes_observed": self._wipes_seen,
            "wipe_schedule": list(self.cfg.node_faults.wipe_schedule()),
            # belief-churn counters cover only the chunks this scorecard
            # observed (a resumed run starts at its resume round)
            "swim_false_down": self.swim_false_down,
            "swim_flaps": self.swim_flaps,
            "sub_delivery": self._sub_delivery(rounds),
            "chunks_checked": self.chunks_checked,
        }
        export_metrics(block)
        return block


def export_metrics(block: dict) -> None:
    """Land one finalized block in the ``corro_resilience_*`` families
    (utils/metrics.py registries — rendered by every /metrics scrape)."""
    from corro_sim.utils.metrics import ROUNDS_BUCKETS, counters, histograms

    sc = block.get("scenario") or "none"
    label = f'{{scenario="{sc}"}}'
    counters.inc(
        "corro_resilience_runs_total", labels=label,
        help_="scorecard-graded chaos runs by scenario "
              "(faults/scorecard.py)",
    )
    for key, name, help_ in (
        ("rows_lost", "corro_resilience_rows_lost_total",
         "cells diverging from the partition reference replica at the "
         "convergence report"),
        ("resync_rows", "corro_resilience_resync_rows_total",
         "version-applications anti-entropy repaid to wiped nodes"),
        ("swim_false_down", "corro_resilience_swim_false_down_total",
         "SWIM belief pairs marking a ground-truth-alive node DOWN"),
        ("swim_flaps", "corro_resilience_swim_flaps_total",
         "SWIM false-DOWN pairs that recovered and relapsed"),
    ):
        v = block.get(key)
        if v:
            counters.inc(name, n=int(v), labels=label, help_=help_)
        else:
            counters.inc(name, n=0, labels=label, help_=help_)
    if block.get("recovery_rounds") is not None:
        histograms.observe(
            "corro_resilience_recovery_rounds",
            float(block["recovery_rounds"]), labels=label,
            help_="rounds from the scheduled heal to re-convergence",
            buckets=ROUNDS_BUCKETS,
        )


# --------------------------------------------------- threshold gating

def load_thresholds(path: str = THRESHOLDS_PATH) -> dict | None:
    """The committed threshold golden, or None when the file is absent.
    A file that EXISTS but does not parse raises: a corrupt golden
    silently returning None would disable the exit-6 CI tripwire while
    SCORECARD.json keeps reporting thresholds_ok — regressions would
    sail through green with the gate off."""
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except OSError:
        return None
    except json.JSONDecodeError as e:
        raise ValueError(
            f"resilience threshold golden {path!r} is unreadable JSON "
            f"({e}) — fix or re-baseline it; a corrupt golden must not "
            "silently disable the threshold gate"
        ) from e


def check_thresholds(block: dict, thresholds: dict) -> list[str]:
    """Grade one resilience block against the committed threshold
    golden: the ``default`` table merged under the scenario's base-name
    entry. Returns human-readable breaches (empty = pass). The golden
    is a REGRESSION tripwire, not a tight bound — re-baseline by
    editing ``analysis/golden/resilience_thresholds.json`` in the PR
    that moved the number, like every other golden
    (doc/fault_injection.md §scorecard)."""
    spec = block.get("scenario") or ""
    base = spec.split(":", 1)[0]
    merged = dict(thresholds.get("default", {}))
    merged.update(thresholds.get("scenarios", {}).get(base, {}))
    breaches: list[str] = []
    if merged.get("require_converged") and block["converged_round"] is None:
        breaches.append(f"{spec}: did not re-converge")
    rec = block.get("recovery_rounds")
    if (
        merged.get("recovery_rounds_max") is not None
        and rec is not None and rec > merged["recovery_rounds_max"]
    ):
        breaches.append(
            f"{spec}: recovery_rounds {rec} > "
            f"{merged['recovery_rounds_max']}"
        )
    if (
        merged.get("rows_lost_max") is not None
        and block.get("rows_lost") is not None
        and block["rows_lost"] > merged["rows_lost_max"]
    ):
        breaches.append(
            f"{spec}: rows_lost {block['rows_lost']} > "
            f"{merged['rows_lost_max']}"
        )
    if (
        merged.get("resync_rows_min") is not None
        and block.get("resync_rows", 0) < merged["resync_rows_min"]
    ):
        breaches.append(
            f"{spec}: resync_rows {block.get('resync_rows', 0)} < "
            f"{merged['resync_rows_min']} (the stale-rejoin repayment "
            "evidence is missing)"
        )
    if (
        merged.get("swim_false_down_max") is not None
        and block.get("swim_false_down", 0)
        > merged["swim_false_down_max"]
    ):
        breaches.append(
            f"{spec}: swim_false_down {block['swim_false_down']} > "
            f"{merged['swim_false_down_max']}"
        )
    return breaches
