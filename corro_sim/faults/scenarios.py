"""Named failure scenarios: seeded generators compiling into vectorized
``Schedule`` arrays + fault-config overrides.

A scenario is the reproducible form of a chaos experiment: the same
``(name, params, n, rounds, seed)`` always produces the same
``(rounds, n)`` alive/partition arrays, the same fault knobs and the
same event markers. The scheduled timeline is indexed by absolute
round, so the rows a chunked driver sees are independent of chunk
boundaries (tests/test_scenarios.py pins it); the *stochastic* knobs
(loss/dup/burst draws) replay exactly under the same run seed and
chunking, like every other random stream in the simulation.

Spec strings (CLI ``--scenario``, ``CORRO_BENCH_SCENARIO``,
``LiveCluster.load_scenario``) are ``name[:k=v,...]``::

    lossy:p=0.1
    rolling_restart:batch=4,down=8
    split_brain_heal:at=8,heal=40
    churn:rate=0.05
    blackhole_one_way:src=0

Event tuples are ``(round, kind, attrs)``; an attrs ``phase="heal"``
marks the moment the last scheduled fault clears — the soak harness
measures recovery time (rounds from heal to re-convergence) from the
latest such event.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from corro_sim.config import SimConfig
from corro_sim.utils.spec import format_spec, parse_spec

__all__ = [
    "SCENARIOS",
    "Scenario",
    "make_scenario",
    "parse_scenario_spec",
    "ring_blackhole",
    "star_blackhole",
]


@dataclasses.dataclass
class Scenario:
    """A compiled failure scenario: schedule arrays + fault overrides."""

    name: str
    params: dict
    rounds: int
    write_rounds: int
    faults: dict  # FaultConfig field overrides
    alive: np.ndarray | None = None  # (rounds, n) bool
    part: np.ndarray | None = None  # (rounds, n) int32
    events: list = dataclasses.field(default_factory=list)
    node_faults: dict = dataclasses.field(default_factory=dict)
    # NodeFaultConfig field overrides (faults/nodes.py): crash/stale
    # wipe schedules, skew planes, straggler duty cycles

    def __post_init__(self):
        # round-sorted invariant: LiveCluster's event cursor and the
        # flight-record reader both assume chronological order (wave
        # generators emit kill/rejoin interleaved)
        self.events.sort(key=lambda ev: ev[0])

    def schedule(self):
        """The vectorized :class:`corro_sim.engine.driver.Schedule`."""
        from corro_sim.engine.driver import Schedule

        return Schedule(
            write_rounds=self.write_rounds,
            alive=self.alive,
            part=self.part,
            events=list(self.events),
            name=self.spec,
        )

    def apply(self, cfg: SimConfig) -> SimConfig:
        """``cfg`` with this scenario's fault knobs merged in — the
        link-level FaultConfig overrides and the node-level
        NodeFaultConfig ones alike."""
        if not self.faults and not self.node_faults:
            return cfg
        kw = {}
        if self.faults:
            kw["faults"] = dataclasses.replace(cfg.faults, **self.faults)
        if self.node_faults:
            kw["node_faults"] = dataclasses.replace(
                cfg.node_faults, **self.node_faults
            )
        return dataclasses.replace(cfg, **kw).validate()

    def fault_window(self) -> tuple[int, int] | None:
        """The ``[first, last]`` round range this scenario's faults are
        actually in effect — from the event timeline when present, else
        the whole run for always-on fault knobs (loss, skew, duty
        cycles). Bookkeeping events that happen on a HEALTHY cluster
        (the stale-rejoin snapshot capture) do not open the window — a
        window starting there would grade fault-free rounds as faulted.
        None only for a scenario with neither events nor overrides."""
        onsets = [ev for ev in self.events if ev[1] != "snapshot"]
        if onsets:
            return (
                int(min(ev[0] for ev in onsets)),
                int(self.heal_round
                    if self.heal_round is not None
                    else max(ev[0] for ev in onsets)),
            )
        if self.faults or self.node_faults:
            return (0, self.rounds - 1)
        return None

    def check_workload(self, workload) -> None:
        """The coupled-spec validation (`run/soak --scenario X
        --workload Y`): the fault window and the workload's write range
        must OVERLAP, or the run is two experiments glued end to end —
        latency-under-load numbers during the fault window would be
        measured against zero traffic (SWARM's
        replication-latency-under-load story needs both at once). ONE
        error message, raised at spec time, not after minutes of
        compile."""
        w = np.asarray(workload.writers)
        if not w.any():
            lo_w, hi_w = 0, -1
        else:
            rows = np.nonzero(w.any(axis=1))[0]
            lo_w, hi_w = int(rows[0]), int(rows[-1])
        fw = self.fault_window()
        if fw is None or (lo_w <= fw[1] and hi_w >= fw[0]):
            return
        raise ValueError(
            f"scenario {self.spec!r} schedules its faults in rounds "
            f"[{fw[0]}, {fw[1]}] but workload {workload.spec!r} writes "
            f"only in rounds [{lo_w}, {hi_w}] — the ranges never "
            "overlap, so no fault would ever land under load; extend "
            "the workload's --write-rounds/rounds or move the "
            "scenario's fault window"
        )

    @property
    def spec(self) -> str:
        return format_spec(self.name, self.params)

    @property
    def heal_round(self) -> int | None:
        heals = [r for r, _, attrs in self.events
                 if attrs.get("phase") == "heal"]
        return max(heals) if heals else None


def _base(n: int, rounds: int) -> tuple[np.ndarray, np.ndarray]:
    return np.ones((rounds, n), bool), np.zeros((rounds, n), np.int32)


def lossy(n, rounds, write_rounds, seed, p: float = 0.1):
    """Uniform stochastic link loss for the whole run — the baseline
    chaos every gossip-theory convergence guarantee is stated under."""
    return Scenario(
        name="lossy", params={"p": p}, rounds=rounds,
        write_rounds=write_rounds, faults={"loss": float(p)},
    )


def duplicating(n, rounds, write_rounds, seed, p: float = 0.1,
                dup: float = 0.2):
    """Lossy AND duplicating links (UDP's full failure menu)."""
    return Scenario(
        name="duplicating", params={"p": p, "dup": dup}, rounds=rounds,
        write_rounds=write_rounds,
        faults={"loss": float(p), "dup": float(dup)},
    )


def burst(n, rounds, write_rounds, seed, enter: float = 0.05,
          exit: float = 0.3, loss: float = 1.0):
    """Gilbert burst loss: node receive paths flip into a high-loss
    state and back (the flaky-NIC / congested-uplink pattern)."""
    return Scenario(
        name="burst",
        params={"enter": enter, "exit": exit, "loss": loss},
        rounds=rounds, write_rounds=write_rounds,
        faults={
            "burst_enter": float(enter), "burst_exit": float(exit),
            "burst_loss": float(loss),
        },
    )


def blackhole_one_way(n, rounds, write_rounds, seed, src: int = 0):
    """Node ``src`` transmits into a void but still receives — the
    asymmetric-partition failure SWIM's indirect probes exist for."""
    return Scenario(
        name="blackhole_one_way", params={"src": int(src)}, rounds=rounds,
        write_rounds=write_rounds,
        faults={"blackhole": ((int(src), -1),)},
    )


def rolling_restart(n, rounds, write_rounds, seed, batch: int = 0,
                    down: int = 6, stagger: int = 0, start: int = 2):
    """Restart every node once, in staggered batches — the deploy-wave
    scenario. ``batch`` nodes go down per wave (default: ~n/8), each wave
    ``stagger`` rounds after the previous (default: down//2, so waves
    overlap like a real rolling deploy), each node down ``down`` rounds.
    """
    batch = int(batch) or max(1, n // 8)
    stagger = int(stagger) or max(1, int(down) // 2)
    down = int(down)
    alive, part = _base(n, rounds)
    events = []
    waves = (n + batch - 1) // batch
    last_up = 0
    for w in range(waves):
        lo, hi = w * batch, min((w + 1) * batch, n)
        t0 = int(start) + w * stagger
        t1 = t0 + down
        if t0 >= rounds:
            break
        alive[t0:min(t1, rounds), lo:hi] = False
        events.append((t0, "kill", {"nodes": [lo, hi], "wave": w}))
        if t1 < rounds:
            events.append((t1, "rejoin", {"nodes": [lo, hi], "wave": w}))
        last_up = max(last_up, min(t1, rounds - 1))
    if events:
        events.append((last_up, "heal", {"phase": "heal"}))
    return Scenario(
        name="rolling_restart",
        params={"batch": batch, "down": down, "stagger": stagger},
        rounds=rounds, write_rounds=write_rounds, faults={},
        alive=alive, part=part, events=events,
    )


def flapper(n, rounds, write_rounds, seed, frac: float = 0.1,
            period: int = 4, until: int = 0):
    """A fraction of nodes flap down/up on a fixed period until round
    ``until`` (default: half the run), then stay up — the crash-looping
    agent that SWIM must keep re-admitting."""
    until = int(until) or rounds // 2
    k = max(1, int(round(n * float(frac))))
    period = max(1, int(period))
    alive, part = _base(n, rounds)
    r = np.arange(rounds)
    flap_down = ((r // period) % 2 == 1) & (r < until)
    alive[:, :k] = ~flap_down[:, None]
    events = [
        (0, "flap_start", {"nodes": [0, k], "period": period}),
        (min(until, rounds - 1), "heal", {"phase": "heal"}),
    ]
    return Scenario(
        name="flapper",
        params={"frac": frac, "period": period, "until": until},
        rounds=rounds, write_rounds=write_rounds, faults={},
        alive=alive, part=part, events=events,
    )


def split_brain_heal(n, rounds, write_rounds, seed, at: int = -1,
                     heal: int = -1, parts: int = 2):
    """Partition the cluster into ``parts`` contiguous islands at round
    ``at`` (default: mid-write-phase; 0 = split from the very first
    round), heal at ``heal`` (default: half the run) — convergence then
    requires anti-entropy to merge the divergent islands' histories."""
    at = int(at) if int(at) >= 0 else max(1, write_rounds // 2)
    heal = int(heal) if int(heal) > at else max(at + 1, rounds // 2)
    parts = max(2, int(parts))
    alive, part = _base(n, rounds)
    island = (np.arange(n) * parts // n).astype(np.int32)
    part[at:heal] = island[None, :]
    events = [
        (at, "split", {"parts": parts}),
        (min(heal, rounds - 1), "heal", {"phase": "heal", "parts": parts}),
    ]
    return Scenario(
        name="split_brain_heal",
        params={"at": at, "heal": heal, "parts": parts},
        rounds=rounds, write_rounds=write_rounds, faults={},
        alive=alive, part=part, events=events,
    )


def churn(n, rounds, write_rounds, seed, rate: float = 0.02,
          down: int = 6, until: int = 0):
    """Memoryless churn: every up node crashes with probability ``rate``
    per round and stays down ``down`` rounds, until round ``until``
    (default: half the run) — the background failure hum of a large
    fleet. Seeded: the same (n, rounds, seed) always crashes the same
    nodes at the same rounds."""
    until = int(until) or rounds // 2
    down = int(down)
    rng = np.random.default_rng(int(seed) ^ 0xC0FF)
    alive, part = _base(n, rounds)
    down_until = np.zeros(n, np.int64)  # round each node revives
    events = []
    kills = 0
    for r in range(min(until, rounds)):
        up = down_until <= r
        crash = up & (rng.random(n) < float(rate))
        if crash.any():
            down_until[crash] = r + down
            kills += int(crash.sum())
            events.append(
                (r, "kill", {"nodes": np.nonzero(crash)[0].tolist()})
            )
        alive[r] = down_until <= r
    # after `until`, everyone is forced back up (the heal edge); nodes
    # still serving a down window revive there
    last_down = int(min(max(down_until.max(), until), rounds - 1))
    for r in range(until, rounds):
        alive[r] = down_until <= r
    alive[last_down:] = True
    events.append((last_down, "heal", {"phase": "heal", "kills": kills}))
    return Scenario(
        name="churn",
        params={"rate": rate, "down": down, "until": until},
        rounds=rounds, write_rounds=write_rounds, faults={},
        alive=alive, part=part, events=events,
    )


# ------------------------------------------------ node-lifecycle scenarios
# (corro_sim/faults/nodes.py): the agent-level failure catalog — state
# loss, stale restores, clock skew, stragglers — compiled into the same
# (alive schedule + config override + event) shape as the link catalog.


def _pick_nodes(n: int, count: int, seed: int, tag: int) -> list[int]:
    rng = np.random.default_rng(int(seed) ^ tag)
    return sorted(
        int(v) for v in rng.choice(n, size=min(int(count), n),
                                   replace=False)
    )


def crash_amnesia(n, rounds, write_rounds, seed, nodes: int = 3,
                  at: int = -1, down: int = 4, jump: int = 0):
    """Corrosion's production failure mode: ``nodes`` agents crash at
    round ``at`` (default mid-write-phase), stay down ``down`` rounds,
    and restart with an EMPTY database — table, bookkeeping, gossip
    rings, SWIM membership all wiped at the rejoin round
    (faults/nodes.py). They rejoin with an epoch-bumped HLC (+ ``jump``
    per restart) and must full-resync via anti-entropy; the scorecard's
    rows_lost==0 / recovery_rounds numbers are this scenario's whole
    point."""
    at = int(at) if int(at) >= 0 else max(2, write_rounds // 2)
    down = max(1, int(down))
    rejoin = min(at + down, rounds - 1)
    victims = _pick_nodes(n, nodes, seed, 0xA3E1)
    alive, part = _base(n, rounds)
    alive[at:rejoin, victims] = False
    events = [
        (at, "kill", {"nodes": victims, "fault": "crash_amnesia"}),
        (rejoin, "rejoin", {"nodes": victims, "amnesia": True}),
        (rejoin, "heal", {"phase": "heal"}),
    ]
    return Scenario(
        name="crash_amnesia",
        params={"nodes": int(nodes), "at": at, "down": down,
                "jump": int(jump)},
        rounds=rounds, write_rounds=write_rounds, faults={},
        alive=alive, part=part, events=events,
        node_faults={
            "crash": tuple((v, rejoin) for v in victims),
            "epoch_jump": int(jump),
        },
    )


def stale_rejoin(n, rounds, write_rounds, seed, nodes: int = 2,
                 snap: int = -1, at: int = -1, down: int = 4):
    """Restart from an old backup: the victims' row state is snapshotted
    at round ``snap`` (default: a quarter into the write phase), they
    crash at ``at`` and rejoin restored FROM THE SNAPSHOT instead of
    empty — anti-entropy repays only the delta (the scorecard's
    resync_rows)."""
    snap = int(snap) if int(snap) >= 0 else max(1, write_rounds // 4)
    at = int(at) if int(at) >= 0 else max(snap + 1, write_rounds // 2)
    down = max(1, int(down))
    rejoin = min(at + down, rounds - 1)
    victims = _pick_nodes(n, nodes, seed, 0x57A1)
    alive, part = _base(n, rounds)
    alive[at:rejoin, victims] = False
    events = [
        (snap, "snapshot", {"nodes": victims}),
        (at, "kill", {"nodes": victims, "fault": "stale_rejoin"}),
        (rejoin, "rejoin", {"nodes": victims, "snapshot_round": snap}),
        (rejoin, "heal", {"phase": "heal"}),
    ]
    return Scenario(
        name="stale_rejoin",
        params={"nodes": int(nodes), "snap": snap, "at": at,
                "down": down},
        rounds=rounds, write_rounds=write_rounds, faults={},
        alive=alive, part=part, events=events,
        node_faults={
            "stale": tuple((v, snap, rejoin) for v in victims),
        },
    )


def clock_skew(n, rounds, write_rounds, seed, nodes: int = 0,
               max_skew: int = 64):
    """Per-node HLC wall-clock offsets (default: a quarter of the
    cluster, seeded offsets up to ``max_skew`` rounds fast or slow) —
    the NTP-drift study: LWW tie-breaks and EmptySet-ts gating must
    stay convergent when some nodes mint timestamps from the future.
    No outage: the heal marker sits at the write-phase end so recovery
    measures the skewed tail."""
    count = int(nodes) or max(1, n // 4)
    victims = _pick_nodes(n, count, seed, 0xC10C)
    rng = np.random.default_rng(int(seed) ^ 0x5CE3)
    offs = rng.integers(1, max(int(max_skew), 2), size=len(victims))
    signs = rng.choice((-1, 1), size=len(victims))
    skew = tuple(
        (v, int(o * s)) for v, o, s in zip(victims, offs, signs)
    )
    events = [
        (0, "skew", {"nodes": victims}),
        (max(write_rounds - 1, 0), "heal", {"phase": "heal"}),
    ]
    return Scenario(
        name="clock_skew",
        params={"nodes": count, "max_skew": int(max_skew)},
        rounds=rounds, write_rounds=write_rounds, faults={},
        events=events, node_faults={"skew": skew},
    )


def stragglers(n, rounds, write_rounds, seed, frac: float = 0.1,
               period: int = 8, active: int = 2):
    """A fraction of nodes run slow: they emit broadcasts and initiate
    sync sweeps only ``active`` of every ``period`` duty rounds
    (faults/nodes.py — they still receive, answer SWIM probes, serve
    inbound sync and commit local writes). The convergence tail
    stretches to the stragglers' cadence; the heal marker sits at the
    write-phase end so recovery measures that stretch."""
    k = max(1, int(round(n * float(frac))))
    victims = _pick_nodes(n, k, seed, 0x57AA)
    events = [
        (0, "straggle", {"nodes": victims, "period": int(period),
                         "active": int(active)}),
        (max(write_rounds - 1, 0), "heal", {"phase": "heal"}),
    ]
    return Scenario(
        name="stragglers",
        params={"frac": frac, "period": int(period),
                "active": int(active)},
        rounds=rounds, write_rounds=write_rounds, faults={},
        events=events,
        node_faults={
            "straggle": tuple(
                (v, int(period), int(active)) for v in victims
            ),
        },
    )


# ----------------------------------------------------- topology constraints
def _allow_only(n: int, allowed: np.ndarray) -> tuple:
    """Blackhole pairs blocking every directed edge NOT in ``allowed``
    ((N, N) bool). Self-edges are irrelevant (never delivered).

    O(N^2) pairs by construction — topology studies are meant for
    modest clusters (the soak default sweep excludes them); the
    validate/mask consumers are vectorized so even a large list only
    costs memory, not Python-loop time."""
    allowed = allowed | np.eye(n, dtype=bool)
    blocked = np.argwhere(~allowed)
    return tuple(map(tuple, blocked.tolist()))


def ring_blackhole(n: int) -> tuple:
    """Blackhole mask constraining gossip to a bidirectional ring —
    node i can only reach i±1 (mod n). The BFS oracle's ring topology
    (obs/probes.py) realized in the transport layer."""
    allowed = np.zeros((n, n), bool)
    i = np.arange(n)
    allowed[i, (i + 1) % n] = True
    allowed[i, (i - 1) % n] = True
    return _allow_only(n, allowed)


def star_blackhole(n: int, hub: int = 0) -> tuple:
    """Blackhole mask constraining gossip to a star around ``hub``."""
    allowed = np.zeros((n, n), bool)
    allowed[hub, :] = True
    allowed[:, hub] = True
    return _allow_only(n, allowed)


def ring(n, rounds, write_rounds, seed, p: float = 0.0):
    """Gossip constrained to a ring topology via blackhole masks (+
    optional loss) — the worst-diameter graph gossip bounds quote."""
    return Scenario(
        name="ring", params={"p": p}, rounds=rounds,
        write_rounds=write_rounds,
        faults={"blackhole": ring_blackhole(n), "loss": float(p)},
    )


def star(n, rounds, write_rounds, seed, hub: int = 0, p: float = 0.0):
    """Gossip constrained to a star topology via blackhole masks."""
    return Scenario(
        name="star", params={"hub": hub, "p": p}, rounds=rounds,
        write_rounds=write_rounds,
        faults={
            "blackhole": star_blackhole(n, int(hub)), "loss": float(p),
        },
    )


SCENARIOS = {
    "lossy": lossy,
    "duplicating": duplicating,
    "burst": burst,
    "blackhole_one_way": blackhole_one_way,
    "rolling_restart": rolling_restart,
    "flapper": flapper,
    "split_brain_heal": split_brain_heal,
    "churn": churn,
    "ring": ring,
    "star": star,
    "crash_amnesia": crash_amnesia,
    "stale_rejoin": stale_rejoin,
    "clock_skew": clock_skew,
    "stragglers": stragglers,
}

# The soak sweep's default set: scenarios whose faults clear (or are
# survivable) so re-convergence is the pass criterion. Excluded by
# design: blackhole_one_way (the hole never heals — an availability
# study, not a recovery one) and ring/star (topology-constrained
# studies whose convergence time grows with the graph diameter).
SOAK_DEFAULT = (
    "lossy", "duplicating", "burst", "rolling_restart", "flapper",
    "split_brain_heal", "churn",
    "crash_amnesia", "stale_rejoin", "clock_skew", "stragglers",
)


def parse_scenario_spec(spec: str) -> tuple[str, dict]:
    """``name[:k=v,...]`` → (name, params) — the shared grammar
    (:mod:`corro_sim.utils.spec`) validated against the scenario table."""
    name, params = parse_spec(spec)
    if name not in SCENARIOS:
        raise ValueError(
            f"unknown scenario {name!r} (have: {', '.join(sorted(SCENARIOS))})"
        )
    return name, params


def make_scenario(
    spec: str,
    n: int,
    rounds: int = 256,
    write_rounds: int = 16,
    seed: int = 0,
) -> Scenario:
    """Compile a ``name[:k=v,...]`` spec for an ``n``-node cluster."""
    name, params = parse_scenario_spec(spec)
    return SCENARIOS[name](n, rounds, write_rounds, seed, **params)
