"""Invariant checkers: the assertions that must hold under ANY fault mix.

Chaos injection is only evidence if something checks the wreckage. These
checkers run host-side between driver chunks (opt-in — one extra
device→host read of the bookkeeping planes per chunk) and accumulate
:class:`InvariantViolation` records instead of raising, so a soak run
reports every broken property, not just the first:

- **head monotonicity** — a node's applied version head per actor never
  decreases: loss, duplication, churn and partitions may stall progress
  but can never un-apply a version (the reference's bookkeeping is
  insert-or-max, never decrement);
- **bookkeeping conservation** — every emitted message is accounted for,
  round by round: ``sent + matured == parked + emit_lost + delivered +
  unreachable + blackholed + lost`` (the fault metrics from
  ``engine/step.py``; checkable only while faults are enabled, which is
  when it matters);
- **convergence honesty** — when the driver reports convergence, every
  pair of live same-partition nodes must actually agree on table state
  (checked pairwise against a per-partition reference replica);
- **SWIM liveness honesty** — a node that has been up and reachable by
  an observer for longer than the suspicion window (plus refutation
  slack) must not be marked DOWN in that observer's belief: the failure
  detector may be slow, never permanently wrong about a live peer.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["InvariantChecker", "InvariantViolation", "merge_reports"]


def merge_reports(reports: list) -> dict:
    """Fold many per-run checker reports (``InvariantChecker.report()``
    dicts) into one summary — the sweep engine grades every lane with
    its own checker, and the matrix report needs the one-line verdict:
    overall ok, total chunks checked, and the violations with their
    originating lane index attached."""
    violations = []
    chunks = 0
    for i, rep in enumerate(reports):
        if rep is None:
            continue
        chunks += int(rep.get("chunks_checked", 0))
        for v in rep.get("violations", []):
            violations.append({"lane": i, **v})
    return {
        "ok": not violations,
        "lanes_checked": sum(1 for r in reports if r is not None),
        "chunks_checked": chunks,
        "violations": violations,
    }


@dataclasses.dataclass
class InvariantViolation:
    round: int | None  # absolute 0-based round (None: end-of-run check)
    invariant: str
    detail: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class InvariantChecker:
    """Accumulating per-chunk invariant checker for ``run_sim``.

    Pass one via ``run_sim(..., invariants=InvariantChecker(cfg))``;
    read ``.violations`` / ``.report()`` afterwards. Stateless apart
    from the previous chunk's snapshots, so one instance covers one run.
    """

    def __init__(self, cfg, round_offset: int = 0):
        self.cfg = cfg
        self.violations: list[InvariantViolation] = []
        self.chunks_checked = 0
        self._prev_head: np.ndarray | None = None
        # (N, N) rounds each directed pair has been continuously
        # mutually-reachable with both ends up — the SWIM check's clock
        self._reach_streak: np.ndarray | None = None
        # scheduled node wipes (faults/nodes.py): the ONE sanctioned way
        # an applied head may decrease — a crash-restart losing its DB is
        # the fault being injected, not a bookkeeping bug. Only the
        # scheduled (node, round) entries are exempt, and only for the
        # chunk the wipe lands in; any other decrease still violates.
        # ``round_offset``: what-if forks (corro_sim/engine/twin.py)
        # schedule faults at ABSOLUTE state rounds (fork round + k)
        # while the driver frame starts at 0 — map the exemptions back.
        self._wipe_schedule = tuple(
            (n, r - int(round_offset))
            for n, r in cfg.node_faults.wipe_schedule()
        )

    # ------------------------------------------------------------- checks
    def on_chunk(self, state, metrics, alive, part, start_round):
        """Run every per-chunk invariant; returns the NEW violations.

        ``alive``/``part``: the chunk's ground-truth schedule rows
        ((chunk, n)); ``start_round``: absolute 0-based round of the
        chunk's first row."""
        new: list[InvariantViolation] = []
        alive = np.asarray(alive, bool)
        part = np.asarray(part)
        chunk = alive.shape[0]
        self.chunks_checked += 1

        # ---- applied-head monotonicity per (node, actor)
        head = np.asarray(state.book.head)
        if self._prev_head is not None:
            dec = head < self._prev_head
            for node, r in self._wipe_schedule:
                if start_round <= r < start_round + chunk:
                    dec[node, :] = False  # scheduled crash-restart wipe
            if dec.any():
                i, a = np.argwhere(dec)[0]
                new.append(InvariantViolation(
                    start_round + chunk - 1, "head_monotonicity",
                    f"book.head[{i}, {a}] decreased "
                    f"{int(self._prev_head[i, a])} → {int(head[i, a])} "
                    f"(+{int(dec.sum()) - 1} more entries)",
                ))
        self._prev_head = head

        # ---- bookkeeping conservation (fault metrics present ⇔ faults on)
        if "fault_delivered" in metrics:
            sent = np.asarray(metrics["msgs_sent"], np.int64)
            lhs = sent + np.asarray(metrics["fault_matured"], np.int64)
            rhs = (
                np.asarray(metrics["fault_parked"], np.int64)
                + np.asarray(metrics["fault_emit_lost"], np.int64)
                + np.asarray(metrics["fault_delivered"], np.int64)
                + np.asarray(metrics["fault_unreachable"], np.int64)
                + np.asarray(metrics["fault_blackholed"], np.int64)
                + np.asarray(metrics["fault_lost"], np.int64)
            )
            bad = lhs != rhs
            if bad.any():
                t = int(np.argmax(bad))
                new.append(InvariantViolation(
                    start_round + t, "conservation",
                    f"sent+matured={int(lhs[t])} != parked+emit_lost+"
                    f"delivered+unreachable+blackholed+lost={int(rhs[t])}"
                    f" ({int(bad.sum())} bad rounds in chunk)",
                ))

        # ---- SWIM: no live long-reachable node marked DOWN
        self._update_reach_streak(alive, part)
        if self.cfg.swim_enabled:
            v = self._check_swim(state, alive[-1], start_round + chunk - 1)
            if v is not None:
                new.append(v)

        self.violations.extend(new)
        return new

    def _update_reach_streak(self, alive, part):
        n = alive.shape[1]
        if self._reach_streak is None:
            self._reach_streak = np.zeros((n, n), np.int64)
        for t in range(alive.shape[0]):
            reach = (
                alive[t][:, None] & alive[t][None, :]
                & (part[t][:, None] == part[t][None, :])
            )
            self._reach_streak = np.where(
                reach, self._reach_streak + 1, 0
            )

    def _swim_window_rounds(self) -> int:
        """Rounds a (kill → refutation-gossip) cycle may legitimately
        take: suspicion timeout + announce cadence + dissemination slack,
        all stretched by the SWIM tick interval."""
        cfg = self.cfg
        return int(cfg.swim_interval) * (
            int(cfg.swim_suspect_rounds)
            + int(cfg.swim_announce_interval) + 8
        )

    def _check_swim(self, state, alive_now, round_idx):
        window = self._swim_window_rounds()
        ok_pairs = self._reach_streak > window  # (observer, subject)
        if not ok_pairs.any():
            return None
        from corro_sim.membership.swim import down_belief_matrix

        n = alive_now.shape[0]
        # [observer, subject] — the canonical belief decoding, shared so
        # a layout change cannot silently desync this checker
        down_belief = down_belief_matrix(state.swim, n)
        bad = down_belief & ok_pairs & alive_now[:, None]
        if bad.any():
            i, j = np.argwhere(bad)[0]
            return InvariantViolation(
                round_idx, "swim_false_down",
                f"observer {i} believes live node {j} DOWN after "
                f"{int(self._reach_streak[i, j])} rounds of mutual "
                f"reachability (window {window})",
            )
        return None

    def on_converged(self, state, alive_now, part_now):
        """The convergence-honesty check: called by the driver at the
        moment it reports convergence. Every live node must agree with
        its partition's reference replica on the full table state."""
        new: list[InvariantViolation] = []
        alive_now = np.asarray(alive_now, bool)
        part_now = np.asarray(part_now)
        cv = np.asarray(state.table.cv)
        vr = np.asarray(state.table.vr)
        cl = np.asarray(state.table.cl)
        for pid in np.unique(part_now[alive_now]):
            members = np.nonzero(alive_now & (part_now == pid))[0]
            if len(members) < 2:
                continue
            ref = members[0]
            for m in members[1:]:
                if not (
                    np.array_equal(cv[ref], cv[m])
                    and np.array_equal(vr[ref], vr[m])
                    and np.array_equal(cl[ref], cl[m])
                ):
                    ncell = int(
                        (cv[ref] != cv[m]).sum() + (vr[ref] != vr[m]).sum()
                    )
                    new.append(InvariantViolation(
                        None, "convergence_disagreement",
                        f"converged reported but live nodes {int(ref)} and "
                        f"{int(m)} (partition {int(pid)}) differ on "
                        f"~{ncell} cells",
                    ))
                    break  # one witness per partition is enough
        self.violations.extend(new)
        return new

    # ------------------------------------------------------------ reporting
    @property
    def ok(self) -> bool:
        return not self.violations

    def report(self) -> dict:
        return {
            "ok": self.ok,
            "chunks_checked": self.chunks_checked,
            "violations": [v.as_dict() for v in self.violations],
        }
