"""On-device fault kernels: the jax side of :class:`FaultConfig`.

Everything here is statically gated on ``cfg.faults`` — a disabled knob
contributes zero traced ops, so the default step program stays
bit-identical to the fault-free one (tests/test_faults.py guard).

Key discipline: the fault lane derives its randomness by ``fold_in`` on
the round key with a fixed tag, NOT by widening the step's 9-way split.
That keeps every existing subkey (writes, broadcast, SWIM, sync)
untouched whether faults are on or off, and lets the repair-specialized
step derive the identical fault keys — the bit-for-bit equivalence the
driver's post-quiesce program switch depends on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from corro_sim.engine.features import FeatureLeaf, register_feature
from corro_sim.faults.masks import pairs_to_mask

# Pre-registry feature (engine/features.py): the Gilbert burst-loss
# Markov plane keeps its placeholder-field layout (SimState.fault_burst,
# a (1,) stub when burst loss is off) — re-homing it into the features
# dict would re-key every committed step program. Builder + scrub rule
# live here so the faults module owns its plane end to end.
register_feature(FeatureLeaf(
    name="fault_burst",
    # a sweep with any bursting lane arms the plane for EVERY lane —
    # burst-free lanes carry enter=0 knobs, which keep it all-False
    # (value-identical to the untraced path; corro_sim/sweep/)
    enabled=lambda cfg: (
        cfg.faults.burst_enter > 0
        or (cfg.sweep.enabled and cfg.sweep.burst)
    ),
    build=lambda cfg, seed: jnp.zeros((cfg.num_nodes,), bool),
    placeholder=lambda cfg: jnp.zeros((1,), bool),
    field="fault_burst",
    volatile=True,
))

# fold_in tag for the fault key lane (arbitrary constant, fixed forever:
# changing it changes every seeded fault stream). Folded on the ROUND
# key itself — the fault lane is a sibling of the step's 9-way
# STEP_KEY_STREAMS split, not a child of it — so the key-lineage
# auditor (analysis/keys.py, K2) proves it disjoint from every
# subsystem stream by construction: different parent, distinct tag.
FAULT_KEY_TAG = 0x0FA17


def fault_keys(key: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(k_burst, k_link, k_sync) — the per-round fault subkeys.

    Derived identically by the full and repair step programs (both hold
    the same round key), so the fault stream is invariant under the
    driver's post-quiesce program specialization. It is a function of
    the ROUND KEY — which ``run_sim`` derives from (seed, chunk index,
    offset) — so exact replay of the stochastic draws needs the same
    seed AND the same chunking, like every other stochastic stream in
    the simulation; only the *scheduled* fault timeline (alive/part
    arrays, events) is chunk-layout-independent.
    """
    kf = jax.random.fold_in(key, FAULT_KEY_TAG)
    k_burst, k_link, k_sync = jax.random.split(kf, 3)
    return k_burst, k_link, k_sync


def blackhole_mask(faults, n: int) -> np.ndarray | None:
    """(N, N) bool host-side constant: True where src→dst silently drops.

    Built from the static ``faults.blackhole`` directed pairs (-1 =
    wildcard; shared expansion in :mod:`corro_sim.faults.masks` so the
    BFS oracle sees the same graph), baked into the program as a
    constant — no runtime cost beyond the gather at the delivery point."""
    if not faults.blackhole:
        return None
    return pairs_to_mask(faults.blackhole, n)


def burst_update(faults, burst: jnp.ndarray, k_burst: jax.Array):
    """Advance the per-node Gilbert burst state one round.

    Two independent uniforms per node: in-burst nodes exit with
    ``burst_exit``, healthy nodes enter with ``burst_enter``. Static
    no-op (returns the placeholder untouched) when the gate is off.
    ``faults`` is a :class:`FaultConfig` or a :class:`LaneFaultKnobs`
    — the gate (``burst_on``) is static either way; the thresholds may
    be per-lane traced scalars under a sweep."""
    if not faults.burst_on:
        return burst
    u = jax.random.uniform(k_burst, (2,) + burst.shape)
    enter = u[0] < faults.burst_enter
    stay = u[1] >= faults.burst_exit
    return jnp.where(burst, stay, enter)


def link_fault_masks(
    faults,
    k_link: jax.Array,
    dst: jnp.ndarray,
    burst: jnp.ndarray,
):
    """(keep, dup) lane masks for the broadcast delivery point.

    ``keep``: survives the Bernoulli loss draw (per-lane, receiver-side
    burst state raises the rate to ``burst_loss``); ``dup``: the lane is
    delivered twice (accounted, not re-merged — the merge paths are
    idempotent per (dst, actor, ver, chunk))."""
    u = jax.random.uniform(k_link, (2,) + dst.shape)
    p = jnp.float32(faults.loss)
    if faults.burst_on:
        p = jnp.where(
            burst[dst], jnp.maximum(p, jnp.float32(faults.burst_loss)), p
        )
    keep = u[0] >= p
    dup = u[1] < jnp.float32(faults.dup)
    return keep, dup


class LaneFaultKnobs:
    """Duck-types :class:`FaultConfig` for the inject kernels with
    per-lane TRACED thresholds (the corro_sim/sweep knob leaf): inside
    the vmapped fleet program each lane reads its own loss/dup/burst/
    sync-loss scalars from the carry instead of baked constants, so one
    compiled dispatch races lanes with different fault knobs. The
    static gates (``burst_on``) come from the union SweepConfig —
    gates must never be traced values."""

    __slots__ = (
        "loss", "dup", "burst_enter", "burst_exit", "burst_loss",
        "resolved_sync_loss", "burst_on",
    )

    def __init__(self, knobs: dict, burst_on: bool):
        self.loss = knobs["loss"]
        self.dup = knobs["dup"]
        self.burst_enter = knobs["burst_enter"]
        self.burst_exit = knobs["burst_exit"]
        self.burst_loss = knobs["burst_loss"]
        self.resolved_sync_loss = knobs["sync_loss"]
        self.burst_on = bool(burst_on)


def sync_grant_keep(
    faults,
    k_sync: jax.Array,
    rows: jnp.ndarray,  # (N,) node iota
    peer: jnp.ndarray,  # (N, P) chosen peers
    bh: jnp.ndarray | None,  # (N, N) blackhole constant or None
):
    """(N, P) keep mask for admitted sync connections.

    A grant fails with ``resolved_sync_loss`` (the QUIC stream-drop
    analog) and deterministically when EITHER direction of the
    client↔server edge is blackholed — sync is a request/response
    exchange, so a one-way hole kills the connection either way."""
    u = jax.random.uniform(k_sync, peer.shape)
    keep = u >= jnp.float32(faults.resolved_sync_loss)
    if bh is not None:
        hole = bh[rows[:, None], peer] | bh[peer, rows[:, None]]
        keep = keep & ~hole
    return keep
