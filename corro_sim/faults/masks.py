"""The blackhole wildcard-pair semantics, in exactly one place.

``FaultConfig.blackhole`` is a tuple of directed ``(src, dst)`` pairs
with ``-1`` as a wildcard. Three consumers need the expanded (N, N)
drop mask — the transport injection point (:mod:`inject`), the sync
grant (:mod:`corro_sim.sync.sync` via inject) and the BFS oracle graph
(:mod:`corro_sim.obs.probes`) — and they MUST agree, or the hop/stretch
bounds the chaos tests assert stop meaning anything. numpy-only so the
jax-free obs layer can import it.
"""

from __future__ import annotations

import numpy as np

__all__ = ["pairs_to_mask"]


def pairs_to_mask(pairs, n: int) -> np.ndarray:
    """(N, N) bool: True where src→dst is blackholed.

    ``(s, d)`` drops that directed edge; ``(s, -1)`` drops everything s
    sends (one-way blackhole: it still receives); ``(-1, d)`` drops
    everything d receives. A ``(-1, -1)`` wildcard is ignored — it would
    drop every edge. Vectorized: topology scenarios carry O(N^2) pairs.
    """
    m = np.zeros((n, n), bool)
    if not len(pairs):
        return m
    arr = np.asarray(pairs, dtype=np.int64)
    s, d = arr[:, 0], arr[:, 1]
    exact = (s >= 0) & (d >= 0)
    m[s[exact], d[exact]] = True
    m[s[(s >= 0) & (d < 0)], :] = True
    m[:, d[(s < 0) & (d >= 0)]] = True
    return m
