"""Chaos engine: on-device fault injection, named failure scenarios, and
invariant-checked soak runs.

Corrosion's value claim is that gossip + anti-entropy *recover* — from
lossy links, crashed agents and partitions — and gossip theory guarantees
convergence precisely under asynchrony and message loss (PAPERS:
"Asynchrony and Acceleration in Gossip Algorithms"; SWARM). A simulator
whose network never fails can only produce happy-path numbers. This
package makes faults first-class:

- :mod:`inject` — the jax kernels behind :class:`corro_sim.config.
  FaultConfig`: seeded Bernoulli loss/duplication masks, Gilbert
  burst-loss Markov state and asymmetric blackhole masks, applied at the
  two transport points in ``engine/step.py`` (broadcast delivery and the
  anti-entropy lane grant);
- :mod:`scenarios` — named, seeded failure generators (``rolling_restart``,
  ``flapper``, ``split_brain_heal``, ``churn``, ``lossy``,
  ``blackhole_one_way``, …) that compile into vectorized ``Schedule``
  arrays plus fault-config overrides, parseable from ``name[:k=v,...]``
  spec strings (CLI ``--scenario``, ``CORRO_BENCH_SCENARIO``,
  ``LiveCluster.load_scenario``);
- :mod:`invariants` — per-chunk assertions that must hold under ANY fault
  mix (applied-head monotonicity, bookkeeping conservation, no
  convergence while a live pair disagrees, SWIM never falsely DOWN), and
  the soak harness behind ``corro-sim soak``;
- :mod:`nodes` — the node-lifecycle fault domain: crash-restart with
  amnesia, stale rejoin from a snapshot leaf, per-node HLC clock skew,
  and straggler duty cycles, landing as registry feature leaves
  (``engine/features.py``) so disabled configs stay byte-identical;
- :mod:`scorecard` — the resilience scorecard grading recovery
  (recovery_rounds, rows_lost, resync_rows, SWIM false-down/flaps,
  sub-delivery degradation under a coupled workload) against the
  committed threshold golden.
"""

from corro_sim.faults.invariants import (
    InvariantChecker,
    InvariantViolation,
    merge_reports,
)
from corro_sim.faults.scenarios import (
    SCENARIOS,
    Scenario,
    make_scenario,
    parse_scenario_spec,
)
from corro_sim.faults.scorecard import (
    ResilienceScorecard,
    check_thresholds,
    fifo_delivery_quantiles,
    load_thresholds,
)

__all__ = [
    "SCENARIOS",
    "Scenario",
    "InvariantChecker",
    "InvariantViolation",
    "ResilienceScorecard",
    "check_thresholds",
    "fifo_delivery_quantiles",
    "load_thresholds",
    "make_scenario",
    "merge_reports",
    "parse_scenario_spec",
]
