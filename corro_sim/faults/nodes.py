"""Node-lifecycle fault kernels: the jax side of :class:`NodeFaultConfig`.

Where :mod:`corro_sim.faults.inject` fails *links* (loss, bursts,
blackholes at the two transport points), this module fails *nodes* —
corrosion's real production failure mode: an agent crashes and restarts
with an empty or stale SQLite DB and must full-resync via anti-entropy
(PAPER.md §survey: agents, SWIM, anti-entropy). Four fault kinds, all
compiled from STATIC schedules over the round counter:

- **crash-restart with amnesia** — at a scheduled round the node's
  replica state (table rows, bookkeeping row, gossip rings, SWIM
  beliefs, HLC, last-cleared stamp) wipes to the empty-DB state; the
  node rejoins with an epoch-bumped HLC and SWIM incarnation and
  anti-entropy serves its history back from peers (the global change
  log survives — surviving replicas hold every actor's rows, exactly
  why a rejoining corrosion agent can be rebuilt from the cluster);
- **stale rejoin** — the wipe restores from a row-state snapshot leaf
  captured at an earlier scheduled round (restart from an old backup)
  instead of zero, so sync repays only the delta;
- **HLC clock skew** — a per-node wall-clock offset plane raises the
  physical floor of timestamp generation (``engine/step.py _hlc_tick``),
  exercising LWW tie-breaks and EmptySet-ts gating under skew;
- **stragglers** — per-node duty-cycle masks that skip broadcast
  emission and anti-entropy participation on inactive rounds (the
  overloaded agent whose flush loop falls behind); the node still
  receives, still answers SWIM probes, still commits local writes.

Discipline (the PR 3 pattern): zero new random draws — every mask is a
pure function of ``state.round`` and baked config constants, so the
full and repair-specialized step programs derive IDENTICAL fault
timelines and the driver's post-quiesce program switch stays
bit-for-bit (tests/test_node_faults.py). Disabled knobs trace zero ops
and contribute zero SimState leaves: the two state planes
(``node_epoch`` restart counter, ``node_snapshot`` stale-rejoin
capture) register through :mod:`corro_sim.engine.features` as
dict-style feature leaves, so every non-enabling config's pytree,
jaxpr and compiled-program cache keys stay byte-identical
(tests/test_cache_stability.py pattern; the cache-key manifest enforces
it in CI).

Write-gate soundness note: in this simulator node ordinal == actor id,
so a wiped node cannot mint fresh versions while its own actor column
is still behind the log head — self-bookkeeping assumes
``book.head[i, i] == log.head[i]`` at write time, and breaking it would
claim old version numbers for new content (silently-wrong state, the
exact sharp edge ``io/checkpoint.restore_into`` documents). The step
therefore gates local commits on the node having recovered its own
write cursor (``recovering_mask``) — the reference's agents likewise
reload ``BookedVersions`` before serving writes
(``agent.rs:1334-1403``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from corro_sim.core.crdt import NEG
from corro_sim.engine.features import FeatureLeaf, register_feature

__all__ = [
    "apply_node_faults",
    "recovering_mask",
    "skew_plane",
    "straggler_active",
]


def _snapshot_leaf(cfg, seed):
    """The stale-rejoin capture plane: the node-indexed replica state a
    restart-from-backup restores — table cell planes + bookkeeping rows,
    initialized to the empty-DB values so a restore scheduled before its
    snapshot round degenerates to amnesia instead of garbage."""
    n, r, c, a = (
        cfg.num_nodes, cfg.num_rows, cfg.num_cols, cfg.num_actors,
    )
    return {
        "cv": jnp.zeros((n, r, c), jnp.int32),
        "vr": jnp.full((n, r, c), NEG, jnp.int32),
        "site": jnp.full((n, r, c), -1, jnp.int32),
        "cl": jnp.zeros((n, r), jnp.int32),
        "head": jnp.zeros((n, a), jnp.int32),
        "win": jnp.zeros((n, a), jnp.uint32),
    }


# Registry features (engine/features.py): disabled configs contribute
# NOTHING — no placeholder, no aval — so registering these planes leaves
# every non-enabling config's pytree/jaxpr/cache keys byte-identical.
register_feature(FeatureLeaf(
    name="node_epoch",
    # the vacuous trace threads the plane too — the guard must exercise
    # the real carry, not a special-cased one. A sweep with any wiping
    # lane arms the plane for every lane (corro_sim/sweep/: wipe-free
    # lanes carry never-firing wipe_round=-1 planes).
    enabled=lambda cfg: bool(
        cfg.node_faults.wipe_enabled or cfg.node_faults.trace_vacuous
        or (cfg.sweep.enabled and cfg.sweep.wipe_planes)
    ),
    build=lambda cfg, seed: jnp.zeros((cfg.num_nodes,), jnp.int32),
    volatile=True,
))
register_feature(FeatureLeaf(
    name="node_snapshot",
    enabled=lambda cfg: bool(
        cfg.node_faults.stale
        or (cfg.sweep.enabled and cfg.sweep.stale)
    ),
    build=_snapshot_leaf,
    volatile=True,
))


def _mask_at(nodes, rounds, n: int, round_) -> jnp.ndarray:
    """(N,) bool: which of the scheduled ``(node, round)`` entries fire
    this round. ``nodes``/``rounds`` are baked host constants (the
    int32 arrays :func:`_sched` builds); duplicates combine via
    scatter-max. Sentinel form (node 0, round -1) never fires but still
    traces the compare + scatter — the vacuous guard's lever."""
    hit = jnp.asarray(rounds) == round_
    return (
        jnp.zeros((n,), bool).at[jnp.asarray(nodes)].max(hit, mode="drop")
    )


def _sched(pairs, vacuous: bool, width: int = 2):
    """Schedule tuples → per-column int32 host constants, substituting a
    never-firing sentinel row when the schedule is empty but the program
    must trace (``trace_vacuous``)."""
    rows = [tuple(int(x) for x in p) for p in pairs]
    if not rows:
        assert vacuous
        rows = [tuple([0] + [-1] * (width - 1))]
    return tuple(np.asarray(col, np.int32) for col in zip(*rows))


def skew_plane(nf, n: int, sweep=None):
    """(N,) int32 per-node wall-clock offset constant for ``_hlc_tick``'s
    physical floor, or None when skew is statically off (the None path
    traces the pre-skew expression exactly). ``sweep``: the per-lane
    knob leaf (corro_sim/sweep/) — when it carries a ``skew`` plane,
    the lane's traced offsets replace the baked constant."""
    if sweep is not None:
        return sweep["skew"] if "skew" in sweep else None
    if not (nf.skew or nf.trace_vacuous):
        return None
    plane = np.zeros((n,), np.int32)
    for node, off in nf.skew:
        plane[int(node)] = int(off)
    return jnp.asarray(plane)


def straggler_active(nf, n: int, round_, sweep=None):
    """(N,) bool participation mask: False while a straggler's duty
    cycle parks it — ``(round + node) % period < active`` (the node-id
    phase decorrelates stragglers so they do not all stall the same
    rounds). None when statically off. Consumers gate broadcast
    emission and sync participation; delivery, SWIM probes and local
    commits stay ungated (a straggler is alive, just slow).

    ``sweep``: the per-lane knob leaf — when it carries duty planes
    the whole mask is the dense per-node form of the same expression
    (non-stragglers ride period=1/active=1, identically True)."""
    if sweep is not None:
        if "straggle_period" not in sweep:
            return None
        ids = jnp.arange(n, dtype=jnp.int32)
        return (
            (round_ + ids) % sweep["straggle_period"]
        ) < sweep["straggle_active"]
    if not (nf.straggle or nf.trace_vacuous):
        return None
    nodes, period, active = _sched(nf.straggle, nf.trace_vacuous, width=3)
    if not nf.straggle:
        # sentinel: period 1 / active 1 — always participating
        period = np.ones_like(period)
        active = np.ones_like(active)
    nodes_a = jnp.asarray(nodes)
    act = ((round_ + nodes_a) % jnp.asarray(period)) < jnp.asarray(active)
    return jnp.ones((n,), bool).at[nodes_a].min(act, mode="drop")


def recovering_mask(book, log) -> jnp.ndarray:
    """(N,) bool: nodes whose own actor column is still behind the log
    head — the post-wipe resync window during which local commits are
    gated (module docstring) and the ``node_fault_recovering`` metric's
    definition (ONE expression, shared so the write gate and the metric
    cannot drift). Identically False absent wipes (every node's
    self-bookkeeping tracks its own writes exactly), so the vacuous
    trace is a bit-identical no-op."""
    n = book.head.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    return book.head[rows, rows] < log.head


def apply_node_faults(cfg, state, round_, sweep=None):
    """The node-fault prologue, applied at the START of a round by BOTH
    step programs: capture stale-rejoin snapshots, then execute every
    wipe scheduled for this round. Returns ``(state, wiped)`` where
    ``wiped`` is the (N,) bool mask of nodes restarted this round (a
    zeros constant when no wipe plane is armed, so the metric surface
    stays static).

    ``sweep``: the per-lane knob leaf (corro_sim/sweep/) — when it
    carries wipe planes, the fire masks derive from per-lane TRACED
    round planes (``wipe_round``/``wipe_stale``/``snap_round``, one
    wipe per node, -1 = never) instead of baked schedule constants, so
    one vmapped program executes a different wipe timeline per lane.
    The restore tail is shared verbatim with the static path — the two
    cannot drift.

    Wipe semantics (the empty-SQLite restart): table cell planes and the
    bookkeeping row reset to init values (or the snapshot's, for stale
    entries — amnesia wins if both fire), gossip pending rings drop
    (the in-memory queue dies with the process), SWIM membership renews
    with a bumped incarnation (:func:`membership.swim.renew_membership`),
    the HLC reboots from the wall clock plus the epoch jump, and the
    last-cleared stamp forgets. NOT wiped: the global change log and
    per-version cleared stamps (actor history survives at peers), the
    in-flight delay ring (packets already on the wire), link-level fault
    state, RTT observations (link properties), and the probe tracer
    (an observer, not node state)."""
    nf = cfg.node_faults
    if sweep is not None and "wipe_round" not in sweep:
        sweep = None  # sweeping, but no lane arms the wipe planes
    if sweep is None and not (nf.wipe_enabled or nf.trace_vacuous):
        return state, jnp.zeros((cfg.num_nodes,), bool)
    n = cfg.num_nodes
    feats = dict(state.features)
    table, book = state.table, state.book

    def _captured(cap, snap):
        """Stale-rejoin snapshot capture (before any wipe this round: a
        same-round capture+restore degenerates to an identity wipe)."""
        return {
            "cv": jnp.where(cap[:, None, None], table.cv, snap["cv"]),
            "vr": jnp.where(cap[:, None, None], table.vr, snap["vr"]),
            "site": jnp.where(
                cap[:, None, None], table.site, snap["site"]
            ),
            "cl": jnp.where(cap[:, None], table.cl, snap["cl"]),
            "head": jnp.where(cap[:, None], book.head, snap["head"]),
            "win": jnp.where(cap[:, None], book.win, snap["win"]),
        }

    if sweep is not None:
        # per-lane traced wipe planes: one wipe per node, -1 = never
        stale_on = "snap_round" in sweep
        if stale_on:
            feats["node_snapshot"] = _captured(
                sweep["snap_round"] == round_, feats["node_snapshot"]
            )
        fire = sweep["wipe_round"] == round_
        if stale_on:
            sv = fire & sweep["wipe_stale"]
            am = fire & ~sweep["wipe_stale"]
        else:
            sv = None
            am = fire
        epoch_jump = sweep["epoch_jump"]
    else:
        # ---- static schedules baked as host constants
        stale_on = bool(nf.stale)
        if stale_on:
            s_nodes = [int(x[0]) for x in nf.stale]
            s_caps = [int(x[1]) for x in nf.stale]
            s_restores = [int(x[2]) for x in nf.stale]
            feats["node_snapshot"] = _captured(
                _mask_at(s_nodes, s_caps, n, round_),
                feats["node_snapshot"],
            )
            sv = _mask_at(s_nodes, s_restores, n, round_)
        else:
            sv = None
        # ---- wipe masks: amnesia + stale restores
        if nf.crash or (nf.trace_vacuous and not stale_on):
            c_nodes, c_rounds = _sched(nf.crash, nf.trace_vacuous)
            am = _mask_at(c_nodes, c_rounds, n, round_)
        else:
            am = jnp.zeros((n,), bool)
        epoch_jump = jnp.int32(nf.epoch_jump)
    wiped = am | sv if sv is not None else am

    # ---- restore sources: empty-DB init values, snapshot where stale
    # (amnesia wins a same-round collision — the fresher failure)
    def pick(live, zero, snap_v=None, expand=1):
        w = wiped.reshape(wiped.shape + (1,) * expand)
        if snap_v is None:
            return jnp.where(w, zero, live)
        a = am.reshape(am.shape + (1,) * expand)
        return jnp.where(w, jnp.where(a, zero, snap_v), live)

    snap = feats.get("node_snapshot")
    table = table.replace(
        cv=pick(table.cv, 0, snap["cv"] if stale_on else None, 2),
        vr=pick(table.vr, NEG, snap["vr"] if stale_on else None, 2),
        site=pick(table.site, -1, snap["site"] if stale_on else None, 2),
        cl=pick(table.cl, 0, snap["cl"] if stale_on else None, 1),
    )
    book = book.replace(
        head=pick(book.head, 0, snap["head"] if stale_on else None, 1),
        win=pick(
            book.win, jnp.uint32(0),
            snap["win"] if stale_on else None, 1,
        ),
    )
    # the in-memory broadcast queue dies with the process (amnesia and
    # stale alike — a disk backup never holds it)
    gossip = state.gossip.replace(
        pend=jnp.where(wiped[:, None, None], 0, state.gossip.pend),
        cursor=jnp.where(wiped, 0, state.gossip.cursor),
    )
    swim = state.swim
    if cfg.swim_enabled:
        from corro_sim.membership.swim import renew_membership

        swim = renew_membership(swim, wiped)
    # epoch-bumped HLC reboot: the restart epoch rides the node_epoch
    # leaf; the clock restarts at the wall clock (round) plus the
    # configured per-epoch jump, and _hlc_tick's max keeps it monotone
    epoch = feats["node_epoch"] + wiped.astype(jnp.int32)
    feats["node_epoch"] = epoch
    hlc = jnp.where(
        wiped,
        (round_ + epoch_jump * epoch).astype(jnp.int32),
        state.hlc,
    )
    last_cleared = jnp.where(wiped, -1, state.last_cleared)
    return state.replace(
        table=table, book=book, gossip=gossip, swim=swim, hlc=hlc,
        last_cleared=last_cleared, features=feats,
    ), wiped
