"""SWIM failure detection as a vmapped per-node automaton.

The reference embeds the ``foca`` SWIM implementation in a dedicated
single-threaded loop (``corro-agent/src/broadcast/mod.rs:120-375``) and
consumes its MemberUp/MemberDown notifications to drive the members map
(``agent/handlers.rs:267-373``). The protocol surface reproduced here:

- each round a node *pings* one random member it believes is up; on no ack
  it launches ``num_indirect_probes`` indirect probes through random
  intermediaries (SWIM's ping-req);
- no ack at all → the member is marked **suspect** with its current
  incarnation; a suspect not refuted within the timeout becomes **down**;
- a node that learns it is suspected/declared-down *refutes* by bumping its
  incarnation — the reference's identity ``renew()`` auto-rejoin
  (``corro-types/src/actor.rs:199-210``);
- membership knowledge disseminates epidemically. foca piggybacks updates
  on gossip datagrams (≤1178 B, ``broadcast/mod.rs:743``); the simulator
  exchanges view rows with ``swim_gossip_peers`` random peers per round
  and merges by ``(incarnation, status-severity)`` — same fixed point,
  bounded per-round traffic.

State is ONE (N, N) uint32 plane — node i's belief about member j, packed
as ``inc << 18 | status << 16 | since`` — sharded over the observer axis.
The packing is chosen so that plain integer ``max`` IS the foca
update-precedence merge: higher incarnation wins, then higher status
severity (down > suspect > alive), then the later suspicion start (a
conservative tie-break — suspicion times out later). Every exchange —
pull gather, push scatter, announce — is therefore a single masked
max over one plane instead of a three-plane gather/merge/select dance;
at 10k nodes that is 400 MB of state instead of 900 MB and ~3x less HBM
traffic per tick (the round profile had the three-plane SWIM tick at
167 ms of a 373 ms round).

Field widths: ``since`` is the suspicion-start round mod 2^16 (timeouts
compare mod-2^16, exact while suspicions resolve within 65k rounds —
they resolve within ``swim_suspect_rounds``); ``inc`` has 14 bits, and
refutation saturates at 16383 rather than wrapping (wrap would reset
precedence to zero and permanently lose every merge). Saturation is not
free: at equal incarnation the higher SEVERITY wins, so a node pinned at
16383 can no longer refute a DOWN verdict — but reaching it takes 16k
suspect/refute cycles of one node, far beyond any simulated scenario,
and the admin ``cluster rejoin`` path clamps identically
(``harness/cluster.py``) so the wrap bug cannot be triggered from there.
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from corro_sim.config import SimConfig

ALIVE = jnp.int8(0)
SUSPECT = jnp.int8(1)
DOWN = jnp.int8(2)

_STATUS_SHIFT = jnp.uint32(16)
_INC_SHIFT = jnp.uint32(18)
_SINCE_MASK = jnp.uint32(0xFFFF)
_STATUS_MASK = jnp.uint32(3 << 16)
INC_MAX = (1 << 14) - 1  # saturation bound for the packed inc field
_INC_MAX = jnp.uint32(INC_MAX)


def pack_swim(status, inc, since) -> jnp.ndarray:
    """(status, inc, since) planes → one packed uint32 plane."""
    return (
        (jnp.asarray(inc).astype(jnp.uint32) << _INC_SHIFT)
        | (jnp.asarray(status).astype(jnp.uint32) << _STATUS_SHIFT)
        | (jnp.asarray(since).astype(jnp.uint32) & _SINCE_MASK)
    )


@flax.struct.dataclass
class SwimState:
    p: jnp.ndarray  # (N, N) uint32 — packed (inc, status, since)

    # unpacked read-only views (metrics, admin surface, tests)
    @property
    def status(self) -> jnp.ndarray:
        return ((self.p >> _STATUS_SHIFT) & jnp.uint32(3)).astype(jnp.int8)

    @property
    def inc(self) -> jnp.ndarray:
        return (self.p >> _INC_SHIFT).astype(jnp.int32)

    @property
    def since(self) -> jnp.ndarray:
        return (self.p & _SINCE_MASK).astype(jnp.int32)


def make_swim_state(num_nodes: int, enabled: bool = True) -> SwimState:
    n = num_nodes if enabled else 1
    return SwimState(p=jnp.zeros((n, n), jnp.uint32))


def down_belief_matrix(sw, n: int):
    """(observer, subject) bool numpy matrix: who currently believes whom
    DOWN (status >= 2). Host-side, handles BOTH belief layouts — the full
    (N, N) plane and the windowed member/belief state — so every consumer
    (the SWIM false-DOWN invariant checker, admin introspection) decodes
    beliefs one way and cannot drift."""
    import numpy as np

    status = np.asarray(sw.status)
    if hasattr(sw, "member"):  # windowed O(N·K) belief state
        member = np.asarray(sw.member)
        out = np.zeros((n, n), bool)
        obs = np.broadcast_to(np.arange(n)[:, None], member.shape)
        hit = (member >= 0) & (status >= 2)
        out[obs[hit], member[hit]] = True
        return out
    return status >= 2


def view_alive(swim: SwimState) -> jnp.ndarray:
    """(N, N) bool: who each node would still gossip/sync with.

    Suspects remain targets (SWIM keeps talking to suspects — that is how
    they get the chance to refute); only DOWN members are excluded, matching
    the reference's members map dropping on MemberDown
    (``handlers.rs:280-330``).
    """
    return (swim.p & _STATUS_MASK) < (
        jnp.uint32(DOWN) << _STATUS_SHIFT
    )


def swim_step(
    cfg: SimConfig,
    swim: SwimState,
    key: jax.Array,
    alive: jnp.ndarray,  # (N,) ground-truth up mask
    reachable,  # callable (src, dst) -> bool mask, ground truth links
    round_idx: jnp.ndarray,
):
    """One SWIM protocol round for every node at once."""
    p = swim.p
    n = p.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    k_tgt, k_ind, k_ex = jax.random.split(key, 3)
    rnd16 = round_idx.astype(jnp.uint32) & _SINCE_MASK

    # --- probe: one random target each -------------------------------------
    tgt = jax.random.randint(k_tgt, (n,), 0, n, dtype=jnp.int32)
    cur = p[rows, tgt]  # (N,) packed belief about the probe target
    cur_status = (cur >> _STATUS_SHIFT) & jnp.uint32(3)
    probing = alive & (tgt != rows) & (cur_status < jnp.uint32(DOWN))

    direct_ack = probing & alive[tgt] & reachable(rows, tgt)

    inter = jax.random.randint(
        k_ind, (n, cfg.swim_indirect_probes), 0, n, dtype=jnp.int32
    )
    ind_ok = (
        alive[inter]
        & alive[tgt][:, None]
        & reachable(rows[:, None], inter)
        & reachable(inter, tgt[:, None])
    ).any(axis=1)
    acked = direct_ack | (probing & ind_ok)
    failed = probing & ~acked

    # --- apply probe outcome to the prober's row ---------------------------
    newly_suspect = failed & (cur_status == jnp.uint32(ALIVE))
    # an ack refutes only our own suspicion at the same incarnation
    refuted = acked & (cur_status == jnp.uint32(SUSPECT))
    new_status = jnp.where(
        newly_suspect,
        jnp.uint32(SUSPECT),
        jnp.where(refuted, jnp.uint32(ALIVE), cur_status),
    )
    new_since = jnp.where(newly_suspect, rnd16, cur & _SINCE_MASK)
    new_p = (
        (cur & ~(_STATUS_MASK | _SINCE_MASK))
        | (new_status << _STATUS_SHIFT)
        | new_since
    )
    p = p.at[rows, tgt].set(jnp.where(probing, new_p, cur))

    # --- suspicion timeout → down -----------------------------------------
    status_pl = (p >> _STATUS_SHIFT) & jnp.uint32(3)
    elapsed = (rnd16 - (p & _SINCE_MASK)) & _SINCE_MASK  # mod-2^16
    timed_out = (
        (status_pl == jnp.uint32(SUSPECT))
        & (elapsed >= jnp.uint32(cfg.swim_suspect_rounds))
        & alive[:, None]
    )
    p = jnp.where(
        timed_out,
        (p & ~_STATUS_MASK) | (jnp.uint32(DOWN) << _STATUS_SHIFT),
        p,
    )

    # --- epidemic view exchange -------------------------------------------
    # Two directions per sub-round:
    #  * pull — i merges a random peer's view, but only contacts peers it
    #    believes are up;
    #  * push — every node pushes to a uniformly random target. Fan-in is
    #    whatever the sampling produces (~Poisson(1): real SWIM fan-in
    #    statistics). Concurrent pushes into one receiver combine via a
    #    scatter-max on the packed plane — precedence IS integer order, so
    #    the winner is the same one foca's sequential update application
    #    would pick. The *pusher's* belief gates the contact, which is what
    #    lets a refuted/rejoined node re-enter views that had written it
    #    off (handlers.rs:188-232, actor.rs:199-210). Pull alone
    #    deadlocks: nobody polls a member they believe is DOWN.
    #
    # Payload bound: each datagram carries at most swim_payload_members
    # member entries (the ≤1178 B packet, broadcast/mod.rs:743) — a
    # contiguous block of the member space at a per-sender random phase,
    # like foca cycling its piggyback backlog. >= n means full views.
    cols = jnp.arange(n, dtype=jnp.int32)
    bounded = cfg.swim_payload_members < n
    down_key = jnp.uint32(DOWN) << _STATUS_SHIFT

    def payload_block(key_b):
        """(N, N) bool — which member columns each sender's datagram carries."""
        if not bounded:
            return None
        off = jax.random.randint(key_b, (n,), 0, n, dtype=jnp.int32)
        return ((cols[None, :] - off[:, None]) % n) < cfg.swim_payload_members

    for g in range(cfg.swim_gossip_peers):
        kg_pull, kg_push, kg_bl1, kg_bl2 = jax.random.split(
            jax.random.fold_in(k_ex, g), 4
        )
        peer = jax.random.randint(kg_pull, (n,), 0, n, dtype=jnp.int32)
        can1 = (
            alive
            & alive[peer]
            & reachable(rows, peer)
            & (peer != rows)
            & ((p[rows, peer] & _STATUS_MASK) < down_key)
        )
        can = can1[:, None]
        block = payload_block(kg_bl1)
        if block is not None:
            can = can & block[peer]  # responder picks the datagram contents
        p = jnp.where(can, jnp.maximum(p, p[peer]), p)
        # Every SWIM message carries the SENDER'S identity + incarnation
        # regardless of payload contents (the protocol's message header;
        # foca refutations ride it) — so a contact always heals the
        # contacted entry itself. Without this, a refutation waits for the
        # random payload window to cover the member, which stretches
        # partition heal far beyond what real SWIM does.
        if block is not None:
            self_of_peer = p[peer, peer]
            p = p.at[rows, peer].max(
                jnp.where(can1, self_of_peer, jnp.uint32(0))
            )

        push_tgt = jax.random.randint(kg_push, (n,), 0, n, dtype=jnp.int32)
        ok_push = (
            alive
            & alive[push_tgt]
            & reachable(rows, push_tgt)
            & (push_tgt != rows)
            & ((p[rows, push_tgt] & _STATUS_MASK) < down_key)
        )
        contrib = jnp.where(ok_push[:, None], p, jnp.uint32(0))
        block = payload_block(kg_bl2)
        if block is not None:
            contrib = jnp.where(block, contrib, jnp.uint32(0))
            # sender's own entry always rides the datagram header
            contrib = contrib.at[rows, rows].set(
                jnp.where(ok_push, p[rows, rows], jnp.uint32(0))
            )
        best = jnp.zeros((n, n), jnp.uint32).at[
            jnp.where(ok_push, push_tgt, n)
        ].max(contrib, mode="drop")
        p = jnp.where(alive[:, None], jnp.maximum(p, best), p)

    # --- periodic announce (belief-independent) ----------------------------
    # After a partition both sides can hold each other DOWN; neither pulls
    # nor pushes across (all contact is gated on believed-up). The reference
    # escapes via its periodic SWIM announcer, which dials bootstrap/member
    # addresses regardless of member state (handlers.rs:188-232,
    # ANNOUNCE_INTERVAL agent/mod.rs:32). Model: every k rounds each node
    # exchanges views with one uniformly random member, gated only on the
    # ground-truth link. The down-side node then sees itself DOWN in the
    # merged view and refutes with a higher incarnation (below), which wins
    # subsequent merges — the standard SWIM heal dance.
    def do_announce(p):
        ka = jax.random.fold_in(k_ex, 997)
        perm = jax.random.permutation(ka, n).astype(jnp.int32)
        inv = jnp.argsort(perm).astype(jnp.int32)
        for partner in (perm, inv):
            can = (
                alive & alive[partner] & reachable(rows, partner)
                & (partner != rows)
            )[:, None]
            p = jnp.where(can, jnp.maximum(p, p[partner]), p)
        return p

    # With swim_interval > 1 this step only runs on every k-th gossip
    # round; gating on `round % announce_interval == 0` there would fire
    # every lcm(k, announce_interval) rounds — up to k× rarer than
    # configured, stretching the only partition-heal path. Fire instead on
    # the one tick inside each announce window: exactly one firing per
    # window while swim_interval <= announce_interval, every tick beyond.
    p = jax.lax.cond(
        (round_idx % cfg.swim_announce_interval) < cfg.swim_interval,
        do_announce,
        lambda q: q,
        p,
    )

    # --- refutation / identity renew --------------------------------------
    self_p = p[rows, rows]
    need_refute = alive & ((self_p & _STATUS_MASK) > jnp.uint32(0))
    inc_next = jnp.minimum((self_p >> _INC_SHIFT) + 1, _INC_MAX)
    refreshed = inc_next << _INC_SHIFT  # status ALIVE, since 0
    p = p.at[rows, rows].set(jnp.where(need_refute, refreshed, self_p))

    status_pl = (p >> _STATUS_SHIFT) & jnp.uint32(3)
    metrics = {
        "swim_suspects": (
            (status_pl == jnp.uint32(SUSPECT)) & alive[:, None]
        ).sum(dtype=jnp.int32),
        "swim_down": (
            (status_pl == jnp.uint32(DOWN)) & alive[:, None]
        ).sum(dtype=jnp.int32),
        "swim_probe_failures": failed.sum(dtype=jnp.int32),
    }
    return SwimState(p=p), metrics
