"""SWIM failure detection as a vmapped per-node automaton.

The reference embeds the ``foca`` SWIM implementation in a dedicated
single-threaded loop (``corro-agent/src/broadcast/mod.rs:120-375``) and
consumes its MemberUp/MemberDown notifications to drive the members map
(``agent/handlers.rs:267-373``). The protocol surface reproduced here:

- each round a node *pings* one random member it believes is up; on no ack
  it launches ``num_indirect_probes`` indirect probes through random
  intermediaries (SWIM's ping-req);
- no ack at all → the member is marked **suspect** with its current
  incarnation; a suspect not refuted within the timeout becomes **down**;
- a node that learns it is suspected/declared-down *refutes* by bumping its
  incarnation — the reference's identity ``renew()`` auto-rejoin
  (``corro-types/src/actor.rs:199-210``);
- membership knowledge disseminates epidemically. foca piggybacks updates
  on gossip datagrams (≤1178 B, ``broadcast/mod.rs:743``); the simulator
  exchanges full view rows with ``swim_gossip_peers`` random peers per
  round and merges by ``(incarnation, status-severity)`` — same fixed
  point, bounded per-round traffic.

State is three (N, N) planes — node i's belief about member j — sharded
over the observer axis. The whole cluster's SWIM tick is elementwise +
gathers: no per-node control flow survives.
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from corro_sim.config import SimConfig

ALIVE = jnp.int8(0)
SUSPECT = jnp.int8(1)
DOWN = jnp.int8(2)


@flax.struct.dataclass
class SwimState:
    status: jnp.ndarray  # (N, N) int8 — i's belief about j
    inc: jnp.ndarray  # (N, N) int32 — incarnation i knows for j
    since: jnp.ndarray  # (N, N) int32 — round suspicion started (else 0)


def make_swim_state(num_nodes: int, enabled: bool = True) -> SwimState:
    n = num_nodes if enabled else 1
    return SwimState(
        status=jnp.zeros((n, n), jnp.int8),
        inc=jnp.zeros((n, n), jnp.int32),
        since=jnp.zeros((n, n), jnp.int32),
    )


def view_alive(swim: SwimState) -> jnp.ndarray:
    """(N, N) bool: who each node would still gossip/sync with.

    Suspects remain targets (SWIM keeps talking to suspects — that is how
    they get the chance to refute); only DOWN members are excluded, matching
    the reference's members map dropping on MemberDown
    (``handlers.rs:280-330``).
    """
    return swim.status < DOWN


def _merge_views(status_a, inc_a, since_a, status_b, inc_b, since_b):
    """Pointwise foca update-precedence merge.

    Higher incarnation always wins; at equal incarnation the more severe
    status wins (down > suspect > alive) — i.e. an alive claim only refutes
    suspicion when it carries a *newer* incarnation.
    """
    better = (inc_b > inc_a) | ((inc_b == inc_a) & (status_b > status_a))
    return (
        jnp.where(better, status_b, status_a),
        jnp.where(better, inc_b, inc_a),
        jnp.where(better, since_b, since_a),
    )


def swim_step(
    cfg: SimConfig,
    swim: SwimState,
    key: jax.Array,
    alive: jnp.ndarray,  # (N,) ground-truth up mask
    reachable,  # callable (src, dst) -> bool mask, ground truth links
    round_idx: jnp.ndarray,
):
    """One SWIM protocol round for every node at once."""
    n = swim.status.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    k_tgt, k_ind, k_ex = jax.random.split(key, 3)

    # --- probe: one random target each -------------------------------------
    tgt = jax.random.randint(k_tgt, (n,), 0, n, dtype=jnp.int32)
    probing = alive & (tgt != rows) & (swim.status[rows, tgt] < DOWN)

    direct_ack = probing & alive[tgt] & reachable(rows, tgt)

    inter = jax.random.randint(
        k_ind, (n, cfg.swim_indirect_probes), 0, n, dtype=jnp.int32
    )
    ind_ok = (
        alive[inter]
        & alive[tgt][:, None]
        & reachable(rows[:, None], inter)
        & reachable(inter, tgt[:, None])
    ).any(axis=1)
    acked = direct_ack | (probing & ind_ok)
    failed = probing & ~acked

    # --- apply probe outcome to the prober's row ---------------------------
    cur_inc = swim.inc[rows, tgt]
    cur_status = swim.status[rows, tgt]
    new_status = jnp.where(
        failed & (cur_status == ALIVE), SUSPECT, cur_status
    )
    # an ack refutes only our own suspicion at the same incarnation
    new_status = jnp.where(acked & (cur_status == SUSPECT), ALIVE, new_status)
    new_since = jnp.where(
        failed & (cur_status == ALIVE), round_idx, swim.since[rows, tgt]
    )
    status = swim.status.at[rows, tgt].set(
        jnp.where(probing, new_status, cur_status)
    )
    since = swim.since.at[rows, tgt].set(
        jnp.where(probing, new_since, swim.since[rows, tgt])
    )
    swim = swim.replace(status=status, since=since)

    # --- suspicion timeout → down -----------------------------------------
    timed_out = (
        (swim.status == SUSPECT)
        & (round_idx - swim.since >= cfg.swim_suspect_rounds)
        & alive[:, None]
    )
    swim = swim.replace(status=jnp.where(timed_out, DOWN, swim.status))

    # --- epidemic view exchange -------------------------------------------
    # Two directions per sub-round:
    #  * pull — i merges a random peer's view, but only contacts peers it
    #    believes are up;
    #  * push — every node pushes to a uniformly random target. Fan-in is
    #    whatever the sampling produces (~Poisson(1): some nodes receive
    #    several pushes, some none — real SWIM fan-in statistics, not the
    #    round-1 permutation's exactly-one). Concurrent pushes into one
    #    receiver combine via a scatter-max on the packed (incarnation,
    #    severity) precedence key — the same winner foca's sequential
    #    update application would pick. The *pusher's* belief gates the
    #    contact, which is what lets a refuted/rejoined node re-enter views
    #    that had written it off (handlers.rs:188-232, actor.rs:199-210).
    #    Pull alone deadlocks: nobody polls a member they believe is DOWN.
    #
    # Payload bound: each datagram carries at most swim_payload_members
    # member entries (the ≤1178 B packet, broadcast/mod.rs:743) — a
    # contiguous block of the member space at a per-sender random phase,
    # like foca cycling its piggyback backlog. >= n means full views.
    cols = jnp.arange(n, dtype=jnp.int32)
    bounded = cfg.swim_payload_members < n

    def payload_block(key_b):
        """(N, N) bool — which member columns each sender's datagram carries."""
        if not bounded:
            return None
        off = jax.random.randint(key_b, (n,), 0, n, dtype=jnp.int32)
        return ((cols[None, :] - off[:, None]) % n) < cfg.swim_payload_members

    for g in range(cfg.swim_gossip_peers):
        kg_pull, kg_push, kg_bl1, kg_bl2 = jax.random.split(
            jax.random.fold_in(k_ex, g), 4
        )
        peer = jax.random.randint(kg_pull, (n,), 0, n, dtype=jnp.int32)
        can = (
            alive
            & alive[peer]
            & reachable(rows, peer)
            & (peer != rows)
            & (swim.status[rows, peer] < DOWN)
        )[:, None]
        block = payload_block(kg_bl1)
        if block is not None:
            can = can & block[peer]  # responder picks the datagram contents
        ps, pi, pse = swim.status[peer], swim.inc[peer], swim.since[peer]
        ms, mi, mse = _merge_views(
            swim.status, swim.inc, swim.since, ps, pi, pse
        )
        swim = swim.replace(
            status=jnp.where(can, ms, swim.status),
            inc=jnp.where(can, mi, swim.inc),
            since=jnp.where(can, mse, swim.since),
        )

        push_tgt = jax.random.randint(kg_push, (n,), 0, n, dtype=jnp.int32)
        ok_push = (
            alive
            & alive[push_tgt]
            & reachable(rows, push_tgt)
            & (push_tgt != rows)
            & (swim.status[rows, push_tgt] < DOWN)  # pusher believes tgt up
        )
        # packed precedence key: higher incarnation wins, then severity —
        # exactly _merge_views' "better" ordering as one int
        key_pl = swim.inc * 4 + swim.status.astype(jnp.int32)
        contrib = jnp.where(ok_push[:, None], key_pl, -1)
        block = payload_block(kg_bl2)
        if block is not None:
            contrib = jnp.where(block, contrib, -1)
        best = jnp.full((n, n), -1, jnp.int32).at[
            jnp.where(ok_push, push_tgt, n)
        ].max(contrib, mode="drop")
        # winner's `since` rides along: among key-tied winners take the max
        # (equal (inc, severity); a later suspicion start is conservative)
        at_tgt = best[jnp.where(ok_push, push_tgt, 0)]
        s_contrib = jnp.where(
            (contrib >= 0) & (contrib == at_tgt), swim.since, -1
        )
        since_best = jnp.full((n, n), -1, jnp.int32).at[
            jnp.where(ok_push, push_tgt, n)
        ].max(s_contrib, mode="drop")
        own_key = swim.inc * 4 + swim.status.astype(jnp.int32)
        take = (best > own_key) & alive[:, None]
        swim = swim.replace(
            status=jnp.where(take, (best % 4).astype(jnp.int8), swim.status),
            inc=jnp.where(take, best // 4, swim.inc),
            since=jnp.where(take, since_best, swim.since),
        )

    # --- periodic announce (belief-independent) ----------------------------
    # After a partition both sides can hold each other DOWN; neither pulls
    # nor pushes across (all contact is gated on believed-up). The reference
    # escapes via its periodic SWIM announcer, which dials bootstrap/member
    # addresses regardless of member state (handlers.rs:188-232,
    # ANNOUNCE_INTERVAL agent/mod.rs:32). Model: every k rounds each node
    # exchanges views with one uniformly random member, gated only on the
    # ground-truth link. The down-side node then sees itself DOWN in the
    # merged view and refutes with a higher incarnation (below), which wins
    # subsequent merges — the standard SWIM heal dance.
    def do_announce(swim):
        ka = jax.random.fold_in(k_ex, 997)
        p = jax.random.permutation(ka, n).astype(jnp.int32)
        inv = jnp.argsort(p).astype(jnp.int32)
        for partner in (p, inv):
            can = (
                alive & alive[partner] & reachable(rows, partner)
                & (partner != rows)
            )[:, None]
            ms, mi, mse = _merge_views(
                swim.status, swim.inc, swim.since,
                swim.status[partner], swim.inc[partner], swim.since[partner],
            )
            swim = swim.replace(
                status=jnp.where(can, ms, swim.status),
                inc=jnp.where(can, mi, swim.inc),
                since=jnp.where(can, mse, swim.since),
            )
        return swim

    swim = jax.lax.cond(
        (round_idx % cfg.swim_announce_interval) == 0,
        do_announce,
        lambda s: s,
        swim,
    )

    # --- refutation / identity renew --------------------------------------
    self_status = swim.status[rows, rows]
    self_inc = swim.inc[rows, rows]
    need_refute = alive & (self_status > ALIVE)
    swim = swim.replace(
        status=swim.status.at[rows, rows].set(
            jnp.where(need_refute, ALIVE, self_status)
        ),
        inc=swim.inc.at[rows, rows].set(
            jnp.where(need_refute, self_inc + 1, self_inc)
        ),
    )

    metrics = {
        "swim_suspects": (
            (swim.status == SUSPECT) & alive[:, None]
        ).sum(dtype=jnp.int32),
        "swim_down": ((swim.status == DOWN) & alive[:, None]).sum(
            dtype=jnp.int32
        ),
        "swim_probe_failures": failed.sum(dtype=jnp.int32),
    }
    return swim, metrics
