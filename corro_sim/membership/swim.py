"""SWIM failure detection as a vmapped per-node automaton.

The reference embeds the ``foca`` SWIM implementation in a dedicated
single-threaded loop (``corro-agent/src/broadcast/mod.rs:120-375``) and
consumes its MemberUp/MemberDown notifications to drive the members map
(``agent/handlers.rs:267-373``). The protocol surface reproduced here:

- each round a node *pings* one random member it believes is up; on no ack
  it launches ``num_indirect_probes`` indirect probes through random
  intermediaries (SWIM's ping-req);
- no ack at all → the member is marked **suspect** with its current
  incarnation; a suspect not refuted within the timeout becomes **down**;
- a node that learns it is suspected/declared-down *refutes* by bumping its
  incarnation — the reference's identity ``renew()`` auto-rejoin
  (``corro-types/src/actor.rs:199-210``);
- membership knowledge disseminates epidemically. foca piggybacks updates
  on gossip datagrams (≤1178 B, ``broadcast/mod.rs:743``); the simulator
  exchanges view rows with ``swim_gossip_peers`` random peers per round
  and merges by ``(incarnation, status-severity)`` — same fixed point,
  bounded per-round traffic.

State is ONE (N, N) unsigned plane — node i's belief about member j,
packed as ``inc << inc_shift | status << status_shift | since``. The
packing is chosen so that plain integer ``max`` IS the foca
update-precedence merge: higher incarnation wins, then higher status
severity (down > suspect > alive), then the later suspicion start (a
conservative tie-break — suspicion times out later). Every exchange —
pull gather, push scatter, announce — is therefore a single masked
max over one plane instead of a three-plane gather/merge/select dance;
at 10k nodes that is 400 MB of state instead of 900 MB and ~3x less HBM
traffic per tick (the round profile had the three-plane SWIM tick at
167 ms of a 373 ms round).

Two field layouts share the automaton, selected by the plane's dtype
(:func:`swim_layout`):

- **wide** (``uint32``, the default): ``since`` is the suspicion-start
  round mod 2^16 (timeouts compare mod-2^16, exact while suspicions
  resolve within 65k rounds — they resolve within
  ``swim_suspect_rounds``); ``inc`` has 14 bits, and refutation
  saturates at 16383 rather than wrapping (wrap would reset precedence
  to zero and permanently lose every merge).
- **narrow** (``uint16``, ``SimConfig.narrow_state``): the same packing
  squeezed to ``inc`` 6 bits (saturating at 63), status 2 bits,
  ``since`` mod-2^8 — halving the widest per-node plane's HBM traffic
  again (200 MB at 10k nodes). Bit-exact with the wide plane while
  incarnations stay under 63 and suspicions resolve within 256 rounds
  (``SimConfig.validate`` bounds ``swim_suspect_rounds`` accordingly;
  tests/test_narrow_state.py pins exactness across the scenario
  library and the saturation boundary). The wide layout's wrap caveat
  shrinks with the field: the ``since`` tie-break and the frozen-entry
  timeout compare mod-2^8 instead of mod-2^16, so two concurrent
  suspicions of the same member straddling a multiple of 256 rounds, or
  a belief frozen across one (observer dead > 256 rounds, then
  revived), can order/time out differently from the wide reference —
  same failure mode wide has at multiples of 65536, just a smaller
  window.

Saturation is not free in either layout: at equal incarnation the higher
SEVERITY wins, so a node pinned at the cap can no longer refute a DOWN
verdict — but reaching it takes inc_max suspect/refute cycles of one
node, far beyond any simulated scenario, and the admin ``cluster
rejoin`` path clamps identically (``harness/cluster.py``) so the wrap
bug cannot be triggered from either layout.
"""

from __future__ import annotations

import dataclasses

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from corro_sim.config import SimConfig

ALIVE = jnp.int8(0)
SUSPECT = jnp.int8(1)
DOWN = jnp.int8(2)

# fold_in tags deriving per-exchange keys from the SWIM k_ex lane
# (STEP_KEY_STREAMS[7] → split3[2]). Declared contract for the
# key-lineage auditor (analysis/keys.py, K2 stream disjointness): peer
# exchange g folds tag ``SWIM_PEER_KEY_TAG_BASE + g`` for g in
# range(cfg.swim_gossip_peers); the periodic announce folds
# SWIM_ANNOUNCE_KEY_TAG, which must stay OUTSIDE the peer tag range
# for every admissible swim_gossip_peers (auditor-enforced ceiling).
# Both are shared with the windowed automaton (swim_window.py). Fixed
# forever — changing either re-keys every seeded membership stream.
SWIM_PEER_KEY_TAG_BASE = 0
SWIM_ANNOUNCE_KEY_TAG = 997


@dataclasses.dataclass(frozen=True)
class SwimLayout:
    """Packed-field geometry of one belief plane dtype. All fields are
    PYTHON ints so bit arithmetic against the plane stays weakly typed —
    the ops inherit the plane's dtype instead of promoting to uint32."""

    dtype: object
    status_shift: int
    inc_shift: int
    since_mask: int
    inc_max: int  # refutation saturation bound for the packed inc field

    @property
    def status_mask(self) -> int:
        return 3 << self.status_shift

    @property
    def down_key(self) -> int:
        return int(DOWN) << self.status_shift

    @property
    def full_mask(self) -> int:
        return (1 << (jnp.dtype(self.dtype).itemsize * 8)) - 1

    # positive-int complements: `~mask` on a python int is negative,
    # which an unsigned jnp array refuses — these stay in range
    @property
    def not_status_mask(self) -> int:
        return self.full_mask ^ self.status_mask

    @property
    def inc_only_mask(self) -> int:
        return self.full_mask ^ (self.status_mask | self.since_mask)


WIDE_LAYOUT = SwimLayout(
    dtype=jnp.uint32, status_shift=16, inc_shift=18,
    since_mask=0xFFFF, inc_max=(1 << 14) - 1,
)
NARROW_LAYOUT = SwimLayout(
    dtype=jnp.uint16, status_shift=8, inc_shift=10,
    since_mask=0xFF, inc_max=(1 << 6) - 1,
)

# back-compat: the wide layout's saturation bound (harness rejoin, tests)
INC_MAX = WIDE_LAYOUT.inc_max


def swim_layout(dtype) -> SwimLayout:
    """The field layout a belief plane uses, keyed by its dtype — state
    carries the truth, so consumers cannot disagree with the step.
    Dtypes are static trace-time metadata, never traced values."""
    if np.dtype(dtype) == np.uint16:
        return NARROW_LAYOUT
    return WIDE_LAYOUT


def belief_dtype(narrow: bool):
    return NARROW_LAYOUT.dtype if narrow else WIDE_LAYOUT.dtype


def pack_swim(status, inc, since, dtype=jnp.uint32) -> jnp.ndarray:
    """(status, inc, since) planes → one packed unsigned plane."""
    lo = swim_layout(dtype)
    return (
        (jnp.asarray(inc).astype(lo.dtype) << lo.inc_shift)
        | (jnp.asarray(status).astype(lo.dtype) << lo.status_shift)
        | (jnp.asarray(since).astype(lo.dtype) & lo.since_mask)
    )


@flax.struct.dataclass
class SwimState:
    p: jnp.ndarray  # (N, N) uint32/uint16 — packed (inc, status, since)

    # unpacked read-only views (metrics, admin surface, tests)
    @property
    def status(self) -> jnp.ndarray:
        lo = swim_layout(self.p.dtype)
        return ((self.p >> lo.status_shift) & 3).astype(jnp.int8)

    @property
    def inc(self) -> jnp.ndarray:
        lo = swim_layout(self.p.dtype)
        return (self.p >> lo.inc_shift).astype(jnp.int32)

    @property
    def since(self) -> jnp.ndarray:
        lo = swim_layout(self.p.dtype)
        return (self.p & lo.since_mask).astype(jnp.int32)


def make_swim_state(
    num_nodes: int, enabled: bool = True, narrow: bool = False
) -> SwimState:
    n = num_nodes if enabled else 1
    return SwimState(p=jnp.zeros((n, n), belief_dtype(narrow)))


def down_belief_matrix(sw, n: int):
    """(observer, subject) bool numpy matrix: who currently believes whom
    DOWN (status >= 2). Host-side, handles BOTH belief layouts — the full
    (N, N) plane and the windowed member/belief state — so every consumer
    (the SWIM false-DOWN invariant checker, admin introspection) decodes
    beliefs one way and cannot drift."""
    import numpy as np

    status = np.asarray(sw.status)
    if hasattr(sw, "member"):  # windowed O(N·K) belief state
        member = np.asarray(sw.member)
        out = np.zeros((n, n), bool)
        obs = np.broadcast_to(np.arange(n)[:, None], member.shape)
        hit = (member >= 0) & (status >= 2)
        out[obs[hit], member[hit]] = True
        return out
    return status >= 2


def renew_membership(swim_state, wipe: jnp.ndarray):
    """Crash-restart a masked set of nodes' membership state, on-device
    (corro_sim/faults/nodes.py; the traced analog of the admin ``cluster
    rejoin`` path in harness/cluster.py): each wiped node's belief row is
    reset to the empty-DB state and its SELF entry comes back ALIVE at a
    bumped (saturating) incarnation — the foca identity ``renew()`` that
    lets peers holding a DOWN verdict re-admit it (``actor.rs:199-210``).
    The pre-wipe self-incarnation is read before the reset: a node's own
    inc is the max of every belief about it (refutation always bumps
    past the suspicion it answers), so old_inc + 1 outranks any DOWN
    entry a peer still gossips. Handles BOTH layouts — the full (N, N)
    plane and the windowed member/belief state — like
    :func:`down_belief_matrix`, so the step cannot drift from the admin
    surface. ``wipe`` is an (N,) bool mask; untouched rows pass through
    bit-identically (an all-False mask is a traced no-op)."""
    if hasattr(swim_state, "member"):  # windowed O(N·K) belief state
        lo = swim_layout(swim_state.belief.dtype)
        n = swim_state.member.shape[0]
        old_inc = swim_state.belief[:, 0] >> lo.inc_shift
        renewed = (
            jnp.minimum(old_inc + 1, lo.inc_max) << lo.inc_shift
        ).astype(lo.dtype)
        member = jnp.where(
            wipe[:, None],
            jnp.full_like(swim_state.member, -1).at[:, 0].set(
                jnp.arange(n, dtype=jnp.int32)
            ),
            swim_state.member,
        )
        belief = jnp.where(
            wipe[:, None], jnp.zeros_like(swim_state.belief),
            swim_state.belief,
        )
        belief = belief.at[:, 0].set(
            jnp.where(wipe, renewed, belief[:, 0])
        )
        cursor = jnp.where(wipe, 1, swim_state.cursor)
        return swim_state.replace(
            member=member, belief=belief, cursor=cursor
        )
    lo = swim_layout(swim_state.p.dtype)
    p = swim_state.p
    n = p.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    old_inc = p[rows, rows] >> lo.inc_shift
    renewed = (
        jnp.minimum(old_inc + 1, lo.inc_max) << lo.inc_shift
    ).astype(lo.dtype)
    p = jnp.where(wipe[:, None], jnp.zeros_like(p), p)
    p = p.at[rows, rows].set(jnp.where(wipe, renewed, p[rows, rows]))
    return swim_state.replace(p=p)


def view_alive(swim: SwimState) -> jnp.ndarray:
    """(N, N) bool: who each node would still gossip/sync with.

    Suspects remain targets (SWIM keeps talking to suspects — that is how
    they get the chance to refute); only DOWN members are excluded, matching
    the reference's members map dropping on MemberDown
    (``handlers.rs:280-330``).
    """
    lo = swim_layout(swim.p.dtype)
    return (swim.p & lo.status_mask) < lo.down_key


def swim_step(
    cfg: SimConfig,
    swim: SwimState,
    key: jax.Array,
    alive: jnp.ndarray,  # (N,) ground-truth up mask
    reachable,  # callable (src, dst) -> bool mask, ground truth links
    round_idx: jnp.ndarray,
    suspect_rounds=None,  # traced per-lane override (sweep sim_knobs);
    # None = the baked cfg.swim_suspect_rounds constant
):
    """One SWIM protocol round for every node at once."""
    p = swim.p
    lo = swim_layout(p.dtype)
    n = p.shape[0]
    rows = jnp.arange(n, dtype=jnp.int32)
    k_tgt, k_ind, k_ex = jax.random.split(key, 3)
    rnd = round_idx.astype(lo.dtype) & lo.since_mask

    # --- probe: one random target each -------------------------------------
    tgt = jax.random.randint(k_tgt, (n,), 0, n, dtype=jnp.int32)
    cur = p[rows, tgt]  # (N,) packed belief about the probe target
    cur_status = (cur >> lo.status_shift) & 3
    probing = alive & (tgt != rows) & (cur_status < 2)

    direct_ack = probing & alive[tgt] & reachable(rows, tgt)

    inter = jax.random.randint(
        k_ind, (n, cfg.swim_indirect_probes), 0, n, dtype=jnp.int32
    )
    ind_ok = (
        alive[inter]
        & alive[tgt][:, None]
        & reachable(rows[:, None], inter)
        & reachable(inter, tgt[:, None])
    ).any(axis=1)
    acked = direct_ack | (probing & ind_ok)
    failed = probing & ~acked

    # --- apply probe outcome to the prober's row ---------------------------
    newly_suspect = failed & (cur_status == 0)
    # an ack refutes only our own suspicion at the same incarnation
    refuted = acked & (cur_status == 1)
    new_status = jnp.where(
        newly_suspect,
        jnp.asarray(1, lo.dtype),
        jnp.where(refuted, jnp.asarray(0, lo.dtype), cur_status),
    )
    new_since = jnp.where(newly_suspect, rnd, cur & lo.since_mask)
    new_p = (
        (cur & jnp.asarray(lo.inc_only_mask, lo.dtype))
        | (new_status << lo.status_shift)
        | new_since
    )
    p = p.at[rows, tgt].set(jnp.where(probing, new_p, cur))

    # --- suspicion timeout → down -----------------------------------------
    status_pl = (p >> lo.status_shift) & 3
    elapsed = (rnd - (p & lo.since_mask)) & lo.since_mask  # mod-2^k
    timed_out = (
        (status_pl == 1)
        & (elapsed >= (
            cfg.swim_suspect_rounds if suspect_rounds is None
            else suspect_rounds.astype(lo.dtype)
        ))
        & alive[:, None]
    )
    p = jnp.where(
        timed_out,
        (p & jnp.asarray(lo.not_status_mask, lo.dtype)) | lo.down_key,
        p,
    )

    # --- epidemic view exchange -------------------------------------------
    # Two directions per sub-round:
    #  * pull — i merges a random peer's view, but only contacts peers it
    #    believes are up;
    #  * push — every node pushes to a uniformly random target. Fan-in is
    #    whatever the sampling produces (~Poisson(1): real SWIM fan-in
    #    statistics). Concurrent pushes into one receiver combine via a
    #    scatter-max on the packed plane — precedence IS integer order, so
    #    the winner is the same one foca's sequential update application
    #    would pick. The *pusher's* belief gates the contact, which is what
    #    lets a refuted/rejoined node re-enter views that had written it
    #    off (handlers.rs:188-232, actor.rs:199-210). Pull alone
    #    deadlocks: nobody polls a member they believe is DOWN.
    #
    # Payload bound: each datagram carries at most swim_payload_members
    # member entries (the ≤1178 B packet, broadcast/mod.rs:743) — a
    # contiguous block of the member space at a per-sender random phase,
    # like foca cycling its piggyback backlog. >= n means full views.
    cols = jnp.arange(n, dtype=jnp.int32)
    bounded = cfg.swim_payload_members < n

    def payload_block(key_b):
        """(N, N) bool — which member columns each sender's datagram carries."""
        if not bounded:
            return None
        off = jax.random.randint(key_b, (n,), 0, n, dtype=jnp.int32)
        return ((cols[None, :] - off[:, None]) % n) < cfg.swim_payload_members

    for g in range(cfg.swim_gossip_peers):
        kg_pull, kg_push, kg_bl1, kg_bl2 = jax.random.split(
            jax.random.fold_in(k_ex, SWIM_PEER_KEY_TAG_BASE + g), 4
        )
        peer = jax.random.randint(kg_pull, (n,), 0, n, dtype=jnp.int32)
        can1 = (
            alive
            & alive[peer]
            & reachable(rows, peer)
            & (peer != rows)
            & ((p[rows, peer] & lo.status_mask) < lo.down_key)
        )
        can = can1[:, None]
        block = payload_block(kg_bl1)
        if block is not None:
            can = can & block[peer]  # responder picks the datagram contents
        p = jnp.where(can, jnp.maximum(p, p[peer]), p)
        # Every SWIM message carries the SENDER'S identity + incarnation
        # regardless of payload contents (the protocol's message header;
        # foca refutations ride it) — so a contact always heals the
        # contacted entry itself. Without this, a refutation waits for the
        # random payload window to cover the member, which stretches
        # partition heal far beyond what real SWIM does.
        if block is not None:
            self_of_peer = p[peer, peer]
            p = p.at[rows, peer].max(
                jnp.where(can1, self_of_peer, jnp.asarray(0, lo.dtype))
            )

        push_tgt = jax.random.randint(kg_push, (n,), 0, n, dtype=jnp.int32)
        ok_push = (
            alive
            & alive[push_tgt]
            & reachable(rows, push_tgt)
            & (push_tgt != rows)
            & ((p[rows, push_tgt] & lo.status_mask) < lo.down_key)
        )
        contrib = jnp.where(ok_push[:, None], p, jnp.asarray(0, lo.dtype))
        block = payload_block(kg_bl2)
        if block is not None:
            contrib = jnp.where(block, contrib, jnp.asarray(0, lo.dtype))
            # sender's own entry always rides the datagram header
            contrib = contrib.at[rows, rows].set(
                jnp.where(ok_push, p[rows, rows], jnp.asarray(0, lo.dtype))
            )
        best = jnp.zeros((n, n), lo.dtype).at[
            jnp.where(ok_push, push_tgt, n)
        ].max(contrib, mode="drop")
        p = jnp.where(alive[:, None], jnp.maximum(p, best), p)

    # --- periodic announce (belief-independent) ----------------------------
    # After a partition both sides can hold each other DOWN; neither pulls
    # nor pushes across (all contact is gated on believed-up). The reference
    # escapes via its periodic SWIM announcer, which dials bootstrap/member
    # addresses regardless of member state (handlers.rs:188-232,
    # ANNOUNCE_INTERVAL agent/mod.rs:32). Model: every k rounds each node
    # exchanges views with one uniformly random member, gated only on the
    # ground-truth link. The down-side node then sees itself DOWN in the
    # merged view and refutes with a higher incarnation (below), which wins
    # subsequent merges — the standard SWIM heal dance.
    def do_announce(p):
        ka = jax.random.fold_in(k_ex, SWIM_ANNOUNCE_KEY_TAG)
        perm = jax.random.permutation(ka, n).astype(jnp.int32)
        inv = jnp.argsort(perm, stable=True).astype(jnp.int32)
        for partner in (perm, inv):
            can = (
                alive & alive[partner] & reachable(rows, partner)
                & (partner != rows)
            )[:, None]
            p = jnp.where(can, jnp.maximum(p, p[partner]), p)
        return p

    # With swim_interval > 1 this step only runs on every k-th gossip
    # round; gating on `round % announce_interval == 0` there would fire
    # every lcm(k, announce_interval) rounds — up to k× rarer than
    # configured, stretching the only partition-heal path. Fire instead on
    # the one tick inside each announce window: exactly one firing per
    # window while swim_interval <= announce_interval, every tick beyond.
    p = jax.lax.cond(
        (round_idx % cfg.swim_announce_interval) < cfg.swim_interval,
        do_announce,
        lambda q: q,
        p,
    )

    # --- refutation / identity renew --------------------------------------
    self_p = p[rows, rows]
    need_refute = alive & ((self_p & lo.status_mask) > 0)
    inc_next = jnp.minimum((self_p >> lo.inc_shift) + 1, lo.inc_max)
    refreshed = inc_next << lo.inc_shift  # status ALIVE, since 0
    p = p.at[rows, rows].set(jnp.where(need_refute, refreshed, self_p))

    status_pl = (p >> lo.status_shift) & 3
    metrics = {
        "swim_suspects": (
            (status_pl == 1) & alive[:, None]
        ).sum(dtype=jnp.int32),
        "swim_down": (
            (status_pl == 2) & alive[:, None]
        ).sum(dtype=jnp.int32),
        "swim_probe_failures": failed.sum(dtype=jnp.int32),
    }
    return SwimState(p=p), metrics
