"""Bootstrap address resolution — ``agent/bootstrap.rs:14-150`` analog.

The reference's ``generate_bootstrap``:

1. parses each configured bootstrap string — ``host:port`` or
   ``host:port@dns_server`` (resolve through that specific DNS server);
2. literal IPs pass straight through; names resolve via trust-dns;
3. if NOTHING resolved, falls back to 5 random rows of the persisted
   ``__corro_members`` table (peers seen in a previous life);
4. returns at most 10 distinct addresses.

The simulator keeps the same contract for its deployment tooling: the
devcluster harness writes per-node bootstrap lists, and a warm-booted
agent falls back to the member addresses recorded in its checkpoint.
Name resolution uses the host resolver (``socket.getaddrinfo``); a
``@dns_server`` suffix is parsed and carried but custom-server lookups
degrade to the host resolver (no raw-DNS client in a zero-egress image —
the entry still validates and the server string is surfaced to the
caller for diagnostics).
"""

from __future__ import annotations

import dataclasses
import ipaddress
import random
import socket

BOOTSTRAP_LIMIT = 10  # reference: choose at most 10 (bootstrap.rs:139-148)
MEMBER_FALLBACK = 5  # random member rows when nothing resolves (:96-118)


class BootstrapError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class BootstrapEntry:
    host: str
    port: int
    dns_server: str | None = None  # "host:port@dns" form


def parse_entry(s: str) -> BootstrapEntry:
    """``host:port`` / ``host:port@dns_server`` / ``[v6]:port`` forms."""
    s = s.strip()
    if not s:
        raise BootstrapError("empty bootstrap entry")
    addr, _, dns = s.partition("@")
    dns_server = dns.strip() or None
    addr = addr.strip()
    if addr.startswith("["):  # [v6]:port
        host, bracket, rest = addr[1:].partition("]")
        if not bracket or not rest.startswith(":"):
            raise BootstrapError(f"malformed bootstrap address {addr!r}")
        port_s = rest[1:]
    else:
        host, colon, port_s = addr.rpartition(":")
        if not colon:
            raise BootstrapError(
                f"bootstrap entry {addr!r} needs a port (host:port)"
            )
    try:
        port = int(port_s)
    except ValueError:
        raise BootstrapError(f"bad port in bootstrap entry {addr!r}") from None
    if not (0 < port < 65536):
        raise BootstrapError(f"port {port} out of range in {addr!r}")
    if not host:
        raise BootstrapError(f"empty host in bootstrap entry {addr!r}")
    return BootstrapEntry(host=host, port=port, dns_server=dns_server)


def _default_resolve(host: str, port: int, dns_server: str | None):
    """Name → addresses via the host resolver (trust-dns stand-in)."""
    try:
        infos = socket.getaddrinfo(host, port, type=socket.SOCK_DGRAM)
    except socket.gaierror:
        return []
    return [(info[4][0], port) for info in infos]


def generate_bootstrap(
    entries,
    member_addrs=(),
    limit: int = BOOTSTRAP_LIMIT,
    fallback_n: int = MEMBER_FALLBACK,
    resolve=_default_resolve,
    rng: random.Random | None = None,
):
    """Resolve bootstrap strings to at most ``limit`` distinct addresses.

    ``entries``: strings or :class:`BootstrapEntry`; ``member_addrs``:
    (host, port) pairs from persisted membership (``__corro_members``),
    used as the fallback pool when nothing resolves. Returns a list of
    (host, port) tuples, first-seen order, deduplicated.
    """
    rng = rng or random.Random()
    out: list = []
    seen = set()

    def add(pair):
        if pair not in seen:
            seen.add(pair)
            out.append(pair)

    for e in entries:
        entry = parse_entry(e) if isinstance(e, str) else e
        try:
            ipaddress.ip_address(entry.host)
            add((entry.host, entry.port))
            continue
        except ValueError:
            pass
        for pair in resolve(entry.host, entry.port, entry.dns_server):
            add(pair)

    if not out:
        # nothing configured or resolvable: fall back to a random sample
        # of previously-seen members (bootstrap.rs:96-118)
        pool = list(member_addrs)
        rng.shuffle(pool)
        for pair in pool[:fallback_n]:
            add(tuple(pair))

    return out[:limit]
