"""Bootstrap address resolution — ``agent/bootstrap.rs:14-150`` analog.

The reference's ``generate_bootstrap``:

1. parses each configured bootstrap string — ``host:port`` or
   ``host:port@dns_server`` (resolve through that specific DNS server);
2. literal IPs pass straight through; names resolve via trust-dns;
3. if NOTHING resolved, falls back to 5 random rows of the persisted
   ``__corro_members`` table (peers seen in a previous life);
4. returns at most 10 distinct addresses.

The simulator keeps the same contract for its deployment tooling: the
devcluster harness writes per-node bootstrap lists, and a warm-booted
agent falls back to the member addresses recorded in its checkpoint.
Plain names resolve via the host resolver (``socket.getaddrinfo``); a
``@dns_server`` suffix queries THAT server directly with a minimal
RFC-1035 A/AAAA lookup over UDP (the hickory-resolver custom-server
path, ``bootstrap.rs:33-94``), falling back to the host resolver if the
named server does not answer.
"""

from __future__ import annotations

import dataclasses
import ipaddress
import random
import socket
import struct

BOOTSTRAP_LIMIT = 10  # reference: choose at most 10 (bootstrap.rs:139-148)
MEMBER_FALLBACK = 5  # random member rows when nothing resolves (:96-118)


class BootstrapError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class BootstrapEntry:
    host: str
    port: int
    dns_server: str | None = None  # "host:port@dns" form


def parse_entry(s: str) -> BootstrapEntry:
    """``host:port`` / ``host:port@dns_server`` / ``[v6]:port`` forms."""
    s = s.strip()
    if not s:
        raise BootstrapError("empty bootstrap entry")
    addr, _, dns = s.partition("@")
    dns_server = dns.strip() or None
    addr = addr.strip()
    if addr.startswith("["):  # [v6]:port
        host, bracket, rest = addr[1:].partition("]")
        if not bracket or not rest.startswith(":"):
            raise BootstrapError(f"malformed bootstrap address {addr!r}")
        port_s = rest[1:]
    else:
        host, colon, port_s = addr.rpartition(":")
        if not colon:
            raise BootstrapError(
                f"bootstrap entry {addr!r} needs a port (host:port)"
            )
    try:
        port = int(port_s)
    except ValueError:
        raise BootstrapError(f"bad port in bootstrap entry {addr!r}") from None
    if not (0 < port < 65536):
        raise BootstrapError(f"port {port} out of range in {addr!r}")
    if not host:
        raise BootstrapError(f"empty host in bootstrap entry {addr!r}")
    return BootstrapEntry(host=host, port=port, dns_server=dns_server)


def _encode_qname(name: str) -> bytes:
    out = b""
    for label in name.rstrip(".").split("."):
        raw = label.encode("idna") if not label.isascii() else label.encode()
        if not 0 < len(raw) < 64:
            raise BootstrapError(f"bad DNS label {label!r} in {name!r}")
        out += bytes([len(raw)]) + raw
    return out + b"\x00"


def _skip_name(buf: bytes, off: int) -> int:
    """Return the offset just past a (possibly compressed) domain name."""
    while True:
        if off >= len(buf):
            raise BootstrapError("truncated DNS name")
        n = buf[off]
        if n == 0:
            return off + 1
        if n & 0xC0 == 0xC0:  # compression pointer ends the name
            return off + 2
        off += 1 + n


def _parse_server(server: str) -> tuple[str, int, int]:
    """``host[:port]`` / ``[v6][:port]`` / bare v6 → (host, port, family)."""
    server = server.strip()
    if server.startswith("["):
        host, bracket, rest = server[1:].partition("]")
        if not bracket:
            raise BootstrapError(f"malformed DNS server {server!r}")
        port = int(rest[1:]) if rest.startswith(":") else 53
    else:
        host, colon, port_s = server.rpartition(":")
        if colon and ":" not in host:  # host:port (v4 or name)
            port = int(port_s)
        else:  # no port, or a bare IPv6 literal full of colons
            host, port = server, 53
    try:
        fam = (
            socket.AF_INET6
            if isinstance(ipaddress.ip_address(host), ipaddress.IPv6Address)
            else socket.AF_INET
        )
    except ValueError:
        fam = socket.AF_INET  # a nameserver given by name; resolve as v4
    if not (0 < port < 65536):
        raise BootstrapError(f"bad DNS server port in {server!r}")
    return host, port, fam


def dns_query(
    name: str, server: str, qtype: int = 1, timeout: float = 1.5,
    txid: int | None = None,
) -> list[str]:
    """Minimal RFC-1035 A (qtype=1) / AAAA (28) lookup against ``server``
    over UDP — the custom-``@dns_server`` path of the reference's
    bootstrap resolution (``bootstrap.rs:33-94``, hickory resolver with a
    caller-chosen nameserver). Returns address strings; [] on timeout,
    SERVFAIL/NXDOMAIN, or a malformed/mismatched reply. Stray datagrams
    (wrong txid or wrong source) are ignored and the socket keeps
    listening until the deadline."""
    import time

    try:
        host, port, fam = _parse_server(server)
        txid = random.getrandbits(16) if txid is None else txid
        # header: id, flags=RD, 1 question; then QNAME QTYPE QCLASS(IN)
        q = struct.pack("!HHHHHH", txid, 0x0100, 1, 0, 0, 0)
        q += _encode_qname(name) + struct.pack("!HH", qtype, 1)
        deadline = time.monotonic() + timeout
        with socket.socket(fam, socket.SOCK_DGRAM) as s:
            s.sendto(q, (host, port))
            while True:
                left = deadline - time.monotonic()
                if left <= 0:
                    return []
                s.settimeout(left)
                buf, src = s.recvfrom(4096)
                if len(buf) < 12 or src[1] != port:
                    continue  # noise; keep waiting for the real reply
                rid, flags, qd, an, _ns, _ar = struct.unpack_from(
                    "!HHHHHH", buf, 0
                )
                if rid != txid:
                    continue  # stray/spoofed datagram
                if not flags & 0x8000 or flags & 0x000F:
                    return []  # not a response, or RCODE != NOERROR
                break
        off = 12
        for _ in range(qd):  # skip echoed questions
            off = _skip_name(buf, off) + 4
        out = []
        for _ in range(an):
            off = _skip_name(buf, off)
            if off + 10 > len(buf):
                break
            rtype, _rc, _ttl, rdlen = struct.unpack_from("!HHIH", buf, off)
            off += 10
            rdata = buf[off:off + rdlen]
            off += rdlen
            if rtype == 1 and rdlen == 4:
                out.append(socket.inet_ntop(socket.AF_INET, rdata))
            elif rtype == 28 and rdlen == 16:
                out.append(socket.inet_ntop(socket.AF_INET6, rdata))
        return out
    except (OSError, ValueError, BootstrapError):
        # unreachable server, bad server string, malformed reply — all
        # degrade to the caller's fallback instead of aborting bootstrap
        return []


def _default_resolve(host: str, port: int, dns_server: str | None,
                     dead_servers: set | None = None):
    """Name → addresses; ``dns_server`` queries that server directly
    (A then AAAA), skipping servers that already failed this pass."""
    if dns_server is not None:
        dead = dead_servers if dead_servers is not None else set()
        if dns_server not in dead:
            addrs = dns_query(host, dns_server)  # A
            if not addrs:
                addrs = dns_query(host, dns_server, qtype=28)  # AAAA
            if addrs:
                return [(a, port) for a in addrs]
            # one timeout costs ≤2 queries; don't re-pay it per entry
            dead.add(dns_server)
        # named server unreachable/empty: degrade to the host resolver
    try:
        infos = socket.getaddrinfo(host, port, type=socket.SOCK_DGRAM)
    except socket.gaierror:
        return []
    return [(info[4][0], port) for info in infos]


def generate_bootstrap(
    entries,
    member_addrs=(),
    limit: int = BOOTSTRAP_LIMIT,
    fallback_n: int = MEMBER_FALLBACK,
    resolve=_default_resolve,
    rng: random.Random | None = None,
):
    """Resolve bootstrap strings to at most ``limit`` distinct addresses.

    ``entries``: strings or :class:`BootstrapEntry`; ``member_addrs``:
    (host, port) pairs from persisted membership (``__corro_members``),
    used as the fallback pool when nothing resolves. Returns a list of
    (host, port) tuples, first-seen order, deduplicated.
    """
    rng = rng or random.Random()
    out: list = []
    seen = set()

    def add(pair):
        if pair not in seen:
            seen.add(pair)
            out.append(pair)

    # one shared dead-server set per pass: an unreachable @dns_server
    # costs its timeout once, not once per entry pointing at it
    dead_servers: set = set()
    if resolve is _default_resolve:
        def resolve(h, p, d, _r=_default_resolve):  # noqa: F811
            return _r(h, p, d, dead_servers=dead_servers)

    for e in entries:
        entry = parse_entry(e) if isinstance(e, str) else e
        try:
            ipaddress.ip_address(entry.host)
            add((entry.host, entry.port))
            continue
        except ValueError:
            pass
        for pair in resolve(entry.host, entry.port, entry.dns_server):
            add(pair)

    if not out:
        # nothing configured or resolvable: fall back to a random sample
        # of previously-seen members (bootstrap.rs:96-118)
        pool = list(member_addrs)
        rng.shuffle(pool)
        for pair in pool[:fallback_n]:
            add(tuple(pair))

    return out[:limit]
