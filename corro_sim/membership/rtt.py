"""Link latency model + measured-RTT rings — the members.rs analog.

Reference behavior (``corro-types/src/members.rs:40,140-188``): every QUIC
contact pushes an RTT sample into a 20-sample circular buffer per peer;
samples bucket into ``RING_BUCKETS`` = {0-6, 6-15, 15-50, 50-100, 100-200,
200-300} ms; a member's ring is recomputed from its bucketed average, and
ring-0 (lowest latency) gets the eager broadcast path
(``broadcast/mod.rs:489-499``) and preferential sync peer choice
(``handlers.rs:1018-1042``).

TPU shape, three pieces:

- **Delay model**: nodes belong to ``latency_regions`` contiguous regions
  (think racks/DCs). A link's delay in rounds is ``latency_intra`` (= 1,
  same-round) within a region and ``latency_inter`` across. Delayed lanes
  park in the engine's in-flight ring (``SimState.inflight``) and deliver
  ``latency_inter - 1`` rounds after emission — real latency, not loss
  (the r2 phase-gated ``link_open`` model read a delay-4 link as 75%
  loss, distorting convergence-round counts; VERDICT r2 next #6).
- **Measurement**: every successful delivery writes the observed edge
  delay into the receiver's ``rtt[dst, src]`` plane (the sample the
  reference takes on connection reuse, ``transport.rs:199-233``).
- **Ring recomputation**: every ``ring_update_interval`` rounds each node
  re-picks its ``ring0_size`` lowest-RTT peers from observations
  (unobserved edges rank last) — ``add_rtt`` → ``recalculate_rings``.
"""

from __future__ import annotations

import jax.numpy as jnp

from corro_sim.config import SimConfig

UNOBSERVED = jnp.uint8(255)


def region_of(cfg: SimConfig, node: jnp.ndarray) -> jnp.ndarray:
    return (node * cfg.latency_regions) // cfg.num_nodes


def link_delay(cfg: SimConfig, src: jnp.ndarray, dst: jnp.ndarray):
    """Delay in rounds for each (src, dst) lane."""
    same = region_of(cfg, src) == region_of(cfg, dst)
    return jnp.where(
        same,
        jnp.int32(cfg.latency_intra),
        jnp.int32(cfg.latency_inter),
    )


def make_rtt(num_nodes: int, enabled: bool) -> jnp.ndarray:
    n = num_nodes if enabled else 1
    return jnp.full((n, n), UNOBSERVED, jnp.uint8)


def observe_rtt(
    cfg: SimConfig,
    rtt: jnp.ndarray,  # (N, N) uint8 — receiver's table, [dst, src]
    dst: jnp.ndarray,
    src: jnp.ndarray,
    delivered: jnp.ndarray,
) -> jnp.ndarray:
    """Record the observed delay of every delivered lane.

    The model's delay is deterministic per edge, so duplicate lanes carry
    equal samples and a plain scatter-set is race-free."""
    n = rtt.shape[0]
    sample = jnp.clip(link_delay(cfg, src, dst), 0, 254).astype(jnp.uint8)
    return rtt.at[jnp.where(delivered, dst, n), src].set(sample, mode="drop")


def recompute_ring0(
    rtt: jnp.ndarray, ring0: jnp.ndarray
) -> jnp.ndarray:
    """Each node's ``ring0_size`` lowest-observed-RTT peers.

    Unobserved peers rank behind every observed one; self is excluded.
    Ties (and the all-unobserved cold start) break toward the previous
    ring's members so an informationless update is a no-op."""
    import jax

    n, k = ring0.shape[0], ring0.shape[1]
    score = rtt.astype(jnp.int32)  # (N, N), 255 = unobserved
    iota = jnp.arange(n, dtype=jnp.int32)
    score = score.at[iota, iota].set(jnp.int32(1000))  # never pick self
    # prefer incumbents on ties: tiny bonus to current ring members
    bonus = jnp.zeros((n, n), jnp.int32).at[
        iota[:, None], ring0
    ].set(1, mode="drop")
    _, new_ring = jax.lax.top_k(-(score * 4 - bonus), k)
    return new_ring.astype(jnp.int32)
