from corro_sim.membership.swim import (
    SwimState,
    make_swim_state,
    swim_step,
    view_alive,
    ALIVE,
    SUSPECT,
    DOWN,
)

__all__ = [
    "SwimState",
    "make_swim_state",
    "swim_step",
    "view_alive",
    "ALIVE",
    "SUSPECT",
    "DOWN",
]
