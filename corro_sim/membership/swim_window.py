"""Windowed SWIM: O(N·K) belief state for 50k+ clusters (VERDICT r4 #8).

The full-view automaton (:mod:`corro_sim.membership.swim`) holds one
(N, N) packed plane — 400 MB at 10k nodes, 10 GB at 50k, which is why
config 5 historically ran ``swim_enabled=False``. foca's per-node state
is O(members known), and a member's datagrams carry at most ~64 entries
(the ≤1178 B packet, ``broadcast/mod.rs:743``) — a node's working
belief set is naturally bounded. This module is that bound made
explicit: each node tracks at most K members,

    member (N, K) int32   — tracked member id, -1 = empty (slot 0 = self)
    belief (N, K) packed  — the same (inc | status | since) packing as
                            the full plane (uint32, or uint16 under
                            ``SimConfig.narrow_state``), so precedence
                            merges stay integer max

and the protocol per tick:

- probe one known believed-up member (direct + indirect through known
  intermediaries), suspect on silence; suspicion times out to DOWN;
- pull-exchange with ``swim_gossip_peers`` known members: merge a
  bounded payload block of the peer's view (matched members merge by
  packed max — exactly foca's update precedence; unknown members fill
  empty/evicted slots through a rotating cursor);
- a periodic ANNOUNCE pull from a uniformly random member id (gated on
  ground truth only) discovers members outside the view and heals
  mutual-down splits, like the reference's announcer
  (``handlers.rs:188-232``);
- refutation: a node that sees itself suspected in its own slot-0 entry
  bumps its incarnation (saturating, like the full automaton).

Prototype scope (documented divergences from the full-view automaton):
exchange is pull-only (the full version also pushes; pulls at the same
cadence reach the same fixed point a few ticks later), and eviction is
rotating-cursor rather than LRU. Consumers get ``believed_up_pairs``
(per-(src, dst) membership test, dense over K) instead of an (N, N)
plane; ``view_alive_dense`` reconstructs the plane for admin surfaces
at small N only.
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from corro_sim.config import SimConfig
from corro_sim.membership.swim import (
    SWIM_PEER_KEY_TAG_BASE,
    belief_dtype,
    swim_layout,
)


@flax.struct.dataclass
class SwimWindowState:
    member: jnp.ndarray  # (N, K) int32, -1 = empty; slot 0 = self
    belief: jnp.ndarray  # (N, K) uint32/uint16 packed (inc|status|since)
    cursor: jnp.ndarray  # (N,) int32 rotating insertion cursor

    # unpacked read-only views mirroring SwimState's — admin surfaces,
    # metrics, and the skip-round path all read these instead of
    # re-implementing the bit layout. Entries of EMPTY slots read as
    # ALIVE/0 — mask with ``member >= 0`` where that matters.
    @property
    def status(self) -> jnp.ndarray:
        lo = swim_layout(self.belief.dtype)
        return ((self.belief >> lo.status_shift) & 3).astype(jnp.int8)

    @property
    def inc(self) -> jnp.ndarray:
        lo = swim_layout(self.belief.dtype)
        return (self.belief >> lo.inc_shift).astype(jnp.int32)

    @property
    def since(self) -> jnp.ndarray:
        lo = swim_layout(self.belief.dtype)
        return (self.belief & lo.since_mask).astype(jnp.int32)

    @property
    def self_inc(self) -> jnp.ndarray:
        """(N,) each node's own incarnation (slot 0 = self)."""
        lo = swim_layout(self.belief.dtype)
        return (self.belief[:, 0] >> lo.inc_shift).astype(jnp.int32)


def make_swim_window_state(
    num_nodes: int, view_size: int, seed: int = 0, enabled: bool = True,
    narrow: bool = False,
) -> SwimWindowState:
    n = num_nodes if enabled else 1
    k = max(view_size, 2) if enabled else 1
    member = jnp.full((n, k), -1, jnp.int32)
    member = member.at[:, 0].set(jnp.arange(n, dtype=jnp.int32))
    if enabled and n > 1:
        # seed the view with a random member sample (the bootstrap
        # peers), never the node itself — self lives ONLY in slot 0
        # (refutation resets slot 0; a duplicate self entry elsewhere
        # could hold a stale suspect belief it never clears)
        key = jax.random.PRNGKey(seed ^ 0x5117)
        fill = jax.random.randint(
            key, (n, k - 1), 1, n, dtype=jnp.int32
        )
        rows = jnp.arange(n, dtype=jnp.int32)[:, None]
        member = member.at[:, 1:].set((rows + fill) % n)
    return SwimWindowState(
        member=member,
        belief=jnp.zeros(member.shape, belief_dtype(narrow)),
        cursor=jnp.ones((n,), jnp.int32),
    )


def _status(b):
    return (b >> swim_layout(b.dtype).status_shift) & 3


def membership_view(cfg, swim_state, n):
    """The ``view`` consumed by gossip/sync: the windowed per-pair test
    (a callable) when ``swim_view_size > 0``, the dense plane otherwise,
    all-up when SWIM is off. One helper so sim_step and _repair_step
    cannot drift."""
    if not cfg.swim_enabled:
        return jnp.ones((1, n), bool)
    if cfg.swim_view_size > 0:
        return lambda src, dst: believed_up_pairs(swim_state, src, dst)
    from corro_sim.membership.swim import view_alive

    return view_alive(swim_state)


def believed_up_pairs(
    st: SwimWindowState, src: jnp.ndarray, dst: jnp.ndarray
) -> jnp.ndarray:
    """Per-pair "would src still talk to dst": True unless src's view
    holds dst as DOWN. Unknown members default to up — the reference
    dials any member address it has until told otherwise. ``src``/``dst``
    may be any equal (broadcastable) shapes; cost is pairs × K dense."""
    mem = st.member[src]  # pairs + (K,)
    bel = st.belief[src]
    lo = swim_layout(bel.dtype)
    hit = mem == dst[..., None]
    down = hit & ((bel & lo.status_mask) >= lo.down_key)
    return ~down.any(axis=-1)


def view_alive_dense(st: SwimWindowState) -> jnp.ndarray:
    """(N, N) believed-up plane for admin/metrics surfaces — O(N²·K);
    call only at small N (the windowed form exists to avoid this)."""
    n = st.member.shape[0]
    ids = jnp.arange(n, dtype=jnp.int32)
    return believed_up_pairs(
        st, jnp.broadcast_to(ids[:, None], (n, n)),
        jnp.broadcast_to(ids[None, :], (n, n)),
    )


def _merge_block(st, peer, ok, pay_off, pay_k):
    """Merge a payload block of ``peer``'s view into every node's view.

    Matched members merge by packed max (foca's update precedence);
    unmatched entries overwrite slots at the rotating cursor (never
    slot 0 — a node's own entry only changes through refutation)."""
    n, k = st.member.shape
    rows = jnp.arange(n, dtype=jnp.int32)
    cols = jnp.arange(k, dtype=jnp.int32)
    # the payload: pay_k contiguous view slots of the peer, from pay_off
    src_slots = (pay_off[:, None] + cols[None, :pay_k]) % k  # (N, P)
    inc_mem = st.member[peer[:, None], src_slots]  # (N, P)
    inc_bel = st.belief[peer[:, None], src_slots]
    inc_ok = ok[:, None] & (inc_mem >= 0)

    # matched merge: for each of my slots, the best incoming belief
    # about the same member
    match = st.member[:, :, None] == jnp.where(
        inc_ok, inc_mem, -2
    )[:, None, :]  # (N, K, P)
    best_in = jnp.max(
        jnp.where(
            match, inc_bel[:, None, :],
            jnp.asarray(0, dtype=inc_bel.dtype),
        ),
        axis=2,
    )
    belief = jnp.maximum(st.belief, best_in)

    # unmatched incoming entries fill rotating-cursor slots
    matched_any = match.any(axis=1)  # (N, P)
    fresh = inc_ok & ~matched_any & (
        inc_mem != rows[:, None]
    )  # never re-insert self
    frank = jnp.cumsum(fresh.astype(jnp.int32), axis=1) - 1  # (N, P)
    dst_slot = jnp.where(
        fresh,
        1 + (st.cursor[:, None] + frank - 1) % (k - 1),
        k,  # OOB — dropped
    )
    member = st.member.at[rows[:, None], dst_slot].set(inc_mem, mode="drop")
    belief = belief.at[rows[:, None], dst_slot].set(inc_bel, mode="drop")
    cursor = 1 + (st.cursor - 1 + fresh.sum(axis=1, dtype=jnp.int32)) % (
        k - 1
    )
    return st.replace(member=member, belief=belief, cursor=cursor)


def swim_window_step(
    cfg: SimConfig,
    st: SwimWindowState,
    key: jax.Array,
    alive: jnp.ndarray,
    reachable,  # callable (src, dst) -> bool mask, ground truth links
    round_idx: jnp.ndarray,
    suspect_rounds=None,  # traced per-lane override (sweep sim_knobs);
    # None = the baked cfg.swim_suspect_rounds constant
):
    """One windowed SWIM round for every node at once."""
    n, k = st.member.shape
    lo = swim_layout(st.belief.dtype)
    rows = jnp.arange(n, dtype=jnp.int32)
    k_tgt, k_ind, k_ex, k_ann = jax.random.split(key, 4)
    rnd = round_idx.astype(lo.dtype) & lo.since_mask
    pay = min(max(cfg.swim_payload_members, 2), k)

    # --- probe: one random KNOWN target each ---------------------------
    slot = jax.random.randint(k_tgt, (n,), 1, k, dtype=jnp.int32)
    tgt = st.member[rows, slot]
    cur = st.belief[rows, slot]
    probing = (
        alive & (tgt >= 0) & (tgt != rows)
        & (_status(cur) < 2)
    )
    tgt_c = jnp.where(tgt >= 0, tgt, 0)
    direct_ack = probing & alive[tgt_c] & reachable(rows, tgt_c)
    islot = jax.random.randint(
        k_ind, (n, cfg.swim_indirect_probes), 1, k, dtype=jnp.int32
    )
    inter = st.member[rows[:, None], islot]
    inter_c = jnp.where(inter >= 0, inter, 0)
    ind_ok = (
        (inter >= 0)
        & alive[inter_c]
        & alive[tgt_c][:, None]
        & reachable(rows[:, None], inter_c)
        & reachable(inter_c, tgt_c[:, None])
    ).any(axis=1)
    acked = direct_ack | (probing & ind_ok)
    failed = probing & ~acked

    newly_suspect = failed & (_status(cur) == 0)
    refuted_ack = acked & (_status(cur) == 1)
    new_status = jnp.where(
        newly_suspect, jnp.asarray(1, lo.dtype),
        jnp.where(refuted_ack, jnp.asarray(0, lo.dtype), _status(cur)),
    )
    new_since = jnp.where(newly_suspect, rnd, cur & lo.since_mask)
    new_b = (
        (cur & jnp.asarray(lo.inc_only_mask, lo.dtype))
        | (new_status << lo.status_shift) | new_since
    )
    onehot = jnp.arange(k, dtype=jnp.int32)[None, :] == slot[:, None]
    belief = jnp.where(
        onehot & probing[:, None], new_b[:, None], st.belief
    )
    st = st.replace(belief=belief)

    # --- suspicion timeout → down --------------------------------------
    elapsed = (rnd - (st.belief & lo.since_mask)) & lo.since_mask
    timed_out = (
        (_status(st.belief) == 1)
        & (elapsed >= (
            cfg.swim_suspect_rounds if suspect_rounds is None
            else suspect_rounds.astype(st.belief.dtype)
        ))
        & alive[:, None]
        & (st.member >= 0)
    )
    st = st.replace(belief=jnp.where(
        timed_out,
        (st.belief & jnp.asarray(lo.not_status_mask, lo.dtype))
        | lo.down_key,
        st.belief,
    ))

    # --- pull exchanges with known believed-up members -----------------
    for g in range(cfg.swim_gossip_peers):
        # the shared peer-exchange tag family (swim.py, auditor K2) —
        # the windowed announce needs no fold: it owns the k_ann lane
        kg_s, kg_o = jax.random.split(
            jax.random.fold_in(k_ex, SWIM_PEER_KEY_TAG_BASE + g)
        )
        pslot = jax.random.randint(kg_s, (n,), 1, k, dtype=jnp.int32)
        peer = st.member[rows, pslot]
        pb = st.belief[rows, pslot]
        peer_c = jnp.where(peer >= 0, peer, 0)
        ok = (
            alive & (peer >= 0) & (peer != rows)
            & ((pb & lo.status_mask) < lo.down_key)
            & alive[peer_c] & reachable(rows, peer_c)
        )
        off = jax.random.randint(kg_o, (n,), 0, k, dtype=jnp.int32)
        st = _merge_block(st, peer_c, ok, off, pay)

    # --- periodic announce: uniform-random member, ground-truth gated --
    def do_announce(st):
        ka_t, ka_o = jax.random.split(k_ann)
        peer = jax.random.randint(ka_t, (n,), 0, n, dtype=jnp.int32)
        ok = (
            alive & (peer != rows) & alive[peer] & reachable(rows, peer)
        )
        off = jax.random.randint(ka_o, (n,), 0, k, dtype=jnp.int32)
        return _merge_block(st, peer, ok, off, pay)

    st = jax.lax.cond(
        (round_idx % cfg.swim_announce_interval) < cfg.swim_interval,
        do_announce, lambda s: s, st,
    )

    # --- refutation / identity renew (slot 0 = self) -------------------
    self_b = st.belief[:, 0]
    need_refute = alive & ((self_b & lo.status_mask) > 0)
    inc_next = jnp.minimum((self_b >> lo.inc_shift) + 1, lo.inc_max)
    st = st.replace(belief=st.belief.at[:, 0].set(
        jnp.where(need_refute, inc_next << lo.inc_shift, self_b)
    ))

    tracked = st.member >= 0
    metrics = {
        "swim_suspects": (
            (_status(st.belief) == 1)
            & tracked & alive[:, None]
        ).sum(dtype=jnp.int32),
        "swim_down": (
            (_status(st.belief) >= 2)
            & tracked & alive[:, None]
        ).sum(dtype=jnp.int32),
        "swim_probe_failures": failed.sum(dtype=jnp.int32),
    }
    return st, metrics
