"""Headline benchmark: 10k-node gossip/CRDT cluster simulation on TPU.

Scenario = BASELINE.md config 4: 10k nodes, SWIM membership enabled, a
network partition during the run, gossip broadcast + anti-entropy sync.
Metric: CRDT changes applied across the cluster per wall-clock second
(local writes + fresh broadcast merges + sync repairs), steady-state,
excluding compile.

Baseline: the reference publishes no benchmarks (BASELINE.md); its only
numeric datum is an incidental sync-throughput log line of 156.04
changes/s on a dev machine (``doc/quick-start.md:121``). vs_baseline is
measured against that number.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

REFERENCE_CHANGES_PER_SEC = 156.04  # doc/quick-start.md:121


def run_headline_bench(
    n: int | None = None,
    chunk: int | None = None,
    measured_chunks: int | None = None,
) -> dict:
    import jax
    import jax.numpy as jnp

    from corro_sim.config import SimConfig
    from corro_sim.engine.driver import Schedule, _chunk_runner
    from corro_sim.engine.state import init_state

    n = n or int(os.environ.get("CORRO_BENCH_NODES", "10000"))
    chunk = chunk or int(os.environ.get("CORRO_BENCH_CHUNK", "8"))
    measured_chunks = measured_chunks or int(
        os.environ.get("CORRO_BENCH_CHUNKS", "4")
    )

    cfg = SimConfig(
        num_nodes=n,
        num_rows=256,
        num_cols=4,
        log_capacity=512,
        write_rate=0.5,
        zipf_alpha=0.8,
        swim_enabled=True,
        swim_suspect_rounds=6,
        sync_interval=8,
        sync_actor_topk=32,
        sync_cap_per_actor=8,
    )
    state = init_state(cfg, seed=0)
    runner = _chunk_runner(cfg)

    def part_fn(r, num):
        p = np.zeros(num, np.int32)
        if 16 <= r < 32:  # partition window mid-run
            p[num // 2:] = 1
        return p

    schedule = Schedule(write_rounds=10**9, part_fn=part_fn)
    root = jax.random.PRNGKey(0)

    def run_chunk(state, ci, start_round):
        alive, part, we = schedule.slice(start_round, chunk, cfg.num_nodes)
        keys = jax.random.split(jax.random.fold_in(root, ci), chunk)
        return runner(
            state, keys, jnp.asarray(alive), jnp.asarray(part), jnp.asarray(we)
        )

    # warm-up / compile
    s, m = run_chunk(state, 0, 0)
    jax.block_until_ready(m)
    del state  # keep exactly one cluster state resident (HBM pressure)
    state = s

    # Per-chunk throughput, median-of-chunks: a transient tunnel or HBM
    # stall in one chunk must not halve the reported steady-state number.
    rates = []
    rounds = 0
    for ci in range(1, 1 + measured_chunks):
        t0 = time.perf_counter()
        new_state, m = run_chunk(state, ci, rounds + chunk)
        m = jax.tree.map(np.asarray, m)
        wall = time.perf_counter() - t0
        del state
        state = new_state
        applied = int(m["writes"].sum()) + int(m["fresh"].sum()) + int(
            m["sync_versions"].sum()
        )
        rates.append(applied / wall)
        rounds += chunk

    changes_per_sec = float(np.median(rates))
    return {
        "metric": f"crdt_changes_applied_per_sec_{n}_node_sim",
        "value": round(changes_per_sec, 2),
        "unit": "changes/s",
        "vs_baseline": round(changes_per_sec / REFERENCE_CHANGES_PER_SEC, 2),
    }


def main() -> int:
    print(json.dumps(run_headline_bench()))
    return 0
